"""The node runtime — AppInitMain and friends.

Reference: src/init.cpp:~1200 (AppInitMain): logging, datadir, DB opens,
LoadBlockIndex, optional -reindex import, CVerifyDB startup integrity check,
mempool + validation-interface wiring, then servers (RPC here; P2P via
p2p/connman). Shutdown = flush everything, close stores (Shutdown(),
src/init.cpp:~150).

The whole node shares one re-entrant lock (`cs_main`) — RPC worker threads
and the P2P event loop serialize on it exactly like the reference's cs_main.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

from ..consensus.block import CBlock
from ..consensus.versionbits import VersionBitsCache
from ..consensus.serialize import hash_to_hex
from ..mempool.accept import accept_to_memory_pool
from ..mempool.mempool import CTxMemPool, MempoolError
from ..mining.assembler import BlockAssembler
from ..mining.generate import MAX_TRIES_DEFAULT, mine_block
from ..store.blockstore import BlockStore
from ..store.chainstatedb import BlockIndexDB, CoinsDB
from ..store.kvstore import KVStore
from ..store.sharded import MANIFEST_NAME as _COINS_MANIFEST
from ..store.sharded import ShardedCoinsDB
from ..util import lockwatch, telemetry
from ..util.log import log_init, log_print, log_printf
from ..validation.chain import BlockStatus
from ..validation.chainstate import BlockValidationError, ChainstateManager
from ..validation.scriptcheck import BlockScriptVerifier
from ..validation.sigcache import SignatureCache
from .config import Config, ConfigError

DEFAULT_FLUSH_INTERVAL = 64  # blocks between periodic FlushStateToDisk calls

# explicit -telemetry levels a -tracefile sink contradicts (node startup
# rejects the combination rather than writing an empty dump)
MODES_BELOW_TRACE = ("off", "counters")


class InitError(Exception):
    pass


class _NativeImportAbort(Exception):
    """A staged fast-import block's signature batch failed after commit —
    recover by rebuilding from the last flush and replaying through the
    Python engine (node.import_block_files)."""


class _ShadowBlockStore:
    """Block-store facade for the assumeutxo shadow chainstate: reads
    delegate to the node's real store (under cs_main — BlockStore file
    handles aren't thread-safe against the main validation path), every
    write is a no-op (the real store already holds the data; the shadow
    exists only to re-derive the UTXO set)."""

    def __init__(self, node: "Node"):
        self._node = node

    def get_block(self, h: bytes):
        with self._node.cs_main:
            return self._node.block_store.get_block(h)

    def have_block(self, h: bytes) -> bool:
        return self.get_block(h) is not None

    def put_block(self, h: bytes, raw: bytes) -> None:
        pass

    def put_undo(self, h: bytes, raw: bytes) -> None:
        pass

    def get_undo(self, h: bytes):
        return None

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class Node:
    """One full node over a datadir. Construct → (optionally) start_rpc/start_p2p
    → work → close(). Usable in-process (tests) or via bcpd (cli/)."""

    def __init__(self, config: Optional[Config] = None, datadir: Optional[str] = None,
                 network: Optional[str] = None):
        if config is None:
            config = Config()
            if datadir:
                config.args["datadir"] = [datadir]
            if network == "regtest":
                config.args["regtest"] = ["1"]
            elif network in ("test", "testnet"):
                config.args["testnet"] = ["1"]
        self.config = config
        self.params = config.chain_params()
        self.datadir = config.datadir
        # JAX_PLATFORMS=cpu must actually mean CPU: an accelerator plugin
        # can still win default-backend selection (tests/conftest.py notes
        # the same), which silently routes every node jit through it — and
        # couples regtest/functional nodes to remote-device availability.
        try:
            from ..ops.sha256 import backend_is_cpu

            if backend_is_cpu():
                import jax

                # hide accelerator plugins entirely (config, not env: the
                # env var alone doesn't stop plugin init, and an unreachable
                # device tunnel would hang the node's first jit)
                jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        os.makedirs(self.datadir, exist_ok=True)
        log_init(
            logfile_path=os.path.join(self.datadir, "debug.log"),
            categories=config.get_multi("debug"),
            print_to_console=config.get_bool("printtoconsole"),
            json_mode=config.get_bool("logjson"),
        )
        # -telemetry=<off|counters|trace> / -tracefile=<path>: resolved
        # BEFORE any import/reindex work so startup spans are captured.
        # Validated here — an unknown level must fail init like any other
        # malformed flag, not degrade silently (telemetry.set_mode raises).
        self.tracefile = config.get("tracefile") or None
        tmode = config.get("telemetry", "")
        if self.tracefile and tmode and tmode in MODES_BELOW_TRACE:
            # an explicit lower level with a trace sink would silently
            # write an empty dump — reject the contradiction instead
            raise ConfigError(
                f"-tracefile requires -telemetry=trace "
                f"(got -telemetry={tmode})")
        if self.tracefile and not tmode:
            tmode = "trace"  # a trace sink implies span tracing
        if tmode:
            try:
                telemetry.set_mode(tmode)
            except ValueError as e:
                raise ConfigError(str(e)) from None
        self.telemetry_mode = telemetry.mode()
        log_printf("bcpd init: network=%s datadir=%s", self.params.network, self.datadir)

        # -par=<n>: thread budget for the native CPU verify fallback
        # (src/init.cpp -par -> CCheckQueue worker count; here the TPU batch
        # is the worker pool, so -par bounds the HOST-side native threads).
        # Reference semantics kept: 0 = auto, -N = leave N cores free.
        from .. import native as _native

        par = config.get_int("par", 0)
        if par < 0:
            par = max(1, (os.cpu_count() or 1) + par)
        _native.PAR_THREADS = par

        # cs_main — one lock serializing all chainstate/mempool access.
        # Plain RLock normally; BCP_LOCKWATCH=1 substitutes the lockwatch
        # sentinel wrapper (util/lockwatch) that feeds the lock-order
        # graph behind gettpuinfo.lockwatch and the atexit cycle report.
        self.cs_main = lockwatch.watched_rlock("cs_main")
        self.shutdown_event = threading.Event()
        self.start_time = int(time.time())
        # wake channel for blocking RPCs (getblocktemplate longpoll,
        # waitfornewblock): notified on tip/mempool change. Waiters poll
        # their predicate under cs_main between short cv waits — notifiers
        # fire while holding cs_main, so waiters must never hold the cv
        # while taking cs_main in the other order.
        self.notify_cv = lockwatch.watched_condition("notify_cv")

        reindex = config.get_bool("reindex")
        self.last_import_stats: Optional[dict] = None
        blocks_dir = os.path.join(self.datadir, "blocks")
        index_path = os.path.join(blocks_dir, "index.sqlite")
        coins_path = os.path.join(self.datadir, "chainstate.sqlite")
        journal_path = os.path.join(self.datadir, "chainstate.journal")
        # -coinshards=<n>: hash-partition fan-out for the sharded coins
        # store (power of two, 1..256; validated by ShardedCoinsDB). An
        # existing sharded datadir's manifest pins the count — the flag
        # only picks the layout for a fresh datadir or a -reindex.
        coinshards = config.get_int("coinshards", 4)
        # -assumeutxo=<blockhash>:<muhash>: authorize loadtxoutset to
        # adopt a UTXO snapshot with exactly this tip hash and set digest
        # (both 32-byte hex, display order). Without it, loadtxoutset is
        # refused — snapshot trust is an explicit operator decision.
        self.assumeutxo: Optional[tuple[bytes, bytes]] = None
        au = config.get("assumeutxo", "")
        if au:
            try:
                h_hex, _, d_hex = au.partition(":")
                h_raw, d_raw = bytes.fromhex(h_hex), bytes.fromhex(d_hex)
                if len(h_raw) != 32 or len(d_raw) != 32:
                    raise ValueError
            except ValueError:
                raise ConfigError(
                    f"-assumeutxo={au!r}: expected "
                    "<blockhash_hex>:<muhash_hex> (32 bytes each)")
            # display order -> internal little-endian hash
            self.assumeutxo = (h_raw[::-1], d_raw)
        # Proof-carrying snapshot knobs (store/certificate.py):
        #  -snapshotepoch=<E>     checkpoint stride for the certificate's
        #                         MuHash trajectory built at dumptxoutset
        #  -snapshotspotcheck=<K> shadow validation re-runs full script
        #                         checks on only K seeded-drawn certified
        #                         epochs (0 = full re-validation); digest
        #                         checkpoints still fire at EVERY boundary
        #  -snapshotcertrequired  refuse certificate-less snapshots at
        #                         loadtxoutset instead of quarantining
        self.snapshot_epoch = config.get_int("snapshotepoch", 64)
        if self.snapshot_epoch < 1:
            raise ConfigError(
                f"-snapshotepoch={self.snapshot_epoch}: must be >= 1")
        self.snapshot_spotcheck = config.get_int("snapshotspotcheck", 0)
        if self.snapshot_spotcheck < 0:
            raise ConfigError(
                f"-snapshotspotcheck={self.snapshot_spotcheck}: must be "
                ">= 0")
        self.snapshot_cert_required = config.get_bool("snapshotcertrequired")
        # the seeded draw reuses -netseed so one seed replays an identical
        # spot-check drill end to end (orphan eviction included)
        self._spotcheck_seed: Optional[int] = (
            config.get_int("netseed", -1)
            if config.get_int("netseed", -1) >= 0 else None)
        if reindex:
            # wipe the derived state; blk*.dat files are the source of truth
            for p in (index_path, coins_path):
                for suffix in ("", "-wal", "-shm"):
                    if os.path.exists(p + suffix):
                        os.remove(p + suffix)
            for p in (journal_path, journal_path + ".tmp"):
                if os.path.exists(p):
                    os.remove(p)
            ShardedCoinsDB.wipe(self.datadir)
            import shutil as _shutil

            _shutil.rmtree(os.path.join(self.datadir, "chainstate_shadow"),
                           ignore_errors=True)
            if os.path.exists(self._snapshot_cert_path()):
                os.remove(self._snapshot_cert_path())
            # undo data is derived too: the import rebuilds every record,
            # and the wiped undo_positions would otherwise leave the old
            # records stranded in the rev files forever (the reference
            # rewrites undo during a reindex as well)
            import glob as _glob

            for p in _glob.glob(os.path.join(blocks_dir, "rev*.dat")):
                with open(p, "wb"):
                    pass
            log_printf("-reindex: wiped block index and chainstate")

        os.makedirs(blocks_dir, exist_ok=True)
        self._index_kv = KVStore(index_path)
        # -maxblockfilesize: test/debug knob for block-file rotation (the
        # reference's MAX_BLOCKFILE_SIZE constant) — lets functional tests
        # exercise pruning without writing 128 MiB of chain
        self.block_store = BlockStore(
            self.datadir, self.params.netmagic,
            max_file_size=config.get_int("maxblockfilesize",
                                         128 * 1024 * 1024),
        )
        self.index_db = BlockIndexDB(self._index_kv)
        # journaled coins commits: every connect/disconnect batch is made
        # durable (fsync-before-rename) before it touches the DB, and
        # ChainstateManager replays/rolls back the journal at startup —
        # a crash at ANY point inside a commit leaves the UTXO set at
        # exactly the pre- or post-block state, never a torn mix.
        # Layout selection: a datadir with the legacy single chainstate
        # file and no shard manifest keeps the old CoinsDB unchanged (the
        # 1-shard degenerate case with the old paths); everything else —
        # fresh datadirs, -reindex, existing sharded datadirs — goes
        # through the sharded facade (store/sharded.py).
        manifest_path = os.path.join(self.datadir, _COINS_MANIFEST)
        if os.path.exists(coins_path) and not os.path.exists(manifest_path):
            self._coins_kv: Optional[KVStore] = KVStore(coins_path)
            self.coins_db = CoinsDB(self._coins_kv,
                                    journal_path=journal_path)
            log_printf("chainstate: legacy single-file layout "
                       "(-reindex migrates to the sharded store)")
        else:
            self._coins_kv = None
            try:
                self.coins_db = ShardedCoinsDB(
                    self.datadir, n_shards=coinshards,
                    wal=config.get_bool("coinswal"))
            except ValueError as e:
                raise ConfigError(f"-coinshards={coinshards}: {e}")
            if self.coins_db.n_shards != coinshards:
                log_printf("chainstate: manifest pins %d shard(s) "
                           "(-coinshards=%d ignored)",
                           self.coins_db.n_shards, coinshards)
        # assumeutxo bookkeeping: a loaded-but-unvalidated snapshot serves
        # RPC at its tip while a background thread re-validates history
        # into a shadow chainstate (load_utxo_snapshot / _snapshot_verify)
        self.snapshot_state: Optional[dict] = getattr(
            self.coins_db, "snapshot_state", None)
        self._snapshot_pending = bool(
            self.snapshot_state
            and not self.snapshot_state.get("validated"))
        self._snapshot_thread: Optional[threading.Thread] = None
        # certificate epoch checkpoints {height: digest_hex} persisted at
        # load time so a restarted shadow validation keeps its O(E)
        # divergence detection instead of regressing to trust-until-tip
        self._cert_checkpoints: Optional[dict] = None
        if self._snapshot_pending:
            from ..store.kvstore import read_json as _read_json

            doc = _read_json(self._snapshot_cert_path())
            if doc and doc.get("checkpoints"):
                self._cert_checkpoints = {
                    int(h): d for h, d in doc["checkpoints"].items()}

        # -maxsigcachesize=<MiB>: byte budget for the signature cache
        # (src/init.cpp DEFAULT_MAX_SIG_CACHE_SIZE). The entry cap is
        # derived FROM the byte budget so the knob governs alone — a fixed
        # entry default would silently bind first above ~17 MiB
        from ..validation.sigcache import ENTRY_COST_BYTES

        sc_bytes = max(1, config.get_int("maxsigcachesize", 32)) * 1024 * 1024
        self.sigcache = SignatureCache(
            max_entries=max(1024, sc_bytes // ENTRY_COST_BYTES),
            max_bytes=sc_bytes,
        )
        self.versionbits_cache = VersionBitsCache()
        backend = config.tpu_backend
        self.backend = backend
        # -ecdsakernel=<glv|w4|msm>: device verify kernel selection. Validated
        # HERE, at startup — an unknown value must fail init (like a
        # malformed -maxsigcachesize), not surface as a per-batch fallback
        # at the first block (ops/ecdsa_batch.set_kernel raises on junk)
        from ..ops import ecdsa_batch as _eb

        if config.has("ecdsakernel"):
            try:
                self.ecdsa_kernel = _eb.set_kernel(config.get("ecdsakernel"))
            except ValueError as e:
                raise ConfigError(str(e)) from None
        else:
            self.ecdsa_kernel = _eb.active_kernel()
        # -compilecache=<dir>: persistent XLA compilation cache (default
        # OFF). The GLV verify programs are ~90 s of cold XLA compile on
        # a CPU backend (BENCH_r08) — with the cache on, every restart,
        # bench subprocess and kernel-pinned import after the first pays
        # a disk read instead. Seeds BCP_COMPILE_CACHE so child processes
        # inherit it; cache hits surface in gettpuinfo.device.
        self.compile_cache = config.get(
            "compilecache", os.environ.get("BCP_COMPILE_CACHE", ""))
        if self.compile_cache:
            from ..util import devicewatch as _dwcc

            try:
                _dwcc.enable_compile_cache(self.compile_cache)
            except (OSError, ValueError) as e:
                raise ConfigError(
                    f"-compilecache={self.compile_cache}: {e}") from None
        # -cashdaa / -daaheight=<n>: enable the BCH-lineage difficulty
        # rules (EDA from activation, cw-144 DAA from daaheight) on this
        # chain — the fork-storm harness crosses the EDA->DAA boundary
        # mid-reorg with these (consensus/pow.py). Applied to the frozen
        # params BEFORE any consensus object is built so every consumer
        # (chainstate, assembler, P2P header checks) sees one rule set.
        if config.get_bool("cashdaa"):
            import dataclasses as _dc

            daa_height = config.get_int("daaheight", 0)
            if daa_height < 0:
                raise ConfigError(
                    f"-daaheight={daa_height}: must be >= 0")
            self.params = _dc.replace(
                self.params,
                consensus=_dc.replace(self.params.consensus,
                                      use_cash_daa=True,
                                      daa_height=daa_height))
        verifier = BlockScriptVerifier(self.params, backend=backend,
                                       sigcache=self.sigcache,
                                       kernel=self.ecdsa_kernel)
        self.chainstate = ChainstateManager(
            self.params, self.coins_db, self.block_store,
            script_verifier=verifier, index_db=self.index_db,
        )
        # -sigservice=<on|off> / -sigservicedeadline=<ms> /
        # -sigservicelanes=<n>: the always-on micro-batching signature
        # service (serving/sigservice). Default ON — with the service off
        # every caller runs the unchanged synchronous path (verdicts
        # identical by construction). Validated here: junk must fail init,
        # not surface at the first transaction.
        svc_mode = config.get("sigservice", "on")
        if svc_mode not in ("on", "off", "1", "0"):
            raise ConfigError(
                f"-sigservice={svc_mode!r}: must be on or off")
        # -watchdogquiet=<seconds>: stall-watchdog quiet period for the
        # SigService flush thread and the pipeline settle horizon
        # (util/devicewatch; observe-only — a stall fires a gauge, a log
        # warning, and a trace instant, never a kill). 0 disables
        # detection; the gauges still export.
        self.watchdog_quiet = config.get_int("watchdogquiet", 10)
        from ..util import devicewatch as _dw

        _dw.WATCHDOG.register(
            "pipeline",
            pending_fn=lambda: len(self.chainstate._spec),
            quiet_s=self.watchdog_quiet)
        # -residentminer=<on|off>: the device-resident mining loop
        # (mining/resident.ResidentSweep — ISSUE 10). Default ON where a
        # batched sweep runs at all; regtest CPU nodes keep the scalar
        # host fast path regardless (see _select_sweep). off = the
        # per-dispatch sweep shapes of PR <=9.
        res_mode = config.get("residentminer", "on")
        if res_mode not in ("on", "off", "1", "0", "force"):
            raise ConfigError(
                f"-residentminer={res_mode!r}: must be on, off or force")
        self.resident_mode = res_mode in ("on", "1", "force")
        # "force" overrides the regtest-CPU scalar fast path too (test/
        # bench hook: exercises the resident loop where mining is trivial)
        self.resident_force = res_mode == "force"
        self.resident_miner = None
        self.sweep_engine = "unselected"
        self.sigservice = None
        if svc_mode in ("on", "1"):
            from ..serving import SigService

            try:
                self.sigservice = SigService(
                    sigcache=self.sigcache,
                    backend="cpu" if backend == "cpu" else "auto",
                    kernel=self.ecdsa_kernel,
                    deadline_ms=config.get_int("sigservicedeadline", 4),
                    lanes=config.get_int("sigservicelanes", 2046),
                    watchdog_quiet=self.watchdog_quiet,
                    # -sigservicebuffers=<n>: in-flight flush slots — 2
                    # overlaps host pack of flush N+1 with device verify
                    # of flush N (1 = the single-slot PR 7 loop)
                    buffers=config.get_int("sigservicebuffers", 2),
                ).start()
            except ValueError as e:
                raise ConfigError(str(e)) from None
            self.chainstate.sig_service = self.sigservice
        # -pipelinedepth=<n>: settle-horizon depth for the Python IBD
        # engine — up to n blocks speculatively connected while their
        # signature batches are in flight (1 = serial; see README
        # "Pipelined validation & the settle horizon")
        self.pipeline_depth = max(1, config.get_int("pipelinedepth", 4))
        self.chainstate.pipeline_depth = self.pipeline_depth
        # -specbranches=<n>: cap on concurrently-validating speculation-
        # tree branches (competing tips); -spechold=<ms>: live-path settle
        # grace — a tip younger than this stays speculative so a fork-race
        # competitor can join the tree (0 = settle eagerly, the default;
        # see README "Speculation tree & fork storms")
        self.spec_branches = config.get_int("specbranches", 4)
        if self.spec_branches < 1:
            raise ConfigError(
                f"-specbranches={self.spec_branches}: must be >= 1")
        spec_hold_ms = config.get_int("spechold", 0)
        if spec_hold_ms < 0:
            raise ConfigError(f"-spechold={spec_hold_ms}: must be >= 0")
        self.spec_hold_s = spec_hold_ms / 1e3
        self.chainstate.max_branches = self.spec_branches
        self.chainstate.spec_hold_s = self.spec_hold_s
        loaded = self.chainstate.load_block_index()
        if loaded:
            log_printf("block index loaded: tip height %d",
                       self.chainstate.tip().height)
        if self._snapshot_pending and loaded:
            # restart mid-assumeutxo: headers along the snapshot chain
            # have no block data yet, so load_block_index left their
            # chain_tx at 0 and parked every descendant — restore the
            # fake linkage before candidate selection runs
            self._fake_snapshot_chaintx()

        if reindex:
            n = self.import_block_files()
            log_printf("-reindex: imported %d blocks, tip height %d",
                       n, self.chainstate.tip().height)
        else:
            # pick up blocks whose index rows were flushed but that were not
            # yet connected at crash time
            self.chainstate.activate_best_chain()
        # -loadblock=<file>: bootstrap.dat-style external imports
        # (init.cpp ThreadImport's vImportFiles leg)
        load_files = config.get_multi("loadblock")
        if load_files:
            n = self.import_block_files(list(load_files))
            log_printf("-loadblock: imported %d blocks, tip height %d",
                       n, self.chainstate.tip().height)

        if self._snapshot_pending:
            # -checkblocks replays recent blocks from local data; below an
            # unvalidated snapshot tip there is none yet. The background
            # verify thread is the (much stronger) integrity check here.
            log_printf("assumeutxo: skipping -checkblocks replay — "
                       "history below the snapshot tip is not local yet")
        else:
            self.verify_db(
                n_blocks=config.get_int("checkblocks", 6),
                level=config.get_int("checklevel", 3),
            )

        self.mempool = CTxMemPool(
            max_size_bytes=config.get_int("maxmempool", 300) * 1_000_000,
            expiry_seconds=config.get_int("mempoolexpiry", 336) * 3600,
            # -mempoolbatch=0 pins the per-tx reference paths everywhere
            # (the differential suite's control); -mempoolselfcheck=1
            # runs the batched-vs-reference gate on every template
            # selection / eviction verdict (debug, like -checkmempool)
            batch=config.get_bool("mempoolbatch", True),
            selfcheck=config.get_bool("mempoolselfcheck", False),
        )
        self.min_relay_fee_rate = config.get_int("minrelaytxfee", 1000)
        # registry collectors (util/telemetry): project this node's
        # sigcache / pipeline / bench / mempool state into the unified
        # metrics namespace at scrape time — the STATS-migration pattern
        # (gettpuinfo keeps reading the same sources directly). A fresh
        # node replaces a closed one's collectors by name.
        telemetry.register_collector("sigcache", self._sigcache_families)
        telemetry.register_collector("pipeline", self._pipeline_families)
        telemetry.register_collector("mempool", self._mempool_families)
        telemetry.register_collector("mempool_perf",
                                     self._mempool_perf_families)
        telemetry.register_collector("mining", self._mining_families)
        telemetry.register_collector("store", self._store_families)
        if self.sigservice is not None:
            telemetry.register_collector("serving", self._serving_families)
        if lockwatch.enabled():
            telemetry.register_collector("lockwatch",
                                         self._lockwatch_families)
        # P2P adversarial-supervision limits (p2p/connman.py): the
        # ban-score discharge threshold, the block-download stall timeout,
        # the supervision tick cadence, the per-peer receive-rate ceiling
        # (bytes/sec, 0 = unlimited), and the deterministic net rng seed
        # (-1 = OS entropy; chaos campaigns pin it for replayability)
        self.net_limits = {
            "banscore": config.get_int("banscore", 100),
            "blockdownloadtimeout":
                config.get_int("blockdownloadtimeout", 60),
            "nettick": config.get_int("nettick", 5),
            "maxrecvrate": config.get_int("maxrecvrate", 4_000_000),
            "netseed": config.get_int("netseed", -1),
            "maxunconnectingheaders":
                config.get_int("maxunconnectingheaders", 10),
        }
        bft = config.get_int("backfilltimeout", 0)
        if bft > 0:
            self.net_limits["backfilltimeout"] = bft
        # -limitancestorcount/-limitancestorsize (kB)/-limitdescendantcount/
        # -limitdescendantsize (kB): ATMP chain limits (validation.h defaults)
        self.ancestor_limits = {
            "limit_count": config.get_int("limitancestorcount", 25),
            "limit_size": config.get_int("limitancestorsize", 101) * 1000,
            "limit_desc": config.get_int("limitdescendantcount", 25),
            "limit_desc_size":
                config.get_int("limitdescendantsize", 101) * 1000,
        }
        # CBlockPolicyEstimator (src/policy/fees.cpp): bucketed
        # confirmation-target tracking with exponential decay, persisted
        # across restarts (mempool/fees.py); fed from accept_to_mempool
        # (entry), _on_block_connected (confirmation), and the mempool
        # removal hook (eviction/expiry/conflict = drop tracking).
        from ..mempool.fees import FeeEstimator

        self.fee_estimator = FeeEstimator(
            os.path.join(self.datadir, "fee_estimates.json")
        )
        # non-block removals (expiry, eviction, conflict) drop tracking;
        # block confirmations are consumed by _on_block_connected FIRST
        self.mempool.on_removed = self.fee_estimator.remove_tx
        self.chainstate.on_block_connected.append(self._on_block_connected)
        self.chainstate.on_block_disconnected.append(self._on_block_disconnected)

        self.flush_interval = config.get_int("flushinterval", DEFAULT_FLUSH_INTERVAL)
        self._blocks_since_flush = 0
        # -dbcache=<MiB>: coins-cache memory budget (init.cpp nCoinCacheUsage
        # -> the FlushStateToDisk IfNeeded trigger). Exceeding it forces a
        # flush regardless of the block-interval policy.
        self.dbcache_bytes = max(1, config.get_int("dbcache", 300)) * 1024 * 1024
        # -prune: 0 = off, 1 = manual (pruneblockchain RPC), >1 = target MB
        prune_arg = config.get_int("prune", 0)
        self.prune_mode = prune_arg > 0
        self.prune_target_bytes = prune_arg * 1_000_000 if prune_arg > 1 else 0
        stored_ph = self._index_kv.get(b"Fpruneheight")
        self.prune_height = int(stored_ph) if stored_ph else 0
        self.txindex = config.get_bool("txindex")
        if self.txindex and self.prune_mode:
            raise InitError("Prune mode is incompatible with -txindex.")
        self._txindex_thread = None
        self._txindex_synced = not self.txindex
        if self.txindex:
            self._start_txindex_backfill()
        self.chainstate.flush()  # persist the (possibly fresh) index/genesis

        self.rpc_server = None
        self.connman = None  # set by start_p2p
        # fleet serving front door (serving/gateway, ISSUE 16):
        # -gateway=<port> binds the admission-controlled load balancer,
        # -replicas=<host:port,...> names the snapshot-bootstrapped read
        # replicas, -maxreplicalag bounds how far a served replica may
        # trail the pool fan-out height (the consistency gate). Flags are
        # validated here so a malformed fleet spec fails init, not the
        # first probe.
        self.gateway = None  # set by start_gateway
        self.gateway_port = config.get_int("gateway", 0)
        if self.gateway_port < 0 or self.gateway_port > 65535:
            raise ConfigError(f"-gateway: invalid port {self.gateway_port}")
        self.max_replica_lag = config.get_int("maxreplicalag", 2)
        if self.max_replica_lag < 0:
            raise ConfigError(
                f"-maxreplicalag must be >= 0 (got {self.max_replica_lag})")
        self.replica_addrs: list[tuple[str, int]] = []
        for spec in str(config.get("replicas", "")).split(","):
            spec = spec.strip()
            if not spec:
                continue
            host, _, port = spec.rpartition(":")
            try:
                self.replica_addrs.append((host or "127.0.0.1", int(port)))
            except ValueError:
                raise ConfigError(
                    f"-replicas: malformed entry '{spec}' "
                    f"(want host:port[,host:port...])") from None
        self.wallet = None  # set by load_wallet
        # wallet-load coordination: RPC threads arriving while another
        # thread is mid-rescan must NOT see partial coin state (the rescan
        # yields cs_main between chunks); they wait on this event instead
        self._wallet_ready = threading.Event()
        self._wallet_loader: Optional[int] = None

        # -zmqpub<topic>=<endpoint> (src/zmq/): like the reference, each
        # distinct endpoint gets its own PUB socket; topics sharing an
        # endpoint share a socket. Accepted forms: tcp://host:port,
        # host:port, or a bare port (host defaults to loopback).
        self.zmq_publishers = []
        by_endpoint: dict[tuple[str, int], set] = {}
        for topic in ("hashblock", "hashtx", "rawblock", "rawtx"):
            val = config.get(f"zmqpub{topic}")
            if not val:
                continue
            spec = str(val)
            if spec.startswith("tcp://"):
                spec = spec[len("tcp://"):]
            host, _, port = spec.rpartition(":")
            by_endpoint.setdefault(
                (host or "127.0.0.1", int(port)), set()).add(topic)
        if by_endpoint:
            from ..rpc.zmq import ZMQPublisher

            for (host, port), topics in by_endpoint.items():
                pub = ZMQPublisher(self, port, topics, host=host)
                pub.start()
                self.zmq_publishers.append(pub)
            self.chainstate.on_block_connected.append(self._zmq_block)

        # LoadMempool (src/validation.cpp): replay mempool.dat unless
        # -persistmempool=0 or we just rebuilt the chainstate
        self.persist_mempool = config.get_bool("persistmempool", True)
        self._mempool_dat = os.path.join(self.datadir, "mempool.dat")
        if self.persist_mempool and not reindex:
            from ..mempool.persist import load_mempool

            load_mempool(self, self._mempool_dat)

        if self._snapshot_pending:
            # restart with an unvalidated snapshot: resume background
            # history validation (the shadow chainstate persisted its own
            # progress, so this picks up where the last run stopped)
            self._start_snapshot_verify()

    # -- telemetry collectors (util/telemetry registry) -----------------

    def _sigcache_families(self) -> list:
        return telemetry.flat_families(
            "bcp_sigcache", self.sigcache.snapshot(), typ="gauge",
            help="validation/sigcache state (entries/bytes gauges, "
                 "hit/miss/insert/eviction tallies)")

    def _pipeline_families(self) -> list:
        cs = self.chainstate
        out = telemetry.flat_families(
            "bcp_pipeline", cs.pipeline_snapshot(), typ="gauge",
            help="pipelined-IBD settle horizon (chainstate.pipeline_stats "
                 "+ cross-block lane packer)")
        out += telemetry.flat_families(
            "bcp_connectblock", cs.bench, typ="counter",
            help="cumulative ConnectBlock phase timings (ms)")
        out += telemetry.flat_families(
            "bcp_bip30", cs.bip30_stats, typ="counter",
            help="BIP30 pre-scan fast-path counters")
        return out

    def _serving_families(self) -> list:
        snap = self.sigservice.snapshot()
        # queue_depth excluded: the native bcp_sigservice_queue_depth
        # gauge owns that name (re-emitting it here would duplicate the
        # family with a conflicting TYPE — the PR 6 in_flight lesson).
        # typ="gauge" like the sibling sigcache collector: the snapshot
        # mixes monotonic tallies with genuinely non-monotonic values
        # (priority_depth, inflight_keys) and config scalars — a TYPE of
        # counter would make rate()/increase() fabricate resets on every
        # decrease.
        snap.pop("queue_depth", None)
        scalars = {k: v for k, v in snap.items()
                   if isinstance(v, (int, float)) and not isinstance(v, bool)}
        return telemetry.flat_families(
            "bcp_sigservice", scalars, typ="gauge",
            help="serving/sigservice micro-batching state (flush reasons, "
                 "dedup/cache hits, preemptions, config)")

    def _mining_families(self) -> list:
        # bcp_mining_state_* prefix: the NATIVE bcp_mining_* counter/
        # histogram families (mining/resident module-level) own their
        # names — re-emitting fifo_depth/tiles under them would duplicate
        # a family with a conflicting TYPE (the PR 6 in_flight lesson).
        # Everything here is a point-in-time projection, so typ="gauge".
        snap = self.mining_snapshot()
        scalars = {k: v for k, v in snap.items()
                   if isinstance(v, (int, float)) and not isinstance(v, bool)}
        return telemetry.flat_families(
            "bcp_mining_state", scalars, typ="gauge",
            help="device-resident mining loop state (template generation, "
                 "segment pipeline, candidate FIFO, rollover passes)")

    def _store_families(self) -> list:
        # bcp_store_state_* prefix: the NATIVE bcp_store_flush_seconds
        # histogram and bcp_store_shard_bytes gauge (store/sharded
        # module-level) own their names — this collector only projects
        # the facade's scalar state (same PR 6 name-ownership lesson as
        # the mining/serving collectors).
        stats_fn = getattr(self.coins_db, "stats", None)
        if stats_fn is None:
            scalars = {"shards": 1, "snapshot_pending": 0}
        else:
            s = stats_fn()
            lf = s.get("last_flush") or {}
            scalars = {
                "shards": s["shards"],
                "epoch": s["epoch"],
                "last_flush_seconds": lf.get("seconds", 0.0),
                "last_flush_coins": lf.get("coins", 0),
                "snapshot_pending": 1 if self._snapshot_pending else 0,
            }
        return telemetry.flat_families(
            "bcp_store_state", scalars, typ="gauge",
            help="sharded chainstate facade state (fan-out, commit epoch, "
                 "last flush, assumeutxo progress)")

    def _mempool_families(self) -> list:
        return [
            {"name": "bcp_mempool_size", "type": "gauge",
             "help": "Transactions in the mempool",
             "samples": [({}, len(self.mempool.entries))]},
            {"name": "bcp_mempool_bytes", "type": "gauge",
             "help": "Serialized mempool size (bytes)",
             "samples": [({}, self.mempool.total_size)]},
        ]

    def _mempool_perf_families(self) -> list:
        # batch-shape observability (ISSUE 20): frontier depths and
        # column occupancy as gauges, the monotone tallies as counters
        snap = self.mempool.perf_snapshot()
        gauges = {
            "frontier_depth_mining": snap["frontier_depth"]["mining"],
            "frontier_depth_evict": snap["frontier_depth"]["evict"],
            "columns_live": snap["columns"]["live"],
            "columns_capacity": snap["columns"]["capacity"],
            "batch": 1 if snap["batch"] else 0,
        }
        counters = {
            "column_syncs": snap["column_syncs"],
            "rows_synced": snap["rows_synced"],
            "frontier_pushes": snap["frontier_pushes"],
            "frontier_stale_pops": snap["frontier_stale_pops"],
            "frontier_rebuilds": snap["frontier_rebuilds"],
            "bulk_evict_episodes": snap["bulk_evict_episodes"],
            "bulk_evicted": snap["bulk_evicted"],
            "staged_removals": snap["staged_removals"],
            "select_batched": snap["select_batched"],
            "select_fallbacks": snap["select_fallbacks"],
            "trim_fallbacks": snap["trim_fallbacks"],
            "selfchecks": snap["selfchecks"],
            "poisoned_verdicts": snap["poisoned_verdicts"],
        }
        return (telemetry.flat_families(
                    "bcp_mempool_perf", gauges, typ="gauge",
                    help="flood-scale mempool state (frontier depth, "
                         "column occupancy, batch mode)")
                + telemetry.flat_families(
                    "bcp_mempool_perf", counters, typ="counter",
                    help="flood-scale mempool tallies (column syncs, "
                         "stale pops, bulk evictions, fallback/gate "
                         "verdicts)"))

    def _lockwatch_families(self) -> list:
        # only registered when the BCP_LOCKWATCH sentinel is on; the
        # bcp_lockwatch prefix owns its namespace (no native families)
        snap = lockwatch.snapshot()
        scalars = {
            "locks": len(snap.get("locks", ())),
            "acquisitions_total": snap.get("acquisitions_total", 0),
            "max_depth": snap.get("max_depth", 0),
            "order_edges": len(snap.get("order_edges", ())),
            "inversions": snap.get("inversions", 0),
        }
        return telemetry.flat_families(
            "bcp_lockwatch", scalars, typ="gauge",
            help="runtime lock-order sentinel (util/lockwatch, "
                 "BCP_LOCKWATCH=1)")

    # -- validation-interface callbacks (CMainSignals analogues) --------

    def notify_waiters(self) -> None:
        """Wake longpoll/waitforblock RPC waiters."""
        with self.notify_cv:
            self.notify_cv.notify_all()

    def wait_for(self, pred, timeout: float):
        """Run pred() under cs_main until it returns non-None or timeout
        (seconds). Returns pred's value or the final (timed-out) value."""
        deadline = time.time() + max(timeout, 0.0)
        while True:
            with self.cs_main:
                val = pred()
            if val is not None:
                return val
            remaining = deadline - time.time()
            if remaining <= 0 or self.shutdown_event.is_set():
                with self.cs_main:
                    return pred()
            with self.notify_cv:
                # bounded wait: a notify can race the re-check, so cap the
                # sleep instead of trusting wakeups alone
                self.notify_cv.wait(min(remaining, 0.5))

    def _on_block_connected(self, block: CBlock, idx) -> None:
        # fee estimator: confirmations MUST be processed before
        # remove_for_block fires on_removed, or confirmed txs would be
        # dropped from tracking as if they failed (fees.py contract)
        self.fee_estimator.process_block(
            idx.height, [tx.txid for tx in block.vtx[1:]]
        )
        self.mempool.remove_for_block(block.vtx)
        if self.txindex:
            self._txindex_add(block, idx)
        self._blocks_since_flush += 1
        if (self._blocks_since_flush >= self.flush_interval
                or self.chainstate.coins.estimated_bytes()
                >= self.dbcache_bytes):
            self.chainstate.flush()
            self._blocks_since_flush = 0
            if self.prune_mode:
                self.auto_prune()
        # -blocknotify=<cmd>: run the shell hook with %s = new block hash
        # (init.cpp BlockNotifyCallback); fire-and-forget, never blocks
        # validation, only on the active tip like the reference. Settled
        # tip, not chain.tip(): during a pipelined import this callback
        # fires at settle time while newer SPECULATIVE blocks sit ahead on
        # the in-memory chain — idx IS the externalizable tip then.
        cmd = self.config.get("blocknotify")
        if cmd and self.chainstate.settled_tip() is idx:
            import subprocess

            from ..consensus.serialize import hash_to_hex as _h2h

            try:
                subprocess.Popen(cmd.replace("%s", _h2h(idx.hash)), shell=True)
            except OSError as e:
                log_printf("blocknotify failed: %r", e)
        self.notify_waiters()

    def _on_block_disconnected(self, block: CBlock, idx) -> None:
        # BlockDisconnected: return the block's transactions to the mempool
        # (reference: DisconnectTip -> mempool resurrection)
        for tx in block.vtx[1:]:
            try:
                # resurrection: entry height unknowable -> no fee sample;
                # use_service=False — this runs mid-disconnect and must
                # never release cs_main around the verdict
                self.accept_to_mempool(tx, fee_estimate=False,
                                       use_service=False)
            except MempoolError:
                pass  # no-longer-valid txs just drop

    def _zmq_publish(self, topic: str, body: bytes) -> None:
        for pub in self.zmq_publishers:  # each filters by its own topics
            pub.publish(topic, body)

    def _zmq_block(self, block: CBlock, idx) -> None:
        """CZMQNotificationInterface::BlockConnected +
        TransactionAddedToMempool-for-confirmed-txs: hashblock/rawblock for
        the block, hashtx/rawtx per transaction."""
        if not self.zmq_publishers:  # torn down mid-shutdown
            return
        if self.chainstate.settled_tip() is not idx:
            return  # only settled-tip connects notify (see -blocknotify)
        self._zmq_publish("hashblock", idx.hash[::-1])  # RPC byte order
        self._zmq_publish("rawblock", block.serialize())
        for tx in block.vtx:
            self._zmq_publish("hashtx", tx.txid[::-1])
            self._zmq_publish("rawtx", tx.serialize())

    # -- mempool entry point -------------------------------------------

    @contextmanager
    def _verify_wait(self):
        """SigService verdict-wait window: release cs_main (when held by
        this thread, exactly one level deep) so concurrent accepts can
        scan and share the in-flight device bucket; reacquire before the
        caller resumes. A deeper re-entrant hold just skips the release —
        correct (the post-wait stale-context re-check finds an unchanged
        world), only less concurrent."""
        released = False
        try:
            self.cs_main.release()
            released = True
        except RuntimeError:
            pass  # not held by us — nothing to release
        try:
            yield
        finally:
            if released:
                self.cs_main.acquire()

    def accept_to_mempool(self, tx, now: Optional[int] = None,
                          fee_estimate: bool = True,
                          use_service: bool = True):
        """AcceptToMemoryPool with this node's policy knobs; caller holds
        cs_main (or is single-threaded). fee_estimate=False for replayed
        txs (mempool.dat reload, reorg resurrection) — their true entry
        height is unknown, and counting them from the current tip would
        bias tight-target estimates low (the reference's
        validFeeEstimate=false). use_service=False keeps the verdict
        synchronous AND the lock held throughout — required on the reorg
        resurrection path, where releasing cs_main mid-disconnect would
        expose half-reorged chainstate to other threads."""
        svc = self.sigservice if use_service else None
        entry = accept_to_memory_pool(
            self.mempool, self.chainstate, tx,
            sigcache=self.sigcache,
            min_fee_rate=self.min_relay_fee_rate,
            backend="cpu" if self.backend == "cpu" else "auto",
            now=now,
            ancestor_limits=self.ancestor_limits,
            sig_service=svc,
            wait_ctx=self._verify_wait if svc is not None else None,
        )
        # fee estimator: track entry height + what the tx actually pays
        # (base fee, not prioritisetransaction-modified fees)
        if fee_estimate and entry.size > 0:
            self.fee_estimator.process_tx(
                tx.txid, self.chainstate.tip().height,
                entry.base_fee * 1000 / entry.size,
            )
        # TransactionAddedToMempool (validationinterface): a loaded wallet
        # tracks unconfirmed receives/spends so it won't double-spend coins
        # already committed by in-pool txs (e.g. after a mempool.dat reload)
        if self.wallet is not None:
            self.wallet.add_tx_if_mine(tx, -1, False)
        if self.zmq_publishers:
            # TransactionAddedToMempool → hashtx/rawtx
            self._zmq_publish("hashtx", tx.txid[::-1])
            self._zmq_publish("rawtx", tx.serialize())
        self.notify_waiters()
        return entry

    # -- mining ---------------------------------------------------------

    def assembler(self) -> BlockAssembler:
        return BlockAssembler(self.chainstate, self.mempool,
                              versionbits_cache=self.versionbits_cache)

    def _select_sweep(self):
        """Pick the PoW sweep for this backend. Default: the DEVICE-
        RESIDENT loop (mining/resident.ResidentSweep, -residentminer=on) —
        a persistent segment pipeline over long-lived template buffers,
        h7-truncated kernel on a real accelerator (fewest ops/nonce,
        candidates host-verified bit-identical) and the exact-compare
        kernel on CPU backends (where the unrolled h7 program's XLA
        compile is pathologically slow — ops/sha256._use_unrolled). With
        -residentminer=off, the PR<=9 per-dispatch shapes: truncated-h7
        sweep_header_fast on the accelerator, the generic looped sweep on
        CPU. Every choice runs under miner-breaker supervision
        (ops/dispatch.supervised_sweep): failures degrade to the scalar
        host loop without stalling block production.

        Regtest on a CPU backend takes the scalar host loop DIRECTLY: the
        trivial target hits within a couple of nonces, so the batched
        sweep's per-dispatch latency (~160 ms of device round-trip per
        block on the CPU jit) dominates a ~2-hash search — generatetoaddress
        at functional-test scale was paying minutes of pure dispatch
        overhead. Real networks keep the batched sweep, where throughput,
        not latency, is what matters."""
        from ..ops.dispatch import supervised_resident_sweep, supervised_sweep

        inner = None
        engine = "generic-dispatch"
        try:
            from ..ops.sha256 import backend_is_cpu

            on_cpu = backend_is_cpu()
            if (on_cpu and self.params.network == "regtest"
                    and not self.resident_force):
                from ..ops.miner import sweep_header_cpu

                engine = "scalar-host"

                def inner(header80, target, start_nonce=0,
                          max_nonces=1 << 32, tile=None):
                    return sweep_header_cpu(header80, target,
                                            start_nonce=start_nonce,
                                            max_nonces=max_nonces)
            elif self.resident_mode:
                if self.resident_miner is None:
                    from ..mining.resident import ResidentSweep

                    kernel = "exact" if on_cpu else "h7"
                    # CPU backends take a smaller tile: the looped-
                    # compress kernel executes ~6k vector ops/nonce on
                    # host ALUs, so a 64Ki tile would make each segment
                    # settle hundreds of ms
                    self.resident_miner = ResidentSweep(
                        tile=(1 << 14) if on_cpu else (1 << 16),
                        kernel=kernel)
                    self.resident_miner.register_watchdog(
                        self.watchdog_quiet)
                engine = f"resident-{self.resident_miner.kernel}"
            elif not on_cpu:
                from ..ops.sha256_sweep import sweep_header_fast

                engine = "h7-dispatch"
                inner = sweep_header_fast
        except Exception:
            pass
        self.sweep_engine = engine
        if engine.startswith("resident-"):
            return supervised_resident_sweep(self.resident_miner)
        return supervised_sweep(inner)

    def mining_snapshot(self) -> dict:
        """gettpuinfo's ``mining`` section: the active sweep engine and,
        when the resident loop is live, its full state (template
        generation, tiles swept, candidate FIFO, buffer swaps, poll
        cadence)."""
        out = {"engine": self.sweep_engine, "resident": False,
               "resident_enabled": self.resident_mode}
        if self.resident_miner is not None:
            out.update(self.resident_miner.snapshot())
        return out

    def generate_to_script(self, script_pubkey: bytes, n_blocks: int,
                           max_tries: int = MAX_TRIES_DEFAULT) -> list[bytes]:
        """generatetoaddress backend (src/rpc/mining.cpp generateBlocks)."""
        hashes: list[bytes] = []
        asm = self.assembler()
        sweep = self._select_sweep()
        for _ in range(n_blocks):
            # per-block extranonce entropy: with sub-second mining the
            # header time pins to MTP+1, and two nodes extending the same
            # parent toward the same script would otherwise assemble
            # byte-identical blocks — a reorg race that never forks
            block = mine_block(asm, script_pubkey, max_tries=max_tries,
                               sweep=sweep,
                               extranonce_start=int.from_bytes(
                                   os.urandom(4), "little"))
            if block is None:
                break
            self.chainstate.process_new_block(block)
            hashes.append(block.get_hash())
        return hashes

    def submit_block(self, block: CBlock) -> Optional[str]:
        """submitblock semantics: None on accept, reject-reason string
        otherwise ('duplicate' when we already have full data)."""
        idx = self.chainstate.block_index.get(block.get_hash())
        if idx is not None and (idx.status & BlockStatus.HAVE_DATA):
            if idx.status & BlockStatus.FAILED_MASK:
                return "duplicate-invalid"
            return "duplicate"
        try:
            self.chainstate.process_new_block(block)
        except BlockValidationError as e:
            return e.reason
        if self.connman is not None:
            self.connman.relay_block(block.get_hash())
        return None

    # -- startup integrity + import ------------------------------------

    def verify_db(self, n_blocks: int = 6, level: int = 3) -> bool:
        """CVerifyDB::VerifyDB (src/validation.cpp:~3700): walk back from the
        tip re-checking recent blocks. Level >=1 re-runs CheckBlock; >=2
        checks undo data presence/decodability; >=3 replays
        disconnect/reconnect on a scratch view checking UTXO consistency."""
        cs = self.chainstate
        tip = cs.tip()
        if tip is None or tip.height == 0 or n_blocks <= 0:
            return True
        from ..validation.coins import BlockUndo, CoinsCache

        # blocks at or below an adopted snapshot tip carry no undo data
        # (history was re-validated by digest in the shadow chainstate,
        # never connected here) — the replay walk must stop above them
        snap = getattr(self, "snapshot_state", None) or {}
        floor = int(snap.get("height", 0))

        checked = 0
        idx = tip
        scratch = CoinsCache(cs.coins)
        to_reconnect = []
        while idx is not None and idx.height > floor and checked < n_blocks:
            raw = cs.block_store.get_block(idx.hash)
            if raw is None:
                raise InitError(f"VerifyDB: missing block data at height {idx.height}")
            block = CBlock.from_bytes(raw)
            if level >= 1:
                cs.check_block(block)
            if level >= 2:
                undo_raw = cs.block_store.get_undo(idx.hash)
                if undo_raw is None:
                    raise InitError(f"VerifyDB: missing undo data at height {idx.height}")
                undo = BlockUndo.from_bytes(undo_raw)
                if level >= 3:
                    cs.disconnect_block(block, idx, undo, view=scratch)
                    to_reconnect.append((block, idx))
            checked += 1
            idx = idx.prev
        if level >= 4:
            for block, bidx in reversed(to_reconnect):
                cs.connect_block(block, bidx, check_scripts=False, view=scratch)
        # scratch view is discarded — this was a read-only replay
        log_print("db", "VerifyDB: %d blocks verified at level %d", checked, level)
        return True

    # -- assumeutxo snapshot onboarding ---------------------------------
    # Reference: Bitcoin Core's assumeutxo (src/node/utxo_snapshot,
    # doc/design/assumeutxo.md): adopt an operator-authorized UTXO
    # snapshot at its tip and serve immediately, while a background
    # chainstate re-validates all of history from genesis into a SHADOW
    # store and promotes the node to fully-validated only when the
    # shadow's recomputed set digest equals the snapshot's.

    def store_info(self) -> dict:
        """The gettpuinfo 'store' section."""
        stats_fn = getattr(self.coins_db, "stats", None)
        if stats_fn is None:
            info: dict = {"backend": "single"}
        else:
            info = stats_fn()
            info["backend"] = "sharded"
        info["snapshot"] = self.snapshot_state
        return info

    def _snapshot_cert_path(self) -> str:
        return os.path.join(self.datadir, "snapshot_cert.json")

    def snapshot_info(self) -> Optional[dict]:
        """The getblockchaininfo 'snapshot' sub-doc — the certificate /
        quarantine view the fleet probe keys on. ``certificate_verified``
        is the serving gate: True when the snapshot carried a verified
        certificate (trust established at load, in seconds) OR when the
        background replay finished (trust established the slow way).
        Absent entirely on nodes that never onboarded from a snapshot."""
        snap = self.snapshot_state
        if not snap:
            return None
        cert = snap.get("cert") or {}
        validated = bool(snap.get("validated"))
        return {
            "height": snap.get("height"),
            "validated": validated,
            "cert_present": bool(cert.get("present")),
            "cert_verified": bool(cert.get("verified")),
            "certificate_verified": bool(cert.get("verified")) or validated,
        }

    def build_snapshot_certificate(self, height: int) -> Optional[dict]:
        """Produce the proof-carrying certificate for a dumptxoutset at
        ``height`` (store/certificate.py), or None when this node cannot
        attest (it onboarded from a snapshot itself and lacks undo data
        below the snapshot tip, or the legacy store has no accumulator).

        The epoch trajectory is reconstructed EXACTLY from undo data by
        walking blocks tip->1 and dividing each block's delta out of the
        live accumulator state — no new runtime bookkeeping, and reorgs
        are a non-issue because the walk happens under cs_main against
        the settled chain."""
        import struct as _struct

        from ..store import certificate as cert_mod
        from ..validation.coins import BlockUndo, Coin

        state_fn = getattr(self.coins_db, "muhash_state", None)
        if state_fn is None:
            return None
        cs = self.chainstate
        header_hashes = [cs.chain[h].hash for h in range(height + 1)]

        def deltas():
            for h in range(height, 0, -1):
                idx = cs.chain[h]
                raw = self.block_store.get_block(idx.hash)
                if raw is None:
                    raise cert_mod.CertificateError(
                        f"no block data at height {h} (snapshot-onboarded "
                        "node without full backfill cannot attest)")
                block = CBlock.from_bytes(raw)
                created = []
                for tx in block.vtx:
                    txid = tx.txid
                    cb = tx is block.vtx[0]
                    for i, out in enumerate(tx.vout):
                        created.append((
                            txid + _struct.pack("<I", i),
                            Coin(out, h, cb).serialize()))
                spent = []
                if len(block.vtx) > 1:
                    rawu = self.block_store.get_undo(idx.hash)
                    if rawu is None:
                        raise cert_mod.CertificateError(
                            f"no undo data at height {h}")
                    undo = BlockUndo.from_bytes(rawu)
                    for t, tx in enumerate(block.vtx[1:]):
                        for vin, coin in zip(tx.vin, undo.vtxundo[t].prevouts):
                            spent.append((
                                vin.prevout.hash
                                + _struct.pack("<I", vin.prevout.n),
                                coin.serialize()))
                yield h, created, spent

        return cert_mod.build_certificate(
            header_hashes, height, self.snapshot_epoch, state_fn(), deltas())

    def load_utxo_snapshot(self, path: str) -> dict:
        """loadtxoutset: adopt the snapshot directory at ``path``.

        Requires -assumeutxo authorization and a fresh node (tip still at
        genesis). On return the node serves RPC at the snapshot tip;
        history validation proceeds in the background."""
        from ..consensus.block import CBlockHeader
        from ..consensus.serialize import ByteReader
        from ..store import snapshot as snapshot_mod
        from ..validation.coins import CoinsCache

        if self.assumeutxo is None:
            raise ValueError(
                "loadtxoutset requires -assumeutxo=<blockhash>:<muhash> "
                "authorization")
        if not isinstance(self.coins_db, ShardedCoinsDB):
            raise ValueError("loadtxoutset requires the sharded chainstate "
                             "layout (-reindex migrates legacy datadirs)")
        exp_hash, exp_digest = self.assumeutxo
        with self.cs_main:
            if self.chainstate.tip().height != 0:
                raise ValueError(
                    "loadtxoutset requires a fresh node (tip at genesis)")
            self.chainstate.flush()  # settle genesis state first
            info = snapshot_mod.load_snapshot(
                path, self.coins_db, self.params.network,
                expected_hash=exp_hash, expected_digest=exp_digest,
                require_certificate=self.snapshot_cert_required)
            cs = self.chainstate
            # headers go through the normal PoW/contextual checks — the
            # snapshot is trusted for the COIN SET only, never for work
            for raw80 in info["headers"]:
                hdr = CBlockHeader.deserialize(ByteReader(raw80))
                if hdr.get_hash() in cs.block_index:
                    continue  # genesis (and any already-known header)
                cs.accept_block_header(hdr)
            tip_idx = cs.block_index.get(info["best_block"])
            if tip_idx is None or tip_idx.height != info["height"]:
                raise snapshot_mod.SnapshotError(
                    "snapshot headers do not reach the snapshot tip")
            cs.chain.set_tip(tip_idx)
            self._fake_snapshot_chaintx()
            # fresh cache over the loaded store — the old one cached
            # genesis-era state that the bulk load just superseded
            cs.coins = CoinsCache(self.coins_db)
            cs.flush()
            self.snapshot_state = self.coins_db.snapshot_state
            self._snapshot_pending = True
            self._cert_checkpoints = info.get("cert_checkpoints")
            if self._cert_checkpoints:
                # persist for restart-resume: the shadow validator must
                # keep its epoch-divergence tripwires across restarts
                from ..store.kvstore import atomic_write_json

                atomic_write_json(self._snapshot_cert_path(), {
                    "checkpoints": {str(h): d for h, d in
                                    self._cert_checkpoints.items()},
                    "epoch_blocks": info["certificate"]["epoch_blocks"],
                })
            log_printf("assumeutxo: serving at snapshot tip %s (height %d)"
                       " — background validation starting%s",
                       hash_to_hex(tip_idx.hash)[:16], tip_idx.height,
                       "" if info.get("certificate") else
                       "; UNCERTIFIED snapshot — replica serving "
                       "quarantined until validation completes")
        with self.notify_cv:
            self.notify_cv.notify_all()
        self._start_snapshot_verify()
        return {"height": info["height"],
                "hash": info["manifest"]["best_block"],
                "coins": info["manifest"]["coins"],
                "muhash": info["manifest"]["muhash"]}

    def _fake_snapshot_chaintx(self) -> None:
        """Core's fake-nChainTx trick: blocks along the snapshot chain
        have headers but (until backfill) no data, so their true tx counts
        are unknown — stamp placeholder n_tx/chain_tx so candidate
        selection and descendant linkage work above the snapshot tip.
        Real counts overwrite the fakes as block data arrives."""
        cs = self.chainstate
        tip = cs.chain.tip()
        if tip is None:
            return
        running = 0
        for h in range(tip.height + 1):
            idx = cs.chain[h]
            if idx.n_tx == 0:
                idx.n_tx = 1
            running += idx.n_tx
            idx.chain_tx = running
            cs._dirty_index.add(idx)
        # relink descendants parked behind chain_tx==0 ancestors
        for h in range(tip.height + 1):
            idx = cs.chain[h]
            for child in cs._unlinked.pop(idx, []):
                cs._link_chain_tx(child)

    def _start_snapshot_verify(self) -> None:
        if self._snapshot_thread is not None and \
                self._snapshot_thread.is_alive():
            return
        self._snapshot_thread = threading.Thread(
            target=self._snapshot_verify_loop,
            name="assumeutxo-verify", daemon=True)
        self._snapshot_thread.start()

    def _snapshot_verify_loop(self) -> None:
        """Background history validation (the assumeutxo promise).

        A SHADOW chainstate — its own sharded coins store + block index
        under datadir/chainstate_shadow, block/undo writes discarded —
        replays every block genesis..snapshot-tip through the full
        consensus path. Blocks not yet local are pulled from peers via
        connman.request_backfill. On reaching the snapshot height the
        shadow's recomputed MuHash digest must equal the snapshot digest;
        only then is the chain marked fully validated. The shadow persists
        its own progress, so a restart resumes instead of starting over."""
        import shutil

        state = dict(self.snapshot_state or {})
        target_h = int(state["height"])
        shadow_dir = os.path.join(self.datadir, "chainstate_shadow")
        os.makedirs(shadow_dir, exist_ok=True)
        shadow_coins = ShardedCoinsDB(
            shadow_dir, n_shards=getattr(self.coins_db, "n_shards", 1))
        shadow_index_kv = KVStore(os.path.join(shadow_dir, "index.sqlite"))
        verifier = BlockScriptVerifier(self.params, backend=self.backend,
                                       sigcache=SignatureCache(),
                                       kernel=self.ecdsa_kernel)
        shadow = ChainstateManager(
            self.params, shadow_coins, _ShadowBlockStore(self),
            script_verifier=verifier,
            index_db=BlockIndexDB(shadow_index_kv))
        # the shadow's ctor re-registered the pipeline watchdog against
        # ITSELF (registration replaces by name) — restore the live
        # manager's probe immediately
        from ..util import devicewatch as _dw

        _dw.WATCHDOG.register(
            "pipeline",
            pending_fn=lambda: len(self.chainstate._spec),
            quiet_s=self.watchdog_quiet)
        # certificate epoch tripwires: {checkpoint height: expected digest}
        # verified as the replay crosses each boundary — a forged epoch is
        # caught O(E) blocks past the forgery, not at height H
        import bisect as _bisect

        cps = self._cert_checkpoints or {}
        cp_heights = sorted(cps)
        sampled: Optional[set] = None
        if cps and self.snapshot_spotcheck > 0:
            from ..store import certificate as _cert_mod

            sampled = set(_cert_mod.sample_epochs(
                cp_heights, self.snapshot_spotcheck, self._spotcheck_seed))
            log_printf("assumeutxo: spot-check mode — full script "
                       "re-validation on %d/%d certified epochs %s; digest "
                       "tripwires stay armed at every boundary",
                       len(sampled), len(cp_heights), sorted(sampled))

        def _epoch_end(height: int) -> Optional[int]:
            i = _bisect.bisect_left(cp_heights, height)
            return cp_heights[i] if i < len(cp_heights) else None

        ok = False
        try:
            shadow.load_block_index()
            h = shadow.tip().height + 1
            if h > 1:
                log_printf("assumeutxo: shadow validation resuming at "
                           "height %d/%d", h, target_h)
            since_flush = 0
            while h <= target_h and not self.shutdown_event.is_set():
                with self.cs_main:
                    idx = self.chainstate.chain[h]
                    raw = (self.block_store.get_block(idx.hash)
                           if idx is not None else None)
                if raw is None:
                    # history not local yet — name the missing heights to
                    # the P2P layer (header sync can't: peers announce
                    # nothing below our locator's snapshot tip)
                    missing = []
                    with self.cs_main:
                        for hh in range(h, min(h + 64, target_h + 1)):
                            i2 = self.chainstate.chain[hh]
                            if i2 is not None and \
                                    not (i2.status & BlockStatus.HAVE_DATA):
                                missing.append(i2.hash)
                    if missing and self.connman is not None:
                        self.connman.request_backfill(missing)
                    self.shutdown_event.wait(0.25)
                    continue
                if sampled is not None:
                    # spot-check: blocks outside the K sampled epochs
                    # replay without script verification (UTXO algebra,
                    # PoW and digest tripwires still fully enforced) —
                    # the onboarding-economics lever the certificate buys
                    shadow.script_verifier = (
                        verifier if _epoch_end(h) in sampled else None)
                if not shadow.process_new_block(CBlock.from_bytes(raw)):
                    log_printf("assumeutxo: shadow validation REJECTED "
                               "block at height %d — snapshot chain is "
                               "invalid, promotion abandoned", h)
                    if self.connman is not None:
                        self.connman.cancel_backfill()
                    return
                if h in cps:
                    shadow.flush()
                    since_flush = 0
                    got = shadow_coins.muhash_digest().hex()
                    if got != cps[h]:
                        log_printf(
                            "assumeutxo: EPOCH DIGEST DIVERGENCE at "
                            "certified checkpoint %d (got %s, certificate "
                            "%s) — snapshot content is FORGED in epoch "
                            "ending here; hard abort for manual "
                            "intervention", h, got[:16], cps[h][:16])
                        if self.connman is not None:
                            self.connman.cancel_backfill()
                        self.shutdown_event.set()
                        return
                h += 1
                since_flush += 1
                if since_flush >= 64:
                    shadow.flush()
                    since_flush = 0
            if h <= target_h:
                return  # shutdown mid-validation: shadow resumes later
            shadow.flush()
            got = shadow_coins.muhash_digest().hex()
            want = state["digest"]
            if got != want or shadow.tip().hash != \
                    bytes.fromhex(state["hash"])[::-1]:
                log_printf("assumeutxo: DIGEST MISMATCH after full replay "
                           "(got %s, snapshot %s) — the snapshot was bad; "
                           "shutting down for manual intervention",
                           got[:16], want[:16])
                if self.connman is not None:
                    self.connman.cancel_backfill()
                self.shutdown_event.set()
                return
            with self.cs_main:
                cs = self.chainstate
                for hh in range(1, target_h + 1):
                    bidx = cs.chain[hh]
                    bidx.raise_validity(BlockStatus.VALID_SCRIPTS)
                    cs._dirty_index.add(bidx)
                state["validated"] = True
                self.coins_db.set_snapshot_state(state)
                self.snapshot_state = state
                self._snapshot_pending = False
                cs.flush()
            ok = True
            log_printf("assumeutxo: background validation complete at "
                       "height %d — digest matches, chain fully validated",
                       target_h)
        except Exception as e:  # noqa: BLE001 — thread must not die silent
            log_printf("assumeutxo: shadow validation error: %r", e)
        finally:
            shadow_coins.close()
            shadow_index_kv.close()
            if ok:
                shutil.rmtree(shadow_dir, ignore_errors=True)
                if os.path.exists(self._snapshot_cert_path()):
                    os.remove(self._snapshot_cert_path())

    def import_block_files(self, paths: Optional[list[str]] = None) -> int:
        """LoadExternalBlockFile (src/validation.cpp:~4000) over every
        blk?????.dat (or the explicit ``paths`` — the -loadblock /
        bootstrap.dat form): scan (netmagic, size, block) records,
        re-register data positions, and ProcessNewBlock each one.
        Out-of-order blocks park via accept-header failure and are retried
        once their parent lands.

        Two engines run this path. The NATIVE fast import (the reference's
        all-C++ pipeline shape: parse, sanity, merkle, UTXO apply, undo and
        the P2PKH sig scan in native/connect.cpp; TPU batch for the ECDSA
        math) handles the dominant linear case; the Python loop below is
        the reference implementation and handles everything the fast path
        declines (reorgs, invalid blocks, -loadblock, hook listeners) —
        every fast-path block still ends in a byte-identical chainstate
        (differential: tests/unit/test_native_connect.py)."""
        from .. import native as _nat

        if (paths is None
                and _nat.engine_available()
                and not os.environ.get("BCP_NO_NATIVE_IMPORT")
                and not self.chainstate.on_block_connected
                and not self.chainstate.on_block_disconnected):
            try:
                return self._import_block_files_native()
            except _NativeImportAbort as e:
                # rare: an in-flight signature batch failed after its block
                # was staged — rebuild the in-memory state from the last
                # flush and let the Python engine produce the verdict
                log_printf("native import aborted (%s); replaying through "
                           "the Python engine", e)
                self._rebuild_chainstate_from_disk()
        return self._import_block_files_python(paths)

    def _rebuild_chainstate_from_disk(self) -> None:
        """Reset the in-memory chain objects to the last flushed on-disk
        state (the native fast-import recovery path). Only callable before
        servers start — import runs during init."""
        verifier = BlockScriptVerifier(self.params, backend=self.backend,
                                       sigcache=self.sigcache,
                                       kernel=self.ecdsa_kernel)
        self.block_store.positions.clear()
        self.block_store.undo_positions.clear()
        self.chainstate = ChainstateManager(
            self.params, self.coins_db, self.block_store,
            script_verifier=verifier, index_db=self.index_db,
        )
        self.chainstate.pipeline_depth = self.pipeline_depth
        self.chainstate.max_branches = self.spec_branches
        self.chainstate.spec_hold_s = self.spec_hold_s
        self.chainstate.sig_service = self.sigservice
        # the fresh manager re-registered the pipeline watchdog with the
        # env default quiet — restore this node's -watchdogquiet wiring
        from ..util import devicewatch as _dw

        _dw.WATCHDOG.register(
            "pipeline",
            pending_fn=lambda: len(self.chainstate._spec),
            quiet_s=getattr(self, "watchdog_quiet", None))
        self.chainstate.load_block_index()

    def _import_block_files_native(self) -> int:
        """The fast -reindex import: native connect engine + packed TPU
        signature batches, linear-extension blocks only (anything else
        flushes and defers to the Python engine per block)."""
        import struct

        import numpy as np

        from .. import native
        from ..consensus.block import CBlockHeader
        from ..consensus.params import get_block_subsidy
        from ..consensus.serialize import ByteReader
        from ..consensus.tx import CTransaction
        from ..ops import ecdsa_batch
        from ..script.interpreter import (
            SCRIPT_VERIFY_NULLFAIL,
            DeferringSignatureChecker,
            ScriptError,
            VerifyScript,
        )
        from ..script.script import script_int
        from ..script.sighash import SighashCache
        from ..validation.chain import BlockStatus, CBlockIndex
        from ..validation.scriptcheck import block_script_flags

        cs = self.chainstate
        params = self.params
        consensus = params.consensus
        magic = params.netmagic
        # import runs before __init__ assigns the post-import knobs
        flush_interval = self.config.get_int("flushinterval",
                                             DEFAULT_FLUSH_INTERVAL)
        dbcache_bytes = max(
            1, self.config.get_int("dbcache", 300)) * 1024 * 1024
        t_start = time.perf_counter()
        cs.flush()  # the engine's base view must be current before takeover

        eng = native.ConnectEngine()
        eng.set_best(cs.coins.best_block())
        stats = {"blocks": 0, "bytes": 0, "native_connect_s": 0.0,
                 "sigscan_s": 0.0, "verify_s": 0.0, "flush_s": 0.0,
                 "slow_path_blocks": 0, "fallback_inputs": 0,
                 "fast_inputs": 0}
        n_imported = 0
        pending: dict[bytes, list[tuple[bytes, Optional[tuple]]]] = {}
        # in-flight signature batches: (block hash, BatchHandle)
        inflight: list[tuple[bytes, object]] = []
        MAX_INFLIGHT = 3
        # cross-block record aggregation: mainnet blocks carry ~2-5k sig
        # inputs, but the device rate at 8k+ lanes is ~1.7x the 2048-lane
        # rate (per-dispatch tunnel latency amortizes) — aggregate fast
        # records across blocks and dispatch at AGG_LANES. Failure
        # granularity stays sound: a bad batch aborts to the Python
        # replay, which re-derives the exact offending block.
        # 8190 = 8192-bucket minus the 2 supervised-dispatch KAT lanes
        # (ops/ecdsa_batch appends them per batch; an exact-8192 slice
        # would spill into the 10240 bucket and pay a fresh compile).
        AGG_LANES = 8190
        agg: list[tuple] = []  # (pub, rs, msg, rn, wrap) per block
        agg_count = [0]
        agg_last_hash = [b""]

        def flush_agg(everything: bool = True):
            if not agg:
                return
            t0 = time.perf_counter()
            arrays = [np.concatenate([a[i] for a in agg])
                      for i in range(5)]
            agg.clear()
            pos = 0
            total = len(arrays[2])
            # dispatch EXACT AGG_LANES slices: the jit bakes the bucket
            # into the program, so steady-state flushes must reuse ONE
            # compiled shape (a stray 10240-lane flush pays a fresh
            # ~60 s Mosaic compile on the tunneled chip); only the final
            # sub-AGG_LANES tail may hit a second bucket
            while total - pos >= AGG_LANES:
                sl = slice(pos, pos + AGG_LANES)
                handle = ecdsa_batch.dispatch_packed(
                    *(a[sl] for a in arrays),
                    backend=self.backend if self.backend == "cpu"
                    else "auto")
                inflight.append((agg_last_hash[0], handle))
                pos += AGG_LANES
            if everything:
                # drain the tail in <=2046-lane chunks (2048-bucket minus
                # the KAT lanes): together with the AGG_LANES slices this
                # bounds the compiled-shape set to {8192, 2048, 1024} for
                # the whole import
                while pos < total:
                    end = min(pos + 2046, total)
                    handle = ecdsa_batch.dispatch_packed(
                        *(a[pos:end] for a in arrays),
                        backend=self.backend if self.backend == "cpu"
                        else "auto")
                    inflight.append((agg_last_hash[0], handle))
                    pos = end
            if pos < total:
                agg.append(tuple(a[pos:] for a in arrays))
            agg_count[0] = total - pos
            dt = time.perf_counter() - t0
            stats["verify_s"] += dt
            cs.bench["verify_ms"] += dt * 1e3
            while len(inflight) > MAX_INFLIGHT:
                settle_oldest()

        def settle_oldest():
            h, handle = inflight.pop(0)
            t0 = time.perf_counter()
            ok = handle.result()
            dt = time.perf_counter() - t0
            stats["verify_s"] += dt
            cs.bench["verify_ms"] += dt * 1e3
            if not bool(np.all(ok)):
                raise _NativeImportAbort(
                    f"sig batch failed in block {hash_to_hex(h)[:16]}"
                )

        def settle_all():
            flush_agg()
            while inflight:
                settle_oldest()

        def fast_flush():
            settle_all()
            t0 = time.perf_counter()
            self.block_store.flush()
            cs.flush_index()
            best = eng.best()
            self.coins_db.batch_write_serialized(eng.flush_entries(), best)
            eng.clear()
            # keep the Python cache's best-block in step: a later
            # cs.flush() must not rewind the marker to its stale cached
            # value (it survives CoinsCache.flush)
            cs.coins.set_best_block(best)
            dt = time.perf_counter() - t0
            stats["flush_s"] += dt
            cs.bench["flush_ms"] += dt * 1e3

        def service_misses(missing_keys) -> int:
            rows = self.coins_db.get_serialized_many(missing_keys)
            for key, ser in rows.items():
                r = ByteReader(ser)
                from ..consensus.serialize import (
                    deser_compact_size,
                    deser_var_bytes,
                )

                code = deser_compact_size(r, range_check=False)
                value = deser_compact_size(r, range_check=False)
                spk = deser_var_bytes(r)
                eng.insert(key, code, value, spk)
            return len(rows)

        def slow_path(raw: bytes, pos_info: Optional[tuple]) -> bool:
            """Flush engine state, process via the Python engine, resync."""
            stats["slow_path_blocks"] += 1
            fast_flush()
            block = CBlock.from_bytes(raw)
            connected = try_process(block, pos_info)
            cs.flush()
            eng.set_best(cs.coins.best_block())
            return connected

        def try_process(block: CBlock, pos_info: Optional[tuple]) -> bool:
            """The Python-engine leg (same parking semantics as the
            reference loop below)."""
            nonlocal n_imported
            h = block.get_hash()
            if pos_info is not None:
                self.block_store.positions.setdefault(h, pos_info)
            try:
                self.chainstate.process_new_block(block)
            except BlockValidationError as e:
                if e.reason == "prev-blk-not-found":
                    pending.setdefault(block.header.hash_prev_block,
                                       []).append((block.serialize(),
                                                   pos_info))
                elif e.reason != "duplicate":
                    log_printf("reindex: rejected %s: %s",
                               hash_to_hex(h)[:16], e.reason)
                return False
            n_imported += 1
            return True

        def fast_connect(raw: bytes, h: bytes, prev, pos_info) -> bool:
            """One linear-extension block through the native engine.
            Returns False when the block must go through the Python path."""
            nonlocal n_imported
            header = CBlockHeader.deserialize(ByteReader(raw[:80]))
            try:
                cs.check_block_header(header)
                cs.contextual_check_block_header(header, prev)
            except BlockValidationError:
                return False  # Python path gives the authoritative verdict
            height = prev.height + 1
            idx = CBlockIndex(header, h, prev)
            check_scripts = (cs.script_checks_needed(idx)
                             and cs.script_verifier is not None)
            flags = block_script_flags(height, header.time, params)
            if check_scripts and not (flags & SCRIPT_VERIFY_NULLFAIL):
                return False  # pre-NULLFAIL: inline-verify via Python
            bip34 = (script_int(height)
                     if height >= consensus.bip34_height else None)
            mtp = prev.get_median_time_past()
            subsidy = get_block_subsidy(height, consensus)
            t0 = time.perf_counter()
            try:
                try:
                    res = eng.connect_block(
                        raw, height, subsidy, params.max_block_size,
                        consensus.coinbase_maturity, mtp, bip34, flags,
                        want_sigs=check_scripts, commit=False,
                        nthreads=native.PAR_THREADS)
                except native.EngineMissing as miss:
                    if service_misses(miss.keys) == 0:
                        return False  # truly missing inputs: Python path
                    res = eng.connect_block(
                        raw, height, subsidy, params.max_block_size,
                        consensus.coinbase_maturity, mtp, bip34, flags,
                        want_sigs=check_scripts, commit=False,
                        nthreads=native.PAR_THREADS)
            except (native.EngineMissing, native.EngineError):
                eng.abort()
                return False
            stats["native_connect_s"] += time.perf_counter() - t0
            stats["sigscan_s"] += res.sigscan_s
            cs.bench["connect_ms"] += (time.perf_counter() - t0) * 1e3

            # BIP30 base-store leg: only pre-BIP34 heights can mint
            # duplicate txids (the engine checked its in-memory map; rows
            # flushed out of it need the batched base lookup)
            if height < consensus.bip34_height and res.n_tx:
                keys = []
                for i in range(res.n_tx):
                    txid = res.txid(i)
                    for o in range(int(res.tx_out_counts[i])):
                        keys.append(txid + struct.pack("<I", o))
                if self.coins_db.get_serialized_many(keys):
                    eng.abort()
                    return False  # Python path raises bad-txns-BIP30

            if check_scripts and res.n_inputs:
                t0 = time.perf_counter()
                status = res.sig_status
                fast_idx = np.nonzero(status == 0)[0]
                stats["fast_inputs"] += int(fast_idx.size)
                ecdsa_batch.STATS.p2pkh_fast_path += int(fast_idx.size)
                pub = res.sig_pub[fast_idx]
                rs = res.sig_rs[fast_idx]
                msg = res.sig_msg[fast_idx]
                rn = res.sig_rn[fast_idx]
                wrap = res.sig_wrap[fast_idx]
                fb_idx = np.nonzero(status == 1)[0]
                if fb_idx.size:
                    # generic-script inputs: the Python interpreter is the
                    # authority; its deferred records join the same batch
                    stats["fallback_inputs"] += int(fb_idx.size)
                    records = []
                    tx_cache: dict[int, tuple] = {}
                    spk_off = res.spent_spk_offsets
                    try:
                        for g in fb_idx:
                            t_i, in_i = (int(res.sig_txin[g, 0]),
                                         int(res.sig_txin[g, 1]))
                            if t_i not in tx_cache:
                                s, e_ = (int(res.tx_offsets[t_i, 0]),
                                         int(res.tx_offsets[t_i, 1]))
                                tx = CTransaction.from_bytes(raw[s:e_])
                                tx_cache[t_i] = (tx, SighashCache(tx))
                            tx, cache = tx_cache[t_i]
                            spk = res.spent_spk_blob[
                                int(spk_off[g]):int(spk_off[g + 1])]
                            checker = DeferringSignatureChecker(
                                tx, in_i, int(res.spent_values[g]),
                                records, cache)
                            VerifyScript(tx.vin[in_i].script_sig, spk,
                                         flags, checker)
                    except ScriptError:
                        eng.abort()
                        return False  # Python path re-derives the reject
                    if records:
                        epub, ers, emsg, ern, ewrap = (
                            ecdsa_batch.records_to_blobs(records))
                        pub = np.concatenate([pub, epub])
                        rs = np.concatenate([rs, ers])
                        msg = np.concatenate([msg, emsg])
                        rn = np.concatenate([rn, ern])
                        wrap = np.concatenate([wrap, ewrap])
                if len(msg):
                    agg.append((pub, rs, msg, rn, wrap))
                    agg_count[0] += len(msg)
                    agg_last_hash[0] = h
                dt = time.perf_counter() - t0
                stats["verify_s"] += dt
                cs.bench["verify_ms"] += dt * 1e3

            eng.commit()
            # -- Python bookkeeping (index, chain, stores) --
            idx.n_tx = res.n_tx
            cs._seq += 1
            idx.sequence_id = cs._seq
            idx.status |= BlockStatus.HAVE_DATA | BlockStatus.HAVE_UNDO
            idx.raise_validity(
                BlockStatus.VALID_SCRIPTS if check_scripts
                else BlockStatus.VALID_CHAIN)
            idx.chain_tx = prev.chain_tx + idx.n_tx
            cs.block_index[h] = idx
            cs._dirty_index.add(idx)
            if pos_info is not None:
                self.block_store.positions.setdefault(h, pos_info)
            self.block_store.put_undo(h, res.undo)
            cs.chain.set_tip(idx)
            cs.bench["blocks"] += 1
            if agg_count[0] >= AGG_LANES:
                flush_agg(everything=False)
            n_imported += 1
            stats["blocks"] += 1
            return True

        def process_raw(raw: bytes, pos_info: Optional[tuple]) -> bool:
            h = sha256d_py(raw[:80])
            idx = cs.block_index.get(h)
            if idx is not None and (idx.status & BlockStatus.HAVE_DATA):
                if pos_info is not None:
                    self.block_store.positions.setdefault(h, pos_info)
                return False  # duplicate
            prev_hash = raw[4:36]
            prev = cs.block_index.get(prev_hash)
            if prev is None:
                pending.setdefault(prev_hash, []).append((raw, pos_info))
                return False
            if prev is cs.chain.tip() and idx is None:
                if fast_connect(raw, h, prev, pos_info):
                    return True
            return slow_path(raw, pos_info)

        from ..crypto.hashes import sha256d as sha256d_py

        # enumerate the store's own blk files (reindex source of truth).
        # The whole walk is wrapped so an abort (settle_oldest raising
        # _NativeImportAbort) still settles every in-flight BatchHandle —
        # an abandoned handle would leak STATS.in_flight and, worse,
        # strand the ecdsa breaker in HALF_OPEN forever if the dropped
        # dispatch was its recovery probe (allow() blocks until the probe
        # reports, and only handle settlement reports).
        try:
            n_file = 0
            while True:
                path = os.path.join(self.datadir, "blocks",
                                    f"blk{n_file:05d}.dat")
                if not os.path.exists(path):
                    break
                with open(path, "rb") as f:
                    data = f.read()
                pos = 0
                blocks_since_flush = 0
                while pos + 8 <= len(data):
                    if data[pos:pos + 4] != magic:
                        pos += 1
                        continue
                    (size,) = struct.unpack_from("<I", data, pos + 4)
                    start = pos + 8
                    if start + size > len(data):
                        break  # truncated tail record (crash mid-append)
                    raw = data[start:start + size]
                    pos_info = (n_file, start, size)
                    stats["bytes"] += size
                    if process_raw(raw, pos_info):
                        # cascade children parked on this block
                        queue = [sha256d_py(raw[:80])]
                        while queue:
                            hh = queue.pop()
                            for c_raw, c_pos in pending.pop(hh, ()):
                                if process_raw(c_raw, c_pos):
                                    queue.append(sha256d_py(c_raw[:80]))
                    pos = start + size
                    blocks_since_flush += 1
                    if (blocks_since_flush >= flush_interval
                            or eng.mem_bytes() >= dbcache_bytes):
                        fast_flush()
                        blocks_since_flush = 0
                n_file += 1

            fast_flush()
        finally:
            while inflight:
                _h, handle = inflight.pop(0)
                try:
                    handle.result()
                except Exception:  # noqa: BLE001 — abort-path drain
                    pass
        cs.activate_best_chain()  # safety: settle any side-chain candidates
        cs.flush()
        eng.close()
        stats["wall_s"] = time.perf_counter() - t_start
        self.last_import_stats = stats
        log_printf(
            "native import: %d blocks (%d slow-path), %.1f MB in %.1fs "
            "(connect %.1fs verify %.1fs flush %.1fs)",
            n_imported, stats["slow_path_blocks"], stats["bytes"] / 1e6,
            stats["wall_s"], stats["native_connect_s"], stats["verify_s"],
            stats["flush_s"])
        return n_imported

    def _import_block_files_python(self, paths: Optional[list[str]] = None) -> int:
        """The Python-engine import loop (reference implementation) — and
        the pipelined IBD driver: with -pipelinedepth > 1 each linear
        extension goes through ChainstateManager.process_new_block_pipelined,
        which overlaps the host script scan, the device signature settle,
        and the chainstate commit across up to ``pipelinedepth`` in-flight
        blocks (backpressure settles the oldest). The horizon is drained
        before the final flush, so the on-disk state a crash could observe
        is always a settled prefix of the import."""
        import struct

        magic = self.params.netmagic
        n_imported = 0
        pending: dict[bytes, list[CBlock]] = {}  # prev_hash -> blocks
        cs = self.chainstate

        def try_process(block: CBlock) -> bool:
            nonlocal n_imported
            try:
                cs.process_new_block_pipelined(block)
            except BlockValidationError as e:
                if e.reason == "prev-blk-not-found":
                    pending.setdefault(block.header.hash_prev_block, []).append(block)
                elif e.reason != "duplicate":
                    log_printf("reindex: rejected %s: %s",
                               hash_to_hex(block.get_hash())[:16], e.reason)
                return False
            n_imported += 1
            # cascade any children that were waiting on this block
            queue = [block.get_hash()]
            while queue:
                h = queue.pop()
                for child in pending.pop(h, ()):
                    try:
                        cs.process_new_block_pipelined(child)
                    except BlockValidationError:
                        continue
                    n_imported += 1
                    queue.append(child.get_hash())
            return True

        # (path, store file number | None). Scanning the store's OWN blk
        # files re-registers positions in place; re-appending each block
        # via put_block would double the on-disk chain every -reindex.
        # Explicit -loadblock files are foreign: those DO append.
        file_list: list[tuple[str, Optional[int]]]
        if paths is None:
            file_list = []
            n_file = 0
            while True:
                p = os.path.join(self.datadir, "blocks",
                                 f"blk{n_file:05d}.dat")
                if not os.path.exists(p):
                    break
                file_list.append((p, n_file))
                n_file += 1
        else:
            file_list = [(p, None) for p in paths]
        positions = getattr(self.block_store, "positions", None)
        for path, n_file in file_list:
            if not os.path.exists(path):
                log_printf("loadblock: %s not found, skipping", path)
                continue
            with open(path, "rb") as f:
                data = f.read()
            pos = 0
            while pos + 8 <= len(data):
                if data[pos:pos + 4] != magic:
                    pos += 1  # scan forward (reference tolerates garbage)
                    continue
                (size,) = struct.unpack_from("<I", data, pos + 4)
                start = pos + 8
                if start + size > len(data):
                    break  # truncated tail record (crash mid-append)
                try:
                    block = CBlock.from_bytes(data[start:start + size])
                except Exception:
                    pos += 1
                    continue
                if n_file is not None and positions is not None:
                    positions.setdefault(block.get_hash(),
                                         (n_file, start, size))
                try_process(block)
                pos = start + size
        # drain the settle horizon (flush() would too, but be explicit:
        # import ends with every block settled or unwound) then persist
        self.chainstate.settle_horizon()
        self.chainstate.flush()
        return n_imported

    # -- pruning (-prune / pruneblockchain) -----------------------------

    MIN_BLOCKS_TO_KEEP = 288  # validation.h MIN_BLOCKS_TO_KEEP

    def prune_block_files(self, prune_height: int, stop_when=None) -> int:
        """FindFilesToPrune + UnlinkPrunedFiles (src/validation.cpp):
        delete whole block files whose every block sits below
        prune_height, clearing HAVE_DATA/HAVE_UNDO on their index rows.
        ``stop_when()`` (checked after each pruned file) lets the -prune
        target mode stop as soon as usage is back under budget instead of
        shedding everything prunable. Returns the number of files pruned.
        Caller holds cs_main."""
        store = self.block_store
        if not hasattr(store, "prune_file"):
            return 0  # memory-backed store (tests)
        cs = self.chainstate
        prune_height = min(prune_height,
                           cs.tip().height - self.MIN_BLOCKS_TO_KEEP)
        pruned = 0
        for n in range(store._cur_file):
            hashes = store.blocks_in_file(n)
            if not hashes:
                continue
            heights = [cs.block_index[h].height
                       for h in hashes if h in cs.block_index]
            if not heights or max(heights) >= prune_height:
                continue
            for h in store.prune_file(n):
                idx = cs.block_index.get(h)
                if idx is not None:
                    idx.status &= ~(BlockStatus.HAVE_DATA
                                    | BlockStatus.HAVE_UNDO)
                    cs._dirty_index.add(idx)
            pruned += 1
            if stop_when is not None and stop_when():
                break
        if pruned:
            self._set_prune_height(max(self.prune_height, prune_height))
            cs.flush()
            log_printf("pruned %d block file(s) below height %d",
                       pruned, prune_height)
        return pruned

    def _set_prune_height(self, height: int) -> None:
        self.prune_height = height
        # survive restarts so pruneblockchain/getblockchaininfo stay right
        self._index_kv.write_batch({b"Fpruneheight": str(height).encode()})

    def auto_prune(self) -> None:
        """-prune=<MB> target mode: shed the OLDEST files until usage is
        back under the target (FindFilesToPrune stops at the budget — it
        never strips the chain down to the 288-block floor)."""
        if self.prune_target_bytes <= 0:
            return
        store = self.block_store
        if not hasattr(store, "file_usage"):
            return
        if store.file_usage() > self.prune_target_bytes:
            self.prune_block_files(
                self.chainstate.tip().height,
                stop_when=lambda: store.file_usage()
                <= self.prune_target_bytes,
            )

    # -- txindex (-txindex) --------------------------------------------

    _TXINDEX_PREFIX = b"t"

    def _txindex_add(self, block: CBlock, idx) -> None:
        puts = {
            self._TXINDEX_PREFIX + tx.txid: idx.hash for tx in block.vtx
        }
        self._index_kv.write_batch(puts)

    def _start_txindex_backfill(self) -> None:
        """-txindex on a synced datadir: backfill runs on a BACKGROUND
        thread in SCAN_CHUNK-height chunks taking cs_main per chunk — the
        reference's TxIndex::ThreadSync shape (init is not blocked; lookups
        can miss until synced, like the reference's 'syncing' txindex).
        New blocks connecting during backfill are indexed by the normal
        _txindex_add hook; re-writing a key is idempotent."""
        if self.index_db.kv.get(b"Ftxindex") == b"1":
            self._txindex_synced = True
            return
        self._txindex_thread = threading.Thread(
            target=self._txindex_backfill, name="txindex-sync", daemon=True
        )
        self._txindex_thread.start()

    def _txindex_backfill(self) -> None:
        try:
            self._txindex_backfill_inner()
        except Exception as e:  # noqa: BLE001 - daemon thread boundary
            # a silently-dead backfill thread would leave txindex
            # 'syncing' forever with no cause on record; the next restart
            # resumes from the persisted rows
            log_printf("txindex backfill aborted: %r", e)

    def _txindex_backfill_inner(self) -> None:
        """Uses the native wire scanner when available (txids without full
        Python deserialization — the reference keeps this path in C++ too);
        falls back to the Python deserializer per block."""
        from .. import native

        use_native = native.available()
        cs = self.chainstate
        height = 0
        while not self.shutdown_event.is_set():
            with self.cs_main:
                tip = cs.chain.height()
                if height > tip:
                    self.index_db.put_flag(b"txindex", True)
                    self._txindex_synced = True
                    log_printf("txindex backfill complete at height %d", tip)
                    return
                end = min(height + self.SCAN_CHUNK, tip + 1)
                for h in range(height, end):
                    idx = cs.chain[h]
                    txids = None
                    if use_native:
                        raw = self.block_store.get_block(idx.hash)
                        if raw is not None:
                            scan = native.scan_block(raw)
                            if scan is not None:
                                txids = scan.txids
                    if txids is None:
                        block = cs.get_block(idx.hash)
                        if block is None:
                            continue
                        txids = [tx.txid for tx in block.vtx]
                    self._index_kv.write_batch({
                        self._TXINDEX_PREFIX + txid: idx.hash
                        for txid in txids
                    })
                height = end
                if height <= tip:
                    log_print("txindex", "backfill: %d/%d blocks",
                              height, tip)
            # lock released between chunks: validation/RPC interleave

    def txindex_lookup(self, txid: bytes) -> Optional[bytes]:
        """GetTransaction's txindex path: txid -> containing block hash."""
        return self._index_kv.get(self._TXINDEX_PREFIX + txid)

    # -- servers --------------------------------------------------------

    def start_rpc(self) -> int:
        """AppInitServers: bind the JSON-RPC server; returns the bound port."""
        from ..rpc.server import RPCServer

        port = self.config.rpc_port(self.params)
        bind = self.config.get("rpcbind", "127.0.0.1")
        self.rpc_server = RPCServer(self, bind, port)
        self.rpc_server.start()
        log_printf("RPC server listening on %s:%d", bind, self.rpc_server.port)
        return self.rpc_server.port

    def start_p2p(self) -> int:
        """CConnman::Start: bind the P2P listener, dial -connect peers."""
        from ..p2p.connman import CConnman

        port = self.config.p2p_port(self.params)
        listen = self.config.get_bool("listen", True)
        self.connman = CConnman(self, "127.0.0.1", port if listen else 0)
        self.connman.start()
        for target in self.config.get_multi("connect"):
            host, _, p = target.rpartition(":")
            self.connman.connect_to(host or "127.0.0.1", int(p))
        return self.connman.port

    def start_gateway(self) -> int:
        """Bind the fleet serving front door (-gateway) over the
        -replicas pool; returns the bound port. The validator leg
        executes RPC handlers in-process (same dispatch as rpc/server);
        the replica legs speak JSON-RPC HTTP with the node's own
        -rpcuser/-rpcpassword — a fleet shares RPC credentials."""
        import base64

        from ..rpc.registry import RPC_METHODS, RPCError
        from ..serving.gateway import BackendRPCError, Gateway
        from ..serving.replicas import Replica, ReplicaPool, http_transport

        def _backend(method, params):
            handler = RPC_METHODS.get(method)
            if handler is None:
                raise BackendRPCError(
                    {"code": -32601, "message": "Method not found"})
            try:
                if getattr(handler, "no_cs_main", False):
                    return handler(self, list(params))
                with self.cs_main:
                    return handler(self, list(params))
            except RPCError as e:
                raise BackendRPCError(
                    {"code": e.code, "message": e.message}) from e

        def _tip_height() -> int:
            with self.cs_main:
                return self.chainstate.tip().height

        user = self.config.get("rpcuser")
        password = self.config.get("rpcpassword")
        if user and password:
            auth = base64.b64encode(f"{user}:{password}".encode()).decode()
        elif self.rpc_server is not None:
            auth = self.rpc_server._auth  # cookie-auth fleet (tests)
        else:
            raise InitError("-gateway needs -rpcuser/-rpcpassword (or a "
                            "running RPC server's cookie) for replica auth")
        replicas = [
            Replica(f"{host}:{port}", http_transport(host, port, auth))
            for host, port in self.replica_addrs
        ]
        pool = ReplicaPool(
            replicas, max_lag=self.max_replica_lag,
            probe_interval=self.config.get_int("gatewayprobems", 500) / 1e3,
            validator_tip=_tip_height)
        self.gateway = Gateway(
            _backend, pool,
            rate=self.config.get_int("gatewayrate", 500),
            burst=self.config.get_int("gatewayburst", 200),
            soft_inflight=self.config.get_int("gatewaysoft", 64),
            hard_inflight=self.config.get_int("gatewayhard", 256),
            bind=self.config.get("gatewaybind", "127.0.0.1"),
            port=self.gateway_port, auth_b64=auth)
        self.gateway.start()
        return self.gateway.port

    def load_wallet(self):
        from ..wallet.wallet import Wallet

        if self.wallet is not None and self._wallet_ready.is_set():
            return self.wallet
        if self._wallet_loader == threading.get_ident():
            return self.wallet  # re-entrant call from our own load path
        if self.wallet is None:
            # first loader: callers hold cs_main, so the None check and the
            # assignment below are mutually exclusive — a second thread can
            # only arrive once we yield mid-rescan, and then takes the
            # wait branch
            self._wallet_loader = threading.get_ident()
            try:
                path = os.path.join(self.datadir, "wallet.json")
                self.wallet = Wallet(params=self.params, path=path)
                self.wallet.load()
                if self.wallet._pkh_index or self.wallet.keys_by_pubkey:
                    self._rescan_wallet()  # ScanForWalletTransactions
                # replay the (possibly mempool.dat-reloaded) pool so pending
                # spends of wallet coins are marked before CreateTransaction
                for e in self.mempool.entries.values():
                    self.wallet.add_tx_if_mine(e.tx, -1, False)
                self.chainstate.on_block_connected.append(
                    self.wallet.block_connected)
                self.chainstate.on_block_disconnected.append(
                    self.wallet.block_disconnected)
                # -walletnotify=<cmd>: shell hook per wallet-affecting tx as
                # it confirms (init.cpp/wallet.cpp BlockConnected notify);
                # registered AFTER wallet.block_connected so tx_log is
                # current
                notify = self.config.get("walletnotify")
                if notify:
                    self.chainstate.on_block_connected.append(
                        lambda block, idx: self._walletnotify(notify, block)
                    )
                self._wallet_ready.set()
            except BaseException:
                # a failed load (corrupt wallet.json, rescan error) must
                # not leave self.wallet half-set with _wallet_ready never
                # signaled — every later wallet RPC would spin in the wait
                # loop forever (ADVICE r4). Reset so a retry can load.
                bad = self.wallet
                self.wallet = None
                if bad is not None:
                    for lst in (self.chainstate.on_block_connected,
                                self.chainstate.on_block_disconnected):
                        for cb in (bad.block_connected,
                                   bad.block_disconnected):
                            if cb in lst:
                                lst.remove(cb)
                raise
            finally:
                self._wallet_loader = None
            return self.wallet
        # another thread is mid-load/rescan: wait for it WITH cs_main
        # released (waiting while holding would deadlock the rescanner's
        # chunk reacquire); non-wallet RPCs keep running in those windows
        while not self._wallet_ready.is_set():
            if self.shutdown_event.is_set():
                break
            released = False
            try:
                self.cs_main.release()
                released = True
            except RuntimeError:
                pass
            try:
                self._wallet_ready.wait(0.05)
            finally:
                if released:
                    self.cs_main.acquire()
        return self.wallet

    def _walletnotify(self, cmd: str, block: CBlock) -> None:
        import subprocess

        from ..consensus.serialize import hash_to_hex as _h2h

        for tx in block.vtx:
            if tx.txid in self.wallet.tx_log:
                try:
                    subprocess.Popen(
                        cmd.replace("%s", _h2h(tx.txid)), shell=True
                    )
                except OSError as e:
                    log_printf("walletnotify failed: %r", e)

    # blocks per cs_main hold during rescan/backfill (liveness knob: the
    # O(height) scans must not starve RPC on a long chain — VERDICT r3 #10)
    SCAN_CHUNK = 200

    def _cs_yield(self) -> bool:
        """Release cs_main (if held exactly once by this thread), give a
        waiting thread a chance to take it, and reacquire. Returns whether
        a yield actually happened. The RPC layer acquires cs_main exactly
        once around handlers; a deeper reentrant hold just skips the yield
        (correct, only less live)."""
        try:
            self.cs_main.release()
        except RuntimeError:
            return False  # not held by us: nothing to yield
        try:
            time.sleep(0)  # scheduler hint: let a blocked RPC thread in
        finally:
            self.cs_main.acquire()
        return True

    def _rescan_wallet(self) -> None:
        """CWallet::ScanForWalletTransactions over the active chain — a
        reloaded wallet file has keys but no coin state. Chunked: cs_main
        is yielded between SCAN_CHUNK-block chunks so concurrent RPC stays
        responsive on a long chain (the reference takes cs_main per block
        in ScanForWalletTransactions, not across the whole scan)."""
        cs = self.chainstate
        height = 0
        total = cs.tip().height
        while height <= total:
            end = min(height + self.SCAN_CHUNK, total + 1)
            for h in range(height, end):
                idx = cs.chain[h]
                block = cs.get_block(idx.hash)
                if block is not None:
                    self.wallet.block_connected(block, idx)
            height = end
            if height <= total:
                log_printf("wallet rescan: %d/%d blocks", height, total)
                self._cs_yield()
                # the tip may have advanced while unlocked; extend the scan
                total = cs.tip().height

    # -- lifecycle ------------------------------------------------------

    def wait_for_shutdown(self) -> None:
        self.shutdown_event.wait()

    def stop(self) -> None:
        self.shutdown_event.set()

    def close(self) -> None:
        """Shutdown (src/init.cpp): stop servers, flush, close stores."""
        self.shutdown_event.set()
        if self._snapshot_thread is not None:
            # the verify thread checks shutdown_event between blocks and
            # persists its shadow progress; it must not race the store
            # closes below
            self._snapshot_thread.join(timeout=30)
            self._snapshot_thread = None
        if self._txindex_thread is not None:
            # the backfill thread checks shutdown_event between chunks and
            # must not race the kv-store closes below
            self._txindex_thread.join(timeout=30)
            self._txindex_thread = None
        if self.zmq_publishers:
            for pub in self.zmq_publishers:
                pub.close()
            self.zmq_publishers = []
            # unregister so a block connecting mid-shutdown can't reach a
            # closed publisher (the guard in _zmq_block is the backstop)
            try:
                self.chainstate.on_block_connected.remove(self._zmq_block)
            except ValueError:
                pass
        if self.gateway is not None:
            # front door first: stop admitting before the backends close
            # (also unregisters the gateway's registry collector)
            self.gateway.close()
            self.gateway = None
        if self.rpc_server is not None:
            self.rpc_server.close()
            self.rpc_server = None
        if self.connman is not None:
            self.connman.close()
            self.connman = None
        if self.sigservice is not None:
            # drain pending lanes before the stores close (a late settle
            # still inserts into the in-memory sigcache — harmless)
            self.sigservice.stop()
        with self.cs_main:
            if self.persist_mempool:
                from ..mempool.persist import dump_mempool

                try:
                    n = dump_mempool(self.mempool, self._mempool_dat)
                    log_print("mempool", "DumpMempool: %d entries", n)
                except OSError as e:
                    # a failed dump must not abort the rest of shutdown
                    # (chainstate flush + store closes still run)
                    log_printf("DumpMempool failed: %r", e)
            try:
                self.fee_estimator.flush()  # fee_estimates.dat analogue
            except OSError as e:
                log_printf("fee estimator flush failed: %r", e)
            self.chainstate.flush()
            self.block_store.close()
            self._index_kv.close()
            if self._coins_kv is not None:
                self._coins_kv.close()
            else:
                self.coins_db.close()
        # drop this node's registry collectors: the bound methods would
        # otherwise keep the closed node's whole object graph (coins
        # cache, mempool, block index) alive in the process-global
        # REGISTRY for the rest of the process
        for name in ("sigcache", "pipeline", "mempool", "mempool_perf",
                     "serving", "mining", "store", "lockwatch"):
            telemetry.REGISTRY.unregister_collector(name)
        if self.resident_miner is not None:
            # drops the device template buffers and the miner watchdog
            # registration (same closure-leak lesson as the collectors)
            self.resident_miner.close()
            self.resident_miner = None
        # same lesson for the watchdog: its pending_fn closures must not
        # keep a closed node alive (sigservice.stop() already dropped its
        # own registration above)
        from ..util import devicewatch as _dw

        _dw.WATCHDOG.unregister("pipeline")
        if self.tracefile:
            # -tracefile: the span ring buffer as Chrome/perfetto JSON,
            # written LAST so shutdown's own flush spans are included
            try:
                n = telemetry.TRACER.dump(self.tracefile)
                log_printf("-tracefile: %d span(s) -> %s", n, self.tracefile)
            except OSError as e:
                log_printf("-tracefile dump failed: %r", e)
        log_printf("bcpd shutdown complete")
