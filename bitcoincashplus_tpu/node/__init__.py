"""Node runtime — process lifecycle, flags, the AppInitMain analogue.

Reference: src/bitcoind.cpp, src/init.cpp, src/util.cpp (ArgsManager-style
flag handling). The `--tpu` backend switch lives here (SURVEY.md §6.6).
"""

from .config import Config  # noqa: F401
from .node import Node  # noqa: F401
