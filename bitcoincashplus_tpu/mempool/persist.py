"""mempool.dat — dump/load the mempool across restarts.

Reference: src/validation.cpp (DumpMempool / LoadMempool, 0.14+). Same
shape: a version field, the entries as (tx, entry time, fee delta), then
the surviving mapDeltas for txs not currently in the pool. Entries are
written parents-first (sorted by in-pool ancestor count, the reference's
GetSortedDepthAndScore ordering) so a straight replay through
AcceptToMemoryPool re-admits chains without an orphan pass.
"""

from __future__ import annotations

import os
import struct
import time as _time
from typing import Optional

from ..consensus.serialize import (
    ByteReader,
    DeserializationError,
    deser_i64,
    deser_u64,
    ser_compact_size,
)
from ..consensus.tx import CTransaction
from ..util.log import log_printf
from .mempool import CTxMemPool, MempoolError

MEMPOOL_DUMP_VERSION = 1


def dump_mempool(pool: CTxMemPool, path: str) -> int:
    """Write pool contents + fee deltas to ``path`` atomically (write to
    .new then rename, like the reference). Returns the entry count."""
    entries = sorted(pool.entries.values(),
                     key=lambda e: e.count_with_ancestors)
    blob = [struct.pack("<Q", MEMPOOL_DUMP_VERSION),
            struct.pack("<Q", len(entries))]
    for e in entries:
        blob.append(e.tx.serialize())
        blob.append(struct.pack("<qq", e.time,
                                pool.map_deltas.get(e.txid, 0)))
    leftover = {txid: delta for txid, delta in pool.map_deltas.items()
                if txid not in pool.entries and delta != 0}
    blob.append(ser_compact_size(len(leftover)))
    for txid, delta in leftover.items():
        blob.append(txid)
        blob.append(struct.pack("<q", delta))
    tmp = path + ".new"
    with open(tmp, "wb") as f:
        f.write(b"".join(blob))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(entries)


def load_mempool(node, path: str,
                 now: Optional[int] = None) -> tuple[int, int, int]:
    """Replay ``path`` through the node's AcceptToMemoryPool. Returns
    (accepted, failed, expired). Unreadable/corrupt files are logged and
    skipped — a bad mempool.dat must never stop the node (reference
    behavior)."""
    if not os.path.exists(path):
        return (0, 0, 0)
    now = int(_time.time()) if now is None else now
    accepted = failed = expired = 0
    try:
        with open(path, "rb") as f:
            r = ByteReader(f.read())
        version = deser_u64(r)
        if version != MEMPOOL_DUMP_VERSION:
            log_printf("mempool.dat: unknown version %d, ignoring", version)
            return (0, 0, 0)
        count = deser_u64(r)
        for _ in range(count):
            tx = CTransaction.deserialize(r)
            entry_time = deser_i64(r)
            delta = deser_i64(r)
            if delta:
                node.mempool.map_deltas[tx.txid] = (
                    node.mempool.map_deltas.get(tx.txid, 0) + delta)
            if entry_time < now - node.mempool.expiry_seconds:
                expired += 1
                continue
            try:
                node.accept_to_mempool(tx, now=entry_time,
                       fee_estimate=False)
                accepted += 1
            except MempoolError:
                failed += 1
        from ..consensus.serialize import deser_compact_size

        n_deltas = deser_compact_size(r)
        for _ in range(n_deltas):
            txid = r.read_bytes(32)
            delta = deser_i64(r)
            node.mempool.map_deltas[txid] = (
                node.mempool.map_deltas.get(txid, 0) + delta)
    except (DeserializationError, struct.error, ValueError, OSError) as e:
        log_printf("mempool.dat: corrupt (%r), continuing with partial load", e)
    log_printf("mempool.dat: %d accepted, %d failed, %d expired",
               accepted, failed, expired)
    return (accepted, failed, expired)
