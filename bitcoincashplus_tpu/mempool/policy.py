"""Relay policy — standardness rules.

Reference: src/policy/policy.{h,cpp} (IsStandardTx, IsStandard,
AreInputsStandard, GetDustThreshold), src/policy/feerate (CFeeRate).
Policy ≠ consensus: these gate mempool admission only.
"""

from __future__ import annotations

from ..consensus.tx import CTransaction
from ..script.script import (
    MAX_SCRIPT_SIZE,
    classify_script,
    count_sigops,
    get_script_ops,
    is_push_only,
)

MAX_STANDARD_TX_SIZE = 100_000  # MAX_STANDARD_TX_SIZE (policy.h)
MAX_STANDARD_SCRIPTSIG_SIZE = 1650
MAX_P2SH_SIGOPS = 15
MAX_OP_RETURN_RELAY = 83  # nMaxDatacarrierBytes
DEFAULT_MIN_RELAY_FEE_RATE = 1000  # sat/kB (DEFAULT_MIN_RELAY_TX_FEE)


def get_min_relay_fee(tx_size: int,
                      rate: int = DEFAULT_MIN_RELAY_FEE_RATE) -> int:
    """CFeeRate::GetFee — rounds up to at least 1 sat when rate > 0."""
    fee = rate * tx_size // 1000
    if fee == 0 and rate > 0:
        fee = rate
    return fee


def get_dust_threshold(txout,
                       rate: int = DEFAULT_MIN_RELAY_FEE_RATE) -> int:
    """GetDustThreshold (policy.h IsDust): an output is dust when spending
    it would cost more than 1/3 of its value — threshold = 3 × relay fee on
    (serialized output + 148 bytes of spending input). 546 sat for P2PKH at
    the default rate; larger scripts scale up."""
    size = len(txout.serialize()) + 148
    return 3 * get_min_relay_fee(size, rate)


def is_standard_tx(tx: CTransaction) -> tuple[bool, str]:
    """IsStandardTx (policy.cpp:~60). Returns (ok, reason)."""
    if tx.version > CTransaction.CURRENT_VERSION or tx.version < 1:
        return False, "version"
    if tx.size() > MAX_STANDARD_TX_SIZE:
        return False, "tx-size"
    for txin in tx.vin:
        if len(txin.script_sig) > MAX_STANDARD_SCRIPTSIG_SIZE:
            return False, "scriptsig-size"
        if not is_push_only(txin.script_sig):
            return False, "scriptsig-not-pushonly"
    n_data = 0
    for txout in tx.vout:
        kind = classify_script(txout.script_pubkey)
        if kind == "nonstandard":
            return False, "scriptpubkey"
        if kind == "nulldata":
            n_data += 1
            if len(txout.script_pubkey) > MAX_OP_RETURN_RELAY:
                return False, "oversize-op-return"
        elif txout.value < get_dust_threshold(txout):
            return False, "dust"
    if n_data > 1:
        return False, "multi-op-return"
    return True, ""


def are_inputs_standard(tx: CTransaction, spent_outputs: list) -> bool:
    """AreInputsStandard (policy.cpp:~150): P2SH redeem scripts bounded to
    MAX_P2SH_SIGOPS; inputs must spend known templates.
    ``spent_outputs``: CTxOut per input."""
    if tx.is_coinbase():
        return True
    for txin, prevout in zip(tx.vin, spent_outputs):
        kind = classify_script(prevout.script_pubkey)
        if kind == "nonstandard":
            return False
        if kind == "scripthash":
            # last push of scriptSig is the redeem script
            redeem = b""
            try:
                for op, data, _ in get_script_ops(txin.script_sig):
                    redeem = data or b""
            except Exception:
                return False
            if len(redeem) > MAX_SCRIPT_SIZE:
                return False
            if count_sigops(redeem, accurate=True) > MAX_P2SH_SIGOPS:
                return False
    return True
