"""Fee estimation — multi-horizon confirmation-target tracking with decay.

Reference: src/policy/fees.cpp (CBlockPolicyEstimator + TxConfirmStats).
The reference tracks, per geometric feerate bucket, exponentially-decayed
counts of (a) transactions seen entering the mempool and (b) how many of
them confirmed within each target number of blocks; an estimate for target
T scans buckets from the highest feerate down until the cumulative
confirmed-within-T ratio drops below the success threshold, answering
"the lowest feerate that historically confirmed within T blocks 95% of
the time". This module reproduces that design, including the 0.15-lineage
split into THREE horizons with distinct decays (VERDICT r4 missing #5 —
the single-horizon simplification is retired):

  - short  (decay 0.962,   targets 1..12):  reacts within hours,
  - medium (decay 0.9952,  targets 1..48):  ~the old single horizon,
  - long   (decay 0.99931, targets 1..1008): captures weekly cycles,

  - geometric buckets (x1.05) from 1000 sat/kB to 1e7 sat/kB,
  - tracked mempool entries keyed by txid with entry height,
  - success-ratio bucket scans with reference-scale sample gates
    (sufficientTxVal / (1 - decay) decayed observations per range — a
    single tracked tx never mints an estimate),
  - still-unconfirmed txs older than the target count in the denominator
    (EstimateMedianVal's unconfTxs legs): congestion can never read as
    ~100% success (ADVICE r4 medium),
  - estimatesmartfee semantics: horizon chosen by target, conservative
    cross-checks against the longer horizons (estimateSmartFee's max),
    widening toward MAX_TARGET until an estimate exists,
  - persistence across restarts (fee_estimates.dat analogue, JSON form).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

MIN_BUCKET_FEERATE = 1000.0     # sat/kB — the relay floor
MAX_BUCKET_FEERATE = 1e7
BUCKET_SPACING = 1.05
SUCCESS_PCT = 0.95

# (name, decay, max target, sufficient txs/block) — the reference's
# shortStats/feeStats/longStats trio (policy/fees.h). The per-range sample
# gate is sufficient / (1 - decay): ~13 decayed obs for short, ~21 medium,
# ~145 long.
HORIZONS = (
    ("short", 0.962, 12, 0.5),
    ("medium", 0.9952, 48, 0.1),
    ("long", 0.99931, 1008, 0.1),
)
MAX_TARGET = HORIZONS[-1][2]

# kept for callers/tests pinning the medium-horizon constants
DECAY = HORIZONS[1][1]
SUFFICIENT_TXS = HORIZONS[1][3]
SUFFICIENT_SAMPLES = SUFFICIENT_TXS / (1.0 - DECAY)


def _make_buckets() -> list:
    out = [MIN_BUCKET_FEERATE]
    while out[-1] < MAX_BUCKET_FEERATE:
        out.append(out[-1] * BUCKET_SPACING)
    return out


class _ConfStats:
    """One TxConfirmStats: decayed per-bucket confirmation history for
    targets 1..max_target at a single decay rate."""

    __slots__ = ("decay", "max_target", "sufficient", "tx_avg", "fee_sum",
                 "conf_avg", "n_buckets")

    def __init__(self, n_buckets: int, decay: float, max_target: int,
                 sufficient: float):
        self.n_buckets = n_buckets
        self.decay = decay
        self.max_target = max_target
        # reference gate: sufficientTxVal per block / (1 - decay)
        self.sufficient = sufficient / (1.0 - decay)
        # numpy-backed: decay_all runs on EVERY block connect and the long
        # horizon alone holds 1008 x ~190 cells — a Python float loop here
        # would cost ~ms per block on the import hot path
        self.tx_avg = np.zeros(n_buckets)
        self.fee_sum = np.zeros(n_buckets)
        self.conf_avg = np.zeros((max_target, n_buckets))

    def decay_all(self) -> None:
        self.tx_avg *= self.decay
        self.fee_sum *= self.decay
        self.conf_avg *= self.decay

    def record(self, bucket: int, feerate: float,
               blocks_to_confirm: int) -> None:
        self.tx_avg[bucket] += 1.0
        self.fee_sum[bucket] += feerate
        self.conf_avg[blocks_to_confirm - 1:, bucket] += 1.0

    def estimate(self, target: int, unconf: list) -> float:
        """EstimateMedianVal over this horizon; ``unconf`` is the
        per-bucket count of tracked txs already older than ``target``
        (failures-so-far, undecayed current mempool state)."""
        if not 1 <= target <= self.max_target:
            return -1.0
        conf = self.conf_avg[target - 1]
        best = -1.0
        cur_need = cur_got = cur_fee = cur_conf_n = 0.0
        # scan high -> low in ranges: each time a range accumulates enough
        # samples AND passes the success ratio it becomes the new answer
        # and the accumulators reset — the result is the LOWEST passing
        # range's decayed-average feerate (estimateMedianVal's shape)
        for b in range(self.n_buckets - 1, -1, -1):
            cur_need += self.tx_avg[b] + unconf[b]
            cur_got += conf[b]
            cur_fee += self.fee_sum[b]
            cur_conf_n += self.tx_avg[b]
            if cur_need >= self.sufficient:
                if cur_got / cur_need < SUCCESS_PCT:
                    break
                # average feerate over CONFIRMED observations only
                # (fee_sum has no unconfirmed component)
                best = cur_fee / cur_conf_n if cur_conf_n else -1.0
                cur_need = cur_got = cur_fee = cur_conf_n = 0.0
        return best

    def to_json(self) -> dict:
        return {"tx_avg": self.tx_avg.tolist(),
                "fee_sum": self.fee_sum.tolist(),
                "conf_avg": self.conf_avg.tolist()}

    def from_json(self, data: dict) -> bool:
        nb = self.n_buckets
        if (len(data.get("tx_avg", ())) != nb
                or len(data.get("fee_sum", ())) != nb
                or len(data.get("conf_avg", ())) != self.max_target
                or any(len(row) != nb for row in data["conf_avg"])):
            return False
        try:
            tx_avg = np.asarray(data["tx_avg"], dtype=float)
            fee_sum = np.asarray(data["fee_sum"], dtype=float)
            conf_avg = np.asarray(data["conf_avg"], dtype=float)
        except (TypeError, ValueError):
            return False
        # shape, not just outer length: nested-list cells would build a
        # 3-D array that passes len() checks and crashes estimate() later
        if (tx_avg.shape != (nb,) or fee_sum.shape != (nb,)
                or conf_avg.shape != (self.max_target, nb)):
            return False
        self.tx_avg = tx_avg
        self.fee_sum = fee_sum
        self.conf_avg = conf_avg
        return True


class FeeEstimator:
    """CBlockPolicyEstimator analogue. All feerates are sat/kB."""

    def __init__(self, path: Optional[str] = None):
        self.buckets = _make_buckets()
        nb = len(self.buckets)
        self.stats = {
            name: _ConfStats(nb, decay, max_t, suff)
            for name, decay, max_t, suff in HORIZONS
        }
        # txid -> (entry_height, bucket_index, feerate)
        self.tracked: dict[bytes, tuple] = {}
        self.best_height = 0
        self.path = path
        if path and os.path.exists(path):
            try:
                self._read(path)
            except Exception:
                pass  # corrupt stats are re-learned, never fatal

    # -- bucket helpers -------------------------------------------------

    def _bucket_for(self, feerate: float) -> int:
        lo, hi = 0, len(self.buckets) - 1
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if self.buckets[mid] <= feerate:
                lo = mid
            else:
                hi = mid - 1
        return lo

    # -- mempool tracking (processTransaction / removeTx) ---------------

    def process_tx(self, txid: bytes, height: int, feerate: float) -> None:
        """A tx entered the mempool at ``height`` paying ``feerate``."""
        if txid in self.tracked:
            return
        self.tracked[txid] = (height, self._bucket_for(feerate), feerate)

    def remove_tx(self, txid: bytes) -> None:
        """Removed for a reason other than inclusion (eviction, expiry,
        conflict): drop without biasing the stats — like the reference."""
        self.tracked.pop(txid, None)

    # -- block processing (processBlock) --------------------------------

    def process_block(self, height: int, confirmed_txids) -> None:
        """Called once per connected block with the txids it confirmed."""
        if height <= self.best_height:
            # reorg replays: never double-count (processBlock guard)
            for txid in confirmed_txids:
                self.tracked.pop(txid, None)
            return
        self.best_height = height
        # decay first, so this block's observations carry full weight
        for st in self.stats.values():
            st.decay_all()
        for txid in confirmed_txids:
            got = self.tracked.pop(txid, None)
            if got is None:
                continue  # never saw it in our mempool: no data point
            entry_height, bucket, feerate = got
            blocks_to_confirm = height - entry_height
            if blocks_to_confirm < 1:
                continue  # same-block or reorg artifact: unmeasurable
            for st in self.stats.values():
                st.record(bucket, feerate, blocks_to_confirm)

    # -- estimation (estimateMedianVal / estimateRawFee) ----------------

    def _tracked_snapshot(self):
        """(ages, buckets) arrays over the tracked mempool txs — built
        once per estimate call so the per-target unconf derivation is a
        vectorized filter, not a dict scan per target."""
        n = len(self.tracked)
        if n == 0:
            return None
        ages = np.empty(n, dtype=np.int64)
        bks = np.empty(n, dtype=np.int64)
        for i, (entry_height, bucket, _fee) in enumerate(
                self.tracked.values()):
            ages[i] = self.best_height - entry_height
            bks[i] = bucket
        return ages, bks

    def _unconf_for(self, target: int, snapshot=None):
        """Per-bucket failures-so-far: tracked txs that have already
        waited >= target blocks without confirming (age == target means
        every block in the window passed; a confirm now would take
        target+1). Undecayed — current mempool state, like the
        reference's unconfTxs rings."""
        if snapshot is None:
            snapshot = self._tracked_snapshot()
        unconf = np.zeros(len(self.buckets))
        if snapshot is not None:
            ages, bks = snapshot
            sel = bks[ages >= target]
            if sel.size:
                np.add.at(unconf, sel, 1.0)
        return unconf

    def _horizon_for(self, target: int) -> str:
        for name, _decay, max_t, _s in HORIZONS:
            if target <= max_t // 2 or max_t == MAX_TARGET:
                return name
        return HORIZONS[-1][0]

    def estimate_fee(self, target: int) -> float:
        """estimateRawFee-flavored single answer: the horizon native to
        ``target`` (short covers 1..6, medium 7..24, long beyond — the
        reference's ConfirmTarget-to-horizon mapping by half-range).
        -1 when no answer (the reference's cold result)."""
        if not 1 <= target <= MAX_TARGET:
            return -1.0
        st = self.stats[self._horizon_for(target)]
        return st.estimate(target, self._unconf_for(target))

    def estimate_smart_fee(self, target: int):
        """(feerate, answered_target): the reference's conservative
        estimateSmartFee — the horizon answer cross-checked against every
        LONGER horizon at the same target, taking the maximum (a
        short-horizon dip below the long-run rate must not underbid);
        widens the target (x2 steps, bounded) until an estimate exists.
        (-1, target) cold."""
        target = max(1, min(int(target), MAX_TARGET))
        # early-out: with nothing tracked and no horizon at gate-level
        # decayed weight, no target can ever answer — skip the widening
        # loop entirely (tracked unconfirmed txs also count toward the
        # sufficiency gate, so the shortcut only applies when none exist)
        if not self.tracked and all(
                float(st.tx_avg.sum()) < st.sufficient
                for st in self.stats.values()):
            return -1.0, target
        snapshot = self._tracked_snapshot()
        # widening ladder: target, then doubling steps, then MAX_TARGET —
        # bounded ~11 probes instead of a +1 walk over a 1008-wide range
        probes = []
        t = target
        while t < MAX_TARGET:
            probes.append(t)
            t = t * 2 if t > 1 else 2
        probes.append(MAX_TARGET)
        for t in probes:
            native = self._horizon_for(t)
            unconf = self._unconf_for(t, snapshot)
            est = self.stats[native].estimate(t, unconf)
            if est <= 0:
                continue
            # conservative: longer horizons may demand more
            passed = False
            for name, _d, max_t, _s in HORIZONS:
                if passed and t <= max_t:
                    alt = self.stats[name].estimate(t, unconf)
                    if alt > est:
                        est = alt
                if name == native:
                    passed = True
            return est, t
        return -1.0, target

    # -- persistence (fee_estimates.dat) --------------------------------

    def flush(self, path: Optional[str] = None) -> None:
        path = path or self.path
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "version": 2,
                "best_height": self.best_height,
                "horizons": {name: st.to_json()
                             for name, st in self.stats.items()},
            }, f)
        os.replace(tmp, path)

    def _read(self, path: str) -> None:
        with open(path) as f:
            data = json.load(f)
        if data.get("version") != 2:
            return  # v1 single-horizon files start cold (layout changed)
        # validate EVERY array dimension before accepting — all-or-nothing
        # into FRESH stats so a bad later horizon can't leave the earlier
        # ones half-loaded, and a truncated/ragged file starts cold rather
        # than IndexError inside block connection ("never fatal" contract)
        nb = len(self.buckets)
        fresh = {
            name: _ConfStats(nb, decay, max_t, suff)
            for name, decay, max_t, suff in HORIZONS
        }
        bh = data.get("best_height")
        if not isinstance(bh, (int, float)):
            return  # malformed height: reject before touching stats
        for name in fresh:
            blob = data.get("horizons", {}).get(name)
            if not isinstance(blob, dict) or not fresh[name].from_json(blob):
                return  # reject the whole file: horizons stay consistent
        self.stats = fresh
        self.best_height = int(bh)
