"""Fee estimation — confirmation-target bucket tracking with decay.

Reference: src/policy/fees.cpp (CBlockPolicyEstimator + TxConfirmStats).
The reference tracks, per geometric feerate bucket, exponentially-decayed
counts of (a) transactions seen entering the mempool and (b) how many of
them confirmed within each target number of blocks; an estimate for target
T scans buckets from the highest feerate down until the cumulative
confirmed-within-T ratio drops below the success threshold, answering
"the lowest feerate that historically confirmed within T blocks 95% of
the time". This module reproduces that design:

  - geometric buckets (x1.05) from 1000 sat/kB to 1e7 sat/kB,
  - per-block exponential decay (0.998 — the reference's long-horizon
    constant pre-0.15 split; one horizon, not three, documented
    simplification),
  - tracked mempool entries keyed by txid with entry height,
  - success-ratio bucket scan with a sufficient-sample floor,
  - estimatesmartfee semantics: try the requested target, then widen
    toward MAX_TARGET until an estimate exists (reporting the target that
    answered),
  - persistence across restarts (fee_estimates.dat analogue, JSON form).

Unlike the round-3 stand-in (a 100-block median deque), estimates now
genuinely depend on conf_target: a tx confirming in 2 blocks feeds targets
>= 2 only, so tight targets demand the feerates that actually confirmed
fast."""

from __future__ import annotations

import json
import os
from typing import Optional

MIN_BUCKET_FEERATE = 1000.0     # sat/kB — the relay floor
MAX_BUCKET_FEERATE = 1e7
BUCKET_SPACING = 1.05
DECAY = 0.998
MAX_TARGET = 25                 # confirmation targets tracked: 1..25
SUCCESS_PCT = 0.95
# Sample floor per evaluated bucket range: the reference gates on
# sufficientTxVal / (1 - decay) (TxConfirmStats::EstimateMedianVal with
# SUFFICIENT_FEETXS = 0.1 txs/block), i.e. ~50 decayed observations at
# this decay — a single tracked tx can never mint an estimate
# (VERDICT r4 item 9).
SUFFICIENT_TXS = 0.1            # per-block rate, reference constant
SUFFICIENT_SAMPLES = SUFFICIENT_TXS / (1.0 - DECAY)


def _make_buckets() -> list:
    out = [MIN_BUCKET_FEERATE]
    while out[-1] < MAX_BUCKET_FEERATE:
        out.append(out[-1] * BUCKET_SPACING)
    return out


class FeeEstimator:
    """CBlockPolicyEstimator analogue. All feerates are sat/kB."""

    def __init__(self, path: Optional[str] = None):
        self.buckets = _make_buckets()
        nb = len(self.buckets)
        # decayed totals per bucket
        self.tx_avg = [0.0] * nb                  # txs seen (confirmed ones)
        self.fee_sum = [0.0] * nb                 # feerate-weighted
        # conf_avg[t-1][b]: txs in bucket b confirmed within t blocks
        self.conf_avg = [[0.0] * nb for _ in range(MAX_TARGET)]
        # txid -> (entry_height, bucket_index, feerate)
        self.tracked: dict[bytes, tuple] = {}
        self.best_height = 0
        self.path = path
        if path and os.path.exists(path):
            try:
                self._read(path)
            except Exception:
                pass  # corrupt stats are re-learned, never fatal

    # -- bucket helpers -------------------------------------------------

    def _bucket_for(self, feerate: float) -> int:
        lo, hi = 0, len(self.buckets) - 1
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if self.buckets[mid] <= feerate:
                lo = mid
            else:
                hi = mid - 1
        return lo

    # -- mempool tracking (processTransaction / removeTx) ---------------

    def process_tx(self, txid: bytes, height: int, feerate: float) -> None:
        """A tx entered the mempool at ``height`` paying ``feerate``."""
        if txid in self.tracked:
            return
        self.tracked[txid] = (height, self._bucket_for(feerate), feerate)

    def remove_tx(self, txid: bytes) -> None:
        """Removed for a reason other than inclusion (eviction, expiry,
        conflict): drop without biasing the stats — like the reference."""
        self.tracked.pop(txid, None)

    # -- block processing (processBlock) --------------------------------

    def process_block(self, height: int, confirmed_txids) -> None:
        """Called once per connected block with the txids it confirmed."""
        if height <= self.best_height:
            # reorg replays: never double-count (processBlock guard)
            for txid in confirmed_txids:
                self.tracked.pop(txid, None)
            return
        self.best_height = height
        # decay first, so this block's observations carry full weight
        nb = len(self.buckets)
        for b in range(nb):
            self.tx_avg[b] *= DECAY
            self.fee_sum[b] *= DECAY
        for t in range(MAX_TARGET):
            row = self.conf_avg[t]
            for b in range(nb):
                row[b] *= DECAY
        for txid in confirmed_txids:
            got = self.tracked.pop(txid, None)
            if got is None:
                continue  # never saw it in our mempool: no data point
            entry_height, bucket, feerate = got
            blocks_to_confirm = height - entry_height
            if blocks_to_confirm < 1:
                continue  # same-block or reorg artifact: unmeasurable
            self.tx_avg[bucket] += 1.0
            self.fee_sum[bucket] += feerate
            for t in range(blocks_to_confirm - 1, MAX_TARGET):
                self.conf_avg[t][bucket] += 1.0

    # -- estimation (estimateMedianVal) ---------------------------------

    def estimate_fee(self, target: int) -> float:
        """Lowest bucket feerate whose cumulative (from the top) success
        ratio for ``target`` stays >= SUCCESS_PCT with enough decayed
        samples. -1 when no answer (the reference's cold result).

        Still-unconfirmed mempool txs older than ``target`` blocks count in
        the denominator (the reference's unconfTxs/oldUnconfTxs legs of
        EstimateMedianVal): under congestion a bucket whose txs mostly sit
        unconfirmed must NOT read as ~100% success — ADVICE r4 medium."""
        if not 1 <= target <= MAX_TARGET:
            return -1.0
        conf = self.conf_avg[target - 1]
        # per-bucket failures-so-far: tracked txs that have already waited
        # longer than the target without confirming (undecayed — they are
        # current mempool state, like the reference's unconfTxs rings)
        unconf = [0.0] * len(self.buckets)
        for entry_height, bucket, _feerate in self.tracked.values():
            # age == target means every block in the window has passed
            # without confirming (a confirm now would be target+1 blocks):
            # already a failure for this target
            if self.best_height - entry_height >= target:
                unconf[bucket] += 1.0
        best = -1.0
        cur_need = cur_got = cur_fee = cur_conf_n = 0.0
        # scan high -> low in ranges: each time a range accumulates enough
        # samples AND passes the success ratio, it becomes the new answer
        # and the accumulators reset — so the result is the LOWEST passing
        # range's decayed-average feerate (estimateMedianVal's shape)
        for b in range(len(self.buckets) - 1, -1, -1):
            cur_need += self.tx_avg[b] + unconf[b]
            cur_got += conf[b]
            cur_fee += self.fee_sum[b]
            cur_conf_n += self.tx_avg[b]
            if cur_need >= SUFFICIENT_SAMPLES:
                if cur_got / cur_need < SUCCESS_PCT:
                    break
                # average feerate over CONFIRMED observations only
                # (fee_sum has no unconfirmed component)
                best = cur_fee / cur_conf_n if cur_conf_n else -1.0
                cur_need = cur_got = cur_fee = cur_conf_n = 0.0
        return best

    def estimate_smart_fee(self, target: int):
        """(feerate, answered_target): widen the horizon until an estimate
        exists, like estimateSmartFee's loop. (-1, target) when cold."""
        target = max(1, min(int(target), MAX_TARGET))
        for t in range(target, MAX_TARGET + 1):
            est = self.estimate_fee(t)
            if est > 0:
                return est, t
        return -1.0, target

    # -- persistence (fee_estimates.dat) --------------------------------

    def flush(self, path: Optional[str] = None) -> None:
        path = path or self.path
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "version": 1,
                "best_height": self.best_height,
                "tx_avg": self.tx_avg,
                "fee_sum": self.fee_sum,
                "conf_avg": self.conf_avg,
            }, f)
        os.replace(tmp, path)

    def _read(self, path: str) -> None:
        with open(path) as f:
            data = json.load(f)
        if data.get("version") != 1:
            return
        nb = len(self.buckets)
        # validate EVERY array dimension before accepting: a truncated
        # fee_sum or ragged conf_avg row would otherwise IndexError inside
        # process_block and abort block connection ("never fatal" contract)
        if (len(data["tx_avg"]) != nb
                or len(data["fee_sum"]) != nb
                or len(data["conf_avg"]) != MAX_TARGET
                or any(len(row) != nb for row in data["conf_avg"])):
            return  # layout changed or corrupt: start fresh
        self.best_height = int(data["best_height"])
        self.tx_avg = [float(v) for v in data["tx_avg"]]
        self.fee_sum = [float(v) for v in data["fee_sum"]]
        self.conf_avg = [[float(v) for v in row] for row in data["conf_avg"]]
