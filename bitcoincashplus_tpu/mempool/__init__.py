"""Mempool (L4) — fee-ordered transaction pool + acceptance policy.

Reference: src/txmempool.{h,cpp} (CTxMemPool, ancestor/descendant
indexing, eviction, expiry), src/validation.cpp:~400 (AcceptToMemoryPool),
src/policy/policy.cpp (IsStandardTx, AreInputsStandard).

The reference's boost::multi_index is replaced by explicit dicts + sorted
views computed on demand: the pool mutates rarely relative to template
assembly, and ancestor aggregates are maintained incrementally exactly as
the reference's CTxMemPoolEntry cached values are.
"""

from .mempool import CTxMemPool, MempoolEntry, MempoolError  # noqa: F401
from .accept import accept_to_memory_pool  # noqa: F401
from .policy import (  # noqa: F401
    DEFAULT_MIN_RELAY_FEE_RATE,
    is_standard_tx,
    are_inputs_standard,
)
