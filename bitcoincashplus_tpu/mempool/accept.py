"""AcceptToMemoryPool — transaction admission.

Reference: src/validation.cpp:~400 (AcceptToMemoryPoolWorker): context-free
checks, standardness policy, finality at next-block height/MTP, conflict
rejection (no in-pool replacement in this lineage), coin lookup through a
mempool-backed view (CCoinsViewMemPool), maturity, fee floor, ancestor
limits, then script verification with STANDARD flags through the signature
cache so ConnectBlock later skips the same signatures.

Script verification reuses the deferral machinery (DeferringSignatureChecker
→ ecdsa_batch) so verified (sighash, r, s, pubkey) tuples land in the shared
SignatureCache — the reference achieves the same via CachingTransactionSignatureChecker.
"""

from __future__ import annotations

import time as _time
from typing import Optional

from ..consensus.tx import CTransaction
from ..consensus.tx_check import TxValidationError, check_transaction, is_final_tx
from ..ops import ecdsa_batch
from ..util import telemetry as tm
from ..script.interpreter import (
    SCRIPT_ENABLE_SIGHASH_FORKID,
    STANDARD_SCRIPT_VERIFY_FLAGS,
    DeferringSignatureChecker,
    ScriptError,
    SigCheckRecord,
    VerifyScript,
)
from ..script.script import count_sigops
from ..script.sighash import SighashCache
from ..validation.coins import Coin
from ..validation.sigcache import SignatureCache
from .mempool import CTxMemPool, MempoolEntry, MempoolError
from .policy import (
    are_inputs_standard,
    get_min_relay_fee,
    is_standard_tx,
)

# MEMPOOL_HEIGHT (src/txmempool.h): marker height for coins created by
# in-pool (unconfirmed) parents.
MEMPOOL_HEIGHT = 0x7FFFFFFF

# MAX_STANDARD_TX_SIGOPS (policy.h): 1/5 of the block sigop limit.
MAX_STANDARD_TX_SIGOPS = 4000

# -- telemetry (util/telemetry): the serving path's p50/p99 accept
# latency (ROADMAP "always-on signature service" ask) — one observation
# per AcceptToMemoryPool call, labeled by outcome. Sub-millisecond
# buckets matter here (a cache-hit accept is ~100 µs), so the default
# latency ladder's low end is kept.
_ACCEPT_H = tm.histogram(
    "bcp_mempool_accept_seconds",
    "AcceptToMemoryPool wall-clock per transaction",
    labels=("result",))
_ACCEPT_REJECTS = tm.counter(
    "bcp_mempool_reject_total",
    "Transactions rejected at mempool admission")
# Per-stage breakdown of a successful accept (ISSUE 20): "context" is
# everything up to and including the ancestor-limit check (policy,
# finality, coin lookup, fee floor), "scripts" the signature/script leg,
# "commit" the pool mutation (add_unchecked + trim_to_size). Under a
# flood the interesting question is WHICH stage the p99 lives in —
# admission CPU vs script verify vs eviction pressure.
_STAGE_H = tm.histogram(
    "bcp_mempool_accept_stage_seconds",
    "AcceptToMemoryPool per-stage wall-clock",
    labels=("stage",))


class _StaleContext(Exception):
    """The validation context moved while cs_main was released for the
    SigService verdict wait (tip advanced, or an in-pool parent vanished)
    — the whole acceptance re-runs from scratch (accept_to_memory_pool's
    retry loop; the final attempt is synchronous and cannot go stale)."""


def accept_latency_quantiles() -> dict:
    """gettpuinfo's serving-path latency view: p50/p90/p99 (ms) of
    ACCEPTED transactions, plus accept/reject tallies."""
    acc = _ACCEPT_H.labels(result="accepted")
    rej = _ACCEPT_H.labels(result="rejected")
    out = {f"{k}_ms": round(v * 1e3, 3)
           for k, v in acc.quantiles((0.5, 0.9, 0.99)).items()}
    out["accepted"] = acc.count
    out["rejected"] = rej.count
    return out


def accept_stage_quantiles() -> dict:
    """gettpuinfo.mempool's stage view: p50/p99 (ms) per accept stage."""
    out = {}
    for stage in ("context", "scripts", "commit"):
        h = _STAGE_H.labels(stage=stage)
        out[stage] = {f"{k}_ms": round(v * 1e3, 3)
                      for k, v in h.quantiles((0.5, 0.99)).items()}
        out[stage]["count"] = h.count
    return out


def standard_script_flags(params, height: int) -> int:
    """STANDARD_SCRIPT_VERIFY_FLAGS + the fork's replay-protection flag once
    UAHF is active at the next block height [fork-delta, hedged]."""
    flags = STANDARD_SCRIPT_VERIFY_FLAGS
    uahf = params.consensus.uahf_height
    if uahf >= 0 and height >= uahf:
        flags |= SCRIPT_ENABLE_SIGHASH_FORKID
    return flags


def _tx_sigops(tx: CTransaction, spent_coins: list[Coin]) -> int:
    """GetTransactionSigOpCount: legacy count over scriptSigs + outputs,
    plus accurate P2SH redeem-script sigops."""
    n = sum(count_sigops(txin.script_sig) for txin in tx.vin)
    n += sum(count_sigops(out.script_pubkey) for out in tx.vout)
    from ..script.script import get_script_ops, is_p2sh

    for txin, coin in zip(tx.vin, spent_coins):
        if is_p2sh(coin.out.script_pubkey):
            redeem = b""
            try:
                for _op, data, _ in get_script_ops(txin.script_sig):
                    redeem = data or b""
            except Exception:
                continue
            n += count_sigops(redeem, accurate=True)
    return n


def verify_tx_scripts(
    tx: CTransaction,
    spent_coins: list[Coin],
    flags: int,
    sigcache: Optional[SignatureCache] = None,
    backend: str = "cpu",
    sig_service=None,
    wait_ctx=None,
) -> None:
    """CheckInputs (src/validation.cpp:~1300) for a single transaction:
    run the interpreter per input, settle deferred sigchecks in one batch,
    insert fresh successes into the sigcache. Raises MempoolError.

    With a ``sig_service`` (serving/sigservice.SigService) the deferred
    records are enqueued into the shared micro-batching lanes and the
    per-tx future is awaited — inside ``wait_ctx()`` when supplied, so
    the caller's cs_main hold can be released while the verdict is in
    flight (concurrent accepts then share one device bucket). The
    service populates the sigcache at settle; verdicts are identical to
    the synchronous path by construction (same records, same engines)."""
    records: list[SigCheckRecord] = []
    cache = SighashCache(tx)
    for i, (txin, coin) in enumerate(zip(tx.vin, spent_coins)):
        checker = DeferringSignatureChecker(
            tx, i, coin.out.value, records, cache
        )
        try:
            VerifyScript(txin.script_sig, coin.out.script_pubkey, flags, checker)
        except ScriptError as e:
            raise MempoolError(
                "mandatory-script-verify-flag-failed",
                f"{e.code} input {i}",
            ) from e
    if not records:
        return
    keys = [
        SignatureCache.entry_key(r.msg_hash, r.r, r.s, r.pubkey, r.algo)
        for r in records
    ]
    if sig_service is not None:
        fut = sig_service.submit(records, keys)
        if wait_ctx is not None:
            with wait_ctx():
                ok = fut.result()
        else:
            ok = fut.result()
        for k, good in enumerate(ok):
            if not good:
                raise MempoolError(
                    "mandatory-script-verify-flag-failed",
                    f"signature verification failed input "
                    f"{records[k].in_idx}",
                )
        return  # sigcache populated by the service at settle
    if sigcache is not None:
        fresh = [k for k, key in enumerate(keys) if not sigcache.contains(key)]
    else:
        fresh = list(range(len(records)))
    if fresh:
        ok = ecdsa_batch.verify_batch(
            [records[k] for k in fresh], backend=backend
        )
        for lane, k in enumerate(fresh):
            if not ok[lane]:
                raise MempoolError(
                    "mandatory-script-verify-flag-failed",
                    f"signature verification failed input {records[k].in_idx}",
                )
        if sigcache is not None:
            for k in fresh:
                sigcache.add(keys[k])


def accept_to_memory_pool(
    pool: CTxMemPool,
    chainstate,
    tx: CTransaction,
    sigcache: Optional[SignatureCache] = None,
    require_standard: Optional[bool] = None,
    min_fee_rate: int = 1000,
    backend: str = "cpu",
    now: Optional[int] = None,
    ancestor_limits: Optional[dict] = None,
    sig_service=None,
    wait_ctx=None,
) -> MempoolEntry:
    """AcceptToMemoryPool (src/validation.cpp:~400). Returns the entry on
    success; raises MempoolError with the reference's reject reason.
    Per-tx wall-clock lands in the bcp_mempool_accept_seconds histogram
    (p50/p99 via gettpuinfo.telemetry.accept_latency).

    ``sig_service``/``wait_ctx`` route the signature verdict through the
    micro-batching SigService with the caller's lock released during the
    wait; a context change in that window (tip moved, in-pool parent
    evicted) raises _StaleContext internally and the acceptance re-runs —
    the FINAL attempt synchronously, which cannot go stale, so the
    verdict always lands and is identical to the service-off path."""
    t0 = _time.monotonic()
    with tm.span("mempool.accept", txid=tx.txid_hex):
        try:
            # serviced attempts first; a last synchronous attempt bounds
            # the retry loop (no unlock window => no staleness possible)
            for svc in (sig_service, sig_service, None):
                try:
                    entry = _accept_to_memory_pool_inner(
                        pool, chainstate, tx, sigcache, require_standard,
                        min_fee_rate, backend, now, ancestor_limits,
                        sig_service=svc, wait_ctx=wait_ctx)
                    break
                except _StaleContext:
                    continue
        except MempoolError:
            _ACCEPT_H.labels(result="rejected").observe(
                _time.monotonic() - t0)
            _ACCEPT_REJECTS.inc()
            raise
    _ACCEPT_H.labels(result="accepted").observe(_time.monotonic() - t0)
    return entry


def _accept_to_memory_pool_inner(
    pool: CTxMemPool,
    chainstate,
    tx: CTransaction,
    sigcache: Optional[SignatureCache],
    require_standard: Optional[bool],
    min_fee_rate: int,
    backend: str,
    now: Optional[int],
    ancestor_limits: Optional[dict],
    sig_service=None,
    wait_ctx=None,
) -> MempoolEntry:
    t_ctx = _time.monotonic()
    params = chainstate.params
    if require_standard is None:
        require_standard = params.require_standard
    tip = chainstate.tip()
    height = tip.height + 1  # validation happens at next-block height
    mtp = tip.get_median_time_past()

    try:
        check_transaction(tx)
    except TxValidationError as e:
        raise MempoolError(e.reason, e.debug) from e
    if tx.is_coinbase():
        raise MempoolError("coinbase")
    if require_standard:
        ok, reason = is_standard_tx(tx)
        if not ok:
            raise MempoolError(reason)
    if not is_final_tx(tx, height, mtp):
        raise MempoolError("non-final")

    txid = tx.txid
    if txid in pool:
        raise MempoolError("txn-already-in-mempool")
    for txin in tx.vin:
        spender = pool.get_spender(txin.prevout)
        if spender is not None:
            raise MempoolError("txn-mempool-conflict")

    # coin lookup: chainstate view backed by in-pool outputs (CCoinsViewMemPool)
    spent_coins: list[Coin] = []
    spends_coinbase = False
    for txin in tx.vin:
        coin = chainstate.coins.get_coin(txin.prevout)
        if coin is None:
            out = pool.get_output(txin.prevout)
            if out is not None:
                coin = Coin(out, MEMPOOL_HEIGHT, False)
        if coin is None:
            # distinguish already-spent-in-chain from never-seen the way the
            # reference's missing-inputs path does (both are non-fatal there;
            # we surface one reason)
            raise MempoolError("missing-inputs", f"{txin.prevout!r}")
        if coin.is_coinbase:
            spends_coinbase = True
            if height - coin.height < params.consensus.coinbase_maturity:
                raise MempoolError(
                    "bad-txns-premature-spend-of-coinbase",
                    f"{height - coin.height} of {params.consensus.coinbase_maturity}",
                )
        spent_coins.append(coin)

    value_in = sum(c.out.value for c in spent_coins)
    value_out = tx.total_output_value()
    if value_in < value_out:
        raise MempoolError("bad-txns-in-belowout", f"{value_in} < {value_out}")
    fee = value_in - value_out

    if require_standard and not are_inputs_standard(
        tx, [c.out for c in spent_coins]
    ):
        raise MempoolError("bad-txns-nonstandard-inputs")

    sigops = _tx_sigops(tx, spent_coins)
    if sigops > MAX_STANDARD_TX_SIGOPS:
        raise MempoolError("bad-txns-too-many-sigops", str(sigops))

    # nModifiedFees: a prioritisetransaction delta counts toward the fee
    # floor and every mining/eviction score, like the reference
    modified_fee = fee + pool.map_deltas.get(txid, 0)
    min_fee = get_min_relay_fee(tx.size(), min_fee_rate)
    if modified_fee < min_fee:
        raise MempoolError("mempool-min-fee-not-met",
                           f"{modified_fee} < {min_fee}")

    ancestors = pool.check_ancestor_limits(tx, fee,
                                           **(ancestor_limits or {}))
    t_scripts = _time.monotonic()
    _STAGE_H.labels(stage="context").observe(t_scripts - t_ctx)

    flags = standard_script_flags(params, height)
    verify_tx_scripts(tx, spent_coins, flags, sigcache, backend=backend,
                      sig_service=sig_service, wait_ctx=wait_ctx)
    if sig_service is not None and wait_ctx is not None:
        # cs_main may have been released during the SigService verdict
        # wait — every pool/chain fact above is a pre-wait snapshot.
        # Re-derive the cheap context; anything that moved retries the
        # whole acceptance (the sigcache now holds the verdicts, so the
        # re-run's verify is pure cache hits).
        if chainstate.tip() is not tip:
            raise _StaleContext
        if txid in pool:
            raise MempoolError("txn-already-in-mempool")
        for txin in tx.vin:
            spender = pool.get_spender(txin.prevout)
            if spender is not None:
                raise MempoolError("txn-mempool-conflict")
        for txin, coin in zip(tx.vin, spent_coins):
            if (coin.height == MEMPOOL_HEIGHT
                    and pool.get_output(txin.prevout) is None):
                raise _StaleContext  # in-pool parent vanished mid-wait
        # the ancestor package may have grown while unlocked
        ancestors = pool.check_ancestor_limits(tx, fee,
                                               **(ancestor_limits or {}))

    t_commit = _time.monotonic()
    _STAGE_H.labels(stage="scripts").observe(t_commit - t_scripts)
    entry = MempoolEntry(
        tx,
        modified_fee,
        now if now is not None else int(_time.time()),
        height,
        sigops=sigops,
        spends_coinbase=spends_coinbase,
        base_fee=fee,
    )
    pool.add_unchecked(entry, ancestors)
    removed = pool.trim_to_size()
    _STAGE_H.labels(stage="commit").observe(_time.monotonic() - t_commit)
    if txid not in pool:
        raise MempoolError("mempool-full", f"evicted with {len(removed) - 1} others")
    return entry
