"""CTxMemPool — the fee-ordered pool with ancestor/descendant tracking.

Reference: src/txmempool.{h,cpp}. The multi_index container becomes plain
dicts; the consensus-relevant invariants are preserved exactly:

* mapNextTx: every in-pool outpoint spend is unique (no conflicts enter).
* CTxMemPoolEntry caches {count, size, fees} aggregates over BOTH the
  ancestor and descendant sets, updated incrementally on add/remove —
  these drive ancestor-feerate mining scores and descendant-score
  eviction, the same quantities addPackageTxs / TrimToSize use.
* remove_for_block prunes confirmed txs and (recursively) conflicts.
"""

from __future__ import annotations

import time as _time
from typing import Iterable, Optional

from ..consensus.tx import COutPoint, CTransaction
from ..consensus.tx_check import is_final_tx


class MempoolError(Exception):
    """Reject reason carrier (the reference's CValidationState)."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        self.detail = detail
        super().__init__(f"{reason}{': ' + detail if detail else ''}")


class MempoolEntry:
    """CTxMemPoolEntry (src/txmempool.h:~60)."""

    __slots__ = (
        "tx", "fee", "base_fee", "time", "entry_height", "size", "sigops",
        "spends_coinbase",
        # cached aggregates (IncludeSelf): reference's nCountWithAncestors…
        "count_with_ancestors", "size_with_ancestors", "fees_with_ancestors",
        "count_with_descendants", "size_with_descendants",
        "fees_with_descendants",
    )

    def __init__(self, tx: CTransaction, fee: int, entry_time: int,
                 entry_height: int, sigops: int = 0,
                 spends_coinbase: bool = False,
                 base_fee: Optional[int] = None):
        self.tx = tx
        # `fee` is the MODIFIED fee (base + prioritisetransaction delta) —
        # it drives every score/aggregate, like the reference's
        # nModifiedFees; `base_fee` is what the tx actually pays.
        self.base_fee = fee if base_fee is None else base_fee
        self.fee = fee
        self.time = entry_time
        self.entry_height = entry_height
        self.size = tx.size()
        self.sigops = sigops
        self.spends_coinbase = spends_coinbase
        self.count_with_ancestors = 1
        self.size_with_ancestors = self.size
        self.fees_with_ancestors = fee
        self.count_with_descendants = 1
        self.size_with_descendants = self.size
        self.fees_with_descendants = fee

    @property
    def txid(self) -> bytes:
        return self.tx.txid

    def fee_rate(self) -> float:
        return self.fee / self.size

    def ancestor_fee_rate(self) -> float:
        """The addPackageTxs mining score: package feerate."""
        return self.fees_with_ancestors / self.size_with_ancestors

    def descendant_fee_rate(self) -> float:
        """The TrimToSize eviction score."""
        return self.fees_with_descendants / self.size_with_descendants


# default policy limits (DEFAULT_ANCESTOR_LIMIT etc., src/validation.h)
DEFAULT_ANCESTOR_LIMIT = 25
DEFAULT_ANCESTOR_SIZE_LIMIT = 101_000  # bytes
DEFAULT_DESCENDANT_LIMIT = 25
DEFAULT_DESCENDANT_SIZE_LIMIT = 101_000
DEFAULT_MEMPOOL_EXPIRY = 336 * 60 * 60  # 2 weeks, seconds
DEFAULT_MAX_MEMPOOL_SIZE = 300 * 1_000_000  # -maxmempool (bytes, approx)


class CTxMemPool:
    def __init__(self, max_size_bytes: int = DEFAULT_MAX_MEMPOOL_SIZE,
                 expiry_seconds: int = DEFAULT_MEMPOOL_EXPIRY):
        self.entries: dict[bytes, MempoolEntry] = {}
        self.map_next_tx: dict[COutPoint, bytes] = {}  # outpoint -> spender
        # removal hook (CTxMemPool::NotifyEntryRemoved analogue): fired for
        # EVERY removal; consumers that care about the reason (the fee
        # estimator must not count block-confirmed txs as failures) handle
        # confirmed txids BEFORE remove_for_block runs
        self.on_removed = None
        self.max_size_bytes = max_size_bytes
        self.expiry_seconds = expiry_seconds
        self.total_size = 0
        self.total_fee = 0
        # bumped on every mutation; getblocktemplate longpoll + caching key
        self.sequence = 0
        # mapDeltas (PrioritiseTransaction): txid -> fee delta in satoshis.
        # Outlives pool membership — a delta set before the tx arrives is
        # applied when it enters via AcceptToMemoryPool.
        self.map_deltas: dict[bytes, int] = {}

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------

    def __contains__(self, txid: bytes) -> bool:
        return txid in self.entries

    def __len__(self) -> int:
        return len(self.entries)

    def get(self, txid: bytes) -> Optional[MempoolEntry]:
        return self.entries.get(txid)

    def get_tx(self, txid: bytes) -> Optional[CTransaction]:
        e = self.entries.get(txid)
        return e.tx if e else None

    def get_spender(self, outpoint: COutPoint) -> Optional[bytes]:
        return self.map_next_tx.get(outpoint)

    def get_output(self, outpoint: COutPoint):
        """CCoinsViewMemPool leg: an in-pool tx's output, or None."""
        e = self.entries.get(outpoint.hash)
        if e is not None and outpoint.n < len(e.tx.vout):
            return e.tx.vout[outpoint.n]
        return None

    def parents_in_pool(self, tx: CTransaction) -> set[bytes]:
        return {
            txin.prevout.hash
            for txin in tx.vin
            if txin.prevout.hash in self.entries
        }

    def calculate_ancestors(self, tx: CTransaction) -> set[bytes]:
        """CalculateMemPoolAncestors: transitive in-pool ancestor txids."""
        out: set[bytes] = set()
        stack = list(self.parents_in_pool(tx))
        while stack:
            txid = stack.pop()
            if txid in out:
                continue
            out.add(txid)
            stack.extend(self.parents_in_pool(self.entries[txid].tx))
        return out

    def calculate_descendants(self, txid: bytes) -> set[bytes]:
        """CalculateDescendants: txid + everything depending on it."""
        out: set[bytes] = set()
        stack = [txid]
        while stack:
            cur = stack.pop()
            if cur in out or cur not in self.entries:
                continue
            out.add(cur)
            e = self.entries[cur]
            for i in range(len(e.tx.vout)):
                spender = self.map_next_tx.get(COutPoint(cur, i))
                if spender is not None:
                    stack.append(spender)
        return out

    def check_ancestor_limits(
        self, tx: CTransaction, fee: int,
        limit_count: int = DEFAULT_ANCESTOR_LIMIT,
        limit_size: int = DEFAULT_ANCESTOR_SIZE_LIMIT,
        limit_desc: int = DEFAULT_DESCENDANT_LIMIT,
        limit_desc_size: int = DEFAULT_DESCENDANT_SIZE_LIMIT,
    ) -> set[bytes]:
        """CalculateMemPoolAncestors' limit-enforcing form; returns the
        ancestor set or raises MempoolError (too-long-mempool-chain)."""
        ancestors = self.calculate_ancestors(tx)
        size = tx.size() + sum(self.entries[a].size for a in ancestors)
        if len(ancestors) + 1 > limit_count:
            raise MempoolError("too-long-mempool-chain", "ancestor count")
        if size > limit_size:
            raise MempoolError("too-long-mempool-chain", "ancestor size")
        for a in ancestors:
            e = self.entries[a]
            if e.count_with_descendants + 1 > limit_desc:
                raise MempoolError("too-long-mempool-chain", "descendant count")
            if e.size_with_descendants + tx.size() > limit_desc_size:
                raise MempoolError("too-long-mempool-chain", "descendant size")
        return ancestors

    # ------------------------------------------------------------------
    # mutation
    # ------------------------------------------------------------------

    def add_unchecked(self, entry: MempoolEntry,
                      ancestors: Optional[set[bytes]] = None) -> None:
        """addUnchecked (txmempool.cpp:~350): caller has validated."""
        txid = entry.txid
        assert txid not in self.entries
        if ancestors is None:
            ancestors = self.calculate_ancestors(entry.tx)
        self.entries[txid] = entry
        for txin in entry.tx.vin:
            assert txin.prevout not in self.map_next_tx, "conflicting spend"
            self.map_next_tx[txin.prevout] = txid
        # update aggregates: self's ancestor cache, ancestors' descendant
        # caches (UpdateAncestorsOf / UpdateEntryForAncestors)
        for a in ancestors:
            ae = self.entries[a]
            ae.count_with_descendants += 1
            ae.size_with_descendants += entry.size
            ae.fees_with_descendants += entry.fee
            entry.count_with_ancestors += 1
            entry.size_with_ancestors += ae.size
            entry.fees_with_ancestors += ae.fee
        self.total_size += entry.size
        self.total_fee += entry.fee
        self.sequence += 1

    def _remove_one(self, txid: bytes) -> MempoolEntry:
        entry = self.entries.pop(txid)
        if self.on_removed is not None:
            self.on_removed(txid)
        for txin in entry.tx.vin:
            self.map_next_tx.pop(txin.prevout, None)
        # fix aggregates on remaining relatives
        for a in self.calculate_ancestors(entry.tx):
            ae = self.entries[a]
            ae.count_with_descendants -= 1
            ae.size_with_descendants -= entry.size
            ae.fees_with_descendants -= entry.fee
        for d in self.calculate_descendants_of_outputs(entry.tx):
            de = self.entries[d]
            de.count_with_ancestors -= 1
            de.size_with_ancestors -= entry.size
            de.fees_with_ancestors -= entry.fee
        self.total_size -= entry.size
        self.total_fee -= entry.fee
        self.sequence += 1
        return entry

    def prioritise(self, txid: bytes, fee_delta: int) -> None:
        """PrioritiseTransaction (txmempool.cpp:~800): accumulate a fee
        delta for txid and, if it is in the pool, push the delta through
        its own and its relatives' fee aggregates."""
        self.map_deltas[txid] = self.map_deltas.get(txid, 0) + fee_delta
        entry = self.entries.get(txid)
        if entry is None:
            return
        entry.fee += fee_delta
        entry.fees_with_ancestors += fee_delta
        entry.fees_with_descendants += fee_delta
        for a in self.calculate_ancestors(entry.tx):
            self.entries[a].fees_with_descendants += fee_delta
        for d in self.calculate_descendants_of_outputs(entry.tx):
            self.entries[d].fees_with_ancestors += fee_delta
        self.total_fee += fee_delta
        self.sequence += 1

    def calculate_descendants_of_outputs(self, tx: CTransaction) -> set[bytes]:
        out: set[bytes] = set()
        for i in range(len(tx.vout)):
            spender = self.map_next_tx.get(COutPoint(tx.txid, i))
            if spender is not None:
                out |= self.calculate_descendants(spender)
        return out

    def remove_recursive(self, txid: bytes) -> list[bytes]:
        """removeRecursive: tx + all descendants. Returns removed txids."""
        removed = []
        for victim in sorted(
            self.calculate_descendants(txid),
            key=lambda t: -self.entries[t].count_with_ancestors,
        ):
            if victim in self.entries:
                self._remove_one(victim)
                removed.append(victim)
        return removed

    def remove_for_block(self, block_txs: Iterable[CTransaction]) -> None:
        """removeForBlock: drop confirmed txs, then conflicts (anything
        spending an outpoint a block tx just spent)."""
        for tx in block_txs:
            # ClearPrioritisation: a confirmed tx's fee delta is spent
            # (coinbases included — their txids can carry stray deltas)
            self.map_deltas.pop(tx.txid, None)
            if tx.is_coinbase():
                continue
            if tx.txid in self.entries:
                # confirmed: remove JUST this tx (descendants re-anchor)
                self._remove_one(tx.txid)
            for txin in tx.vin:
                conflict = self.map_next_tx.get(txin.prevout)
                if conflict is not None and conflict != tx.txid:
                    self.remove_recursive(conflict)

    def expire(self, now: Optional[int] = None) -> int:
        """Expire (txmempool.cpp:~600): drop entries older than the expiry
        window, with their descendants."""
        now = now if now is not None else int(_time.time())
        cutoff = now - self.expiry_seconds
        stale = [t for t, e in self.entries.items() if e.time < cutoff]
        n = 0
        for txid in stale:
            if txid in self.entries:
                n += len(self.remove_recursive(txid))
        return n

    def trim_to_size(self, max_bytes: Optional[int] = None) -> list[bytes]:
        """TrimToSize: evict lowest descendant-score packages until the
        pool fits. Returns removed txids."""
        max_bytes = max_bytes if max_bytes is not None else self.max_size_bytes
        removed = []
        while self.total_size > max_bytes and self.entries:
            worst = min(
                self.entries.values(), key=lambda e: e.descendant_fee_rate()
            )
            removed.extend(self.remove_recursive(worst.txid))
        return removed

    # ------------------------------------------------------------------
    # mining interface (BlockAssembler.addPackageTxs parity)
    # ------------------------------------------------------------------

    def select_for_block(self, max_size: int, height: int,
                         block_time: int) -> list[MempoolEntry]:
        """Greedy ancestor-feerate package selection — addPackageTxs
        (src/miner.cpp:~300): repeatedly take the entry with the best
        ancestor-package feerate, emit its not-yet-selected ancestors
        first (topological order), and account the whole package; skip
        packages that would overflow the block.
        """
        selected: list[MempoolEntry] = []
        in_block: set[bytes] = set()
        used = 0
        # effective (fees, size) of each entry's package minus what's
        # already in the block — recomputed lazily like the reference's
        # mapModifiedTx rescoring
        skipped: set[bytes] = set()
        # IsFinalTx gate (addPackageTxs → TestBlockValidity parity): a
        # non-final tx poisons its whole descendant subtree for this block.
        for txid, e in self.entries.items():
            if txid not in skipped and not is_final_tx(e.tx, height, block_time):
                skipped |= self.calculate_descendants(txid)
        while True:
            best: Optional[MempoolEntry] = None
            best_rate = -1.0
            best_pkg: Optional[list[bytes]] = None
            for e in self.entries.values():
                if e.txid in in_block or e.txid in skipped:
                    continue
                anc = [
                    a for a in self.calculate_ancestors(e.tx)
                    if a not in in_block
                ]
                pkg_size = e.size + sum(self.entries[a].size for a in anc)
                pkg_fees = e.fee + sum(self.entries[a].fee for a in anc)
                rate = pkg_fees / pkg_size
                if rate > best_rate:
                    best, best_rate, best_pkg = e, rate, anc + [e.txid]
            if best is None:
                return selected
            pkg_size = sum(self.entries[t].size for t in best_pkg)
            if used + pkg_size > max_size:
                skipped.add(best.txid)
                continue
            # topological emit: parents before children
            order = sorted(
                best_pkg, key=lambda t: self.entries[t].count_with_ancestors
            )
            for txid in order:
                selected.append(self.entries[txid])
                in_block.add(txid)
            used += pkg_size

    def info(self) -> dict:
        """getmempoolinfo backend."""
        return {
            "size": len(self.entries),
            "bytes": self.total_size,
            "total_fee": self.total_fee,
            "maxmempool": self.max_size_bytes,
        }
