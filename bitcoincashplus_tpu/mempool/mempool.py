"""CTxMemPool — the fee-ordered pool with ancestor/descendant tracking.

Reference: src/txmempool.{h,cpp}. The multi_index container becomes plain
dicts; the consensus-relevant invariants are preserved exactly:

* mapNextTx: every in-pool outpoint spend is unique (no conflicts enter).
* CTxMemPoolEntry caches {count, size, fees} aggregates over BOTH the
  ancestor and descendant sets, updated incrementally on add/remove —
  these drive ancestor-feerate mining scores and descendant-score
  eviction, the same quantities addPackageTxs / TrimToSize use.
* remove_for_block prunes confirmed txs and (recursively) conflicts.

Flood-scale shape (ISSUE 20): at exchange-scale tx floods the per-query
walks around those aggregates were the wall — ``trim_to_size`` re-scanned
every entry per eviction round, ``select_for_block`` recomputed greedy
package selection from scratch per template, and every removal re-walked
the graph per tx. Admission and assembly are now batch-shaped:

* **Columns** — entry aggregates mirrored into parallel numpy arrays
  (fee/size/ancestor/descendant aggregates + entry time), kept in sync by
  the same incremental add/remove/prioritise hooks that maintain the
  per-entry caches. Limit checks and expiry scans are vectorized gathers
  instead of per-entry Python walks.
* **Frontiers** — two incrementally-maintained lazy heaps: the MINING
  frontier (max ancestor-package feerate — addPackageTxs' score) and the
  EVICTION frontier (min descendant feerate — TrimToSize's score). Every
  aggregate mutation pushes a refreshed key; stale keys are detected at
  pop (stored aggregates no longer match) and discarded. Neither is ever
  recomputed from scratch on the hot path.
* **Staged removal** — ``remove_for_block``/eviction/expiry remove whole
  sets through one ``_remove_staged`` pass that applies every surviving
  relative's aggregate fix against the PRE-removal graph (the reference's
  ``UpdateForRemoveFromMempool`` over a stage set). This also fixes a
  real leak in the old sequential path: removing a parent before its
  child (block order!) broke the child's ancestor walk, so grandparents
  kept phantom descendant aggregates forever.
* **Exact feerate order** — all score comparisons are integer
  cross-multiplications (fee_a*size_b vs fee_b*size_a) with txid
  tie-breaks, so ordering is exact and platform-stable even at fee
  magnitudes where float64 ties lie. The float ``*_fee_rate`` forms
  remain for display only. Heap keys use a 64-bit fixed-point form,
  ``(fee << 64) // size``: package sizes are bounded well below 2**32,
  so distinct rationals always map to distinct keys and the heap order
  equals the cross-multiplication order.

The per-tx reference paths survive as ``*_reference`` — they are the
fault-injection fallback (``BCP_FAULT_OPS=mempool``, fail-*) and the
differential gate's oracle (poison-output / -mempoolselfcheck): the gate
recomputes each batched verdict per-tx and any mismatch falls back to
the reference answer (counted in ``perf_snapshot``).
"""

from __future__ import annotations

import heapq
import time as _time
from typing import Iterable, Optional

import numpy as np

from ..consensus.tx import COutPoint, CTransaction
from ..consensus.tx_check import is_final_tx
from ..util.faults import INJECTOR, MEMPOOL_SITE, InjectedFault
from ..util.log import log_printf


class MempoolError(Exception):
    """Reject reason carrier (the reference's CValidationState)."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        self.detail = detail
        super().__init__(f"{reason}{': ' + detail if detail else ''}")


class MempoolEntry:
    """CTxMemPoolEntry (src/txmempool.h:~60)."""

    __slots__ = (
        "tx", "fee", "base_fee", "time", "entry_height", "size", "sigops",
        "spends_coinbase",
        # cached aggregates (IncludeSelf): reference's nCountWithAncestors…
        "count_with_ancestors", "size_with_ancestors", "fees_with_ancestors",
        "count_with_descendants", "size_with_descendants",
        "fees_with_descendants",
    )

    def __init__(self, tx: CTransaction, fee: int, entry_time: int,
                 entry_height: int, sigops: int = 0,
                 spends_coinbase: bool = False,
                 base_fee: Optional[int] = None):
        self.tx = tx
        # `fee` is the MODIFIED fee (base + prioritisetransaction delta) —
        # it drives every score/aggregate, like the reference's
        # nModifiedFees; `base_fee` is what the tx actually pays.
        self.base_fee = fee if base_fee is None else base_fee
        self.fee = fee
        self.time = entry_time
        self.entry_height = entry_height
        self.size = tx.size()
        self.sigops = sigops
        self.spends_coinbase = spends_coinbase
        self.count_with_ancestors = 1
        self.size_with_ancestors = self.size
        self.fees_with_ancestors = fee
        self.count_with_descendants = 1
        self.size_with_descendants = self.size
        self.fees_with_descendants = fee

    @property
    def txid(self) -> bytes:
        return self.tx.txid

    def fee_rate(self) -> float:
        """Display only — ordering uses feerate_gt/score_key (exact)."""
        return self.fee / self.size

    def ancestor_fee_rate(self) -> float:
        """The addPackageTxs mining score: package feerate (display
        only — ordering uses feerate_gt/score_key)."""
        return self.fees_with_ancestors / self.size_with_ancestors

    def descendant_fee_rate(self) -> float:
        """The TrimToSize eviction score (display only — ordering uses
        feerate_gt/score_key)."""
        return self.fees_with_descendants / self.size_with_descendants


# -- exact feerate order (ISSUE 20 satellite) --------------------------
#
# fee/size comparisons via integer cross-multiplication: exact at any
# fee magnitude (float64 ties at ~2**53) and platform-stable. Ties break
# on txid so every ordering consumer (reference scans, heaps, sorts)
# agrees byte-for-byte.

_SCORE_SHIFT = 64


def feerate_gt(fee_a: int, size_a: int, fee_b: int, size_b: int) -> bool:
    """fee_a/size_a > fee_b/size_b, exactly (sizes are positive)."""
    return fee_a * size_b > fee_b * size_a


def score_key(fee: int, size: int) -> int:
    """64-bit fixed-point feerate: (fee << 64) // size. Monotone in the
    exact rational order, and injective on DISTINCT rationals whenever
    size_a * size_b < 2**64 (package sizes are < 2**32), so comparing
    keys equals cross-multiplying — heap-friendly exactness."""
    return (fee << _SCORE_SHIFT) // size


def _pkg_better(fee_a, size_a, txid_a, fee_b, size_b, txid_b) -> bool:
    """Mining-score total order: higher package feerate wins, ties to
    the smaller txid (both paths — reference scan and frontier heap —
    use exactly this order, so templates are deterministic)."""
    if feerate_gt(fee_a, size_a, fee_b, size_b):
        return True
    if feerate_gt(fee_b, size_b, fee_a, size_a):
        return False
    return txid_a < txid_b


def _evict_worse(fee_a, size_a, txid_a, fee_b, size_b, txid_b) -> bool:
    """Eviction total order: lower descendant feerate is worse, ties to
    the smaller txid (evicted first)."""
    if feerate_gt(fee_b, size_b, fee_a, size_a):
        return True
    if feerate_gt(fee_a, size_a, fee_b, size_b):
        return False
    return txid_a < txid_b


# default policy limits (DEFAULT_ANCESTOR_LIMIT etc., src/validation.h)
DEFAULT_ANCESTOR_LIMIT = 25
DEFAULT_ANCESTOR_SIZE_LIMIT = 101_000  # bytes
DEFAULT_DESCENDANT_LIMIT = 25
DEFAULT_DESCENDANT_SIZE_LIMIT = 101_000
DEFAULT_MEMPOOL_EXPIRY = 336 * 60 * 60  # 2 weeks, seconds
DEFAULT_MAX_MEMPOOL_SIZE = 300 * 1_000_000  # -maxmempool (bytes, approx)


class MempoolColumns:
    """Parallel numpy mirror of the per-entry aggregate caches.

    One row per pool entry; rows are recycled through a free list and the
    arrays double on growth. The pool's mutation hooks mark dirty txids
    and ``sync_row`` copies the entry fields — score scans, limit checks
    and expiry cutoffs then run as vectorized gathers over live rows
    instead of per-entry Python attribute walks.
    """

    FIELDS = ("fee", "size", "fees_wa", "size_wa", "count_wa",
              "fees_wd", "size_wd", "count_wd", "time")

    __slots__ = ("cap", "txrow", "rowtx", "free", "live", "grows") + FIELDS

    def __init__(self, cap: int = 1024):
        self.cap = cap
        self.txrow: dict[bytes, int] = {}
        self.rowtx: list = [None] * cap
        self.free = list(range(cap - 1, -1, -1))
        self.live = np.zeros(cap, dtype=bool)
        self.grows = 0
        for f in self.FIELDS:
            setattr(self, f, np.zeros(cap, dtype=np.int64))

    def _grow(self) -> None:
        new_cap = self.cap * 2
        pad = self.cap
        for f in self.FIELDS:
            setattr(self, f, np.concatenate(
                [getattr(self, f), np.zeros(pad, dtype=np.int64)]))
        self.live = np.concatenate([self.live, np.zeros(pad, dtype=bool)])
        self.rowtx.extend([None] * pad)
        self.free.extend(range(new_cap - 1, self.cap - 1, -1))
        self.cap = new_cap
        self.grows += 1

    def add(self, entry: MempoolEntry) -> int:
        if not self.free:
            self._grow()
        row = self.free.pop()
        self.txrow[entry.txid] = row
        self.rowtx[row] = entry.txid
        self.live[row] = True
        self.sync_row(row, entry)
        return row

    def sync_row(self, row: int, e: MempoolEntry) -> None:
        self.fee[row] = e.fee
        self.size[row] = e.size
        self.fees_wa[row] = e.fees_with_ancestors
        self.size_wa[row] = e.size_with_ancestors
        self.count_wa[row] = e.count_with_ancestors
        self.fees_wd[row] = e.fees_with_descendants
        self.size_wd[row] = e.size_with_descendants
        self.count_wd[row] = e.count_with_descendants
        self.time[row] = e.time

    def drop(self, txid: bytes) -> None:
        row = self.txrow.pop(txid)
        self.live[row] = False
        self.rowtx[row] = None
        self.free.append(row)

    def rows_for(self, txids) -> np.ndarray:
        return np.fromiter((self.txrow[t] for t in txids),
                           dtype=np.int64, count=len(txids))

    def stale_txids(self, cutoff: int) -> list[bytes]:
        """Vectorized expiry scan: txids of live rows with time < cutoff."""
        rows = np.flatnonzero(self.live & (self.time < cutoff))
        return [self.rowtx[r] for r in rows]

    def snapshot(self) -> dict:
        return {"capacity": self.cap, "live": len(self.txrow),
                "grows": self.grows}


class CTxMemPool:
    # machine-enforced by bcplint BCP009 (the CConnman.GUARDED_BY
    # pattern): the batch-shape state — the column mirror, both frontier
    # heaps, and the perf tallies — is mutated on every pool mutation,
    # and every runtime mutation path (RPC workers, the P2P event loop,
    # the resident miner) serializes on the node's cs_main; the
    # interprocedural lockset proves it, so a future lock-free caller is
    # a lint failure, not a heisenbug.
    GUARDED_BY = {
        "columns": "cs_main",
        "_mine_heap": "cs_main",
        "_evict_heap": "cs_main",
        "perf": "cs_main",
    }

    def __init__(self, max_size_bytes: int = DEFAULT_MAX_MEMPOOL_SIZE,
                 expiry_seconds: int = DEFAULT_MEMPOOL_EXPIRY,
                 batch: bool = True, selfcheck: bool = False):
        self.entries: dict[bytes, MempoolEntry] = {}
        self.map_next_tx: dict[COutPoint, bytes] = {}  # outpoint -> spender
        # removal hook (CTxMemPool::NotifyEntryRemoved analogue): fired for
        # EVERY removal; consumers that care about the reason (the fee
        # estimator must not count block-confirmed txs as failures) handle
        # confirmed txids BEFORE remove_for_block runs
        self.on_removed = None
        self.max_size_bytes = max_size_bytes
        self.expiry_seconds = expiry_seconds
        self.total_size = 0
        self.total_fee = 0
        # bumped on every mutation; getblocktemplate longpoll + caching key
        self.sequence = 0
        # mapDeltas (PrioritiseTransaction): txid -> fee delta in satoshis.
        # Outlives pool membership — a delta set before the tx arrives is
        # applied when it enters via AcceptToMemoryPool.
        self.map_deltas: dict[bytes, int] = {}
        # -mempoolbatch: columns + frontiers on (default). Off = the
        # per-tx reference paths everywhere (the fault-fallback mode,
        # pinned by the differential suite).
        self.batch = batch
        # -mempoolselfcheck: run the differential gate on every batched
        # select/trim verdict (the poison-output drill arms it too).
        self.selfcheck = selfcheck
        self.columns = MempoolColumns() if batch else None
        # Frontier heaps (lazy deletion): entries are
        #   mining:   (-score_key(fees_wa, size_wa), txid, fees_wa, size_wa)
        #   eviction: ( score_key(fees_wd, size_wd), txid, fees_wd, size_wd)
        # a popped key is valid only if its stored aggregates still match
        # the entry's current ones; every mutation pushes a fresh key.
        self._mine_heap: list = []
        self._evict_heap: list = []
        # batch-shape observability (perf_snapshot / gettpuinfo.mempool)
        self.perf = {
            "column_syncs": 0, "rows_synced": 0,
            "frontier_pushes": 0, "frontier_stale_pops": 0,
            "frontier_rebuilds": 0,
            "bulk_evict_episodes": 0, "bulk_evicted": 0,
            "staged_removals": 0,
            "select_batched": 0, "select_fallbacks": 0,
            "trim_fallbacks": 0, "selfchecks": 0, "poisoned_verdicts": 0,
        }

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------

    def __contains__(self, txid: bytes) -> bool:
        return txid in self.entries

    def __len__(self) -> int:
        return len(self.entries)

    def get(self, txid: bytes) -> Optional[MempoolEntry]:
        return self.entries.get(txid)

    def get_tx(self, txid: bytes) -> Optional[CTransaction]:
        e = self.entries.get(txid)
        return e.tx if e else None

    def get_spender(self, outpoint: COutPoint) -> Optional[bytes]:
        return self.map_next_tx.get(outpoint)

    def get_output(self, outpoint: COutPoint):
        """CCoinsViewMemPool leg: an in-pool tx's output, or None."""
        e = self.entries.get(outpoint.hash)
        if e is not None and outpoint.n < len(e.tx.vout):
            return e.tx.vout[outpoint.n]
        return None

    def parents_in_pool(self, tx: CTransaction) -> set[bytes]:
        return {
            txin.prevout.hash
            for txin in tx.vin
            if txin.prevout.hash in self.entries
        }

    def calculate_ancestors(self, tx: CTransaction) -> set[bytes]:
        """CalculateMemPoolAncestors: transitive in-pool ancestor txids."""
        out: set[bytes] = set()
        stack = list(self.parents_in_pool(tx))
        while stack:
            txid = stack.pop()
            if txid in out:
                continue
            out.add(txid)
            stack.extend(self.parents_in_pool(self.entries[txid].tx))
        return out

    def calculate_descendants(self, txid: bytes) -> set[bytes]:
        """CalculateDescendants: txid + everything depending on it."""
        out: set[bytes] = set()
        stack = [txid]
        while stack:
            cur = stack.pop()
            if cur in out or cur not in self.entries:
                continue
            out.add(cur)
            e = self.entries[cur]
            for i in range(len(e.tx.vout)):
                spender = self.map_next_tx.get(COutPoint(cur, i))
                if spender is not None:
                    stack.append(spender)
        return out

    def check_ancestor_limits(
        self, tx: CTransaction, fee: int,
        limit_count: int = DEFAULT_ANCESTOR_LIMIT,
        limit_size: int = DEFAULT_ANCESTOR_SIZE_LIMIT,
        limit_desc: int = DEFAULT_DESCENDANT_LIMIT,
        limit_desc_size: int = DEFAULT_DESCENDANT_SIZE_LIMIT,
    ) -> set[bytes]:
        """CalculateMemPoolAncestors' limit-enforcing form; returns the
        ancestor set or raises MempoolError (too-long-mempool-chain).
        Batch mode gathers the ancestor rows from the columns — the sums
        and the per-ancestor descendant-limit probes are one vectorized
        pass instead of a Python attribute walk per ancestor."""
        ancestors = self.calculate_ancestors(tx)
        if len(ancestors) + 1 > limit_count:
            raise MempoolError("too-long-mempool-chain", "ancestor count")
        if self.batch and ancestors:
            rows = self.columns.rows_for(ancestors)
            size = tx.size() + int(self.columns.size[rows].sum())
            if size > limit_size:
                raise MempoolError("too-long-mempool-chain", "ancestor size")
            if bool((self.columns.count_wd[rows] + 1 > limit_desc).any()):
                raise MempoolError("too-long-mempool-chain",
                                   "descendant count")
            if bool((self.columns.size_wd[rows] + tx.size()
                     > limit_desc_size).any()):
                raise MempoolError("too-long-mempool-chain",
                                   "descendant size")
            return ancestors
        size = tx.size() + sum(self.entries[a].size for a in ancestors)
        if size > limit_size:
            raise MempoolError("too-long-mempool-chain", "ancestor size")
        for a in ancestors:
            e = self.entries[a]
            if e.count_with_descendants + 1 > limit_desc:
                raise MempoolError("too-long-mempool-chain",
                                   "descendant count")
            if e.size_with_descendants + tx.size() > limit_desc_size:
                raise MempoolError("too-long-mempool-chain",
                                   "descendant size")
        return ancestors

    # ------------------------------------------------------------------
    # column / frontier maintenance
    # ------------------------------------------------------------------

    def _push_frontiers(self, e: MempoolEntry) -> None:
        heapq.heappush(self._mine_heap, (
            -score_key(e.fees_with_ancestors, e.size_with_ancestors),
            e.txid, e.fees_with_ancestors, e.size_with_ancestors))
        heapq.heappush(self._evict_heap, (
            score_key(e.fees_with_descendants, e.size_with_descendants),
            e.txid, e.fees_with_descendants, e.size_with_descendants))
        self.perf["frontier_pushes"] += 2
        # lazy-heap hygiene: dead keys accumulate per mutation; compact
        # when the heaps dwarf the pool so memory stays O(pool)
        if len(self._mine_heap) > max(256, 8 * len(self.entries)):
            self._rebuild_frontiers()

    def _rebuild_frontiers(self) -> None:
        self._mine_heap = [
            (-score_key(e.fees_with_ancestors, e.size_with_ancestors),
             t, e.fees_with_ancestors, e.size_with_ancestors)
            for t, e in self.entries.items()]
        self._evict_heap = [
            (score_key(e.fees_with_descendants, e.size_with_descendants),
             t, e.fees_with_descendants, e.size_with_descendants)
            for t, e in self.entries.items()]
        heapq.heapify(self._mine_heap)
        heapq.heapify(self._evict_heap)
        self.perf["frontier_rebuilds"] += 1

    def _sync(self, dirty: Iterable[bytes]) -> None:
        """One column write + frontier key push per dirty SURVIVING txid —
        called once at the end of every mutating operation (the batch
        analogue of the reference's per-entry cache updates)."""
        if not self.batch:
            return
        self.perf["column_syncs"] += 1
        cols = self.columns
        for txid in dirty:
            e = self.entries.get(txid)
            if e is None:
                continue
            cols.sync_row(cols.txrow[txid], e)
            self.perf["rows_synced"] += 1
            self._push_frontiers(e)

    # ------------------------------------------------------------------
    # mutation
    # ------------------------------------------------------------------

    def add_unchecked(self, entry: MempoolEntry,
                      ancestors: Optional[set[bytes]] = None) -> None:
        """addUnchecked (txmempool.cpp:~350): caller has validated."""
        txid = entry.txid
        assert txid not in self.entries
        if ancestors is None:
            ancestors = self.calculate_ancestors(entry.tx)
        self.entries[txid] = entry
        for txin in entry.tx.vin:
            assert txin.prevout not in self.map_next_tx, "conflicting spend"
            self.map_next_tx[txin.prevout] = txid
        # update aggregates: self's ancestor cache, ancestors' descendant
        # caches (UpdateAncestorsOf / UpdateEntryForAncestors)
        for a in ancestors:
            ae = self.entries[a]
            ae.count_with_descendants += 1
            ae.size_with_descendants += entry.size
            ae.fees_with_descendants += entry.fee
            entry.count_with_ancestors += 1
            entry.size_with_ancestors += ae.size
            entry.fees_with_ancestors += ae.fee
        self.total_size += entry.size
        self.total_fee += entry.fee
        self.sequence += 1
        if self.batch:
            self.columns.add(entry)
            self._push_frontiers(entry)
            self._sync(ancestors)

    def _remove_staged(self, stage: set[bytes]) -> list[bytes]:
        """RemoveStaged/UpdateForRemoveFromMempool: remove a whole set in
        one pass. Every surviving relative's aggregate fix is computed
        against the PRE-removal graph while all stage entries are still
        present — parent-before-child removal order can no longer break
        an ancestor walk (the sequential ``_remove_one`` leak).
        Returns the removed txids, children-first (the old
        remove_recursive emission order)."""
        if not stage:
            return []
        dirty: set[bytes] = set()
        for txid in stage:
            e = self.entries[txid]
            for a in self.calculate_ancestors(e.tx):
                if a in stage:
                    continue
                ae = self.entries[a]
                ae.count_with_descendants -= 1
                ae.size_with_descendants -= e.size
                ae.fees_with_descendants -= e.fee
                dirty.add(a)
            for d in self.calculate_descendants_of_outputs(e.tx):
                if d in stage:
                    continue
                de = self.entries[d]
                de.count_with_ancestors -= 1
                de.size_with_ancestors -= e.size
                de.fees_with_ancestors -= e.fee
                dirty.add(d)
        out = sorted(
            stage,
            key=lambda t: (-self.entries[t].count_with_ancestors, t))
        for txid in out:
            entry = self.entries.pop(txid)
            if self.on_removed is not None:
                self.on_removed(txid)
            for txin in entry.tx.vin:
                self.map_next_tx.pop(txin.prevout, None)
            self.total_size -= entry.size
            self.total_fee -= entry.fee
            self.sequence += 1
            if self.batch:
                self.columns.drop(txid)
        self.perf["staged_removals"] += 1
        self._sync(dirty)
        return out

    def _remove_one(self, txid: bytes) -> None:
        """Remove JUST this tx (descendants re-anchor) — a 1-element
        stage."""
        self._remove_staged({txid})

    def prioritise(self, txid: bytes, fee_delta: int) -> None:
        """PrioritiseTransaction (txmempool.cpp:~800): accumulate a fee
        delta for txid and, if it is in the pool, push the delta through
        its own and its relatives' fee aggregates."""
        self.map_deltas[txid] = self.map_deltas.get(txid, 0) + fee_delta
        entry = self.entries.get(txid)
        if entry is None:
            return
        entry.fee += fee_delta
        entry.fees_with_ancestors += fee_delta
        entry.fees_with_descendants += fee_delta
        dirty = {txid}
        for a in self.calculate_ancestors(entry.tx):
            self.entries[a].fees_with_descendants += fee_delta
            dirty.add(a)
        for d in self.calculate_descendants_of_outputs(entry.tx):
            self.entries[d].fees_with_ancestors += fee_delta
            dirty.add(d)
        self.total_fee += fee_delta
        self.sequence += 1
        self._sync(dirty)

    def calculate_descendants_of_outputs(self, tx: CTransaction) -> set[bytes]:
        out: set[bytes] = set()
        for i in range(len(tx.vout)):
            spender = self.map_next_tx.get(COutPoint(tx.txid, i))
            if spender is not None:
                out |= self.calculate_descendants(spender)
        return out

    def remove_recursive(self, txid: bytes) -> list[bytes]:
        """removeRecursive: tx + all descendants. Returns removed txids."""
        return self._remove_staged(self.calculate_descendants(txid))

    def remove_for_block(self, block_txs: Iterable[CTransaction]) -> None:
        """removeForBlock: drop confirmed txs, then conflicts (anything
        spending an outpoint a block tx just spent). One staged removal
        for the whole block — the ancestor/descendant fixes amortize
        across the block's txs instead of re-walking per removal."""
        stage: set[bytes] = set()
        for tx in block_txs:
            # ClearPrioritisation: a confirmed tx's fee delta is spent
            # (coinbases included — their txids can carry stray deltas)
            self.map_deltas.pop(tx.txid, None)
            if tx.is_coinbase():
                continue
            if tx.txid in self.entries:
                # confirmed: remove JUST this tx (descendants re-anchor)
                stage.add(tx.txid)
            for txin in tx.vin:
                conflict = self.map_next_tx.get(txin.prevout)
                if (conflict is not None and conflict != tx.txid
                        and conflict not in stage):
                    stage |= self.calculate_descendants(conflict)
        self._remove_staged(stage)

    def expire(self, now: Optional[int] = None) -> int:
        """Expire (txmempool.cpp:~600): drop entries older than the expiry
        window, with their descendants. Batch mode finds the stale set
        with one vectorized cutoff scan over the time column."""
        now = now if now is not None else int(_time.time())
        cutoff = now - self.expiry_seconds
        if self.batch:
            stale = self.columns.stale_txids(cutoff)
        else:
            stale = [t for t, e in self.entries.items() if e.time < cutoff]
        stage: set[bytes] = set()
        for txid in stale:
            if txid not in stage:
                stage |= self.calculate_descendants(txid)
        return len(self._remove_staged(stage))

    # ------------------------------------------------------------------
    # eviction (TrimToSize)
    # ------------------------------------------------------------------

    def _worst_reference(self) -> bytes:
        """Per-tx oracle: the entry with the lowest descendant feerate
        (exact comparison, smaller txid on ties)."""
        worst = None
        for e in self.entries.values():
            if worst is None or _evict_worse(
                    e.fees_with_descendants, e.size_with_descendants,
                    e.txid, worst.fees_with_descendants,
                    worst.size_with_descendants, worst.txid):
                worst = e
        return worst.txid

    def _pop_worst_evict(self) -> bytes:
        """Pop the eviction frontier until a FRESH key surfaces (stored
        descendant aggregates still match the live entry)."""
        while self._evict_heap:
            _key, txid, f, s = heapq.heappop(self._evict_heap)
            e = self.entries.get(txid)
            if (e is None or e.fees_with_descendants != f
                    or e.size_with_descendants != s):
                self.perf["frontier_stale_pops"] += 1
                continue
            return txid
        # heap starved (only possible after external surgery): rebuild
        self._rebuild_frontiers()
        return self._pop_worst_evict()

    def trim_to_size(self, max_bytes: Optional[int] = None) -> list[bytes]:
        """TrimToSize: evict lowest descendant-score packages until the
        pool fits. Returns removed txids. Batched: victims come off the
        incrementally-maintained eviction frontier (amortized O(log n)
        per victim) instead of a full O(n) score scan per round; the
        surviving ancestors the staged removal dirties are re-pushed with
        fresh keys, so the next round's pop is already exact."""
        max_bytes = max_bytes if max_bytes is not None else self.max_size_bytes
        if self.total_size <= max_bytes:
            return []
        if not self.batch:
            return self._trim_reference(max_bytes)
        try:
            INJECTOR.on_call(MEMPOOL_SITE)
        except InjectedFault:
            self.perf["trim_fallbacks"] += 1
            return self._trim_reference(max_bytes)
        gate = self.selfcheck or (INJECTOR.mode == "poison-output"
                                  and INJECTOR.armed_for(MEMPOOL_SITE))
        poison = gate and INJECTOR.should_poison(MEMPOOL_SITE)
        removed: list[bytes] = []
        episode = False
        while self.total_size > max_bytes and self.entries:
            victim = self._pop_worst_evict()
            if gate:
                self.perf["selfchecks"] += 1
                checked = victim
                if poison and len(self.entries) > 1:
                    # corrupt the batched verdict: claim a different
                    # victim — the differential gate must catch it
                    checked = next(t for t in self.entries if t != victim)
                oracle = self._worst_reference()
                if checked != oracle:
                    self.perf["poisoned_verdicts"] += 1
                    log_printf(
                        "mempool: batched evict verdict poisoned/diverged "
                        "(got %s, oracle %s) — using the per-tx oracle",
                        checked.hex()[:16], oracle.hex()[:16])
                    if victim != oracle:
                        # the popped key belonged to a survivor — re-push
                        # it so the frontier stays complete
                        self._push_frontiers(self.entries[victim])
                    victim = oracle
            removed.extend(self._remove_staged(
                self.calculate_descendants(victim)))
            episode = True
        if episode:
            self.perf["bulk_evict_episodes"] += 1
            self.perf["bulk_evicted"] += len(removed)
        return removed

    def _trim_reference(self, max_bytes: int) -> list[bytes]:
        """The per-tx fallback: full worst-scan per eviction round."""
        removed: list[bytes] = []
        while self.total_size > max_bytes and self.entries:
            removed.extend(self._remove_staged(
                self.calculate_descendants(self._worst_reference())))
        return removed

    # ------------------------------------------------------------------
    # mining interface (BlockAssembler.addPackageTxs parity)
    # ------------------------------------------------------------------

    def _nonfinal_poison(self, height: int, block_time: int) -> set[bytes]:
        """IsFinalTx gate (addPackageTxs → TestBlockValidity parity): a
        non-final tx poisons its whole descendant subtree for this
        block."""
        skipped: set[bytes] = set()
        for txid, e in self.entries.items():
            if txid not in skipped and not is_final_tx(e.tx, height,
                                                       block_time):
                skipped |= self.calculate_descendants(txid)
        return skipped

    def select_for_block(self, max_size: int, height: int,
                         block_time: int) -> list[MempoolEntry]:
        """Greedy ancestor-feerate package selection — addPackageTxs
        (src/miner.cpp:~300): repeatedly take the entry with the best
        ancestor-package feerate, emit its not-yet-selected ancestors
        first (topological order), and account the whole package; skip
        packages that would overflow the block.

        Batched: candidates pop off the incrementally-maintained mining
        frontier; emitted packages re-score their remaining descendants
        through a local modified-package map (the reference's
        mapModifiedTx) with refreshed heap keys — no full rescan per
        round. Byte-identical to the per-tx reference path (the
        differential gate / -mempoolselfcheck asserts it live)."""
        if not self.batch:
            return self._select_reference(max_size, height, block_time)
        try:
            INJECTOR.on_call(MEMPOOL_SITE)
        except InjectedFault:
            self.perf["select_fallbacks"] += 1  # BCPLINT-IGNORE[BCP009]: caller holds cs_main through BlockAssembler (untyped mempool param hides the edge)
            return self._select_reference(max_size, height, block_time)
        self.perf["select_batched"] += 1
        selected = self._select_batched(max_size, height, block_time)
        gate = self.selfcheck or (INJECTOR.mode == "poison-output"
                                  and INJECTOR.armed_for(MEMPOOL_SITE))
        if gate:
            self.perf["selfchecks"] += 1
            checked = selected
            if INJECTOR.should_poison(MEMPOOL_SITE) and selected:
                checked = selected[:-1]  # corrupted batched verdict
            oracle = self._select_reference(max_size, height, block_time)
            if [e.txid for e in checked] != [e.txid for e in oracle]:
                self.perf["poisoned_verdicts"] += 1
                log_printf(
                    "mempool: batched template selection poisoned/"
                    "diverged (%d vs oracle %d txs) — using the per-tx "
                    "oracle", len(checked), len(oracle))
                return oracle
        return selected

    def _select_batched(self, max_size: int, height: int,
                        block_time: int) -> list[MempoolEntry]:
        selected: list[MempoolEntry] = []
        in_block: set[bytes] = set()
        used = 0
        skipped = self._nonfinal_poison(height, block_time)
        failed: set[bytes] = set()  # overflowed packages, final this block
        # local working copy of the global frontier (lazy keys included;
        # staleness is re-checked against mod/entry state at pop)
        heap = list(self._mine_heap)
        # mapModifiedTx: package aggregates minus what's already in the
        # block, for entries whose ancestors got emitted
        mod: dict[bytes, tuple[int, int]] = {}
        while heap:
            _key, txid, sf, ss = heapq.heappop(heap)
            e = self.entries.get(txid)
            if (e is None or txid in in_block or txid in skipped
                    or txid in failed):
                continue
            cur = mod.get(txid)
            if cur is None:
                cur = (e.fees_with_ancestors, e.size_with_ancestors)
            if (sf, ss) != cur:
                self.perf["frontier_stale_pops"] += 1  # BCPLINT-IGNORE[BCP009]: caller holds cs_main through BlockAssembler (untyped mempool param hides the edge)
                continue
            pkg_fees, pkg_size = cur
            if used + pkg_size > max_size:
                failed.add(txid)
                continue
            anc = [a for a in self.calculate_ancestors(e.tx)
                   if a not in in_block]
            # topological emit: parents before children (deterministic —
            # count ties break on txid, both paths)
            order = sorted(
                anc + [txid],
                key=lambda t: (self.entries[t].count_with_ancestors, t))
            for t in order:
                selected.append(self.entries[t])
                in_block.add(t)
            used += pkg_size
            # rescoring (mapModifiedTx): every not-in-block descendant of
            # an emitted tx loses that tx from its effective package
            for t in order:
                te = self.entries[t]
                for d in self.calculate_descendants(t):
                    if d in in_block:
                        continue
                    df, ds = mod.get(d) or (
                        self.entries[d].fees_with_ancestors,
                        self.entries[d].size_with_ancestors)
                    df -= te.fee
                    ds -= te.size
                    mod[d] = (df, ds)
                    heapq.heappush(heap, (-score_key(df, ds), d, df, ds))
        return selected

    def _select_reference(self, max_size: int, height: int,
                          block_time: int) -> list[MempoolEntry]:
        """The per-tx oracle: full package re-scan per selection round
        (the pre-batch greedy loop, now on the exact comparator)."""
        selected: list[MempoolEntry] = []
        in_block: set[bytes] = set()
        used = 0
        skipped = self._nonfinal_poison(height, block_time)
        while True:
            best: Optional[MempoolEntry] = None
            best_f = best_s = 0
            best_pkg: Optional[list[bytes]] = None
            for e in self.entries.values():
                if e.txid in in_block or e.txid in skipped:
                    continue
                anc = [
                    a for a in self.calculate_ancestors(e.tx)
                    if a not in in_block
                ]
                pkg_size = e.size + sum(self.entries[a].size for a in anc)
                pkg_fees = e.fee + sum(self.entries[a].fee for a in anc)
                if best is None or _pkg_better(pkg_fees, pkg_size, e.txid,
                                               best_f, best_s, best.txid):
                    best, best_f, best_s = e, pkg_fees, pkg_size
                    best_pkg = anc + [e.txid]
            if best is None:
                return selected
            if used + best_s > max_size:
                skipped.add(best.txid)
                continue
            # topological emit: parents before children (deterministic —
            # count ties break on txid, both paths)
            order = sorted(
                best_pkg,
                key=lambda t: (self.entries[t].count_with_ancestors, t))
            for txid in order:
                selected.append(self.entries[txid])
                in_block.add(txid)
            used += best_s

    def info(self) -> dict:
        """getmempoolinfo backend."""
        return {
            "size": len(self.entries),
            "bytes": self.total_size,
            "total_fee": self.total_fee,
            "maxmempool": self.max_size_bytes,
        }

    def perf_snapshot(self) -> dict:
        """gettpuinfo.mempool / getmempoolinfo.perf: the batch-shape
        counters — frontier depths, column-sync tallies, bulk-evict
        episodes, fallback/differential-gate verdicts."""
        out = {
            "batch": self.batch,
            "selfcheck": self.selfcheck,
            "frontier_depth": {"mining": len(self._mine_heap),
                               "evict": len(self._evict_heap)},
            "columns": (self.columns.snapshot() if self.batch
                        else {"capacity": 0, "live": 0, "grows": 0}),
        }
        out.update(self.perf)
        return out
