"""Base58Check — address / WIF codec.

Reference: src/base58.{h,cpp} (EncodeBase58Check, DecodeBase58Check,
CBitcoinAddress, CBitcoinSecret). Pure host-side; never hot.
"""

from __future__ import annotations

from typing import Optional

from .hashes import sha256d

_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_INDEX = {c: i for i, c in enumerate(_ALPHABET)}


def b58encode(data: bytes) -> str:
    """EncodeBase58 (src/base58.cpp:~15)."""
    n = int.from_bytes(data, "big")
    out = []
    while n:
        n, rem = divmod(n, 58)
        out.append(_ALPHABET[rem])
    # leading zero bytes -> leading '1's
    pad = 0
    for b in data:
        if b == 0:
            pad += 1
        else:
            break
    return "1" * pad + "".join(reversed(out))


def b58decode(s: str) -> Optional[bytes]:
    """DecodeBase58 — None on any non-alphabet char."""
    n = 0
    for c in s:
        v = _INDEX.get(c)
        if v is None:
            return None
        n = n * 58 + v
    body = n.to_bytes((n.bit_length() + 7) // 8, "big") if n else b""
    pad = 0
    for c in s:
        if c == "1":
            pad += 1
        else:
            break
    return b"\x00" * pad + body


def b58check_encode(payload: bytes) -> str:
    """EncodeBase58Check: payload + 4-byte sha256d checksum."""
    return b58encode(payload + sha256d(payload)[:4])


def b58check_decode(s: str) -> Optional[bytes]:
    """DecodeBase58Check — None on bad charset or checksum."""
    raw = b58decode(s)
    if raw is None or len(raw) < 4:
        return None
    payload, checksum = raw[:-4], raw[-4:]
    if sha256d(payload)[:4] != checksum:
        return None
    return payload
