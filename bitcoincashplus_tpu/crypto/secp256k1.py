"""secp256k1 — CPU reference implementation (Python ints).

Reference: src/secp256k1/ (secp256k1_ecdsa_verify at src/secp256k1.c:~340,
secp256k1_ecmult at ecmult_impl.h, group law in group_impl.h, RFC6979
nonces in secp256k1_nonce_function_rfc6979). This module is:
  (a) the correctness oracle for the TPU batch kernel (ops/secp256k1.py),
  (b) the scalar fallback path for non-batchable checks,
  (c) the wallet's signer.

Python ints make the field/scalar arithmetic exact and readable; this path
is never the block-validation hot loop (that's the TPU batch).
"""

from __future__ import annotations

import hashlib
import hmac
from typing import Optional

# Curve: y^2 = x^3 + 7 over F_p
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
B = 7

# Affine points as (x, y) tuples; None is the point at infinity.
G = (GX, GY)


def is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - B) % P == 0


def point_add(p1, p2):
    """Affine group law (group_impl.h secp256k1_gej_add_var semantics)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None  # inverses
        return point_double(p1)
    lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def point_double(pt):
    if pt is None:
        return None
    x, y = pt
    if y == 0:
        return None
    lam = 3 * x * x * pow(2 * y, P - 2, P) % P
    x3 = (lam * lam - 2 * x) % P
    return (x3, (lam * (x - x3) - y) % P)


def point_mul(k: int, pt):
    """Double-and-add (the constant-time wNAF machinery of ecmult_impl.h is
    irrelevant off the hot path; verification needs no side-channel armor)."""
    k %= N
    result = None
    addend = pt
    while k:
        if k & 1:
            result = point_add(result, addend)
        addend = point_double(addend)
        k >>= 1
    return result


def point_neg(pt):
    if pt is None:
        return None
    x, y = pt
    return (x, (-y) % P)


# ---- key / pubkey codecs (src/pubkey.cpp CPubKey) ----

def pubkey_serialize(pt, compressed: bool = True) -> bytes:
    x, y = pt
    if compressed:
        return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")
    return b"\x04" + x.to_bytes(32, "big") + y.to_bytes(32, "big")


def pubkey_parse(data: bytes) -> Optional[tuple]:
    """CPubKey decompression — secp256k1_ec_pubkey_parse. Returns None for
    anything malformed or off-curve."""
    if len(data) == 33 and data[0] in (2, 3):
        x = int.from_bytes(data[1:], "big")
        if x >= P:
            return None
        y2 = (x * x * x + B) % P
        y = pow(y2, (P + 1) // 4, P)
        if y * y % P != y2:
            return None
        if (y & 1) != (data[0] & 1):
            y = P - y
        return (x, y)
    if len(data) == 65 and data[0] in (4, 6, 7):
        x = int.from_bytes(data[1:33], "big")
        y = int.from_bytes(data[33:], "big")
        if x >= P or y >= P:
            return None
        # hybrid forms (6/7) must have matching parity
        if data[0] in (6, 7) and (y & 1) != (data[0] & 1):
            return None
        pt = (x, y)
        return pt if is_on_curve(pt) else None
    return None


def privkey_to_pubkey(secret: int, compressed: bool = True) -> bytes:
    return pubkey_serialize(point_mul(secret, G), compressed)


# ---- ECDSA (secp256k1.c secp256k1_ecdsa_verify / _sign) ----

def ecdsa_verify(pubkey, r: int, s: int, e: int) -> bool:
    """Raw ECDSA verify: pubkey affine point, (r, s) signature scalars,
    e = message hash as integer. Matches secp256k1_ecdsa_sig_verify
    (ecdsa_impl.h): accepts any s in [1, n-1] (low-s policy is enforced at
    the script layer, not here — like the reference library)."""
    if pubkey is None or not (1 <= r < N) or not (1 <= s < N):
        return False
    w = pow(s, N - 2, N)
    u1 = e * w % N
    u2 = r * w % N
    pt = point_add(point_mul(u1, G), point_mul(u2, pubkey))
    if pt is None:
        return False
    # r == x_R mod n (x_R in [0, p); the x_R >= n wraparound folds in here)
    return (pt[0] - r) % N == 0


def ecdsa_sign(secret: int, e: int, nonce: Optional[int] = None) -> tuple[int, int]:
    """Returns (r, s) with low-s normalization (the reference signer's
    secp256k1_ecdsa_sig_sign + secp256k1_scalar_cond_negate)."""
    if nonce is None:
        nonce = rfc6979_nonce(secret, e)
    k = nonce
    pt = point_mul(k, G)
    r = pt[0] % N
    assert r != 0
    s = pow(k, N - 2, N) * (e + r * secret) % N
    assert s != 0
    if s > N // 2:
        s = N - s
    return r, s


def rfc6979_nonce(secret: int, e: int, extra: bytes = b"") -> int:
    """RFC6979 deterministic nonce (secp256k1_nonce_function_rfc6979),
    HMAC-SHA256 variant, as the reference library uses."""
    x = secret.to_bytes(32, "big")
    msg = (e % (1 << 256)).to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x + msg + extra, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + msg + extra, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        candidate = int.from_bytes(v, "big")
        if 1 <= candidate < N:
            return candidate
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


# ---- Recoverable ECDSA (secp256k1 recovery module:
# secp256k1_ecdsa_sign_recoverable / secp256k1_ecdsa_recover) ----

def ecdsa_sign_recoverable(secret: int, e: int) -> tuple[int, int, int]:
    """Returns (r, s, recid) with low-s normalization. recid bit 0 is the
    parity of R.y (flipped when s is negated), bit 1 flags R.x >= n
    (secp256k1_ecdsa_sig_sign's recid computation)."""
    k = rfc6979_nonce(secret, e)
    pt = point_mul(k, G)
    x, y = pt
    r = x % N
    assert r != 0
    recid = (2 if x >= N else 0) | (y & 1)
    s = pow(k, N - 2, N) * (e + r * secret) % N
    assert s != 0
    if s > N // 2:
        s = N - s
        recid ^= 1
    return r, s, recid


def ecdsa_recover(r: int, s: int, recid: int, e: int):
    """Recover the signing pubkey point, or None (secp256k1_ecdsa_recover:
    Q = r^-1 (s·R − e·G) with R reconstructed from r/recid)."""
    if not (1 <= r < N) or not (1 <= s < N) or not (0 <= recid <= 3):
        return None
    x = r + (N if recid & 2 else 0)
    if x >= P:
        return None
    y2 = (x * x * x + B) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        return None
    if (y & 1) != (recid & 1):
        y = P - y
    r_inv = pow(r, N - 2, N)
    q = point_add(point_mul(s * r_inv % N, (x, y)),
                  point_mul(-e * r_inv % N, G))
    return q


# ---- BCH Schnorr (2019-05 upgrade, spec 2019-05-15-schnorr.md) ----
#
# 64-byte (r || s) signatures over the SAME sighash digests as ECDSA,
# discriminated from DER by length at the script layer. Verification:
#   e = SHA256(r32 || ser_compressed(P) || m32) mod n
#   R = s·G + (n − e)·P;  accept iff R finite, jacobi(R.y) = 1, R.x = r
# This is the BCH rule set, NOT BIP340: the y-coordinate gate is the
# Jacobi symbol (not even-y), r is a full field element (no x-only
# pubkeys), and the challenge commits to the 33-byte COMPRESSED pubkey
# serialization regardless of how the key appeared on the stack.
# Schnorr is what makes TRUE batch verification possible (the batch MSM
# check in ops/secp256k1.py): unlike ECDSA, the verifier learns R itself
# (lifted from r), so N verifies collapse into one random-linear-
# combination multi-scalar multiplication.

def jacobi(a: int) -> int:
    """Jacobi symbol (a | p) via Euler's criterion (p prime): 1 for a
    quadratic residue, p − 1 (≡ −1) for a non-residue, 0 for 0."""
    return pow(a, (P - 1) // 2, P)


def schnorr_challenge(r: int, pubkey, msg_hash: int) -> int:
    """e = SHA256(r || ser(P) || m) mod n — the challenge scalar. Binds
    the compressed pubkey form so the same (r, s) can never be replayed
    against a different key encoding."""
    h = hashlib.sha256(
        r.to_bytes(32, "big")
        + pubkey_serialize(pubkey, compressed=True)
        + (msg_hash % (1 << 256)).to_bytes(32, "big")
    ).digest()
    return int.from_bytes(h, "big") % N


def schnorr_lift_x(r: int):
    """The affine point (r, y) with jacobi(y) = 1, or None when r³ + 7 is
    a non-residue (no such point exists, so no signature with this r can
    ever verify — the batch layer pre-rejects those host-side). p ≡ 3
    (mod 4), so the residue root is v^((p+1)/4); exactly one of {y, p−y}
    has Jacobi symbol 1 (p ≡ 3 mod 4 makes −1 a non-residue)."""
    if not (0 <= r < P):
        return None
    y2 = (r * r * r + B) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        return None
    if jacobi(y) != 1:
        y = P - y
    return (r, y)


def schnorr_verify(pubkey, r: int, s: int, msg_hash: int) -> bool:
    """BCH Schnorr verify. Range rules per the spec: fail if r >= p or
    s >= n (r/s = 0 are in-range but can only verify if the algebra
    happens to — no special-case)."""
    if pubkey is None or not (0 <= r < P) or not (0 <= s < N):
        return False
    e = schnorr_challenge(r, pubkey, msg_hash)
    R = point_add(point_mul(s, G), point_mul(N - e, pubkey))
    if R is None:
        return False
    if jacobi(R[1]) != 1:
        return False
    return R[0] == r


def schnorr_sign(secret: int, msg_hash: int) -> tuple[int, int]:
    """Deterministic BCH Schnorr signer: RFC6979 nonce with the spec's
    "Schnorr+SHA256" additional data (verification never sees the nonce
    scheme, so any deterministic derivation interoperates). k is negated
    when jacobi(R.y) != 1 so the verifier's Jacobi gate holds; r is R.x
    as a FULL field element (may exceed n, unlike ECDSA's r)."""
    assert 1 <= secret < N
    k = rfc6979_nonce(secret, msg_hash, extra=b"Schnorr+SHA256  ")
    Rp = point_mul(k, G)
    if jacobi(Rp[1]) != 1:
        k = N - k
    r = Rp[0]
    e = schnorr_challenge(r, point_mul(secret, G), msg_hash)
    s = (k + e * secret) % N
    return r, s


# ---- DER (src/pubkey.cpp CPubKey::CheckLowS / ecdsa_signature_parse_der_lax) ----

def sig_der_encode(r: int, s: int) -> bytes:
    def enc_int(v: int) -> bytes:
        b = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
        if b[0] & 0x80:
            b = b"\x00" + b
        return b"\x02" + bytes([len(b)]) + b

    body = enc_int(r) + enc_int(s)
    return b"\x30" + bytes([len(body)]) + body


def _lax_len(sig: bytes, pos: int) -> Optional[tuple[int, int]]:
    """One BER length field at ``pos``: returns (length, new_pos) or None.
    Multi-byte (0x80-flagged) lengths are decoded after skipping leading
    zero bytes, exactly as ecdsa_signature_parse_der_lax does."""
    if pos >= len(sig):
        return None
    lenbyte = sig[pos]
    pos += 1
    if not lenbyte & 0x80:
        return lenbyte, pos
    lenbyte &= 0x7F
    if lenbyte > len(sig) - pos:
        return None
    while lenbyte > 0 and sig[pos] == 0:
        pos += 1
        lenbyte -= 1
    if lenbyte >= 8:  # sizeof(size_t) guard in the reference
        return None
    out = 0
    while lenbyte > 0:
        out = (out << 8) + sig[pos]
        pos += 1
        lenbyte -= 1
    return out, pos


def sig_der_decode(sig: bytes) -> Optional[tuple[int, int]]:
    """Permissive BER-ish parse mirroring ecdsa_signature_parse_der_lax
    (src/pubkey.cpp — the consensus behavior pre-BIP66 strictness; strict
    DER enforcement is a script-flag check done on the raw bytes, not here).

    Parity-critical details: an R/S length that overclaims the remaining
    input REJECTS (reference nodes fail the parse, so accepting it here
    would be a chain-split vector); an integer wider than 32 bytes after
    stripping leading zeros "overflows" and yields (0, 0) — a parse
    success whose verify then fails, matching the reference exactly."""
    if len(sig) < 2 or sig[0] != 0x30:
        return None
    got = _lax_len(sig, 1)
    if got is None:
        return None
    _seq_len, pos = got  # sequence length value is ignored (lax), bounds aren't

    def int_at(pos: int) -> Optional[tuple[int, int]]:
        if pos >= len(sig) or sig[pos] != 0x02:
            return None
        got = _lax_len(sig, pos + 1)
        if got is None:
            return None
        vlen, vpos = got
        if vlen > len(sig) - vpos:
            return None  # length exceeds input: reject, don't truncate
        start, end = vpos, vpos + vlen
        while start < end and sig[start] == 0:
            start += 1
        if end - start > 32:
            return -1, end  # overflow marker
        return int.from_bytes(sig[start:end], "big"), end

    got = int_at(pos)
    if got is None:
        return None
    r, pos = got
    got = int_at(pos)
    if got is None:
        return None
    s, _pos = got
    if r < 0 or s < 0:  # overflow: reference zeroes the whole signature
        return (0, 0)
    return (r, s)
