"""SipHash-2-4 — the BIP152 short-transaction-ID hash.

Reference: src/crypto/siphash.cpp (CSipHasher, SipHashUint256Extra). Pure
host-side (tiny keyed hash over 32-byte txids); nothing to accelerate.
"""

from __future__ import annotations

import struct

_MASK = 0xFFFFFFFFFFFFFFFF


def _rotl(x: int, b: int) -> int:
    return ((x << b) | (x >> (64 - b))) & _MASK


def siphash24(k0: int, k1: int, data: bytes) -> int:
    """Standard SipHash-2-4 of ``data`` under key (k0, k1) → u64."""
    v0 = 0x736F6D6570736575 ^ k0
    v1 = 0x646F72616E646F6D ^ k1
    v2 = 0x6C7967656E657261 ^ k0
    v3 = 0x7465646279746573 ^ k1

    def rounds(n: int) -> None:
        nonlocal v0, v1, v2, v3
        for _ in range(n):
            v0 = (v0 + v1) & _MASK
            v1 = _rotl(v1, 13) ^ v0
            v0 = _rotl(v0, 32)
            v2 = (v2 + v3) & _MASK
            v3 = _rotl(v3, 16) ^ v2
            v0 = (v0 + v3) & _MASK
            v3 = _rotl(v3, 21) ^ v0
            v2 = (v2 + v1) & _MASK
            v1 = _rotl(v1, 17) ^ v2
            v2 = _rotl(v2, 32)

    n_blocks = len(data) // 8
    for i in range(n_blocks):
        (m,) = struct.unpack_from("<Q", data, i * 8)
        v3 ^= m
        rounds(2)
        v0 ^= m
    # final block: remaining bytes + length in the top byte
    tail = data[n_blocks * 8:]
    b = (len(data) & 0xFF) << 56
    for i, byte in enumerate(tail):
        b |= byte << (8 * i)
    v3 ^= b
    rounds(2)
    v0 ^= b
    v2 ^= 0xFF
    rounds(4)
    return (v0 ^ v1 ^ v2 ^ v3) & _MASK
