"""CPU crypto reference paths (the TPU kernels are differential-tested against these)."""
