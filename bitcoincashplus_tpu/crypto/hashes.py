"""CPU hash primitives.

Reference: src/hash.h:~22 (CHash256 = double-SHA256), src/crypto/sha256.cpp
(CSHA256), src/crypto/ripemd160.cpp, src/crypto/hmac_sha512.cpp. Here the CPU
path delegates to OpenSSL via hashlib (the TPU path in ops/sha256.py is
the performance path; this is the correctness oracle and small-input path).

Also exposes the SHA-256 midstate utilities the mining kernel needs: the
80-byte header's first 64 bytes are constant across a nonce sweep, so the
compression state after block 0 ("midstate") is computed once per template
(SURVEY.md §4.5 kernel-critical structure).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import struct

# SHA-256 initial state (FIPS 180-4) — shared with ops/sha256.py.
SHA256_INIT = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)

SHA256_K = (
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
)

_M32 = 0xFFFFFFFF


def sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def sha256d(b: bytes) -> bytes:
    """Double SHA-256 — CHash256 (src/hash.h:~22). Block/tx/checksum hash."""
    return hashlib.sha256(hashlib.sha256(b).digest()).digest()


def ripemd160(b: bytes) -> bytes:
    return hashlib.new("ripemd160", b).digest()


def hash160(b: bytes) -> bytes:
    """RIPEMD160(SHA256(x)) — CHash160 (src/hash.h:~40). Addresses."""
    return ripemd160(sha256(b))


def hmac_sha512(key: bytes, msg: bytes) -> bytes:
    """BIP32 key derivation MAC (src/crypto/hmac_sha512.cpp)."""
    return _hmac.new(key, msg, hashlib.sha512).digest()


# ---- pure-Python SHA-256 compression (midstate support) ----

def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & _M32


def sha256_compress(state: tuple, block: bytes) -> tuple:
    """One 64-byte compression round — CSHA256::Transform
    (src/crypto/sha256.cpp:~40). Pure Python: used only for midstates and as
    the oracle for the Pallas kernel; bulk hashing goes through hashlib or TPU.
    """
    assert len(block) == 64
    w = list(struct.unpack(">16I", block))
    for i in range(16, 64):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ (w[i - 15] >> 3)
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ (w[i - 2] >> 10)
        w.append((w[i - 16] + s0 + w[i - 7] + s1) & _M32)
    a, b, c, d, e, f, g, h = state
    for i in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = (h + s1 + ch + SHA256_K[i] + w[i]) & _M32
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (s0 + maj) & _M32
        h, g, f, e, d, c, b, a = g, f, e, (d + t1) & _M32, c, b, a, (t1 + t2) & _M32
    return tuple((x + y) & _M32 for x, y in zip(state, (a, b, c, d, e, f, g, h)))


def header_midstate(header80: bytes) -> tuple:
    """SHA-256 state after compressing the first 64 of the 80 header bytes.

    Constant across a nonce sweep (nonce lives at bytes 76..79, in block 1) —
    the key PoW optimization (SURVEY.md §4.5).
    """
    assert len(header80) == 80
    return sha256_compress(SHA256_INIT, header80[:64])


def chunk2_round_state(midstate: tuple, tail12: bytes, rounds: int = 3) -> tuple:
    """Compression state after the first ``rounds`` rounds of the header's
    SECOND block (bytes 64..79 + padding), consuming only the
    nonce-independent words w0..w2 (merkle tail, nTime, nBits) — so
    ``rounds`` must be <= 3 (the nonce is w3).

    This is the CPU twin of the sweep kernel's per-template chunk-2 hoist
    (ops/sha256_sweep.hoist_template): the device precompute's early-round
    state is pinned bit-exactly against this oracle by the mining tests.
    """
    assert len(tail12) == 12 and 0 <= rounds <= 3
    w = struct.unpack(">3I", tail12)
    a, b, c, d, e, f, g, h = midstate
    for i in range(rounds):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = (h + s1 + ch + SHA256_K[i] + w[i]) & _M32
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (s0 + maj) & _M32
        h, g, f, e, d, c, b, a = g, f, e, (d + t1) & _M32, c, b, a, (t1 + t2) & _M32
    return (a, b, c, d, e, f, g, h)


def sha256d_from_midstate(midstate: tuple, tail16: bytes) -> bytes:
    """Finish SHA-256d of an 80-byte header given the block-0 midstate and the
    final 16 header bytes (merkle tail + time + bits + nonce)."""
    assert len(tail16) == 16
    # block 1: 16 bytes of message + 0x80 pad + zeros + 64-bit length (640 bits)
    block1 = tail16 + b"\x80" + b"\x00" * 39 + struct.pack(">Q", 80 * 8)
    h1 = sha256_compress(midstate, block1)
    digest1 = struct.pack(">8I", *h1)
    # second hash: 32-byte message, single padded block
    block2 = digest1 + b"\x80" + b"\x00" * 23 + struct.pack(">Q", 32 * 8)
    h2 = sha256_compress(SHA256_INIT, block2)
    return struct.pack(">8I", *h2)
