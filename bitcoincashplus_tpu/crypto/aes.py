"""AES-256-CBC — the src/crypto/ctaes + src/crypto/aes.{h,cpp} equivalent.

The reference vendors ctaes (a constant-time bitsliced C implementation)
solely for wallet encryption (src/wallet/crypter.cpp). Python's stdlib has
no AES and this environment installs nothing, so this is a small table-based
FIPS-197 implementation. Wallet encryption is not a consensus or hot path —
it runs a handful of times per unlock — so clarity beats constant-time here
(the host Python runtime leaks timing everywhere regardless; the threat
model for wallet files is offline theft, where timing is moot).

Tested against the FIPS-197 / NIST SP 800-38A known-answer vectors in
tests/unit/test_aes.py.
"""

from __future__ import annotations

# -- tables -------------------------------------------------------------------

_SBOX = bytes.fromhex(
    "637c777bf26b6fc53001672bfed7ab76ca82c97dfa5947f0add4a2af9ca472c0"
    "b7fd9326363ff7cc34a5e5f171d8311504c723c31896059a071280e2eb27b275"
    "09832c1a1b6e5aa0523bd6b329e32f8453d100ed20fcb15b6acbbe394a4c58cf"
    "d0efaafb434d338545f9027f503c9fa851a3408f929d38f5bcb6da2110fff3d2"
    "cd0c13ec5f974417c4a77e3d645d197360814fdc222a908846eeb814de5e0bdb"
    "e0323a0a4906245cc2d3ac629195e479e7c8376d8dd54ea96c56f4ea657aae08"
    "ba78252e1ca6b4c6e8dd741f4bbd8b8a703eb5664803f60e613557b986c11d9e"
    "e1f8981169d98e949b1e87e9ce5528df8ca1890dbfe6426841992d0fb054bb16"
)
_INV_SBOX = bytearray(256)
for i, v in enumerate(_SBOX):
    _INV_SBOX[v] = i
_INV_SBOX = bytes(_INV_SBOX)

_RCON = (0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36,
         0x6C, 0xD8, 0xAB, 0x4D)


def _xtime(a: int) -> int:
    a <<= 1
    if a & 0x100:
        a ^= 0x11B
    return a & 0xFF


_MUL = [[0] * 256 for _ in range(16)]
for x in range(256):
    _MUL[1][x] = x
    _MUL[2][x] = _xtime(x)
    _MUL[3][x] = _MUL[2][x] ^ x
for x in range(256):
    _MUL[9][x] = _MUL[2][_MUL[2][_MUL[2][x]]] ^ x
    _MUL[11][x] = _MUL[2][_MUL[2][_MUL[2][x]] ^ x] ^ x
    _MUL[13][x] = _MUL[2][_MUL[2][_MUL[2][x] ^ x]] ^ x
    _MUL[14][x] = _MUL[2][_MUL[2][_MUL[2][x] ^ x] ^ x]


def _expand_key(key: bytes) -> list[bytes]:
    """Key schedule -> list of 16-byte round keys (15 for AES-256)."""
    assert len(key) == 32
    nk, rounds = 8, 14
    words = [key[4 * i:4 * i + 4] for i in range(nk)]
    for i in range(nk, 4 * (rounds + 1)):
        t = words[i - 1]
        if i % nk == 0:
            t = bytes(_SBOX[b] for b in t[1:] + t[:1])
            t = bytes([t[0] ^ _RCON[i // nk - 1], t[1], t[2], t[3]])
        elif i % nk == 4:
            t = bytes(_SBOX[b] for b in t)
        words.append(bytes(a ^ b for a, b in zip(words[i - nk], t)))
    return [b"".join(words[4 * r:4 * r + 4]) for r in range(rounds + 1)]


def _encrypt_block(block: bytes, rks: list[bytes]) -> bytes:
    s = bytearray(a ^ b for a, b in zip(block, rks[0]))
    for rnd in range(1, len(rks)):
        # SubBytes + ShiftRows (column-major state, row r shifts left by r)
        t = bytearray(16)
        for c in range(4):
            for r in range(4):
                t[4 * c + r] = _SBOX[s[4 * ((c + r) % 4) + r]]
        s = t
        if rnd != len(rks) - 1:  # MixColumns
            m = bytearray(16)
            for c in range(4):
                col = s[4 * c:4 * c + 4]
                m[4 * c + 0] = _MUL[2][col[0]] ^ _MUL[3][col[1]] ^ col[2] ^ col[3]
                m[4 * c + 1] = col[0] ^ _MUL[2][col[1]] ^ _MUL[3][col[2]] ^ col[3]
                m[4 * c + 2] = col[0] ^ col[1] ^ _MUL[2][col[2]] ^ _MUL[3][col[3]]
                m[4 * c + 3] = _MUL[3][col[0]] ^ col[1] ^ col[2] ^ _MUL[2][col[3]]
            s = m
        s = bytearray(a ^ b for a, b in zip(s, rks[rnd]))
    return bytes(s)


def _decrypt_block(block: bytes, rks: list[bytes]) -> bytes:
    s = bytearray(a ^ b for a, b in zip(block, rks[-1]))
    for rnd in range(len(rks) - 2, -1, -1):
        # InvShiftRows + InvSubBytes
        t = bytearray(16)
        for c in range(4):
            for r in range(4):
                t[4 * ((c + r) % 4) + r] = _INV_SBOX[s[4 * c + r]]
        s = t
        s = bytearray(a ^ b for a, b in zip(s, rks[rnd]))
        if rnd != 0:  # InvMixColumns
            m = bytearray(16)
            for c in range(4):
                col = s[4 * c:4 * c + 4]
                m[4 * c + 0] = _MUL[14][col[0]] ^ _MUL[11][col[1]] ^ _MUL[13][col[2]] ^ _MUL[9][col[3]]
                m[4 * c + 1] = _MUL[9][col[0]] ^ _MUL[14][col[1]] ^ _MUL[11][col[2]] ^ _MUL[13][col[3]]
                m[4 * c + 2] = _MUL[13][col[0]] ^ _MUL[9][col[1]] ^ _MUL[14][col[2]] ^ _MUL[11][col[3]]
                m[4 * c + 3] = _MUL[11][col[0]] ^ _MUL[13][col[1]] ^ _MUL[9][col[2]] ^ _MUL[14][col[3]]
            s = m
    return bytes(s)


# -- public API (mirrors AES256CBCEncrypt/Decrypt, src/crypto/aes.h) ----------

def aes256_cbc_encrypt(key: bytes, iv: bytes, data: bytes,
                       pad: bool = True) -> bytes:
    """AES256CBCEncrypt: PKCS7-padded CBC encryption."""
    assert len(key) == 32 and len(iv) == 16
    if pad:
        n = 16 - len(data) % 16
        data = data + bytes([n]) * n
    elif len(data) % 16:
        raise ValueError("unpadded data must be block-aligned")
    rks = _expand_key(key)
    out = bytearray()
    prev = iv
    for i in range(0, len(data), 16):
        block = bytes(a ^ b for a, b in zip(data[i:i + 16], prev))
        prev = _encrypt_block(block, rks)
        out += prev
    return bytes(out)


def aes256_cbc_decrypt(key: bytes, iv: bytes, data: bytes,
                       pad: bool = True) -> bytes:
    """AES256CBCDecrypt; raises ValueError on bad padding (the reference
    returns 0 length — callers treat both as 'wrong passphrase')."""
    assert len(key) == 32 and len(iv) == 16
    if len(data) % 16 or not data:
        raise ValueError("ciphertext not block-aligned")
    rks = _expand_key(key)
    out = bytearray()
    prev = iv
    for i in range(0, len(data), 16):
        block = data[i:i + 16]
        out += bytes(a ^ b for a, b in zip(_decrypt_block(block, rks), prev))
        prev = block
    if pad:
        n = out[-1]
        if not 1 <= n <= 16 or out[-n:] != bytes([n]) * n:
            raise ValueError("bad padding")
        del out[-n:]
    return bytes(out)
