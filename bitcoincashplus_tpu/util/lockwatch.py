"""Runtime lock-order sentinel (a Python TSan-lite).

Env-gated: when ``BCP_LOCKWATCH=1`` the :func:`watched_lock` /
:func:`watched_rlock` / :func:`watched_condition` factories return
instrumented wrappers in place of the plain ``threading`` primitives at
the node's real lock sites (``cs_main``, sigcache, banlist, SigService,
per-shard store write locks). Each wrapper reports every *first-hold*
acquisition to the process-global :data:`MONITOR`, which keeps a
per-thread stack of currently-held locks and folds each acquisition into
a directed lock-order graph: an edge ``A -> B`` means some thread
acquired ``B`` while holding ``A``. A cycle in that graph is a latent
deadlock — two code paths that take the same locks in opposite orders —
even if the schedules never actually collided during the run (the same
happens-before generalization TSan applies to data races).

When the gate is off the factories return the plain primitive: zero
wrapper frames, zero bookkeeping, nothing to reason about in production.

Findings surface three ways: the :func:`snapshot` feed behind
``gettpuinfo``'s ``lockwatch`` section, the node's ``lockwatch``
telemetry collector, and an atexit report on stderr (tier-1 functional
nodes run with the gate on, so an inversion introduced by a patch fails
the suite loudly instead of waiting for the unlucky schedule).

Static extraction of the same ordering lives in bcplint's BCP004; this
module is the runtime half that sees through indirection the AST can't.
"""

from __future__ import annotations

import atexit
import os
import sys
import threading

_ENV_GATE = "BCP_LOCKWATCH"


def enabled() -> bool:
    """True when the sentinel gate is set (checked per factory call, so a
    test can flip the env var before constructing a node)."""
    return os.environ.get(_ENV_GATE, "") not in ("", "0")


class _ThreadState(threading.local):
    def __init__(self):
        self.stack = []   # lock names, first-hold acquisition order
        self.counts = {}  # name -> recursion depth (RLock re-entry)


class LockMonitor:
    """Process-global acquisition-order graph.

    Edges are recorded on the *first* hold of a lock by a thread
    (re-entrant RLock acquires add depth, never edges, so ``cs_main``
    recursion cannot self-cycle). Release order is free to differ from
    acquisition order — the stack is a held-set with stable insertion
    order, not a strict LIFO.
    """

    def __init__(self):
        self._mu = threading.Lock()  # guards the shared graph/counters
        self._tls = _ThreadState()
        self.names: set[str] = set()
        self.acquisitions: dict[str, int] = {}
        self.max_depth = 0
        # (held, acquired) -> times observed; first-seen code site kept
        # separately so the cycle report can say WHERE each leg happened
        self.edges: dict[tuple[str, str], int] = {}
        self.edge_sites: dict[tuple[str, str], str] = {}
        # GUARDED_BY vocabulary: lock name -> sorted fields declared
        # guarded by it (bcplint BCP009's annotation convention), so the
        # runtime snapshot and the static concurrency report agree on
        # which locks are annotation-declared vs merely inferred
        self.declared_guards: dict[str, list[str]] = {}

    def declare_guards(self, lock_name: str, fields) -> None:
        with self._mu:
            cur = set(self.declared_guards.get(lock_name, ()))
            cur.update(fields)
            self.declared_guards[lock_name] = sorted(cur)

    # -- registration ---------------------------------------------------

    def register(self, name: str) -> None:
        with self._mu:
            self.names.add(name)
            self.acquisitions.setdefault(name, 0)

    # -- acquisition bookkeeping (called by WatchedLock only) -----------

    @staticmethod
    def _call_site() -> str:
        # first frame outside this module = the real acquire site
        f = sys._getframe(2)
        here = __file__
        while f is not None and f.f_code.co_filename == here:
            f = f.f_back
        if f is None:
            return "?"
        return "%s:%d" % (os.path.basename(f.f_code.co_filename), f.f_lineno)

    def on_acquire(self, name: str) -> None:
        st = self._tls
        if st.counts.get(name, 0):
            st.counts[name] += 1  # re-entrant: depth only, no edges
            return
        # resolve the code site before taking _mu, and only when this
        # acquisition can mint edges (a held stack exists)
        site = self._call_site() if st.stack else None
        held = tuple(st.stack)
        st.stack.append(name)
        st.counts[name] = 1
        with self._mu:
            self.acquisitions[name] = self.acquisitions.get(name, 0) + 1
            if len(st.stack) > self.max_depth:
                self.max_depth = len(st.stack)
            for h in held:
                if h == name:
                    continue
                key = (h, name)
                if key not in self.edges:
                    self.edges[key] = 0
                    self.edge_sites[key] = site or "?"
                self.edges[key] += 1

    def on_release(self, name: str) -> None:
        st = self._tls
        n = st.counts.get(name, 0)
        if n > 1:
            st.counts[name] = n - 1
            return
        if n == 1:
            del st.counts[name]
            st.stack.remove(name)

    def on_release_all(self, name: str) -> int:
        """Condition.wait() path: drop every recursion level at once.
        Returns the depth so the restore can reinstate it."""
        st = self._tls
        n = st.counts.pop(name, 0)
        if n:
            st.stack.remove(name)
        return n

    def on_acquire_restore(self, name: str, depth: int) -> None:
        self.on_acquire(name)
        self._tls.counts[name] = max(depth, 1)

    # -- reporting ------------------------------------------------------

    def cycles(self) -> list[dict]:
        """Strongly-connected components of the order graph with more
        than one lock (or a self-loop): each is a lock-order inversion.
        Returns ``[{"locks": [...], "edges": {"a->b": "file:line"}}]``."""
        with self._mu:
            edges = dict(self.edges)
            sites = dict(self.edge_sites)
        adj: dict[str, list[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        # iterative Tarjan SCC
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        for root in adj:
            if root in index:
                continue
            work = [(root, 0)]
            while work:
                node, i = work.pop()
                if i == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                succs = adj[node]
                while i < len(succs):
                    w = succs[i]
                    i += 1
                    if w not in index:
                        work.append((node, i))
                        work.append((w, 0))
                        recurse = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if recurse:
                    continue
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    sccs.append(scc)
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])

        out = []
        for scc in sccs:
            members = set(scc)
            if len(scc) < 2 and not any((n, n) in edges for n in scc):
                continue
            cyc_edges = {
                "%s->%s" % (a, b): sites[(a, b)]
                for (a, b) in edges
                if a in members and b in members
            }
            out.append({"locks": sorted(members), "edges": cyc_edges})
        return out

    def snapshot(self) -> dict:
        cycles = self.cycles()
        with self._mu:
            acq = dict(self.acquisitions)
            return {
                "enabled": True,
                "locks": sorted(self.names),
                "acquisitions": acq,
                "acquisitions_total": sum(acq.values()),
                "max_depth": self.max_depth,
                "order_edges": {
                    "%s->%s" % k: n for k, n in sorted(self.edges.items())
                },
                "inversions": len(cycles),
                "cycles": cycles,
                "declared_guards": {
                    k: list(v)
                    for k, v in sorted(self.declared_guards.items())
                },
            }

    def reset(self) -> None:
        """Test hook: drop the graph (thread-local stacks of live threads
        are left alone — callers reset between quiescent phases)."""
        with self._mu:
            self.names.clear()
            self.acquisitions.clear()
            self.edges.clear()
            self.edge_sites.clear()
            self.declared_guards.clear()
            self.max_depth = 0


MONITOR = LockMonitor()


class WatchedLock:
    """Instrumented wrapper over a ``threading`` Lock/RLock.

    Implements the full ``Condition`` lock duck-type — ``_release_save``
    / ``_acquire_restore`` / ``_is_owned`` — so a ``Condition`` built
    over a watched lock keeps correct wait() semantics AND correct
    held-stack bookkeeping across the wait's release/reacquire.
    """

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner
        MONITOR.register(name)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            MONITOR.on_acquire(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        MONITOR.on_release(self.name)

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    # -- Condition protocol --------------------------------------------

    def _release_save(self):
        inner = self._inner
        if hasattr(inner, "_release_save"):
            state = inner._release_save()  # RLock: full recursive release
        else:
            inner.release()
            state = None
        return (state, MONITOR.on_release_all(self.name))

    def _acquire_restore(self, saved) -> None:
        state, depth = saved
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        MONITOR.on_acquire_restore(self.name, depth)

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        # plain-Lock heuristic (threading.Condition's own): bypasses the
        # wrapper deliberately so the probe never touches the bookkeeping
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __repr__(self) -> str:
        return "<WatchedLock %s over %r>" % (self.name, self._inner)


_exit_hooked = False


def _hook_exit_report() -> None:
    global _exit_hooked
    if _exit_hooked:
        return
    _exit_hooked = True
    atexit.register(_exit_report)


def _exit_report() -> None:
    snap = MONITOR.snapshot()
    if not snap["acquisitions_total"]:
        return
    line = ("bcp-lockwatch: %d locks, %d acquisitions, max depth %d, "
            "%d inversion(s)\n" % (len(snap["locks"]),
                                   snap["acquisitions_total"],
                                   snap["max_depth"], snap["inversions"]))
    sys.stderr.write(line)
    for cyc in snap["cycles"]:
        sys.stderr.write("bcp-lockwatch: CYCLE %s\n" % " <-> ".join(
            cyc["locks"]))
        for edge, site in sorted(cyc["edges"].items()):
            sys.stderr.write("bcp-lockwatch:   %s at %s\n" % (edge, site))
    sys.stderr.flush()


def watched_lock(name: str, inner=None):
    """A ``threading.Lock`` (or the supplied inner lock), wrapped when
    the sentinel gate is on; the plain primitive otherwise."""
    if inner is None:
        inner = threading.Lock()
    if not enabled():
        return inner
    _hook_exit_report()
    return WatchedLock(name, inner)


def watched_rlock(name: str):
    if not enabled():
        return threading.RLock()
    _hook_exit_report()
    return WatchedLock(name, threading.RLock())


def watched_condition(name: str):
    """A ``threading.Condition`` whose underlying lock is watched (the
    cv's lock participates in the order graph like any other lock)."""
    return threading.Condition(watched_lock(name))


def declare_guards(lock_name: str, fields) -> None:
    """Record the GUARDED_BY vocabulary for ``lock_name`` — called by
    classes adopting bcplint's BCP009 annotation so gettpuinfo reports
    which locks are declared guards (vs inferred from order edges)."""
    MONITOR.declare_guards(lock_name, fields)


def snapshot() -> dict:
    """gettpuinfo's ``lockwatch`` section."""
    if not enabled():
        return {"enabled": False}
    return MONITOR.snapshot()
