"""Utility layer — logging, time, small shared helpers.

Reference: src/util.{h,cpp}. Kept dependency-free so every layer (consensus,
validation, node) can import it without cycles.
"""

from .log import (  # noqa: F401
    log_accept_category,
    log_init,
    log_print,
    log_printf,
)
