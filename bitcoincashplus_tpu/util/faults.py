"""Fault-injection harness, jittered backoff, and crash points.

The accelerator graft puts both consensus-critical hot paths (SHA-256d and
batch ECDSA) behind a device boundary; hardware-miner practice (AsicBoost,
arXiv:1604.00575; FPGA miners, arXiv:2212.05033) treats device failure as an
expected operating mode with host fallback, not an exception. This module is
the failure-side toolkit shared by the supervised dispatch layer
(ops/dispatch.py), the crash-safe chainstate commit (store/), and P2P
reconnect pacing (p2p/connman.py):

  - ``FaultInjector`` — deterministic, env-driven fault injection at every
    backend-crossing call site, so tests can kill the TPU path anywhere:
        BCP_FAULT_MODE   off | fail-once | fail-n | fail-always | fail-rate
                         | latency-spike | poison-output
        BCP_FAULT_OPS    comma list of sites ("sha256,ecdsa") or "all"
        BCP_FAULT_N      failure count for fail-n (default 1)
        BCP_FAULT_RATE   failure probability for fail-rate (default 0.5)
        BCP_FAULT_SEED   rng seed for fail-rate (default 0 — deterministic)
        BCP_FAULT_LATENCY_MS  sleep per call for latency-spike (default 50)
  - ``maybe_crash`` — hard-kill crash points (BCP_FAULT_CRASH=<point>) used
    by the chainstate-commit journal tests: os._exit, no atexit, no sqlite
    rollback — a genuine mid-commit death.
  - ``Backoff`` — jittered exponential backoff (full-jitter variant) used by
    dispatch retries and the connection manager's dial loop.

Everything here is stdlib-only so every layer can import it without cycles
(and the crash-test worker subprocess stays jax-free).
"""

from __future__ import annotations

import os
import random
import time
from typing import Optional

# The four supervised accelerator subsystems (ops/dispatch.py breakers).
SITES = ("sha256", "merkle", "miner", "ecdsa")


class InjectedFault(RuntimeError):
    """A deliberately injected device failure (never raised in production
    unless BCP_FAULT_MODE is armed)."""


class PoisonedOutput(RuntimeError):
    """Device output failed its host-side validation probe (known-answer
    lane / witness / spot-check) — the output must not be trusted."""


class FaultInjector:
    """Env-configured, per-site deterministic fault injection.

    Call counting is per site so fail-once/fail-n behave identically
    regardless of which subsystem fires first. ``reload()`` re-reads the
    environment — tests arm/disarm by setting BCP_FAULT_* and reloading.
    """

    def __init__(self):
        self.reload()

    def reload(self) -> None:
        self.mode = os.environ.get("BCP_FAULT_MODE", "off").strip().lower()
        ops = os.environ.get("BCP_FAULT_OPS", "all").strip().lower()
        self.sites = (
            set(SITES) if ops in ("", "all")
            else {s.strip() for s in ops.split(",") if s.strip()}
        )
        self.fail_n = int(os.environ.get("BCP_FAULT_N", "1"))
        self.rate = float(os.environ.get("BCP_FAULT_RATE", "0.5"))
        self.latency_s = (
            float(os.environ.get("BCP_FAULT_LATENCY_MS", "50")) / 1e3
        )
        self._rng = random.Random(int(os.environ.get("BCP_FAULT_SEED", "0")))
        self.crash_point = os.environ.get("BCP_FAULT_CRASH", "")
        self.calls: dict[str, int] = {}
        self.injected: dict[str, int] = {}
        self.poisoned: dict[str, int] = {}

    # -- call-site hooks ------------------------------------------------

    def armed_for(self, site: str) -> bool:
        return self.mode != "off" and site in self.sites

    def on_call(self, site: str) -> None:
        """Invoked by the supervised dispatcher immediately before each
        device attempt. May sleep (latency-spike) or raise InjectedFault."""
        if not self.armed_for(site):
            return
        n = self.calls.get(site, 0) + 1
        self.calls[site] = n
        fail = False
        if self.mode == "fail-once":
            fail = n == 1
        elif self.mode == "fail-n":
            fail = n <= self.fail_n
        elif self.mode == "fail-always":
            fail = True
        elif self.mode == "fail-rate":
            fail = self._rng.random() < self.rate
        elif self.mode == "latency-spike":
            time.sleep(self.latency_s)
        if fail:
            self.injected[site] = self.injected.get(site, 0) + 1
            raise InjectedFault(
                f"injected fault at {site} (mode={self.mode}, call #{n})"
            )

    def should_poison(self, site: str) -> bool:
        """True when the dispatcher must corrupt this call's device output
        (the validation probe is then expected to catch it)."""
        if self.mode != "poison-output" or site not in self.sites:
            return False
        self.poisoned[site] = self.poisoned.get(site, 0) + 1
        return True

    def snapshot(self) -> dict:
        return {
            "mode": self.mode,
            "sites": sorted(self.sites) if self.mode != "off" else [],
            "calls": dict(self.calls),
            "injected": dict(self.injected),
            "poisoned": dict(self.poisoned),
        }


INJECTOR = FaultInjector()


def maybe_crash(point: str) -> None:
    """Hard-kill the process at a named crash point when armed
    (BCP_FAULT_CRASH=<point>). os._exit skips atexit/finally/sqlite
    rollback — the honest simulation of a power cut mid-commit."""
    if INJECTOR.crash_point and INJECTOR.crash_point == point:
        os._exit(137)


class Backoff:
    """Jittered exponential backoff (full-jitter): delay_k is drawn
    uniformly from [(1-jitter)*d_k, d_k] with d_k = min(base*factor^k, max).
    ``reset()`` on success returns to the base delay. An injectable rng
    keeps tests deterministic."""

    def __init__(self, base: float = 0.5, factor: float = 2.0,
                 maximum: float = 30.0, jitter: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.factor = factor
        self.maximum = maximum
        self.jitter = jitter
        self._rng = rng if rng is not None else random.Random()
        self.attempts = 0

    def next(self) -> float:
        d = min(self.base * (self.factor ** self.attempts), self.maximum)
        self.attempts += 1
        return d * (1.0 - self.jitter * self._rng.random())

    def reset(self) -> None:
        self.attempts = 0


def retry_call(fn, attempts: int = 3, backoff: Optional[Backoff] = None,
               retry_on: tuple = (Exception,), sleep=time.sleep):
    """Call ``fn`` up to ``attempts`` times with backoff sleeps between
    tries; re-raises the last error when every attempt fails."""
    boff = backoff if backoff is not None else Backoff(base=0.02, maximum=1.0)
    last: Optional[BaseException] = None
    for i in range(max(1, attempts)):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            last = e
            if i + 1 < attempts:
                sleep(boff.next())
    assert last is not None
    raise last
