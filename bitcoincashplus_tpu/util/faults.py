"""Fault-injection harness, jittered backoff, and crash points.

The accelerator graft puts both consensus-critical hot paths (SHA-256d and
batch ECDSA) behind a device boundary; hardware-miner practice (AsicBoost,
arXiv:1604.00575; FPGA miners, arXiv:2212.05033) treats device failure as an
expected operating mode with host fallback, not an exception. This module is
the failure-side toolkit shared by the supervised dispatch layer
(ops/dispatch.py), the crash-safe chainstate commit (store/), and P2P
reconnect pacing (p2p/connman.py):

  - ``FaultInjector`` — deterministic, env-driven fault injection at every
    backend-crossing call site, so tests can kill the TPU path anywhere:
        BCP_FAULT_MODE   off | fail-once | fail-n | fail-always | fail-rate
                         | latency-spike | poison-output
        BCP_FAULT_OPS    comma list of sites ("sha256,ecdsa") or "all"
        BCP_FAULT_N      failure count for fail-n (default 1)
        BCP_FAULT_RATE   failure probability for fail-rate (default 0.5)
        BCP_FAULT_SEED   rng seed for fail-rate (default 0 — deterministic)
        BCP_FAULT_LATENCY_MS  sleep per call for latency-spike (default 50)
  - ``maybe_crash`` — hard-kill crash points (BCP_FAULT_CRASH=<point>) used
    by the chainstate-commit journal tests: os._exit, no atexit, no sqlite
    rollback — a genuine mid-commit death.
  - ``Backoff`` — jittered exponential backoff (full-jitter variant) used by
    dispatch retries and the connection manager's dial loop.
  - ``ChaosSchedule`` — deterministic, seeded planner of adversarial network
    actions (flood bursts, non-connecting headers, stalls, scripted
    disconnects) driving the functional ``ChaosPeer`` harness and the
    ``net`` injection site below.

Network fault site: ``BCP_FAULT_OPS=net`` arms the injector at the P2P
message-dispatch boundary (p2p/connman.py) — ``fail-rate`` then models
message loss, ``latency-spike`` a slow link. The ``net`` site is only armed
when named explicitly; ``BCP_FAULT_OPS=all`` still means the accelerator
subsystems only, so existing dead-backend drills are unchanged.

Everything here is stdlib-only so every layer can import it without cycles
(and the crash-test worker subprocess stays jax-free).
"""

from __future__ import annotations

import os
import random
import time
from typing import Optional

# The four supervised accelerator subsystems (ops/dispatch.py breakers).
SITES = ("sha256", "merkle", "miner", "ecdsa")

# The P2P message-dispatch injection site (explicit opt-in only — never
# part of the "all" set, see module docstring).
NET_SITE = "net"
# "ecdsa_glv" (ops/ecdsa_batch.GLV_SITE) is likewise explicit-only: it
# targets the GLV kernel LEG inside the ecdsa dispatch so drills can prove
# the glv -> w4 -> CPU degradation chain without disturbing the
# whole-subsystem "ecdsa" site the dead-backend suite arms via "all".
# "ecdsa_glv_dev" (ops/ecdsa_batch.GLV_DEV_SITE) targets the
# device-decompose leg specifically (ISSUE 11): fail-* proves the
# device-decompose -> host-decompose rung, poison-output proves the KAT
# gate; also explicit-only, for the same reason.
# "ecdsa_msm" (ops/ecdsa_batch.MSM_SITE) targets the Schnorr Pippenger
# batch-check leg (ISSUE 19): fail-* proves the bisect-to-oracle
# fallback rung, poison-output flips every batch verdict — canary
# batches included — proving the per-session canary gate catches a
# corrupted verdict stream; also explicit-only.
# "store_shard" (store/sharded.STORE_SHARD_SITE) fires at the head of
# every shard's journal leg inside a sharded chainstate commit: fail-*
# proves one failing shard aborts the WHOLE commit with the already-
# written journals unlinked (no shard ever ahead of the manifest epoch),
# latency-spike models one slow shard dragging the parallel flush.
# Explicit-only: "all" must keep meaning the accelerator subsystems so
# the dead-backend drills don't suddenly fail chainstate flushes.

# Fleet serving injection sites (ISSUE 16), both explicit-only for the
# same reason as "net": "all" keeps meaning the accelerator subsystems.
# GATEWAY_SITE fires at the gateway's admission/dispatch boundary —
# fail-* models a front-door hiccup the client sees as a retryable RPC
# error, latency-spike a slow front door (burns the admission budget,
# drives graduated shedding). REPLICA_RPC_SITE fires on the replica leg
# of every proxied read — fail-* models a dying replica (drives breaker
# trips and mid-request failover), latency-spike a GC-pausing one.
GATEWAY_SITE = "gateway"
REPLICA_RPC_SITE = "replica_rpc"

# Proof-carrying snapshot certificate site (ISSUE 17), explicit-only like
# the other non-accelerator sites. It fires on BOTH legs of the
# certificate lifecycle: at build (dumptxoutset) poison-output corrupts
# one mid-trajectory epoch digest BEFORE the commitment chain is sealed —
# the forged-epoch snapshot that passes structural verification at load
# and must be caught at the first divergent epoch checkpoint by the
# shadow validator; at verify (loadtxoutset) fail-* models a certificate
# check blowing up mid-load and must take the wipe-and-reject path, never
# a half-loaded chainstate.
SNAPSHOT_CERT_SITE = "snapshot_cert"

# Flood-scale mempool site (ISSUE 20), explicit-only like the other
# non-accelerator sites. It fires at the head of the batched legs of
# template selection (CTxMemPool.select_for_block) and bulk eviction
# (trim_to_size): fail-* proves the per-tx reference fallback rung
# (frontier/columns bypassed, answer unchanged), poison-output corrupts
# the batched verdict — a dropped template tail, a wrong eviction victim
# — and must be caught by the differential gate re-deriving the verdict
# through the per-tx oracle (the -mempoolselfcheck path, always-on under
# poison drills).
MEMPOOL_SITE = "mempool"


class InjectedFault(RuntimeError):
    """A deliberately injected device failure (never raised in production
    unless BCP_FAULT_MODE is armed)."""


class PoisonedOutput(RuntimeError):
    """Device output failed its host-side validation probe (known-answer
    lane / witness / spot-check) — the output must not be trusted."""


class FaultInjector:
    """Env-configured, per-site deterministic fault injection.

    Call counting is per site so fail-once/fail-n behave identically
    regardless of which subsystem fires first. ``reload()`` re-reads the
    environment — tests arm/disarm by setting BCP_FAULT_* and reloading.
    """

    def __init__(self):
        self.reload()

    def reload(self) -> None:
        self.mode = os.environ.get("BCP_FAULT_MODE", "off").strip().lower()
        ops = os.environ.get("BCP_FAULT_OPS", "all").strip().lower()
        self.sites = (
            set(SITES) if ops in ("", "all")
            else {s.strip() for s in ops.split(",") if s.strip()}
        )
        self.fail_n = int(os.environ.get("BCP_FAULT_N", "1"))
        self.rate = float(os.environ.get("BCP_FAULT_RATE", "0.5"))
        self.latency_s = (
            float(os.environ.get("BCP_FAULT_LATENCY_MS", "50")) / 1e3
        )
        self._rng = random.Random(int(os.environ.get("BCP_FAULT_SEED", "0")))
        self.crash_point = os.environ.get("BCP_FAULT_CRASH", "")
        self.calls: dict[str, int] = {}
        self.injected: dict[str, int] = {}
        self.poisoned: dict[str, int] = {}

    # -- call-site hooks ------------------------------------------------

    def armed_for(self, site: str) -> bool:
        return self.mode != "off" and site in self.sites

    def on_call(self, site: str) -> None:
        """Invoked by the supervised dispatcher immediately before each
        device attempt. May sleep (latency-spike) or raise InjectedFault."""
        if not self.armed_for(site):
            return
        n = self.calls.get(site, 0) + 1
        self.calls[site] = n
        fail = False
        if self.mode == "fail-once":
            fail = n == 1
        elif self.mode == "fail-n":
            fail = n <= self.fail_n
        elif self.mode == "fail-always":
            fail = True
        elif self.mode == "fail-rate":
            fail = self._rng.random() < self.rate
        elif self.mode == "latency-spike":
            time.sleep(self.latency_s)
        if fail:
            self.injected[site] = self.injected.get(site, 0) + 1
            raise InjectedFault(
                f"injected fault at {site} (mode={self.mode}, call #{n})"
            )

    def latency(self, site: str) -> float:
        """Latency-spike support for callers on an event loop: returns the
        sleep they must apply themselves (``await asyncio.sleep(...)``)
        instead of letting :meth:`on_call`'s blocking ``time.sleep`` stall
        the whole loop. Zero when the site isn't armed for latency-spike.
        Calls served this way are not tallied in ``calls``."""
        if self.armed_for(site) and self.mode == "latency-spike":
            return self.latency_s
        return 0.0

    def should_poison(self, site: str) -> bool:
        """True when the dispatcher must corrupt this call's device output
        (the validation probe is then expected to catch it)."""
        if self.mode != "poison-output" or site not in self.sites:
            return False
        self.poisoned[site] = self.poisoned.get(site, 0) + 1
        return True

    def snapshot(self) -> dict:
        return {
            "mode": self.mode,
            "sites": sorted(self.sites) if self.mode != "off" else [],
            "calls": dict(self.calls),
            "injected": dict(self.injected),
            "poisoned": dict(self.poisoned),
        }


INJECTOR = FaultInjector()


def maybe_crash(point: str) -> None:
    """Hard-kill the process at a named crash point when armed
    (BCP_FAULT_CRASH=<point>). os._exit skips atexit/finally/sqlite
    rollback — the honest simulation of a power cut mid-commit."""
    if INJECTOR.crash_point and INJECTOR.crash_point == point:
        os._exit(137)


class Backoff:
    """Jittered exponential backoff (full-jitter): delay_k is drawn
    uniformly from [(1-jitter)*d_k, d_k] with d_k = min(base*factor^k, max).
    ``reset()`` on success returns to the base delay. An injectable rng
    keeps tests deterministic."""

    def __init__(self, base: float = 0.5, factor: float = 2.0,
                 maximum: float = 30.0, jitter: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.factor = factor
        self.maximum = maximum
        self.jitter = jitter
        self._rng = rng if rng is not None else random.Random()
        self.attempts = 0

    def next(self) -> float:
        d = min(self.base * (self.factor ** self.attempts), self.maximum)
        self.attempts += 1
        return d * (1.0 - self.jitter * self._rng.random())

    def reset(self) -> None:
        self.attempts = 0


# The per-round action vocabulary drawn by a scheduled chaos peer
# (tests/functional/framework.ChaosPeer's "garbage" behavior; the flood
# and stall behaviors are continuous rather than action-scheduled).
CHAOS_ACTIONS = (
    "garbage-headers",  # valid-PoW headers on an unknown parent
    "ghost",            # stop talking, keep the socket open
    "reconnect",        # scripted disconnect + fresh session
)

# The fleet-level action vocabulary for multi-node fork-storm campaigns
# (tests/functional/test_fork_storm.py): the scheduler draws these to
# drive a whole topology — split the fleet, mine competing branches on
# both sides, heal and watch convergence. Seeded like everything else:
# one -netseed replays the identical storm.
FLEET_ACTIONS = (
    "mine",        # extend the majority side's chain
    "fork",        # mine a competing branch on a minority side
    "partition",   # split the fleet into two seeded halves
    "heal",        # reconnect the halves (the fork war resolves)
)


class ChaosSchedule:
    """Deterministic, seeded adversarial-action planner.

    One instance per chaos peer: every draw (next action, pause length,
    burst size, random bytes/hashes) comes from a single seeded rng, so a
    campaign is replayable from its seed alone — the property the
    randomized differential tests in this repo already rely on. The
    schedule records its history for post-mortem assertions."""

    def __init__(self, seed: int, actions: tuple = CHAOS_ACTIONS,
                 min_pause: float = 0.05, max_pause: float = 0.4):
        self.seed = seed
        self._rng = random.Random(seed)
        self.actions = tuple(actions)
        self.min_pause = min_pause
        self.max_pause = max_pause
        self.history: list[str] = []

    def next_action(self) -> str:
        action = self._rng.choice(self.actions)
        self.history.append(action)
        return action

    def pause(self) -> float:
        span = self.max_pause - self.min_pause
        return self.min_pause + span * self._rng.random()

    def burst_size(self, lo: int = 4, hi: int = 32) -> int:
        return self._rng.randint(lo, hi)

    def randbytes(self, n: int) -> bytes:
        return self._rng.randbytes(n)

    def randhash(self) -> bytes:
        return self._rng.randbytes(32)

    def randint(self, lo: int, hi: int) -> int:
        return self._rng.randint(lo, hi)

    def shuffle(self, items: list) -> list:
        """In-place seeded shuffle (tx-storm delivery order); returns the
        list for chaining."""
        self._rng.shuffle(items)
        return items

    def rand(self) -> float:
        return self._rng.random()

    def choice(self, items):
        """Seeded pick from any sequence (fleet action targets)."""
        return self._rng.choice(items)

    def bipartition(self, n: int) -> tuple[list[int], list[int]]:
        """Seeded split of node indices 0..n-1 into two non-empty halves
        — the ``partition`` fleet action's topology draw. The cut point
        and the membership are both schedule-driven, so one seed replays
        the identical partition sequence."""
        idxs = list(range(n))
        self._rng.shuffle(idxs)
        cut = self._rng.randint(1, max(1, n - 1))
        return sorted(idxs[:cut]), sorted(idxs[cut:])


def retry_call(fn, attempts: int = 3, backoff: Optional[Backoff] = None,
               retry_on: tuple = (Exception,), sleep=time.sleep):
    """Call ``fn`` up to ``attempts`` times with backoff sleeps between
    tries; re-raises the last error when every attempt fails."""
    boff = backoff if backoff is not None else Backoff(base=0.02, maximum=1.0)
    last: Optional[BaseException] = None
    for i in range(max(1, attempts)):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            last = e
            if i + 1 < attempts:
                sleep(boff.next())
    assert last is not None
    raise last
