"""Device-lane observability — the half of the system PR 6 couldn't see.

The unified telemetry layer (util/telemetry) made the HOST side
measurable; every number below the dispatch boundary was still dark:
nothing verified the bounded-recompile bucket invariant at runtime
(ops/ecdsa_batch pads batches to a small compiled-shape set precisely so
XLA retraces stay bounded), nothing accounted for host<->device bytes,
and the "mining loses ~15x to host dispatch" claim (BENCH_r05) had no
per-phase decomposition behind it. This module is the device-lane
monitor registered around every jit entrypoint:

- **Compile/retrace sentinel** (``program()``/``ProgramWatch.dispatch``):
  each watched program counts dispatches per abstract-shape signature; a
  ``jax.monitoring`` listener attributes XLA trace/lower/compile seconds
  to the dispatch that paid them (``bcp_xla_compile_seconds{program}``).
  A program that grows more distinct signatures than its DECLARED shape
  budget fires ``bcp_xla_retrace_unexpected_total{program}``, a trace
  instant, and a log warning — the bucket design's bounded-recompile
  invariant, checked at runtime instead of assumed.

- **Transfer & memory accounting** (``note_transfer``, the
  ``devicewatch_memory`` collector): ``bcp_device_transfer_bytes_total
  {site,direction}`` totals on host->device staging and result fetch,
  transfer-time histograms where a site can actually isolate the wait
  (result fetch; explicit device_put in the bench), and a scrape-time
  collector projecting ``device.memory_stats()`` into HBM gauges —
  graceful no-op on CPU backends, whose ``memory_stats()`` is None.

- **Dispatch-phase profiling** (``phase()``, ``start_profile``/
  ``stop_profile``): per-dispatch pack/transfer/execute/fetch legs into
  ``bcp_dispatch_phase_seconds{site,phase}``, plus an on-demand
  ``jax.profiler`` wrapper (TensorBoard-compatible dump into the
  datadir) surfaced as the ``startprofile``/``stopprofile`` RPC pair.

- **Stall watchdog** (``Watchdog``/``WATCHDOG``): a no-progress sentinel
  for threads that must keep draining work (the SigService flush loop,
  the pipeline settle horizon). Subsystems register a pending-work probe
  and ``beat()`` on every unit of progress; pending work with no beat
  for the quiet period fires ``bcp_watchdog_stalled{subsystem}``, a log
  warning, and a trace instant. OBSERVE-ONLY by design: the watchdog
  never kills or restarts anything — the degradation machinery
  (breakers, caller-side CPU re-verify) already owns recovery, and a
  false-positive kill would be worse than a loud gauge.

No jax import at module level: validation/ and the crash-test workers
import this (via ops/dispatch) without touching the backend; every jax
access is lazy and guarded on ``"jax" in sys.modules`` so a metrics
scrape can never be the thing that initializes a wedged device tunnel.

Env knobs:
    BCP_DEVICEWATCH_COST   cost_analysis capture at first compile:
                           "auto" (default: only when the measured
                           compile was cheap, < 0.5 s — the capture
                           re-lowers, and must never double a minutes-
                           long CPU kernel compile), "always", "never"
    BCP_WATCHDOG_QUIET     default stall quiet period, seconds (10)
    BCP_WATCHDOG_INTERVAL  global watchdog ticker cadence, seconds (1)
"""

from __future__ import annotations

import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional

from . import telemetry as tm
from .log import log_printf

# -- telemetry families (util/telemetry). Registered at import so the
# whole namespace is visible on /metrics from the first scrape, samples
# or not — the acceptance surface for "is device accounting wired".
_COMPILE_H = tm.histogram(
    "bcp_xla_compile_seconds",
    "XLA trace+lower+compile seconds attributed to a watched program's "
    "dispatch (one observation per compiling dispatch)",
    labels=("program",),
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0, 60.0, 120.0, 300.0))
_COMPILES_C = tm.counter(
    "bcp_xla_compiles_total",
    "Dispatches of a watched program that paid an XLA trace/compile",
    labels=("program",))
_RETRACE_C = tm.counter(
    "bcp_xla_retrace_unexpected_total",
    "New abstract-shape signatures beyond a program's declared shape "
    "budget — the bounded-recompile invariant, violated",
    labels=("program",))
_XFER_B = tm.counter(
    "bcp_device_transfer_bytes_total",
    "Bytes crossing the host<->device boundary per site and direction "
    "(h2d = staging, d2h = result fetch)",
    labels=("site", "direction"))
_XFER_H = tm.histogram(
    "bcp_device_transfer_seconds",
    "Transfer wait where a site can isolate it (result fetch; explicit "
    "device_put staging in the bench) — h2d bytes are always counted, "
    "h2d TIME only where it is not hidden inside an async dispatch",
    labels=("site", "direction"),
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0))
_PHASE_H = tm.histogram(
    "bcp_dispatch_phase_seconds",
    "Per-dispatch phase decomposition (pack = host SoA/byte-matrix "
    "emit, transfer = explicit staging, execute = program call, fetch = "
    "blocking result materialization)",
    labels=("site", "phase"))
_WD_STALLED_G = tm.gauge(
    "bcp_watchdog_stalled",
    "1 while a subsystem has pending work but made no progress for its "
    "quiet period, else 0 (observe-only — no kill action)",
    labels=("subsystem",))
_WD_EPISODES_C = tm.counter(
    "bcp_watchdog_stall_episodes_total",
    "Stall episodes detected per subsystem",
    labels=("subsystem",))
_WD_IDLE_G = tm.gauge(
    "bcp_watchdog_idle_seconds",
    "Seconds since the subsystem's last progress beat (the last-progress "
    "gauge; meaningful while pending work exists)",
    labels=("subsystem",))


# ---------------------------------------------------------------------------
# Compile/retrace sentinel
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_PROGRAMS: dict[str, "ProgramWatch"] = {}
_TLS = threading.local()
_LISTENER_INSTALLED = False
# compile seconds observed by the jax.monitoring listener while no
# watched dispatch was active on that thread (other jits in the process)
_UNATTRIBUTED = {"compile_s": 0.0, "events": 0}
# persistent XLA compilation cache state (-compilecache / BCP_COMPILE_CACHE
# -> enable_compile_cache): BENCH_r08 recorded a 92.9 s cold GLV compile
# that every bench subprocess and kernel-pinned import re-paid; the cache
# makes it a once-per-toolchain cost. Event tallies come from the
# jax.monitoring event listener (cache_hits etc.), surfaced in
# gettpuinfo.device.
_COMPILE_CACHE = {"dir": None, "enabled": False, "events": {}}
_CACHE_EVENT_PREFIX = "/jax/compilation_cache/"


def _ctx_stack() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _on_compile_event(event: str, duration: float, **_kw) -> None:
    """jax.monitoring duration listener: attribute XLA compile-pipeline
    seconds (/jax/core/compile/*: jaxpr trace, MLIR lowering, backend
    compile) to the watched dispatch active on this thread, if any. jit
    compiles synchronously on the calling thread, so thread-local
    attribution is exact."""
    if not event.startswith("/jax/core/compile/"):
        return
    stack = getattr(_TLS, "stack", None)
    if stack:
        stack[-1]["compile_s"] += duration
        stack[-1]["events"] += 1
    else:
        with _LOCK:
            _UNATTRIBUTED["compile_s"] += duration
            _UNATTRIBUTED["events"] += 1


def _on_cache_event(event: str, **_kw) -> None:
    """jax.monitoring event listener: tally compilation-cache events
    (/jax/compilation_cache/cache_hits and friends) so gettpuinfo.device
    can prove the persistent cache is actually being hit."""
    if not event.startswith(_CACHE_EVENT_PREFIX):
        return
    key = event[len(_CACHE_EVENT_PREFIX):]
    with _LOCK:
        _COMPILE_CACHE["events"][key] = \
            _COMPILE_CACHE["events"].get(key, 0) + 1


def _ensure_listener() -> bool:
    """Install the jax.monitoring listeners once, lazily, and only when
    jax is already imported (a watch must never be the thing that
    initializes the backend). Returns whether the listener is live."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return True
    if "jax" not in sys.modules:
        return False
    with _LOCK:
        if _LISTENER_INSTALLED:
            return True
        try:
            from jax import monitoring as _jm

            _jm.register_event_duration_secs_listener(_on_compile_event)
            try:
                _jm.register_event_listener(_on_cache_event)
            except Exception:  # pragma: no cover - older monitoring API
                pass
            _LISTENER_INSTALLED = True
        except Exception:  # pragma: no cover - jax without monitoring
            return False
    return True


def enable_compile_cache(path: str) -> dict:
    """Turn on jax's persistent XLA compilation cache at ``path`` (the
    -compilecache=<dir> knob; default OFF). Seeds BCP_COMPILE_CACHE so
    subprocesses this process spawns (bench kernel-pinned imports, the
    functional-test node fleet) inherit the same cache, and installs the
    monitoring listener so cache hits surface in gettpuinfo.device.
    Imports jax eagerly — only an explicit opt-in calls this."""
    import jax

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # the kernels this repo cares about are all multi-second compiles;
    # 2 s keeps trivial jits out of the cache directory
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    os.environ["BCP_COMPILE_CACHE"] = path
    _ensure_listener()
    with _LOCK:
        _COMPILE_CACHE["dir"] = path
        _COMPILE_CACHE["enabled"] = True
    return compile_cache_snapshot()


def compile_cache_snapshot() -> dict:
    """Compilation-cache state for gettpuinfo.device: directory, enabled
    flag, and the monitoring event tallies (cache_hits is the number of
    compiles this process skipped by reading the cache)."""
    with _LOCK:
        events = dict(_COMPILE_CACHE["events"])
        return {
            "dir": _COMPILE_CACHE["dir"],
            "enabled": _COMPILE_CACHE["enabled"],
            "cache_hits": events.get("cache_hits", 0),
            "events": events,
        }


def _cost_capture_mode() -> str:
    return os.environ.get("BCP_DEVICEWATCH_COST", "auto")


class ProgramWatch:
    """Per-program compile/shape accounting around a jit entrypoint.

    ``dispatch(sig)`` wraps ONE call of the program: ``sig`` is the
    abstract-shape signature the caller derives from its bucketing (for
    the ECDSA kernels that is the padded bucket size — the compiled
    shape IS the bucket). A signature never seen before counts a
    (re)trace; compile seconds come from the jax.monitoring listener
    (falling back to the wrapped call's wall time when the listener is
    unavailable). ``shape_budget`` declares how many distinct signatures
    the program's bucket design allows — one more is an invariant
    violation, not a tuning knob, and fires the sentinel."""

    def __init__(self, name: str, shape_budget: Optional[int] = None):
        self.name = name
        self.shape_budget = shape_budget
        self.signatures: dict[tuple, int] = {}
        self.dispatches = 0
        self.compiles = 0
        self.compile_seconds = 0.0
        self.retraces_unexpected = 0
        self.warnings = 0
        self.last_warning = ""
        self.cost: dict[str, dict] = {}  # sig -> first-compile cost analysis

    @contextmanager
    def dispatch(self, *sig_parts, jitfn=None, args=None, kwargs=None):
        """Wrap one program call. Bookkeeping runs even when the wrapped
        call raises (a failed compile still consumed a shape attempt and
        compile time); cost capture runs only on success."""
        listener = _ensure_listener()
        sig = tuple(sig_parts)
        rec = {"compile_s": 0.0, "events": 0}
        _ctx_stack().append(rec)
        t0 = time.perf_counter()
        failed = False
        try:
            yield self
        except BaseException:
            failed = True
            raise
        finally:
            dt = time.perf_counter() - t0
            stack = _ctx_stack()
            if stack and stack[-1] is rec:
                stack.pop()
            self._after_dispatch(sig, rec, dt, listener, failed,
                                 jitfn, args, kwargs)

    def _after_dispatch(self, sig, rec, dt, listener, failed,
                        jitfn, args, kwargs) -> None:
        with _LOCK:
            new = sig not in self.signatures
            self.signatures[sig] = self.signatures.get(sig, 0) + 1
            self.dispatches += 1
            compiled = rec["compile_s"] > 0.0 or (new and not listener)
            compile_s = rec["compile_s"] if rec["compile_s"] > 0.0 else dt
            if compiled:
                self.compiles += 1
                self.compile_seconds += compile_s
            over_budget = (new and self.shape_budget is not None
                           and len(self.signatures) > self.shape_budget)
            if over_budget:
                self.retraces_unexpected += 1
                self.warnings += 1
                self.last_warning = (
                    f"program {self.name!r}: unexpected retrace — shape "
                    f"signature {sig!r} is distinct shape "
                    f"#{len(self.signatures)} against a declared budget "
                    f"of {self.shape_budget} (bounded-recompile invariant "
                    f"violated; compile {compile_s:.3f}s)")
        if compiled:
            _COMPILES_C.labels(program=self.name).inc()
            _COMPILE_H.labels(program=self.name).observe(compile_s)
        if over_budget:
            _RETRACE_C.labels(program=self.name).inc()
            tm.instant("devicewatch.retrace_unexpected",
                       program=self.name, sig=str(sig),
                       shapes=len(self.signatures),
                       budget=self.shape_budget)
            log_printf("WARNING: %s", self.last_warning)
        if (new and not failed and jitfn is not None
                and args is not None):
            self._capture_cost(sig, compile_s, jitfn, args, kwargs or {})

    def _capture_cost(self, sig, compile_s, jitfn, args, kwargs) -> None:
        """First-compile cost analysis (FLOPs / bytes accessed) via the
        AOT lower+compile path. That path does NOT share the dispatch
        cache, so a second compile is paid — gated to cheap compiles
        ("auto": < 0.5 s measured, where the persistent compilation
        cache or plain speed makes the re-lower negligible) unless
        BCP_DEVICEWATCH_COST=always forces it. The listener is suspended
        for the capture so its compile doesn't count as a dispatch."""
        mode = _cost_capture_mode()
        if mode in ("0", "off", "never"):
            return
        if mode not in ("1", "always") and compile_s >= 0.5:
            return
        _ctx_stack().append({"compile_s": 0.0, "events": 0})  # sink
        try:
            ca = jitfn.lower(*args, **kwargs).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            entry = {
                "flops": float(ca.get("flops", 0.0)),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
            }
            tr = ca.get("transcendentals")
            if tr:
                entry["transcendentals"] = float(tr)
            with _LOCK:
                self.cost[str(sig)] = entry
        except Exception:  # noqa: BLE001 — cost capture is best-effort
            pass
        finally:
            stack = _ctx_stack()
            if stack:
                stack.pop()

    def snapshot(self) -> dict:
        with _LOCK:
            return {
                "dispatches": self.dispatches,
                "compiles": self.compiles,
                "compile_seconds": round(self.compile_seconds, 4),
                "shapes": len(self.signatures),
                "shape_budget": self.shape_budget,
                "retraces_unexpected": self.retraces_unexpected,
                "signatures": {str(k): v
                               for k, v in sorted(self.signatures.items())},
                "cost": {k: dict(v) for k, v in self.cost.items()},
                "last_warning": self.last_warning,
            }


def program(name: str, shape_budget: Optional[int] = None) -> ProgramWatch:
    """Get-or-register the watch for one jit program. A later caller
    passing a budget upgrades a budget-less registration (modules
    register lazily, in whatever import order the process took)."""
    with _LOCK:
        pw = _PROGRAMS.get(name)
        if pw is None:
            pw = _PROGRAMS[name] = ProgramWatch(name, shape_budget)
        elif shape_budget is not None and pw.shape_budget is None:
            pw.shape_budget = shape_budget
        return pw


# ---------------------------------------------------------------------------
# Transfer accounting + phase profiling
# ---------------------------------------------------------------------------

_TRANSFERS: dict[tuple, int] = {}  # (site, direction) -> bytes, ungated


def note_transfer(site: str, direction: str, nbytes: int,
                  seconds: Optional[float] = None) -> None:
    """Account one host<->device crossing: bytes always, wait time only
    when the caller measured a real blocking transfer (direction is
    "h2d" or "d2h")."""
    n = int(nbytes)
    with _LOCK:
        _TRANSFERS[(site, direction)] = \
            _TRANSFERS.get((site, direction), 0) + n
    _XFER_B.labels(site=site, direction=direction).inc(n)
    if seconds is not None:
        _XFER_H.labels(site=site, direction=direction).observe(seconds)


def note_phase(site: str, phase_name: str, seconds: float) -> None:
    _PHASE_H.labels(site=site, phase=phase_name).observe(seconds)


@contextmanager
def phase(site: str, phase_name: str):
    """Time one dispatch phase (pack/transfer/execute/fetch) into the
    per-site phase histogram."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        note_phase(site, phase_name, time.perf_counter() - t0)


def transfer_snapshot() -> dict:
    with _LOCK:
        out: dict[str, dict] = {}
        for (site, direction), n in sorted(_TRANSFERS.items()):
            out.setdefault(site, {})[direction] = n
        return out


# ---------------------------------------------------------------------------
# Device-memory collector (HBM gauges; graceful no-op on CPU backends)
# ---------------------------------------------------------------------------


def _devices():
    """The live device list WITHOUT triggering backend init: if jax has
    not been imported by real work yet, a metrics scrape must not be the
    thing that wakes a (possibly wedged) accelerator tunnel."""
    if "jax" not in sys.modules:
        return []
    try:
        import jax

        return list(jax.devices())
    except Exception:  # noqa: BLE001 — scrape must survive a dead backend
        return []


def _collect_device_memory():
    """Registry collector: per-device memory_stats() projected into HBM
    gauges. CPU backends return None from memory_stats() — the families
    are still emitted (empty / supported=0) so the namespace is stable
    across backends."""
    mem = {"name": "bcp_device_memory_bytes", "type": "gauge",
           "help": "device.memory_stats() projection (bytes_in_use, "
                   "peak_bytes_in_use, bytes_limit, ... per device)",
           "samples": []}
    sup = {"name": "bcp_device_memory_supported", "type": "gauge",
           "help": "1 when the device exposes memory_stats() "
                   "(accelerators), 0 otherwise (CPU backends)",
           "samples": []}
    devices = _devices()
    for i, d in enumerate(devices):
        label = f"{getattr(d, 'platform', 'unknown')}:{i}"
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — per-device probe
            stats = None
        sup["samples"].append(({"device": label}, 1 if stats else 0))
        for k, v in (stats or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                mem["samples"].append(
                    ({"device": label, "stat": k}, float(v)))
    count = {"name": "bcp_device_count", "type": "gauge",
             "help": "Devices visible to the process (0 until jax is "
                     "imported by real work)",
             "samples": [({}, float(len(devices)))]}
    return [mem, sup, count]


def _collect_programs():
    """Registry collector: per-program distinct-shape counts (the compile
    counters themselves are native families)."""
    with _LOCK:
        shapes = {name: len(pw.signatures) for name, pw in _PROGRAMS.items()}
    if not shapes:
        return []
    return [{
        "name": "bcp_xla_program_shapes", "type": "gauge",
        "help": "Distinct abstract-shape signatures seen per watched "
                "program (compare against the declared budget)",
        "samples": [({"program": n}, v) for n, v in sorted(shapes.items())],
    }]


tm.register_collector("devicewatch_memory", _collect_device_memory)
tm.register_collector("devicewatch_programs", _collect_programs)


# ---------------------------------------------------------------------------
# On-demand jax.profiler wrapper (startprofile / stopprofile RPCs)
# ---------------------------------------------------------------------------

_PROFILE = {"active": False, "path": None, "t0": 0.0, "dumps": 0}


def start_profile(logdir: str) -> dict:
    """Start a jax.profiler trace into ``logdir`` (TensorBoard-compatible
    dump: plugins/profile/<ts>/*.xplane.pb + trace.json.gz). Raises
    RuntimeError when a profile is already running (the profiler is
    process-global)."""
    import jax

    with _LOCK:
        if _PROFILE["active"]:
            raise RuntimeError(
                f"profiler already active (dir: {_PROFILE['path']})")
        _PROFILE["active"] = True
        _PROFILE["path"] = logdir
        _PROFILE["t0"] = time.monotonic()
    try:
        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)
    except BaseException:
        with _LOCK:
            _PROFILE["active"] = False
            _PROFILE["path"] = None
        raise
    return {"path": logdir, "active": True}


def stop_profile() -> dict:
    """Stop the running jax.profiler trace; returns {path, seconds}.
    Raises RuntimeError when no profile is running."""
    import jax

    with _LOCK:
        if not _PROFILE["active"]:
            raise RuntimeError("profiler not active (startprofile first)")
        path = _PROFILE["path"]
        seconds = time.monotonic() - _PROFILE["t0"]
    try:
        jax.profiler.stop_trace()
    finally:
        with _LOCK:
            _PROFILE["active"] = False
            _PROFILE["path"] = None
            _PROFILE["dumps"] += 1
    return {"path": path, "seconds": round(seconds, 3)}


def profile_snapshot() -> dict:
    with _LOCK:
        return {"active": _PROFILE["active"], "path": _PROFILE["path"],
                "dumps": _PROFILE["dumps"]}


# ---------------------------------------------------------------------------
# Stall watchdog (observe-only)
# ---------------------------------------------------------------------------

def _default_quiet() -> float:
    try:
        return float(os.environ.get("BCP_WATCHDOG_QUIET", "10"))
    except ValueError:
        return 10.0


class Watchdog:
    """No-progress sentinel. Subsystems register a ``pending_fn`` (how
    many units of work are parked right now — must be lock-free/cheap)
    and ``beat()`` on every unit of progress. ``check()`` marks a
    subsystem stalled when it has pending work and the last beat is
    older than its quiet period; the episode fires the counter, a log
    warning, and a trace instant ONCE per stall, and clears on the next
    beat (or when the pending work drains). Observe-only: no kill, no
    restart — the breaker/fallback machinery owns recovery.

    ``clock`` is injectable (fake-clock unit tests); the process-global
    ``WATCHDOG`` additionally runs a lazy 1 Hz daemon ticker so stalls
    surface even when nobody scrapes /metrics."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 auto_ticker: bool = False):
        self._clock = clock
        self._auto_ticker = auto_ticker
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}
        # cumulative per-subsystem beats, surviving re-registration (a
        # bench that closes its node must still be able to prove the
        # watchdog was exercised)
        self._beat_totals: dict[str, int] = {}
        self._ticker: Optional[threading.Thread] = None
        self._ticker_stop = threading.Event()

    def register(self, subsystem: str, pending_fn: Callable[[], int],
                 quiet_s: Optional[float] = None) -> None:
        """(Re-)register a subsystem; a fresh owner supersedes a closed
        one's closure by name (the PR 6 collector pattern). quiet_s <= 0
        disables detection for the subsystem (gauges still export)."""
        q = _default_quiet() if quiet_s is None else float(quiet_s)
        with self._lock:
            self._entries[subsystem] = {
                "pending_fn": pending_fn, "quiet_s": q,
                "last_beat": self._clock(), "stalled": False,
                "episodes": 0, "beats": 0,
            }
        _WD_STALLED_G.labels(subsystem=subsystem).set(0)
        if self._auto_ticker:
            self._ensure_ticker()

    def unregister(self, subsystem: str) -> None:
        with self._lock:
            self._entries.pop(subsystem, None)

    def beat(self, subsystem: str) -> None:
        """Record one unit of progress. Unregistered names are a cheap
        no-op (a bare ChainstateManager in a unit test must not have to
        care whether a node wired the watchdog)."""
        with self._lock:
            self._beat_totals[subsystem] = \
                self._beat_totals.get(subsystem, 0) + 1
            ent = self._entries.get(subsystem)
            if ent is None:
                return
            ent["last_beat"] = self._clock()
            ent["beats"] += 1
            was_stalled, ent["stalled"] = ent["stalled"], False
        if was_stalled:
            _WD_STALLED_G.labels(subsystem=subsystem).set(0)
            log_printf("watchdog: %s recovered (progress beat)", subsystem)
            tm.instant("watchdog.recovered", subsystem=subsystem)

    def check(self, now: Optional[float] = None) -> list[str]:
        """Evaluate every subsystem; returns the currently-stalled names.
        Called by the ticker, the scrape-time collector, and tests."""
        now = self._clock() if now is None else now
        with self._lock:
            entries = list(self._entries.items())
        stalled_names = []
        for name, ent in entries:
            try:
                pending = int(ent["pending_fn"]())
            except Exception:  # noqa: BLE001 — a dead probe isn't a stall
                pending = 0
            idle = max(0.0, now - ent["last_beat"])
            _WD_IDLE_G.labels(subsystem=name).set(round(idle, 3))
            is_stalled = (pending > 0 and ent["quiet_s"] > 0
                          and idle >= ent["quiet_s"])
            fire = clear = False
            with self._lock:
                live = self._entries.get(name)
                if live is not ent:
                    continue  # re-registered mid-check
                if is_stalled and not ent["stalled"]:
                    ent["stalled"] = True
                    ent["episodes"] += 1
                    fire = True
                elif not is_stalled and ent["stalled"]:
                    ent["stalled"] = False
                    clear = True
            if fire:
                _WD_STALLED_G.labels(subsystem=name).set(1)
                _WD_EPISODES_C.labels(subsystem=name).inc()
                log_printf(
                    "WARNING: watchdog: %s stalled — %d pending unit(s), "
                    "no progress for %.1fs (quiet period %.1fs); "
                    "observe-only, no action taken",
                    name, pending, idle, ent["quiet_s"])
                tm.instant("watchdog.stalled", subsystem=name,
                           pending=pending, idle_s=round(idle, 3),
                           quiet_s=ent["quiet_s"])
            elif clear:
                _WD_STALLED_G.labels(subsystem=name).set(0)
                log_printf("watchdog: %s recovered (pending drained)", name)
            if is_stalled:
                stalled_names.append(name)
        return stalled_names

    def snapshot(self) -> dict:
        now = self._clock()
        with self._lock:
            return {
                name: {
                    "stalled": ent["stalled"],
                    "episodes": ent["episodes"],
                    "beats": ent["beats"],
                    "quiet_s": ent["quiet_s"],
                    "idle_s": round(max(0.0, now - ent["last_beat"]), 3),
                }
                for name, ent in self._entries.items()
            }

    def beat_totals(self) -> dict:
        """Cumulative beats per subsystem across registrations (survives
        a node close/unregister — bench/test evidence the watchdog ran)."""
        with self._lock:
            return dict(self._beat_totals)

    # -- ticker ---------------------------------------------------------

    def _ensure_ticker(self) -> None:
        with self._lock:
            if self._ticker is not None and self._ticker.is_alive():
                return
            self._ticker_stop.clear()
            self._ticker = threading.Thread(
                target=self._tick_loop, name="devicewatch-watchdog",
                daemon=True)
            self._ticker.start()

    def _tick_loop(self) -> None:
        try:
            interval = float(os.environ.get("BCP_WATCHDOG_INTERVAL", "1"))
        except ValueError:
            interval = 1.0
        interval = max(0.05, interval)
        while not self._ticker_stop.wait(interval):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — the ticker must survive
                pass

    def stop_ticker(self) -> None:
        self._ticker_stop.set()
        with self._lock:
            t, self._ticker = self._ticker, None
        if t is not None:
            t.join(timeout=5)


WATCHDOG = Watchdog(auto_ticker=True)


def _collect_watchdog():
    """Scrape-time evaluation: a /metrics pull re-checks every subsystem
    (the gauges/counters are native families, set inside check())."""
    WATCHDOG.check()
    return []


tm.register_collector("devicewatch_watchdog", _collect_watchdog)


# ---------------------------------------------------------------------------
# gettpuinfo's "device" section
# ---------------------------------------------------------------------------


def snapshot() -> dict:
    """The device-lane monitor's full state: per-program compile/shape
    accounting (+ first-compile cost analysis), transfer totals, the
    profiler state, unattributed compile time, and the watchdog."""
    with _LOCK:
        programs = {name: pw for name, pw in sorted(_PROGRAMS.items())}
        unattr = dict(_UNATTRIBUTED)
    return {
        "programs": {name: pw.snapshot() for name, pw in programs.items()},
        "transfer_bytes": transfer_snapshot(),
        "unattributed_compiles": {
            "compile_s": round(unattr["compile_s"], 4),
            "events": unattr["events"],
        },
        "compilation_cache": compile_cache_snapshot(),
        "profiler": profile_snapshot(),
        "watchdog": WATCHDOG.snapshot(),
    }


def reset() -> None:
    """Test isolation: drop program watches, transfer tallies, and
    watchdog registrations (the global families live in the telemetry
    registry and are zeroed by telemetry.reset())."""
    with _LOCK:
        _PROGRAMS.clear()
        _TRANSFERS.clear()
        _UNATTRIBUTED["compile_s"] = 0.0
        _UNATTRIBUTED["events"] = 0
        _COMPILE_CACHE["events"].clear()
    with WATCHDOG._lock:
        WATCHDOG._entries.clear()
        WATCHDOG._beat_totals.clear()
