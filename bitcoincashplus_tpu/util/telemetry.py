"""Unified telemetry — process-global metrics registry + pipeline span tracer.

Every performance claim before this layer was projection-grade: the node's
instrumentation was a patchwork of ad-hoc ``STATS`` dataclasses and one-off
``snapshot()`` methods, aggregatable only by hand, with no latency
distributions and no way to see where wall-clock goes inside the pipelined
settle horizon. This module is the single aggregation surface:

- **Metrics registry** (``REGISTRY``): counters, gauges, and fixed-bucket
  latency histograms with p50/p90/p99 estimation, grouped into labeled
  families (Prometheus data model). Hot layers create their families at
  import time and record per-batch/per-block/per-tx — never per-sig.
  Modules that already keep their own counters (ops/ecdsa_batch.STATS,
  ops/dispatch breakers, sigcache, the pipeline stats, connman's
  net_stats) are migrated onto the registry via **collectors**: scrape-time
  callbacks that project the live state into families, so ``getmetrics``
  and ``/metrics`` see one namespace while ``gettpuinfo`` keeps its
  established shape as a thin view over the same sources.

- **Span tracer** (``TRACER``): ``with span("block.scan", height=h):``
  context managers record completed spans into a bounded ring buffer with
  thread + correlation ids; nested spans carry parent links, and a
  correlation context can be handed across the supervised-dispatch thread
  boundary (``trace_context()`` at enqueue, ``parent=ctx`` at settle) so a
  batch settled on another thread still traces back to the block that
  dispatched it. Export is Chrome-trace/perfetto JSON (``chrome_trace()``,
  ``dump()``; surfaced via the ``dumptrace`` RPC and the ``-tracefile``
  shutdown hook).

Gating: ``-telemetry=off|counters|trace`` (env ``BCP_TELEMETRY`` seeds the
default for subprocesses). ``off`` turns every record call into a cheap
flag check; ``counters`` (default) enables the registry with a
bench-proven overhead budget (< 2 % on the import_pipeline corpus —
bench.py telemetry_overhead / BENCH_r06.json); ``trace`` additionally
records spans.

Metric naming scheme: ``bcp_<subsystem>_<what>[_<unit>]`` — e.g.
``bcp_dispatch_latency_seconds{site="ecdsa",path="device"}``,
``bcp_pipeline_scan_seconds``, ``bcp_mempool_accept_seconds{result=...}``.
Durations are seconds; sizes are lanes/bytes; states are small-int gauges.
"""

from __future__ import annotations

import bisect
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Iterable, Optional, Sequence

MODES = ("off", "counters", "trace")

_MODE: Optional[str] = None  # resolved lazily from BCP_TELEMETRY


def mode() -> str:
    """The active telemetry level. An invalid BCP_TELEMETRY value falls
    back to the default with no error — the -telemetry flag is the
    validated front door (node startup rejects junk)."""
    global _MODE
    if _MODE is None:
        env = os.environ.get("BCP_TELEMETRY", "counters")
        _MODE = env if env in MODES else "counters"
    return _MODE


def set_mode(name: str) -> str:
    """Select the telemetry level; raises ValueError on unknown names
    (node startup turns that into a ConfigError)."""
    global _MODE
    if name not in MODES:
        raise ValueError(
            f"-telemetry={name!r}: unknown level "
            f"(valid: {', '.join(MODES)})"
        )
    _MODE = name
    return name


def metrics_enabled() -> bool:
    return mode() != "off"


def trace_enabled() -> bool:
    return mode() == "trace"


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

# Default latency buckets (seconds): geometric 1-2.5-5 ladder from 100 µs
# to 60 s — wide enough for a device dispatch and a whole-block settle.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Counter:
    """Monotonic counter. inc() is lock-protected — concurrent writers
    (RPC threads, the P2P loop, validation) never lose increments."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not metrics_enabled():
            return
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        if not metrics_enabled():
            return
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        if not metrics_enabled():
            return
        with self._lock:
            self.value += n


class Histogram:
    """Fixed-bucket histogram with Prometheus ``le`` semantics (bucket i
    counts observations <= bounds[i]; the last slot is +Inf overflow) and
    interpolated quantile estimation (the histogram_quantile formula:
    linear within the target bucket)."""

    __slots__ = ("_lock", "bounds", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        bounds = tuple(float(b) for b in buckets)
        if list(bounds) != sorted(bounds) or not bounds:
            raise ValueError(f"histogram buckets must ascend: {buckets!r}")
        self._lock = threading.Lock()
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # +1 = +Inf overflow
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        if not metrics_enabled():
            return
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0 < q <= 1). Rank = q * count; the bucket
        where the cumulative count first reaches the rank is interpolated
        linearly between its bounds. Observations beyond the last finite
        bound clamp to it (Prometheus histogram_quantile behavior). 0.0
        when the histogram is empty."""
        with self._lock:
            counts = list(self.counts)
            total = self.count
        if total <= 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                if i >= len(self.bounds):
                    return self.bounds[-1]  # overflow: clamp to last bound
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                if c <= 0:
                    return hi
                return lo + (hi - lo) * (rank - (cum - c)) / c
        return self.bounds[-1]

    def quantiles(self, qs: Sequence[float] = (0.5, 0.9, 0.99)) -> dict:
        return {f"p{int(q * 100)}": self.quantile(q) for q in qs}


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """One named metric family: a set of children keyed by label values
    (Prometheus data model). An unlabeled family has exactly one child and
    proxies inc/set/observe straight to it."""

    __slots__ = ("name", "help", "type", "labelnames", "_buckets",
                 "_lock", "_children")

    def __init__(self, name: str, typ: str, help: str = "",
                 labels: Sequence[str] = (), buckets=None):
        self.name = name
        self.help = help
        self.type = typ
        self.labelnames = tuple(labels)
        self._buckets = tuple(buckets) if buckets else None
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}
        if not self.labelnames:
            self._children[()] = self._make()

    def _make(self):
        if self.type == "histogram" and self._buckets:
            return Histogram(self._buckets)
        return _TYPES[self.type]()

    def labels(self, **kv):
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make()
            return child

    # unlabeled conveniences
    def inc(self, n: float = 1.0) -> None:
        self._children[()].inc(n)

    def set(self, v: float) -> None:
        self._children[()].set(v)

    def observe(self, v: float) -> None:
        self._children[()].observe(v)

    def quantiles(self, qs=(0.5, 0.9, 0.99)) -> dict:
        return self._children[()].quantiles(qs)

    def samples(self) -> list:
        """[(labels_dict, child), ...] in insertion order."""
        with self._lock:
            items = list(self._children.items())
        return [
            (dict(zip(self.labelnames, key)), child) for key, child in items
        ]

    def _zero(self) -> None:
        with self._lock:
            for key in list(self._children):
                self._children[key] = self._make()
            if not self.labelnames and () not in self._children:
                self._children[()] = self._make()


class Registry:
    """Process-global metric namespace. Families register once (import
    time); ``collectors`` are scrape-time callbacks that project existing
    state objects (STATS dataclasses, breaker registries, per-node caches)
    into families — the migration path for the pre-telemetry snapshot()
    surfaces. Collector exceptions are swallowed per collector (a closed
    node's stale collector must not take /metrics down)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, Family] = {}
        self._collectors: dict[str, Callable[[], Iterable[dict]]] = {}

    def _family(self, name, typ, help, labels, buckets=None) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = Family(
                    name, typ, help, labels, buckets)
            elif fam.type != typ or fam.labelnames != tuple(labels):
                raise ValueError(
                    f"metric {name!r} re-registered as {typ}{tuple(labels)} "
                    f"(was {fam.type}{fam.labelnames})")
            return fam

    def counter(self, name, help="", labels=()) -> Family:
        return self._family(name, "counter", help, labels)

    def gauge(self, name, help="", labels=()) -> Family:
        return self._family(name, "gauge", help, labels)

    def histogram(self, name, help="", labels=(), buckets=None) -> Family:
        return self._family(name, "histogram", help, labels, buckets)

    def register_collector(self, name: str, fn: Callable) -> None:
        """fn() -> iterable of {"name", "type", "help", "samples":
        [(labels_dict, value), ...]} — counter/gauge families only.
        Re-registering a name replaces the previous collector (a fresh
        node supersedes a closed one's closures)."""
        with self._lock:
            self._collectors[name] = fn

    def unregister_collector(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    def reset(self) -> None:
        """Zero every registered family's samples (test isolation).
        Families and collectors SURVIVE — module-level family handles must
        keep pointing at live, registered metrics."""
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            fam._zero()

    def _collected(self) -> list[dict]:
        with self._lock:
            collectors = list(self._collectors.items())
        out = []
        for _name, fn in collectors:
            try:
                out.extend(fn())
            except Exception:  # noqa: BLE001 — scrape must survive one bad source
                continue
        return out

    def snapshot(self) -> dict:
        """getmetrics RPC body: every family (native + collected), with
        histogram bucket counts and p50/p90/p99 estimates inline."""
        with self._lock:
            fams = list(self._families.values())
        out = {}
        for fam in fams:
            values = []
            for labels, child in fam.samples():
                if fam.type == "histogram":
                    values.append({
                        "labels": labels,
                        "count": child.count,
                        "sum": round(child.sum, 9),
                        "buckets": dict(zip(
                            [str(b) for b in child.bounds] + ["+Inf"],
                            child.counts)),
                        **{k: round(v, 9)
                           for k, v in child.quantiles().items()},
                    })
                else:
                    values.append({"labels": labels, "value": child.value})
            out[fam.name] = {"type": fam.type, "help": fam.help,
                             "values": values}
        for item in self._collected():
            out[item["name"]] = {
                "type": item.get("type", "gauge"),
                "help": item.get("help", ""),
                "values": [{"labels": dict(labels), "value": value}
                           for labels, value in item.get("samples", ())],
            }
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition (version 0.0.4) over every family,
        native and collected."""
        lines: list[str] = []

        def header(name, typ, help):
            if help:
                lines.append(f"# HELP {name} {_escape_help(help)}")
            lines.append(f"# TYPE {name} {typ}")

        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            header(fam.name, fam.type, fam.help)
            for labels, child in fam.samples():
                if fam.type == "histogram":
                    cum = 0
                    for b, c in zip(child.bounds, child.counts):
                        cum += c
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_label_str(labels, le=_fmt(b))} {cum}")
                    cum += child.counts[-1]
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_label_str(labels, le='+Inf')} {cum}")
                    lines.append(
                        f"{fam.name}_sum{_label_str(labels)}"
                        f" {_fmt(child.sum)}")
                    lines.append(
                        f"{fam.name}_count{_label_str(labels)}"
                        f" {child.count}")
                else:
                    lines.append(
                        f"{fam.name}{_label_str(labels)}"
                        f" {_fmt(child.value)}")
        for item in self._collected():
            header(item["name"], item.get("type", "gauge"),
                   item.get("help", ""))
            for labels, value in item.get("samples", ()):
                lines.append(
                    f"{item['name']}{_label_str(dict(labels))}"
                    f" {_fmt(value)}")
        return "\n".join(lines) + "\n"


def _fmt(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(labels: dict, **extra) -> str:
    items = {**labels, **extra}
    if not items:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in items.items())
    return "{" + inner + "}"


def flat_families(prefix: str, d: dict, typ: str = "gauge",
                  help: str = "") -> list[dict]:
    """Project a flat numeric dict (the shape every pre-telemetry
    snapshot() returns) into one single-sample family per key — the
    collector-side migration helper. Non-numeric values are skipped;
    nested dicts are flattened one level with ``_`` joins."""
    out = []
    for k, v in d.items():
        if isinstance(v, bool) or v is None:
            continue
        if isinstance(v, dict):
            for k2, v2 in v.items():
                if isinstance(v2, (int, float)) and not isinstance(v2, bool):
                    out.append({
                        "name": f"{prefix}_{k}_{k2}", "type": typ,
                        "help": help,
                        "samples": [({}, float(v2))],
                    })
            continue
        if isinstance(v, (int, float)):
            out.append({"name": f"{prefix}_{k}", "type": typ, "help": help,
                        "samples": [({}, float(v))]})
    return out


REGISTRY = Registry()


def counter(name, help="", labels=()) -> Family:
    return REGISTRY.counter(name, help, labels)


def gauge(name, help="", labels=()) -> Family:
    return REGISTRY.gauge(name, help, labels)


def histogram(name, help="", labels=(), buckets=None) -> Family:
    return REGISTRY.histogram(name, help, labels, buckets)


def register_collector(name: str, fn: Callable) -> None:
    REGISTRY.register_collector(name, fn)


# ---------------------------------------------------------------------------
# Span tracer
# ---------------------------------------------------------------------------

_SPANS_CAP = int(os.environ.get("BCP_TRACE_SPANS", "65536"))


class _NullSpan:
    """The no-op span returned when tracing is off — one shared instance,
    no allocation on the hot path."""

    __slots__ = ()
    corr = None
    span_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "args", "corr", "span_id", "parent",
                 "_t0")

    def __init__(self, tracer, name, args, corr, span_id, parent):
        self._tracer = tracer
        self.name = name
        self.args = args
        self.corr = corr
        self.span_id = span_id
        self.parent = parent
        self._t0 = 0.0

    def __enter__(self):
        self._tracer._stack().append(self)
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.monotonic()
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        self._tracer._record(self, self._t0, t1)
        return False


class Tracer:
    """Bounded ring buffer of completed spans, Chrome-trace export.

    Correlation model: every top-level span starts a fresh correlation id;
    nested spans inherit it and link to their enclosing span via
    ``parent``. ``context()`` captures (corr, span_id) of the active span
    so work handed to another thread (the supervised-dispatch settle, a
    packer flush) can open its spans with ``parent=ctx`` and stay on the
    same correlation chain — the trace viewer stitches the block's scan
    and its device settle back together across threads."""

    def __init__(self, capacity: int = _SPANS_CAP):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._epoch = time.monotonic()
        self.recorded = 0  # total ever recorded (dropped = recorded - len)

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str, parent: Optional[tuple] = None, **args):
        """Context manager recording one complete ('X') span. ``parent``
        is a context() capture for cross-thread correlation; otherwise the
        enclosing span on this thread (if any) is the parent."""
        if not trace_enabled():
            return _NULL_SPAN
        sid = next(self._ids)
        if parent is not None:
            corr, parent_id = parent
        else:
            stack = self._stack()
            if stack:
                corr, parent_id = stack[-1].corr, stack[-1].span_id
            else:
                corr, parent_id = sid, None
        return _Span(self, name, args, corr, sid, parent_id)

    def context(self) -> Optional[tuple]:
        """(corr, span_id) of this thread's active span, or None — the
        cross-thread correlation handoff token."""
        if not trace_enabled():
            return None
        stack = self._stack()
        if not stack:
            return None
        top = stack[-1]
        return (top.corr, top.span_id)

    def current_corr(self) -> Optional[int]:
        """Correlation id of the active span (the -logjson stamp)."""
        stack = getattr(self._local, "stack", None)
        return stack[-1].corr if stack else None

    def instant(self, name: str, **args) -> None:
        """One instant ('i') event — unwinds, breaker trips."""
        if not trace_enabled():
            return
        now = time.monotonic()
        ctx = self.context()
        ev = {
            "name": name, "ph": "i", "s": "t",
            "ts": round((now - self._epoch) * 1e6, 1),
            "pid": os.getpid(), "tid": threading.get_ident(),
            "args": dict(args),
        }
        if ctx is not None:
            ev["args"]["corr"] = ctx[0]
        with self._lock:
            self._events.append(ev)
            self.recorded += 1

    def _record(self, span: _Span, t0: float, t1: float) -> None:
        args = dict(span.args)
        args["corr"] = span.corr
        args["span_id"] = span.span_id
        if span.parent is not None:
            args["parent"] = span.parent
        ev = {
            "name": span.name, "ph": "X",
            "ts": round((t0 - self._epoch) * 1e6, 1),
            "dur": round((t1 - t0) * 1e6, 1),
            "pid": os.getpid(), "tid": threading.get_ident(),
            "args": args,
        }
        with self._lock:
            self._events.append(ev)
            self.recorded += 1

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.recorded = 0

    def stats(self) -> dict:
        with self._lock:
            buffered = len(self._events)
            recorded = self.recorded
        return {"recorded": recorded, "buffered": buffered,
                "dropped": recorded - buffered,
                "capacity": self._events.maxlen}

    def chrome_trace(self) -> dict:
        """Chrome-trace/perfetto JSON object (load at ui.perfetto.dev or
        chrome://tracing)."""
        return {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {"producer": "bitcoincashplus-tpu telemetry"},
        }

    def dump(self, path: str) -> int:
        """Write the trace JSON; returns the number of events written."""
        trace = self.chrome_trace()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(trace, f)
        return len(trace["traceEvents"])


TRACER = Tracer()


def span(name: str, parent: Optional[tuple] = None, **args):
    return TRACER.span(name, parent=parent, **args)


def trace_context() -> Optional[tuple]:
    return TRACER.context()


def current_corr() -> Optional[int]:
    return TRACER.current_corr()


def instant(name: str, **args) -> None:
    TRACER.instant(name, **args)


def reset() -> None:
    """Test isolation: zero every family, drop buffered spans, and
    re-read the mode from env. Families and collectors survive (module-
    level handles keep pointing at registered metrics)."""
    global _MODE
    _MODE = None
    REGISTRY.reset()
    TRACER.clear()
