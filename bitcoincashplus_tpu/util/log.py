"""Category-gated logging — the LogPrintf / LogPrint(category, ...) system.

Reference: src/util.cpp (LogPrintf, LogPrint, LogAcceptCategory,
OpenDebugLog, fPrintToConsole). `-debug=<cat>` gates category logs;
`-debug=1`/`-debug=all` enables everything. Unconditional logs
(log_printf) always reach debug.log once initialized.

Categories used in this framework (superset of the reference's that apply):
  net, mempool, rpc, bench, db, validation, tpu

Structured mode (`-logjson`): each record is one JSON object per line
(`{"ts", "msg", "cat", "corr"}`) instead of the classic text line. `corr`
is the active telemetry span's correlation id (util/telemetry) when span
tracing is on — logs and -tracefile dumps cross-reference through it, so
"which block's settle emitted this warning" is a join, not a guess.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import IO, Iterable, Optional

_lock = threading.Lock()
_logfile: Optional[IO[str]] = None
_categories: set[str] = set()
_all_categories = False
_print_to_console = False
_json_mode = False
_started = time.time()


def log_init(logfile_path: Optional[str] = None,
             categories: Iterable[str] = (),
             print_to_console: bool = False,
             json_mode: bool = False) -> None:
    """InitLogging + OpenDebugLog. Safe to call more than once (tests)."""
    global _logfile, _all_categories, _print_to_console, _json_mode
    with _lock:
        if _logfile is not None:
            try:
                _logfile.close()
            except OSError:
                pass
            _logfile = None
        _categories.clear()
        _all_categories = False
        _print_to_console = print_to_console
        _json_mode = json_mode
        for cat in categories:
            if cat in ("1", "all"):
                _all_categories = True
            elif cat.startswith("-") or cat == "0":
                pass  # -debug=0 / exclusion: keep disabled
            else:
                _categories.add(cat)
        if logfile_path:
            os.makedirs(os.path.dirname(logfile_path) or ".", exist_ok=True)
            _logfile = open(logfile_path, "a", buffering=1)


def log_accept_category(category: str) -> bool:
    """LogAcceptCategory (src/util.cpp)."""
    return _all_categories or category in _categories


def _emit(line: str, category: Optional[str] = None) -> None:
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    if _json_mode:
        rec = {"ts": stamp, "msg": line}
        if category is not None:
            rec["cat"] = category
        try:
            from . import telemetry

            corr = telemetry.current_corr()
            if corr is not None:
                rec["corr"] = corr
        except Exception:  # telemetry must never take logging down
            pass
        out = json.dumps(rec) + "\n"
    else:
        out = f"{stamp} {line}\n"
    with _lock:
        if _logfile is not None:
            _logfile.write(out)
        if _print_to_console or _logfile is None:
            sys.stderr.write(out)
            sys.stderr.flush()


def log_printf(msg: str, *args) -> None:
    """LogPrintf — unconditional."""
    _emit(msg % args if args else msg)


def log_print(category: str, msg: str, *args) -> None:
    """LogPrint(category, ...) — emitted only when -debug=<category>."""
    if log_accept_category(category):
        _emit(msg % args if args else msg, category=category)


def uptime() -> int:
    """Seconds since process logging start — `uptime` RPC backend."""
    return int(time.time() - _started)
