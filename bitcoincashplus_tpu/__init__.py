"""bitcoincashplus_tpu — a TPU-native full-node framework.

A from-scratch re-design of the capabilities of ``grospy/bitcoincashplus``
(a Bitcoin-Core-lineage full node; see SURVEY.md for the layer map) built
TPU-first on JAX / XLA / Pallas / pjit:

- consensus/  : params, serialization, tx/block primitives, Merkle, PoW rules
                (reference: src/primitives/, src/consensus/, src/pow.cpp)
- crypto/     : CPU crypto reference paths (sha256d, ripemd160, secp256k1 scalar)
                (reference: src/crypto/, src/secp256k1/)
- ops/        : Pallas/jnp TPU kernels (SHA-256d, Merkle tree-reduce, batch ECDSA)
- parallel/   : device mesh, shard_map nonce sharding, dispatch/batching layer
                (reference analogue: src/checkqueue.h CCheckQueue)
- validation/ : chainstate engine — ConnectBlock/ActivateBestChain/coins views
                (reference: src/validation.cpp, src/coins.*)
- store/      : block files + sqlite-backed index/UTXO (reference: src/txdb.*,
                src/dbwrapper.* over LevelDB)
- mempool/    : ancestor-feerate mempool (reference: src/txmempool.*)
- mining/     : block assembler + extranonce (reference: src/miner.cpp)
- p2p/        : asyncio wire protocol (reference: src/net.*, src/net_processing.*)
- rpc/        : JSON-RPC parity surface (reference: src/rpc/, src/httpserver.*)
- node/       : init/flags/logging/scheduler; the --tpu flag (reference: src/init.*)
- cli/        : bcpd / bcp-cli entry points (reference: src/bitcoind.cpp,
                src/bitcoin-cli.cpp)
- native/     : C++ hot-path CPU fallbacks loaded via ctypes
"""

__version__ = "0.1.0"
