"""RPC method table + error model.

Reference: src/rpc/server.cpp (CRPCTable::appendCommand / execute),
src/rpc/protocol.h (the RPC error-code enum — same numeric values here).
"""

from __future__ import annotations

from typing import Callable

# src/rpc/protocol.h
RPC_MISC_ERROR = -1
RPC_TYPE_ERROR = -3
RPC_INVALID_ADDRESS_OR_KEY = -5
RPC_OUT_OF_MEMORY = -7
RPC_INVALID_PARAMETER = -8
RPC_DATABASE_ERROR = -20
RPC_DESERIALIZATION_ERROR = -22
RPC_VERIFY_ERROR = -25
RPC_VERIFY_REJECTED = -26
RPC_VERIFY_ALREADY_IN_CHAIN = -27
RPC_IN_WARMUP = -28
RPC_METHOD_NOT_FOUND = -32601
RPC_INVALID_REQUEST = -32600
RPC_PARSE_ERROR = -32700
RPC_INTERNAL_ERROR = -32603


class RPCError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


# name -> handler(node, params: list) -> json-serializable result
RPC_METHODS: dict[str, Callable] = {}


def rpc_method(name: str):
    def deco(fn):
        RPC_METHODS[name] = fn
        return fn
    return deco


def require_params(params: list, n_min: int, n_max: int, usage: str):
    if not (n_min <= len(params) <= n_max):
        raise RPCError(RPC_INVALID_PARAMETER, usage)


def param_hash(params: list, i: int) -> bytes:
    """Parse a hex block/tx hash parameter into wire order (little-endian)."""
    from ..consensus.serialize import hex_to_hash

    try:
        h = hex_to_hash(params[i])
    except Exception:
        raise RPCError(RPC_INVALID_PARAMETER,
                       f"parameter {i + 1} must be a 64-character hex hash") from None
    if len(h) != 32:
        raise RPCError(RPC_INVALID_PARAMETER,
                       f"parameter {i + 1} must be a 64-character hex hash")
    return h
