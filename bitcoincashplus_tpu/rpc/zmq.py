"""ZMQ block/tx notifications — a pure-Python ZMTP 3.0 PUB socket.

Reference: src/zmq/zmqpublishnotifier.cpp (CZMQAbstractPublishNotifier:
hashblock / hashtx / rawblock / rawtx topics over a PUB socket). The
environment has no libzmq/pyzmq, so this speaks the ZMTP 3.0 wire
protocol directly (greeting, NULL-mechanism READY handshake, framed
messages) — real ZMQ SUB clients (pyzmq, libzmq) can connect to it.

Publisher semantics match PUB: per-subscriber topic filters learned from
SUBSCRIBE (0x01) / CANCEL (0x00) messages, prefix matching, silent drop
for slow/dead subscribers. Each notification is a 3-part message
[topic, body, LE32 sequence] exactly like the reference.
"""

from __future__ import annotations

import asyncio
import struct
import threading
from typing import Optional

from ..util.log import log_print, log_printf

_SIGNATURE = b"\xff" + b"\x00" * 8 + b"\x7f"


def _greeting(as_server: bool = False) -> bytes:
    # 64-byte ZMTP 3.0 greeting: signature, version, mechanism, as-server
    return (_SIGNATURE + bytes([3, 0])
            + b"NULL" + b"\x00" * 16
            + (b"\x01" if as_server else b"\x00")
            + b"\x00" * 31)


def _command(name: bytes, body: bytes) -> bytes:
    payload = bytes([len(name)]) + name + body
    if len(payload) <= 255:
        return bytes([0x04, len(payload)]) + payload
    return b"\x06" + struct.pack(">Q", len(payload)) + payload


def _frame(body: bytes, more: bool) -> bytes:
    flags = 0x01 if more else 0x00
    if len(body) <= 255:
        return bytes([flags, len(body)]) + body
    return bytes([flags | 0x02]) + struct.pack(">Q", len(body)) + body


class _Subscriber:
    def __init__(self, writer):
        self.writer = writer
        self.topics: set[bytes] = set()

    def wants(self, topic: bytes) -> bool:
        return any(topic.startswith(t) for t in self.topics)


class ZMQPublisher:
    """One PUB endpoint serving all enabled topics (the reference binds one
    socket per -zmqpub* arg; a shared socket is protocol-equivalent for
    subscribers, which filter by topic)."""

    # per-subscriber high-water mark: past this buffered-byte count new
    # messages are dropped for that subscriber (ZMQ_SNDHWM role)
    SNDHWM_BYTES = 4 * 1024 * 1024

    def __init__(self, node, port: int, topics: set[str],
                 host: str = "127.0.0.1"):
        self.node = node
        self.host = host
        self.port = port
        self.topics = {t.encode() for t in topics}
        self.sequences = {t.encode(): 0 for t in topics}
        self._subs: list[_Subscriber] = []
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._start_error: Optional[BaseException] = None

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="zmq",
                                        daemon=True)
        self._thread.start()
        if not self._started.wait(15):
            raise RuntimeError("ZMQ publisher failed to start")
        if self._start_error is not None:
            raise RuntimeError(
                f"ZMQ publisher bind failed on {self.host}:{self.port}: "
                f"{self._start_error}") from self._start_error
        log_printf("ZMQ publisher on tcp://%s:%d topics=%s",
                   self.host, self.port,
                   ",".join(sorted(t.decode() for t in self.topics)))

    def _run(self) -> None:
        self.loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self.loop)

        async def _serve():
            self._server = await asyncio.start_server(
                self._on_subscriber, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]

        try:
            self.loop.run_until_complete(_serve())
        except BaseException as e:  # surfaced by start() with the cause
            self._start_error = e
            self._started.set()
            self.loop.close()
            return
        self._started.set()
        self.loop.run_forever()
        self.loop.close()

    def close(self) -> None:
        if self.loop is None:
            return

        def _shutdown():
            for sub in self._subs:
                try:
                    sub.writer.close()
                except Exception:
                    pass
            if self._server is not None:
                self._server.close()
            self.loop.stop()

        self.loop.call_soon_threadsafe(_shutdown)
        if self._thread is not None:
            self._thread.join(10)
        self.loop = None  # publish() after close becomes a no-op

    # -- subscriber handling -------------------------------------------

    async def _on_subscriber(self, reader, writer) -> None:
        sub = _Subscriber(writer)
        try:
            writer.write(_greeting(as_server=True))
            peer_greeting = await reader.readexactly(64)
            # RFC 23: only the signature's first and last byte are fixed —
            # libzmq fills the padding with a ZMTP/1.0 compat length field,
            # so checking the zero bytes would reject real clients
            if peer_greeting[0] != 0xFF or peer_greeting[9] != 0x7F:
                writer.close()
                return
            writer.write(_command(b"READY", b"\x0bSocket-Type\x00\x00\x00\x03PUB"))
            await writer.drain()
            self._subs.append(sub)
            while True:
                flags = (await reader.readexactly(1))[0]
                if flags & 0x02:  # long frame
                    (size,) = struct.unpack(">Q", await reader.readexactly(8))
                else:
                    size = (await reader.readexactly(1))[0]
                body = await reader.readexactly(size) if size else b""
                if flags & 0x04:
                    continue  # commands (READY etc.) — nothing to do
                if body[:1] == b"\x01":
                    sub.topics.add(body[1:])
                elif body[:1] == b"\x00":
                    sub.topics.discard(body[1:])
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            if sub in self._subs:
                self._subs.remove(sub)
            try:
                writer.close()
            except Exception:
                pass

    # -- publishing -----------------------------------------------------

    def publish(self, topic: str, body: bytes) -> None:
        """Send [topic, body, seq] to interested subscribers (thread-safe;
        callable from validation/RPC threads)."""
        t = topic.encode()
        loop = self.loop  # snapshot: close() clears it concurrently
        if t not in self.topics or loop is None:
            return
        seq = self.sequences[t]
        self.sequences[t] = (seq + 1) & 0xFFFFFFFF
        wire = (_frame(t, more=True) + _frame(body, more=True)
                + _frame(struct.pack("<I", seq), more=False))

        def _do():
            for sub in list(self._subs):
                if not sub.wants(t):
                    continue
                try:
                    transport = sub.writer.transport
                    # ZMQ_SNDHWM analogue: a stalled-but-alive subscriber
                    # gets messages DROPPED, not buffered without bound
                    if (transport is not None and
                            transport.get_write_buffer_size()
                            > self.SNDHWM_BYTES):
                        continue
                    sub.writer.write(wire)
                except Exception:
                    pass  # PUB drops to dead subscribers silently
        try:
            loop.call_soon_threadsafe(_do)
        except RuntimeError:
            pass  # loop closed by a concurrent shutdown


# -- test/client helper: a minimal ZMTP SUB client ----------------------


class ZMQSubscriber:
    """Blocking SUB client for tests and tooling (what a pyzmq SUB socket
    would do): connect, subscribe to topics, recv_multipart()."""

    def __init__(self, port: int, topics: list[bytes], timeout: float = 30.0):
        import socket as _socket

        self.sock = _socket.create_connection(("127.0.0.1", port),
                                              timeout=timeout)
        self.sock.sendall(_greeting(as_server=False))
        self._recv_exact(64)  # their greeting
        self.sock.sendall(_command(b"READY", b"\x0bSocket-Type\x00\x00\x00\x03SUB"))
        self._read_frame()  # their READY
        for t in topics:
            self.sock.sendall(_frame(b"\x01" + t, more=False))

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("publisher closed")
            buf += chunk
        return buf

    def _read_frame(self) -> tuple[int, bytes]:
        flags = self._recv_exact(1)[0]
        if flags & 0x02:
            (size,) = struct.unpack(">Q", self._recv_exact(8))
        else:
            size = self._recv_exact(1)[0]
        return flags, (self._recv_exact(size) if size else b"")

    def recv_multipart(self) -> list[bytes]:
        parts = []
        while True:
            flags, body = self._read_frame()
            if flags & 0x04:
                continue  # skip commands
            parts.append(body)
            if not flags & 0x01:
                return parts

    def close(self) -> None:
        self.sock.close()
