"""Mining RPCs.

Reference: src/rpc/mining.cpp (getblocktemplate :~350, submitblock,
generatetoaddress :~200, getmininginfo, getnetworkhashps,
prioritisetransaction). The nonce search behind generatetoaddress is the
TPU sweep (ops/miner), not the reference's scalar while-loop (SURVEY.md
§4.5) — the RPC surface is identical.
"""

from __future__ import annotations

from ..consensus.block import CBlock
from ..consensus.serialize import hash_to_hex
from ..mining.generate import MAX_TRIES_DEFAULT
from ..wallet.keys import address_to_script
from .blockchain import difficulty_from_bits
from .registry import (
    RPC_DESERIALIZATION_ERROR,
    RPC_INVALID_ADDRESS_OR_KEY,
    RPCError,
    require_params,
    rpc_method,
)


@rpc_method("generatetoaddress")
def generatetoaddress(node, params):
    require_params(params, 2, 3, "generatetoaddress nblocks \"address\" ( maxtries )")
    n_blocks = int(params[0])
    script = address_to_script(params[1], node.params)
    if script is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Error: Invalid address or script")
    max_tries = int(params[2]) if len(params) > 2 else MAX_TRIES_DEFAULT
    hashes = node.generate_to_script(script, n_blocks, max_tries)
    return [hash_to_hex(h) for h in hashes]


@rpc_method("getblocktemplate")
def getblocktemplate(node, params):
    """getblocktemplate (src/rpc/mining.cpp:~350) — BIP22 shape. A
    template_request with 'longpollid' blocks (~60s max) until the tip or
    the mempool changes, like the reference's checktxtime/hashWatchedChain
    wait loop."""
    request = params[0] if params and isinstance(params[0], dict) else {}
    if request.get("mode") == "proposal":
        # BIP22 proposal mode: validate a block against the current tip
        # without submitting it (TestBlockValidity; rpc/mining.cpp)
        try:
            block = CBlock.from_bytes(bytes.fromhex(request.get("data", "")))
        except Exception:
            raise RPCError(RPC_DESERIALIZATION_ERROR,
                           "Block decode failed") from None
        with node.cs_main:
            cs = node.chainstate
            if block.header.hash_prev_block != cs.tip().hash:
                return "inconclusive-not-best-prevblk"
            from ..validation.chainstate import BlockValidationError

            # proposal re-validation rides the signature service: any
            # non-mempool transactions in the proposed block settle
            # through the shared lanes first, so TestBlockValidity's
            # script pass is sigcache hits (serving/sigservice).
            # require_pow=False: proposals are legitimately unmined and
            # the RPC surface is local/authenticated; the merkle gate
            # inside prewarm still applies
            if getattr(node, "sigservice", None) is not None:
                from ..serving import prewarm_block_sigs

                prewarm_block_sigs(node, block, require_pow=False)
            try:
                cs.test_block_validity(block)
            except BlockValidationError as e:
                return e.reason
        return None
    longpollid = request.get("longpollid")
    if longpollid:
        def changed():
            tip = node.chainstate.tip()
            cur = hash_to_hex(tip.hash) + f"{node.mempool.sequence}"
            return True if cur != longpollid else None

        node.wait_for(changed, timeout=60.0)
    with node.cs_main:
        return _template_json(node)


getblocktemplate.no_cs_main = True


def _template_json(node):
    tmpl = node.assembler().create_new_block(script_pubkey=b"\x51")  # OP_TRUE placeholder
    block = tmpl.block
    cs = node.chainstate
    tip = cs.tip()
    txs = []
    txid_to_pos = {}
    for i, tx in enumerate(block.vtx[1:], start=1):
        txid_to_pos[tx.txid] = i
        depends = sorted(
            txid_to_pos[vin.prevout.hash]
            for vin in tx.vin
            if vin.prevout.hash in txid_to_pos
        )
        txs.append({
            "data": tx.serialize().hex(),
            "txid": tx.txid_hex,
            "hash": tx.txid_hex,
            "depends": depends,
            "fee": tmpl.fees[i],
            "sigops": 0,
        })
    return {
        "capabilities": ["proposal"],
        "version": block.header.version,
        "previousblockhash": hash_to_hex(tip.hash),
        "transactions": txs,
        "coinbaseaux": {"flags": ""},
        "coinbasevalue": block.vtx[0].total_output_value(),
        "longpollid": hash_to_hex(tip.hash) + f"{node.mempool.sequence}",
        "target": f"{tmpl.target:064x}",
        "mintime": tip.get_median_time_past() + 1,
        "mutable": ["time", "transactions", "prevblock"],
        "noncerange": "00000000ffffffff",
        "sigoplimit": node.params.max_block_sigops,
        "sizelimit": node.params.max_block_size,
        "curtime": block.header.time,
        "bits": f"{block.header.bits:08x}",
        "height": tmpl.height,
    }


@rpc_method("submitblock")
def submitblock(node, params):
    require_params(params, 1, 2, "submitblock \"hexdata\" ( \"dummy\" )")
    try:
        block = CBlock.from_bytes(bytes.fromhex(params[0]))
    except Exception:
        raise RPCError(RPC_DESERIALIZATION_ERROR, "Block decode failed") from None
    return node.submit_block(block)  # None on success, reason string otherwise


@rpc_method("getmininginfo")
def getmininginfo(node, params):
    cs = node.chainstate
    tip = cs.tip()
    return {
        "blocks": tip.height,
        "currentblocksize": 0,
        "currentblocktx": 0,
        "difficulty": difficulty_from_bits(tip.header.bits),
        "networkhashps": getnetworkhashps(node, []),
        "pooledtx": len(node.mempool),
        "chain": node.params.network,
    }


@rpc_method("getnetworkhashps")
def getnetworkhashps(node, params):
    """GetNetworkHashPS: work over the last nblocks' wall time."""
    n_blocks = int(params[0]) if params else 120
    cs = node.chainstate
    tip = cs.tip()
    if tip is None or tip.height == 0:
        return 0
    n_blocks = min(n_blocks if n_blocks > 0 else tip.height, tip.height)
    first = cs.chain[tip.height - n_blocks]
    time_diff = tip.time - first.time
    if time_diff <= 0:
        return 0
    return (tip.chain_work - first.chain_work) / time_diff


@rpc_method("prioritisetransaction")
def prioritisetransaction(node, params):
    """prioritisetransaction "txid" priority_delta fee_delta — the priority
    delta is accepted-and-ignored (priority was removed from this lineage's
    successor policy); the fee delta (satoshis) feeds mapDeltas."""
    from .registry import require_params

    require_params(params, 3, 3,
                   "prioritisetransaction \"txid\" priority_delta fee_delta")
    from ..consensus.serialize import hex_to_hash

    txid = hex_to_hash(params[0])
    node.mempool.prioritise(txid, int(params[2]))
    return True


@rpc_method("estimatefee")
def estimatefee(node, params):
    """estimatefee (nblocks) — CBlockPolicyEstimator (src/policy/fees.cpp):
    bucketed confirmation tracking with decay; -1 with no data, exactly
    like the reference's cold answer."""
    from ..consensus.tx import COIN

    nblocks = int(params[0]) if params else 1
    est = node.fee_estimator.estimate_fee(max(1, nblocks))
    return -1 if est <= 0 else est / COIN


@rpc_method("estimatesmartfee")
def estimatesmartfee(node, params):
    """estimatesmartfee (conf_target) — honors the target: tries it, then
    widens the horizon, reporting the target that actually answered
    (estimateSmartFee semantics)."""
    from ..consensus.tx import COIN

    nblocks = int(params[0]) if params else 6
    est, answered = node.fee_estimator.estimate_smart_fee(nblocks)
    if est <= 0:
        # smart variant falls back to the relay floor instead of failing
        return {"feerate": node.min_relay_fee_rate / COIN, "blocks": nblocks,
                "errors": ["Insufficient data or no feerate found"]}
    return {"feerate": est / COIN, "blocks": answered}


def _tip_json(node):
    tip = node.chainstate.tip()
    return {"hash": hash_to_hex(tip.hash), "height": tip.height}


@rpc_method("waitfornewblock")
def waitfornewblock(node, params):
    """waitfornewblock ( timeout_ms ) — block until the tip changes."""
    # Core semantics: timeout 0 (or absent) = wait indefinitely
    timeout = (int(params[0]) / 1000) if params and params[0] else float("inf")
    with node.cs_main:
        start = node.chainstate.tip().hash

    node.wait_for(
        lambda: _tip_json(node) if node.chainstate.tip().hash != start else None,
        timeout,
    )
    with node.cs_main:
        return _tip_json(node)


waitfornewblock.no_cs_main = True


@rpc_method("waitforblock")
def waitforblock(node, params):
    """waitforblock "hash" ( timeout_ms )"""
    require_params(params, 1, 2, "waitforblock \"blockhash\" ( timeout )")
    from ..consensus.serialize import hex_to_hash

    target = hex_to_hash(params[0])
    timeout = (int(params[1]) / 1000) if len(params) > 1 and params[1] else float("inf")

    def reached():
        cs = node.chainstate
        idx = cs.block_index.get(target)
        if idx is not None and cs.chain[idx.height] is idx:
            return _tip_json(node)
        return None

    node.wait_for(reached, timeout)
    with node.cs_main:
        return _tip_json(node)


waitforblock.no_cs_main = True


@rpc_method("waitforblockheight")
def waitforblockheight(node, params):
    """waitforblockheight height ( timeout_ms )"""
    require_params(params, 1, 2, "waitforblockheight height ( timeout )")
    height = int(params[0])
    timeout = (int(params[1]) / 1000) if len(params) > 1 and params[1] else float("inf")
    node.wait_for(
        lambda: _tip_json(node) if node.chainstate.tip().height >= height else None,
        timeout,
    )
    with node.cs_main:
        return _tip_json(node)


waitforblockheight.no_cs_main = True


@rpc_method("estimatepriority")
def estimatepriority(node, params):
    """Deprecated priority estimator — always -1, like the reference's
    data-less answer (priority was removed from fee logic)."""
    return -1


@rpc_method("estimatesmartpriority")
def estimatesmartpriority(node, params):
    nblocks = int(params[0]) if params else 6
    return {"priority": -1, "blocks": nblocks,
            "errors": ["Insufficient data or no priority found"]}
