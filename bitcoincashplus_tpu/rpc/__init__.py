"""JSON-RPC interface — the capability-parity surface (SURVEY.md §3.1).

Reference: src/rpc/server.cpp (CRPCTable), src/httpserver.cpp,
src/httprpc.cpp, src/rpc/{blockchain,mining,rawtransaction,net,misc}.cpp.
Method names, parameter shapes, and error codes follow the reference; the
transport is Python's stdlib http.server instead of libevent.
"""

from .registry import RPCError, rpc_method, RPC_METHODS  # noqa: F401

# import for registration side effects
from . import blockchain, control, mining, net, rawtransaction, wallet  # noqa: F401,E402
