"""HTTP JSON-RPC server.

Reference: src/httpserver.cpp (StartHTTPServer — libevent evhttp + a worker
queue; here ThreadingHTTPServer gives the same request-per-thread shape),
src/httprpc.cpp (HTTPReq_JSONRPC: basic auth, single + batch requests),
src/rpc/protocol.cpp (GenerateAuthCookie — the `.cookie` file contract that
bitcoin-cli and the functional framework rely on).

All handlers run under node.cs_main — the RPC layer is the reference's
"everything takes cs_main" model, minus the footguns.
"""

from __future__ import annotations

import base64
import json
import os
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..util.log import log_print, log_printf
from .registry import (
    RPC_INTERNAL_ERROR,
    RPC_INVALID_REQUEST,
    RPC_METHOD_NOT_FOUND,
    RPC_PARSE_ERROR,
    RPC_METHODS,
    RPCError,
)

COOKIE_USER = "__cookie__"


def generate_auth_cookie(datadir: str) -> str:
    """GenerateAuthCookie (src/rpc/protocol.cpp): random credential written
    to <datadir>/.cookie as `__cookie__:<hex>`."""
    password = secrets.token_hex(32)
    path = os.path.join(datadir, ".cookie")
    with open(path, "w") as f:
        f.write(f"{COOKIE_USER}:{password}")
    os.chmod(path, 0o600)
    return password


class RPCServer:
    def __init__(self, node, bind: str = "127.0.0.1", port: int = 0):
        self.node = node
        user = node.config.get("rpcuser")
        password = node.config.get("rpcpassword")
        if not (user and password):
            user, password = COOKIE_USER, generate_auth_cookie(node.datadir)
        self._auth = base64.b64encode(f"{user}:{password}".encode()).decode()
        self._httpd = ThreadingHTTPServer((bind, port), _make_handler(self))
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="rpc", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5)
        cookie = os.path.join(self.node.datadir, ".cookie")
        if os.path.exists(cookie):
            os.remove(cookie)

    # -- dispatch -------------------------------------------------------

    def execute(self, request: dict) -> dict:
        """CRPCTable::execute — one JSON-RPC call object to one response."""
        req_id = request.get("id")
        method = request.get("method")
        params = request.get("params") or []
        if not isinstance(method, str) or not isinstance(params, list):
            return _error_obj(req_id, RPC_INVALID_REQUEST, "Invalid Request")
        handler = RPC_METHODS.get(method)
        if handler is None:
            return _error_obj(req_id, RPC_METHOD_NOT_FOUND, "Method not found")
        log_print("rpc", "ThreadRPCServer method=%s", method)
        try:
            if getattr(handler, "no_cs_main", False):
                # blocking handlers (longpoll, waitfor*) manage cs_main
                # themselves so other RPC threads aren't starved
                result = handler(self.node, params)
            else:
                with self.node.cs_main:
                    result = handler(self.node, params)
        except RPCError as e:
            return _error_obj(req_id, e.code, e.message)
        except Exception as e:  # the reference wraps these the same way
            log_printf("RPC internal error in %s: %r", method, e)
            return _error_obj(req_id, RPC_INTERNAL_ERROR, str(e))
        return {"result": result, "error": None, "id": req_id}


def _error_obj(req_id, code: int, message: str) -> dict:
    return {"result": None, "error": {"code": code, "message": message}, "id": req_id}


def _make_handler(server: RPCServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route into our logger
            log_print("rpc", "http: " + fmt, *args)

        def _reply(self, status: int, payload: bytes) -> None:
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            """-rest interface (src/rest.cpp): unauthenticated GET routes,
            enabled by the `rest` config flag; 403 otherwise."""
            from .rest import RestError, handle_rest

            if not server.node.config.get_bool("rest"):
                payload = b"REST interface disabled (enable with -rest)\n"
                self.send_response(403)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                return
            try:
                status, ctype, body = handle_rest(server.node, self.path)
            except RestError as e:
                status, ctype = e.status, "text/plain"
                body = (e.message + "\r\n").encode()
            except Exception as e:  # parity with the POST-side wrapping
                log_printf("REST internal error %s: %r", self.path, e)
                status, ctype, body = 500, "text/plain", b"internal error\r\n"
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            auth = self.headers.get("Authorization", "")
            if auth != f"Basic {server._auth}":
                self.send_response(401)
                self.send_header("WWW-Authenticate", 'Basic realm="jsonrpc"')
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length))
            except (ValueError, json.JSONDecodeError):
                self._reply(500, json.dumps(
                    _error_obj(None, RPC_PARSE_ERROR, "Parse error")).encode())
                return
            if isinstance(body, list):  # JSON-RPC batch
                response = [server.execute(req) for req in body]
            else:
                response = server.execute(body)
            status = 200
            if not isinstance(response, list) and response.get("error"):
                code = response["error"]["code"]
                status = 404 if code == RPC_METHOD_NOT_FOUND else 500
            self._reply(status, json.dumps(response).encode())

    return Handler
