"""Wallet RPCs — src/wallet/rpcwallet.cpp / rpcdump.cpp.

The wallet is loaded lazily on first wallet-RPC use (the reference loads at
init; lazy keeps non-wallet nodes wallet-free). All handlers already hold
cs_main via the server dispatch; wallet state is only touched under it.
"""

from __future__ import annotations

from ..consensus.serialize import hash_to_hex
from ..consensus.tx import COIN
from ..mempool.mempool import MempoolError
from ..wallet.keys import CKey
from ..wallet.wallet import WalletError
from .registry import (
    RPC_INVALID_ADDRESS_OR_KEY,
    RPC_INVALID_PARAMETER,
    RPC_MISC_ERROR,
    RPC_TYPE_ERROR,
    RPCError,
    require_params,
    rpc_method,
)

RPC_WALLET_ERROR = -4
RPC_WALLET_PASSPHRASE_INCORRECT = -14
RPC_WALLET_WRONG_ENC_STATE = -15
RPC_WALLET_UNLOCK_NEEDED = -13


def _wallet(node):
    w = node.load_wallet()
    w.maybe_relock()
    return w


@rpc_method("getnewaddress")
def getnewaddress(node, params):
    require_params(params, 0, 1, "getnewaddress ( \"account\" )")
    try:
        return _wallet(node).get_new_address(
            str(params[0]) if params and params[0] else "")
    except WalletError as e:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED, str(e)) from None


@rpc_method("getbalance")
def getbalance(node, params):
    w = _wallet(node)
    return w.balance(node.chainstate.tip().height) / COIN


@rpc_method("listunspent")
def listunspent(node, params):
    w = _wallet(node)
    tip = node.chainstate.tip().height
    out = []
    for coin in w.available_coins(tip, include_watch_only=True):
        out.append({
            "txid": hash_to_hex(coin.outpoint.hash),
            "vout": coin.outpoint.n,
            "amount": coin.txout.value / COIN,
            "confirmations": tip - coin.height + 1,
            "scriptPubKey": coin.txout.script_pubkey.hex(),
            "spendable": (not w.is_locked
                          and w.can_sign(coin.txout.script_pubkey)),
        })
    return out


@rpc_method("sendtoaddress")
def sendtoaddress(node, params):
    require_params(params, 2, 2, "sendtoaddress \"address\" amount")
    address = params[0]
    amount = int(round(float(params[1]) * COIN))
    if amount <= 0:
        raise RPCError(RPC_INVALID_PARAMETER, "Invalid amount for send")
    w = _wallet(node)
    try:
        tx = w.create_transaction(
            address, amount, node.chainstate.tip().height,
            fee=_wallet_fee(node), enable_forkid=True,
            fee_rate=_wallet_fee(node),
        )
    except WalletError as e:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED, str(e)) from None
    except ValueError as e:
        raise RPCError(RPC_WALLET_ERROR, str(e)) from None
    try:
        node.accept_to_mempool(tx)
    except MempoolError as e:
        raise RPCError(RPC_WALLET_ERROR, f"transaction rejected: {e}") from None
    if node.connman is not None:
        node.connman.relay_tx(tx.txid)
    return tx.txid_hex


@rpc_method("getwalletinfo")
def getwalletinfo(node, params):
    w = _wallet(node)
    tip = node.chainstate.tip().height
    info = {
        "walletname": "wallet.json",
        "balance": w.balance(tip) / COIN,
        "txcount": len(w.coins),
        "keypoolsize": len(w.keys_by_pubkey) or len(w.encrypted_keys),
    }
    if w.is_crypted:
        info["unlocked_until"] = (
            0 if w.is_locked else int(w.unlocked_until)
        )
    if w.hd_seed is not None:
        from ..crypto.hashes import hash160
        from ..wallet.bip32 import ExtKey

        info["hdmasterkeyid"] = hash160(
            ExtKey.from_seed(w.hd_seed).pubkey_bytes()
        ).hex()
    return info


@rpc_method("encryptwallet")
def encryptwallet(node, params):
    require_params(params, 1, 1, "encryptwallet \"passphrase\"")
    w = _wallet(node)
    if w.is_crypted:
        raise RPCError(RPC_WALLET_WRONG_ENC_STATE,
                       "Wallet is already encrypted")
    try:
        w.encrypt(str(params[0]))
    except WalletError as e:
        raise RPCError(RPC_MISC_ERROR, str(e)) from None
    # the reference shuts down after encryptwallet; we just lock
    return ("wallet encrypted; the wallet is now locked — use "
            "walletpassphrase to unlock")


@rpc_method("walletpassphrase")
def walletpassphrase(node, params):
    require_params(params, 2, 2, "walletpassphrase \"passphrase\" timeout")
    w = _wallet(node)
    if not w.is_crypted:
        raise RPCError(RPC_WALLET_WRONG_ENC_STATE,
                       "running with an unencrypted wallet, but "
                       "walletpassphrase was called")
    timeout = float(params[1])
    if timeout <= 0:
        raise RPCError(RPC_INVALID_PARAMETER, "timeout must be positive")
    if not w.unlock(str(params[0]), timeout):
        raise RPCError(RPC_WALLET_PASSPHRASE_INCORRECT,
                       "Error: The wallet passphrase entered was incorrect.")
    return None


@rpc_method("walletlock")
def walletlock(node, params):
    w = _wallet(node)
    if not w.is_crypted:
        raise RPCError(RPC_WALLET_WRONG_ENC_STATE,
                       "running with an unencrypted wallet, but "
                       "walletlock was called")
    w.lock()
    return None


@rpc_method("walletpassphrasechange")
def walletpassphrasechange(node, params):
    require_params(params, 2, 2,
                   "walletpassphrasechange \"oldpassphrase\" \"newpassphrase\"")
    w = _wallet(node)
    if not w.is_crypted:
        raise RPCError(RPC_WALLET_WRONG_ENC_STATE,
                       "running with an unencrypted wallet")
    if not w.change_passphrase(str(params[0]), str(params[1])):
        raise RPCError(RPC_WALLET_PASSPHRASE_INCORRECT,
                       "Error: The wallet passphrase entered was incorrect.")
    return None


@rpc_method("dumpprivkey")
def dumpprivkey(node, params):
    require_params(params, 1, 1, "dumpprivkey \"address\"")
    from ..wallet.keys import address_to_script
    from ..script.script import get_script_ops

    w = _wallet(node)
    if w.is_locked:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED,
                       "Error: Please enter the wallet passphrase with "
                       "walletpassphrase first.")
    spk = address_to_script(params[0], node.params)
    if spk is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Invalid address")
    pkh = list(get_script_ops(spk))[2][1]
    key = w.keys_by_pkh.get(pkh)
    if key is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Private key for address is not known")
    return key.to_wif(node.params)


@rpc_method("importprivkey")
def importprivkey(node, params):
    require_params(params, 1, 2, "importprivkey \"privkey\" ( \"label\" )")
    w = _wallet(node)
    key = CKey.from_wif(params[0], node.params)
    if key is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Invalid private key encoding")
    try:
        w.add_key(key)
    except WalletError as e:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED, str(e)) from None
    node._rescan_wallet()
    return None

@rpc_method("signmessage")
def signmessage(node, params):
    require_params(params, 2, 2, "signmessage \"address\" \"message\"")
    from ..wallet.keys import address_to_script
    from ..wallet.message import sign_message
    from ..script.script import get_script_ops

    w = _wallet(node)
    if w.is_locked:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED,
                       "Error: Please enter the wallet passphrase with "
                       "walletpassphrase first.")
    spk = address_to_script(params[0], node.params)
    if spk is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Invalid address")
    try:
        pkh = list(get_script_ops(spk))[2][1]
    except Exception:
        pkh = None
    if pkh is None or len(pkh) != 20:  # P2SH scripts land here too
        raise RPCError(RPC_TYPE_ERROR, "Address does not refer to key")
    key = w.keys_by_pkh.get(pkh)
    if key is None:
        raise RPCError(RPC_WALLET_ERROR, "Private key not available")
    return sign_message(key, str(params[1]))


def _tx_log_json(node, w, txid: bytes, entry: dict) -> dict:
    """One listtransactions/gettransaction row (rpcwallet.cpp WalletTxToJSON)."""
    tip = node.chainstate.tip().height
    height = entry["height"]
    confirmations = 0 if height < 0 else tip - height + 1
    net = entry["received"] - entry["sent"]
    if entry["is_coinbase"]:
        maturity = node.params.consensus.coinbase_maturity
        category = "generate" if confirmations >= maturity else "immature"
    elif entry["sent"] > 0:
        category = "send"
    else:
        category = "receive"
    out = {
        "txid": hash_to_hex(txid),
        "category": category,
        "amount": net / COIN,
        "confirmations": confirmations,
    }
    if height >= 0:
        idx = node.chainstate.chain[height]
        if idx is not None:
            out["blockhash"] = hash_to_hex(idx.hash)
            out["blocktime"] = idx.header.time
    return out


@rpc_method("listtransactions")
def listtransactions(node, params):
    """listtransactions ( "account" count skip ) — newest first."""
    count = int(params[1]) if len(params) > 1 else 10
    skip = int(params[2]) if len(params) > 2 else 0
    w = _wallet(node)
    entries = list(w.tx_log.items())[::-1][skip:skip + count]
    return [_tx_log_json(node, w, txid, e) for txid, e in entries][::-1]


@rpc_method("gettransaction")
def gettransaction(node, params):
    require_params(params, 1, 1, "gettransaction \"txid\"")
    from ..consensus.serialize import hex_to_hash

    w = _wallet(node)
    txid = hex_to_hash(params[0])
    entry = w.tx_log.get(txid)
    if entry is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Invalid or non-wallet transaction id")
    out = _tx_log_json(node, w, txid, entry)
    out["fee"] = 0.0  # fee tracking requires full input provenance
    out["details"] = [out.copy()]
    return out


def _received_by_spk(w, minconf: int, tip: int) -> dict:
    """spk -> total satoshis received across wallet coins (spent or not),
    rpcwallet.cpp GetReceived semantics: receipts count even if later
    spent, gated on confirmations."""
    out = {}
    for coin in w.coins.values():
        conf = 0 if coin.height < 0 else tip - coin.height + 1
        if conf < minconf:
            continue
        spk = coin.txout.script_pubkey
        out[spk] = out.get(spk, 0) + coin.txout.value
    return out


@rpc_method("getreceivedbyaddress")
def getreceivedbyaddress(node, params):
    require_params(params, 1, 2, "getreceivedbyaddress \"address\" ( minconf )")
    from ..wallet.keys import address_to_script

    spk = address_to_script(params[0], node.params)
    if spk is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Invalid address")
    minconf = int(params[1]) if len(params) > 1 else 1
    w = _wallet(node)
    tip = node.chainstate.tip().height
    return _received_by_spk(w, minconf, tip).get(spk, 0) / COIN


@rpc_method("listreceivedbyaddress")
def listreceivedbyaddress(node, params):
    minconf = int(params[0]) if params else 1
    include_empty = bool(params[1]) if len(params) > 1 else False
    from ..wallet.keys import script_to_address

    w = _wallet(node)
    tip = node.chainstate.tip().height
    received = _received_by_spk(w, minconf, tip)
    out = []
    seen_spks = set(received)
    if include_empty:
        from ..script.script import p2pkh_script

        for pkh in w._pkh_index:
            seen_spks.add(p2pkh_script(pkh))
    for spk in seen_spks:
        addr = script_to_address(spk, node.params)
        if addr is None:
            continue
        out.append({
            "address": addr,
            "amount": received.get(spk, 0) / COIN,
            "confirmations": minconf,
        })
    return sorted(out, key=lambda r: r["address"])


@rpc_method("backupwallet")
def backupwallet(node, params):
    require_params(params, 1, 1, "backupwallet \"destination\"")
    import shutil

    w = _wallet(node)
    w.save()
    if not w.path:
        raise RPCError(RPC_WALLET_ERROR, "wallet has no backing file")
    try:
        shutil.copyfile(w.path, str(params[0]))
    except OSError as e:
        raise RPCError(RPC_WALLET_ERROR, f"Error: {e}") from None
    return None


@rpc_method("dumpwallet")
def dumpwallet(node, params):
    """dumpwallet "filename" — human-readable key dump (rpcdump.cpp):
    one WIF per line with its hdkeypath; the HD seed leads the file."""
    require_params(params, 1, 1, "dumpwallet \"filename\"")
    import time as _t

    w = _wallet(node)
    if w.is_locked:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED,
                       "Error: Please enter the wallet passphrase with "
                       "walletpassphrase first.")
    lines = [
        "# Wallet dump created by bcpd",
        f"# * Created on {int(_t.time())}",
    ]
    if w.hd_seed is not None:
        from ..wallet.bip32 import ExtKey

        lines.append("# extended private masterkey: "
                     + ExtKey.from_seed(w.hd_seed).serialize())
    for key in w.keys_by_pubkey.values():
        path = w.key_paths.get(key.pubkey, "")
        tag = f"hdkeypath={path}" if path else "imported"
        lines.append(f"{key.to_wif(node.params)} 0 {tag} "
                     f"# addr={key.p2pkh_address(node.params)}")
    lines.append("# End of dump")
    import os as _os

    try:
        # 0600 like the wallet file itself — this is every private key
        fd = _os.open(str(params[0]),
                      _os.O_WRONLY | _os.O_CREAT | _os.O_TRUNC, 0o600)
        with _os.fdopen(fd, "w") as f:
            f.write("\n".join(lines) + "\n")
    except OSError as e:
        raise RPCError(RPC_WALLET_ERROR, f"Error: {e}") from None
    return None


@rpc_method("importwallet")
def importwallet(node, params):
    """importwallet "filename" — re-add every WIF line from a dump."""
    require_params(params, 1, 1, "importwallet \"filename\"")
    w = _wallet(node)
    if w.is_locked:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED,
                       "Error: Please enter the wallet passphrase with "
                       "walletpassphrase first.")
    try:
        with open(str(params[0])) as f:
            content = f.read()
    except OSError as e:
        raise RPCError(RPC_WALLET_ERROR, f"Error: {e}") from None
    n = 0
    for line in content.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key = CKey.from_wif(line.split()[0], node.params)
        if key is not None and key.pubkey not in w.keys_by_pubkey:
            w.add_key(key, persist=False)
            n += 1
    w.save()
    if n:
        node._rescan_wallet()
    return None


@rpc_method("keypoolrefill")
def keypoolrefill(node, params):
    """keypoolrefill ( newsize ) — keys derive on demand from the HD chain,
    so the pool never empties while unlocked; kept for parity."""
    w = _wallet(node)
    if w.is_locked:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED,
                       "Error: Please enter the wallet passphrase with "
                       "walletpassphrase first.")
    return None


def _wallet_fee(node) -> int:
    """Flat per-tx fee: -paytxfee/settxfee rate if set (treated per-kB
    against the typical ~1 kB wallet tx), else the relay floor."""
    return max(1000, getattr(node, "paytxfee", 0))


@rpc_method("settxfee")
def settxfee(node, params):
    require_params(params, 1, 1, "settxfee amount")
    rate = float(params[0])
    if rate < 0:
        raise RPCError(RPC_INVALID_PARAMETER, "amount cannot be negative")
    node.paytxfee = int(round(rate * COIN))
    return True


@rpc_method("sendmany")
def sendmany(node, params):
    """sendmany "" {"address":amount,...} — one tx, many outputs."""
    require_params(params, 2, 4,
                   "sendmany \"account\" {\"address\":amount,...}")
    from ..wallet.keys import address_to_script

    if not isinstance(params[1], dict) or not params[1]:
        raise RPCError(RPC_INVALID_PARAMETER,
                       "Parameter 2 must be a non-empty object")
    outputs = []
    for addr, amt in params[1].items():
        spk = address_to_script(addr, node.params)
        if spk is None:
            raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                           f"Invalid address: {addr}")
        value = int(round(float(amt) * COIN))
        if value <= 0:
            raise RPCError(RPC_INVALID_PARAMETER, "Invalid amount for send")
        outputs.append((spk, value))
    w = _wallet(node)
    try:
        tx = w.create_transaction_multi(
            outputs, node.chainstate.tip().height,
            fee=_wallet_fee(node), enable_forkid=True,
            fee_rate=_wallet_fee(node),
        )
    except WalletError as e:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED, str(e)) from None
    except ValueError as e:
        raise RPCError(RPC_WALLET_ERROR, str(e)) from None
    try:
        node.accept_to_mempool(tx)
    except MempoolError as e:
        raise RPCError(RPC_WALLET_ERROR, f"transaction rejected: {e}") from None
    if node.connman is not None:
        node.connman.relay_tx(tx.txid)
    return tx.txid_hex


@rpc_method("lockunspent")
def lockunspent(node, params):
    """lockunspent unlock ([{"txid":..,"vout":..},...]) — true unlocks."""
    require_params(params, 1, 2, "lockunspent unlock ( [{\"txid\":...}] )")
    from ..consensus.serialize import hex_to_hash
    from ..consensus.tx import COutPoint

    unlock = bool(params[0])
    w = _wallet(node)
    if len(params) < 2 or not params[1]:
        if unlock:
            w.locked_coins.clear()  # unlock-all form
            return True
        raise RPCError(RPC_INVALID_PARAMETER,
                       "Invalid parameter, expected locked outputs")
    for item in params[1]:
        try:
            op = COutPoint(hex_to_hash(item["txid"]), int(item["vout"]))
        except Exception:
            raise RPCError(RPC_INVALID_PARAMETER,
                           "Invalid parameter, invalid outpoint") from None
        if unlock:
            w.locked_coins.discard(op)
        else:
            w.locked_coins.add(op)
    return True


@rpc_method("listlockunspent")
def listlockunspent(node, params):
    w = _wallet(node)
    return [
        {"txid": hash_to_hex(op.hash), "vout": op.n}
        for op in sorted(w.locked_coins, key=lambda o: (o.hash, o.n))
    ]


@rpc_method("listsinceblock")
def listsinceblock(node, params):
    """listsinceblock ( "blockhash" ) — wallet txs at heights above the
    given block (or all), plus the lastblock cursor."""
    from ..consensus.serialize import hex_to_hash

    w = _wallet(node)
    since_height = -1
    if params and params[0]:
        idx = node.chainstate.block_index.get(hex_to_hash(params[0]))
        if idx is None:
            raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Block not found")
        since_height = idx.height
    txs = []
    for txid, entry in w.tx_log.items():
        if entry.get("abandoned"):
            continue
        if entry["height"] < 0 or entry["height"] > since_height:
            txs.append(_tx_log_json(node, w, txid, entry))
    return {
        "transactions": txs,
        "lastblock": hash_to_hex(node.chainstate.tip().hash),
    }


@rpc_method("abandontransaction")
def abandontransaction(node, params):
    require_params(params, 1, 1, "abandontransaction \"txid\"")
    from ..consensus.serialize import hex_to_hash

    txid = hex_to_hash(params[0])
    if txid in node.mempool:
        raise RPCError(RPC_MISC_ERROR,
                       "Transaction not eligible for abandonment")
    w = _wallet(node)
    if txid not in w.tx_log:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Invalid or non-wallet transaction id")
    try:
        w.abandon_transaction(txid)
    except WalletError:
        raise RPCError(RPC_MISC_ERROR,
                       "Transaction not eligible for abandonment") from None
    return None


@rpc_method("addmultisigaddress")
def addmultisigaddress(node, params):
    """addmultisigaddress nrequired ["key",...] — watch the P2SH script."""
    require_params(params, 2, 3,
                   "addmultisigaddress nrequired [\"key\",...]")
    from ..crypto.hashes import hash160
    from ..script.script import p2sh_script
    from ..wallet.keys import script_to_address

    w = _wallet(node)
    m, redeem = _parse_multisig_params(node, w, params)
    spk = p2sh_script(hash160(redeem))
    w.watched_scripts.add(spk)
    w.save()
    return script_to_address(spk, node.params)


def _parse_multisig_params(node, wallet, params):
    """Shared createmultisig/addmultisigaddress validation → (m, redeem)."""
    from ..script.script import multisig_script

    m = int(params[0])
    keys_param = params[1]
    if not isinstance(keys_param, list) or not keys_param:
        raise RPCError(RPC_INVALID_PARAMETER, "keys must be a non-empty array")
    if m < 1:
        raise RPCError(RPC_INVALID_PARAMETER,
                       "a multisignature address must require at least one key")
    if m > len(keys_param):
        raise RPCError(RPC_INVALID_PARAMETER,
                       "not enough keys supplied (got %d, need %d)"
                       % (len(keys_param), m))
    from ..script.script import MAX_PUBKEYS_PER_MULTISIG

    if len(keys_param) > MAX_PUBKEYS_PER_MULTISIG:
        raise RPCError(RPC_INVALID_PARAMETER,
                       "Number of addresses involved in the multisignature "
                       f"address creation > {MAX_PUBKEYS_PER_MULTISIG}")
    pubkeys = []
    for item in keys_param:
        item = str(item)
        pk = None
        if len(item) in (66, 130):
            try:
                pk = bytes.fromhex(item)
            except ValueError:
                pk = None
        if pk is None and wallet is not None:
            # address form: look up the wallet key
            from ..wallet.keys import address_to_script
            from ..script.script import get_script_ops

            spk = address_to_script(item, node.params)
            if spk is not None:
                try:
                    pkh = list(get_script_ops(spk))[2][1]
                    key = wallet.keys_by_pkh.get(pkh)
                    if key is not None:
                        pk = key.pubkey
                    elif pkh in wallet._pkh_index:
                        pk = wallet._pkh_index[pkh]
                except Exception:
                    pk = None
        if pk is None:
            raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                           f"Invalid public key or address: {item}")
        from ..crypto.secp256k1 import pubkey_parse

        if pubkey_parse(pk) is None:
            raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                           f"Invalid public key: {item}")
        pubkeys.append(pk)
    return m, multisig_script(m, pubkeys)


# ---- legacy accounts API (rpcwallet.cpp, deprecated in later lineages
# but part of this one's surface). Account balance here = unspent coins
# held by the account's labelled addresses + `move` deltas — the
# reference's full debit/credit history bookkeeping collapsed to its
# steady-state observable. ----


def _address_of_coin(node, coin):
    from ..wallet.keys import script_to_address

    return script_to_address(coin.txout.script_pubkey, node.params)


def _account_balances(node, w, include_watch_only: bool = False,
                      minconf: int = 1) -> dict:
    tip = node.chainstate.tip().height
    out = {"": 0}
    for acct in set(w.labels.values()) | set(w.account_moves):
        out.setdefault(acct, 0)
    for coin in w.available_coins(tip, include_watch_only=include_watch_only):
        conf = 0 if coin.height < 0 else tip - coin.height + 1
        if conf < minconf:
            continue
        addr = _address_of_coin(node, coin)
        acct = w.labels.get(addr, "") if addr else ""
        out[acct] = out.get(acct, 0) + coin.txout.value
    for acct, delta in w.account_moves.items():
        out[acct] = out.get(acct, 0) + delta
        out[""] = out.get("", 0) - delta
    return out


@rpc_method("getaccount")
def getaccount(node, params):
    require_params(params, 1, 1, "getaccount \"address\"")
    return _wallet(node).labels.get(str(params[0]), "")


@rpc_method("setaccount")
def setaccount(node, params):
    require_params(params, 2, 2, "setaccount \"address\" \"account\"")
    from ..wallet.keys import address_to_script

    if address_to_script(str(params[0]), node.params) is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Invalid address")
    w = _wallet(node)
    w.labels[str(params[0])] = str(params[1])
    w.save()
    return None


@rpc_method("getaccountaddress")
def getaccountaddress(node, params):
    """getaccountaddress "account" — a stable receiving address per
    account (fresh one on first use)."""
    require_params(params, 1, 1, "getaccountaddress \"account\"")
    account = str(params[0])
    w = _wallet(node)
    addr = w.account_addresses.get(account)
    if addr is not None:
        return addr
    try:
        addr = w.get_new_address(account)
    except WalletError as e:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED, str(e)) from None
    w.account_addresses[account] = addr
    w.save()
    return addr


@rpc_method("getaddressesbyaccount")
def getaddressesbyaccount(node, params):
    require_params(params, 1, 1, "getaddressesbyaccount \"account\"")
    w = _wallet(node)
    account = str(params[0])
    return sorted(a for a, acct in w.labels.items() if acct == account)


@rpc_method("listaccounts")
def listaccounts(node, params):
    """listaccounts ( minconf includeWatchonly ) — watch-only coins count
    only with the explicit flag, like the reference."""
    minconf = int(params[0]) if params and params[0] is not None else 1
    include_watch = bool(params[1]) if len(params) > 1 else False
    w = _wallet(node)
    return {acct: bal / COIN
            for acct, bal in _account_balances(
                node, w, include_watch, minconf).items()}


@rpc_method("getreceivedbyaccount")
def getreceivedbyaccount(node, params):
    require_params(params, 1, 2, "getreceivedbyaccount \"account\" ( minconf )")
    from ..wallet.keys import address_to_script

    account = str(params[0])
    minconf = int(params[1]) if len(params) > 1 else 1
    w = _wallet(node)
    tip = node.chainstate.tip().height
    received = _received_by_spk(w, minconf, tip)
    total = 0
    for addr, acct in w.labels.items():
        if acct == account:
            spk = address_to_script(addr, node.params)
            if spk is not None:
                total += received.get(spk, 0)
    return total / COIN


@rpc_method("move")
def move(node, params):
    """move "fromaccount" "toaccount" amount — internal bookkeeping only."""
    require_params(params, 3, 5, "move \"fromaccount\" \"toaccount\" amount")
    w = _wallet(node)
    amount = int(round(float(params[2]) * COIN))
    src, dst = str(params[0]), str(params[1])
    w.account_moves[src] = w.account_moves.get(src, 0) - amount
    w.account_moves[dst] = w.account_moves.get(dst, 0) + amount
    # "" is the implicit default account; drop zero entries
    for acct in (src, dst):
        if w.account_moves.get(acct) == 0:
            w.account_moves.pop(acct, None)
    w.save()
    return True


@rpc_method("sendfrom")
def sendfrom(node, params):
    """sendfrom "account" "address" amount — spends from the shared pool
    like the reference (accounts never restricted coin selection), gated
    on the account's balance. Under this wallet's steady-state account
    model (balances derive from labelled-coin ownership + move deltas) a
    spend of the account's own coins debits it naturally, so no extra
    delta is recorded — recording one on top double-counts."""
    require_params(params, 3, 6, "sendfrom \"account\" \"toaddress\" amount")
    RPC_WALLET_INSUFFICIENT_FUNDS = -6
    account = str(params[0])
    amount = int(round(float(params[2]) * COIN))
    fee = _wallet_fee(node)
    w = _wallet(node)
    if _account_balances(node, w).get(account, 0) < amount + fee:
        raise RPCError(RPC_WALLET_INSUFFICIENT_FUNDS,
                       "Account has insufficient funds")
    return sendtoaddress(node, [params[1], params[2]])


# ---- watch-only imports (rpcdump.cpp importaddress/importpubkey) ----


@rpc_method("importaddress")
def importaddress(node, params):
    """importaddress "address-or-script" ( "label" rescan )"""
    require_params(params, 1, 3, "importaddress \"address\" ( \"label\" rescan )")
    from ..wallet.keys import address_to_script

    w = _wallet(node)
    target = str(params[0])
    spk = address_to_script(target, node.params)
    if spk is None:
        try:
            spk = bytes.fromhex(target)  # raw script form
        except ValueError:
            raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                           "Invalid address or script") from None
    w.watched_scripts.add(spk)
    if len(params) > 1 and params[1]:
        w.labels[target] = str(params[1])
    w.save()
    rescan = bool(params[2]) if len(params) > 2 else True
    if rescan:
        node._rescan_wallet()
    return None


@rpc_method("importpubkey")
def importpubkey(node, params):
    """importpubkey "pubkey" ( "label" rescan ) — watch P2PK + P2PKH."""
    require_params(params, 1, 3, "importpubkey \"pubkey\" ( \"label\" rescan )")
    from ..crypto.secp256k1 import pubkey_parse
    from ..script.script import p2pk_script, p2pkh_script_for_pubkey

    try:
        pk = bytes.fromhex(str(params[0]))
    except ValueError:
        pk = b""
    if not pk or pubkey_parse(pk) is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Pubkey must be a valid hex public key")
    w = _wallet(node)
    w.watched_scripts.add(p2pk_script(pk))
    w.watched_scripts.add(p2pkh_script_for_pubkey(pk))
    if len(params) > 1 and params[1]:
        from ..crypto.hashes import hash160
        from ..crypto.base58 import b58check_encode

        addr = b58check_encode(
            bytes([node.params.pubkey_addr_prefix]) + hash160(pk))
        w.labels[addr] = str(params[1])
    w.save()
    rescan = bool(params[2]) if len(params) > 2 else True
    if rescan:
        node._rescan_wallet()
    return None


@rpc_method("importmulti")
def importmulti(node, params):
    """importmulti [{"scriptPubKey":{"address":...}|"<hex>", "timestamp":...,
    "keys":[wif], "pubkeys":[hex], "redeemscript":hex, "watchonly":bool},...]
    ( {"rescan":bool} ) — bulk import (rpcdump.cpp importmulti). One rescan
    at the end regardless of request count."""
    require_params(params, 1, 2, "importmulti requests ( options )")
    if not isinstance(params[0], list):
        raise RPCError(RPC_INVALID_PARAMETER, "requests must be an array")
    options = params[1] if len(params) > 1 and isinstance(params[1], dict) else {}
    do_rescan = bool(options.get("rescan", True))
    from ..crypto.hashes import hash160
    from ..crypto.secp256k1 import pubkey_parse
    from ..script.script import p2pk_script, p2pkh_script_for_pubkey, p2sh_script
    from ..wallet.keys import address_to_script

    w = _wallet(node)
    results = []
    imported_any = False
    for req in params[0]:
        # PHASE 1 — validate and stage everything; no wallet mutation yet,
        # so a mid-request failure can't leave a partial import behind
        try:
            if not isinstance(req, dict):
                raise ValueError("request must be an object")
            if "timestamp" not in req:
                raise ValueError(
                    "Missing required timestamp field for key scan")
            watchonly = req.get("watchonly")
            if watchonly is True and req.get("keys"):
                raise ValueError(
                    "Incompatibility found between watchonly and keys")
            spk_field = req.get("scriptPubKey")
            spk = None
            if isinstance(spk_field, dict) and "address" in spk_field:
                spk = address_to_script(str(spk_field["address"]), node.params)
                if spk is None:
                    raise ValueError("Invalid address")
            elif isinstance(spk_field, str):
                spk = bytes.fromhex(spk_field)
            elif spk_field is not None:
                raise ValueError("Invalid scriptPubKey")

            staged_keys = []
            for wif in req.get("keys", []) or []:
                key = CKey.from_wif(str(wif), node.params)
                if key is None:
                    raise ValueError("Invalid private key encoding")
                staged_keys.append(key)
            staged_scripts = []
            for pk_hex in req.get("pubkeys", []) or []:
                pk = bytes.fromhex(str(pk_hex))
                if pubkey_parse(pk) is None:
                    raise ValueError("Pubkey is not a valid public key")
                staged_scripts.append(p2pk_script(pk))
                staged_scripts.append(p2pkh_script_for_pubkey(pk))
            redeem = req.get("redeemscript")
            if redeem:
                staged_scripts.append(
                    p2sh_script(hash160(bytes.fromhex(str(redeem)))))
            if spk is not None and not staged_keys:
                staged_scripts.append(spk)
            if not staged_keys and not staged_scripts:
                raise ValueError("Request contains nothing to import")
        except (ValueError, WalletError) as e:
            results.append({"success": False,
                            "error": {"code": RPC_INVALID_ADDRESS_OR_KEY,
                                      "message": str(e)}})
            continue
        # PHASE 2 — apply the fully-validated request
        try:
            for key in staged_keys:
                w.add_key(key, persist=False)
        except WalletError as e:  # locked wallet
            results.append({"success": False,
                            "error": {"code": RPC_WALLET_UNLOCK_NEEDED,
                                      "message": str(e)}})
            continue
        w.watched_scripts.update(staged_scripts)
        imported_any = True
        results.append({"success": True})
    if imported_any:
        w.save()
        if do_rescan:
            node._rescan_wallet()
    return results
