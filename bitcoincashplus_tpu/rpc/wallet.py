"""Wallet RPCs — src/wallet/rpcwallet.cpp / rpcdump.cpp.

The wallet is loaded lazily on first wallet-RPC use (the reference loads at
init; lazy keeps non-wallet nodes wallet-free). All handlers already hold
cs_main via the server dispatch; wallet state is only touched under it.
"""

from __future__ import annotations

from ..consensus.serialize import hash_to_hex
from ..consensus.tx import COIN
from ..mempool.mempool import MempoolError
from ..wallet.keys import CKey
from ..wallet.wallet import WalletError
from .registry import (
    RPC_INVALID_ADDRESS_OR_KEY,
    RPC_INVALID_PARAMETER,
    RPC_MISC_ERROR,
    RPC_TYPE_ERROR,
    RPCError,
    require_params,
    rpc_method,
)

RPC_WALLET_ERROR = -4
RPC_WALLET_PASSPHRASE_INCORRECT = -14
RPC_WALLET_WRONG_ENC_STATE = -15
RPC_WALLET_UNLOCK_NEEDED = -13


def _wallet(node):
    w = node.load_wallet()
    w.maybe_relock()
    return w


@rpc_method("getnewaddress")
def getnewaddress(node, params):
    require_params(params, 0, 1, "getnewaddress ( \"account\" )")
    try:
        return _wallet(node).get_new_address()
    except WalletError as e:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED, str(e)) from None


@rpc_method("getbalance")
def getbalance(node, params):
    w = _wallet(node)
    return w.balance(node.chainstate.tip().height) / COIN


@rpc_method("listunspent")
def listunspent(node, params):
    w = _wallet(node)
    tip = node.chainstate.tip().height
    out = []
    for coin in w.available_coins(tip):
        out.append({
            "txid": hash_to_hex(coin.outpoint.hash),
            "vout": coin.outpoint.n,
            "amount": coin.txout.value / COIN,
            "confirmations": tip - coin.height + 1,
            "scriptPubKey": coin.txout.script_pubkey.hex(),
            "spendable": not w.is_locked,
        })
    return out


@rpc_method("sendtoaddress")
def sendtoaddress(node, params):
    require_params(params, 2, 2, "sendtoaddress \"address\" amount")
    address = params[0]
    amount = int(round(float(params[1]) * COIN))
    if amount <= 0:
        raise RPCError(RPC_INVALID_PARAMETER, "Invalid amount for send")
    w = _wallet(node)
    try:
        tx = w.create_transaction(
            address, amount, node.chainstate.tip().height, enable_forkid=True
        )
    except WalletError as e:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED, str(e)) from None
    except ValueError as e:
        raise RPCError(RPC_WALLET_ERROR, str(e)) from None
    try:
        node.accept_to_mempool(tx)
    except MempoolError as e:
        raise RPCError(RPC_WALLET_ERROR, f"transaction rejected: {e}") from None
    if node.connman is not None:
        node.connman.relay_tx(tx.txid)
    return tx.txid_hex


@rpc_method("getwalletinfo")
def getwalletinfo(node, params):
    w = _wallet(node)
    tip = node.chainstate.tip().height
    info = {
        "walletname": "wallet.json",
        "balance": w.balance(tip) / COIN,
        "txcount": len(w.coins),
        "keypoolsize": len(w.keys_by_pubkey) or len(w.encrypted_keys),
    }
    if w.is_crypted:
        info["unlocked_until"] = (
            0 if w.is_locked else int(w.unlocked_until)
        )
    return info


@rpc_method("encryptwallet")
def encryptwallet(node, params):
    require_params(params, 1, 1, "encryptwallet \"passphrase\"")
    w = _wallet(node)
    if w.is_crypted:
        raise RPCError(RPC_WALLET_WRONG_ENC_STATE,
                       "Wallet is already encrypted")
    try:
        w.encrypt(str(params[0]))
    except WalletError as e:
        raise RPCError(RPC_MISC_ERROR, str(e)) from None
    # the reference shuts down after encryptwallet; we just lock
    return ("wallet encrypted; the wallet is now locked — use "
            "walletpassphrase to unlock")


@rpc_method("walletpassphrase")
def walletpassphrase(node, params):
    require_params(params, 2, 2, "walletpassphrase \"passphrase\" timeout")
    w = _wallet(node)
    if not w.is_crypted:
        raise RPCError(RPC_WALLET_WRONG_ENC_STATE,
                       "running with an unencrypted wallet, but "
                       "walletpassphrase was called")
    timeout = float(params[1])
    if timeout <= 0:
        raise RPCError(RPC_INVALID_PARAMETER, "timeout must be positive")
    if not w.unlock(str(params[0]), timeout):
        raise RPCError(RPC_WALLET_PASSPHRASE_INCORRECT,
                       "Error: The wallet passphrase entered was incorrect.")
    return None


@rpc_method("walletlock")
def walletlock(node, params):
    w = _wallet(node)
    if not w.is_crypted:
        raise RPCError(RPC_WALLET_WRONG_ENC_STATE,
                       "running with an unencrypted wallet, but "
                       "walletlock was called")
    w.lock()
    return None


@rpc_method("walletpassphrasechange")
def walletpassphrasechange(node, params):
    require_params(params, 2, 2,
                   "walletpassphrasechange \"oldpassphrase\" \"newpassphrase\"")
    w = _wallet(node)
    if not w.is_crypted:
        raise RPCError(RPC_WALLET_WRONG_ENC_STATE,
                       "running with an unencrypted wallet")
    if not w.change_passphrase(str(params[0]), str(params[1])):
        raise RPCError(RPC_WALLET_PASSPHRASE_INCORRECT,
                       "Error: The wallet passphrase entered was incorrect.")
    return None


@rpc_method("dumpprivkey")
def dumpprivkey(node, params):
    require_params(params, 1, 1, "dumpprivkey \"address\"")
    from ..wallet.keys import address_to_script
    from ..script.script import get_script_ops

    w = _wallet(node)
    if w.is_locked:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED,
                       "Error: Please enter the wallet passphrase with "
                       "walletpassphrase first.")
    spk = address_to_script(params[0], node.params)
    if spk is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Invalid address")
    pkh = list(get_script_ops(spk))[2][1]
    key = w.keys_by_pkh.get(pkh)
    if key is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Private key for address is not known")
    return key.to_wif(node.params)


@rpc_method("importprivkey")
def importprivkey(node, params):
    require_params(params, 1, 2, "importprivkey \"privkey\" ( \"label\" )")
    w = _wallet(node)
    key = CKey.from_wif(params[0], node.params)
    if key is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Invalid private key encoding")
    try:
        w.add_key(key)
    except WalletError as e:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED, str(e)) from None
    node._rescan_wallet()
    return None

@rpc_method("signmessage")
def signmessage(node, params):
    require_params(params, 2, 2, "signmessage \"address\" \"message\"")
    from ..wallet.keys import address_to_script
    from ..wallet.message import sign_message
    from ..script.script import get_script_ops

    w = _wallet(node)
    if w.is_locked:
        raise RPCError(RPC_WALLET_UNLOCK_NEEDED,
                       "Error: Please enter the wallet passphrase with "
                       "walletpassphrase first.")
    spk = address_to_script(params[0], node.params)
    if spk is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Invalid address")
    try:
        pkh = list(get_script_ops(spk))[2][1]
    except Exception:
        pkh = None
    if pkh is None or len(pkh) != 20:  # P2SH scripts land here too
        raise RPCError(RPC_TYPE_ERROR, "Address does not refer to key")
    key = w.keys_by_pkh.get(pkh)
    if key is None:
        raise RPCError(RPC_WALLET_ERROR, "Private key not available")
    return sign_message(key, str(params[1]))


def _tx_log_json(node, w, txid: bytes, entry: dict) -> dict:
    """One listtransactions/gettransaction row (rpcwallet.cpp WalletTxToJSON)."""
    tip = node.chainstate.tip().height
    height = entry["height"]
    confirmations = 0 if height < 0 else tip - height + 1
    net = entry["received"] - entry["sent"]
    if entry["is_coinbase"]:
        maturity = node.params.consensus.coinbase_maturity
        category = "generate" if confirmations >= maturity else "immature"
    elif entry["sent"] > 0:
        category = "send"
    else:
        category = "receive"
    out = {
        "txid": hash_to_hex(txid),
        "category": category,
        "amount": net / COIN,
        "confirmations": confirmations,
    }
    if height >= 0:
        idx = node.chainstate.chain[height]
        if idx is not None:
            out["blockhash"] = hash_to_hex(idx.hash)
            out["blocktime"] = idx.header.time
    return out


@rpc_method("listtransactions")
def listtransactions(node, params):
    """listtransactions ( "account" count skip ) — newest first."""
    count = int(params[1]) if len(params) > 1 else 10
    skip = int(params[2]) if len(params) > 2 else 0
    w = _wallet(node)
    entries = list(w.tx_log.items())[::-1][skip:skip + count]
    return [_tx_log_json(node, w, txid, e) for txid, e in entries][::-1]


@rpc_method("gettransaction")
def gettransaction(node, params):
    require_params(params, 1, 1, "gettransaction \"txid\"")
    from ..consensus.serialize import hex_to_hash

    w = _wallet(node)
    txid = hex_to_hash(params[0])
    entry = w.tx_log.get(txid)
    if entry is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Invalid or non-wallet transaction id")
    out = _tx_log_json(node, w, txid, entry)
    out["fee"] = 0.0  # fee tracking requires full input provenance
    out["details"] = [out.copy()]
    return out


def _received_by_spk(w, minconf: int, tip: int) -> dict:
    """spk -> total satoshis received across wallet coins (spent or not),
    rpcwallet.cpp GetReceived semantics: receipts count even if later
    spent, gated on confirmations."""
    out = {}
    for coin in w.coins.values():
        conf = 0 if coin.height < 0 else tip - coin.height + 1
        if conf < minconf:
            continue
        spk = coin.txout.script_pubkey
        out[spk] = out.get(spk, 0) + coin.txout.value
    return out


@rpc_method("getreceivedbyaddress")
def getreceivedbyaddress(node, params):
    require_params(params, 1, 2, "getreceivedbyaddress \"address\" ( minconf )")
    from ..wallet.keys import address_to_script

    spk = address_to_script(params[0], node.params)
    if spk is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Invalid address")
    minconf = int(params[1]) if len(params) > 1 else 1
    w = _wallet(node)
    tip = node.chainstate.tip().height
    return _received_by_spk(w, minconf, tip).get(spk, 0) / COIN


@rpc_method("listreceivedbyaddress")
def listreceivedbyaddress(node, params):
    minconf = int(params[0]) if params else 1
    include_empty = bool(params[1]) if len(params) > 1 else False
    from ..wallet.keys import script_to_address

    w = _wallet(node)
    tip = node.chainstate.tip().height
    received = _received_by_spk(w, minconf, tip)
    out = []
    seen_spks = set(received)
    if include_empty:
        from ..script.script import p2pkh_script

        for pkh in w._pkh_index:
            seen_spks.add(p2pkh_script(pkh))
    for spk in seen_spks:
        addr = script_to_address(spk, node.params)
        if addr is None:
            continue
        out.append({
            "address": addr,
            "amount": received.get(spk, 0) / COIN,
            "confirmations": minconf,
        })
    return sorted(out, key=lambda r: r["address"])
