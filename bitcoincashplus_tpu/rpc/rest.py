"""Unauthenticated REST interface — src/rest.cpp (-rest flag).

The reference registers GET handlers on the same evhttp server the JSON-RPC
listener uses; here the RPCServer's request handler routes GETs to
handle_rest when `-rest` is enabled. Same endpoint contract:

  /rest/tx/<txid>.{hex,json}
  /rest/block/<hash>.{hex,json}
  /rest/headers/<count>/<hash>.hex
  /rest/blockhashbyheight/<height>.{hex,json}
  /rest/chaininfo.json
  /rest/mempool/info.json
  /rest/mempool/contents.json

Plus this framework's observability endpoint (not in the reference):

  /metrics   Prometheus text exposition (version 0.0.4) over the unified
             telemetry registry (util/telemetry) — counters, gauges, and
             latency histograms covering dispatch, ecdsa, pipeline,
             sigcache, mempool-accept, and net. Same `-rest` gate as the
             other unauthenticated GETs.

Errors are plain-text with the reference's status codes (400 bad input,
404 unknown object, 403 when -rest is off — callers without auth cookies
use this surface, so it never throws RPC errors outward).
"""

from __future__ import annotations

import json

from ..consensus.serialize import hash_to_hex, hex_to_hash
from .blockchain import (
    _mempool_entry_json,
    getblockchaininfo,
    getmempoolinfo,
    header_to_json,
)
from .rawtransaction import tx_to_json

MAX_REST_HEADERS = 2000


class RestError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def _parse_hash(s: str) -> bytes:
    try:
        h = hex_to_hash(s)
    except Exception:
        raise RestError(400, f"Invalid hash: {s}") from None
    if len(h) != 32:
        raise RestError(400, f"Invalid hash: {s}")
    return h


def _split_format(tail: str) -> tuple[str, str]:
    if "." not in tail:
        raise RestError(400, "output format not found (try .json or .hex)")
    base, fmt = tail.rsplit(".", 1)
    if fmt not in ("hex", "json"):
        raise RestError(400, f"output format not supported: .{fmt}")
    return base, fmt


def handle_rest(node, path: str) -> tuple[int, str, bytes]:
    """GET /rest/... (or /metrics) -> (status, content_type, body)."""
    if path == "/metrics" or path.startswith("/metrics?"):
        return handle_metrics(node)
    if not path.startswith("/rest/"):
        raise RestError(404, "not a REST path")
    parts = path[len("/rest/"):].split("/")

    if parts[0].startswith("tx"):
        return _rest_tx(node, parts)
    if parts[0].startswith("block") and not parts[0].startswith("blockhash"):
        return _rest_block(node, parts)
    if parts[0] == "headers" and len(parts) == 3:
        return _rest_headers(node, parts)
    if parts[0].startswith("blockhashbyheight"):
        return _rest_blockhash_by_height(node, parts)
    if parts[0] == "chaininfo.json":
        with node.cs_main:
            return _json(getblockchaininfo(node, []))
    if parts[0].startswith("getutxos"):
        return _rest_getutxos(node, parts)
    if parts[0] == "mempool" and len(parts) == 2:
        if parts[1] == "info.json":
            with node.cs_main:
                return _json(getmempoolinfo(node, []))
        if parts[1] == "contents.json":
            with node.cs_main:
                out = {
                    hash_to_hex(txid): _mempool_entry_json(node.mempool, e)
                    for txid, e in node.mempool.entries.items()
                }
            return _json(out)
    raise RestError(404, f"unknown REST endpoint: {path}")


def handle_metrics(node) -> tuple[int, str, bytes]:
    """GET /metrics — Prometheus text exposition over the telemetry
    registry. Scrape-safe with -telemetry=off too (families expose their
    frozen values; the header names the active mode for operators)."""
    from ..util import telemetry

    body = (f"# bcp telemetry mode={telemetry.mode()}\n"
            + telemetry.REGISTRY.prometheus_text())
    return 200, "text/plain; version=0.0.4; charset=utf-8", body.encode()


def _json(obj) -> tuple[int, str, bytes]:
    return 200, "application/json", (json.dumps(obj) + "\n").encode()


def _hex(raw: bytes) -> tuple[int, str, bytes]:
    return 200, "text/plain", (raw.hex() + "\n").encode()


def _rest_tx(node, parts):
    base, fmt = _split_format(parts[0][len("tx"):].lstrip("/") or
                              (parts[1] if len(parts) > 1 else ""))
    txid = _parse_hash(base)
    with node.cs_main:
        # mempool first, then txindex (the getrawtransaction lookup order)
        tx = node.mempool.get_tx(txid)
        block_hash = None
        if tx is None and node.txindex:
            block_hash = node.txindex_lookup(txid)
            if block_hash is not None:
                block = node.chainstate.get_block(block_hash)
                if block is not None:
                    tx = next((t for t in block.vtx if t.txid == txid), None)
        if tx is None:
            raise RestError(404, f"{base} not found")
        if fmt == "hex":
            return _hex(tx.serialize())
        return _json(tx_to_json(node, tx, block_hash))


def _rest_block(node, parts):
    base, fmt = _split_format(parts[0][len("block"):].lstrip("/") or
                              (parts[1] if len(parts) > 1 else ""))
    h = _parse_hash(base)
    with node.cs_main:
        idx = node.chainstate.block_index.get(h)
        raw = node.block_store.get_block(h)
    if idx is None or raw is None:
        raise RestError(404, f"{base} not found")
    if fmt == "hex":
        return _hex(raw)
    from ..consensus.block import CBlock

    block = CBlock.from_bytes(raw)
    with node.cs_main:
        out = header_to_json(node, idx)
        out["tx"] = [tx_to_json(node, tx) for tx in block.vtx]
    out["size"] = len(raw)
    return _json(out)


def _rest_headers(node, parts):
    try:
        count = int(parts[1])
    except ValueError:
        raise RestError(400, f"invalid count: {parts[1]}") from None
    if not 1 <= count <= MAX_REST_HEADERS:
        raise RestError(400, f"header count out of range: {count}")
    base, fmt = _split_format(parts[2])
    if fmt != "hex":
        raise RestError(400, "output format not supported (headers: .hex)")
    h = _parse_hash(base)
    with node.cs_main:
        cs = node.chainstate
        idx = cs.block_index.get(h)
        headers = []
        while idx is not None and len(headers) < count:
            headers.append(idx.header.serialize())
            idx = cs.chain[idx.height + 1] if cs.chain[idx.height] is idx else None
    if not headers:
        raise RestError(404, f"{base} not found")
    return _hex(b"".join(headers))


def _rest_blockhash_by_height(node, parts):
    base, fmt = _split_format(
        parts[0][len("blockhashbyheight"):].lstrip("/") or
        (parts[1] if len(parts) > 1 else ""))
    try:
        height = int(base)
    except ValueError:
        raise RestError(400, f"invalid height: {base}") from None
    with node.cs_main:
        idx = node.chainstate.chain[height] if height >= 0 else None
    if idx is None:
        raise RestError(404, "block height out of range")
    if fmt == "hex":
        return 200, "text/plain", (hash_to_hex(idx.hash) + "\n").encode()
    return _json({"blockhash": hash_to_hex(idx.hash)})


def _rest_getutxos(node, parts):
    """GET /rest/getutxos[/checkmempool]/<txid>-<n>/....json — UTXO query
    (src/rest.cpp rest_getutxos). JSON output form only."""
    from ..consensus.tx import COutPoint
    from .rawtransaction import script_pubkey_json

    args = list(parts)
    args[-1], fmt = _split_format(args[-1])
    if fmt != "json":
        raise RestError(400, "getutxos supports .json only")
    check_mempool = len(args) > 1 and args[1] == "checkmempool"
    outpoint_parts = args[(2 if check_mempool else 1):]
    if not outpoint_parts or len(outpoint_parts) > 15:  # MAX_GETUTXOS_OUTPOINTS
        raise RestError(400, "expected 1-15 <txid>-<n> outpoints")
    outpoints = []
    for op in outpoint_parts:
        try:
            txid_hex, n = op.rsplit("-", 1)
            outpoints.append(COutPoint(_parse_hash(txid_hex), int(n)))
        except (ValueError, RestError):
            raise RestError(400, f"bad outpoint {op!r}") from None
    with node.cs_main:
        tip = node.chainstate.tip()
        bitmap = []
        utxos = []
        for op in outpoints:
            coin = node.chainstate.coins.get_coin(op)
            spent_in_pool = (check_mempool
                            and node.mempool.get_spender(op) is not None)
            if coin is None and check_mempool:
                out = node.mempool.get_output(op)
                if out is not None and not spent_in_pool:
                    bitmap.append(1)
                    utxos.append({
                        "height": 0x7FFFFFFF,
                        "value": out.value / 1e8,
                        "scriptPubKey": script_pubkey_json(node, out.script_pubkey),
                    })
                    continue
            if coin is None or spent_in_pool:
                bitmap.append(0)
                continue
            bitmap.append(1)
            utxos.append({
                "height": coin.height,
                "value": coin.out.value / 1e8,
                "scriptPubKey": script_pubkey_json(node, coin.out.script_pubkey),
            })
        return _json({
            "chainHeight": tip.height,
            "chaintipHash": hash_to_hex(tip.hash),
            "bitmap": "".join(str(b) for b in bitmap),
            "utxos": utxos,
        })
