"""Network RPCs.

Reference: src/rpc/net.cpp (getconnectioncount, getpeerinfo, getnettotals,
addnode, getnetworkinfo). Backed by p2p/connman when P2P is running; a
node without P2P reports zero peers, like a -connect=0 reference node.
"""

from __future__ import annotations

from .registry import RPC_INVALID_PARAMETER, RPCError, require_params, rpc_method

PROTOCOL_VERSION = 70015
SUBVERSION = "/bcpd-tpu:0.3.0/"


@rpc_method("getconnectioncount")
def getconnectioncount(node, params):
    return len(node.connman.peers) if node.connman else 0


@rpc_method("getpeerinfo")
def getpeerinfo(node, params):
    """getpeerinfo — per-peer connection stats plus this framework's
    DoS-supervision state: ``banscore`` (misbehavior ledger total),
    ``charges`` (reason -> accumulated score), ``inflight`` (blocks
    getdata'd and not yet received), ``stalling`` (download-timeout flag),
    ``recvrate`` (bytes/sec over the last supervision tick) and
    ``floodstrikes`` (receive-ceiling violations)."""
    if node.connman is None:
        return []
    # snapshot: the event loop evicts peers concurrently (discharges,
    # stall/flood evictions) and a mid-iteration pop would RuntimeError
    return [peer.info() for peer in list(node.connman.peers.values())]


@rpc_method("getnettotals")
def getnettotals(node, params):
    cm = node.connman
    return {
        "totalbytesrecv": cm.bytes_recv if cm else 0,
        "totalbytessent": cm.bytes_sent if cm else 0,
    }


@rpc_method("getnetworkinfo")
def getnetworkinfo(node, params):
    return {
        "version": 30000,
        "subversion": SUBVERSION,
        "protocolversion": PROTOCOL_VERSION,
        "localservices": "0000000000000001",
        "timeoffset": 0,
        "connections": len(node.connman.peers) if node.connman else 0,
        "networkactive": node.connman is not None,
        "relayfee": node.min_relay_fee_rate / 1e8,
        "warnings": "",
    }


@rpc_method("addnode")
def addnode(node, params):
    require_params(params, 2, 2, "addnode \"node\" \"add|remove|onetry\"")
    if node.connman is None:
        raise RPCError(RPC_INVALID_PARAMETER, "P2P is not enabled")
    target, cmd = params[0], params[1]
    if cmd in ("add", "onetry"):
        host, _, port = target.rpartition(":")
        if cmd == "add":
            if target in node.connman.added_nodes:
                raise RPCError(-23, "Error: Node already added")
            node.connman.added_nodes.append(target)
        node.connman.connect_to(host or "127.0.0.1", int(port))
    elif cmd == "remove":
        try:
            node.connman.added_nodes.remove(target)
        except ValueError:
            pass
        node.connman.disconnect(target)
    else:
        raise RPCError(RPC_INVALID_PARAMETER, f"unknown command {cmd!r}")
    return None


@rpc_method("getaddednodeinfo")
def getaddednodeinfo(node, params):
    """getaddednodeinfo — the addnode-list with live-connection status
    (src/rpc/net.cpp getaddednodeinfo). Runs without cs_main: DNS
    resolution of hostname-form targets can block for seconds and must
    not stall validation."""
    if node.connman is None:
        return []
    with node.cs_main:
        targets = list(node.connman.added_nodes)
        peers = {p.addr: p for p in list(node.connman.peers.values())}
    if params and params[-1] and isinstance(params[-1], str):
        if params[-1] not in targets:
            raise RPCError(-24, "Error: Node has not been added.")
        targets = [params[-1]]
    import socket as _socket

    out = []
    for t in targets:
        # resolve a hostname-form target so it matches peer.addr, which
        # records getpeername's numeric ip:port
        host, _, port = t.rpartition(":")
        try:
            resolved = f"{_socket.gethostbyname(host or '127.0.0.1')}:{port}"
        except OSError:
            resolved = t
        peer = peers.get(t) or peers.get(resolved)
        entry = {"addednode": t, "connected": peer is not None,
                 "addresses": []}
        if peer is not None:
            entry["addresses"] = [{
                "address": peer.addr,
                "connected": "inbound" if not peer.outbound else "outbound",
            }]
        out.append(entry)
    return out


getaddednodeinfo.no_cs_main = True


@rpc_method("disconnectnode")
def disconnectnode(node, params):
    require_params(params, 1, 1, "disconnectnode \"address\"")
    if node.connman is not None:
        node.connman.disconnect(params[0])
    return None


@rpc_method("setban")
def setban(node, params):
    """setban "ip" "add|remove" (bantime) — src/rpc/net.cpp:~560, backed by
    the connman ban list (banman.cpp). Host granularity, like peer tracking."""
    require_params(params, 2, 3, "setban \"ip\" \"add|remove\" ( bantime )")
    if node.connman is None:
        raise RPCError(RPC_INVALID_PARAMETER, "P2P networking is disabled")
    ip, cmd = str(params[0]), str(params[1])
    if cmd == "add":
        bantime = int(params[2]) if len(params) > 2 and params[2] else 0
        node.connman.ban(ip, bantime)
    elif cmd == "remove":
        if not node.connman.unban(ip):
            raise RPCError(
                RPC_INVALID_PARAMETER,
                "Error: Unban failed. Requested address/subnet "
                "was not previously banned.",
            )
    else:
        raise RPCError(RPC_INVALID_PARAMETER, f"unknown command {cmd!r}")
    return None


@rpc_method("listbanned")
def listbanned(node, params):
    if node.connman is None:
        return []
    return [
        {"address": ip, "banned_until": int(until)}
        for ip, until in sorted(node.connman.banned().items())
    ]


@rpc_method("clearbanned")
def clearbanned(node, params):
    if node.connman is not None:
        node.connman.clear_banned()
    return None


@rpc_method("ping")
def ping(node, params):
    """Queue a ping to every connected peer (src/rpc/net.cpp ping)."""
    if node.connman is not None:
        node.connman.ping_all()
    return None
