"""Raw-transaction RPCs.

Reference: src/rpc/rawtransaction.cpp (sendrawtransaction,
getrawtransaction, decoderawtransaction, createrawtransaction),
src/core_io.h (TxToUniv / ScriptPubKeyToUniv decoding shapes).
"""

from __future__ import annotations

from ..consensus.serialize import hash_to_hex, hex_to_hash
from ..consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from ..mempool.mempool import MempoolError
from ..script.script import classify_script, get_script_ops, push_data
from ..wallet.keys import script_to_address
from .registry import (
    RPC_DESERIALIZATION_ERROR,
    RPC_INVALID_ADDRESS_OR_KEY,
    RPC_INVALID_PARAMETER,
    RPC_VERIFY_ALREADY_IN_CHAIN,
    RPC_VERIFY_REJECTED,
    RPCError,
    param_hash,
    require_params,
    rpc_method,
)


def script_asm(script: bytes) -> str:
    """ScriptToAsmStr (src/core_io): best-effort opcode/data rendering."""
    from ..script.script import OPCODE_NAMES

    parts = []
    try:
        for op, data, _ in get_script_ops(script):
            if data is not None:
                parts.append(data.hex() if data else "0")
            else:
                parts.append(OPCODE_NAMES.get(op, f"OP_UNKNOWN_{op:#x}"))
    except Exception:
        parts.append("[error]")
    return " ".join(parts)


def script_pubkey_json(node, script: bytes) -> dict:
    out = {
        "asm": script_asm(script),
        "hex": script.hex(),
        "type": classify_script(script),
    }
    addr = script_to_address(script, node.params)
    if addr is not None:
        out["addresses"] = [addr]
    return out


def tx_to_json(node, tx: CTransaction, block_hash: bytes = None) -> dict:
    out = {
        "txid": tx.txid_hex,
        "hash": tx.txid_hex,
        "version": tx.version,
        "size": tx.size(),
        "locktime": tx.locktime,
        "vin": [],
        "vout": [],
        "hex": tx.serialize().hex(),
    }
    for txin in tx.vin:
        if tx.is_coinbase():
            out["vin"].append({
                "coinbase": txin.script_sig.hex(),
                "sequence": txin.sequence,
            })
        else:
            out["vin"].append({
                "txid": hash_to_hex(txin.prevout.hash),
                "vout": txin.prevout.n,
                "scriptSig": {"asm": script_asm(txin.script_sig),
                              "hex": txin.script_sig.hex()},
                "sequence": txin.sequence,
            })
    for n, txout in enumerate(tx.vout):
        out["vout"].append({
            "value": txout.value / 1e8,
            "n": n,
            "scriptPubKey": script_pubkey_json(node, txout.script_pubkey),
        })
    if block_hash is not None:
        idx = node.chainstate.block_index.get(block_hash)
        if idx is not None and idx in node.chainstate.chain:
            out["blockhash"] = hash_to_hex(block_hash)
            out["confirmations"] = node.chainstate.chain.height() - idx.height + 1
            out["time"] = out["blocktime"] = idx.header.time
    return out


def _parse_tx_hex(hex_str) -> CTransaction:
    try:
        return CTransaction.from_bytes(bytes.fromhex(hex_str))
    except Exception:
        raise RPCError(RPC_DESERIALIZATION_ERROR, "TX decode failed") from None


@rpc_method("sendrawtransaction")
def sendrawtransaction(node, params):
    require_params(params, 1, 2, "sendrawtransaction \"hexstring\" ( allowhighfees )")
    tx = _parse_tx_hex(params[0])
    txid = tx.txid
    if txid not in node.mempool:
        # already confirmed? (reference: RPC_VERIFY_ALREADY_IN_CHAIN)
        if node.chainstate.coins.get_coin(COutPoint(txid, 0)) is not None:
            raise RPCError(RPC_VERIFY_ALREADY_IN_CHAIN,
                           "transaction already in block chain")
        try:
            node.accept_to_mempool(tx)
        except MempoolError as e:
            raise RPCError(RPC_VERIFY_REJECTED,
                           f"{e.reason} {e.detail}".strip()) from None
    if node.connman is not None:
        node.connman.relay_tx(txid)
    return tx.txid_hex


@rpc_method("getrawtransaction")
def getrawtransaction(node, params):
    require_params(params, 1, 2, "getrawtransaction \"txid\" ( verbose )")
    txid = param_hash(params, 0)
    verbose = params[1] if len(params) > 1 else False
    tx = node.mempool.get_tx(txid)
    block_hash = None
    if tx is None:
        block_hash = node.txindex_lookup(txid) if node.txindex else None
        if block_hash is not None:
            block = node.chainstate.get_block(block_hash)
            if block is not None:
                for cand in block.vtx:
                    if cand.txid == txid:
                        tx = cand
                        break
    if tx is None:
        if node.txindex and not node._txindex_synced:
            # the reference's txindex reports "is still syncing" rather
            # than pretending the tx doesn't exist mid-backfill
            raise RPCError(
                RPC_INVALID_ADDRESS_OR_KEY,
                "No such mempool transaction. Blockchain transactions are "
                "still in the process of being indexed.",
            )
        raise RPCError(
            RPC_INVALID_ADDRESS_OR_KEY,
            "No such mempool transaction. Use -txindex to enable "
            "blockchain transaction queries.",
        )
    if not verbose:
        return tx.serialize().hex()
    return tx_to_json(node, tx, block_hash)


@rpc_method("decoderawtransaction")
def decoderawtransaction(node, params):
    require_params(params, 1, 1, "decoderawtransaction \"hexstring\"")
    tx = _parse_tx_hex(params[0])
    out = tx_to_json(node, tx)
    del out["hex"]
    return out


@rpc_method("createrawtransaction")
def createrawtransaction(node, params):
    """createrawtransaction [{"txid","vout"},...] {"address":amount,...}"""
    require_params(params, 2, 3, "createrawtransaction inputs outputs ( locktime )")
    inputs, outputs = params[0], params[1]
    locktime = int(params[2]) if len(params) > 2 else 0
    vin = []
    for inp in inputs:
        sequence = int(inp.get("sequence", 0xFFFFFFFF if locktime == 0 else 0xFFFFFFFE))
        vin.append(CTxIn(COutPoint(hex_to_hash(inp["txid"]), int(inp["vout"])),
                         b"", sequence))
    vout = []
    from ..wallet.keys import address_to_script

    for addr, amount in outputs.items():
        if addr == "data":
            from ..script.script import null_data_script

            vout.append(CTxOut(0, null_data_script(bytes.fromhex(amount))))
            continue
        script = address_to_script(addr, node.params)
        if script is None:
            raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, f"Invalid address: {addr}")
        vout.append(CTxOut(int(round(float(amount) * 1e8)), script))
    tx = CTransaction(version=1, vin=tuple(vin), vout=tuple(vout), locktime=locktime)
    return tx.serialize().hex()


@rpc_method("decodescript")
def decodescript(node, params):
    require_params(params, 1, 1, "decodescript \"hexstring\"")
    try:
        script = bytes.fromhex(params[0])
    except ValueError:
        raise RPCError(RPC_INVALID_PARAMETER, "argument must be hexadecimal string") from None
    out = script_pubkey_json(node, script)
    del out["hex"]  # reference omits hex in decodescript
    from ..crypto.hashes import hash160
    from ..script.script import p2sh_script

    out["p2sh"] = script_to_address(p2sh_script(hash160(script)), node.params)
    return out


@rpc_method("signrawtransaction")
def signrawtransaction(node, params):
    """signrawtransaction (src/rpc/rawtransaction.cpp:~700): sign inputs
    using wallet keys or caller-provided WIF keys; prevout scripts come from
    the UTXO set, the mempool, or the caller's prevtxs array. Partial
    signing returns complete=false with per-input errors."""
    require_params(params, 1, 3,
                   "signrawtransaction \"hexstring\" ( [{prevtxs},...] "
                   "[\"privatekey\",...] )")
    from ..consensus.tx import COIN
    from ..script.sighash import SIGHASH_ALL, SIGHASH_FORKID, SighashCache
    from ..wallet.keys import CKey
    from ..wallet.signing import SignError, solve_script_sig

    tx = _parse_tx_hex(params[0])
    prevtxs = params[1] if len(params) > 1 and params[1] else []
    privkeys = params[2] if len(params) > 2 and params[2] else None

    spents = {}
    for p in prevtxs:
        spk = bytes.fromhex(p["scriptPubKey"])
        amount = int(round(float(p.get("amount", 0)) * COIN))
        spents[(hex_to_hash(p["txid"]), int(p["vout"]))] = (spk, amount)
    for txin in tx.vin:
        key = (txin.prevout.hash, txin.prevout.n)
        if key in spents:
            continue
        coin = node.chainstate.coins.get_coin(txin.prevout)
        if coin is not None:
            spents[key] = (coin.out.script_pubkey, coin.out.value)
            continue
        parent = node.mempool.get_tx(txin.prevout.hash)
        if parent is not None and txin.prevout.n < len(parent.vout):
            out = parent.vout[txin.prevout.n]
            spents[key] = (out.script_pubkey, out.value)

    if privkeys is not None:
        keymap = {}
        for wif in privkeys:
            k = CKey.from_wif(wif, node.params)
            if k is None:
                raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                               "Invalid private key")
            keymap[k.pubkey_hash] = k
            keymap[k.pubkey] = k
        key_for_id = keymap.get
    else:
        wallet = node.load_wallet()
        wallet.maybe_relock()
        key_for_id = wallet.key_for_id

    hashtype = SIGHASH_ALL | SIGHASH_FORKID
    cache = SighashCache(tx)
    new_vin = []
    errors = []
    for i, txin in enumerate(tx.vin):
        ent = spents.get((txin.prevout.hash, txin.prevout.n))
        if ent is None:
            errors.append({
                "txid": hash_to_hex(txin.prevout.hash),
                "vout": txin.prevout.n,
                "error": "Input not found or already spent",
            })
            new_vin.append(txin)
            continue
        spk, amount = ent
        try:
            script_sig = solve_script_sig(
                spk, tx, i, amount, key_for_id, hashtype,
                enable_forkid=True, cache=cache,
            )
            new_vin.append(CTxIn(txin.prevout, script_sig, txin.sequence))
        except SignError as e:
            errors.append({
                "txid": hash_to_hex(txin.prevout.hash),
                "vout": txin.prevout.n,
                "error": str(e),
            })
            new_vin.append(txin)
    signed = CTransaction(tx.version, tuple(new_vin), tx.vout, tx.locktime)
    out = {"hex": signed.serialize().hex(), "complete": not errors}
    if errors:
        out["errors"] = errors
    return out


@rpc_method("gettxoutproof")
def gettxoutproof(node, params):
    """gettxoutproof ["txid",...] ( "blockhash" ) — hex-serialized
    CMerkleBlock proving the txids' inclusion (rpc/rawtransaction.cpp)."""
    require_params(params, 1, 2, "gettxoutproof [\"txid\",...] ( \"blockhash\" )")
    from ..consensus.merkleblock import CMerkleBlock

    if not isinstance(params[0], list) or not params[0]:
        raise RPCError(RPC_INVALID_PARAMETER, "Parameter 1 must be a non-empty array")
    txids = set()
    for t in params[0]:
        try:
            h = hex_to_hash(t)
        except Exception:
            raise RPCError(RPC_INVALID_PARAMETER,
                           f"Invalid txid: {t!r}") from None
        if h in txids:
            raise RPCError(RPC_INVALID_PARAMETER, f"Duplicated txid: {t}")
        txids.add(h)

    block_hash = None
    if len(params) > 1:
        block_hash = param_hash(params, 1)
        if node.chainstate.block_index.get(block_hash) is None:
            raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Block not found")
    else:
        # locate via txindex (or the UTXO set for an unspent output)
        any_txid = next(iter(txids))
        if node.txindex:
            block_hash = node.txindex_lookup(any_txid)
        if block_hash is None:
            from ..consensus.tx import COutPoint

            for n in range(64):
                coin = node.chainstate.coins.get_coin(COutPoint(any_txid, n))
                if coin is not None and coin.height >= 0:
                    idx = node.chainstate.chain[coin.height]
                    if idx is not None:
                        block_hash = idx.hash
                    break
        if block_hash is None:
            raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                           "Transaction not yet in block")
    block = node.chainstate.get_block(block_hash)
    if block is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Block not available")
    in_block = {tx.txid for tx in block.vtx}
    if not txids <= in_block:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Not all transactions found in specified or retrieved block")
    return CMerkleBlock.from_block(block, txid_set=txids).serialize().hex()


@rpc_method("verifytxoutproof")
def verifytxoutproof(node, params):
    """verifytxoutproof "proof" — txids the proof commits to, [] if the
    proven block is not in the active chain, error if malformed."""
    require_params(params, 1, 1, "verifytxoutproof \"proof\"")
    from ..consensus.merkleblock import CMerkleBlock

    try:
        mb = CMerkleBlock.from_bytes(bytes.fromhex(params[0]))
    except Exception:
        raise RPCError(RPC_DESERIALIZATION_ERROR, "Bad proof") from None
    got = mb.pmt.extract_matches()
    if got is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Invalid proof")
    root, matches = got
    if root != mb.header.hash_merkle_root:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Merkle root in proof does not match block header")
    idx = node.chainstate.block_index.get(mb.header.get_hash())
    if idx is None or node.chainstate.chain[idx.height] is not idx:
        return []  # proof is internally valid but block isn't in our chain
    return [hash_to_hex(txid) for _pos, txid in matches]


@rpc_method("fundrawtransaction")
def fundrawtransaction(node, params):
    """fundrawtransaction "hexstring" — add wallet inputs (and change)
    until the outputs + fee are covered; inputs stay UNSIGNED
    (src/wallet/rpcwallet.cpp fundrawtransaction)."""
    require_params(params, 1, 2, "fundrawtransaction \"hexstring\"")
    from ..consensus.tx import COIN
    from .wallet import RPC_WALLET_ERROR, _wallet, _wallet_fee

    tx = _parse_tx_hex(params[0])
    w = _wallet(node)
    tip = node.chainstate.tip().height
    fee = _wallet_fee(node)
    out_value = tx.total_output_value()
    # value already provided by existing inputs (wallet coins only)
    in_value = 0
    for txin in tx.vin:
        coin = w.coins.get(txin.prevout)
        if coin is not None:
            in_value += coin.txout.value
    need = out_value + fee - in_value
    selected = []
    if need > 0:
        coins = sorted(
            (c for c in w.available_coins(tip)
             if w.can_sign(c.txout.script_pubkey)
             and not any(i.prevout == c.outpoint for i in tx.vin)),
            key=lambda c: c.txout.value, reverse=True,
        )
        got = 0
        for c in coins:
            selected.append(c)
            got += c.txout.value
            if got >= need:
                break
        if got < need:
            raise RPCError(RPC_WALLET_ERROR, "Insufficient funds")
        in_value += got
    change = in_value - out_value - fee
    vout = list(tx.vout)
    changepos = -1
    if change > 546:
        from ..wallet.wallet import WalletError

        try:
            change_key = w.derive_new_key()
        except WalletError as e:
            from .wallet import RPC_WALLET_UNLOCK_NEEDED

            raise RPCError(RPC_WALLET_UNLOCK_NEEDED, str(e)) from None
        w.add_key(change_key)
        changepos = len(vout)
        vout.append(CTxOut(change, change_key.p2pkh_script()))
    else:
        fee += max(change, 0)  # dust change folds into the fee — report it
    funded = CTransaction(
        tx.version,
        tuple(tx.vin) + tuple(CTxIn(c.outpoint) for c in selected),
        tuple(vout),
        tx.locktime,
    )
    return {
        "hex": funded.serialize().hex(),
        "fee": fee / COIN,
        "changepos": changepos,
    }
