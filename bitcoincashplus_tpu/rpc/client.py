"""Thin JSON-RPC client — the bitcoin-cli / test-framework transport.

Reference: src/bitcoin-cli.cpp (CallRPC: HTTP POST with basic auth from
-rpcuser/-rpcpassword or the datadir `.cookie` file).
"""

from __future__ import annotations

import base64
import http.client
import json
import os
from typing import Optional


class JSONRPCException(Exception):
    def __init__(self, error: dict):
        super().__init__(error.get("message", str(error)))
        self.error = error
        self.code = error.get("code", -1)


def read_cookie(datadir: str) -> tuple[str, str]:
    with open(os.path.join(datadir, ".cookie")) as f:
        user, _, password = f.read().strip().partition(":")
    return user, password


class RPCClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8332,
                 user: str = "", password: str = "",
                 datadir: Optional[str] = None, timeout: float = 120.0):
        if datadir and not (user and password):
            user, password = read_cookie(datadir)
        self.host, self.port, self.timeout = host, port, timeout
        self._auth = base64.b64encode(f"{user}:{password}".encode()).decode()
        self._id = 0

    def call(self, method: str, *params):
        self._id += 1
        payload = json.dumps({
            "jsonrpc": "1.0", "id": self._id,
            "method": method, "params": list(params),
        })
        conn = http.client.HTTPConnection(self.host, self.port, timeout=self.timeout)
        try:
            conn.request("POST", "/", payload, {
                "Authorization": f"Basic {self._auth}",
                "Content-Type": "application/json",
            })
            resp = conn.getresponse()
            body = json.loads(resp.read())
        finally:
            conn.close()
        if body.get("error"):
            raise JSONRPCException(body["error"])
        return body["result"]

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda *params: self.call(name, *params)
