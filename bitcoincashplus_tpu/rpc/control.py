"""Control / introspection RPCs.

Reference: src/rpc/server.cpp (help, stop, uptime), src/rpc/misc.cpp
(getmemoryinfo, validateaddress). `gettpuinfo` is this framework's own
observability surface (SURVEY.md §6.5): per-dispatch TPU batch stats,
ConnectBlock phase timings, and backend/device identity.
"""

from __future__ import annotations

from ..util.log import uptime as _uptime
from .registry import (
    RPC_METHODS,
    RPC_METHOD_NOT_FOUND,
    RPCError,
    require_params,
    rpc_method,
)


@rpc_method("help")
def help_(node, params):
    if params:
        name = params[0]
        fn = RPC_METHODS.get(name)
        if fn is None:
            raise RPCError(RPC_METHOD_NOT_FOUND, f"help: unknown command: {name}")
        return (fn.__doc__ or name).strip()
    return "\n".join(sorted(RPC_METHODS))


@rpc_method("stop")
def stop(node, params):
    node.stop()
    return "bcpd stopping"


@rpc_method("uptime")
def uptime(node, params):
    import time

    return int(time.time()) - node.start_time


@rpc_method("getmemoryinfo")
def getmemoryinfo(node, params):
    import resource

    usage = resource.getrusage(resource.RUSAGE_SELF)
    return {"locked": {"used": usage.ru_maxrss * 1024, "free": 0,
                       "total": usage.ru_maxrss * 1024}}


@rpc_method("verifymessage")
def verifymessage(node, params):
    require_params(params, 3, 3,
                   "verifymessage \"address\" \"signature\" \"message\"")
    from ..wallet.message import verify_message

    return verify_message(str(params[0]), str(params[1]), str(params[2]),
                          node.params)


@rpc_method("signmessagewithprivkey")
def signmessagewithprivkey(node, params):
    require_params(params, 2, 2,
                   "signmessagewithprivkey \"privkey\" \"message\"")
    from ..wallet.keys import CKey
    from ..wallet.message import sign_message
    from .registry import RPC_INVALID_ADDRESS_OR_KEY

    key = CKey.from_wif(str(params[0]), node.params)
    if key is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Invalid private key")
    return sign_message(key, str(params[1]))


@rpc_method("validateaddress")
def validateaddress(node, params):
    require_params(params, 1, 1, "validateaddress \"address\"")
    from ..wallet.keys import address_to_script

    script = address_to_script(params[0], node.params)
    if script is None:
        return {"isvalid": False}
    return {
        "isvalid": True,
        "address": params[0],
        "scriptPubKey": script.hex(),
    }


@rpc_method("gettpuinfo")
def gettpuinfo(node, params):
    """TPU observability: ECDSA batch-dispatch stats (ops/ecdsa_batch.STATS),
    supervised-dispatch circuit-breaker state per subsystem (ops/dispatch:
    state, trip counts, fallback call/item tallies — fallback_items is sigs
    for ecdsa, hashes for sha256, leaves for merkle), the active
    fault-injection config (BCP_FAULT_*), sigcache hit/insert/eviction
    rates, the device-resident mining loop (``mining``: active sweep
    engine, template generation, tiles swept, candidate FIFO depth/hits,
    buffer-swap count, poll cadence — mining/resident.py),
    ConnectBlock phase timings (-debug=bench counters), the
    pipelined-IBD settle horizon (``pipeline``: depth/occupancy, per-leg
    times, unwind count, cross-block lane fill and overlap fraction, and
    the speculation tree's live shape under ``pipeline.tree`` — branches,
    layers, drops, reorg depth, collapse level), the
    BIP30 pre-scan fast-path counters (``bip30``), the active
    backend/device, the always-on signature service (``serving``: flush
    reasons, queue depth, dedup/cache hits, import-priority preemptions,
    enqueue->verdict wait quantiles), and — when P2P is running — the
    peer-supervision ledger (``net``: misbehavior charges, discharge
    reasons, stall re-requests, flood charges, orphan pool accounting,
    banlist size), plus the sharded chainstate store (``store``: shard
    fan-out, commit epoch, MuHash set digest, last parallel flush,
    assumeutxo snapshot progress — store/sharded.py), and — when the
    fleet front door is up — the gateway (``gateway``: admission/shed/
    coalesce/failover tallies and the replica rotation with per-replica
    breaker state and probed tips — serving/gateway.py)."""
    from ..ops import dispatch, ecdsa_batch
    from ..util import faults

    stats = ecdsa_batch.STATS.snapshot()
    devices = []
    try:
        import jax

        devices = [str(d) for d in jax.devices()]
    except Exception:
        pass
    from ..mempool.accept import accept_latency_quantiles, accept_stage_quantiles
    from ..mining.assembler import template_build_quantiles
    from ..util import devicewatch, lockwatch, telemetry

    return {
        "backend": node.backend,
        "devices": devices,
        # active verify-kernel selection (-ecdsakernel) + GLV health: the
        # fixed-base comb build cost (0.0 until the first GLV dispatch
        # builds it), host decompose/pack stage times, fallback tallies
        "ecdsa": ecdsa_batch.kernel_info(),
        "batch": stats,
        "breakers": dispatch.snapshot(),
        "faults": faults.INJECTOR.snapshot(),
        "sigcache": node.sigcache.snapshot(),
        # flood-scale mempool (ISSUE 20): frontier depth, column-sync
        # tallies, bulk-evict episodes, fallback/differential-gate
        # verdicts, plus the per-stage accept and template-build p50/p99;
        # getattr-guarded for harness stubs that pass a bare namespace
        "mempool": ({**node.mempool.perf_snapshot(),
                     "accept_stages": accept_stage_quantiles(),
                     "template_build": template_build_quantiles()}
                    if hasattr(getattr(node, "mempool", None),
                               "perf_snapshot") else {}),
        # the device-resident mining loop (mining/resident): sweep engine
        # selection + resident-loop state; getattr-guarded for harness
        # stubs that pass a bare node namespace
        "mining": (node.mining_snapshot()
                   if hasattr(node, "mining_snapshot") else {}),
        "connectblock": dict(node.chainstate.bench),
        # getattr-guarded: harness stubs pass a bare chainstate namespace
        "pipeline": (node.chainstate.pipeline_snapshot()
                     if hasattr(node.chainstate, "pipeline_snapshot")
                     else {}),
        "bip30": dict(getattr(node.chainstate, "bip30_stats", {})),
        # the sharded chainstate facade (store/sharded): fan-out, commit
        # epoch, set digest, last parallel flush, assumeutxo progress;
        # getattr-guarded for harness stubs and legacy single-file nodes
        "store": (node.store_info()
                  if hasattr(node, "store_info") else {}),
        "net": (node.connman.net_snapshot()
                if getattr(node, "connman", None) is not None else {}),
        # the always-on signature service (serving/sigservice): flush
        # reasons, queue depth, dedup/cache hits, preemptions, and the
        # enqueue->verdict wait quantiles; {"enabled": False} when
        # -sigservice=off
        "serving": (node.sigservice.snapshot()
                    if getattr(node, "sigservice", None) is not None
                    else {"enabled": False}),
        # fleet serving front door (serving/gateway): admission/shed/
        # coalesce/failover tallies plus the replica rotation (per-replica
        # breaker state, probed tip, lag verdict); {"enabled": False}
        # unless -gateway is up
        "gateway": ({"enabled": True, **node.gateway.snapshot()}
                    if getattr(node, "gateway", None) is not None
                    else {"enabled": False}),
        # unified-telemetry view (util/telemetry): the active level, span
        # ring-buffer occupancy, and the serving path's p50/p90/p99
        # mempool accept latency (the registry's histogram — getmetrics /
        # /metrics expose the full distribution)
        "telemetry": {
            "mode": telemetry.mode(),
            "spans": telemetry.TRACER.stats(),
            "accept_latency": accept_latency_quantiles(),
        },
        # device-lane monitor (util/devicewatch): per-program compile
        # counts + distinct-shape signatures vs declared budgets (+ any
        # first-compile cost-analysis FLOPs/bytes), host<->device
        # transfer byte totals per site, profiler state, and the stall
        # watchdog
        "device": devicewatch.snapshot(),
        # runtime lock-order sentinel (util/lockwatch): locks watched,
        # acquisition counts, max held-depth, the live ordering edges,
        # and any inversions/cycles; {"enabled": False} unless the
        # process runs with BCP_LOCKWATCH=1
        "lockwatch": lockwatch.snapshot(),
    }


@rpc_method("getmetrics")
def getmetrics(node, params):
    """getmetrics

    The unified telemetry registry (util/telemetry): every counter/gauge/
    histogram family — native metrics plus the collector-projected STATS,
    breaker, sigcache, pipeline, and net surfaces — with histogram bucket
    counts and p50/p90/p99 estimates inline. The same namespace Prometheus
    scrapes at /metrics on the REST server."""
    from ..util import telemetry

    return telemetry.REGISTRY.snapshot()


@rpc_method("dumptrace")
def dumptrace(node, params):
    """dumptrace ( "path" )

    Write the span tracer's ring buffer as Chrome-trace/perfetto JSON
    (load at ui.perfetto.dev). Default path: <datadir>/trace.json.
    Returns {path, events, mode} — with -telemetry below `trace` the
    buffer is empty and the dump says so rather than erroring."""
    import os as _os

    from ..util import telemetry

    path = str(params[0]) if params else _os.path.join(node.datadir,
                                                       "trace.json")
    events = telemetry.TRACER.dump(path)
    return {"path": path, "events": events, "mode": telemetry.mode()}


@rpc_method("startprofile")
def startprofile(node, params):
    """startprofile ( "dir" )

    Start an on-demand jax.profiler trace (device-side XLA timeline —
    the layer below the span tracer's host view). Default directory:
    <datadir>/profile. Stop with ``stopprofile``; the dump is
    TensorBoard-compatible (plugins/profile/<ts>/*.xplane.pb +
    trace.json.gz — load with tensorboard --logdir or xprof). Errors if
    a profile is already running (the profiler is process-global)."""
    import os as _os

    from ..util import devicewatch
    from .registry import RPC_INVALID_PARAMETER, RPC_MISC_ERROR

    path = str(params[0]) if params else _os.path.join(node.datadir,
                                                       "profile")
    try:
        return devicewatch.start_profile(path)
    except RuntimeError as e:
        raise RPCError(RPC_INVALID_PARAMETER, str(e)) from None
    except Exception as e:  # noqa: BLE001 — backend/profiler failure
        raise RPCError(RPC_MISC_ERROR,
                       f"startprofile failed: {type(e).__name__}: {e}"
                       ) from None


@rpc_method("stopprofile")
def stopprofile(node, params):
    """stopprofile

    Stop the running jax.profiler trace started by ``startprofile``;
    returns {path, seconds}. Errors if no profile is running."""
    from ..util import devicewatch
    from .registry import RPC_INVALID_PARAMETER, RPC_MISC_ERROR

    try:
        return devicewatch.stop_profile()
    except RuntimeError as e:
        raise RPCError(RPC_INVALID_PARAMETER, str(e)) from None
    except Exception as e:  # noqa: BLE001 — backend/profiler failure
        raise RPCError(RPC_MISC_ERROR,
                       f"stopprofile failed: {type(e).__name__}: {e}"
                       ) from None


@rpc_method("createmultisig")
def createmultisig(node, params):
    """createmultisig nrequired ["key",...] — address + redeemScript
    (src/rpc/misc.cpp). Keys must be hex pubkeys (no wallet lookup)."""
    require_params(params, 2, 2, "createmultisig nrequired [\"key\",...]")
    from ..crypto.hashes import hash160
    from ..script.script import p2sh_script
    from ..wallet.keys import script_to_address
    from .wallet import _parse_multisig_params

    m, redeem = _parse_multisig_params(node, None, params)
    return {
        "address": script_to_address(p2sh_script(hash160(redeem)),
                                     node.params),
        "redeemScript": redeem.hex(),
    }


@rpc_method("getinfo")
def getinfo(node, params):
    """getinfo — the classic aggregated snapshot (src/rpc/misc.cpp; still
    present in this lineage, deprecated later)."""
    from ..consensus.tx import COIN
    from .blockchain import difficulty_from_bits

    tip = node.chainstate.tip()
    out = {
        "version": 140000,
        "protocolversion": 70015,
        "blocks": tip.height,
        "timeoffset": 0,
        "connections": (len(node.connman.peers)
                        if node.connman is not None else 0),
        "proxy": "",
        "difficulty": difficulty_from_bits(tip.header.bits),
        "testnet": node.params.network == "test",
        "chain": node.params.network,
        "relayfee": node.min_relay_fee_rate / COIN,
        "errors": "",
    }
    if node.wallet is not None:
        out["walletversion"] = 2
        out["balance"] = node.wallet.balance(tip.height) / COIN
        out["keypoololdest"] = 0
        out["keypoolsize"] = len(node.wallet.keys_by_pubkey)
        if node.wallet.is_crypted:
            out["unlocked_until"] = (0 if node.wallet.is_locked
                                     else int(node.wallet.unlocked_until))
    return out
