"""Blockchain RPCs.

Reference: src/rpc/blockchain.cpp (getblockchaininfo, getbestblockhash,
getblockcount, getblockhash, getblock, getblockheader, getdifficulty,
getrawmempool, getmempoolinfo, getmempoolentry, gettxout, gettxoutsetinfo,
invalidateblock, reconsiderblock, verifychain).
"""

from __future__ import annotations

from ..consensus.serialize import hash_to_hex
from ..consensus.tx import COutPoint
from ..validation.chain import BlockStatus
from .rawtransaction import tx_to_json
from .registry import (
    RPC_INVALID_ADDRESS_OR_KEY,
    RPC_INVALID_PARAMETER,
    RPC_MISC_ERROR,
    RPCError,
    param_hash,
    require_params,
    rpc_method,
)


def difficulty_from_bits(bits: int) -> float:
    """GetDifficulty (src/rpc/blockchain.cpp): ratio of the max target
    (0x1d00ffff) to the current target."""
    shift = (bits >> 24) & 0xFF
    diff = 0x0000FFFF / (bits & 0x00FFFFFF)
    while shift < 29:
        diff *= 256.0
        shift += 1
    while shift > 29:
        diff /= 256.0
        shift -= 1
    return diff


def _block_index_or_raise(node, h: bytes):
    idx = node.chainstate.block_index.get(h)
    if idx is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Block not found")
    return idx


def header_to_json(node, idx) -> dict:
    cs = node.chainstate
    nxt = cs.chain.next(idx)
    return {
        "hash": hash_to_hex(idx.hash),
        "confirmations": (cs.chain.height() - idx.height + 1)
        if idx in cs.chain else -1,
        "height": idx.height,
        "version": idx.header.version,
        "versionHex": f"{idx.header.version & 0xFFFFFFFF:08x}",
        "merkleroot": hash_to_hex(idx.header.hash_merkle_root),
        "time": idx.header.time,
        "mediantime": idx.get_median_time_past(),
        "nonce": idx.header.nonce,
        "bits": f"{idx.header.bits:08x}",
        "difficulty": difficulty_from_bits(idx.header.bits),
        "chainwork": f"{idx.chain_work:064x}",
        "previousblockhash": hash_to_hex(idx.prev.hash) if idx.prev else None,
        "nextblockhash": hash_to_hex(nxt.hash) if nxt else None,
    }


@rpc_method("getblockchaininfo")
def getblockchaininfo(node, params):
    cs = node.chainstate
    tip = cs.tip()
    best_header = max(cs.block_index.values(), key=lambda i: i.chain_work)
    out = {
        "chain": node.params.network,
        "blocks": tip.height,
        "headers": best_header.height,
        "bestblockhash": hash_to_hex(tip.hash),
        "difficulty": difficulty_from_bits(tip.header.bits),
        "mediantime": tip.get_median_time_past(),
        "verificationprogress": 1.0,
        "chainwork": f"{tip.chain_work:064x}",
        "pruned": node.prune_mode,
        "softforks": _softforks(node, tip),
    }
    if node.prune_mode:
        # prune_height is tracked incrementally (and persisted) by
        # prune_block_files — no chain scan under cs_main here
        out["pruneheight"] = node.prune_height
    # snapshot-onboarded nodes expose the certificate/quarantine view the
    # fleet probe keys on (serving/replicas.py); absent everywhere else
    snap_info = node.snapshot_info()
    if snap_info is not None:
        out["snapshot"] = snap_info
    return out


def _softforks(node, tip):
    """BIP9 deployment status per getblockchaininfo's bip9_softforks
    (rpc/blockchain.cpp:~1200) + the unknown-version upgrade warning count
    (validation.cpp:~2200)."""
    from ..consensus.versionbits import (
        get_state_for,
        get_state_since_height,
        unknown_version_signalling,
    )

    c = node.params.consensus
    out = {}
    for dep in c.deployments:
        cache = node.versionbits_cache.for_dep(dep)
        state = get_state_for(
            dep, tip, c.miner_confirmation_window,
            c.rule_change_activation_threshold, cache,
        )
        out[dep.name] = {
            "status": state.value,
            "bit": dep.bit,
            "startTime": dep.start_time,
            "timeout": dep.timeout,
            "since": get_state_since_height(
                dep, tip, c.miner_confirmation_window,
                c.rule_change_activation_threshold, cache,
            ),
        }
    out["unknown_versions_last_100"] = unknown_version_signalling(
        tip, c.deployments, c.miner_confirmation_window
    )
    return out


@rpc_method("getbestblockhash")
def getbestblockhash(node, params):
    # settled_tip, not chain.tip(): a block inside the pipelined-IBD settle
    # horizon (signature batch still in flight) is never externalized
    return hash_to_hex(node.chainstate.settled_tip().hash)


@rpc_method("getblockcount")
def getblockcount(node, params):
    # settled height: must agree with getbestblockhash under an open
    # settle horizon (a speculative block may still unwind)
    return node.chainstate.settled_tip().height


@rpc_method("getblockhash")
def getblockhash(node, params):
    require_params(params, 1, 1, "getblockhash height")
    height = int(params[0])
    idx = node.chainstate.chain[height]
    if idx is None or height > node.chainstate.settled_tip().height:
        raise RPCError(RPC_INVALID_PARAMETER, "Block height out of range")
    return hash_to_hex(idx.hash)


@rpc_method("getblockheader")
def getblockheader(node, params):
    require_params(params, 1, 2, "getblockheader \"hash\" ( verbose )")
    h = param_hash(params, 0)
    idx = _block_index_or_raise(node, h)
    verbose = params[1] if len(params) > 1 else True
    if not verbose:
        return idx.header.serialize().hex()
    return header_to_json(node, idx)


@rpc_method("getblock")
def getblock(node, params):
    require_params(params, 1, 2, "getblock \"hash\" ( verbosity )")
    h = param_hash(params, 0)
    idx = _block_index_or_raise(node, h)
    block = node.chainstate.get_block(h)
    if block is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Block not available (no data)")
    verbosity = params[1] if len(params) > 1 else 1
    if isinstance(verbosity, bool):
        verbosity = int(verbosity)
    if verbosity == 0:
        return block.serialize().hex()
    out = header_to_json(node, idx)
    out["size"] = block.size()
    out["nTx"] = len(block.vtx)
    if verbosity == 1:
        out["tx"] = [tx.txid_hex for tx in block.vtx]
    else:
        out["tx"] = [tx_to_json(node, tx) for tx in block.vtx]
    return out


@rpc_method("getdifficulty")
def getdifficulty(node, params):
    return difficulty_from_bits(node.chainstate.tip().header.bits)


@rpc_method("getchaintips")
def getchaintips(node, params):
    """getchaintips (src/rpc/blockchain.cpp): every fork tip + its status."""
    cs = node.chainstate
    has_child = {idx.prev for idx in cs.block_index.values() if idx.prev}
    tips = [i for i in cs.block_index.values() if i not in has_child]
    out = []
    for idx in tips:
        fork = cs.chain.find_fork(idx)
        branch_len = idx.height - (fork.height if fork else 0)
        if idx in cs.chain:
            status = "active"
        elif idx.status & BlockStatus.FAILED_MASK:
            status = "invalid"
        elif idx.chain_tx == 0:
            status = "headers-only"
        elif idx.is_valid(BlockStatus.VALID_SCRIPTS):
            status = "valid-fork"
        else:
            status = "valid-headers"
        out.append({
            "height": idx.height,
            "hash": hash_to_hex(idx.hash),
            "branchlen": branch_len,
            "status": status,
        })
    return out


@rpc_method("getrawmempool")
def getrawmempool(node, params):
    verbose = params[0] if params else False
    pool = node.mempool
    if not verbose:
        return [hash_to_hex(txid) for txid in pool.entries]
    return {hash_to_hex(txid): _mempool_entry_json(pool, e)
            for txid, e in pool.entries.items()}


def _mempool_entry_json(pool, e) -> dict:
    return {
        "size": e.size,
        "fee": e.base_fee / 1e8,
        "modifiedfee": e.fee / 1e8,
        "time": e.time,
        "height": e.entry_height,
        "descendantcount": e.count_with_descendants,
        "descendantsize": e.size_with_descendants,
        "descendantfees": e.fees_with_descendants,
        "ancestorcount": e.count_with_ancestors,
        "ancestorsize": e.size_with_ancestors,
        "ancestorfees": e.fees_with_ancestors,
        "depends": [hash_to_hex(p) for p in pool.parents_in_pool(e.tx)],
    }


@rpc_method("getmempoolentry")
def getmempoolentry(node, params):
    require_params(params, 1, 1, "getmempoolentry \"txid\"")
    txid = param_hash(params, 0)
    e = node.mempool.get(txid)
    if e is None:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY, "Transaction not in mempool")
    return _mempool_entry_json(node.mempool, e)


@rpc_method("savemempool")
def savemempool(node, params):
    """savemempool — dump the mempool to disk now (mempool.dat)."""
    from ..mempool.persist import dump_mempool

    dump_mempool(node.mempool, node._mempool_dat)
    return None


@rpc_method("getmempoolinfo")
def getmempoolinfo(node, params):
    info = node.mempool.info()
    info["mempoolminfee"] = node.min_relay_fee_rate / 1e8
    # flood-scale perf section (ISSUE 20): batch mode, frontier depths,
    # column occupancy, bulk-evict / fallback / gate tallies
    info["perf"] = node.mempool.perf_snapshot()
    return info


@rpc_method("gettxout")
def gettxout(node, params):
    require_params(params, 2, 3, "gettxout \"txid\" n ( include_mempool )")
    txid = param_hash(params, 0)
    n = int(params[1])
    include_mempool = params[2] if len(params) > 2 else True
    op = COutPoint(txid, n)
    if include_mempool and node.mempool.get_spender(op) is not None:
        return None  # spent by an in-pool tx
    coin = node.chainstate.coins.get_coin(op)
    if coin is None and include_mempool:
        out = node.mempool.get_output(op)
        if out is not None:
            from ..validation.coins import Coin

            coin = Coin(out, 0x7FFFFFFF, False)
    if coin is None:
        return None
    cs = node.chainstate
    return {
        "bestblock": hash_to_hex(cs.tip().hash),
        "confirmations": 0 if coin.height == 0x7FFFFFFF
        else cs.chain.height() - coin.height + 1,
        "value": coin.out.value / 1e8,
        "scriptPubKey": {"hex": coin.out.script_pubkey.hex()},
        "coinbase": coin.is_coinbase,
    }


@rpc_method("gettxoutsetinfo")
def gettxoutsetinfo(node, params):
    cs = node.chainstate
    cs.flush()  # count the persistent set, like the reference's FlushStateToDisk
    total = 0
    n = 0
    for op, coin in _iterate_coins(node):
        n += 1
        total += coin.out.value
    out = {
        "height": cs.chain.height(),
        "bestblock": hash_to_hex(cs.tip().hash),
        "txouts": n,
        "total_amount": total / 1e8,
    }
    # the incremental MuHash set digest (sharded store only — the legacy
    # single-file layout predates accumulator maintenance)
    digest_fn = getattr(node.coins_db, "muhash_digest", None)
    if digest_fn is not None:
        out["muhash"] = digest_fn().hex()
        out["shards"] = node.coins_db.n_shards
        out["epoch"] = node.coins_db.epoch
    return out


def _iterate_coins(node):
    import struct

    from ..validation.coins import Coin

    # facade-uniform iteration (CoinsDB and ShardedCoinsDB both expose
    # iterate_coins) — never reach into a .kv that sharded stores lack
    for k36, v in node.coins_db.iterate_coins():
        op = COutPoint(k36[:32], struct.unpack("<I", k36[32:36])[0])
        yield op, Coin.deserialize(v)


@rpc_method("dumptxoutset")
def dumptxoutset(node, params):
    require_params(params, 1, 1, "dumptxoutset \"path\"")
    cs = node.chainstate
    cs.flush()  # the snapshot is cut from the PERSISTED set
    tip = cs.tip()
    headers = [cs.chain[h].header.serialize() for h in range(tip.height + 1)]
    from ..store import snapshot as snapshot_mod

    # proof-carrying certificate: built from this node's own undo data
    # (store/certificate.py). A node that cannot attest — legacy store,
    # or itself snapshot-onboarded without full backfill — dumps an
    # uncertified snapshot with a warning rather than failing the dump.
    from ..store.certificate import CertificateError
    from ..util.log import log_printf

    certificate = None
    try:
        certificate = node.build_snapshot_certificate(tip.height)
    except CertificateError as e:
        log_printf("dumptxoutset: cannot attest (%s) — writing an "
                   "UNCERTIFIED snapshot; loaders will quarantine it "
                   "until fully validated", e)

    manifest = snapshot_mod.dump_snapshot(
        node.coins_db, str(params[0]), headers, tip.height, tip.hash,
        node.params.network, certificate=certificate)
    return {
        "path": str(params[0]),
        "height": manifest["height"],
        "bestblock": manifest["best_block"],
        "coins": manifest["coins"],
        "muhash": manifest["muhash"],
        "nfiles": len(manifest["files"]),
        "certified": certificate is not None,
        "epochs": len((certificate or {}).get("epochs", [])),
    }


@rpc_method("loadtxoutset")
def loadtxoutset(node, params):
    require_params(params, 1, 1, "loadtxoutset \"path\"")
    from ..store.snapshot import SnapshotError

    try:
        return node.load_utxo_snapshot(str(params[0]))
    except (SnapshotError, ValueError, OSError) as e:
        raise RPCError(RPC_MISC_ERROR, f"loadtxoutset: {e}")


@rpc_method("invalidateblock")
def invalidateblock(node, params):
    require_params(params, 1, 1, "invalidateblock \"hash\"")
    idx = _block_index_or_raise(node, param_hash(params, 0))
    node.chainstate.invalidate_block(idx)
    node.chainstate.flush()
    return None


@rpc_method("reconsiderblock")
def reconsiderblock(node, params):
    require_params(params, 1, 1, "reconsiderblock \"hash\"")
    idx = _block_index_or_raise(node, param_hash(params, 0))
    node.chainstate.reconsider_block(idx)
    node.chainstate.flush()
    return None


@rpc_method("verifychain")
def verifychain(node, params):
    level = int(params[0]) if params else 3
    n_blocks = int(params[1]) if len(params) > 1 else 6
    try:
        return node.verify_db(n_blocks=n_blocks, level=level)
    except Exception:
        return False


@rpc_method("getblockstats")
def getblockstats(node, params):
    """getblockstats (rpc/blockchain.cpp:~1700): per-block fee/size stats.
    Fees come from the block's undo data (spent-coin values), the same
    source the reference uses, so no txindex is needed."""
    require_params(params, 1, 2, "getblockstats hash_or_height ( stats )")
    from ..consensus.params import get_block_subsidy
    from ..validation.coins import BlockUndo

    cs = node.chainstate
    target = params[0]
    if isinstance(target, int) or (isinstance(target, str) and
                                   len(target) != 64):
        idx = cs.chain[int(target)]
        if idx is None:
            raise RPCError(RPC_INVALID_PARAMETER,
                           "Target block height out of range")
    else:
        idx = _block_index_or_raise(node, param_hash(params, 0))
    block = cs.get_block(idx.hash)
    if block is None:
        raise RPCError(RPC_MISC_ERROR, "Block not available")

    raw_undo = node.block_store.get_undo(idx.hash)
    undo = BlockUndo.from_bytes(raw_undo) if raw_undo else None

    fees, feerates, sizes = [], [], []
    ins = outs = total_out = 0
    for t, tx in enumerate(block.vtx[1:]):
        size = len(tx.serialize())
        sizes.append(size)
        ins += len(tx.vin)
        outs += len(tx.vout)
        out_sum = sum(o.value for o in tx.vout)
        total_out += out_sum
        if undo is not None and t < len(undo.vtxundo):
            in_sum = sum(c.out.value for c in undo.vtxundo[t].prevouts)
            fee = in_sum - out_sum
            fees.append(fee)
            if size:
                feerates.append(fee * 1000 // size)
    outs += len(block.vtx[0].vout)
    total_out += sum(o.value for o in block.vtx[0].vout)

    def med(v):
        return sorted(v)[len(v) // 2] if v else 0

    return {
        "blockhash": hash_to_hex(idx.hash),
        "height": idx.height,
        "time": idx.header.time,
        "mediantime": idx.get_median_time_past(),
        "txs": len(block.vtx),
        "ins": ins,
        "outs": outs,
        "subsidy": get_block_subsidy(idx.height, node.params.consensus),
        "totalfee": sum(fees),
        "avgfee": sum(fees) // len(fees) if fees else 0,
        "medianfee": med(fees),
        "minfee": min(fees) if fees else 0,
        "maxfee": max(fees) if fees else 0,
        "avgfeerate": sum(feerates) // len(feerates) if feerates else 0,
        "medianfeerate": med(feerates),
        "minfeerate": min(feerates) if feerates else 0,
        "maxfeerate": max(feerates) if feerates else 0,
        "total_size": sum(sizes),
        "avgtxsize": sum(sizes) // len(sizes) if sizes else 0,
        "mediantxsize": med(sizes),
        "mintxsize": min(sizes) if sizes else 0,
        "maxtxsize": max(sizes) if sizes else 0,
        "total_out": total_out,
    }


@rpc_method("getmempoolancestors")
def getmempoolancestors(node, params):
    """getmempoolancestors (rpc/blockchain.cpp): in-pool ancestors of a
    mempool tx, txid list or verbose entry map."""
    require_params(params, 1, 2, "getmempoolancestors \"txid\" ( verbose )")
    txid = param_hash(params, 0)
    pool = node.mempool
    if txid not in pool.entries:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Transaction not in mempool")
    anc = pool.calculate_ancestors(pool.entries[txid].tx) - {txid}
    verbose = params[1] if len(params) > 1 else False
    if not verbose:
        return [hash_to_hex(t) for t in sorted(anc)]
    return {hash_to_hex(t): _mempool_entry_json(pool, pool.entries[t])
            for t in anc}


@rpc_method("getmempooldescendants")
def getmempooldescendants(node, params):
    require_params(params, 1, 2, "getmempooldescendants \"txid\" ( verbose )")
    txid = param_hash(params, 0)
    pool = node.mempool
    if txid not in pool.entries:
        raise RPCError(RPC_INVALID_ADDRESS_OR_KEY,
                       "Transaction not in mempool")
    desc = pool.calculate_descendants(txid) - {txid}
    verbose = params[1] if len(params) > 1 else False
    if not verbose:
        return [hash_to_hex(t) for t in sorted(desc)]
    return {hash_to_hex(t): _mempool_entry_json(pool, pool.entries[t])
            for t in desc}


@rpc_method("preciousblock")
def preciousblock(node, params):
    """preciousblock \"hash\": prefer this block over equal-work
    competitors (validation.cpp PreciousBlock)."""
    require_params(params, 1, 1, "preciousblock \"blockhash\"")
    idx = _block_index_or_raise(node, param_hash(params, 0))
    node.chainstate.precious_block(idx)
    return None


@rpc_method("getchaintxstats")
def getchaintxstats(node, params):
    """getchaintxstats ( nblocks "blockhash" ) — tx rate over a window
    ending at the given block (src/rpc/blockchain.cpp)."""
    cs = node.chainstate
    final = cs.tip()
    if len(params) > 1 and params[1]:
        final = _block_index_or_raise(node, param_hash(params, 1))
        if cs.chain[final.height] is not final:
            raise RPCError(RPC_INVALID_PARAMETER,
                           "Block is not in main chain")
    # default window: one month of target spacing, clamped to the chain
    spacing = node.params.consensus.pow_target_spacing
    if params and params[0] is not None:
        window = int(params[0])
    else:
        window = max(0, min(final.height - 1, 30 * 24 * 3600 // spacing))
    # Core's bound: 0 <= blockcount < height (0 = totals only)
    if window < 0 or (window > 0 and window >= final.height):
        raise RPCError(RPC_INVALID_PARAMETER,
                       "Invalid block count: should be between 0 and the "
                       "block's height - 1")
    out = {
        "time": final.header.time,
        "txcount": final.chain_tx,
        "window_final_block_hash": hash_to_hex(final.hash),
        "window_block_count": window,
    }
    if window > 0:
        first = cs.chain[final.height - window]
        interval = final.get_median_time_past() - first.get_median_time_past()
        out["window_tx_count"] = final.chain_tx - first.chain_tx
        out["window_interval"] = interval
        if interval > 0:
            out["txrate"] = (final.chain_tx - first.chain_tx) / interval
    return out


@rpc_method("pruneblockchain")
def pruneblockchain(node, params):
    """pruneblockchain height — manual prune (requires -prune=1)."""
    require_params(params, 1, 1, "pruneblockchain height")
    if not node.prune_mode:
        raise RPCError(RPC_MISC_ERROR,
                       "Cannot prune blocks because node is not in prune "
                       "mode.")
    height = int(params[0])
    tip = node.chainstate.tip().height
    if height < 0 or height > tip:
        raise RPCError(RPC_INVALID_PARAMETER,
                       "Blockchain block height out of range")
    node.prune_block_files(height)
    return node.prune_height
