"""CScript: opcodes, script numbers, templates, sigop counting.

Reference: src/script/script.{h,cpp} (opcodetype enum, CScriptNum,
CScript::GetSigOpCount, IsPayToScriptHash, IsPushOnly) and
src/script/standard.cpp (output templates). Scripts are plain ``bytes``
here — the reference's CScript is a byte vector with helper methods; we
keep the bytes and provide free functions, which serializes identically.
"""

from __future__ import annotations

from typing import Iterator, Optional

from ..crypto.hashes import hash160

# ---- opcodes (src/script/script.h opcodetype) ----

# push value
OP_0 = OP_FALSE = 0x00
OP_PUSHDATA1 = 0x4C
OP_PUSHDATA2 = 0x4D
OP_PUSHDATA4 = 0x4E
OP_1NEGATE = 0x4F
OP_RESERVED = 0x50
OP_1 = OP_TRUE = 0x51
OP_2 = 0x52
OP_3 = 0x53
OP_4 = 0x54
OP_5 = 0x55
OP_6 = 0x56
OP_7 = 0x57
OP_8 = 0x58
OP_9 = 0x59
OP_10 = 0x5A
OP_11 = 0x5B
OP_12 = 0x5C
OP_13 = 0x5D
OP_14 = 0x5E
OP_15 = 0x5F
OP_16 = 0x60

# control
OP_NOP = 0x61
OP_VER = 0x62
OP_IF = 0x63
OP_NOTIF = 0x64
OP_VERIF = 0x65
OP_VERNOTIF = 0x66
OP_ELSE = 0x67
OP_ENDIF = 0x68
OP_VERIFY = 0x69
OP_RETURN = 0x6A

# stack ops
OP_TOALTSTACK = 0x6B
OP_FROMALTSTACK = 0x6C
OP_2DROP = 0x6D
OP_2DUP = 0x6E
OP_3DUP = 0x6F
OP_2OVER = 0x70
OP_2ROT = 0x71
OP_2SWAP = 0x72
OP_IFDUP = 0x73
OP_DEPTH = 0x74
OP_DROP = 0x75
OP_DUP = 0x76
OP_NIP = 0x77
OP_OVER = 0x78
OP_PICK = 0x79
OP_ROLL = 0x7A
OP_ROT = 0x7B
OP_SWAP = 0x7C
OP_TUCK = 0x7D

# splice ops (disabled in this lineage)
OP_CAT = 0x7E
OP_SUBSTR = 0x7F
OP_LEFT = 0x80
OP_RIGHT = 0x81
OP_SIZE = 0x82

# bit logic (disabled except EQUAL/EQUALVERIFY)
OP_INVERT = 0x83
OP_AND = 0x84
OP_OR = 0x85
OP_XOR = 0x86
OP_EQUAL = 0x87
OP_EQUALVERIFY = 0x88
OP_RESERVED1 = 0x89
OP_RESERVED2 = 0x8A

# numeric
OP_1ADD = 0x8B
OP_1SUB = 0x8C
OP_2MUL = 0x8D
OP_2DIV = 0x8E
OP_NEGATE = 0x8F
OP_ABS = 0x90
OP_NOT = 0x91
OP_0NOTEQUAL = 0x92
OP_ADD = 0x93
OP_SUB = 0x94
OP_MUL = 0x95
OP_DIV = 0x96
OP_MOD = 0x97
OP_LSHIFT = 0x98
OP_RSHIFT = 0x99
OP_BOOLAND = 0x9A
OP_BOOLOR = 0x9B
OP_NUMEQUAL = 0x9C
OP_NUMEQUALVERIFY = 0x9D
OP_NUMNOTEQUAL = 0x9E
OP_LESSTHAN = 0x9F
OP_GREATERTHAN = 0xA0
OP_LESSTHANOREQUAL = 0xA1
OP_GREATERTHANOREQUAL = 0xA2
OP_MIN = 0xA3
OP_MAX = 0xA4
OP_WITHIN = 0xA5

# crypto
OP_RIPEMD160 = 0xA6
OP_SHA1 = 0xA7
OP_SHA256 = 0xA8
OP_HASH160 = 0xA9
OP_HASH256 = 0xAA
OP_CODESEPARATOR = 0xAB
OP_CHECKSIG = 0xAC
OP_CHECKSIGVERIFY = 0xAD
OP_CHECKMULTISIG = 0xAE
OP_CHECKMULTISIGVERIFY = 0xAF

# expansion
OP_NOP1 = 0xB0
OP_CHECKLOCKTIMEVERIFY = OP_NOP2 = 0xB1
OP_CHECKSEQUENCEVERIFY = OP_NOP3 = 0xB2
OP_NOP4 = 0xB3
OP_NOP5 = 0xB4
OP_NOP6 = 0xB5
OP_NOP7 = 0xB6
OP_NOP8 = 0xB7
OP_NOP9 = 0xB8
OP_NOP10 = 0xB9

OP_INVALIDOPCODE = 0xFF

# consensus limits (src/script/script.h)
MAX_SCRIPT_ELEMENT_SIZE = 520
MAX_OPS_PER_SCRIPT = 201
MAX_PUBKEYS_PER_MULTISIG = 20
MAX_SCRIPT_SIZE = 10_000
MAX_STACK_SIZE = 1_000


class ScriptParseError(ValueError):
    """Malformed pushdata — CScript::GetOp returning false."""


class ScriptNumError(ValueError):
    """CScriptNum overflow / non-minimal encoding (scriptnum_error)."""


class CScriptNum:
    """Numeric stack-element codec — CScriptNum (src/script/script.h:~190).

    Little-endian sign-magnitude with a sign bit in the top byte's MSB.
    Operands are limited to 4 bytes on input (results may be 5)."""

    DEFAULT_MAX_SIZE = 4

    @staticmethod
    def encode(n: int) -> bytes:
        if n == 0:
            return b""
        neg = n < 0
        absvalue = -n if neg else n
        out = bytearray()
        while absvalue:
            out.append(absvalue & 0xFF)
            absvalue >>= 8
        if out[-1] & 0x80:
            out.append(0x80 if neg else 0x00)
        elif neg:
            out[-1] |= 0x80
        return bytes(out)

    @staticmethod
    def decode(data: bytes, require_minimal: bool = False,
               max_size: int = DEFAULT_MAX_SIZE) -> int:
        if len(data) > max_size:
            raise ScriptNumError("script number overflow")
        if require_minimal and data:
            # top byte must carry information beyond the sign bit
            if data[-1] & 0x7F == 0 and (
                len(data) <= 1 or data[-2] & 0x80 == 0
            ):
                raise ScriptNumError("non-minimally encoded script number")
        if not data:
            return 0
        result = 0
        for i, b in enumerate(data):
            result |= b << (8 * i)
        if data[-1] & 0x80:
            return -(result & ~(0x80 << (8 * (len(data) - 1))))
        return result


def push_data(data: bytes) -> bytes:
    """Serialize a data push — CScript operator<<(vector) semantics."""
    n = len(data)
    if n == 0:
        return bytes([OP_0])
    if n == 1 and 1 <= data[0] <= 16:
        return bytes([OP_1 + data[0] - 1])
    if n == 1 and data[0] == 0x81:
        return bytes([OP_1NEGATE])
    if n < OP_PUSHDATA1:
        return bytes([n]) + data
    if n <= 0xFF:
        return bytes([OP_PUSHDATA1, n]) + data
    if n <= 0xFFFF:
        return bytes([OP_PUSHDATA2]) + n.to_bytes(2, "little") + data
    return bytes([OP_PUSHDATA4]) + n.to_bytes(4, "little") + data


def push_data_raw(data: bytes) -> bytes:
    """Direct-length push without the small-int opcode shortcut — what
    signature/pubkey pushes in real scriptSigs look like."""
    n = len(data)
    if n < OP_PUSHDATA1:
        return bytes([n]) + data
    if n <= 0xFF:
        return bytes([OP_PUSHDATA1, n]) + data
    if n <= 0xFFFF:
        return bytes([OP_PUSHDATA2]) + n.to_bytes(2, "little") + data
    return bytes([OP_PUSHDATA4]) + n.to_bytes(4, "little") + data


def script_int(n: int) -> bytes:
    """CScript << n: OP_0/OP_1..OP_16/OP_1NEGATE for small values, else a
    CScriptNum push. This is the BIP34 height encoding (src/miner.cpp uses
    CScript() << nHeight)."""
    if n == 0:
        return bytes([OP_0])
    if n == -1:
        return bytes([OP_1NEGATE])
    if 1 <= n <= 16:
        return bytes([OP_1 + n - 1])
    return push_data(CScriptNum.encode(n))


def get_script_ops(script: bytes) -> Iterator[tuple[int, Optional[bytes], int]]:
    """Iterate (opcode, pushed_data_or_None, pc_after) — CScript::GetOp.
    Raises ScriptParseError on truncated pushdata."""
    pc = 0
    end = len(script)
    while pc < end:
        opcode = script[pc]
        pc += 1
        data = None
        if opcode <= OP_PUSHDATA4:
            if opcode < OP_PUSHDATA1:
                size = opcode
            elif opcode == OP_PUSHDATA1:
                if pc + 1 > end:
                    raise ScriptParseError("truncated PUSHDATA1 length")
                size = script[pc]
                pc += 1
            elif opcode == OP_PUSHDATA2:
                if pc + 2 > end:
                    raise ScriptParseError("truncated PUSHDATA2 length")
                size = int.from_bytes(script[pc : pc + 2], "little")
                pc += 2
            else:  # OP_PUSHDATA4
                if pc + 4 > end:
                    raise ScriptParseError("truncated PUSHDATA4 length")
                size = int.from_bytes(script[pc : pc + 4], "little")
                pc += 4
            if pc + size > end:
                raise ScriptParseError("push past end of script")
            data = script[pc : pc + size]
            pc += size
        yield opcode, data, pc


def decode_op_n(opcode: int) -> int:
    """CScript::DecodeOP_N."""
    if opcode == OP_0:
        return 0
    assert OP_1 <= opcode <= OP_16
    return opcode - (OP_1 - 1)


def is_push_only(script: bytes) -> bool:
    """CScript::IsPushOnly — every op <= OP_16 (includes 1NEGATE/reserved)."""
    try:
        return all(op <= OP_16 for op, _, _ in get_script_ops(script))
    except ScriptParseError:
        return False


def is_p2sh(script_pubkey: bytes) -> bool:
    """CScript::IsPayToScriptHash: HASH160 <20 bytes> EQUAL, exactly."""
    return (
        len(script_pubkey) == 23
        and script_pubkey[0] == OP_HASH160
        and script_pubkey[1] == 0x14
        and script_pubkey[22] == OP_EQUAL
    )


def is_unspendable(script_pubkey: bytes) -> bool:
    """CScript::IsUnspendable: OP_RETURN-led or oversized."""
    return (
        (len(script_pubkey) > 0 and script_pubkey[0] == OP_RETURN)
        or len(script_pubkey) > MAX_SCRIPT_SIZE
    )


# ---- standard output templates (src/script/standard.cpp Solver) ----

def p2pkh_script(pubkey_hash: bytes) -> bytes:
    """DUP HASH160 <hash160> EQUALVERIFY CHECKSIG."""
    assert len(pubkey_hash) == 20
    return (
        bytes([OP_DUP, OP_HASH160, 20]) + pubkey_hash
        + bytes([OP_EQUALVERIFY, OP_CHECKSIG])
    )


def p2pkh_script_for_pubkey(pubkey: bytes) -> bytes:
    return p2pkh_script(hash160(pubkey))


def p2pk_script(pubkey: bytes) -> bytes:
    """<pubkey> CHECKSIG."""
    return push_data_raw(pubkey) + bytes([OP_CHECKSIG])


def p2sh_script(script_hash: bytes) -> bytes:
    """HASH160 <hash160> EQUAL."""
    assert len(script_hash) == 20
    return bytes([OP_HASH160, 20]) + script_hash + bytes([OP_EQUAL])


def p2sh_script_for_redeem(redeem_script: bytes) -> bytes:
    return p2sh_script(hash160(redeem_script))


def multisig_script(m: int, pubkeys: list[bytes]) -> bytes:
    """m <pk...> n CHECKMULTISIG."""
    assert 1 <= m <= len(pubkeys) <= MAX_PUBKEYS_PER_MULTISIG
    out = script_int(m)
    for pk in pubkeys:
        out += push_data_raw(pk)
    return out + script_int(len(pubkeys)) + bytes([OP_CHECKMULTISIG])


def null_data_script(data: bytes) -> bytes:
    """OP_RETURN <data> (standard.cpp TX_NULL_DATA)."""
    return bytes([OP_RETURN]) + push_data(data)


def classify_script(script_pubkey: bytes) -> str:
    """Solver (src/script/standard.cpp:~30) — returns one of
    'pubkey' | 'pubkeyhash' | 'scripthash' | 'multisig' | 'nulldata' |
    'nonstandard'."""
    if is_p2sh(script_pubkey):
        return "scripthash"
    try:
        ops = list(get_script_ops(script_pubkey))
    except ScriptParseError:
        return "nonstandard"
    if len(script_pubkey) >= 1 and script_pubkey[0] == OP_RETURN:
        if is_push_only(script_pubkey[1:]):
            return "nulldata"
        return "nonstandard"
    if (
        len(ops) == 5
        and ops[0][0] == OP_DUP and ops[1][0] == OP_HASH160
        and ops[2][1] is not None and len(ops[2][1]) == 20
        and ops[3][0] == OP_EQUALVERIFY and ops[4][0] == OP_CHECKSIG
    ):
        return "pubkeyhash"
    if (
        len(ops) == 2 and ops[1][0] == OP_CHECKSIG
        and ops[0][1] is not None and len(ops[0][1]) in (33, 65)
    ):
        return "pubkey"
    if (
        len(ops) >= 4 and ops[-1][0] == OP_CHECKMULTISIG
        and OP_1 <= ops[0][0] <= OP_16 and OP_1 <= ops[-2][0] <= OP_16
    ):
        m = decode_op_n(ops[0][0])
        n = decode_op_n(ops[-2][0])
        keys = ops[1:-2]
        if (
            1 <= m <= n <= MAX_PUBKEYS_PER_MULTISIG and len(keys) == n
            and all(k[1] is not None and len(k[1]) in (33, 65) for k in keys)
        ):
            return "multisig"
    return "nonstandard"


def count_sigops(script: bytes, accurate: bool = False) -> int:
    """CScript::GetSigOpCount (src/script/script.cpp:~150): CHECKSIG counts
    1, CHECKMULTISIG counts 20 — or, in 'accurate' mode (P2SH redeem
    scripts), the preceding OP_N when present. Parse errors truncate the
    count, as the reference's GetOp loop does."""
    n = 0
    last_opcode = OP_INVALIDOPCODE
    try:
        for opcode, _, _ in get_script_ops(script):
            if opcode in (OP_CHECKSIG, OP_CHECKSIGVERIFY):
                n += 1
            elif opcode in (OP_CHECKMULTISIG, OP_CHECKMULTISIGVERIFY):
                if accurate and OP_1 <= last_opcode <= OP_16:
                    n += decode_op_n(last_opcode)
                else:
                    n += MAX_PUBKEYS_PER_MULTISIG
            last_opcode = opcode
    except ScriptParseError:
        pass
    return n


def count_p2sh_sigops(script_pubkey: bytes, script_sig: bytes) -> int:
    """CScript::GetSigOpCount(scriptSig) for P2SH: sigops of the redeem
    script (the last push of scriptSig), accurate mode."""
    if not is_p2sh(script_pubkey):
        return 0
    redeem = b""
    try:
        for op, data, _ in get_script_ops(script_sig):
            if op > OP_16:
                return 0  # non-push-only: invalid spend, no sigops
            redeem = data or b""
    except ScriptParseError:
        return 0
    return count_sigops(redeem, accurate=True)


def find_and_delete(script: bytes, elem: bytes) -> bytes:
    """CScript::FindAndDelete — remove every serialized occurrence of
    ``elem`` (as full pushes) from the script. Used by the legacy sighash
    to strip the signature from scriptCode."""
    if not elem:
        return script
    out = bytearray()
    pc = 0
    end = len(script)
    while pc < end:
        # match at op boundaries only, like the reference
        if script[pc : pc + len(elem)] == elem:
            pc += len(elem)
            continue
        start = pc
        opcode = script[pc]
        pc += 1
        if opcode <= OP_PUSHDATA4:
            if opcode < OP_PUSHDATA1:
                size = opcode
            elif opcode == OP_PUSHDATA1:
                size = script[pc] if pc < end else 0
                pc += 1
            elif opcode == OP_PUSHDATA2:
                size = int.from_bytes(script[pc : pc + 2], "little")
                pc += 2
            else:
                size = int.from_bytes(script[pc : pc + 4], "little")
                pc += 4
            pc += size
        out += script[start : min(pc, end)]
    return bytes(out)


# ---- opcode names (GetOpName, src/script/script.cpp) ----

OPCODE_NAMES: dict[int, str] = {
    v: k
    for k, v in sorted(globals().items())
    if k.startswith("OP_") and isinstance(v, int)
}
# canonical spellings where aliases exist
OPCODE_NAMES[0x00] = "0"
OPCODE_NAMES[0x51] = "OP_1"
OPCODE_NAMES[0x87] = "OP_EQUAL"
