"""Bitcoin Script — the L3 consensus script layer.

Reference: src/script/ (script.{h,cpp} — CScript + opcodes;
interpreter.{h,cpp} — EvalScript/VerifyScript/SignatureHash;
standard.{h,cpp} — output classification; sign.cpp — solver/signing glue).

TPU-first split: the stack machine itself is branchy host code (not
TPU-able, SURVEY.md §3.1), but it *defers* the expensive ECDSA verifies
into per-block sigcheck records that ops/ecdsa_batch ships to the chip in
one dispatch. Sighash preimage construction lives here; the double-SHA of
those preimages can batch on-chip as well.
"""

from .script import (  # noqa: F401
    OP_0, OP_1, OP_16, OP_CHECKSIG, OP_DUP, OP_EQUAL, OP_EQUALVERIFY,
    OP_HASH160, OP_RETURN, CScriptNum, ScriptNumError,
    p2pkh_script, p2pk_script, p2sh_script, script_int,
    get_script_ops, is_p2sh, is_push_only, count_sigops,
)
from .interpreter import (  # noqa: F401
    SCRIPT_VERIFY_NONE, SCRIPT_VERIFY_P2SH, SCRIPT_VERIFY_STRICTENC,
    SCRIPT_VERIFY_DERSIG, SCRIPT_VERIFY_LOW_S, SCRIPT_VERIFY_NULLDUMMY,
    SCRIPT_VERIFY_NULLFAIL, SCRIPT_ENABLE_SIGHASH_FORKID,
    MANDATORY_SCRIPT_VERIFY_FLAGS, STANDARD_SCRIPT_VERIFY_FLAGS,
    ScriptError, EvalScript, VerifyScript,
    BaseSignatureChecker, TransactionSignatureChecker,
    DeferringSignatureChecker, SigCheckRecord,
)
from .sighash import (  # noqa: F401
    SIGHASH_ALL, SIGHASH_NONE, SIGHASH_SINGLE, SIGHASH_ANYONECANPAY,
    SIGHASH_FORKID, signature_hash, signature_hash_legacy,
    signature_hash_forkid,
)
