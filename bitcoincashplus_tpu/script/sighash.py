"""SignatureHash — the transaction digest that ECDSA signs.

Reference: src/script/interpreter.cpp:~1100 (SignatureHash). Two variants:

* **legacy** — the original algorithm: serialize a modified copy of the tx
  (inputs' scriptSigs replaced by scriptCode for the signed input, empty
  elsewhere; NONE/SINGLE/ANYONECANPAY mutations), append the 32-bit sighash
  type, SHA256d. Includes the notorious SIGHASH_SINGLE out-of-range "one"
  bug, reproduced bit-for-bit.
* **forkid (BIP143-style)** — the BCH-family replay-protected digest
  [fork-delta, hedged — SURVEY.md §0]: commits to hashPrevouts /
  hashSequence / hashOutputs midstates and the spent amount. Used when the
  signature's hashtype has SIGHASH_FORKID set and the
  SCRIPT_ENABLE_SIGHASH_FORKID flag is active (post-uahf_height blocks).

The midstate hashes (hash_prevouts etc.) are cacheable per transaction —
PrecomputedTransactionData in the reference — which turns sighash cost for
an n-input tx from O(n^2) to O(n). ``SighashCache`` provides that.
"""

from __future__ import annotations

from ..consensus.serialize import ser_u32, ser_u64, ser_var_bytes, ser_vector
from ..consensus.tx import CTransaction, CTxOut
from ..crypto.hashes import sha256d
from .script import OP_CODESEPARATOR, find_and_delete, get_script_ops

SIGHASH_ALL = 1
SIGHASH_NONE = 2
SIGHASH_SINGLE = 3
SIGHASH_FORKID = 0x40  # BCH-family replay protection bit
SIGHASH_ANYONECANPAY = 0x80

# SignatureHash returns this constant for the SINGLE-with-no-matching-output
# bug (uint256(1) — interpreter.cpp "one").
_ONE = (1).to_bytes(32, "little")


def strip_code_separators(script_code: bytes) -> bytes:
    """Remove OP_CODESEPARATOR opcodes — SignatureHash's scriptCode
    normalization (both variants do this)."""
    out = bytearray()
    pos = 0
    for op, _data, pc in get_script_ops(script_code):
        if op == OP_CODESEPARATOR:
            pos = pc
            continue
        out += script_code[pos:pc]
        pos = pc
    return bytes(out)


def signature_hash_legacy(
    script_code: bytes,
    tx: CTransaction,
    in_idx: int,
    hashtype: int,
    *,
    strip_sig: bytes | None = None,
) -> bytes:
    """Original SignatureHash (interpreter.cpp:~1100). ``strip_sig`` is the
    signature being checked; legacy sighash FindAndDelete's it from
    scriptCode (only relevant to pathological self-referencing scripts)."""
    if in_idx >= len(tx.vin):
        return _ONE  # "nIn out of range" bug path
    base_type = hashtype & 0x1F
    if base_type == SIGHASH_SINGLE and in_idx >= len(tx.vout):
        return _ONE  # the SIGHASH_SINGLE bug

    code = strip_code_separators(script_code)
    if strip_sig:
        code = find_and_delete(code, strip_sig)

    anyonecanpay = bool(hashtype & SIGHASH_ANYONECANPAY)

    # serialize CTransactionSignatureSerializer-style
    parts = [ser_u32(tx.version & 0xFFFFFFFF)]

    # inputs
    if anyonecanpay:
        vin = [tx.vin[in_idx]]
        idx_map = [in_idx]
    else:
        vin = list(tx.vin)
        idx_map = list(range(len(tx.vin)))
    in_parts = []
    for i, txin in zip(idx_map, vin):
        script = code if i == in_idx else b""
        seq = txin.sequence
        if i != in_idx and base_type in (SIGHASH_NONE, SIGHASH_SINGLE):
            seq = 0
        in_parts.append(
            txin.prevout.serialize() + ser_var_bytes(script) + ser_u32(seq)
        )
    parts.append(ser_vector(in_parts, lambda b: b))

    # outputs
    if base_type == SIGHASH_NONE:
        outs: list[CTxOut] = []
    elif base_type == SIGHASH_SINGLE:
        # outputs up to and including in_idx; earlier ones blanked
        outs = [CTxOut() for _ in range(in_idx)] + [tx.vout[in_idx]]
    else:
        outs = list(tx.vout)
    parts.append(ser_vector(outs, CTxOut.serialize))

    parts.append(ser_u32(tx.locktime))
    parts.append(ser_u32(hashtype & 0xFFFFFFFF))
    return sha256d(b"".join(parts))


class SighashCache:
    """PrecomputedTransactionData (src/script/interpreter.h): the three
    midstate hashes the forkid digest commits to, computed once per tx."""

    __slots__ = ("hash_prevouts", "hash_sequence", "hash_outputs")

    def __init__(self, tx: CTransaction):
        self.hash_prevouts = sha256d(
            b"".join(txin.prevout.serialize() for txin in tx.vin)
        )
        self.hash_sequence = sha256d(
            b"".join(ser_u32(txin.sequence) for txin in tx.vin)
        )
        self.hash_outputs = sha256d(
            b"".join(out.serialize() for out in tx.vout)
        )


def signature_hash_forkid(
    script_code: bytes,
    tx: CTransaction,
    in_idx: int,
    hashtype: int,
    amount: int,
    cache: SighashCache | None = None,
) -> bytes:
    """BIP143-style value-committing digest, selected by SIGHASH_FORKID
    (interpreter.cpp SignatureHash forkid branch) [fork-delta, hedged]."""
    assert in_idx < len(tx.vin)
    base_type = hashtype & 0x1F
    anyonecanpay = bool(hashtype & SIGHASH_ANYONECANPAY)
    cache = cache or SighashCache(tx)

    zero = b"\x00" * 32
    hash_prevouts = zero if anyonecanpay else cache.hash_prevouts
    if anyonecanpay or base_type in (SIGHASH_NONE, SIGHASH_SINGLE):
        hash_sequence = zero
    else:
        hash_sequence = cache.hash_sequence
    if base_type not in (SIGHASH_NONE, SIGHASH_SINGLE):
        hash_outputs = cache.hash_outputs
    elif base_type == SIGHASH_SINGLE and in_idx < len(tx.vout):
        hash_outputs = sha256d(tx.vout[in_idx].serialize())
    else:
        hash_outputs = zero

    # NB: unlike the legacy serializer, the forkid/BIP143-style branch
    # hashes scriptCode AS-IS — no OP_CODESEPARATOR stripping and no
    # FindAndDelete (the reference's SignatureHash forkid path serializes
    # the raw scriptCode).
    txin = tx.vin[in_idx]
    preimage = (
        ser_u32(tx.version & 0xFFFFFFFF)
        + hash_prevouts
        + hash_sequence
        + txin.prevout.serialize()
        + ser_var_bytes(script_code)
        + ser_u64(amount)
        + ser_u32(txin.sequence)
        + hash_outputs
        + ser_u32(tx.locktime)
        + ser_u32(hashtype & 0xFFFFFFFF)
    )
    return sha256d(preimage)


def signature_hash(
    script_code: bytes,
    tx: CTransaction,
    in_idx: int,
    hashtype: int,
    amount: int,
    *,
    enable_forkid: bool = False,
    cache: SighashCache | None = None,
    strip_sig: bytes | None = None,
) -> bytes:
    """Dispatch: forkid digest iff the hashtype carries SIGHASH_FORKID and
    the flag allows it; legacy otherwise — matching the reference's
    SignatureHash signature-type gate."""
    if enable_forkid and (hashtype & SIGHASH_FORKID):
        return signature_hash_forkid(script_code, tx, in_idx, hashtype, amount, cache)
    return signature_hash_legacy(
        script_code, tx, in_idx, hashtype, strip_sig=strip_sig
    )
