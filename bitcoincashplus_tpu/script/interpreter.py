"""EvalScript / VerifyScript — the Bitcoin Script stack machine.

Reference: src/script/interpreter.cpp:~250–1100 (EvalScript), :~1400
(VerifyScript), TransactionSignatureChecker::CheckSig, plus the signature/
pubkey encoding rules (IsValidSignatureEncoding, IsLowDERSignature,
CheckSignatureEncoding, CheckPubKeyEncoding).

TPU-first deferral (the CCheckQueue replacement, SURVEY.md §4.2): the
interpreter is branchy host code, but OP_CHECKSIG's expensive
secp256k1_ecdsa_verify is *deferred* — ``DeferringSignatureChecker``
records (pubkey, r, s, msghash) and speculatively reports success; the
per-block batch then runs in ONE TPU dispatch (ops/ecdsa_batch). This is
sound iff SCRIPT_VERIFY_NULLFAIL is active: a failing check with a
non-empty signature then always invalidates the script, so "all deferred
records verify" ⇔ "all scripts that reported success actually succeed".
The checker asserts that precondition. CHECKMULTISIG trials are verified
eagerly (sig→pubkey assignment is outcome-dependent, so deferral is
unsound there); multisig is rare and stays on the CPU fallback path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..consensus.tx import (
    SEQUENCE_LOCKTIME_DISABLE_FLAG,
    SEQUENCE_LOCKTIME_MASK,
    SEQUENCE_LOCKTIME_TYPE_FLAG,
    LOCKTIME_THRESHOLD,
    CTransaction,
)
from ..crypto import secp256k1 as secp
from ..crypto.hashes import hash160, ripemd160, sha256, sha256d
from . import script as S
from .script import (
    MAX_OPS_PER_SCRIPT,
    MAX_PUBKEYS_PER_MULTISIG,
    MAX_SCRIPT_ELEMENT_SIZE,
    MAX_SCRIPT_SIZE,
    MAX_STACK_SIZE,
    CScriptNum,
    ScriptNumError,
    ScriptParseError,
)
from .sighash import (
    SIGHASH_ANYONECANPAY,
    SIGHASH_FORKID,
    SIGHASH_SINGLE,
    SighashCache,
    signature_hash,
)

# ---- verification flags (src/script/interpreter.h) ----

SCRIPT_VERIFY_NONE = 0
SCRIPT_VERIFY_P2SH = 1 << 0
SCRIPT_VERIFY_STRICTENC = 1 << 1
SCRIPT_VERIFY_DERSIG = 1 << 2
SCRIPT_VERIFY_LOW_S = 1 << 3
SCRIPT_VERIFY_NULLDUMMY = 1 << 4
SCRIPT_VERIFY_SIGPUSHONLY = 1 << 5
SCRIPT_VERIFY_MINIMALDATA = 1 << 6
SCRIPT_VERIFY_DISCOURAGE_UPGRADABLE_NOPS = 1 << 7
SCRIPT_VERIFY_CLEANSTACK = 1 << 8
SCRIPT_VERIFY_CHECKLOCKTIMEVERIFY = 1 << 9
SCRIPT_VERIFY_CHECKSEQUENCEVERIFY = 1 << 10
SCRIPT_VERIFY_NULLFAIL = 1 << 14
SCRIPT_ENABLE_SIGHASH_FORKID = 1 << 16  # BCH-family [fork-delta, hedged]

# Consensus-mandatory flags for block validation (policy/policy.h
# MANDATORY_SCRIPT_VERIFY_FLAGS). Post-fork blocks add FORKID+NULLFAIL via
# validation/scriptcheck.block_script_flags.
MANDATORY_SCRIPT_VERIFY_FLAGS = SCRIPT_VERIFY_P2SH | SCRIPT_VERIFY_STRICTENC
STANDARD_SCRIPT_VERIFY_FLAGS = (
    MANDATORY_SCRIPT_VERIFY_FLAGS
    | SCRIPT_VERIFY_DERSIG
    | SCRIPT_VERIFY_LOW_S
    | SCRIPT_VERIFY_NULLDUMMY
    | SCRIPT_VERIFY_SIGPUSHONLY
    | SCRIPT_VERIFY_MINIMALDATA
    | SCRIPT_VERIFY_DISCOURAGE_UPGRADABLE_NOPS
    | SCRIPT_VERIFY_CLEANSTACK
    | SCRIPT_VERIFY_CHECKLOCKTIMEVERIFY
    | SCRIPT_VERIFY_CHECKSEQUENCEVERIFY
    | SCRIPT_VERIFY_NULLFAIL
)


class ScriptError(Exception):
    """script_error (src/script/script_error.h) — carries the reject code."""

    def __init__(self, code: str, detail: str = ""):
        self.code = code
        super().__init__(f"{code}{': ' + detail if detail else ''}")


# ---- signature / pubkey encoding (interpreter.cpp:~60–230) ----

def is_valid_signature_encoding(sig: bytes) -> bool:
    """IsValidSignatureEncoding — strict DER incl. 1-byte hashtype tail."""
    if len(sig) < 9 or len(sig) > 73:
        return False
    if sig[0] != 0x30 or sig[1] != len(sig) - 3:
        return False
    len_r = sig[3]
    if 5 + len_r >= len(sig):
        return False
    len_s = sig[5 + len_r]
    if len_r + len_s + 7 != len(sig):
        return False
    if sig[2] != 0x02 or len_r == 0 or (sig[4] & 0x80):
        return False
    if len_r > 1 and sig[4] == 0x00 and not (sig[5] & 0x80):
        return False
    if sig[len_r + 4] != 0x02 or len_s == 0 or (sig[len_r + 6] & 0x80):
        return False
    if len_s > 1 and sig[len_r + 6] == 0x00 and not (sig[len_r + 7] & 0x80):
        return False
    return True


def is_low_der_signature(sig: bytes) -> bool:
    """IsLowDERSignature: s <= n/2 (CPubKey::CheckLowS)."""
    if not is_valid_signature_encoding(sig):
        raise ScriptError("sig-der")
    rs = secp.sig_der_decode(sig[:-1])
    if rs is None:
        return False
    return rs[1] <= secp.N // 2


def is_defined_hashtype_signature(sig: bytes) -> bool:
    """IsDefinedHashtypeSignature: base type must be ALL/NONE/SINGLE (after
    stripping ANYONECANPAY and the fork's FORKID bit)."""
    if not sig:
        return False
    hashtype = sig[-1] & ~(SIGHASH_ANYONECANPAY | SIGHASH_FORKID)
    return 1 <= hashtype <= SIGHASH_SINGLE


def is_schnorr_signature(sig: bytes) -> bool:
    """BCH 2019-05 Schnorr discrimination (CheckTransactionECDSASignature-
    Encoding's complement): a transaction signature of exactly 65 bytes
    (64-byte r||s body + 1 hashtype byte) IS Schnorr, by consensus rule.
    DER encodings of 65 total bytes exist, but the upgrade removed them
    from validity — length alone decides, so there is no parse
    ambiguity."""
    return len(sig) == 65


def _check_hashtype_encoding(sig: bytes, flags: int) -> None:
    """The STRICTENC hashtype/forkid rules, shared by the DER and Schnorr
    encoding checks (the sighash byte plumbing is scheme-independent)."""
    if not is_defined_hashtype_signature(sig):
        raise ScriptError("sig-hashtype")
    uses_forkid = bool(sig[-1] & SIGHASH_FORKID)
    forkid_on = bool(flags & SCRIPT_ENABLE_SIGHASH_FORKID)
    if not forkid_on and uses_forkid:
        raise ScriptError("illegal-forkid")
    if forkid_on and not uses_forkid:
        raise ScriptError("must-use-forkid")


def check_signature_encoding(sig: bytes, flags: int) -> None:
    """CheckSignatureEncoding — raises ScriptError on violation."""
    if len(sig) == 0:
        return
    if is_schnorr_signature(sig):
        # Schnorr: the fixed-width encoding has no DER/low-s malleable
        # forms, so those checks don't apply — but the STRICTENC
        # hashtype/forkid rules still do
        if flags & SCRIPT_VERIFY_STRICTENC:
            _check_hashtype_encoding(sig, flags)
        return
    if flags & (
        SCRIPT_VERIFY_DERSIG | SCRIPT_VERIFY_LOW_S | SCRIPT_VERIFY_STRICTENC
    ) and not is_valid_signature_encoding(sig):
        raise ScriptError("sig-der")
    if flags & SCRIPT_VERIFY_LOW_S and not is_low_der_signature(sig):
        raise ScriptError("sig-high-s")
    if flags & SCRIPT_VERIFY_STRICTENC:
        _check_hashtype_encoding(sig, flags)


def check_pubkey_encoding(pubkey: bytes, flags: int) -> None:
    """CheckPubKeyEncoding: STRICTENC ⇒ compressed-or-uncompressed form."""
    if flags & SCRIPT_VERIFY_STRICTENC:
        ok = (
            (len(pubkey) == 33 and pubkey[0] in (2, 3))
            or (len(pubkey) == 65 and pubkey[0] == 4)
        )
        if not ok:
            raise ScriptError("pubkeytype")


def check_minimal_push(data: bytes, opcode: int) -> bool:
    """CheckMinimalPush (interpreter.cpp:~240)."""
    if len(data) == 0:
        return opcode == S.OP_0
    if len(data) == 1 and 1 <= data[0] <= 16:
        return opcode == S.OP_1 + data[0] - 1
    if len(data) == 1 and data[0] == 0x81:
        return opcode == S.OP_1NEGATE
    if len(data) <= 75:
        return opcode == len(data)
    if len(data) <= 255:
        return opcode == S.OP_PUSHDATA1
    if len(data) <= 65535:
        return opcode == S.OP_PUSHDATA2
    return True


def cast_to_bool(v: bytes) -> bool:
    """CastToBool: any non-zero byte, except a trailing negative-zero 0x80."""
    for i, b in enumerate(v):
        if b != 0:
            return not (i == len(v) - 1 and b == 0x80)
    return False


def _pubkey_parse_fast(pubkey: bytes):
    """pubkey_parse via the native module when present (the Python path's
    per-key modular sqrt was ~30% of reindex host time); oracle fallback.
    Same acceptance set (test_native.py differential)."""
    from .. import native

    if native.available():
        return native.pubkey_parse(pubkey)
    return secp.pubkey_parse(pubkey)


def _ecdsa_verify_scalar(pt, r: int, s: int, e: int) -> bool:
    """Scalar (non-batched) verify: the native C++ module when present
    (SURVEY §3.1 binding plan's CPU fallback — ~500x the Python oracle),
    else the oracle. Same acceptance set either way (test_native.py runs
    the differential)."""
    from .. import native

    if native.available():
        return native.ecdsa_verify(pt, r, s, e)
    return secp.ecdsa_verify(pt, r, s, e)


# ---- signature checkers (interpreter.h BaseSignatureChecker) ----

@dataclass
class SigCheckRecord:
    """One deferred signature verification — the unit the TPU batch
    consumes (pubkey point + (r,s) scalars + message-hash int, with
    attribution). ``algo`` discriminates the scheme: "ecdsa" records ride
    the per-lane GLV/w4 kernels, "schnorr" records are batchable into the
    MSM check (ops/ecdsa_batch partitions on this field)."""

    pubkey: tuple  # affine (x, y)
    r: int
    s: int
    msg_hash: int  # sighash as big-endian int
    txid: bytes = b""
    in_idx: int = -1
    algo: str = "ecdsa"


class BaseSignatureChecker:
    """No-transaction-context checker: every check fails (interpreter.h)."""

    def check_sig(self, sig: bytes, pubkey: bytes, script_code: bytes,
                  flags: int, defer_ok: bool = True) -> bool:
        return False

    def check_locktime(self, locktime: int) -> bool:
        return False

    def check_sequence(self, sequence: int) -> bool:
        return False


class TransactionSignatureChecker(BaseSignatureChecker):
    """TransactionSignatureChecker (interpreter.cpp): computes the sighash
    for (tx, in_idx, amount) and verifies via the CPU secp oracle."""

    def __init__(self, tx: CTransaction, in_idx: int, amount: int,
                 cache: Optional[SighashCache] = None):
        self.tx = tx
        self.in_idx = in_idx
        self.amount = amount
        self.cache = cache

    def _sighash_and_parse(self, sig: bytes, pubkey: bytes, script_code: bytes,
                           flags: int):
        """Shared parse path: returns (point, r, s, e, algo) or None if any
        parse fails (pubkey off-curve, empty/garbled sig). ``algo`` is
        "schnorr" for 65-byte signatures (BCH length discrimination),
        "ecdsa" for DER — both run over the SAME sighash digests."""
        if not sig:
            return None
        pt = _pubkey_parse_fast(pubkey)
        if pt is None:
            return None
        hashtype = sig[-1]
        if is_schnorr_signature(sig):
            algo = "schnorr"
            r = int.from_bytes(sig[0:32], "big")
            s = int.from_bytes(sig[32:64], "big")
        else:
            algo = "ecdsa"
            rs = secp.sig_der_decode(sig[:-1])
            if rs is None:
                return None
            r, s = rs
        ehash = signature_hash(
            script_code, self.tx, self.in_idx, hashtype, self.amount,
            enable_forkid=bool(flags & SCRIPT_ENABLE_SIGHASH_FORKID),
            cache=self.cache,
            strip_sig=S.push_data_raw(sig),
        )
        return pt, r, s, int.from_bytes(ehash, "big"), algo

    def check_sig(self, sig: bytes, pubkey: bytes, script_code: bytes,
                  flags: int, defer_ok: bool = True) -> bool:
        parsed = self._sighash_and_parse(sig, pubkey, script_code, flags)
        if parsed is None:
            return False
        pt, r, s, e, algo = parsed
        if algo == "schnorr":
            return secp.schnorr_verify(pt, r, s, e)
        return _ecdsa_verify_scalar(pt, r, s, e)

    def check_locktime(self, locktime: int) -> bool:
        """CheckLockTime (interpreter.cpp:~1230) — BIP65 semantics."""
        tx_lock = self.tx.locktime
        same_type = (
            (tx_lock < LOCKTIME_THRESHOLD and locktime < LOCKTIME_THRESHOLD)
            or (tx_lock >= LOCKTIME_THRESHOLD and locktime >= LOCKTIME_THRESHOLD)
        )
        if not same_type:
            return False
        if locktime > tx_lock:
            return False
        if self.tx.vin[self.in_idx].sequence == 0xFFFFFFFF:
            return False
        return True

    def check_sequence(self, sequence: int) -> bool:
        """CheckSequence (interpreter.cpp:~1270) — BIP112 semantics."""
        tx_seq = self.tx.vin[self.in_idx].sequence
        if self.tx.version < 2:
            return False
        if tx_seq & SEQUENCE_LOCKTIME_DISABLE_FLAG:
            return False
        mask = SEQUENCE_LOCKTIME_TYPE_FLAG | SEQUENCE_LOCKTIME_MASK
        masked_tx = tx_seq & mask
        masked_stack = sequence & mask
        same_type = (
            (masked_tx < SEQUENCE_LOCKTIME_TYPE_FLAG
             and masked_stack < SEQUENCE_LOCKTIME_TYPE_FLAG)
            or (masked_tx >= SEQUENCE_LOCKTIME_TYPE_FLAG
                and masked_stack >= SEQUENCE_LOCKTIME_TYPE_FLAG)
        )
        if not same_type:
            return False
        return masked_stack <= masked_tx


class DeferringSignatureChecker(TransactionSignatureChecker):
    """Records CHECKSIG verifications for the per-block TPU batch instead
    of running them. Requires NULLFAIL in flags (see module docstring);
    VerifyScript enforces this. Multisig trials (defer_ok=False) verify
    eagerly via the parent."""

    def __init__(self, tx: CTransaction, in_idx: int, amount: int,
                 records: list[SigCheckRecord],
                 cache: Optional[SighashCache] = None):
        super().__init__(tx, in_idx, amount, cache)
        self.records = records

    def check_sig(self, sig: bytes, pubkey: bytes, script_code: bytes,
                  flags: int, defer_ok: bool = True) -> bool:
        if not defer_ok:
            from ..ops.ecdsa_batch import STATS

            STATS.eager_multisig_sigs += 1
            return super().check_sig(sig, pubkey, script_code, flags, defer_ok)
        parsed = self._sighash_and_parse(sig, pubkey, script_code, flags)
        if parsed is None:
            return False
        pt, r, s, e, algo = parsed
        if algo == "schnorr":
            # Schnorr ranges: r is a field element, s a scalar (spec:
            # fail if r >= p or s >= n) — out-of-range never verifies
            if not (r < secp.P and s < secp.N):
                return False
        elif not (1 <= r < secp.N and 1 <= s < secp.N):
            return False  # out-of-range scalars never verify; don't defer
        self.records.append(
            SigCheckRecord(pt, r, s, e, self.tx.txid, self.in_idx, algo)
        )
        return True  # speculative success — batch settles it


# ---- EvalScript (interpreter.cpp:~250) ----

_DISABLED_OPCODES = frozenset({
    S.OP_CAT, S.OP_SUBSTR, S.OP_LEFT, S.OP_RIGHT,
    S.OP_INVERT, S.OP_AND, S.OP_OR, S.OP_XOR,
    S.OP_2MUL, S.OP_2DIV, S.OP_MUL, S.OP_DIV, S.OP_MOD,
    S.OP_LSHIFT, S.OP_RSHIFT,
})


def EvalScript(stack: list[bytes], script: bytes, flags: int,
               checker: BaseSignatureChecker) -> None:
    """Execute one script over ``stack`` in place. Raises ScriptError."""
    if len(script) > MAX_SCRIPT_SIZE:
        raise ScriptError("script-size")

    altstack: list[bytes] = []
    vexec: list[bool] = []  # conditional-execution stack (vfExec)
    op_count = 0
    minimal = bool(flags & SCRIPT_VERIFY_MINIMALDATA)
    pc = 0
    begincode = 0  # pbegincodehash: scriptCode start (OP_CODESEPARATOR)

    try:
        ops = list(S.get_script_ops(script))
    except ScriptParseError as e:
        raise ScriptError("bad-opcode", str(e)) from e

    def popstack() -> bytes:
        if not stack:
            raise ScriptError("invalid-stack-operation")
        return stack.pop()

    def popnum() -> int:
        return CScriptNum.decode(popstack(), minimal)

    def pushint(n: int) -> None:
        stack.append(CScriptNum.encode(n))

    def pushbool(b: bool) -> None:
        stack.append(b"\x01" if b else b"")

    try:
        for opcode, data, pc_after in ops:
            fexec = all(vexec)

            if data is not None and len(data) > MAX_SCRIPT_ELEMENT_SIZE:
                raise ScriptError("push-size")
            if opcode > S.OP_16:
                op_count += 1
                if op_count > MAX_OPS_PER_SCRIPT:
                    raise ScriptError("op-count")
            if opcode in _DISABLED_OPCODES:
                raise ScriptError("disabled-opcode")  # even if unexecuted

            if fexec and 0 <= opcode <= S.OP_PUSHDATA4:
                if minimal and not check_minimal_push(data, opcode):
                    raise ScriptError("minimaldata")
                stack.append(bytes(data))
            elif fexec or (S.OP_IF <= opcode <= S.OP_ENDIF):
                # ---- push small ints ----
                if opcode == S.OP_1NEGATE:
                    pushint(-1)
                elif S.OP_1 <= opcode <= S.OP_16:
                    pushint(opcode - (S.OP_1 - 1))

                # ---- control ----
                elif opcode == S.OP_NOP:
                    pass
                elif opcode == S.OP_CHECKLOCKTIMEVERIFY:
                    if not (flags & SCRIPT_VERIFY_CHECKLOCKTIMEVERIFY):
                        if flags & SCRIPT_VERIFY_DISCOURAGE_UPGRADABLE_NOPS:
                            raise ScriptError("discourage-upgradable-nops")
                    else:
                        if not stack:
                            raise ScriptError("invalid-stack-operation")
                        # 5-byte numeric operand (BIP65)
                        locktime = CScriptNum.decode(stack[-1], minimal, 5)
                        if locktime < 0:
                            raise ScriptError("negative-locktime")
                        if not checker.check_locktime(locktime):
                            raise ScriptError("unsatisfied-locktime")
                elif opcode == S.OP_CHECKSEQUENCEVERIFY:
                    if not (flags & SCRIPT_VERIFY_CHECKSEQUENCEVERIFY):
                        if flags & SCRIPT_VERIFY_DISCOURAGE_UPGRADABLE_NOPS:
                            raise ScriptError("discourage-upgradable-nops")
                    else:
                        if not stack:
                            raise ScriptError("invalid-stack-operation")
                        seq = CScriptNum.decode(stack[-1], minimal, 5)
                        if seq < 0:
                            raise ScriptError("negative-locktime")
                        if not (seq & SEQUENCE_LOCKTIME_DISABLE_FLAG):
                            if not checker.check_sequence(seq):
                                raise ScriptError("unsatisfied-locktime")
                elif opcode in (S.OP_NOP1, S.OP_NOP4, S.OP_NOP5, S.OP_NOP6,
                                S.OP_NOP7, S.OP_NOP8, S.OP_NOP9, S.OP_NOP10):
                    if flags & SCRIPT_VERIFY_DISCOURAGE_UPGRADABLE_NOPS:
                        raise ScriptError("discourage-upgradable-nops")
                elif opcode in (S.OP_IF, S.OP_NOTIF):
                    value = False
                    if fexec:
                        value = cast_to_bool(popstack())
                        if opcode == S.OP_NOTIF:
                            value = not value
                    vexec.append(value)
                elif opcode == S.OP_ELSE:
                    if not vexec:
                        raise ScriptError("unbalanced-conditional")
                    vexec[-1] = not vexec[-1]
                elif opcode == S.OP_ENDIF:
                    if not vexec:
                        raise ScriptError("unbalanced-conditional")
                    vexec.pop()
                elif opcode == S.OP_VERIFY:
                    if not cast_to_bool(popstack()):
                        raise ScriptError("verify")
                elif opcode == S.OP_RETURN:
                    raise ScriptError("op-return")
                elif opcode in (S.OP_VER, S.OP_VERIF, S.OP_VERNOTIF,
                                S.OP_RESERVED, S.OP_RESERVED1, S.OP_RESERVED2):
                    # VERIF/VERNOTIF fail even unexecuted in the reference;
                    # they reach here only via the IF..ENDIF passthrough
                    if opcode in (S.OP_VERIF, S.OP_VERNOTIF) or fexec:
                        raise ScriptError("bad-opcode")

                # ---- stack ----
                elif opcode == S.OP_TOALTSTACK:
                    altstack.append(popstack())
                elif opcode == S.OP_FROMALTSTACK:
                    if not altstack:
                        raise ScriptError("invalid-altstack-operation")
                    stack.append(altstack.pop())
                elif opcode == S.OP_2DROP:
                    popstack(); popstack()
                elif opcode == S.OP_2DUP:
                    if len(stack) < 2:
                        raise ScriptError("invalid-stack-operation")
                    stack.extend(stack[-2:])
                elif opcode == S.OP_3DUP:
                    if len(stack) < 3:
                        raise ScriptError("invalid-stack-operation")
                    stack.extend(stack[-3:])
                elif opcode == S.OP_2OVER:
                    if len(stack) < 4:
                        raise ScriptError("invalid-stack-operation")
                    stack.extend(stack[-4:-2])
                elif opcode == S.OP_2ROT:
                    if len(stack) < 6:
                        raise ScriptError("invalid-stack-operation")
                    x = stack[-6:-4]
                    del stack[-6:-4]
                    stack.extend(x)
                elif opcode == S.OP_2SWAP:
                    if len(stack) < 4:
                        raise ScriptError("invalid-stack-operation")
                    stack[-4:-2], stack[-2:] = stack[-2:], stack[-4:-2]
                elif opcode == S.OP_IFDUP:
                    if not stack:
                        raise ScriptError("invalid-stack-operation")
                    if cast_to_bool(stack[-1]):
                        stack.append(stack[-1])
                elif opcode == S.OP_DEPTH:
                    pushint(len(stack))
                elif opcode == S.OP_DROP:
                    popstack()
                elif opcode == S.OP_DUP:
                    if not stack:
                        raise ScriptError("invalid-stack-operation")
                    stack.append(stack[-1])
                elif opcode == S.OP_NIP:
                    if len(stack) < 2:
                        raise ScriptError("invalid-stack-operation")
                    del stack[-2]
                elif opcode == S.OP_OVER:
                    if len(stack) < 2:
                        raise ScriptError("invalid-stack-operation")
                    stack.append(stack[-2])
                elif opcode in (S.OP_PICK, S.OP_ROLL):
                    if len(stack) < 2:
                        raise ScriptError("invalid-stack-operation")
                    n = popnum()
                    if n < 0 or n >= len(stack):
                        raise ScriptError("invalid-stack-operation")
                    item = stack[-n - 1]
                    if opcode == S.OP_ROLL:
                        del stack[-n - 1]
                    stack.append(item)
                elif opcode == S.OP_ROT:
                    if len(stack) < 3:
                        raise ScriptError("invalid-stack-operation")
                    stack[-3], stack[-2], stack[-1] = (
                        stack[-2], stack[-1], stack[-3]
                    )
                elif opcode == S.OP_SWAP:
                    if len(stack) < 2:
                        raise ScriptError("invalid-stack-operation")
                    stack[-2], stack[-1] = stack[-1], stack[-2]
                elif opcode == S.OP_TUCK:
                    if len(stack) < 2:
                        raise ScriptError("invalid-stack-operation")
                    stack.insert(-2, stack[-1])
                elif opcode == S.OP_SIZE:
                    if not stack:
                        raise ScriptError("invalid-stack-operation")
                    pushint(len(stack[-1]))

                # ---- equality ----
                elif opcode in (S.OP_EQUAL, S.OP_EQUALVERIFY):
                    b1 = popstack()
                    b2 = popstack()
                    equal = b1 == b2
                    if opcode == S.OP_EQUALVERIFY:
                        if not equal:
                            raise ScriptError("equalverify")
                    else:
                        pushbool(equal)

                # ---- numeric ----
                elif opcode in (S.OP_1ADD, S.OP_1SUB, S.OP_NEGATE, S.OP_ABS,
                                S.OP_NOT, S.OP_0NOTEQUAL):
                    n = popnum()
                    if opcode == S.OP_1ADD:
                        n += 1
                    elif opcode == S.OP_1SUB:
                        n -= 1
                    elif opcode == S.OP_NEGATE:
                        n = -n
                    elif opcode == S.OP_ABS:
                        n = abs(n)
                    elif opcode == S.OP_NOT:
                        n = int(n == 0)
                    else:  # 0NOTEQUAL
                        n = int(n != 0)
                    pushint(n)
                elif opcode in (S.OP_ADD, S.OP_SUB, S.OP_BOOLAND, S.OP_BOOLOR,
                                S.OP_NUMEQUAL, S.OP_NUMEQUALVERIFY,
                                S.OP_NUMNOTEQUAL, S.OP_LESSTHAN,
                                S.OP_GREATERTHAN, S.OP_LESSTHANOREQUAL,
                                S.OP_GREATERTHANOREQUAL, S.OP_MIN, S.OP_MAX):
                    n2 = popnum()
                    n1 = popnum()
                    if opcode == S.OP_ADD:
                        out = n1 + n2
                    elif opcode == S.OP_SUB:
                        out = n1 - n2
                    elif opcode == S.OP_BOOLAND:
                        out = int(n1 != 0 and n2 != 0)
                    elif opcode == S.OP_BOOLOR:
                        out = int(n1 != 0 or n2 != 0)
                    elif opcode in (S.OP_NUMEQUAL, S.OP_NUMEQUALVERIFY):
                        out = int(n1 == n2)
                    elif opcode == S.OP_NUMNOTEQUAL:
                        out = int(n1 != n2)
                    elif opcode == S.OP_LESSTHAN:
                        out = int(n1 < n2)
                    elif opcode == S.OP_GREATERTHAN:
                        out = int(n1 > n2)
                    elif opcode == S.OP_LESSTHANOREQUAL:
                        out = int(n1 <= n2)
                    elif opcode == S.OP_GREATERTHANOREQUAL:
                        out = int(n1 >= n2)
                    elif opcode == S.OP_MIN:
                        out = min(n1, n2)
                    else:
                        out = max(n1, n2)
                    if opcode == S.OP_NUMEQUALVERIFY:
                        if not out:
                            raise ScriptError("numequalverify")
                    else:
                        pushint(out)
                elif opcode == S.OP_WITHIN:
                    n3 = popnum()
                    n2 = popnum()
                    n1 = popnum()
                    pushbool(n2 <= n1 < n3)

                # ---- crypto ----
                elif opcode in (S.OP_RIPEMD160, S.OP_SHA1, S.OP_SHA256,
                                S.OP_HASH160, S.OP_HASH256):
                    v = popstack()
                    if opcode == S.OP_RIPEMD160:
                        out_b = ripemd160(v)
                    elif opcode == S.OP_SHA1:
                        import hashlib
                        out_b = hashlib.sha1(v).digest()
                    elif opcode == S.OP_SHA256:
                        out_b = sha256(v)
                    elif opcode == S.OP_HASH160:
                        out_b = hash160(v)
                    else:
                        out_b = sha256d(v)
                    stack.append(out_b)
                elif opcode == S.OP_CODESEPARATOR:
                    begincode = pc_after
                elif opcode in (S.OP_CHECKSIG, S.OP_CHECKSIGVERIFY):
                    if len(stack) < 2:
                        raise ScriptError("invalid-stack-operation")
                    pubkey = popstack()
                    sig = stack.pop()  # order: sig below pubkey
                    # NB: reference pops (pubkey, sig) from top: sig is
                    # second from top. We popped pubkey then sig. Correct.
                    script_code = script[begincode:]
                    check_signature_encoding(sig, flags)
                    check_pubkey_encoding(pubkey, flags)
                    ok = checker.check_sig(sig, pubkey, script_code, flags)
                    if not ok and (flags & SCRIPT_VERIFY_NULLFAIL) and sig:
                        raise ScriptError("sig-nullfail")
                    if opcode == S.OP_CHECKSIGVERIFY:
                        if not ok:
                            raise ScriptError("checksigverify")
                    else:
                        pushbool(ok)
                elif opcode in (S.OP_CHECKMULTISIG, S.OP_CHECKMULTISIGVERIFY):
                    i = 1
                    if len(stack) < i:
                        raise ScriptError("invalid-stack-operation")
                    keys_count = CScriptNum.decode(stack[-i], minimal)
                    if keys_count < 0 or keys_count > MAX_PUBKEYS_PER_MULTISIG:
                        raise ScriptError("pubkey-count")
                    op_count += keys_count
                    if op_count > MAX_OPS_PER_SCRIPT:
                        raise ScriptError("op-count")
                    ikey = i + 1
                    i += keys_count + 1
                    if len(stack) < i:
                        raise ScriptError("invalid-stack-operation")
                    sigs_count = CScriptNum.decode(stack[-i], minimal)
                    if sigs_count < 0 or sigs_count > keys_count:
                        raise ScriptError("sig-count")
                    isig = i + 1
                    i += sigs_count + 1
                    if len(stack) < i:
                        raise ScriptError("invalid-stack-operation")

                    sigs = [stack[-(isig + k)] for k in range(sigs_count)]
                    keys = [stack[-(ikey + k)] for k in range(keys_count)]
                    # reference multisig FindAndDeletes EVERY sig from
                    # scriptCode before any CheckSig — EXCEPT when that
                    # sig uses the FORKID digest (CleanupScriptCode skips
                    # FindAndDelete for forkid signatures; stripping there
                    # would diverge from reference nodes on crafted
                    # redeem scripts embedding a signature push)
                    script_code = script[begincode:]
                    forkid_on = bool(flags & SCRIPT_ENABLE_SIGHASH_FORKID)
                    for sig in sigs:
                        if sig and not (forkid_on and sig[-1] & SIGHASH_FORKID):
                            script_code = S.find_and_delete(
                                script_code, S.push_data_raw(sig)
                            )

                    success = True
                    si, ki = 0, 0
                    while success and sigs_count - si > 0:
                        sig = sigs[si]
                        pubkey = keys[ki]
                        if is_schnorr_signature(sig):
                            # BCH consensus: 65-byte (Schnorr-sized) sigs
                            # are forbidden in legacy CHECKMULTISIG — the
                            # key-trial loop can't attribute a Schnorr sig
                            # to a key without running the verify, which
                            # defeats batching (spec 2019-05-15-schnorr)
                            raise ScriptError("sig-badlength")
                        check_signature_encoding(sig, flags)
                        check_pubkey_encoding(pubkey, flags)
                        ok = checker.check_sig(
                            sig, pubkey, script_code, flags, defer_ok=False
                        )
                        if ok:
                            si += 1
                        ki += 1
                        if sigs_count - si > keys_count - ki:
                            success = False
                    if not success and (flags & SCRIPT_VERIFY_NULLFAIL):
                        if any(s for s in sigs):
                            raise ScriptError("sig-nullfail")

                    # pop all sigs/keys/counts + the extra dummy element
                    for _ in range(i - 1):
                        popstack()
                    if not stack:
                        raise ScriptError("invalid-stack-operation")
                    dummy = popstack()
                    if (flags & SCRIPT_VERIFY_NULLDUMMY) and dummy:
                        raise ScriptError("sig-nulldummy")

                    if opcode == S.OP_CHECKMULTISIGVERIFY:
                        if not success:
                            raise ScriptError("checkmultisigverify")
                    else:
                        pushbool(success)
                else:
                    raise ScriptError("bad-opcode", f"0x{opcode:02x}")

            if len(stack) + len(altstack) > MAX_STACK_SIZE:
                raise ScriptError("stack-size")
    except ScriptNumError as e:
        raise ScriptError("unknown-error", str(e)) from e

    if vexec:
        raise ScriptError("unbalanced-conditional")


def VerifyScript(script_sig: bytes, script_pubkey: bytes, flags: int,
                 checker: BaseSignatureChecker) -> None:
    """VerifyScript (interpreter.cpp:~1400): run scriptSig then
    scriptPubKey (+ P2SH redeem script), enforce final-stack truth.
    Raises ScriptError; returns None on success."""
    if isinstance(checker, DeferringSignatureChecker):
        assert flags & SCRIPT_VERIFY_NULLFAIL, (
            "deferred sig batching requires NULLFAIL for soundness"
        )
    if (flags & SCRIPT_VERIFY_SIGPUSHONLY) and not S.is_push_only(script_sig):
        raise ScriptError("sig-pushonly")

    stack: list[bytes] = []
    EvalScript(stack, script_sig, flags, checker)
    stack_copy = list(stack) if flags & SCRIPT_VERIFY_P2SH else None
    EvalScript(stack, script_pubkey, flags, checker)
    if not stack:
        raise ScriptError("eval-false")
    if not cast_to_bool(stack[-1]):
        raise ScriptError("eval-false")

    # P2SH (interpreter.cpp:~1440)
    if (flags & SCRIPT_VERIFY_P2SH) and S.is_p2sh(script_pubkey):
        if not S.is_push_only(script_sig):
            raise ScriptError("sig-pushonly")
        stack = stack_copy
        assert stack  # scriptSig pushed at least the redeem script
        redeem = stack.pop()
        EvalScript(stack, redeem, flags, checker)
        if not stack:
            raise ScriptError("eval-false")
        if not cast_to_bool(stack[-1]):
            raise ScriptError("eval-false")

    if flags & SCRIPT_VERIFY_CLEANSTACK:
        assert flags & SCRIPT_VERIFY_P2SH  # reference asserts this pairing
        if len(stack) != 1:
            raise ScriptError("cleanstack")
