"""bcpd — the daemon entry point.

Reference: src/bitcoind.cpp (main → AppInit → AppInitMain → run until
StartShutdown). SIGINT/SIGTERM trigger the same orderly shutdown as the
`stop` RPC.
"""

from __future__ import annotations

import signal
import sys

from ..node.config import HELP_MESSAGE, Config, ConfigError
from ..node.node import InitError, Node


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    config = Config()
    try:
        config.parse_args(argv)
    except ConfigError as e:
        print(f"Error parsing command line arguments: {e}", file=sys.stderr)
        return 1
    if config.get_bool("?") or config.get_bool("help"):
        print(HELP_MESSAGE)
        return 0
    try:
        config.read_config_file()
    except ConfigError as e:
        print(f"Error reading configuration file: {e}", file=sys.stderr)
        return 1

    try:
        node = Node(config)
    except (InitError, Exception) as e:
        print(f"Error: {e}", file=sys.stderr)
        raise

    def handle_signal(signum, frame):
        node.stop()

    signal.signal(signal.SIGINT, handle_signal)
    signal.signal(signal.SIGTERM, handle_signal)

    if config.get_bool("server", True):
        node.start_rpc()
    if config.get_bool("listen", True) or config.has("connect"):
        try:
            node.start_p2p()
        except Exception as e:
            print(f"P2P disabled: {e}", file=sys.stderr)
    if config.get_int("gateway", 0):
        node.start_gateway()

    print(f"bcpd started: network={node.params.network} datadir={node.datadir}",
          flush=True)
    try:
        node.wait_for_shutdown()
    finally:
        node.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
