"""Process entry points: bcpd (daemon), bcp-cli (RPC client), bcp-tx
(offline transaction editor).

Reference: src/bitcoind.cpp, src/bitcoin-cli.cpp, src/bitcoin-tx.cpp.
Runnable both as installed console scripts and as modules
(`python -m bitcoincashplus_tpu.cli.bcpd`).
"""
