"""bcp-cli — command-line RPC client.

Reference: src/bitcoin-cli.cpp: flags mirror bcpd's (-datadir, -regtest,
-rpcport, -rpcuser/-rpcpassword), positionals are `method [params...]`.
JSON-looking params are parsed as JSON, everything else passes as strings
(the reference's univalue coercion behaves the same for our method set).
"""

from __future__ import annotations

import json
import sys

from ..node.config import Config, ConfigError
from ..rpc.client import JSONRPCException, RPCClient


def _coerce(value: str):
    try:
        return json.loads(value)
    except json.JSONDecodeError:
        return value


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    config = Config()
    positionals = []
    for arg in argv:
        if arg.startswith("-") and not positionals:
            try:
                config.parse_args([arg])
            except ConfigError as e:
                print(f"Error: {e}", file=sys.stderr)
                return 1
        else:
            positionals.append(arg)
    if not positionals:
        print("usage: bcp-cli [options] <method> [params...]", file=sys.stderr)
        return 1
    config.read_config_file()
    params = config.chain_params()
    client = RPCClient(
        host=config.get("rpcconnect", "127.0.0.1"),
        port=config.rpc_port(params),
        user=config.get("rpcuser"),
        password=config.get("rpcpassword"),
        datadir=None if config.get("rpcuser") else config.datadir,
    )
    method, *raw_params = positionals
    try:
        result = client.call(method, *(_coerce(p) for p in raw_params))
    except JSONRPCException as e:
        print(f"error code: {e.code}\nerror message:\n{e}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"error: couldn't connect to server: {e}", file=sys.stderr)
        return 1
    if isinstance(result, (dict, list)):
        print(json.dumps(result, indent=2))
    elif result is None:
        pass
    else:
        print(result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
