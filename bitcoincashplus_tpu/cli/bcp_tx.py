"""bcp-tx — offline transaction builder/editor (src/bitcoin-tx.cpp).

Command-style arguments applied left to right to a transaction, like the
reference tool:

    bcp-tx [-regtest|-testnet] [-json] [-create | <hextx>] <command>...

Commands:
    nversion=N                    set tx version
    locktime=N                    set nLockTime
    in=TXID:VOUT[:SEQUENCE]       append an input (txid in display hex)
    out=AMOUNT:ADDRESS            append a P2PKH output (amount in coins)
    outscript=AMOUNT:HEXSCRIPT    append a raw-script output
    outdata=HEXDATA               append an OP_RETURN data output
    delin=N / delout=N            delete input/output N
    sign=WIF:TXID:VOUT:SPKHEX:AMOUNT
                                  sign one matching input (FORKID sighash)

Runs entirely offline — no node, no RPC, no device."""

from __future__ import annotations

import json
import sys

from ..consensus.params import select_params
from ..consensus.serialize import ByteReader, hash_to_hex, hex_to_hash
from ..consensus.tx import COIN, COutPoint, CTransaction, CTxIn, CTxOut
from ..script.script import OP_RETURN, push_data_raw
from ..script.sighash import SIGHASH_ALL, SIGHASH_FORKID
from ..wallet.keys import CKey, address_to_script
from ..wallet.signing import solve_script_sig


def _fail(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    return 1


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    network = "main"
    as_json = False
    while args and args[0].startswith("-") and args[0] != "-create":
        flag = args.pop(0)
        if flag == "-regtest":
            network = "regtest"
        elif flag == "-testnet":
            network = "test"
        elif flag == "-json":
            as_json = True
        elif flag in ("-h", "-help", "--help"):
            print(__doc__)
            return 0
        else:
            return _fail(f"unknown flag {flag}")
    params = select_params(network)
    if not args:
        print(__doc__)
        return 1

    first = args.pop(0)
    if first == "-create":
        tx = CTransaction(vin=(), vout=())
    else:
        try:
            tx = CTransaction.deserialize(ByteReader(bytes.fromhex(first)))
        except Exception as e:
            return _fail(f"bad transaction hex: {e}")

    for cmd in args:
        key_, _, value = cmd.partition("=")
        try:
            tx = _apply(tx, key_, value, params)
        except Exception as e:
            return _fail(f"{cmd}: {e}")

    if as_json:
        print(json.dumps(_decode(tx), indent=2))
    else:
        print(tx.serialize().hex())
    return 0


def _apply(tx: CTransaction, key: str, value: str, params) -> CTransaction:
    vin, vout = list(tx.vin), list(tx.vout)
    version, locktime = tx.version, tx.locktime
    if key == "nversion":
        version = int(value)
    elif key == "locktime":
        locktime = int(value)
    elif key == "in":
        parts = value.split(":")
        txid, n = hex_to_hash(parts[0]), int(parts[1])
        seq = int(parts[2]) if len(parts) > 2 else 0xFFFFFFFF
        vin.append(CTxIn(COutPoint(txid, n), b"", seq))
    elif key == "out":
        amount_s, _, addr = value.partition(":")
        spk = address_to_script(addr, params)
        if spk is None:
            raise ValueError(f"bad address {addr}")
        vout.append(CTxOut(int(round(float(amount_s) * COIN)), spk))
    elif key == "outscript":
        amount_s, _, hexscript = value.partition(":")
        vout.append(CTxOut(int(round(float(amount_s) * COIN)),
                           bytes.fromhex(hexscript)))
    elif key == "outdata":
        vout.append(CTxOut(0, bytes([OP_RETURN]) +
                           push_data_raw(bytes.fromhex(value))))
    elif key == "delin":
        del vin[int(value)]
    elif key == "delout":
        del vout[int(value)]
    elif key == "sign":
        wif, txid_hex, n_s, spk_hex, amount_s = value.split(":")
        signer = CKey.from_wif(wif, params)
        if signer is None:
            raise ValueError("bad WIF key")
        prevout = COutPoint(hex_to_hash(txid_hex), int(n_s))
        spk = bytes.fromhex(spk_hex)
        amount = int(round(float(amount_s) * COIN))
        base = CTransaction(version, tuple(vin), tuple(vout), locktime)
        for i, txin in enumerate(vin):
            if txin.prevout == prevout:
                script_sig = solve_script_sig(
                    spk, base, i, amount,
                    lambda ident: signer if ident in (
                        signer.pubkey_hash, signer.pubkey) else None,
                    SIGHASH_ALL | SIGHASH_FORKID, enable_forkid=True,
                )
                vin[i] = CTxIn(txin.prevout, script_sig, txin.sequence)
                break
        else:
            raise ValueError("no matching input to sign")
    else:
        raise ValueError(f"unknown command {key!r}")
    return CTransaction(version, tuple(vin), tuple(vout), locktime)


def _decode(tx: CTransaction) -> dict:
    return {
        "txid": tx.txid_hex,
        "version": tx.version,
        "locktime": tx.locktime,
        "size": len(tx.serialize()),
        "vin": [
            {"txid": hash_to_hex(i.prevout.hash), "vout": i.prevout.n,
             "scriptSig": i.script_sig.hex(), "sequence": i.sequence}
            for i in tx.vin
        ],
        "vout": [
            {"n": n, "value": o.value / COIN,
             "scriptPubKey": o.script_pubkey.hex()}
            for n, o in enumerate(tx.vout)
        ],
    }


if __name__ == "__main__":
    sys.exit(main())
