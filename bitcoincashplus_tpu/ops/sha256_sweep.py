"""Specialized SHA-256d nonce-sweep kernel (op-count-minimal h7 path).

The generic sweep (ops/miner.py + ops/sha256.py) computes the full 8-word
double-SHA digest per nonce and an 8-limb target compare. This module is the
miner-grade specialization of the same search — the moral equivalent of the
hand-scheduled Transform specializations the reference keeps per-ISA
(src/crypto/sha256_sse4.cpp, sha256_avx2.cpp: same math, fewer ops per hash):

  1. **Shared prefix** — header bytes 0..63 are midstate (already exploited);
     on top of that, rounds 0..2 of the second compression consume only
     header words w0..w2 (merkle tail / nTime / nBits), which are constant
     across the sweep, so those rounds and every schedule term not touching
     the nonce fold to constants (the AsicBoost-style schedule sharing of
     PAPERS.md item 2, applied to the nonce axis).
  2. **Zero/constant padding algebra** — block 2 of the first hash is
     [w0,w1,w2,nonce,PAD,0*10,len]; most σ0/σ1 schedule terms vanish or fold.
  3. **Truncated tail + h7-first early exit** — PoW compares the hash as a
     little-endian uint256, whose topmost 32 bits are digest word h[7]
     byte-swapped (src/pow.cpp:~74 CheckProofOfWork / arith_uint256). h[7] =
     IV7 + e_61, and e_61 = a_57 + t1_60, so rounds 61..63 of the second
     compression are never computed and rounds 57..60 need only their
     e-chain (t1); the other seven digest words are never produced. The
     device returns *candidate* nonces (limb7 <= target limb7); the host
     re-verifies the full 256-bit compare with the scalar oracle and resumes
     the sweep past false positives (~2^-32 per hash when limb7 ties).
  4. **Chunk-2 midstate hoisting** (``hoist_template``) — the per-template
     precompute is now EXPLICIT instead of relying on numpy's left-to-right
     constant folding: the first three compression rounds of chunk 2, every
     K[i]+w[i] pair whose message word is sweep-constant, and the
     constant-only legs of the schedule expansion (words 16..32 carried as
     (scalar, vector) pairs, materialized lazily) are computed ONCE per
     template — on the host as numpy scalars (trace-time folded into the
     compiled program) or on device as traced scalars (the resident mining
     loop's template swap: XLA lifts them out of the per-nonce vector
     fusion, so a swap never changes the compiled shape). The explicit
     grouping also removes the add-0 / scalar-chain vector ops the implicit
     folding missed — a measured ops/nonce reduction in the roofline census
     (ROOFLINE.md §8) with bit-identical digests vs the CPU oracle.

All round/schedule code below is polymorphic over numpy uint32 scalars and
traced jax arrays: anything not data-dependent on the nonce lane vector stays
a numpy scalar at trace time (folded into the program as a literal), or a
traced scalar (hoisted by XLA out of the vector fusion) when the midstate is
passed as a device array. Only nonce-dependent values become (tile,)-shaped
vector ops — the count that sets throughput on the VPU (see ROOFLINE.md).

Differential-tested against hashlib in tests/unit/test_sha256_sweep.py and
tests/unit/test_mining_resident.py (hoisted vs sweep_header_cpu).
"""

from __future__ import annotations

import warnings
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.hashes import SHA256_INIT, SHA256_K, header_midstate, sha256d
from .sha256 import bswap32, bytes_to_words_np, target_to_limbs_np

_K = [np.uint32(k) for k in SHA256_K]
_IV = [np.uint32(v) for v in SHA256_INIT]
_PAD = np.uint32(0x80000000)
_Z = np.uint32(0)
_LEN80 = np.uint32(640)
_LEN32 = np.uint32(256)


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _S0(x):
    return _rotr(x, 2) ^ _rotr(x, 13) ^ _rotr(x, 22)


def _S1(x):
    return _rotr(x, 6) ^ _rotr(x, 11) ^ _rotr(x, 25)


def _s0(x):
    return _rotr(x, 7) ^ _rotr(x, 18) ^ (x >> np.uint32(3))


def _s1(x):
    return _rotr(x, 17) ^ _rotr(x, 19) ^ (x >> np.uint32(10))


def _ch(e, f, g):
    # g ^ (e & (f ^ g)) == (e & f) | (~e & g): one op fewer than the
    # textbook form (no ~), and f^g is shared when f,g are still scalar.
    return g ^ (e & (f ^ g))


def _maj(a, b, c):
    # (a & (b ^ c)) ^ (b & c): 4 ops vs 5 for the three-AND form.
    return ((b ^ c) & a) ^ (b & c)


def _round(state, k, w):
    a, b, c, d, e, f, g, h = state
    t1 = h + _S1(e) + _ch(e, f, g) + k + w
    t2 = _S0(a) + _maj(a, b, c)
    return (t1 + t2, a, b, c, d + t1, e, f, g)


def _round_kw(state, kw, vecw=None):
    """One compression round with the round constant pre-folded: ``kw`` is
    K[i] + (the sweep-constant part of w[i]) — one vector add instead of
    two; ``vecw`` is the nonce-dependent remainder of the message word
    (None for fully-constant words)."""
    a, b, c, d, e, f, g, h = state
    t1 = (h + kw) + _S1(e) + _ch(e, f, g)
    if vecw is not None:
        t1 = t1 + vecw
    t2 = _S0(a) + _maj(a, b, c)
    return (t1 + t2, a, b, c, d + t1, e, f, g)


# ---------------------------------------------------------------------------
# Per-template chunk-2 hoist
# ---------------------------------------------------------------------------

# chunk-2 schedule words carried as (scalar, vector) pairs: index -> True
# when the scalar leg is identically zero for every template (w5..w14 are
# padding zeros), so materialization skips the add.
_SC_ZERO = frozenset((21, 28))

# chunk-3 (second hash) K+w folds for the padding rounds 8..15 — template-
# independent global constants: w8=PAD, w9..w14=0, w15=LEN32.
_KW3 = tuple(
    np.uint32((SHA256_K[8 + i] + w) & 0xFFFFFFFF)
    for i, w in enumerate(
        (0x80000000, 0, 0, 0, 0, 0, 0, 256))
)
_S1_LEN32 = _s1(_LEN32)  # σ1 of the chunk-3 length word (constant)
_S0_PAD = _s0(_PAD)      # σ0 of the padding word (constant)


def hoist_template(midstate8, tail3):
    """Per-template chunk-2 precompute (AsicBoost-style shared-computation
    reuse, PAPERS.md 1604.00575): everything in the second compression of
    the first hash that does not depend on the nonce, computed once per
    template instead of once per nonce.

    midstate8: 8 uint32 scalars (numpy or traced) — SHA-256 state after
    header bytes 0..63. tail3: 3 uint32 scalars — BE words of bytes 64..75
    (merkle tail, nTime, nBits). Returns a dict of sweep-constant scalars:

      mid    the midstate (for the chunk-2 feedback add)
      st3    compression state after rounds 0..2 (they consume only
             w0..w2 — hoisted entirely)
      c3t1   round 3's folded scalar leg: h3 + Σ1(e3) + ch(e3,f3,g3) + K3
             (the round's t1 is this plus the nonce word)
      t2_3   round 3's t2 (pure scalar)
      kw     K[i]+w[i] for rounds 4..15 (w = PAD / zeros / length — all
             sweep-constant)
      sc     scalar legs of schedule words 16..32 (16/17 are FULLY scalar;
             18..32 split into scalar + nonce-dependent vector parts;
             indices in _SC_ZERO are identically zero and omitted)
      kwsc   K[i] + sc[i] for rounds 16..32, pre-folded for _round_kw

    Polymorphic: numpy inputs fold at trace time (per-dispatch host
    hoist); traced scalars are computed on device once per template and
    lifted out of the per-nonce vector fusion by XLA — the resident
    loop's buffer swap re-runs only this scalar prologue, never a
    retrace (asserted by the devicewatch sentinel test)."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        w0, w1, w2 = tail3
        st = tuple(midstate8)
        for i, w in enumerate((w0, w1, w2)):
            st = _round(st, _K[i], w)
        a3, b3, c3, d3, e3, f3, g3, h3 = st
        c3t1 = h3 + _S1(e3) + _ch(e3, f3, g3) + _K[3]
        t2_3 = _S0(a3) + _maj(a3, b3, c3)
        # rounds 4..15: the message words are PAD / zeros / LEN80
        w_const = [_PAD] + [_Z] * 10 + [_LEN80]
        kw = [_K[4 + i] + w for i, w in enumerate(w_const)]
        # schedule words 16/17 are fully sweep-constant; 18..32 carry a
        # scalar leg next to their nonce-dependent vector leg
        sc = {}
        sc[16] = w0 + _s0(w1)                      # + w9 + σ1(w14), both 0
        sc[17] = w1 + _s0(w2) + _s1(_LEN80)        # + w10 = 0
        sc[18] = w2 + _s1(sc[16])                  # + w11 = 0; σ0(nonce) vec
        sc[19] = _s0(_PAD) + _s1(sc[17])           # + w12 = 0; + nonce vec
        sc[20] = _PAD                              # σ0(w5)=0, w13=0
        # sc[21] == 0 (w5 + σ0(w6) + w14)
        sc[22] = _LEN80                            # w6 + σ0(w7) + w15
        sc[23] = sc[16]                            # w7 + σ0(w8) + w16
        sc[24] = sc[17]                            # w8 + σ0(w9) + w17
        sc[25] = sc[18]                            # w9 + σ0(w10) + sc(w18)
        sc[26] = sc[19]
        sc[27] = sc[20]
        # sc[28] == 0 (sc[21])
        sc[29] = sc[22]
        sc[30] = _s0(_LEN80) + sc[23]              # w14=0, σ0(w15) const
        sc[31] = _LEN80 + _s0(sc[16]) + sc[24]     # w15 + σ0(w16) + sc(w24)
        sc[32] = sc[16] + _s0(sc[17]) + sc[25]     # w16 + σ0(w17) + sc(w25)
        kwsc = {i: (_K[i] + sc[i]) if i in sc else _K[i]
                for i in range(16, 33)}
        return {"mid": list(midstate8), "st3": st, "c3t1": c3t1,
                "t2_3": t2_3, "kw": kw, "sc": sc, "kwsc": kwsc}


def _chunk2_digest_hoisted(pre, nonces):
    """First-hash digest words (8 vectors shaped like ``nonces``) from a
    hoisted template: compression 2 over [w0,w1,w2,nonce,PAD,0*10,len]
    with every sweep-constant leg taken from ``pre``."""
    n = bswap32(nonces)
    sc = pre["sc"]
    vec = {18: _s0(n), 19: n}
    full = {16: sc[16], 17: sc[17]}

    def mat(i):
        """Materialize schedule word i (scalar + vector legs, memoized;
        zero scalar legs skip the add)."""
        w = full.get(i)
        if w is None:
            w = vec[i] if i in _SC_ZERO else sc[i] + vec[i]
            full[i] = w
        return w

    for i in range(20, 25):
        vec[i] = _s1(mat(i - 2))
    for i in range(25, 33):
        vec[i] = vec[i - 7] + _s1(mat(i - 2))
    for i in range(33, 64):
        full[i] = (mat(i - 16) + _s0(mat(i - 15)) + mat(i - 7)
                   + _s1(mat(i - 2)))

    # rounds 0..2 hoisted (pre["st3"]); round 3 consumes the nonce word
    a3, b3, c3, d3, e3, f3, g3, h3 = pre["st3"]
    t1 = pre["c3t1"] + n
    st = (t1 + pre["t2_3"], a3, b3, c3, d3 + t1, e3, f3, g3)
    for i in range(4, 16):
        st = _round_kw(st, pre["kw"][i - 4])
    for i in range(16, 33):
        st = _round_kw(st, pre["kwsc"][i], vec.get(i))
    for i in range(33, 64):
        st = _round(st, _K[i], full[i])
    return [m + s for m, s in zip(pre["mid"], st)]  # feedback -> digest


def _chunk3_words(d8, upto: int) -> list:
    """Second-hash message schedule [d8 || PAD || 0*6 || len], expanded to
    ``upto`` words with the constant legs folded (zero words skipped,
    σ of the padding/length words as module constants)."""
    w = list(d8) + [None] * (upto - 8)  # indices 8..15 never read below
    w[16] = w[0] + _s0(w[1])                       # w9=0, σ1(w14)=0
    w[17] = w[1] + _s0(w[2]) + _S1_LEN32           # w10=0
    for i in range(18, 22):                        # w11..w14 = 0
        w[i] = w[i - 16] + _s0(w[i - 15]) + _s1(w[i - 2])
    w[22] = w[6] + _s0(w[7]) + _LEN32 + _s1(w[20])
    w[23] = (w[7] + _S0_PAD) + w[16] + _s1(w[21])
    w[24] = (w[17] + _s1(w[22])) + _PAD            # σ0(w9)=0
    for i in range(25, 30):          # w[i-16] = 0, σ0(w[i-15]) = σ0(0) = 0
        w[i] = w[i - 7] + _s1(w[i - 2])
    w[30] = w[23] + _s1(w[28]) + _s0(_LEN32)       # w14 = 0, w15 = len
    w[31] = _LEN32 + _s0(w[16]) + w[24] + _s1(w[29])
    for i in range(32, upto):
        w[i] = w[i - 16] + _s0(w[i - 15]) + w[i - 7] + _s1(w[i - 2])
    return w


def _chunk3_rounds(w, upto: int):
    """Run second-hash compression rounds 0..upto-1 from the fresh IV;
    rounds 8..15 use the pre-folded K+w constants (_KW3)."""
    st = tuple(_IV)
    for i in range(min(8, upto)):
        st = _round(st, _K[i], w[i])
    for i in range(8, min(16, upto)):
        st = _round_kw(st, _KW3[i - 8])
    for i in range(16, upto):
        st = _round(st, _K[i], w[i])
    return st


def sweep_h7_hoisted(pre, nonces):
    """Digest word h[7] of sha256d(header) for each nonce, from a hoisted
    template (``hoist_template``). Returns (tile,) uint32 h[7] values; the
    PoW limb is bswap32(h7) (top 32 bits of the LE uint256 hash)."""
    with warnings.catch_warnings():
        # numpy scalar uint32 arithmetic wraps mod 2^32 (what SHA needs)
        # but warns; the traced side never warns.
        warnings.simplefilter("ignore", RuntimeWarning)
        d8 = _chunk2_digest_hoisted(pre, nonces)
        # second hash, truncated to the h7 chain: rounds 61..63 never run,
        # w61..w63 never expanded, 7 of 8 digest words never formed.
        w = _chunk3_words(d8, 61)
        st = _chunk3_rounds(w, 57)
        a57, b57, c57, d57, e, f, g, h = st
        # rounds 57..59: e-chain only (t1); a/b/c/d successors are known
        # shifts of a57..c57, so no Σ0/maj work is ever done here.
        for r, dprev in zip((57, 58, 59), (d57, c57, b57)):
            t1 = h + _S1(e) + _ch(e, f, g) + _K[r] + w[r]
            e, f, g, h = dprev + t1, e, f, g
        # round 60: only t1 is needed; e_61 = d_60 + t1_60 with d_60 = a_57.
        t1_60 = h + _S1(e) + _ch(e, f, g) + _K[60] + w[60]
        return _IV[7] + a57 + t1_60


def sweep_digest_hoisted(pre, nonces):
    """Full 8-word sha256d digest state per nonce from a hoisted template —
    the exact-compare twin of ``sweep_h7_hoisted`` (same hoisted chunk 2,
    full second compression). Used by the generic sweep tile
    (ops/miner._sweep_tile) and the resident mining loop's exact on-device
    compare; same output contract as ops/sha256.header_sweep_digest."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        d8 = _chunk2_digest_hoisted(pre, nonces)
        w = _chunk3_words(d8, 64)
        st = _chunk3_rounds(w, 64)
        return [v + s for v, s in zip(_IV, st)]


def sweep_h7(midstate8, tail3, nonces):
    """Digest word h[7] of sha256d(header) for each nonce in `nonces`.

    midstate8: 8 uint32 scalars (numpy or traced) — SHA-256 state after
    header bytes 0..63. tail3: 3 uint32 scalars — BE words of bytes 64..75.
    nonces: (tile,) uint32 device array. Hoists the template once
    (``hoist_template``) and runs the per-nonce remainder."""
    return sweep_h7_hoisted(hoist_template(midstate8, tail3), nonces)


@partial(jax.jit, static_argnames=("tile",))
def sweep_fast_jit(midstate, tail, t7, start_nonce, n_tiles, tile: int):
    """Candidate sweep of [start, start + n_tiles*tile): first nonce whose
    hash's top LE limb (bswap32(h7)) is <= t7.

    midstate: (8,) uint32; tail: (3,) uint32; t7: uint32 scalar (top limb of
    the target; 0 for any real-difficulty target). Returns (found, nonce,
    tiles_done). Candidates must be host-verified against the full 256-bit
    target (sweep_header_fast does); at limb equality the compare is
    undecided at this truncation.
    """
    mid8 = [midstate[i] for i in range(8)]
    tail3 = [tail[i] for i in range(3)]
    # template hoist: traced scalars, computed once per dispatch and lifted
    # out of the while_loop by XLA (loop-invariant)
    pre = hoist_template(mid8, tail3)

    def tile_fn(base):
        lanes = jax.lax.broadcasted_iota(jnp.uint32, (tile, 1), 0).squeeze(-1)
        nonces = base + lanes
        h7 = sweep_h7_hoisted(pre, nonces)
        ok = bswap32(h7) <= t7
        return jnp.any(ok), nonces[jnp.argmax(ok)]

    def cond(carry):
        i, found, _ = carry
        return jnp.logical_and(i < n_tiles, jnp.logical_not(found))

    def body(carry):
        i, _, _ = carry
        hit, nonce = tile_fn(start_nonce + i.astype(jnp.uint32) * np.uint32(tile))
        return i + np.uint32(1), hit, nonce

    tiles, found, nonce = jax.lax.while_loop(
        cond, body, (jnp.uint32(0), jnp.array(False), jnp.uint32(0))
    )
    return found, nonce, tiles


DEFAULT_TILE = 1 << 20


def sweep_header_fast(header80: bytes, target: int, start_nonce: int = 0,
                      max_nonces: int = 1 << 32, tile: int = DEFAULT_TILE):
    """Host API: find a nonce with sha256d(header) <= target, or None.

    Same contract as ops.miner.sweep_header (first hit in nonce order wins,
    returns (nonce_or_None, hashes_attempted)) but on the truncated-h7
    kernel: device candidates are exact-verified on the host and the sweep
    resumes past false positives, so the result is bit-identical to the
    generic path while doing fewer vector ops per nonce. Like sweep_header,
    the search stops at the 2^32 nonce-space boundary (no silent wrap into
    already-swept territory — the resident loop owns rollover policy).
    """
    assert len(header80) == 80
    midstate = jnp.asarray(np.array(header_midstate(header80), dtype=np.uint32))
    tail_np = bytes_to_words_np(np.frombuffer(header80[64:76], dtype=np.uint8))
    tail = jnp.asarray(tail_np)
    t7 = jnp.uint32(target_to_limbs_np(target)[7])

    hashes = 0
    nonce = start_nonce & 0xFFFFFFFF
    remaining = min(max_nonces, (1 << 32) - nonce)
    while remaining > 0:
        space = (1 << 32) - nonce  # tiles left before the 2^32 boundary
        n_tiles = min((remaining + tile - 1) // tile,
                      (space + tile - 1) // tile)
        found, cand, tiles = sweep_fast_jit(
            midstate, tail, t7, jnp.uint32(nonce), jnp.uint32(n_tiles), tile=tile
        )
        done = min(int(tiles) * tile, space)
        hashes += done
        if not bool(found):
            return None, hashes
        cand = int(cand)
        # exact host check of the candidate (scalar oracle)
        hdr = header80[:76] + int(cand).to_bytes(4, "little")
        if int.from_bytes(sha256d(hdr), "little") <= target:
            return cand, hashes
        # false positive (limb7 tie): resume just past it. The tiles the
        # device already swept before the candidate stay counted; the
        # candidate's own tile is partially re-swept, which is harmless.
        consumed = (cand - nonce) & 0xFFFFFFFF
        remaining -= consumed + 1
        nonce = (cand + 1) & 0xFFFFFFFF
        remaining = min(remaining, (1 << 32) - nonce)
    return None, hashes
