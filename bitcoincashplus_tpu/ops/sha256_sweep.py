"""Specialized SHA-256d nonce-sweep kernel (op-count-minimal h7 path).

The generic sweep (ops/miner.py + ops/sha256.py) computes the full 8-word
double-SHA digest per nonce and an 8-limb target compare. This module is the
miner-grade specialization of the same search — the moral equivalent of the
hand-scheduled Transform specializations the reference keeps per-ISA
(src/crypto/sha256_sse4.cpp, sha256_avx2.cpp: same math, fewer ops per hash):

  1. **Shared prefix** — header bytes 0..63 are midstate (already exploited);
     on top of that, rounds 0..2 of the second compression consume only
     header words w0..w2 (merkle tail / nTime / nBits), which are constant
     across the sweep, so those rounds and every schedule term not touching
     the nonce fold to constants (the AsicBoost-style schedule sharing of
     PAPERS.md item 2, applied to the nonce axis).
  2. **Zero/constant padding algebra** — block 2 of the first hash is
     [w0,w1,w2,nonce,PAD,0*10,len]; most σ0/σ1 schedule terms vanish or fold.
  3. **Truncated tail + h7-first early exit** — PoW compares the hash as a
     little-endian uint256, whose topmost 32 bits are digest word h[7]
     byte-swapped (src/pow.cpp:~74 CheckProofOfWork / arith_uint256). h[7] =
     IV7 + e_61, and e_61 = a_57 + t1_60, so rounds 61..63 of the second
     compression are never computed and rounds 57..60 need only their
     e-chain (t1); the other seven digest words are never produced. The
     device returns *candidate* nonces (limb7 <= target limb7); the host
     re-verifies the full 256-bit compare with the scalar oracle and resumes
     the sweep past false positives (~2^-32 per hash when limb7 ties).

All round/schedule code below is polymorphic over numpy uint32 scalars and
traced jax arrays: anything not data-dependent on the nonce lane vector stays
a numpy scalar at trace time (folded into the program as a literal), or a
traced scalar (hoisted by XLA out of the vector fusion) when the midstate is
passed as a device array. Only nonce-dependent values become (tile,)-shaped
vector ops — the count that sets throughput on the VPU (see ROOFLINE.md).

Differential-tested against hashlib in tests/unit/test_sha256_sweep.py.
"""

from __future__ import annotations

import warnings
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.hashes import SHA256_INIT, SHA256_K, header_midstate, sha256d
from .sha256 import bswap32, bytes_to_words_np, target_to_limbs_np

_K = [np.uint32(k) for k in SHA256_K]
_IV = [np.uint32(v) for v in SHA256_INIT]
_PAD = np.uint32(0x80000000)
_Z = np.uint32(0)
_LEN80 = np.uint32(640)
_LEN32 = np.uint32(256)


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _S0(x):
    return _rotr(x, 2) ^ _rotr(x, 13) ^ _rotr(x, 22)


def _S1(x):
    return _rotr(x, 6) ^ _rotr(x, 11) ^ _rotr(x, 25)


def _s0(x):
    return _rotr(x, 7) ^ _rotr(x, 18) ^ (x >> np.uint32(3))


def _s1(x):
    return _rotr(x, 17) ^ _rotr(x, 19) ^ (x >> np.uint32(10))


def _ch(e, f, g):
    # g ^ (e & (f ^ g)) == (e & f) | (~e & g): one op fewer than the
    # textbook form (no ~), and f^g is shared when f,g are still scalar.
    return g ^ (e & (f ^ g))


def _maj(a, b, c):
    # (a & (b ^ c)) ^ (b & c): 4 ops vs 5 for the three-AND form.
    return ((b ^ c) & a) ^ (b & c)


def _round(state, k, w):
    a, b, c, d, e, f, g, h = state
    t1 = h + _S1(e) + _ch(e, f, g) + k + w
    t2 = _S0(a) + _maj(a, b, c)
    return (t1 + t2, a, b, c, d + t1, e, f, g)


def _expand(w, upto: int):
    """Extend a 16-entry message schedule list in place to `upto` words.
    Entries that are numpy scalars stay numpy (folded at trace time)."""
    for i in range(16, upto):
        w.append(w[i - 16] + _s0(w[i - 15]) + w[i - 7] + _s1(w[i - 2]))
    return w


def sweep_h7(midstate8, tail3, nonces):
    """Digest word h[7] of sha256d(header) for each nonce in `nonces`.

    midstate8: 8 uint32 scalars (numpy or traced) — SHA-256 state after
    header bytes 0..63. tail3: 3 uint32 scalars — BE words of bytes 64..75.
    nonces: (tile,) uint32 device array. Returns (tile,) uint32 h[7] values;
    the PoW limb is bswap32(h7) (top 32 bits of the LE uint256 hash).
    """
    with warnings.catch_warnings():
        # numpy scalar uint32 arithmetic wraps mod 2^32 (what SHA needs) but
        # warns; the traced side never warns.
        warnings.simplefilter("ignore", RuntimeWarning)

        # ---- compression 2: midstate + [w0,w1,w2,nonce,PAD,0*10,len] ----
        w = list(tail3) + [bswap32(nonces), _PAD] + [_Z] * 10 + [_LEN80]
        _expand(w, 64)
        st = tuple(midstate8)
        for i in range(64):
            st = _round(st, _K[i], w[i])
        d8 = [m + s for m, s in zip(midstate8, st)]  # feedback -> digest words

        # ---- compression 3 (second hash), truncated to the h7 chain ----
        w = list(d8) + [_PAD] + [_Z] * 6 + [_LEN32]
        _expand(w, 61)  # w61..w63 are never consumed
        st = tuple(_IV)
        for i in range(57):
            st = _round(st, _K[i], w[i])
        a57, b57, c57, d57, e, f, g, h = st
        # rounds 57..59: e-chain only (t1); a/b/c/d successors are known
        # shifts of a57..c57, so no Σ0/maj work is ever done here.
        d_chain = (d57, c57, b57)
        for r, dprev in zip((57, 58, 59), d_chain):
            t1 = h + _S1(e) + _ch(e, f, g) + _K[r] + w[r]
            e, f, g, h = dprev + t1, e, f, g
        # round 60: only t1 is needed; e_61 = d_60 + t1_60 with d_60 = a_57.
        t1_60 = h + _S1(e) + _ch(e, f, g) + _K[60] + w[60]
        return _IV[7] + a57 + t1_60


@partial(jax.jit, static_argnames=("tile",))
def sweep_fast_jit(midstate, tail, t7, start_nonce, n_tiles, tile: int):
    """Candidate sweep of [start, start + n_tiles*tile): first nonce whose
    hash's top LE limb (bswap32(h7)) is <= t7.

    midstate: (8,) uint32; tail: (3,) uint32; t7: uint32 scalar (top limb of
    the target; 0 for any real-difficulty target). Returns (found, nonce,
    tiles_done). Candidates must be host-verified against the full 256-bit
    target (sweep_header_fast does); at limb equality the compare is
    undecided at this truncation.
    """
    mid8 = [midstate[i] for i in range(8)]
    tail3 = [tail[i] for i in range(3)]

    def tile_fn(base):
        lanes = jax.lax.broadcasted_iota(jnp.uint32, (tile, 1), 0).squeeze(-1)
        nonces = base + lanes
        h7 = sweep_h7(mid8, tail3, nonces)
        ok = bswap32(h7) <= t7
        return jnp.any(ok), nonces[jnp.argmax(ok)]

    def cond(carry):
        i, found, _ = carry
        return jnp.logical_and(i < n_tiles, jnp.logical_not(found))

    def body(carry):
        i, _, _ = carry
        hit, nonce = tile_fn(start_nonce + i.astype(jnp.uint32) * np.uint32(tile))
        return i + np.uint32(1), hit, nonce

    tiles, found, nonce = jax.lax.while_loop(
        cond, body, (jnp.uint32(0), jnp.array(False), jnp.uint32(0))
    )
    return found, nonce, tiles


DEFAULT_TILE = 1 << 20


def sweep_header_fast(header80: bytes, target: int, start_nonce: int = 0,
                      max_nonces: int = 1 << 32, tile: int = DEFAULT_TILE):
    """Host API: find a nonce with sha256d(header) <= target, or None.

    Same contract as ops.miner.sweep_header (first hit in nonce order wins,
    returns (nonce_or_None, hashes_attempted)) but on the truncated-h7
    kernel: device candidates are exact-verified on the host and the sweep
    resumes past false positives, so the result is bit-identical to the
    generic path while doing ~12% fewer vector ops per nonce.
    """
    assert len(header80) == 80
    midstate = jnp.asarray(np.array(header_midstate(header80), dtype=np.uint32))
    tail_np = bytes_to_words_np(np.frombuffer(header80[64:76], dtype=np.uint8))
    tail = jnp.asarray(tail_np)
    t7 = jnp.uint32(target_to_limbs_np(target)[7])

    hashes = 0
    nonce = start_nonce & 0xFFFFFFFF
    remaining = max_nonces
    while remaining > 0:
        n_tiles = min((remaining + tile - 1) // tile, (1 << 32) // tile)
        found, cand, tiles = sweep_fast_jit(
            midstate, tail, t7, jnp.uint32(nonce), jnp.uint32(n_tiles), tile=tile
        )
        done = int(tiles) * tile
        hashes += done
        if not bool(found):
            return None, hashes
        cand = int(cand)
        # exact host check of the candidate (scalar oracle)
        hdr = header80[:76] + int(cand).to_bytes(4, "little")
        if int.from_bytes(sha256d(hdr), "little") <= target:
            return cand, hashes
        # false positive (limb7 tie): resume just past it. The tiles the
        # device already swept before the candidate stay counted; the
        # candidate's own tile is partially re-swept, which is harmless.
        consumed = (cand - nonce) & 0xFFFFFFFF
        remaining -= consumed + 1
        nonce = (cand + 1) & 0xFFFFFFFF
    return None, hashes
