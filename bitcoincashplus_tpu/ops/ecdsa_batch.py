"""ECDSA batch dispatch — the host side of the TPU signature graft.

Reference: this layer replaces CCheckQueue (src/checkqueue.h:~30) +
ThreadScriptCheck (src/validation.cpp): instead of fanning CScriptCheck
closures to worker threads, the block's deferred sigcheck records are
packed SoA (scalar decomposition on host, bit-planes + 13-bit limbs) and
verified in ONE device dispatch via ops/secp256k1.ecdsa_verify_batch_jit
(SURVEY.md §3.2 P1, §8.4 "ECDSA batch").

Pipeline per batch:
  1. host: w = s⁻¹ mod n, u1 = e·w, u2 = r·w  (native C++/Python ints,
     µs per sig). The GLV lattice split (k = k1 + λ·k2, |k1|,|k2| <
     2^128) rides the DEVICE program since ISSUE 11 (_glv_dev_program —
     raw scalar bytes in, exact in-kernel rounding); the host split
     (pack_records_glv, numpy limb batches) is the retained fallback
  2. pack: u1/u2 → (256, B) MSB-first bit planes (ladder kernels), raw
     (B, 32) byte matrices (w4 bytes AND device-decompose GLV), or
     split-scalar byte matrices + sign flags (host-decompose GLV);
     qx/qy/r/rn → (20, B) 13-bit limbs or bytes; wrap_ok = (r + n < p)
     per lane (the kernel gates the x-wraparound candidate on it — see
     ecdsa_verify_batch_device)
  3. pad B up to a bucket size (bounds XLA recompiles to len(BUCKETS))
  4. one jit dispatch; padded lanes are poisoned (q_inf) and ignored
  5. device returns a (B,) validity mask; caller attributes failures

CPU fallback (``backend="cpu"`` or batches below the dispatch floor) runs
the Python-int oracle — the reference's single-threaded VerifyScript path.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..crypto import secp256k1 as oracle
from ..util import devicewatch as dw
from ..util import telemetry as tm
from ..util.faults import INJECTOR, Backoff, PoisonedOutput
from ..util.log import log_printf
from . import dispatch

# -- telemetry families (util/telemetry): per-stage host-pack latency,
# device settle-wait distribution, dispatch/flush lane-size histograms,
# and the lane-fill / in-flight gauges. STATS itself is projected onto
# the registry by the collector below, so getmetrics' /metrics namespace
# and gettpuinfo's `batch` section read the same counters.
_STAGE_H = tm.histogram(
    "bcp_ecdsa_stage_seconds",
    "Host pack-stage latency per dispatch (decompose = GLV lattice split, "
    "pack = byte-matrix emit)", labels=("stage",),
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0))
_SETTLE_H = tm.histogram(
    "bcp_ecdsa_settle_wait_seconds",
    "Blocking wait at BatchHandle.result() — near zero when the pipeline "
    "hid the device latency")
_LANES_H = tm.histogram(
    "bcp_ecdsa_dispatch_lanes",
    "Real (unpadded) lanes per device dispatch",
    buckets=(32, 128, 512, 1024, 2048, 4096, 8192, 16384, 32768))
_PACKER_FLUSH_H = tm.histogram(
    "bcp_packer_flush_lanes",
    "Lanes per cross-block LanePacker bucket flush",
    buckets=(32, 128, 512, 1024, 2046, 4096, 8190, 16384))
_LANE_FILL_G = tm.gauge(
    "bcp_packer_lane_fill_pct",
    "Cumulative real-lane fill of padded device buckets (percent)")
_IN_FLIGHT_G = tm.gauge(
    "bcp_ecdsa_in_flight",
    "Device verify dispatches currently in flight")


def _collect_ecdsa_stats():
    """Registry collector: every numeric BatchStats field as
    bcp_ecdsa_<field>, plus per-bucket dispatch counts. in_flight is
    excluded — the native _IN_FLIGHT_G gauge already owns that name, and
    a collector re-emitting it would duplicate the family with a
    conflicting TYPE in the Prometheus exposition."""
    snap = STATS.snapshot()
    snap.pop("in_flight", None)
    buckets = snap.pop("buckets_used", {})
    out = tm.flat_families("bcp_ecdsa", snap, typ="counter",
                           help="ops/ecdsa_batch.STATS")
    if buckets:
        out.append({
            "name": "bcp_ecdsa_bucket_dispatches_total", "type": "counter",
            "help": "Device dispatches per padded bucket size",
            "samples": [({"bucket": str(b)}, n)
                        for b, n in sorted(buckets.items())],
        })
    return out


tm.register_collector("ecdsa_stats", _collect_ecdsa_stats)

# Pad-to-bucket sizes (SURVEY.md §8.4 dispatch layer). One compiled
# executable per bucket; persistent across blocks via jit cache.
BUCKETS = (32, 128, 512, 2048, 8192, 16384, 32768)
# Below this lane count a device round-trip costs more than host verify.
CPU_FLOOR = 8

# ---- device-lane watches (util/devicewatch) --------------------------------
# The bucket design's WHOLE POINT is a bounded compiled-shape set; these
# declared budgets turn that invariant into a runtime check (a dispatch
# that mints a shape beyond its program's budget fires
# bcp_xla_retrace_unexpected_total + a log warning + a trace instant).
# The byte-pipeline ladder is {1024, 2048, 4096} then 2048-granular to
# 16384 = 9 shapes (_bucket_for pallas=True; >16384 splits per program
# call, so no extra shapes); the plane/ladder programs pad to BUCKETS.
PALLAS_SHAPE_BUDGET = 9
_PW_GLV = dw.program("ecdsa_glv", shape_budget=PALLAS_SHAPE_BUDGET)
# the fused decompose+verify program (ISSUE 11): same bucket ladder as
# the other byte pipelines, so the same 9-shape budget applies
_PW_GLV_DEV = dw.program("ecdsa_glv_decompose",
                         shape_budget=PALLAS_SHAPE_BUDGET)
_PW_W4_BYTES = dw.program("ecdsa_w4_bytes", shape_budget=PALLAS_SHAPE_BUDGET)
_PW_W4 = dw.program("ecdsa_w4", shape_budget=len(BUCKETS))
_PW_XLA = dw.program("ecdsa_xla", shape_budget=len(BUCKETS))
# Pippenger MSM batch-verification program (ISSUE 19): term counts pad to
# the _MSM_BUCKETS ladder, and the canary batches reuse the smallest
# bucket, so the compiled-shape set is exactly that ladder.
_MSM_BUCKETS = (64, 256, 1024, 4096, 8192, 16384)
MSM_SHAPE_BUDGET = len(_MSM_BUCKETS)
_PW_MSM = dw.program("ecdsa_msm", shape_budget=MSM_SHAPE_BUDGET)
# A batch of n Schnorr sigs costs M = 2n+1 MSM terms (R_i, P_i, and the
# shared G term); the cap keeps M inside the largest bucket — bigger
# submissions chunk (the MSM sum cannot ride the ladder kernels' 16384-
# lane program splitting, each chunk is an independent batch equation).
MSM_MAX_RECORDS = 8190
# Below this the bisection hands lanes straight to the per-lane oracle —
# a device round trip per 8 sigs costs more than 8 scalar verifies.
MSM_MIN_BATCH = 8


def _watched_kernel(pw, bucket: int, arrays, fn, jitfn=None, kwargs=None,
                    split: int | None = 16384):
    """One watched kernel call: the program watch sees the compiled-shape
    signature and attributes compile time; h2d staging bytes and the
    execute phase land in the transfer/phase accounting. ``arrays`` are
    the packed host-side numpy inputs (their nbytes IS the staging
    payload); ``jitfn`` enables first-compile cost-analysis capture.

    ``split`` is the wrapper's per-program-call cap: the glv / w4-bytes
    entry points slice batches beyond 16384 lanes into 16384-lane
    program calls, so the COMPILED shape — the signature the retrace
    sentinel must see — is min(bucket, split), never the raw bucket (an
    unclamped 32768 would read as a fresh shape and fire a false
    invariant alarm). Pass split=None for programs that do not slice
    (the XLA ladder compiles the padded bucket as-is)."""
    dw.note_transfer("ecdsa", "h2d",
                     sum(int(a.nbytes) for a in arrays))
    sig = bucket if split is None else min(bucket, split)
    t0 = time.monotonic()
    with pw.dispatch(sig, jitfn=jitfn, args=arrays, kwargs=kwargs):
        out = fn()
    dw.note_phase("ecdsa", "execute", time.monotonic() - t0)
    return out

# ---- kernel selection (-ecdsakernel=glv|w4|msm) ----------------------------
# "glv": the λ-endomorphism split verifier (ops/secp256k1 GLV core — 32
# windows / 128 doublings over four addition streams + the fixed-base G
# comb). "w4": the previous-generation 64-window kernel, kept in-tree as
# the differential oracle and the breaker/dispatch fallback. The GLV path
# degrades w4 -> XLA ladder -> CPU on failure; selection is validated at
# node startup (node.py rejects unknown values before the first batch).
# "msm": the Pippenger batch-verification rung (ISSUE 19) — it applies to
# SCHNORR records only (the batch equation needs Schnorr's linear verify
# relation); ECDSA records under -ecdsakernel=msm ride the GLV ladder,
# and a failed/rejected MSM batch bisects down to the per-lane oracle.
ECDSA_KERNELS = ("glv", "w4", "msm")
# Fault-injection site for the GLV leg specifically (explicit opt-in only,
# like util/faults' "net" site: BCP_FAULT_OPS=all keeps meaning the four
# accelerator subsystems, so existing dead-backend drills are unchanged).
# fail-* modes prove the glv -> w4 dispatch fallback; poison-output proves
# the KAT gate catches a lying GLV mask and settles on the CPU engine.
GLV_SITE = "ecdsa_glv"
# Device-decompose leg of the GLV path (ISSUE 11), likewise explicit-only:
# fail-* proves the device-decompose -> host-decompose fallback (the
# degradation ladder's first rung); poison-output proves the KAT gate.
# GLV_SITE stays armed across the WHOLE GLV family (both legs consult
# it), so the pre-existing glv -> w4 drills keep their meaning.
GLV_DEV_SITE = "ecdsa_glv_dev"
# MSM batch-verification site (ISSUE 19), explicit-only like the GLV
# legs: fail-* proves the msm -> per-lane fallback rung (a dead MSM
# program degrades to the scalar oracle, never drops verdicts),
# poison-output proves the canary gate catches a lying batch verdict
# (the per-lane KAT gate cannot ride a ONE-bit batch result, so the MSM
# path carries its own known-answer batches — see _msm_verify_records).
MSM_SITE = "ecdsa_msm"
_KERNEL = None  # set_kernel() override; None = BCP_ECDSA_KERNEL or "glv"
_BAD_ENV_WARNED = False


def active_kernel() -> str:
    """The kernel the next device dispatch will try first. An invalid
    BCP_ECDSA_KERNEL value falls back to the default with a one-time
    warning (this runs on the dispatch hot path, so it must not raise —
    the -ecdsakernel flag is the validated front door)."""
    global _BAD_ENV_WARNED
    if _KERNEL is not None:
        return _KERNEL
    env = os.environ.get("BCP_ECDSA_KERNEL", "glv")
    if env in ECDSA_KERNELS:
        return env
    if not _BAD_ENV_WARNED:
        _BAD_ENV_WARNED = True
        log_printf("BCP_ECDSA_KERNEL=%r is not one of %s — using 'glv'",
                   env, "/".join(ECDSA_KERNELS))
    return "glv"


def set_kernel(name: str) -> str:
    """Select the device verify kernel; raises ValueError on unknown names
    (node startup turns that into a ConfigError — reject at init, not at
    the first batch)."""
    global _KERNEL
    if name not in ECDSA_KERNELS:
        raise ValueError(
            f"-ecdsakernel={name!r}: unknown kernel "
            f"(valid: {', '.join(ECDSA_KERNELS)})"
        )
    _KERNEL = name
    return name


def kernel_info() -> dict:
    """gettpuinfo's ``ecdsa`` section: the active kernel, GLV health, the
    one-time fixed-base-table build cost, and the pack-stage split —
    decompose (host lattice split; ~0 while the device-decompose leg is
    healthy), emit (numpy byte emission) and dispatch (host-side program
    enqueue) reported SEPARATELY since ISSUE 11 (decompose_s/pack_s keep
    their PR-8 meanings, so the section stays a key-for-key superset)."""
    from . import secp256k1 as dev_mod

    return {
        "kernel": active_kernel(),
        "kernels": list(ECDSA_KERNELS),
        "glv_broken": _GLV_BROKEN,
        "glv_dispatches": STATS.glv_dispatches,
        "glv_fallbacks": STATS.glv_fallbacks,
        "table_build_s": round(dev_mod.GLV_TABLE_BUILD_S, 4),
        "decompose_s": round(STATS.glv_decompose_s, 4),
        "pack_s": round(STATS.glv_pack_s, 4),
        "emit_s": round(STATS.glv_emit_s, 4),
        "dispatch_s": round(STATS.glv_dispatch_s, 4),
        "dev_decompose": {
            "enabled": glv_dev_enabled(),
            "broken": _GLV_DEV_BROKEN,
            "dispatches": STATS.glv_dev_dispatches,
            "fallbacks": STATS.glv_dev_fallbacks,
        },
        "msm": {
            "schnorr_sigs": STATS.schnorr_sigs,
            "schnorr_cpu_sigs": STATS.schnorr_cpu_sigs,
            "dispatches": STATS.msm_dispatches,
            "batches_accepted": STATS.msm_batches_accepted,
            "batches_rejected": STATS.msm_batches_rejected,
            "bisects": STATS.msm_bisects,
            "bisect_depth_max": STATS.msm_bisect_depth_max,
            "fallback_sigs": STATS.msm_fallback_sigs,
            "canary_failures": STATS.msm_canary_failures,
        },
    }


@dataclass
class BatchStats:
    """Per-dispatch metrics surfaced via gettpuinfo (SURVEY.md §6.5)."""

    dispatches: int = 0
    sigs_verified: int = 0
    sigs_padded: int = 0
    cpu_fallback_sigs: int = 0
    # sigchecks that never reach the batch at all (gettpuinfo honesty:
    # what fraction of a block's sigops actually ran on the chip):
    eager_multisig_sigs: int = 0   # CHECKMULTISIG trials, verified inline
    inline_legacy_sigs: int = 0    # pre-NULLFAIL blocks, deferral unsound
    sigcache_hits: int = 0         # records dropped by the sigcache probe
    p2pkh_fast_path: int = 0       # inputs that skipped the generic EvalScript
    device_seconds: float = 0.0
    last_batch: int = 0
    # P3 pipeline overlap: dispatches currently in flight / high-water mark
    in_flight: int = 0
    max_in_flight: int = 0
    pallas_fallbacks: int = 0  # Mosaic compile failures -> XLA kernel
    # w4/glv kernel lanes flagged degenerate (adversarially-crafted H == 0
    # collisions) and re-verified on the CPU path — see ops/secp256k1.py
    degenerate_rechecks: int = 0
    # GLV kernel accounting: dispatches that ran the GLV program, GLV-leg
    # failures that degraded to the w4 kernel, and the host-side pack
    # stage split (lattice decomposition vs byte packing) for the
    # per-stage bench timings (gettpuinfo `ecdsa` section)
    glv_dispatches: int = 0
    glv_fallbacks: int = 0
    glv_decompose_s: float = 0.0
    glv_pack_s: float = 0.0
    # device-decompose leg (ISSUE 11): dispatches that ran the fused
    # decompose+verify program, failures that degraded to the host
    # lattice split, and the decompose/emit/dispatch stage separation
    # (decompose_s above stays HOST decompose only — ~0 when the device
    # leg is healthy; emit_s is the numpy byte emission across BOTH GLV
    # legs; dispatch_s is the host-side enqueue of the glv programs)
    glv_dev_dispatches: int = 0
    glv_dev_fallbacks: int = 0
    glv_emit_s: float = 0.0
    glv_dispatch_s: float = 0.0
    # supervised-dispatch accounting (ops/dispatch breaker layer): sigs
    # re-verified on the CPU engine because the device path failed or its
    # known-answer lanes came back wrong. NOTE sigs_padded includes the 2
    # KAT lanes riding every device batch.
    fault_fallback_sigs: int = 0
    kat_failures: int = 0
    # device-False lanes host-confirmed before they could reject a block
    # (reject-side verdicts are never the device's alone to make)
    reject_confirm_sigs: int = 0
    # Schnorr + MSM batch verification (ISSUE 19): schnorr_sigs counts
    # every Schnorr record entering dispatch; schnorr_cpu_sigs the ones
    # settled by the per-lane oracle (no MSM, bisect base cases, and
    # fallback re-verifies). msm_dispatches counts MSM PROGRAM calls
    # (canary batches included); a rejected batch bisects (msm_bisects,
    # with the deepest split level in msm_bisect_depth_max — O(log N)
    # per forged sig). msm_fallback_sigs are lanes that abandoned the
    # MSM rung entirely (dead program after retries -> per-lane oracle);
    # msm_canary_failures are canary-gate trips (also kat_failures).
    schnorr_sigs: int = 0
    schnorr_cpu_sigs: int = 0
    msm_dispatches: int = 0
    msm_batches_accepted: int = 0
    msm_batches_rejected: int = 0
    msm_bisects: int = 0
    msm_bisect_depth_max: int = 0
    msm_fallback_sigs: int = 0
    msm_canary_failures: int = 0
    buckets_used: dict = field(default_factory=dict)

    def snapshot(self) -> dict:
        d = self.__dict__.copy()
        d["buckets_used"] = dict(self.buckets_used)
        return d


STATS = BatchStats()


def _note_device_dispatch(n: int, bucket: int) -> None:
    """Shared per-dispatch STATS bookkeeping (record-level and packed
    entry points must never diverge on the gettpuinfo counters)."""
    STATS.dispatches += 1
    STATS.sigs_verified += n
    STATS.sigs_padded += bucket - n
    STATS.last_batch = n
    STATS.buckets_used[bucket] = STATS.buckets_used.get(bucket, 0) + 1
    STATS.in_flight += 1
    STATS.max_in_flight = max(STATS.max_in_flight, STATS.in_flight)
    _LANES_H.observe(n)
    _IN_FLIGHT_G.set(STATS.in_flight)


def _bucket_for(n: int, pallas: bool = False) -> int:
    if pallas and n > 128:
        # w4-bytes program buckets: {1024, 2048, 4096} then 2048-granular
        # up to 16384, then 16384-granular (the program splits at 16384
        # per call) — the jit bakes B into shapes and grid, so bucket
        # sizes ARE compiled-program shapes and must stay a small bounded
        # set (a fresh Mosaic compile is ~1-2 min on a tunneled chip; at
        # most 9 shapes exist, and only the ones actually hit compile).
        # 2048-granularity bounds worst-case padding waste at ~33%
        # (n=4097 -> 6144) and ~20% at the 10k scale — a pure pow2 ladder
        # padded the bench's 10k batch to 16384 (39% wasted grid steps).
        # Batches <= 128 lanes use the 2D kernel's small buckets.
        if n <= 1024:
            return 1024
        if n <= 4096:
            return 2048 if n <= 2048 else 4096
        if n <= 16384:
            return ((n + 2047) // 2048) * 2048
        return ((n + 16383) // 16384) * 16384
    for b in BUCKETS:
        if n <= b:
            return b
    return ((n + BUCKETS[-1] - 1) // BUCKETS[-1]) * BUCKETS[-1]


def decompose_scalars(records: Sequence) -> list[tuple[int, int]]:
    """Step 1: (u1, u2) per record. Records carry (r, s, msg_hash)."""
    out = []
    for rec in records:
        w = pow(rec.s, oracle.N - 2, oracle.N)
        out.append((rec.msg_hash * w % oracle.N, rec.r * w % oracle.N))
    return out


def _scalar_bitplanes(records: Sequence, n: int) -> tuple:
    """(u1, u2) for all records as (n, 32) big-endian byte matrices, ready
    for unpackbits. Native C++ when available (bcp_ecdsa_precompute — the
    Python pow() loop was ~40% of host pack time at 10k sigs), else the
    Python-int path. Range-invalid records (never produced by the deferral
    layer, which pre-checks) come back flagged; callers poison those lanes."""
    from .. import native

    if native.available():
        u1_blob, u2_blob, ok = native.ecdsa_precompute(records)
        u1 = np.frombuffer(u1_blob, np.uint8).reshape(n, 32)
        u2 = np.frombuffer(u2_blob, np.uint8).reshape(n, 32)
        return u1, u2, ok
    scalars = decompose_scalars(records)
    u1 = np.frombuffer(
        b"".join(u1.to_bytes(32, "big") for u1, _ in scalars), np.uint8
    ).reshape(n, 32)
    u2 = np.frombuffer(
        b"".join(u2.to_bytes(32, "big") for _, u2 in scalars), np.uint8
    ).reshape(n, 32)
    return u1, u2, None


_LIMB_WEIGHTS = (1 << np.arange(13)).astype(np.uint32)


def _limb_cols(blob: bytes, n: int, bucket: int) -> np.ndarray:
    """n concatenated 32-byte big-endian values -> (20, bucket) 13-bit limb
    columns (padding lanes zero). Fully vectorized — the per-record
    to_limbs_np loop was ~60% of host pack time at 10k sigs."""
    from . import secp256k1 as dev

    mat = np.frombuffer(blob, np.uint8).reshape(n, 32)
    bits = np.unpackbits(mat, axis=1)[:, ::-1]  # LSB-first bit order
    bits = np.concatenate(
        [bits, np.zeros((n, 13 * dev.N_LIMBS - 256), np.uint8)], axis=1
    )
    limbs = (
        bits.reshape(n, dev.N_LIMBS, 13).astype(np.uint32) * _LIMB_WEIGHTS
    ).sum(axis=2)
    out = np.zeros((dev.N_LIMBS, bucket), np.uint32)
    out[:, :n] = limbs.T
    return out


def _pack_limbs(records: Sequence, bucket: int):
    """Shared SoA limb packing: pubkey/r limbs + poison masks, padded to
    ``bucket`` lanes. Padded lanes get q_inf=True (poisoned: kernel reports
    False) and are masked out by the caller — they can never turn a bad
    batch good or a good batch bad. Returns the (n, 32) u1/u2 scalar byte
    matrices alongside (the caller picks bit planes or window planes)."""
    n = len(records)
    u1_bytes, u2_bytes, range_ok = _scalar_bitplanes(records, n)
    wraps = [rec.r + oracle.N < oracle.P for rec in records]
    qx = _limb_cols(
        b"".join(rec.pubkey[0].to_bytes(32, "big") for rec in records),
        n, bucket)
    qy = _limb_cols(
        b"".join(rec.pubkey[1].to_bytes(32, "big") for rec in records),
        n, bucket)
    r0 = _limb_cols(
        b"".join(rec.r.to_bytes(32, "big") for rec in records), n, bucket)
    rn = _limb_cols(
        b"".join(
            (rec.r + oracle.N if w else rec.r).to_bytes(32, "big")
            for rec, w in zip(records, wraps)
        ), n, bucket)
    q_inf = np.ones(bucket, bool)  # default poisoned (padding)
    wrap_ok = np.zeros(bucket, bool)
    wrap_ok[:n] = wraps
    # real lanes un-poisoned, except any the precompute range-flagged
    q_inf[:n] = False if range_ok is None else ~np.asarray(range_ok, bool)
    return u1_bytes, u2_bytes, qx, qy, q_inf, r0, rn, wrap_ok


def pack_records(records: Sequence, bucket: int):
    """Step 2+3 for the bit-ladder kernels: SoA arrays padded to ``bucket``
    lanes with (256, B) MSB-first bit planes. unpackbits on the 32-byte
    big-endian scalars — vectorized, not a 256·B Python loop (host packing
    must stay negligible next to the device dispatch)."""
    n = len(records)
    u1_bytes, u2_bytes, qx, qy, q_inf, r0, rn, wrap_ok = _pack_limbs(
        records, bucket
    )
    u1b = np.zeros((256, bucket), np.uint32)
    u2b = np.zeros((256, bucket), np.uint32)
    u1b[:, :n] = np.unpackbits(u1_bytes, axis=1).T
    u2b[:, :n] = np.unpackbits(u2_bytes, axis=1).T
    return u1b, u2b, qx, qy, q_inf, r0, rn, wrap_ok


def pack_records_w4(records: Sequence, bucket: int):
    """pack_records for the w=4 windowed Pallas kernel: (64, B) 4-bit
    window planes instead of bit planes."""
    from . import secp256k1 as dev

    u1_bytes, u2_bytes, qx, qy, q_inf, r0, rn, wrap_ok = _pack_limbs(
        records, bucket
    )
    u1w = dev.bits_to_windows_np(u1_bytes, bucket)
    u2w = dev.bits_to_windows_np(u2_bytes, bucket)
    return u1w, u2w, qx, qy, q_inf, r0, rn, wrap_ok


def pack_records_w4_bytes(records: Sequence, bucket: int):
    """Byte-matrix pack for the single-dispatch w4 pipeline: every 256-bit
    field as a (bucket, 32) big-endian uint8 matrix (window/limb expansion
    happens ON DEVICE — ops/secp256k1._w4_bytes_program), masks as uint8
    vectors. ~5x less host->device traffic than the expanded planes."""
    n = len(records)
    u1_bytes, u2_bytes, range_ok = _scalar_bitplanes(records, n)
    wraps = [rec.r + oracle.N < oracle.P for rec in records]

    def mat(blob: bytes) -> np.ndarray:
        out = np.zeros((bucket, 32), np.uint8)
        out[:n] = np.frombuffer(blob, np.uint8).reshape(n, 32)
        return out

    u1m = np.zeros((bucket, 32), np.uint8)
    u1m[:n] = u1_bytes
    u2m = np.zeros((bucket, 32), np.uint8)
    u2m[:n] = u2_bytes
    qxb = mat(b"".join(rec.pubkey[0].to_bytes(32, "big") for rec in records))
    qyb = mat(b"".join(rec.pubkey[1].to_bytes(32, "big") for rec in records))
    r0b = mat(b"".join(rec.r.to_bytes(32, "big") for rec in records))
    rnb = mat(b"".join(
        (rec.r + oracle.N if w else rec.r).to_bytes(32, "big")
        for rec, w in zip(records, wraps)
    ))
    q_inf = np.ones(bucket, np.uint8)
    q_inf[:n] = 0 if range_ok is None else \
        (~np.asarray(range_ok, bool)).astype(np.uint8)
    wrap8 = np.zeros(bucket, np.uint8)
    wrap8[:n] = np.asarray(wraps, np.uint8)
    return u1m, u2m, qxb, qyb, q_inf, r0b, rnb, wrap8


def _glv_pack_parts(u1_bytes, u2_bytes, qx_bytes, qy_bytes, r_bytes,
                    rn_bytes, wraps, range_bad, bucket: int):
    """Shared HOST-decompose GLV pack (the device-decompose leg's
    fallback): lattice-decompose the (u1, u2) scalars with the numpy
    limb-batch split (ops/secp256k1.glv_split_batch_np — vectorized
    since ISSUE 11; the per-record Python-bigint loop it replaced was
    the BENCH_r08 host_share 0.56 leg) and emit the GLV program's byte
    matrices. u1/u2/qx/qy/r/rn: (n, 32) uint8 big-endian. range_bad:
    (n,) bool poison mask or None. Decompose and emit stages are timed
    into STATS for the bench's per-stage split."""
    from . import secp256k1 as dev

    n = len(qy_bytes)
    t0 = time.monotonic()
    if n:
        a1m, na1, a2m, na2 = dev.glv_decompose_batch_np(u1_bytes)
        b1m, nb1, b2m, nb2 = dev.glv_decompose_batch_np(u2_bytes)
    dt = time.monotonic() - t0
    STATS.glv_decompose_s += dt
    _STAGE_H.labels(stage="decompose").observe(dt)
    dw.note_phase("ecdsa", "decompose", dt)

    t0 = time.monotonic()
    d1m = np.zeros((bucket, 16), np.uint8)
    d2m = np.zeros((bucket, 16), np.uint8)
    s1m = np.zeros((bucket, 16), np.uint8)
    s2m = np.zeros((bucket, 16), np.uint8)
    sg1 = np.zeros(bucket, np.uint8)
    sg2 = np.zeros(bucket, np.uint8)
    ydiff = np.zeros(bucket, np.uint8)
    qyb = np.zeros((bucket, 32), np.uint8)
    if n:
        # comb digits little-endian (position i = weight 256^i); ladder
        # scalars big-endian (MSB-first nibble windows on device)
        d1m[:n] = a1m
        d2m[:n] = a2m
        s1m[:n] = b1m[:, ::-1]
        s2m[:n] = b2m[:, ::-1]
        sg1[:n] = na1
        sg2[:n] = na2
        ydiff[:n] = nb1 ^ nb2
        # first Q-stream sign folds into qy (device never negates Q)
        fold = nb1.astype(bool)
        qyb[:n] = qy_bytes
        if fold.any():
            qyb[:n][fold] = dev.field_neg_bytes_np(qy_bytes[fold])

    def pad(mat: np.ndarray) -> np.ndarray:
        out = np.zeros((bucket, 32), np.uint8)
        out[:n] = mat
        return out

    q_inf = np.ones(bucket, np.uint8)
    q_inf[:n] = (np.asarray(range_bad, bool).astype(np.uint8)
                 if range_bad is not None else 0)
    wrap8 = np.zeros(bucket, np.uint8)
    wrap8[:n] = np.asarray(wraps, np.uint8)
    out = (d1m, d2m, sg1, sg2, s1m, s2m, ydiff, pad(qx_bytes), qyb,
           q_inf, pad(r_bytes), pad(rn_bytes), wrap8)
    dt = time.monotonic() - t0
    STATS.glv_pack_s += dt
    STATS.glv_emit_s += dt
    _STAGE_H.labels(stage="pack").observe(dt)
    dw.note_phase("ecdsa", "pack", dt)
    return out


def pack_records_glv(records: Sequence, bucket: int):
    """pack_records for the GLV kernel: split scalars + signs (the packer
    emits the λ-decomposition; LanePacker buckets are unchanged). Padded
    lanes are poisoned exactly like the w4 packers."""
    n = len(records)
    u1_bytes, u2_bytes, range_ok = _scalar_bitplanes(records, n)
    wraps = [rec.r + oracle.N < oracle.P for rec in records]
    qx_bytes = np.frombuffer(
        b"".join(rec.pubkey[0].to_bytes(32, "big") for rec in records),
        np.uint8).reshape(n, 32) if n else np.zeros((0, 32), np.uint8)
    r_bytes = np.frombuffer(
        b"".join(rec.r.to_bytes(32, "big") for rec in records),
        np.uint8).reshape(n, 32) if n else np.zeros((0, 32), np.uint8)
    rn_bytes = np.frombuffer(
        b"".join((rec.r + oracle.N if w else rec.r).to_bytes(32, "big")
                 for rec, w in zip(records, wraps)),
        np.uint8).reshape(n, 32) if n else np.zeros((0, 32), np.uint8)
    qy_bytes = np.frombuffer(
        b"".join(rec.pubkey[1].to_bytes(32, "big") for rec in records),
        np.uint8).reshape(n, 32) if n else np.zeros((0, 32), np.uint8)
    range_bad = None if range_ok is None else ~np.asarray(range_ok, bool)
    return _glv_pack_parts(
        u1_bytes, u2_bytes, qx_bytes, qy_bytes, r_bytes, rn_bytes, wraps,
        range_bad, bucket,
    )


def _verify_cpu_ecdsa(records: Sequence) -> np.ndarray:
    """ECDSA CPU lane: the native C++ scalar module (threaded via -par)
    when available, else the Python-int oracle. Differential parity is
    covered by tests/unit/test_native.py."""
    from .. import native

    if native.available():
        return np.array(native.ecdsa_verify_batch(records), dtype=bool)
    return np.array(
        [
            oracle.ecdsa_verify(rec.pubkey, rec.r, rec.s, rec.msg_hash)
            for rec in records
        ],
        dtype=bool,
    )


def _schnorr_oracle(records: Sequence) -> np.ndarray:
    """Per-lane Schnorr verify on the Python-int oracle — the accept/
    reject reference every MSM verdict must match byte-identically (and
    the reject-side engine the bisection funnels into)."""
    STATS.schnorr_cpu_sigs += len(records)
    return np.array(
        [
            oracle.schnorr_verify(rec.pubkey, rec.r, rec.s, rec.msg_hash)
            for rec in records
        ],
        dtype=bool,
    )


def _verify_cpu(records: Sequence) -> np.ndarray:
    """CPU lane, algorithm-aware: ECDSA records take the native/oracle
    scalar path, Schnorr records the Schnorr oracle. Mixed batches are
    partitioned and re-merged in submission order (the deferral layer
    tags every SigCheckRecord with ``algo``; blob-path _LazyRecords and
    legacy callers without the field default to ECDSA)."""
    algos = [getattr(rec, "algo", "ecdsa") for rec in records]
    if "schnorr" not in algos:
        return _verify_cpu_ecdsa(records)
    out = np.zeros(len(records), bool)
    ecd = [i for i, a in enumerate(algos) if a != "schnorr"]
    sch = [i for i, a in enumerate(algos) if a == "schnorr"]
    if ecd:
        out[ecd] = _verify_cpu_ecdsa([records[i] for i in ecd])
    out[sch] = _schnorr_oracle([records[i] for i in sch])
    return out


_KAT = None


def _kat_records() -> tuple:
    """Known-answer probe lanes appended to every device batch: one
    signature that MUST verify and one that MUST NOT (same sig, different
    message). A device that inverts, zeroes, or fabricates the validity
    mask gets both polarities wrong-side and the batch is discarded before
    any verdict can see it (BatchHandle.result's KAT gate). Generated once
    from the Python-int oracle."""
    global _KAT
    if _KAT is None:
        import hashlib

        from ..script.interpreter import SigCheckRecord

        d = 0x1D3F2A9C5B7E6D4F8A1B2C3D4E5F60718293A4B5C6D7E8F9
        e = int.from_bytes(
            hashlib.sha256(b"bcp-supervised-dispatch-kat").digest(), "big"
        ) % oracle.N
        r, s = oracle.ecdsa_sign(d, e)
        pub = oracle.point_mul(d, oracle.G)
        good = SigCheckRecord(pub, r, s, e)
        bad = SigCheckRecord(pub, r, s, (e + 1) % oracle.N)
        _KAT = (good, bad)
    return _KAT


# ---- Schnorr MSM batch verification (ISSUE 19) -----------------------------
#
# The device kernel (ops/secp256k1._msm_program) answers ONE bit per
# batch: does Σ a_i·R_i + Σ (a_i·e_i)·P_i + ((n − Σ a_i·s_i) mod n)·G
# land on the point at infinity. Trust architecture around that bit:
#
#   accept side — a CANARY gate per verify session: the program must
#     accept a known-good batch AND reject that batch with a known-bad
#     sig appended, before any real verdict is trusted (the per-lane KAT
#     gate can't ride a one-bit result). With the canary green, a false
#     accept requires the 2^-128 coefficient collision.
#   reject side — never the device's alone (repo invariant): a rejected
#     batch BISECTS with fresh coefficients per sub-batch; sub-batches at
#     or below MSM_MIN_BATCH settle on the per-lane Python oracle. One
#     forged signature therefore costs O(log N) sub-batch checks, and
#     every False the caller sees was produced by the oracle.
#   host prechecks — r/s range and the R = lift_x(r) existence test run
#     on the host and pre-reject without any device work. This cannot
#     diverge from the oracle: schnorr_verify accepts only if R'.x == r
#     for the computed finite R', which forces r³+7 to be a quadratic
#     residue — exactly the condition lift_x tests (and the oracle's
#     jacobi(R'.y) gate matches lift_x's root choice).

_SCHNORR_KAT = None


def _schnorr_kat_records() -> tuple:
    """Known-answer Schnorr records for the MSM canary batches: one
    signature that MUST verify and one that MUST NOT (same sig, shifted
    message). Generated once from the Python-int oracle."""
    global _SCHNORR_KAT
    if _SCHNORR_KAT is None:
        import hashlib

        from ..script.interpreter import SigCheckRecord

        d = 0x5A7D1C9E3B8F6A2D4C1E8B7F9A3D5C6E8F1A2B4D6C8E9F1B3A5C7E9D2B4F6A8C
        d %= oracle.N
        e = int.from_bytes(
            hashlib.sha256(b"bcp-msm-batch-kat").digest(), "big"
        ) % oracle.N
        r, s = oracle.schnorr_sign(d, e)
        pub = oracle.point_mul(d, oracle.G)
        good = SigCheckRecord(pub, r, s, e, algo="schnorr")
        bad = SigCheckRecord(pub, r, s, (e + 1) % oracle.N, algo="schnorr")
        _SCHNORR_KAT = (good, bad)
    return _SCHNORR_KAT


def _msm_rng() -> random.Random:
    """Coefficient RNG for one verify session. Security rests on the
    coefficients being unpredictable to whoever crafted the signatures;
    os.urandom seeds each session. BCP_MSM_SEED pins the stream for
    deterministic drills/benches (never set in production)."""
    seed = os.environ.get("BCP_MSM_SEED")
    if seed is not None:
        return random.Random(int(seed, 0))
    return random.Random(int.from_bytes(os.urandom(16), "big"))


def _schnorr_precheck(rec):
    """Host-side pre-reject + R lift: returns the affine R = lift_x(r)
    for a structurally admissible record, None where the oracle is
    guaranteed to reject (range violation, unliftable r, missing
    pubkey) — see the section comment for the oracle-consistency
    argument."""
    if rec.pubkey is None:
        return None
    if not (0 <= rec.r < oracle.P and 0 <= rec.s < oracle.N):
        return None
    return oracle.schnorr_lift_x(rec.r)


def _msm_bucket_for(m: int) -> int:
    for b in _MSM_BUCKETS:
        if m <= b:
            return b
    raise ValueError(f"MSM term count {m} exceeds the bucket ladder")


def _msm_pack(terms, bucket: int):
    """(x, y, scalar) Python-int terms -> the MSM program's byte
    matrices, padded to ``bucket`` with infinity-flagged zero-scalar
    lanes (contribute nothing by construction)."""
    m = len(terms)
    xm = np.zeros((bucket, 32), np.uint8)
    ym = np.zeros((bucket, 32), np.uint8)
    km = np.zeros((bucket, 32), np.uint8)
    inf8 = np.ones(bucket, np.uint8)
    xm[:m] = np.frombuffer(
        b"".join(x.to_bytes(32, "big") for x, _, _ in terms),
        np.uint8).reshape(m, 32)
    ym[:m] = np.frombuffer(
        b"".join(y.to_bytes(32, "big") for _, y, _ in terms),
        np.uint8).reshape(m, 32)
    km[:m] = np.frombuffer(
        b"".join(k.to_bytes(32, "big") for _, _, k in terms),
        np.uint8).reshape(m, 32)
    inf8[:m] = 0
    return xm, ym, inf8, km


def _msm_device_check(pairs, rng: random.Random) -> bool:
    """ONE batch-equation check on the device: ``pairs`` is a list of
    (record, lifted_R) with every record already through
    _schnorr_precheck. Draws FRESH random coefficients (bisection calls
    this per sub-batch — reusing coefficients across splits would let a
    crafted pair of forgeries cancel in one half). Returns the batch
    verdict."""
    from . import secp256k1 as dev

    INJECTOR.on_call(MSM_SITE)
    s_acc = 0
    terms = []
    for i, (rec, lift) in enumerate(pairs):
        # a_0 = 1 is safe (the adversary can't anticipate which sig lands
        # first in a *sub*-batch) and saves one 128-bit scalar ladder
        a = 1 if i == 0 else rng.getrandbits(128) | 1
        e = oracle.schnorr_challenge(rec.r, rec.pubkey, rec.msg_hash)
        s_acc = (s_acc + a * rec.s) % oracle.N
        terms.append((lift[0], lift[1], a))
        terms.append((rec.pubkey[0], rec.pubkey[1], (a * e) % oracle.N))
    terms.append((oracle.GX, oracle.GY, (oracle.N - s_acc) % oracle.N))
    bucket = _msm_bucket_for(len(terms))
    with dw.phase("ecdsa", "pack"):
        arrays = _msm_pack(terms, bucket)
    out = _watched_kernel(
        _PW_MSM, bucket, arrays,
        lambda: dev.schnorr_msm_is_infinity(*arrays),
        jitfn=dev._msm_program, split=None)
    STATS.msm_dispatches += 1
    ok = bool(np.asarray(out)[0])
    if INJECTOR.should_poison(MSM_SITE):
        ok = not ok
    return ok


def _msm_verify_records(records: Sequence) -> np.ndarray:
    """Verdicts for a pure-Schnorr batch via the MSM batch check +
    bisection. Byte-identical to the per-lane oracle: pre-rejected lanes
    are oracle-guaranteed False, rejected batches funnel to the oracle,
    and accepted batches are wrong only on a 2^-128 coefficient
    collision (with the canary proving the program can tell good from
    bad at all). Raises on device/canary failure — _dispatch_msm owns
    the retry/fallback supervision."""
    n = len(records)
    out = np.zeros(n, bool)
    lifts = [_schnorr_precheck(rec) for rec in records]
    live = [i for i in range(n) if lifts[i] is not None]
    if not live:
        return out
    rng = _msm_rng()

    # canary gate (see section comment): both polarities must be right
    # before any real verdict from this session is trusted
    kg, kb = _schnorr_kat_records()
    kgl = _schnorr_precheck(kg)
    kbl = _schnorr_precheck(kb)
    if (not _msm_device_check([(kg, kgl)], rng)
            or _msm_device_check([(kg, kgl), (kb, kbl)], rng)):
        STATS.msm_canary_failures += 1
        STATS.kat_failures += 1
        raise PoisonedOutput("ecdsa msm canary batches wrong")

    depth_max = 0

    def solve(idxs, depth: int) -> None:
        nonlocal depth_max
        depth_max = max(depth_max, depth)
        if len(idxs) <= MSM_MIN_BATCH:
            out[idxs] = _schnorr_oracle([records[i] for i in idxs])
            return
        if _msm_device_check([(records[i], lifts[i]) for i in idxs], rng):
            out[idxs] = True
            STATS.msm_batches_accepted += 1
            return
        STATS.msm_batches_rejected += 1
        STATS.msm_bisects += 1
        mid = len(idxs) // 2
        solve(idxs[:mid], depth + 1)
        solve(idxs[mid:], depth + 1)

    # chunk so M = 2n+1 stays inside the bucket ladder; each chunk is an
    # independent batch equation
    for s in range(0, len(live), MSM_MAX_RECORDS):
        solve(live[s:s + MSM_MAX_RECORDS], 0)
    STATS.msm_bisect_depth_max = max(STATS.msm_bisect_depth_max, depth_max)
    return out


def _dispatch_msm(records: Sequence, br) -> Optional[BatchHandle]:
    """Supervised MSM dispatch for a pure-Schnorr record list. EAGER
    (synchronous settle): the bisection ladder is verdict-driven, so
    there is nothing to pipeline — the returned handle already carries
    the final verdicts. Returns None when every attempt failed (caller
    owns the per-lane fallback). Mirrors _dispatch_device's supervision:
    breaker retries with backoff, programming errors re-raise, canary
    trips are PoisonedOutput and retried like any device fault."""
    boff = Backoff(base=br.cfg.backoff_base, maximum=1.0)
    last: Optional[BaseException] = None
    for attempt in range(br.cfg.retries + 1):
        try:
            INJECTOR.on_call("ecdsa")
            out = _msm_verify_records(records)
            br.record_success()
            return BatchHandle(len(records), cpu_ok=out)
        except (KeyboardInterrupt, SystemExit):
            raise
        except (NameError, AttributeError, UnboundLocalError):
            raise  # programming errors must not degrade silently
        except Exception as e:  # noqa: BLE001 — supervised boundary
            last = e
            if attempt < br.cfg.retries:
                time.sleep(boff.next())
    br.record_failure(last)
    br.note_fallback(len(records))
    STATS.msm_fallback_sigs += len(records)
    log_printf("schnorr msm dispatch failed (%s: %s) — per-lane oracle "
               "fallback for %d sig(s)", type(last).__name__,
               str(last)[:120], len(records))
    return None


def _dispatch_schnorr(records: Sequence, backend: str,
                      kernel: str | None) -> "BatchHandle":
    """Dispatch a pure-Schnorr record list: the MSM batch check when the
    msm kernel is selected and a device is worth dispatching to, else
    the per-lane oracle. The per-lane path IS the reference engine — no
    KAT/confirm layer needed."""
    n = len(records)
    STATS.schnorr_sigs += n
    kern = kernel if kernel in ECDSA_KERNELS else active_kernel()
    use_device = kern == "msm" and (
        backend == "device"
        or (backend == "auto" and n >= CPU_FLOOR and _device_available())
    )
    if use_device:
        br = dispatch.breaker("ecdsa")
        if br.allow():
            handle = _dispatch_msm(records, br)
            if handle is not None:
                return handle
            STATS.fault_fallback_sigs += n
        else:
            br.note_fallback(n)
            STATS.fault_fallback_sigs += n
    STATS.cpu_fallback_sigs += n
    return BatchHandle(n, cpu_ok=_schnorr_oracle(records))


class _MergedHandle:
    """Mixed ECDSA/Schnorr dispatch: per-algorithm sub-handles re-merged
    into submission order at settle. Result is memoized like
    BatchHandle; _bucket mirrors the widest sub-dispatch so LanePacker's
    fill metering keeps working through mixed batches."""

    __slots__ = ("_n", "_parts", "_result", "_bucket")

    def __init__(self, n: int, parts):
        self._n = n
        self._parts = parts  # [(handle, submission indices), ...]
        self._bucket = max(
            (getattr(h, "_bucket", 0) for h, _ in parts), default=0)
        self._result = None

    def result(self) -> np.ndarray:
        if self._result is None:
            out = np.zeros(self._n, bool)
            for handle, idxs in self._parts:
                out[idxs] = handle.result()
            self._result = out
            self._parts = ()
        return self._result


def _device_available() -> bool:
    """True when the JAX backend is worth dispatching to. An accelerator
    always is. When JAX is CPU-only, the XLA form of the verify kernel is
    ~20x slower than the native C++ batch (measured 250 vs 4600 sigs/s),
    so "auto" prefers the CPU lane — but only when the native library
    actually loaded; without it the CPU lane is the per-sig Python oracle
    (~10 sigs/s), and the XLA kernel is still the best option.
    backend="device" always forces the XLA path (virtual-mesh tests)."""
    if os.environ.get("BCP_NO_DEVICE"):
        return False
    try:
        from .sha256 import backend_is_cpu

        if backend_is_cpu():
            from .. import native

            if native.available():
                return False
        import jax

        return len(jax.devices()) > 0
    except Exception:
        return False


class BatchHandle:
    """An in-flight verify dispatch (P3 pipeline overlap, SURVEY.md §3.2).

    JAX dispatch is asynchronous: `dispatch_batch` returns immediately with
    the device computation enqueued, and the host keeps interpreting the
    next transactions' scripts while the chip verifies — the CCheckQueue
    master/worker overlap, with XLA's async runtime as the worker pool.
    `.result()` materializes (blocks) and finalizes stats.

    Supervision (ops/dispatch): device-path handles carry the records and
    the ecdsa breaker; a materialization error or a wrong known-answer
    lane at settle time counts a breaker failure and the verdict is a
    FRESH CPU re-verification of the real records — never a cached or
    fabricated mask."""

    __slots__ = ("_n", "_bucket", "_device_ok", "_cpu_ok", "_degen",
                 "_records", "_breaker", "_kat", "_recover", "_ctx")

    def __init__(self, n, bucket=0, device_ok=None, cpu_ok=None,
                 degen=None, records=None, breaker=None, kat=False,
                 recover=None, ctx=None):
        self._n = n
        self._bucket = bucket
        self._device_ok = device_ok
        self._cpu_ok = cpu_ok
        self._degen = degen
        self._records = records
        self._breaker = breaker
        self._kat = kat
        self._recover = recover  # fast whole-batch CPU verdict (packed)
        # enqueue-side trace context: the settle span (possibly another
        # thread, possibly many blocks later) links back to the span that
        # dispatched this batch
        self._ctx = ctx

    def _device_failed(self, err: BaseException) -> np.ndarray:
        """Settle-time device failure: breaker bookkeeping + CPU re-verify
        of the real lanes (the verdict that reaches the caller is computed
        by the reference engine, not recycled device output)."""
        if self._breaker is not None:
            self._breaker.record_failure(err)
            self._breaker.note_fallback(self._n)
        STATS.cpu_fallback_sigs += self._n
        STATS.fault_fallback_sigs += self._n
        log_printf("ecdsa device batch failed at settle (%s: %s) — CPU "
                   "re-verify of %d sig(s)",
                   type(err).__name__, str(err)[:120], self._n)
        if self._recover is not None:
            # packed batches carry a fast whole-batch CPU path (native
            # threaded verify over the original blobs)
            out = self._recover()
        else:
            out = _verify_cpu([self._records[i] for i in range(self._n)])
        self._degen = None
        self._records = None
        self._cpu_ok = np.asarray(out, dtype=bool)
        return self._cpu_ok

    def result(self) -> np.ndarray:
        if self._device_ok is None:
            return self._cpu_ok
        t0 = time.monotonic()
        try:
            with tm.span("ecdsa.settle", parent=self._ctx, lanes=self._n,
                         bucket=self._bucket):
                ok = np.asarray(self._device_ok)  # blocks until chip done
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # device died between enqueue and settle
            STATS.in_flight = max(0, STATS.in_flight - 1)
            _IN_FLIGHT_G.set(STATS.in_flight)
            self._device_ok = None
            return self._device_failed(e)
        # device_seconds counts only the blocking wait — when the P3
        # overlap is doing its job the host hid the latency and this is
        # near zero; summing dispatch->settle spans would double-count
        # concurrent chunks and absorb host interpreter time.
        wait = time.monotonic() - t0
        STATS.device_seconds += wait
        _SETTLE_H.observe(wait)
        # result fetch: the d2h crossing this settle actually paid
        # (validity mask bytes; the wait is the isolatable transfer time)
        dw.note_transfer("ecdsa", "d2h", int(np.asarray(ok).nbytes),
                         seconds=wait)
        dw.note_phase("ecdsa", "fetch", wait)
        STATS.in_flight = max(0, STATS.in_flight - 1)
        _IN_FLIGHT_G.set(STATS.in_flight)
        self._device_ok = None
        ok = np.asarray(ok, dtype=bool)
        if INJECTOR.should_poison("ecdsa"):
            ok = ~ok
        if self._kat:
            # known-answer gate: lanes n and n+1 are the good/bad probe
            # records appended at dispatch; both polarities must be right
            # before ANY lane of this batch is trusted
            if not bool(ok[self._n]) or bool(ok[self._n + 1]):
                STATS.kat_failures += 1
                return self._device_failed(
                    PoisonedOutput("ecdsa known-answer lanes wrong"))
        out = ok[: self._n].copy()
        if self._degen is not None:
            # w4 kernel: degenerate lanes (adversarial H == 0 collisions)
            # carry garbage — re-verify them on the scalar CPU path. The
            # kernel's verdict for those lanes is NEVER trusted.
            degen = np.asarray(self._degen)[: self._n]
            idxs = np.nonzero(degen)[0]
            if idxs.size:
                STATS.degenerate_rechecks += int(idxs.size)
                redo = _verify_cpu([self._records[i] for i in idxs])
                out[idxs] = redo
            self._degen = None
        if self._records is not None:
            # reject-side host confirmation: a device False is never
            # allowed to reject a block on its own (the KAT lanes can't
            # see a single corrupted real lane) — same contract as the
            # pow.py batch check and dispatch.merkle_root. Honest-valid
            # blocks have zero False lanes, so this is free in the common
            # case; an invalid-sig block pays one oracle verify per bad
            # lane, which the pure-CPU reference paid anyway.
            bad = np.nonzero(~out)[0]
            if bad.size:
                STATS.reject_confirm_sigs += int(bad.size)
                out[bad] = _verify_cpu([self._records[i] for i in bad])
        if self._breaker is not None:
            self._breaker.record_success()
        self._records = None
        self._cpu_ok = out
        return self._cpu_ok


def dispatch_batch(records: Sequence, backend: str = "auto",
                   kernel: str | None = None) -> BatchHandle:
    """Enqueue a verify batch without waiting; returns a BatchHandle.

    backend: "auto" (device if available and batch >= CPU_FLOOR),
    "device" (force), "cpu" (force oracle — synchronous).
    kernel: per-call override of the device verify kernel
    ("glv"/"w4"/"msm"); None uses active_kernel() (the -ecdsakernel
    startup selection). "msm" selects the Pippenger batch check for the
    Schnorr lanes; ECDSA lanes under "msm" ride the GLV ladder (the MSM
    batch equation is Schnorr-shaped).

    The device leg is supervised (ops/dispatch): the ecdsa circuit breaker
    gates it, bounded retries absorb transient dispatch errors, and a
    failed dispatch degrades to a fresh CPU verification of the same
    records — the verdict the caller sees is never dropped or fabricated."""
    if not records:
        return BatchHandle(0, cpu_ok=np.zeros(0, bool))
    n = len(records)
    # Schnorr lanes (script interpreter 64-byte-sig discrimination) take
    # the MSM batch path; mixed batches split per algorithm and re-merge
    # in submission order at settle
    algos = [getattr(r, "algo", "ecdsa") for r in records]
    if any(a == "schnorr" for a in algos):
        if all(a == "schnorr" for a in algos):
            return _dispatch_schnorr(records, backend, kernel)
        e_idx = [i for i, a in enumerate(algos) if a != "schnorr"]
        s_idx = [i for i, a in enumerate(algos) if a == "schnorr"]
        return _MergedHandle(n, [
            (dispatch_batch([records[i] for i in e_idx], backend,
                            kernel=kernel), e_idx),
            (_dispatch_schnorr([records[i] for i in s_idx], backend,
                               kernel), s_idx),
        ])
    use_device = backend == "device" or (
        backend == "auto"
        and n >= CPU_FLOOR
        and _device_available()
    )
    if use_device:
        br = dispatch.breaker("ecdsa")
        if br.allow():
            handle = _dispatch_device(records, br, kernel=kernel)
            if handle is not None:
                return handle
            # device leg failed after retries (breaker already charged):
            # fresh CPU re-verification, counted as fault fallback
            STATS.fault_fallback_sigs += n
        else:
            br.note_fallback(n)
            STATS.fault_fallback_sigs += n
    STATS.cpu_fallback_sigs += n
    return BatchHandle(n, cpu_ok=_verify_cpu(records))


def _interpret_kernels() -> bool:
    """True when the Pallas w4 kernels must run in interpret mode: CPU
    backends have no Mosaic, and WITHOUT this the dispatch path silently
    degraded every CPU "device" batch to the 256-step XLA bit ladder
    (pallas_call raises "Only interpret mode is supported on CPU
    backend"). Interpret mode lowers the real w4 kernel through XLA — the
    same arrangement parallel/sig_shard uses on virtual CPU meshes."""
    from .sha256 import backend_is_cpu

    return backend_is_cpu()


def _dispatch_device(records: Sequence, br,
                     kernel: str | None = None) -> Optional[BatchHandle]:
    """One supervised device enqueue attempt (with retries). Returns None
    when every attempt failed — the caller owns the CPU fallback. Two
    known-answer lanes (good + bad signature) ride after the real records
    so BatchHandle.result can detect a lying validity mask (the KAT lanes
    ride — and therefore exercise — whichever kernel actually ran,
    GLV included).

    Kernel chain: GLV (when selected and not latched broken) -> w4 Pallas
    -> XLA bit ladder; a GLV-leg failure is metered (STATS.glv_fallbacks)
    and degrades to w4 within the same attempt."""
    from . import secp256k1 as dev

    wire = list(records) + list(_kat_records())
    boff = Backoff(base=br.cfg.backoff_base, maximum=1.0)
    last: Optional[BaseException] = None
    kern = kernel if kernel in ECDSA_KERNELS else active_kernel()
    if kern == "msm":
        # the MSM batch equation verifies Schnorr sigs only; ECDSA lanes
        # under -ecdsakernel=msm keep the strongest per-lane ladder
        kern = "glv"
    # the enqueuing span (block.scan during the pipelined import) is the
    # settle span's parent — settle may run threads/blocks away
    ctx = tm.trace_context()
    for attempt in range(br.cfg.retries + 1):
        try:
            INJECTOR.on_call("ecdsa")
            device_ok = degen = None
            if kern == "glv" and glv_enabled():
                # floor 1024: the GLV program shapes stay the packed-path
                # bucket set {1024, 2048, ...} — sub-128 record batches
                # would otherwise each compile a tiny one-off shape
                # (~minutes per shape on a CPU backend, and every shape is
                # a fresh XLA program on the chip too)
                bucket = max(1024, _bucket_for(len(wire), pallas=True))
                if glv_dev_enabled():
                    # device-decompose leg (ISSUE 11): the host pack is
                    # the w4 byte emit ONLY — the lattice split runs
                    # inside the fused program
                    try:
                        INJECTOR.on_call(GLV_DEV_SITE)
                        INJECTOR.on_call(GLV_SITE)
                        t0 = time.monotonic()
                        with dw.phase("ecdsa", "pack"):
                            arrays = pack_records_w4_bytes(wire, bucket)
                        dt = time.monotonic() - t0
                        STATS.glv_emit_s += dt
                        _STAGE_H.labels(stage="emit").observe(dt)
                        t0 = time.monotonic()
                        device_ok, degen = _watched_kernel(
                            _PW_GLV_DEV, bucket, arrays,
                            lambda: dev.ecdsa_verify_batch_glv_dev(*arrays),
                            jitfn=(dev._glv_dev_program
                                   if bucket <= 16384 else None))
                        STATS.glv_dispatch_s += time.monotonic() - t0
                        if (INJECTOR.should_poison(GLV_DEV_SITE)
                                or INJECTOR.should_poison(GLV_SITE)):
                            device_ok = ~device_ok
                        STATS.glv_dispatches += 1
                        STATS.glv_dev_dispatches += 1
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except Exception as e:
                        _note_glv_dev_failure(e)
                        device_ok = degen = None
                if device_ok is None and glv_enabled():
                    # host-decompose fallback (the pre-ISSUE-11 path,
                    # itself numpy-vectorized now)
                    try:
                        INJECTOR.on_call(GLV_SITE)
                        arrays = pack_records_glv(wire, bucket)
                        t0 = time.monotonic()
                        device_ok, degen = _watched_kernel(
                            _PW_GLV, bucket, arrays,
                            lambda: dev.ecdsa_verify_batch_glv(*arrays),
                            jitfn=(dev._glv_program
                                   if bucket <= 16384 else None))
                        STATS.glv_dispatch_s += time.monotonic() - t0
                        if INJECTOR.should_poison(GLV_SITE):
                            device_ok = ~device_ok
                        STATS.glv_dispatches += 1
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except Exception as e:
                        _note_glv_failure(e)
                        device_ok = degen = None
            if device_ok is None and pallas_enabled():
                bucket = _bucket_for(len(wire), pallas=True)
                try:
                    if bucket % 1024 == 0:
                        # single-dispatch byte pipeline: (rows, 8, 128)
                        # exact-vreg tiles over a grid, device-side
                        # expansion — the whole batch is one program/round
                        # trip (ops/secp256k1.py)
                        with dw.phase("ecdsa", "pack"):
                            arrays = pack_records_w4_bytes(wire, bucket)
                        interp = _interpret_kernels()
                        device_ok, degen = _watched_kernel(
                            _PW_W4_BYTES, bucket, arrays,
                            lambda: dev.ecdsa_verify_batch_pallas_w4_bytes(
                                *arrays, interpret=interp),
                            jitfn=(dev._w4_bytes_program
                                   if bucket <= 16384 else None),
                            kwargs={"interpret": interp})
                    else:
                        with dw.phase("ecdsa", "pack"):
                            arrays = [np.asarray(a) for a in
                                      pack_records_w4(wire, bucket)]
                        device_ok, degen = _watched_kernel(
                            _PW_W4, bucket, arrays,
                            lambda: dev.ecdsa_verify_batch_pallas_w4(
                                *arrays),
                            split=None)
                except Exception as e:
                    _note_pallas_failure(e)
                    device_ok = None
            if device_ok is None:
                bucket = _bucket_for(len(wire), pallas=False)
                with dw.phase("ecdsa", "pack"):
                    arrays = [np.asarray(a) for a in
                              pack_records(wire, bucket)]
                device_ok = _watched_kernel(
                    _PW_XLA, bucket, arrays,
                    lambda: dev.ecdsa_verify_batch_jit(*arrays),
                    jitfn=dev.ecdsa_verify_batch_jit, split=None)
            _note_device_dispatch(len(records), bucket)
            return BatchHandle(len(records), bucket, device_ok, degen=degen,
                               records=wire, breaker=br, kat=True, ctx=ctx)
        except (KeyboardInterrupt, SystemExit):
            raise
        except (NameError, AttributeError, UnboundLocalError):
            # programming errors must not degrade silently to the CPU
            # engine forever — same invariant as _note_pallas_failure
            raise
        except Exception as e:  # noqa: BLE001 — supervised boundary
            last = e
            if attempt < br.cfg.retries:
                time.sleep(boff.next())
    br.record_failure(last)
    br.note_fallback(len(records))
    log_printf("ecdsa device dispatch failed (%s: %s) — CPU fallback for "
               "%d sig(s)", type(last).__name__, str(last)[:120],
               len(records))
    return None


_PALLAS_BROKEN = False
_GLV_BROKEN = False
_GLV_DEV_BROKEN = False


def glv_dev_enabled() -> bool:
    """Gate for the device-decompose GLV leg (ISSUE 11) — the first rung
    of the degradation ladder (device-decompose -> host decompose -> w4
    -> XLA -> CPU); latched off on deterministic lowering failures only."""
    return not _GLV_DEV_BROKEN


def _note_glv_dev_failure(e: Exception) -> None:
    """Device-decompose-leg failure bookkeeping: the dispatch degrades to
    the host-decompose GLV pack (same supervised attempt). Deterministic
    lowering failures latch _GLV_DEV_BROKEN; transient errors (including
    injected drill faults) do not. Programming errors re-raise — the
    _note_pallas_failure invariant: a NameError in the decompose kernel
    must not hide behind a green host fallback forever."""
    global _GLV_DEV_BROKEN
    if isinstance(e, (NameError, AttributeError, UnboundLocalError)):
        raise e
    STATS.glv_dev_fallbacks += 1
    text = f"{type(e).__name__}: {e}"
    if ("Mosaic" in text or "NotImplementedError" in text
            or "lowering" in text):
        _GLV_DEV_BROKEN = True
    log_printf("glv device-decompose leg failed (%s) — host decompose "
               "fallback%s", text[:200],
               " (latched)" if _GLV_DEV_BROKEN else "")


def glv_enabled() -> bool:
    """Gate for the GLV device leg (kernel selection happens separately —
    see active_kernel); latched off on deterministic lowering failures so
    a toolchain that can't compile the GLV program degrades to w4 once,
    not per dispatch."""
    return not _GLV_BROKEN


def _note_glv_failure(e: Exception) -> None:
    """GLV-leg failure bookkeeping: the dispatch degrades to the w4 kernel
    (same supervised attempt). Deterministic lowering failures latch
    _GLV_BROKEN; transient errors (including injected drill faults) do
    not. Programming errors re-raise — same invariant as
    _note_pallas_failure: a NameError in the GLV core must not hide
    behind a green w4 fallback forever."""
    global _GLV_BROKEN
    if isinstance(e, (NameError, AttributeError, UnboundLocalError)):
        raise e
    STATS.glv_fallbacks += 1
    text = f"{type(e).__name__}: {e}"
    if ("Mosaic" in text or "NotImplementedError" in text
            or "lowering" in text):
        _GLV_BROKEN = True
    log_printf("glv ECDSA kernel failed (%s) — w4 fallback%s",
               text[:200],
               " (latched)" if _GLV_BROKEN else "")


def pallas_enabled() -> bool:
    """Single source of truth for the Pallas-vs-XLA kernel choice — bucket
    granularity (dispatch_batch) and kernel selection (_dispatch_device)
    must agree or big batches get Pallas-sized buckets on the XLA kernel,
    defeating the bounded-recompile bucket design."""
    return (
        not _PALLAS_BROKEN
        and os.environ.get("BCP_SECP_PALLAS", "1") not in ("0", "false")
    )


def _note_pallas_failure(e: Exception) -> None:
    """Pallas compile failure bookkeeping (jit compilation is synchronous,
    so failures surface at the dispatch call). Deterministic Mosaic/
    lowering failures latch _PALLAS_BROKEN; transient remote-compile-
    service errors do NOT — the next dispatch retries.

    Programming errors are NOT toolchain failures: a NameError inside the
    kernel code would otherwise degrade silently to the XLA fallback
    forever (it happened — a refactor deleted _PALLAS_SUPER and every
    test stayed green on the fallback). Those re-raise."""
    global _PALLAS_BROKEN
    if isinstance(e, (NameError, AttributeError, UnboundLocalError)):
        raise e
    STATS.pallas_fallbacks += 1
    text = f"{type(e).__name__}: {e}"
    if ("Mosaic" in text or "NotImplementedError" in text
            or "lowering" in text):
        _PALLAS_BROKEN = True  # this toolchain can't compile it
    from ..util.log import log_printf

    log_printf("pallas ECDSA kernel failed (%s) — XLA fallback%s",
               text[:200],
               " (latched)" if _PALLAS_BROKEN else "")


def verify_batch(records: Sequence, backend: str = "auto",
                 kernel: str | None = None) -> np.ndarray:
    """Verify all records synchronously; returns (len(records),) bool."""
    return dispatch_batch(records, backend, kernel=kernel).result()


# ---------------------------------------------------------------------------
# Cross-block lane packer — the pipelined IBD engine's aggregation layer.
#
# A single mainnet-shaped block rarely fills a padded bucket, so per-block
# dispatch pays padding (and, on a tunneled chip, a whole round trip) for
# partially-filled lanes. The packer aggregates deferred records from
# MULTIPLE in-flight blocks (the ChainstateManager settle horizon) and
# dispatches only full buckets; each contributing block gets its own
# SigBatchFuture whose lanes map back into the shared BatchHandles, so
# failure attribution and settle order stay per-block. Supervision is
# unchanged: every underlying dispatch is the breaker/KAT-gated
# dispatch_batch, and BatchHandle.result() is memoized, so many futures
# can share one handle safely.
# ---------------------------------------------------------------------------


class SigBatchFuture:
    """One block's slice of the cross-block packed dispatches. result()
    returns a bool verdict per record in submission order; it forces a
    packer flush if any of this block's records are still undispatched
    (settling the horizon's oldest block must never deadlock on lanes
    parked behind it)."""

    __slots__ = ("_packer", "_segments", "_queued", "_result", "_tag")

    def __init__(self, packer):
        self._packer = packer
        self._segments = []  # (handle-wrapper, start, end), dispatch order
        self._queued = 0     # records still in the packer's pending buffer
        self._result = None
        self._tag = None     # speculation-tree branch attribution

    def result(self) -> np.ndarray:
        if self._result is None:
            if self._queued:
                self._packer.flush_for(self)
            parts = [self._packer._settle(h)[s:e]
                     for h, s, e in self._segments]
            self._result = (np.concatenate(parts) if parts
                            else np.zeros(0, bool))
            self._segments = []
        return self._result

    def drain(self) -> None:
        """Abort-path settle: records still parked in the packer's pending
        buffer are DISCARDED (verifying doomed lanes — up to a whole
        horizon's worth on an unwind — would be pure waste), while
        already-dispatched segments are materialized so STATS.in_flight
        and a breaker probe riding one of them never strand. Verdicts are
        ignored."""
        try:
            if self._queued:
                self._packer.discard(self)
            for pd, _s, _e in self._segments:
                try:
                    self._packer._settle(pd)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception:  # noqa: BLE001 — abort-path drain
                    pass
        finally:
            self._segments = []
            if self._result is None:
                self._result = np.zeros(0, bool)


class _PackedDispatch:
    """A shared BatchHandle plus the overlap-metering timestamps."""

    __slots__ = ("handle", "t_enqueue", "settled")

    def __init__(self, handle, t_enqueue):
        self.handle = handle
        self.t_enqueue = t_enqueue
        self.settled = False


class LanePacker:
    """Aggregate SigCheckRecords across blocks into full padded buckets.

    ``lanes`` is the dispatch size; the default (2046) fills the 2048
    bucket exactly once the supervised dispatch appends its 2 known-answer
    lanes. When the ecdsa breaker is not healthy the packer stops
    aggregating (target 0): every add flushes immediately, because with
    the device path open all lanes go to the CPU engine and aggregation
    would only add settle latency."""

    # branch-attribution bound: the per-tag lane tallies must not grow
    # without limit under a fork storm minting fresh branch tags
    MAX_TAGS = 64

    def __init__(self, backend: str = "auto", lanes: int = 2046,
                 kernel: str | None = None):
        self.backend = backend
        self.lanes = lanes
        self.kernel = kernel  # per-packer -ecdsakernel override (wiring)
        self._pending: list = []           # records awaiting dispatch
        self._pending_futs: list = []      # (future, count) per add(), order
        self.stats = {
            "dispatches": 0, "lanes_real": 0, "lanes_padded": 0,
            "lanes_discarded": 0, "blocks": 0,
            "inflight_s": 0.0, "blocked_s": 0.0,
        }
        # speculation-tree branch attribution (ISSUE 9): lanes added /
        # discarded per branch tag — competing branches share buckets,
        # this is the per-branch split of the shared device work
        self.branch_lanes: dict[str, int] = {}
        self.branch_discards: dict[str, int] = {}

    def _tag_note(self, table: dict, tag: str | None, n: int) -> None:
        if tag is None or n <= 0:
            return
        if tag not in table and len(table) >= self.MAX_TAGS:
            table.pop(next(iter(table)))  # oldest tag out
        table[tag] = table.get(tag, 0) + n

    def _target_lanes(self) -> int:
        if self.backend == "cpu":
            return self.lanes  # no padding concept, but batching still wins
        if not dispatch.breaker("ecdsa").healthy():
            return 0  # device path distrusted: no point holding lanes back
        return self.lanes

    def add(self, records: Sequence, tag: str | None = None
            ) -> SigBatchFuture:
        """Enqueue one block's fresh (sigcache-missed) records; returns the
        block's future. Dispatches fire whenever a full bucket is banked.
        ``tag`` attributes the lanes to a speculation-tree branch."""
        fut = SigBatchFuture(self)
        fut._queued = len(records)
        fut._tag = tag
        self._tag_note(self.branch_lanes, tag, len(records))
        if records:
            self._pending.extend(records)
            self._pending_futs.append((fut, len(records)))
        target = self._target_lanes()
        if target <= 0:
            self.flush()  # device distrusted: don't hold lanes back
        else:
            while len(self._pending) >= target:
                self._dispatch(target)
        return fut

    def flush(self) -> None:
        """Dispatch everything still pending (sub-bucket tail included)."""
        while self._pending:
            self._dispatch(min(len(self._pending), max(self.lanes, 1)))

    def discard(self, fut: SigBatchFuture) -> None:
        """Drop ``fut``'s still-undispatched records from the pending
        buffer (abort path — see SigBatchFuture.drain)."""
        if fut._queued <= 0:
            return
        off = 0
        for i, (f, count) in enumerate(self._pending_futs):
            if f is fut:
                del self._pending[off:off + count]
                self._pending_futs.pop(i)
                self.stats["lanes_discarded"] += count
                self._tag_note(self.branch_discards, fut._tag, count)
                fut._queued = 0
                return
            off += count

    def flush_for(self, fut: SigBatchFuture) -> None:
        """Dispatch only the pending PREFIX up to (and including) ``fut``'s
        records — settling the horizon's oldest block must not also ship
        younger blocks' sub-bucket tails, which can keep aggregating
        toward full buckets (lanes queue FIFO, so the prefix is exactly
        what fut needs)."""
        while fut._queued > 0 and self._pending:
            self._dispatch(min(len(self._pending), max(self.lanes, 1)))

    def _dispatch(self, n: int) -> None:
        batch = self._pending[:n]
        del self._pending[:n]
        try:
            handle = dispatch_batch(batch, backend=self.backend,
                                    kernel=self.kernel)
        except (KeyboardInterrupt, SystemExit,
                NameError, AttributeError, UnboundLocalError):
            raise  # programming errors must surface, not degrade
        except Exception:
            # same last-line-of-defense contract as the per-block verifier:
            # a supervision-layer crash must not drop the batch
            STATS.fault_fallback_sigs += len(batch)
            handle = dispatch_batch(batch, backend="cpu")
        pd = _PackedDispatch(handle, time.monotonic())
        st = self.stats
        st["dispatches"] += 1
        st["lanes_real"] += len(batch)
        _PACKER_FLUSH_H.observe(len(batch))
        # padding booked from the handle's ACTUAL bucket (0 = the dispatch
        # took the CPU lane, which has no padding concept); the 2 KAT lanes
        # ride every device batch and are excluded from the fill metric
        bucket = getattr(handle, "_bucket", 0)
        if bucket:
            st["lanes_padded"] += max(0, bucket - len(batch) - 2)
        total = st["lanes_real"] + st["lanes_padded"]
        if total:
            _LANE_FILL_G.set(round(100.0 * st["lanes_real"] / total, 2))
        # carve the dispatched records back into per-block segments
        pos = 0
        consumed = []
        for i, (fut, count) in enumerate(self._pending_futs):
            take = min(count, n - pos)
            if take <= 0:
                break
            fut._segments.append((pd, pos, pos + take))
            fut._queued -= take
            pos += take
            if take == count:
                consumed.append(i)
                st["blocks"] += 1
            else:
                self._pending_futs[i] = (fut, count - take)
        for i in reversed(consumed):
            self._pending_futs.pop(i)

    def _settle(self, pd: _PackedDispatch) -> np.ndarray:
        """Settle a shared dispatch (first consumer pays the blocking wait
        and the overlap metering; BatchHandle memoizes for the rest)."""
        if pd.settled:
            return pd.handle.result()
        t0 = time.monotonic()
        out = pd.handle.result()
        now = time.monotonic()
        pd.settled = True
        self.stats["blocked_s"] += now - t0
        self.stats["inflight_s"] += now - pd.t_enqueue
        return out

    def snapshot(self) -> dict:
        st = dict(self.stats)
        total = st["lanes_real"] + st["lanes_padded"]
        st["lane_fill_pct"] = round(100.0 * st["lanes_real"] / total, 2) \
            if total else 100.0
        # fraction of dispatched-batch lifetime the host spent NOT blocked
        # on settle — >0 means the pipeline actually hid device latency
        # (on a synchronous CPU backend the verify cost lands at enqueue,
        # inside the scan leg, and this reads as fully hidden)
        st["overlap_fraction"] = round(
            1.0 - st["blocked_s"] / st["inflight_s"], 4) \
            if st["inflight_s"] > 0 else 0.0
        st["pending_lanes"] = len(self._pending)
        st["branch_lanes"] = dict(self.branch_lanes)
        st["branch_discards"] = dict(self.branch_discards)
        return st


# ---------------------------------------------------------------------------
# Blob-level dispatch — the native connect engine's sigscan
# (native/connect.cpp) emits (pub64, r||s, msg, rn, wrap) byte blobs; this
# entry feeds them straight into the w4-bytes device program (or the native
# threaded CPU verify) with zero per-record Python-int work. The record-level
# dispatch_batch above remains the generic path (script interpreter output).
# ---------------------------------------------------------------------------

class _LazyRecords:
    """SigCheckRecord view over packed blobs, materialized per index — only
    degenerate-lane rechecks (rare) ever touch it."""

    __slots__ = ("pub", "rs", "msg")

    def __init__(self, pub: np.ndarray, rs: np.ndarray, msg: np.ndarray):
        self.pub = pub
        self.rs = rs
        self.msg = msg

    def __getitem__(self, i: int):
        from ..script.interpreter import SigCheckRecord

        pub = self.pub[i].tobytes()
        rs = self.rs[i].tobytes()
        return SigCheckRecord(
            (int.from_bytes(pub[:32], "big"), int.from_bytes(pub[32:], "big")),
            int.from_bytes(rs[:32], "big"), int.from_bytes(rs[32:], "big"),
            int.from_bytes(self.msg[i].tobytes(), "big"),
        )


def records_to_blobs(records: Sequence):
    """Pack script-interpreter SigCheckRecords into the blob layout so the
    occasional generic-path record can join a packed dispatch. Also emits
    rn/wrap (the x-wraparound candidate gate)."""
    n = len(records)
    pub = np.frombuffer(
        b"".join(r.pubkey[0].to_bytes(32, "big") + r.pubkey[1].to_bytes(32, "big")
                 for r in records), np.uint8).reshape(n, 64)
    rs = np.frombuffer(
        b"".join((r.r % (1 << 256)).to_bytes(32, "big")
                 + (r.s % (1 << 256)).to_bytes(32, "big")
                 for r in records), np.uint8).reshape(n, 64)
    msg = np.frombuffer(
        b"".join((r.msg_hash % (1 << 256)).to_bytes(32, "big")
                 for r in records), np.uint8).reshape(n, 32)
    wraps = [r.r + oracle.N < oracle.P for r in records]
    rn = np.frombuffer(
        b"".join((r.r + oracle.N if w else r.r).to_bytes(32, "big")
                 for r, w in zip(records, wraps)), np.uint8).reshape(n, 32)
    return pub, rs, msg, rn, np.asarray(wraps, np.uint8)


# below this lane count the device round trip loses to the threaded native
# CPU verify even on real hardware (dispatch+transfer latency)
PACKED_DEVICE_FLOOR = 512


def dispatch_packed(pub: np.ndarray, rs: np.ndarray, msg: np.ndarray,
                    rn: np.ndarray, wrap: np.ndarray,
                    backend: str = "auto") -> BatchHandle:
    """Enqueue a packed verify batch: pub (n,64), rs (n,64), msg (n,32),
    rn (n,32), wrap (n,) — all uint8, big-endian fields, caller-validated
    ranges (1 <= r,s < N; pubkey on-curve affine). Device leg is breaker-
    supervised like dispatch_batch (same KAT lanes, same CPU re-verify on
    failure)."""
    from .. import native

    n = len(msg)
    if n == 0:
        return BatchHandle(0, cpu_ok=np.zeros(0, bool))
    use_device = backend == "device" or (
        backend == "auto" and n >= PACKED_DEVICE_FLOOR and _device_available()
    )
    if not use_device and native.available():
        return _packed_cpu_handle(pub, rs, msg, n)
    # the packed device leg is viable when EITHER byte-pipeline kernel can
    # run: the GLV program is plain XLA and does not need Pallas, so a
    # latched-broken Mosaic toolchain must not push the hottest import
    # path through the per-record Python repack below
    packed_ok = pallas_enabled() or (
        active_kernel() == "glv" and glv_enabled()
    )
    if not (use_device and packed_ok):
        # XLA fallback (both kernels broken / no native lib): go through
        # the record-level path — rare, and it keeps one source of truth
        recs = _LazyRecords(pub, rs, msg)
        return dispatch_batch([recs[i] for i in range(n)], backend=backend)

    br = dispatch.breaker("ecdsa")
    if not br.allow():
        br.note_fallback(n)
        STATS.fault_fallback_sigs += n
        return _packed_cpu_handle(pub, rs, msg, n)
    handle = _dispatch_packed_device(pub, rs, msg, rn, wrap, n, br)
    if handle is None:
        STATS.fault_fallback_sigs += n
        return _packed_cpu_handle(pub, rs, msg, n)
    return handle


def _packed_cpu_handle(pub, rs, msg, n: int) -> BatchHandle:
    """CPU verdict for a packed batch (native threaded verify when the
    library loaded, Python-int oracle otherwise)."""
    from .. import native

    STATS.cpu_fallback_sigs += n
    if native.available():
        ok = native.ecdsa_verify_batch_blobs(
            pub.tobytes(), rs.tobytes(), msg.tobytes(), n)
        return BatchHandle(n, cpu_ok=np.asarray(ok, bool))
    recs = _LazyRecords(pub, rs, msg)
    return BatchHandle(n, cpu_ok=_verify_cpu([recs[i] for i in range(n)]))


def _dispatch_packed_device(pub, rs, msg, rn, wrap, n: int,
                            br) -> Optional[BatchHandle]:
    """Supervised packed enqueue (retries + KAT lanes); None when every
    attempt failed."""
    from .. import native
    from . import secp256k1 as dev

    # KAT probe lanes appended after the real records (blob layout)
    kpub, krs, kmsg, krn, kwrap = records_to_blobs(list(_kat_records()))
    pub2 = np.concatenate([pub, kpub])
    rs2 = np.concatenate([rs, krs])
    msg2 = np.concatenate([msg, kmsg])
    rn2 = np.concatenate([rn, krn])
    wrap2 = np.concatenate([np.asarray(wrap, np.uint8), kwrap])
    m = n + 2
    bucket = max(1024, _bucket_for(m, pallas=True))

    def pad(mat: np.ndarray, width: int) -> np.ndarray:
        out = np.zeros((bucket, width), np.uint8)
        out[:m] = mat
        return out

    boff = Backoff(base=br.cfg.backoff_base, maximum=1.0)
    last: Optional[BaseException] = None
    ctx = tm.trace_context()  # settle-span parent (see _dispatch_device)
    for attempt in range(br.cfg.retries + 1):
        try:
            INJECTOR.on_call("ecdsa")
            # u1/u2 via the threaded native modular-inverse leg;
            # Python-int loop only if the native library is missing
            if native.available():
                u1_blob, u2_blob, ok = native.ecdsa_precompute_blobs(
                    rs2.tobytes(), msg2.tobytes(), m)
                u1 = np.frombuffer(u1_blob, np.uint8).reshape(m, 32)
                u2 = np.frombuffer(u2_blob, np.uint8).reshape(m, 32)
                range_bad = ~np.asarray(ok, bool)
            else:
                recs = _LazyRecords(pub2, rs2, msg2)
                scalars = decompose_scalars([recs[i] for i in range(m)])
                u1 = np.frombuffer(
                    b"".join(a.to_bytes(32, "big") for a, _ in scalars),
                    np.uint8).reshape(m, 32)
                u2 = np.frombuffer(
                    b"".join(b.to_bytes(32, "big") for _, b in scalars),
                    np.uint8).reshape(m, 32)
                range_bad = np.zeros(m, bool)
            q_inf = np.ones(bucket, np.uint8)
            q_inf[:m] = range_bad.astype(np.uint8)
            wrap8 = np.zeros(bucket, np.uint8)
            wrap8[:m] = wrap2
            device_ok = degen = None
            if (active_kernel() == "glv" and glv_enabled()
                    and glv_dev_enabled()):
                # device-decompose GLV leg for the packed path (ISSUE
                # 11): the blobs pad straight into the fused program's
                # byte matrices — zero per-record host work beyond the
                # precompute above; failure degrades to the host lattice
                # split below, then the w4 kernel
                try:
                    INJECTOR.on_call(GLV_DEV_SITE)
                    INJECTOR.on_call(GLV_SITE)
                    t0 = time.monotonic()
                    with dw.phase("ecdsa", "pack"):
                        arrays = [pad(u1, 32), pad(u2, 32),
                                  pad(pub2[:, :32], 32),
                                  pad(pub2[:, 32:], 32), q_inf,
                                  pad(rs2[:, :32], 32), pad(rn2, 32),
                                  wrap8]
                    dt = time.monotonic() - t0
                    STATS.glv_emit_s += dt
                    _STAGE_H.labels(stage="emit").observe(dt)
                    t0 = time.monotonic()
                    device_ok, degen = _watched_kernel(
                        _PW_GLV_DEV, bucket, arrays,
                        lambda: dev.ecdsa_verify_batch_glv_dev(*arrays),
                        jitfn=(dev._glv_dev_program
                               if bucket <= 16384 else None))
                    STATS.glv_dispatch_s += time.monotonic() - t0
                    if (INJECTOR.should_poison(GLV_DEV_SITE)
                            or INJECTOR.should_poison(GLV_SITE)):
                        device_ok = ~device_ok
                    STATS.glv_dispatches += 1
                    STATS.glv_dev_dispatches += 1
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    _note_glv_dev_failure(e)
                    device_ok = degen = None
            if (device_ok is None and active_kernel() == "glv"
                    and glv_enabled()):
                # host-decompose GLV leg: same lattice split as
                # pack_records_glv (numpy limb batches), fed from the
                # blobs; failure degrades to the w4 kernel below
                try:
                    INJECTOR.on_call(GLV_SITE)
                    arrays = _glv_pack_parts(
                        u1, u2, pub2[:, :32], pub2[:, 32:], rs2[:, :32],
                        rn2, wrap2.astype(bool), range_bad, bucket)
                    t0 = time.monotonic()
                    device_ok, degen = _watched_kernel(
                        _PW_GLV, bucket, arrays,
                        lambda: dev.ecdsa_verify_batch_glv(*arrays),
                        jitfn=dev._glv_program if bucket <= 16384 else None)
                    STATS.glv_dispatch_s += time.monotonic() - t0
                    if INJECTOR.should_poison(GLV_SITE):
                        device_ok = ~device_ok
                    STATS.glv_dispatches += 1
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    _note_glv_failure(e)
                    device_ok = degen = None
            if device_ok is None:
                try:
                    with dw.phase("ecdsa", "pack"):
                        arrays = [pad(u1, 32), pad(u2, 32),
                                  pad(pub2[:, :32], 32),
                                  pad(pub2[:, 32:], 32), q_inf,
                                  pad(rs2[:, :32], 32), pad(rn2, 32),
                                  wrap8]
                    interp = _interpret_kernels()
                    device_ok, degen = _watched_kernel(
                        _PW_W4_BYTES, bucket, arrays,
                        lambda: dev.ecdsa_verify_batch_pallas_w4_bytes(
                            *arrays, interpret=interp),
                        jitfn=(dev._w4_bytes_program
                               if bucket <= 16384 else None),
                        kwargs={"interpret": interp})
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    # pallas bookkeeping scoped to the KERNEL call only —
                    # a failure in the precompute/pack legs above must not
                    # latch _PALLAS_BROKEN (may re-raise programming
                    # errors)
                    _note_pallas_failure(e)
                    raise
            _note_device_dispatch(n, bucket)

            def recover() -> np.ndarray:
                # settle-time failure on a packed batch: the native
                # threaded verify over the original blobs beats walking
                # _LazyRecords through the Python-int oracle by orders of
                # magnitude at reindex batch sizes
                if native.available():
                    return np.asarray(native.ecdsa_verify_batch_blobs(
                        pub.tobytes(), rs.tobytes(), msg.tobytes(), n),
                        bool)
                recs = _LazyRecords(pub, rs, msg)
                return _verify_cpu([recs[i] for i in range(n)])

            return BatchHandle(n, bucket, device_ok, degen=degen,
                               records=_LazyRecords(pub2, rs2, msg2),
                               breaker=br, kat=True, recover=recover,
                               ctx=ctx)
        except (KeyboardInterrupt, SystemExit):
            raise
        except (NameError, AttributeError, UnboundLocalError):
            raise  # programming errors must not degrade silently
        except Exception as e:  # noqa: BLE001 — supervised boundary
            last = e
            if attempt < br.cfg.retries:
                time.sleep(boff.next())
    br.record_failure(last)
    br.note_fallback(n)
    log_printf("ecdsa packed device dispatch failed (%s: %s) — CPU "
               "fallback for %d sig(s)", type(last).__name__,
               str(last)[:120], n)
    return None
