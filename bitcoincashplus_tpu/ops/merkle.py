"""TPU Merkle-root construction.

Replaces src/consensus/merkle.cpp:~45 (ComputeMerkleRoot)'s serial pairwise
loop with a lane-parallel tree reduction: each level hashes all digest pairs
at once (double-SHA of the 64-byte concatenation, 3 compressions), log2(n)
levels total (BASELINE.json config: 4k-tx snapshot -> 12 levels).

Consensus-exact odd handling: when a level has an odd node count the LAST
node is paired with itself (the CVE-2012-2459 duplication rule) — applied
per level on the host between device calls, never by power-of-two padding,
because padding changes the tree shape for non-pow2 counts.

Lane padding: each level is padded up to a multiple of PAD_LANES with
garbage pairs (masked out on the host) so recompilation is bounded by the
number of distinct padded sizes, not distinct tx counts (SURVEY.md §8.4
bucketing).

Also detects the known Merkle "mutation" (two identical consecutive hashes
forming a duplicated pair), which the reference surfaces via the *mutated
flag for CheckBlock's duplicate-tx rule.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .sha256 import sha256d_64

PAD_LANES = 128  # one VPU lane row; keeps distinct compiled shapes ~O(log n)


def _digests_to_words(digests: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 digests -> (N, 8) uint32 BE words."""
    return digests.reshape(-1, 8, 4).view(">u4").squeeze(-1).astype(np.uint32)


def _words_to_digests(words: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(words).astype(">u4").view(np.uint8).reshape(-1, 32)


@partial(jax.jit, static_argnums=(1,))
def _tree_reduce_jit(words, n_levels: int, m):
    """Whole-tree reduction in ONE dispatch.

    words: (2**n_levels, 8) u32 leaf digests, zero-padded past the true
    count ``m`` (dynamic scalar). Per level the garbage lanes compute
    harmlessly at full width; the consensus odd-duplication is a masked
    select (pair i takes left for right when 2i+1 >= m), so the compiled
    shape depends only on the pow2 bucket — never on the tx count. The
    mutation flag considers only whole pairs inside the live prefix,
    matching consensus/merkle.py's check-before-duplicate ordering.
    """
    level = [words[:, i] for i in range(8)]  # column-major: 8 arrays (B,)
    mutated = jnp.zeros((), dtype=bool)
    witness = None  # first-level pair-0 hash — the host validation probe
    for k in range(n_levels):
        half = 1 << (n_levels - k - 1)
        pair_idx = jnp.arange(half, dtype=jnp.uint32)
        left = [c[0::2] for c in level]
        right = [c[1::2] for c in level]
        equal = jnp.ones((half,), dtype=bool)
        for l_col, r_col in zip(left, right):
            equal &= l_col == r_col
        live_pair = 2 * pair_idx + 1 < m  # both nodes inside the prefix
        mutated |= jnp.any(equal & live_pair)
        dup = 2 * pair_idx + 1 >= m  # odd tail (and dead lanes): self-pair
        right = [jnp.where(dup, l_col, r_col)
                 for l_col, r_col in zip(left, right)]
        hashed = sha256d_64(left + right)
        if k == 0:
            # pair 0 of level 1 = sha256d(leaf0 || leaf1): recomputable on
            # host in 2 hashes, so the caller can prove the device actually
            # ran SHA rounds (poisoned-output detection, ops/dispatch)
            witness = jnp.stack([c[0] for c in hashed], axis=-1)
        # the bucket can be taller than the real tree: once the live count
        # reaches 1 the root rides through untouched instead of being
        # self-hashed up the remaining levels
        done = m <= 1
        level = [jnp.where(done, l_col, h_col)
                 for l_col, h_col in zip(left, hashed)]
        m = jnp.where(done, m, (m + 1) // 2)
    return jnp.stack(level, axis=-1)[0], mutated, witness


@jax.jit
def _hash_pairs_jit(words):
    """(N, 16) u32 rows — each row a 64-byte left||right concatenation —
    double-SHA'd lane-parallel to (N, 8) u32 digests. One flat level, no
    tree: the snapshot-certificate MMR (store/certificate.py) drives this
    once per level over the pow2 peak decomposition."""
    cols = [words[:, i] for i in range(16)]
    return jnp.stack(sha256d_64(cols), axis=-1)


def sha256d_pairs(pairs: list[bytes]) -> list[bytes]:
    """Batched sha256d over 64-byte concatenations — the level primitive
    the snapshot-certificate MMR builds on. Small batches take the host
    loop outright (dispatch latency dominates); large ones ride the
    supervised ``merkle`` subsystem with the same poisoned-output witness
    discipline as the block-Merkle tree: pair 0 is recomputed on the host
    in 2 hashes, and any device failure degrades to the CPU loop with the
    result unchanged."""
    from ..crypto.hashes import sha256d
    from ..util import devicewatch as dw
    from . import dispatch

    n = len(pairs)
    if n == 0:
        return []
    if n < PAD_LANES:
        return [sha256d(p) for p in pairs]

    def device():
        bucket = -(-n // PAD_LANES) * PAD_LANES
        words = np.frombuffer(b"".join(pairs), dtype=np.uint8) \
            .reshape(-1, 16, 4).view(">u4").squeeze(-1).astype(np.uint32)
        if bucket != n:
            words = np.concatenate(
                [words, np.zeros((bucket - n, 16), dtype=np.uint32)], axis=0)
        dw.note_transfer("merkle", "h2d", int(words.nbytes))
        # PAD_LANES buckets bound the compiled shapes exactly like the
        # tree path; the budget mirrors merkle_tree's pow2 rationale
        with dw.program("merkle_pairs", shape_budget=24).dispatch(
                bucket, jitfn=_hash_pairs_jit, args=(words,)):
            out = _hash_pairs_jit(jnp.asarray(words))
        out = np.asarray(out, dtype=np.uint32)[:n]
        dw.note_transfer("merkle", "d2h", int(out.nbytes))
        return [d.tobytes() for d in _words_to_digests(out)]

    out, _used_device = dispatch.supervised_call(
        "merkle", device, lambda: [sha256d(p) for p in pairs],
        validate=lambda res: res[0] == sha256d(pairs[0]),
        poison=lambda res: [bytes(b ^ 0xFF for b in res[0])] + res[1:],
        items=n,
    )
    return out


def compute_merkle_root_tpu(hashes: list[bytes]) -> tuple[bytes, bool]:
    """Drop-in for consensus.merkle.compute_merkle_root on large inputs
    (see compute_merkle_root_tpu_ex for the full contract)."""
    root, mutated, _used_device = compute_merkle_root_tpu_ex(hashes)
    return root, mutated


def compute_merkle_root_tpu_ex(hashes: list[bytes]) -> tuple:
    """Supervised device Merkle root: (root, mutated, used_device) —
    used_device is False whenever the CPU reference produced the result
    (small input, open breaker, or fallback), letting callers skip their
    own CPU confirmation.

    The whole log2(n)-level tree runs as a single
    device dispatch (dispatch latency dominated the old per-level loop —
    12 round-trips for 4k txids); compilation is bounded by the number of
    distinct pow2 buckets, not tx counts.

    Supervised (ops/dispatch): the device also returns the level-1 pair-0
    node, which the host recomputes in 2 hashes — a device that didn't
    really run the SHA rounds (or a poisoned output) is caught and the
    call degrades to the CPU reference loop, verdict unchanged.
    """
    from ..consensus.merkle import compute_merkle_root
    from ..crypto.hashes import sha256d
    from . import dispatch

    if not hashes:
        return b"\x00" * 32, False, False
    if len(hashes) == 1:
        return hashes[0], False, False
    n = len(hashes)

    def device():
        from ..util import devicewatch as dw

        bucket = max(PAD_LANES, 1 << (n - 1).bit_length())
        words = _digests_to_words(
            np.frombuffer(b"".join(hashes), dtype=np.uint8).reshape(-1, 32)
        )
        if bucket != n:
            words = np.concatenate(
                [words, np.zeros((bucket - n, 8), dtype=np.uint32)], axis=0
            )
        # watched dispatch: pow2 buckets bound the compiled shapes to one
        # per level count — declare the budget as the plausible pow2 range
        # (2^7 leaf floor .. 2^30), so a padding regression that starts
        # compiling per-tx-count shapes fires the retrace sentinel
        dw.note_transfer("merkle", "h2d", int(words.nbytes))
        with dw.program("merkle_tree", shape_budget=24).dispatch(
                bucket, jitfn=_tree_reduce_jit,
                args=(words, bucket.bit_length() - 1, np.uint32(n))):
            root_words, mutated, witness = _tree_reduce_jit(
                jnp.asarray(words), bucket.bit_length() - 1, jnp.uint32(n)
            )
        root = np.asarray(root_words, dtype=np.uint32)
        wit = np.asarray(witness, dtype=np.uint32)
        dw.note_transfer("merkle", "d2h",
                         int(root.nbytes) + int(wit.nbytes))
        return (_words_to_digests(root[None, :])[0].tobytes(), bool(mutated),
                _words_to_digests(wit[None, :])[0].tobytes())

    def validate(res) -> bool:
        _root, _mut, witness = res
        return witness == sha256d(hashes[0] + hashes[1])

    def poison(res):
        root, mut, witness = res
        flip = bytes(b ^ 0xFF for b in root)
        return flip, mut, bytes(b ^ 0xFF for b in witness)

    out, used_device = dispatch.supervised_call(
        "merkle", device, lambda: compute_merkle_root(hashes),
        validate=validate, poison=poison, items=n,
    )
    if used_device:
        root, mutated, _witness = out
        return root, mutated, True
    root, mutated = out
    return root, mutated, False
