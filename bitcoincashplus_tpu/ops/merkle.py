"""TPU Merkle-root construction.

Replaces src/consensus/merkle.cpp:~45 (ComputeMerkleRoot)'s serial pairwise
loop with a lane-parallel tree reduction: each level hashes all digest pairs
at once (double-SHA of the 64-byte concatenation, 3 compressions), log2(n)
levels total (BASELINE.json config: 4k-tx snapshot -> 12 levels).

Consensus-exact odd handling: when a level has an odd node count the LAST
node is paired with itself (the CVE-2012-2459 duplication rule) — applied
per level on the host between device calls, never by power-of-two padding,
because padding changes the tree shape for non-pow2 counts.

Lane padding: each level is padded up to a multiple of PAD_LANES with
garbage pairs (masked out on the host) so recompilation is bounded by the
number of distinct padded sizes, not distinct tx counts (SURVEY.md §8.4
bucketing).

Also detects the known Merkle "mutation" (two identical consecutive hashes
forming a duplicated pair), which the reference surfaces via the *mutated
flag for CheckBlock's duplicate-tx rule.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .sha256 import sha256d_64

PAD_LANES = 128  # one VPU lane row; keeps distinct compiled shapes ~O(log n)


@jax.jit
def _level_jit(words):
    """(n_pairs, 16) uint32 pair words -> (n_pairs, 8) parent digest words.
    jit specializes on the (lane-padded) shape; recompiles are bounded by
    the number of distinct padded sizes."""
    return jnp.stack(sha256d_64([words[:, i] for i in range(16)]), axis=-1)


def _digests_to_words(digests: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 digests -> (N, 8) uint32 BE words."""
    return digests.reshape(-1, 8, 4).view(">u4").squeeze(-1).astype(np.uint32)


def _words_to_digests(words: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(words).astype(">u4").view(np.uint8).reshape(-1, 32)


def compute_merkle_root_tpu(hashes: list[bytes]) -> tuple[bytes, bool]:
    """Drop-in for consensus.merkle.compute_merkle_root on large inputs.

    Returns (root, mutated). Device round-trips once per level; each level is
    one fused XLA computation over all pairs.
    """
    if not hashes:
        return b"\x00" * 32, False
    mutated = False
    level = _digests_to_words(
        np.frombuffer(b"".join(hashes), dtype=np.uint8).reshape(-1, 32)
    )
    while len(level) > 1:
        n = len(level)
        # Mutation check runs BEFORE odd-duplication (identical adjacent
        # nodes at even positions; the legitimate self-pair added below must
        # not flag) — same order as consensus/merkle.py and the reference.
        whole = n - (n & 1)
        mutated |= bool(
            np.any(np.all(level[0:whole:2] == level[1:whole:2], axis=1))
        )
        if n & 1:
            level = np.concatenate([level, level[-1:]], axis=0)
            n += 1
        left, right = level[0::2], level[1::2]
        pairs = np.concatenate([left, right], axis=1)  # (n/2, 16)
        n_pairs = len(pairs)
        padded = -(-n_pairs // PAD_LANES) * PAD_LANES
        if padded != n_pairs:
            pairs = np.concatenate(
                [pairs, np.zeros((padded - n_pairs, 16), dtype=np.uint32)], axis=0
            )
        out = np.asarray(_level_jit(jnp.asarray(pairs)))[:n_pairs]
        level = out
    return _words_to_digests(level)[0].tobytes(), mutated
