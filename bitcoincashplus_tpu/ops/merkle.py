"""TPU Merkle-root construction.

Replaces src/consensus/merkle.cpp:~45 (ComputeMerkleRoot)'s serial pairwise
loop with a lane-parallel tree reduction: each level hashes all digest pairs
at once (double-SHA of the 64-byte concatenation, 3 compressions), log2(n)
levels total (BASELINE.json config: 4k-tx snapshot -> 12 levels).

Consensus-exact odd handling: when a level has an odd node count the LAST
node is paired with itself (the CVE-2012-2459 duplication rule) — applied
per level on the host between device calls, never by power-of-two padding,
because padding changes the tree shape for non-pow2 counts.

Lane padding: each level is padded up to a multiple of PAD_LANES with
garbage pairs (masked out on the host) so recompilation is bounded by the
number of distinct padded sizes, not distinct tx counts (SURVEY.md §8.4
bucketing).

Also detects the known Merkle "mutation" (two identical consecutive hashes
forming a duplicated pair), which the reference surfaces via the *mutated
flag for CheckBlock's duplicate-tx rule.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .sha256 import sha256d_64

PAD_LANES = 128  # one VPU lane row; keeps distinct compiled shapes ~O(log n)


def _digests_to_words(digests: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 digests -> (N, 8) uint32 BE words."""
    return digests.reshape(-1, 8, 4).view(">u4").squeeze(-1).astype(np.uint32)


def _words_to_digests(words: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(words).astype(">u4").view(np.uint8).reshape(-1, 32)


@partial(jax.jit, static_argnums=(1,))
def _tree_reduce_jit(words, n_levels: int, m):
    """Whole-tree reduction in ONE dispatch.

    words: (2**n_levels, 8) u32 leaf digests, zero-padded past the true
    count ``m`` (dynamic scalar). Per level the garbage lanes compute
    harmlessly at full width; the consensus odd-duplication is a masked
    select (pair i takes left for right when 2i+1 >= m), so the compiled
    shape depends only on the pow2 bucket — never on the tx count. The
    mutation flag considers only whole pairs inside the live prefix,
    matching consensus/merkle.py's check-before-duplicate ordering.
    """
    level = [words[:, i] for i in range(8)]  # column-major: 8 arrays (B,)
    mutated = jnp.zeros((), dtype=bool)
    for k in range(n_levels):
        half = 1 << (n_levels - k - 1)
        pair_idx = jnp.arange(half, dtype=jnp.uint32)
        left = [c[0::2] for c in level]
        right = [c[1::2] for c in level]
        equal = jnp.ones((half,), dtype=bool)
        for l_col, r_col in zip(left, right):
            equal &= l_col == r_col
        live_pair = 2 * pair_idx + 1 < m  # both nodes inside the prefix
        mutated |= jnp.any(equal & live_pair)
        dup = 2 * pair_idx + 1 >= m  # odd tail (and dead lanes): self-pair
        right = [jnp.where(dup, l_col, r_col)
                 for l_col, r_col in zip(left, right)]
        hashed = sha256d_64(left + right)
        # the bucket can be taller than the real tree: once the live count
        # reaches 1 the root rides through untouched instead of being
        # self-hashed up the remaining levels
        done = m <= 1
        level = [jnp.where(done, l_col, h_col)
                 for l_col, h_col in zip(left, hashed)]
        m = jnp.where(done, m, (m + 1) // 2)
    return jnp.stack(level, axis=-1)[0], mutated


def compute_merkle_root_tpu(hashes: list[bytes]) -> tuple[bytes, bool]:
    """Drop-in for consensus.merkle.compute_merkle_root on large inputs.

    Returns (root, mutated). The whole log2(n)-level tree runs as a single
    device dispatch (dispatch latency dominated the old per-level loop —
    12 round-trips for 4k txids); compilation is bounded by the number of
    distinct pow2 buckets, not tx counts.
    """
    if not hashes:
        return b"\x00" * 32, False
    if len(hashes) == 1:
        return hashes[0], False
    n = len(hashes)
    bucket = max(PAD_LANES, 1 << (n - 1).bit_length())
    words = _digests_to_words(
        np.frombuffer(b"".join(hashes), dtype=np.uint8).reshape(-1, 32)
    )
    if bucket != n:
        words = np.concatenate(
            [words, np.zeros((bucket - n, 8), dtype=np.uint32)], axis=0
        )
    root_words, mutated = _tree_reduce_jit(
        jnp.asarray(words), bucket.bit_length() - 1, jnp.uint32(n)
    )
    root = np.asarray(root_words, dtype=np.uint32)
    return _words_to_digests(root[None, :])[0].tobytes(), bool(mutated)
