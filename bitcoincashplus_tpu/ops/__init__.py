"""TPU compute kernels (JAX/XLA/Pallas).

The node's two compute-bound subsystems (BASELINE.json north star):
  - sha256.py / miner.py / merkle.py — SHA-256d PoW search, batched header
    and Merkle hashing (replaces src/crypto/sha256*.cpp + the scalar nonce
    loop in src/rpc/mining.cpp:~120 (generateBlocks)).
  - secp256k1.py / ecdsa_batch.py — vectorized batch ECDSA verification
    (replaces src/secp256k1 + CCheckQueue fan-out).

Everything here is pure-functional and jit-compatible; host orchestration
lives in validation/ and mining/.
"""
