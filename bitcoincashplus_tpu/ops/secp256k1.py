"""Vectorized secp256k1 batch ECDSA verification (jnp core).

Replaces the per-input secp256k1_ecdsa_verify calls fanned out by
CCheckQueue (src/checkqueue.h:~30 + src/secp256k1.c:~340) with one
lane-parallel dispatch: every VPU lane verifies one signature.

Design (SURVEY.md §8.4 "ECDSA batch"):
  - Field elements mod p live as (20, B) uint32 arrays: 20 limbs x 13 bits,
    limb-major so every op is elementwise over the lane (batch) axis.
    13-bit limbs make schoolbook products (< 2^26) directly accumulable in
    u32: a 20-term column sum stays under 2^31 with NO carry splitting —
    the reference's 5x52/10x26 limb choice (field_5x52_impl.h /
    field_10x26_impl.h) re-derived for a 32-bit-lane machine with no carry
    flag and no widening multiply.
  - Compact traces: carry sweeps are lax.scan over the limb axis and the
    schoolbook product is a lax.fori_loop of dynamic-slice adds, so the
    whole 256-step verify loop compiles in seconds (a fully unrolled SoA
    form measured 15s of XLA compile per single field-mul — unusable).
  - Magnitude discipline (stated per function):
      "weak"  = 13-bit limbs (top limb <= 0x1FF + eps), value < p + 2^33
      "loose" = limbs < 2^15 (add/sub outputs) — f_carry before multiplying
  - Jacobian points, branchless-complete add/double via jnp.where selects.
  - Verify needs NO field inversion: u1*G + u2*Q is compared via
    X_R == (r + k*n) * Z_R^2 for k in {0,1} (x-wraparound case included).
  - Scalar work mod n (w = s^-1, u1 = e*w, u2 = r*w) runs on the HOST with
    Python ints (ops/ecdsa_batch.py) — O(batch) microseconds.

Differentially tested against crypto/secp256k1.py (the Python-int oracle).
"""

from __future__ import annotations

import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.secp256k1 import GX, GY, N, P

LIMB_BITS = 13
N_LIMBS = 20  # 20*13 = 260 bits
MASK = np.uint32((1 << LIMB_BITS) - 1)
U32_0 = np.uint32(0)

# p = 2^256 - C with C = 2^32 + 977:
#   2^256 == C                   (mod p)
#   2^260 == 16C = 2^36 + 15632  (mod p);  2^36 = 2^(13*2 + 10)
_FOLD_LO = np.uint32(15632)


def to_limbs_np(x: int) -> np.ndarray:
    return np.array(
        [(x >> (LIMB_BITS * i)) & int(MASK) for i in range(N_LIMBS)],
        dtype=np.uint32,
    )


def from_limbs_np(limbs) -> int:
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(np.asarray(limbs)))


def pack_batch_np(values: list[int]) -> np.ndarray:
    """list of ints -> (20, B) uint32."""
    return np.stack([to_limbs_np(v) for v in values], axis=-1)


def _const(value: int) -> np.ndarray:
    """(20, 1) constant, broadcastable against (20, B)."""
    return to_limbs_np(value).reshape(N_LIMBS, 1)


# Subtraction bias: 2p redistributed so every limb i<19 is >= 2^13 and limb
# 19 >= 0x1FF + 1 — (a + BIAS - b) is limbwise non-negative for weak a, b.
def _make_bias() -> np.ndarray:
    l = [int(v) for v in to_limbs_np(2 * P)]
    for i in range(N_LIMBS - 1):
        l[i] += 1 << LIMB_BITS
        l[i + 1] -= 1
    assert all(v >= (1 << LIMB_BITS) for v in l[:-1]) and l[-1] > 0x1FF
    assert sum(v << (LIMB_BITS * i) for i, v in enumerate(l)) == 2 * P
    return np.array(l, dtype=np.uint32).reshape(N_LIMBS, 1)


_BIAS_2P = _make_bias()


# ---- carry & reduction ----

def _sweep(limbs):
    """Carry-propagate along axis 0 (any u32 magnitudes < 2^31 + 2^19).
    Returns (13-bit limbs, carry) — carry < 2^19 at weight 2^(13*L)."""

    def body(carry, row):
        v = row + carry
        return v >> np.uint32(LIMB_BITS), v & MASK

    # init derived from the input so it stays chip-varying under shard_map
    # (an invariant jnp.zeros init trips the scan carry-vma check there)
    carry, out = jax.lax.scan(body, limbs[0] * U32_0, limbs)
    return out, carry


def _fold_260(lo, hi):
    """lo: (20, B) limbs (any magnitude < 2^30); hi: (H, B) 13-bit limbs at
    weights 2^(13*(20+j)). Folds hi in via 2^260 == 2^36 + 15632. Returns
    (max(20, H+2), B) with limbs < 2^31. Requires H + 2 <= 20 + H."""
    h_len = hi.shape[0]
    width = max(lo.shape[0], h_len + 2)
    zero = jnp.zeros((width - lo.shape[0],) + lo.shape[1:], dtype=lo.dtype)
    out = jnp.concatenate([lo, zero], axis=0)
    pr = hi * _FOLD_LO  # < 2^13 * 2^14 = 2^27
    out = out.at[0:h_len].add(pr & MASK)
    out = out.at[1 : h_len + 1].add(pr >> np.uint32(LIMB_BITS))
    out = out.at[2 : h_len + 2].add(hi << np.uint32(10))  # < 2^23
    return out


def _weaken(limbs20):
    """256-bit-boundary fold: bits >= 2^256 (top limb >> 9) fold down by
    C = 2^32 + 977 (977 at limb 0; 2^32 -> limb 2, factor 2^6). Input 13-bit
    normalized; output weak (top limb <= 0x1FF, early limbs may carry +1)."""
    h = limbs20[19] >> np.uint32(9)  # < 2^4
    out = limbs20.at[19].set(limbs20[19] & np.uint32(0x1FF))
    out = out.at[0].add(h * np.uint32(977))
    out = out.at[2].add(h << np.uint32(6))
    head, carry = _sweep(out[:5])
    out = jnp.concatenate([head, out[5:6] + carry, out[6:]], axis=0)
    return out


def field_parallel() -> bool:
    """Device path: fully parallel field ops (no scan/fori, no dynamic
    slicing). The compact looped forms below exist because unrolled code is
    compile-hostile on the XLA CPU backend; on TPU they are catastrophic at
    RUN time instead — each fori iteration's read-modify-write of the
    (39, B) accumulator materializes a full buffer copy through HBM
    (measured ~42us per inner iteration at B=16384, ~1M loop iterations per
    verify dispatch — the kernel was copy-bound at ~0.3% ALU utilization).
    Overridable via BCP_SECP_PARALLEL for differential testing."""
    override = os.environ.get("BCP_SECP_PARALLEL")
    if override is not None:
        return override not in ("0", "false", "")
    from .sha256 import backend_is_cpu

    return not backend_is_cpu()


def _pcarry_round(v):
    """One parallel carry round: out[j] = (v[j] & MASK) + (v[j-1] >> 13).
    Width grows by one row (the top carry). From any magnitude < 2^31,
    three rounds converge to limbs <= 2^13 + 2:
        R1 <= 2^13-1 + 2^18,  R2 <= 2^13-1 + 2^5.1,  R3 <= 2^13 + 2."""
    z1 = jnp.zeros_like(v[:1])
    return (
        jnp.concatenate([v & MASK, z1], axis=0)
        + jnp.concatenate([z1, v >> np.uint32(LIMB_BITS)], axis=0)
    )


def _carry3(v):
    for _ in range(3):
        v = _pcarry_round(v)
    return v


def _pad_rows(x, before: int, width: int):
    pad = ((before, width - before - x.shape[0]),) + ((0, 0),) * (x.ndim - 1)
    return jnp.pad(x, pad)


def _fold_parallel(v):
    """Static-shape fold of rows >= 20 via 2^260 == 2^36 + 15632 (same
    relation as _fold_260, no .at/dynamic ops). Rows must be <= 2^13 + eps
    so hi * 15632 stays < 2^27."""
    if v.shape[0] <= N_LIMBS:
        return v
    lo, hi = v[:N_LIMBS], v[N_LIMBS:]
    width = max(N_LIMBS, hi.shape[0] + 2)
    pr = hi * _FOLD_LO
    return (
        _pad_rows(lo, 0, width)
        + _pad_rows(pr & MASK, 0, width)
        + _pad_rows(pr >> np.uint32(LIMB_BITS), 1, width)
        + _pad_rows(hi << np.uint32(10), 2, width)
    )


def _weaken_parallel(limbs20):
    """_weaken without the head sweep: parallel rounds over rows 0..4,
    carry landing in row 5 (same contract: early limbs may carry +eps)."""
    h = limbs20[19] >> np.uint32(9)
    top = limbs20[19:20] & np.uint32(0x1FF)
    head = jnp.concatenate(
        [
            limbs20[0:1] + h * np.uint32(977),
            limbs20[1:2],
            limbs20[2:3] + (h << np.uint32(6)),
            limbs20[3:5],
        ],
        axis=0,
    )
    head = _pcarry_round(_pcarry_round(head))  # (7, B), rows <= 2^13 + eps
    return jnp.concatenate(
        [head[:5], limbs20[5:6] + head[5] + (head[6] << np.uint32(LIMB_BITS)),
         limbs20[6:19], top],
        axis=0,
    )


def _f_carry_parallel(limbs) -> jnp.ndarray:
    """Parallel-form normalize: {3 carry rounds; fold} x 3 + weaken.
    Width trajectory from 39: 42 -> fold 24 -> 27 -> fold 20 -> 23 ->
    fold 20 -> 23 -> final fold/trim 20."""
    v = limbs
    for _ in range(3):
        v = _fold_parallel(_carry3(v))
    v = _fold_parallel(_carry3(v))
    v = _carry3(v)
    v = _fold_parallel(v)[:N_LIMBS]
    return _weaken_parallel(v)


def f_carry(limbs) -> jnp.ndarray:
    """Normalize any accumulation ((L, B), limbs < 2^31, L in [20, 39]) to
    weak form. Each round: sweep to 13-bit (+carry), fold positions >= 20
    via 2^260 == 16C. Length trajectory 39 -> 23 -> 20 -> 20; the fixed
    round count always settles."""
    if field_parallel():
        return _f_carry_parallel(limbs)
    for _ in range(3):
        norm, carry = _sweep(limbs)
        hi = jnp.stack([carry & MASK, carry >> np.uint32(LIMB_BITS)], axis=0)
        if norm.shape[0] > N_LIMBS:
            hi = jnp.concatenate([norm[N_LIMBS:], hi], axis=0)
        limbs = _fold_260(norm[:N_LIMBS], hi)
    norm, carry = _sweep(limbs)
    # value < 2^260 by construction now; carry is structurally zero but is
    # folded anyway (no-op when zero) instead of asserting on a traced value
    hi = jnp.stack([carry & MASK, carry >> np.uint32(LIMB_BITS)], axis=0)
    limbs = _fold_260(norm[:N_LIMBS], hi)[:N_LIMBS]
    norm, _ = _sweep(limbs)
    return _weaken(norm)


def f_mul(a, b) -> jnp.ndarray:
    """(20,B) x (20,B) schoolbook; REQUIRES weak inputs. Products < 2^26+eps,
    20-term column sums < 2^31. Output weak."""
    if field_parallel():
        # static diagonal accumulation: 20 shifted adds, zero dynamic ops
        cols = None
        for i in range(N_LIMBS):
            t = _pad_rows(a[i] * b, i, 2 * N_LIMBS - 1)
            cols = t if cols is None else cols + t
        return f_carry(cols)
    width = 2 * N_LIMBS - 1
    shape = (width,) + tuple(np.broadcast_shapes(a.shape[1:], b.shape[1:]))
    # varying-safe zero init (see _sweep)
    cols0 = jnp.zeros(shape, dtype=jnp.uint32) + (a[0] * b[0] * U32_0)

    def body(i, cols):
        ai = jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=True)  # (1, B)
        return jax.lax.dynamic_update_slice_in_dim(
            cols,
            jax.lax.dynamic_slice_in_dim(cols, i, N_LIMBS, 0) + ai * b,
            i,
            0,
        )

    cols = jax.lax.fori_loop(0, N_LIMBS, body, cols0)
    return f_carry(cols)


def f_sqr(a) -> jnp.ndarray:
    return f_mul(a, a)


def f_add(a, b):
    """Limbwise add of weak values -> 'loose' (limbs < 2^14 + eps)."""
    return a + b


def f_sub(a, b):
    """(a - b) + 2p via the redistributed bias; weak inputs -> 'loose'."""
    return a + _BIAS_2P - b


def f_carry_sub(a, b):
    return f_carry(f_sub(a, b))


# ---- canonical form & comparisons ----

def _f_ge(a, b):
    """a >= b, MSB-first lexicographic over 13-bit-normalized (20,B) limbs."""

    def body(state, rows):
        gt, eq = state
        ai, bi = rows
        gt = gt | (eq & (ai > bi))
        eq = eq & (ai == bi)
        return (gt, eq), None

    init = (a[0] > a[0], a[0] == a[0])  # varying-safe (False…, True…)
    (gt, eq), _ = jax.lax.scan(body, init, (a[::-1], b[::-1]))
    return gt | eq


def _f_sub_exact(a, b):
    """a - b for normalized limbs with a >= b (borrow scan)."""

    def body(borrow, rows):
        ai, bi = rows
        v = ai - bi - borrow
        under = (v >> np.uint32(31)).astype(bool)
        out = jnp.where(under, v + np.uint32(1 << LIMB_BITS), v)
        return under.astype(jnp.uint32), out

    _, out = jax.lax.scan(body, a[0] * U32_0, (a, b))
    return out


_P_CONST = _const(P)
_ONE_CONST = _const(1)


def f_canonical(a_weak):
    """Weak (< 2p) -> canonical [0, p): one conditional subtract of p."""
    p_limbs = jnp.broadcast_to(_P_CONST, a_weak.shape).astype(jnp.uint32)
    ge = _f_ge(a_weak, p_limbs)
    sub = _f_sub_exact(a_weak, p_limbs)
    return jnp.where(ge, sub, a_weak)


def _exact_norm20(v):
    """Weak (20,B) -> EXACT 13-bit limbs (unique representation).

    20 parallel single-carry rounds: a carry unit ripples at most one row
    per round, and from weak input every row is <= MASK + 1 after round 1,
    so 20 rounds fully settle. Row-19 overflow is impossible (weak top
    limb <= 0x1FF + eps, value < p + 2^33 < 2^257). Scan-free on purpose:
    this runs inside the Pallas verify kernel where lax.scan cannot lower."""
    for _ in range(N_LIMBS):
        c = v >> np.uint32(LIMB_BITS)
        v = (v & MASK) + jnp.concatenate(
            [jnp.zeros_like(c[:1]), c[:-1]], axis=0
        )
    return v


def f_is_zero(a_weak, keepdims: bool = False):
    if field_parallel():
        # exact normalization, then value in {0, p} <=> zero mod p
        # (weak value < p + 2^33 < 2p, and the 13-bit form is unique)
        v = _exact_norm20(a_weak)
        p_limbs = jnp.broadcast_to(_P_CONST, v.shape).astype(jnp.uint32)
        z0 = jnp.all(v == 0, axis=0, keepdims=keepdims)
        zp = jnp.all(v == p_limbs, axis=0, keepdims=keepdims)
        return z0 | zp
    return jnp.all(f_canonical(a_weak) == 0, axis=0, keepdims=keepdims)


def f_eq(a_weak, b_weak, keepdims: bool = False):
    return f_is_zero(f_carry_sub(a_weak, b_weak), keepdims=keepdims)


# ---- Jacobian point ops ----
# Point: dict {X, Y, Z: (20,B) weak, inf: (B,) bool}. Coordinate garbage
# under inf=True is never semantically read (selects gate it).

def pt_infinity(batch: int) -> dict:
    one = jnp.broadcast_to(_const(1), (N_LIMBS, batch)).astype(jnp.uint32)
    return {
        "X": one,
        "Y": one,
        "Z": jnp.zeros((N_LIMBS, batch), jnp.uint32),
        "inf": jnp.ones((batch,), bool),
    }


def pt_select(mask, t: dict, f: dict) -> dict:
    return {
        "X": jnp.where(mask, t["X"], f["X"]),
        "Y": jnp.where(mask, t["Y"], f["Y"]),
        "Z": jnp.where(mask, t["Z"], f["Z"]),
        "inf": jnp.where(mask, t["inf"], f["inf"]),
    }


def pt_double(pt: dict) -> dict:
    """Jacobian doubling on y² = x³ + 7 (a = 0) — dbl-2009-l:
    A=X², B=Y², C=B², D=2((X+B)²−A−C), E=3A, F=E²,
    X3=F−2D, Y3=E(D−X3)−8C, Z3=2YZ.
    secp256k1 has no 2-torsion (Y=0 unreachable on-curve), so doubling a
    finite point never lands at infinity — inf propagates unchanged (same
    argument as group_impl.h secp256k1_gej_double)."""
    X, Y, Z = pt["X"], pt["Y"], pt["Z"]
    A = f_sqr(X)
    Bb = f_sqr(Y)
    Cc = f_sqr(Bb)
    D = f_sqr(f_carry(f_add(X, Bb)))
    D = f_carry_sub(D, f_carry(f_add(A, Cc)))
    D = f_carry(f_add(D, D))
    E = f_carry(f_add(f_add(A, A), A))
    F = f_sqr(E)
    X3 = f_carry_sub(F, f_carry(f_add(D, D)))
    Y3 = f_mul(E, f_carry_sub(D, X3))
    C4 = f_carry(f_add(f_add(Cc, Cc), f_add(Cc, Cc)))
    C8 = f_carry(f_add(C4, C4))
    Y3 = f_carry_sub(Y3, C8)
    YZ = f_mul(Y, Z)
    Z3 = f_carry(f_add(YZ, YZ))
    return {"X": X3, "Y": Y3, "Z": Z3, "inf": pt["inf"]}


def pt_add_mixed(pt: dict, qx, qy, q_inf, mask2d: bool = False) -> dict:
    """P (Jacobian) + Q (affine), complete via selects — the branchless
    analogue of secp256k1_gej_add_ge_var's case analysis:
      P=inf -> Q;  Q=inf -> P;  P==Q -> double(P);  P==-Q -> infinity.
    madd: Z1Z1=Z², U2=qx·Z1Z1, S2=qy·Z·Z1Z1, H=U2−X, R=S2−Y,
    HH=H², HHH=H·HH, V=X·HH, X3=R²−HHH−2V, Y3=R(V−X3)−Y·HHH, Z3=Z·H.
    mask2d: masks (incl. q_inf and pt['inf']) are (1,B) instead of (B,) —
    the Pallas kernel path, where 1D vectors don't lower well."""
    X, Y, Z = pt["X"], pt["Y"], pt["Z"]
    Z1Z1 = f_sqr(Z)
    U2 = f_mul(qx, Z1Z1)
    S2 = f_mul(qy, f_mul(Z, Z1Z1))
    H = f_carry_sub(U2, X)
    R = f_carry_sub(S2, Y)
    h_zero = f_is_zero(H, keepdims=mask2d)
    r_zero = f_is_zero(R, keepdims=mask2d)
    finite_both = ~pt["inf"] & ~q_inf
    same = h_zero & r_zero & finite_both
    opposite = h_zero & ~r_zero & finite_both
    HH = f_sqr(H)
    HHH = f_mul(H, HH)
    V = f_mul(X, HH)
    X3 = f_carry_sub(f_sqr(R), f_carry(f_add(HHH, f_carry(f_add(V, V)))))
    Y3 = f_carry_sub(f_mul(R, f_carry_sub(V, X3)), f_mul(Y, HHH))
    Z3 = f_mul(Z, H)
    out = {"X": X3, "Y": Y3, "Z": Z3, "inf": opposite}

    out = pt_select(same, pt_double(pt), out)
    q_as_jac = {
        "X": jnp.broadcast_to(qx, X.shape).astype(jnp.uint32),
        "Y": jnp.broadcast_to(qy, X.shape).astype(jnp.uint32),
        "Z": jnp.broadcast_to(_ONE_CONST, X.shape).astype(jnp.uint32),
        "inf": q_inf,
    }
    out = pt_select(pt["inf"], q_as_jac, out)
    out = pt_select(q_inf & ~pt["inf"], pt, out)
    return out


# ---- batched u1*G + u2*Q and the verify equation ----

_GX_CONST = _const(GX)
_GY_CONST = _const(GY)


def ecdsa_verify_batch_device(u1_bits, u2_bits, qx, qy, q_inf, r0, rn,
                              wrap_ok):
    """u1_bits/u2_bits: (256, B) uint32 in {0,1}, MSB first. qx/qy/r0/rn:
    (20, B) weak limbs. q_inf: (B,) poison mask (malformed pubkey lanes).
    wrap_ok: (B,) bool — True iff r + n < p, i.e. the x-coordinate
    wraparound candidate rn = r + n is admissible. The reference
    (secp256k1_ecdsa_sig_verify, ecdsa_impl.h) only retries the +n
    candidate under that bound; accepting X == rn·Z² without the gate
    would falsely accept signatures with x_R = r + n - p. The gate is
    enforced HERE, in-kernel, so a host layer cannot mis-use rn.
    Returns (B,) bool validity.

    MSB-first joint double-and-add: 256 x (double + 2 select-merged mixed
    adds) — no data-dependent control flow; poisoned lanes compute garbage
    and report False."""
    batch = qx.shape[1]
    gx = jnp.broadcast_to(_GX_CONST, (N_LIMBS, batch)).astype(jnp.uint32)
    gy = jnp.broadcast_to(_GY_CONST, (N_LIMBS, batch)).astype(jnp.uint32)
    never_inf = jnp.zeros((batch,), bool)

    def step(i, acc):
        acc = pt_double(acc)
        with_g = pt_add_mixed(acc, gx, gy, never_inf)
        acc = pt_select(u1_bits[i].astype(bool), with_g, acc)
        with_q = pt_add_mixed(acc, qx, qy, q_inf)
        acc = pt_select(u2_bits[i].astype(bool) & ~q_inf, with_q, acc)
        return acc

    # infinity init derived from qx/q_inf so the fori_loop carry stays
    # chip-varying under shard_map (parallel/sig_shard)
    zero_v = qx * U32_0
    acc0 = {
        "X": zero_v + _const(1),
        "Y": zero_v + _const(1),
        "Z": zero_v,
        "inf": q_inf | (q_inf == q_inf),  # all True, varying
    }
    acc = jax.lax.fori_loop(0, 256, step, acc0)

    ZZ = f_sqr(acc["Z"])
    ok0 = f_eq(acc["X"], f_mul(r0, ZZ))
    ok1 = f_eq(acc["X"], f_mul(rn, ZZ)) & wrap_ok
    return ~acc["inf"] & ~q_inf & (ok0 | ok1)


@jax.jit
def ecdsa_verify_batch_jit(u1_bits, u2_bits, qx, qy, q_inf, r0, rn, wrap_ok):
    return ecdsa_verify_batch_device(
        u1_bits, u2_bits, qx, qy, q_inf, r0, rn, wrap_ok
    )


# ---- Pallas verify kernel ---------------------------------------------------

def _build_const_limbs(value_limbs, shape):
    """Build a limb-constant array INSIDE a Pallas kernel: Mosaic forbids
    captured array constants, so the (20, ...) pattern is synthesized from
    scalar literals with an iota row select (traces to ~20 where-ops, run
    once per tile)."""
    rows = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    out = jnp.zeros(shape, jnp.uint32)
    for i, limb in enumerate(value_limbs):
        if int(limb):
            out = out + jnp.where(
                rows == np.uint32(i), np.uint32(int(limb)), U32_0
            )
    return out


class _KernelConsts:
    """Swap the module's numpy limb constants for in-kernel-built arrays
    while the Pallas kernel traces (f_sub reads _BIAS_2P, f_is_zero reads
    _P_CONST as module globals). Built at full (20, *lanes) width — lane-1
    arrays trip Mosaic layout assertions on multi-step grids. ``lanes`` is
    an int (2D tile width) or a shape tuple (the 3D kernel's (8, T))."""

    def __init__(self, lanes):
        self.lanes = (lanes,) if isinstance(lanes, int) else tuple(lanes)

    def __enter__(self):
        global _BIAS_2P, _P_CONST, _ONE_CONST
        self._old = (_BIAS_2P, _P_CONST, _ONE_CONST)
        shape = (N_LIMBS,) + self.lanes
        _BIAS_2P = _build_const_limbs(
            [int(v) for v in self._old[0][:, 0]], shape
        )
        _P_CONST = _build_const_limbs(to_limbs_np(P), shape)
        _ONE_CONST = _build_const_limbs([1], shape)
        return self

    def __exit__(self, *exc):
        global _BIAS_2P, _P_CONST, _ONE_CONST
        _BIAS_2P, _P_CONST, _ONE_CONST = self._old


# Kernel-side mask algebra: Mosaic cannot carry/select i1 (bool) VECTORS as
# data ("Unsupported target bitwidth for truncation"), so inside the kernel
# every mask — including the point's `inf` flag — is an int32 0/1 plane;
# booleans exist only transiently as select predicates (`mask != 0`).

def _is_zero_u(a_weak):
    """f_is_zero, int32-mask form: (1,B) 0/1. Exact normalization then
    value in {0, p} (min-reduce of equality indicators; int32 because
    Mosaic lacks unsigned reductions)."""
    v = _exact_norm20(a_weak)
    p_l = jnp.broadcast_to(_P_CONST, v.shape).astype(jnp.uint32)
    z0 = jnp.min(jnp.where(v == 0, 1, 0).astype(jnp.int32),
                 axis=0, keepdims=True)
    zp = jnp.min(jnp.where(v == p_l, 1, 0).astype(jnp.int32),
                 axis=0, keepdims=True)
    return jnp.maximum(z0, zp)


def _pt_select_u(mask_u, t: dict, f: dict) -> dict:
    pred = mask_u != 0
    return {
        "X": jnp.where(pred, t["X"], f["X"]),
        "Y": jnp.where(pred, t["Y"], f["Y"]),
        "Z": jnp.where(pred, t["Z"], f["Z"]),
        "inf": jnp.where(pred, t["inf"], f["inf"]),
    }


# (The round-3 bit-at-a-time Pallas ladder — _verify_core_2d /
# ecdsa_verify_batch_pallas — was removed in round 4: the w=4 windowed
# kernels below replaced it in dispatch and nothing else consumed it.
# The XLA bit-ladder form ecdsa_verify_batch_jit above remains as the
# compile-failure fallback and the mesh-sharded path.)

# Mosaic on this toolchain rejects >128-LANE tiles; small (<=128-lane)
# batches run the 2D kernel in one 128-lane tile, and the 2D wrapper
# splits anything larger into <=4096-lane jit programs (the 3D byte
# pipeline below is the production path for those).
_PALLAS_TILE = 128
_PALLAS_SUPER = 4096

# ---- w=4 windowed Pallas verify kernel (round 4) --------------------------
#
# The bit-at-a-time ladder above costs, per scalar bit, 1 explicit double +
# 2 complete mixed adds — and each COMPLETE add internally computes another
# pt_double for its `same` select plus two exact-norm zero tests. The
# windowed form replaces that with, per 4 bits: 4 doubles + ONE add from a
# 15-entry G table (affine, compile-time constants) + ONE add from a
# 15-entry per-lane Q table (Jacobian, built per batch) — ~3x fewer
# field-mul-equivalents.
#
# Completeness moves OFF the chip: the cheap adds omit the `same`/`opposite`
# case analysis entirely. An H == 0 collision between finite points means
# acc == +/-(table entry), which an adversary CAN engineer (pick Q = kG with
# known k and solve the prefix relation), so the kernel FLAGS the lane
# (degen plane) and the host re-verifies it on the scalar CPU path. The
# attacker gains nothing: a crafted collision costs them a whole signature
# slot to push one lane onto the CPU verify the reference runs for every
# signature anyway. Flagged-lane results are never trusted: garbage
# coordinates (Z3 = Z*H = 0 onward) are overridden by the host re-check.

def _pt_add_mixed_cheap_u(pt: dict, qx, qy, q_inf_u, one):
    """madd core with NO same/opposite resolution: returns (point, hzero)
    where hzero is the (1, B) int32 H == 0 indicator between two finite
    points (caller turns it into a degenerate-lane flag). One exact-norm
    (vs 2) and no internal double (vs 1) compared to _pt_add_mixed_u."""
    X, Y, Z = pt["X"], pt["Y"], pt["Z"]
    Z1Z1 = f_sqr(Z)
    U2 = f_mul(qx, Z1Z1)
    S2 = f_mul(qy, f_mul(Z, Z1Z1))
    H = f_carry_sub(U2, X)
    R = f_carry_sub(S2, Y)
    finite_both = (1 - pt["inf"]) * (1 - q_inf_u)
    hzero = _is_zero_u(H) * finite_both
    HH = f_sqr(H)
    HHH = f_mul(H, HH)
    V = f_mul(X, HH)
    X3 = f_carry_sub(f_sqr(R), f_carry(f_add(HHH, f_carry(f_add(V, V)))))
    Y3 = f_carry_sub(f_mul(R, f_carry_sub(V, X3)), f_mul(Y, HHH))
    Z3 = f_mul(Z, H)
    out = {"X": X3, "Y": Y3, "Z": Z3,
           "inf": jnp.zeros_like(pt["inf"])}
    q_as_jac = {
        "X": jnp.broadcast_to(qx, X.shape).astype(jnp.uint32),
        "Y": jnp.broadcast_to(qy, X.shape).astype(jnp.uint32),
        "Z": one,
        "inf": q_inf_u,
    }
    out = _pt_select_u(pt["inf"], q_as_jac, out)
    out = _pt_select_u(q_inf_u * (1 - pt["inf"]), pt, out)
    return out, hzero


def _pt_add_full_cheap_u(pt: dict, q: dict):
    """Full Jacobian + Jacobian cheap add (table entries have Z != 1), same
    no-completeness contract as _pt_add_mixed_cheap_u."""
    X1, Y1, Z1 = pt["X"], pt["Y"], pt["Z"]
    X2, Y2, Z2 = q["X"], q["Y"], q["Z"]
    Z1Z1 = f_sqr(Z1)
    Z2Z2 = f_sqr(Z2)
    U1 = f_mul(X1, Z2Z2)
    U2 = f_mul(X2, Z1Z1)
    S1 = f_mul(Y1, f_mul(Z2, Z2Z2))
    S2 = f_mul(Y2, f_mul(Z1, Z1Z1))
    H = f_carry_sub(U2, U1)
    R = f_carry_sub(S2, S1)
    finite_both = (1 - pt["inf"]) * (1 - q["inf"])
    hzero = _is_zero_u(H) * finite_both
    HH = f_sqr(H)
    HHH = f_mul(H, HH)
    V = f_mul(U1, HH)
    X3 = f_carry_sub(f_sqr(R), f_carry(f_add(HHH, f_carry(f_add(V, V)))))
    Y3 = f_carry_sub(f_mul(R, f_carry_sub(V, X3)), f_mul(S1, HHH))
    Z3 = f_mul(f_mul(Z1, Z2), H)
    out = {"X": X3, "Y": Y3, "Z": Z3, "inf": jnp.zeros_like(pt["inf"])}
    out = _pt_select_u(pt["inf"], q, out)
    out = _pt_select_u(q["inf"] * (1 - pt["inf"]), pt, out)
    return out, hzero


def _tab_select_u(win, tab: list) -> dict:
    """Branchless 15-way table read: tab[j] for j = win in 1..15 (win == 0
    lanes get tab[1]; the caller masks the add out). ~45 cheap vector
    selects vs the hundreds of ops in one field-mul."""
    out = {k: tab[1][k] for k in ("X", "Y", "Z", "inf")}
    for j in range(2, 16):
        pred = win == j
        e = tab[j]
        out = {
            "X": jnp.where(pred, e["X"], out["X"]),
            "Y": jnp.where(pred, e["Y"], out["Y"]),
            "Z": jnp.where(pred, e["Z"], out["Z"]),
            "inf": jnp.where(pred, e["inf"], out["inf"]),
        }
    return out


def _w4_tables(qx, qy, q_inf_u, one, shape):
    """The w4 core's tables. G table: jG for j = 1..15 as affine
    compile-time constants (synthesized in-kernel — Mosaic forbids
    captured arrays; Python ints at trace time). Q table: jQ for
    j = 1..15, Jacobian, built with cheap adds — collisions in the build
    need (j-1)Q = +/-Q with 3 <= j <= 15, impossible in a prime-order
    group, so no degeneracy tracking here; j = 2 uses the double
    (1Q + 1Q IS the `same` case). Split out of _verify_core_w4 so the
    roofline op census (tools/roofline.py --ecdsa) can cost the table
    build separately from the ladder."""
    from ..crypto.secp256k1 import G, point_add

    g_tab = [None]
    pt = G
    for j in range(1, 16):
        g_tab.append((
            _build_const_limbs(to_limbs_np(pt[0]), shape),
            _build_const_limbs(to_limbs_np(pt[1]), shape),
        ))
        pt = point_add(pt, G) if j < 15 else pt

    q_jac = {
        "X": jnp.broadcast_to(qx, shape).astype(jnp.uint32),
        "Y": jnp.broadcast_to(qy, shape).astype(jnp.uint32),
        "Z": one,
        "inf": q_inf_u,
    }
    q_tab = [None, q_jac, pt_double(q_jac)]
    for j in range(3, 16):
        added, _hz = _pt_add_mixed_cheap_u(q_tab[j - 1], qx, qy, q_inf_u, one)
        q_tab.append(added)
    return g_tab, q_tab


def _w4_window_step(carry, w1, w2, g_tab, q_tab, q_inf_u, one, never_inf):
    """One w4 window: 4 doublings + select-merged G (mixed) and Q (full)
    adds. w1/w2 are (1, *lanes) int32 window values in 0..15."""
    acc, degen = carry
    acc = pt_double(pt_double(pt_double(pt_double(acc))))
    # G leg: mixed add from the constant affine table
    gx_sel, gy_sel = g_tab[1]
    for j in range(2, 16):
        pred = w1 == j
        gx_sel = jnp.where(pred, g_tab[j][0], gx_sel)
        gy_sel = jnp.where(pred, g_tab[j][1], gy_sel)
    act1 = jnp.where(w1 != 0, 1, 0)
    added, hz = _pt_add_mixed_cheap_u(acc, gx_sel, gy_sel, never_inf, one)
    acc = _pt_select_u(act1, added, acc)
    degen = jnp.maximum(degen, hz * act1)
    # Q leg: full add from the per-lane Jacobian table
    q_sel = _tab_select_u(w2, q_tab)
    act2 = jnp.where(w2 != 0, 1, 0) * (1 - q_inf_u)
    added, hz = _pt_add_full_cheap_u(acc, q_sel)
    acc = _pt_select_u(act2, added, acc)
    degen = jnp.maximum(degen, hz * act2)
    return acc, degen


def _verify_final(acc, degen, q_inf_u, r0, rn, wrap2):
    """Shared verify-equation epilogue (w4 and GLV cores): X_R == r·Z²
    for r in {r0, rn}, the rn candidate gated by wrap_ok."""
    ZZ = f_sqr(acc["Z"])
    ok0 = _is_zero_u(f_carry_sub(acc["X"], f_mul(r0, ZZ)))
    ok1 = (
        _is_zero_u(f_carry_sub(acc["X"], f_mul(rn, ZZ)))
        * wrap2.astype(jnp.int32)
    )
    ok = (1 - acc["inf"]) * (1 - q_inf_u) * jnp.maximum(ok0, ok1)
    return ok, degen * (1 - q_inf_u)


def _verify_core_w4(get_w1, get_w2, qx, qy, q_inf2, r0, rn, wrap2):
    """Windowed ecdsa verify core: window planes are (64, *lanes) int32
    values in 0..15, MSB-first. Lane axes are generic: (B,) for the 2D
    kernel, (8, T) for the aligned 3D kernel. Returns (ok, degen) as
    (1, *lanes) int32 0/1 planes — degen lanes carry garbage and MUST be
    re-verified by the caller."""
    lanes = qx.shape[1:]
    shape = (N_LIMBS,) + lanes
    one = _build_const_limbs([1], shape)
    q_inf_u = q_inf2.astype(jnp.int32)
    never_inf = jnp.zeros((1,) + lanes, jnp.int32)

    g_tab, q_tab = _w4_tables(qx, qy, q_inf_u, one, shape)

    zero_v = qx * U32_0
    acc0 = {
        "X": zero_v + one,
        "Y": zero_v + one,
        "Z": zero_v,
        "inf": jnp.ones((1,) + lanes, jnp.int32) * (1 + q_inf_u * 0),
    }
    degen0 = jnp.zeros((1,) + lanes, jnp.int32)

    def wstep(i, carry):
        w1 = get_w1(i).astype(jnp.int32)
        w2 = get_w2(i).astype(jnp.int32)
        return _w4_window_step(carry, w1, w2, g_tab, q_tab, q_inf_u, one,
                               never_inf)

    acc, degen = jax.lax.fori_loop(0, 64, wstep, (acc0, degen0))
    return _verify_final(acc, degen, q_inf_u, r0, rn, wrap2)


def _verify_kernel_w4(u1w_ref, u2w_ref, qx_ref, qy_ref, qinf_ref, r0_ref,
                      rn_ref, wrap_ref, out_ref):
    from jax.experimental import pallas as pl

    with _KernelConsts(u1w_ref.shape[1]):
        ok, degen = _verify_core_w4(
            lambda i: u1w_ref[pl.ds(i, 1), :],
            lambda i: u2w_ref[pl.ds(i, 1), :],
            qx_ref[...], qy_ref[...], qinf_ref[0:1, :],
            r0_ref[...], rn_ref[...], wrap_ref[0:1, :],
        )
    plane = jnp.concatenate(
        [ok.astype(jnp.uint32), degen.astype(jnp.uint32)]
        + [jnp.zeros_like(ok, jnp.uint32)] * 6,
        axis=0,
    )
    out_ref[...] = plane


@jax.jit
def _pallas_verify_w4_program(u1w, u2w, qx, qy, q2, r0, rn, w2):
    """<=4096-lane slice -> (8, S) plane: row 0 = ok, row 1 = degenerate."""
    from jax.experimental import pallas as pl

    S = qx.shape[1]
    tile = min(_PALLAS_TILE, S)
    assert S % tile == 0, (S, tile)
    bs = lambda r: pl.BlockSpec((r, tile), lambda i: (0, 0))  # noqa: E731
    call = pl.pallas_call(
        _verify_kernel_w4,
        grid=(1,),
        in_specs=[bs(64), bs(64), bs(N_LIMBS), bs(N_LIMBS), bs(8),
                  bs(N_LIMBS), bs(N_LIMBS), bs(8)],
        out_specs=bs(8),
        out_shape=jax.ShapeDtypeStruct((8, tile), jnp.uint32),
    )
    outs = []
    for c in range(S // tile):
        sl = slice(c * tile, (c + 1) * tile)
        outs.append(call(
            u1w[:, sl], u2w[:, sl], qx[:, sl], qy[:, sl],
            q2[:, sl], r0[:, sl], rn[:, sl], w2[:, sl],
        ))
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)


def _verify_kernel_w4_3d(u1w_ref, u2w_ref, qx_ref, qy_ref, qinf_ref, r0_ref,
                         rn_ref, wrap_ref, out_ref):
    from jax.experimental import pallas as pl

    with _KernelConsts(u1w_ref.shape[1:]):
        ok, degen = _verify_core_w4(
            lambda i: u1w_ref[pl.ds(i, 1), :, :],
            lambda i: u2w_ref[pl.ds(i, 1), :, :],
            qx_ref[...], qy_ref[...], qinf_ref[...],
            r0_ref[...], rn_ref[...], wrap_ref[...],
        )
    out_ref[...] = jnp.concatenate(
        [ok.astype(jnp.uint32), degen.astype(jnp.uint32)], axis=0
    )


def _expand_nibble_windows(m):
    """Device-side scalar expansion: (B, nb) uint8 big-endian bytes ->
    (2*nb, B) int32 MSB-first 4-bit windows. Shared by the w4 and GLV
    byte programs — the nibble order must never drift between them."""
    hi = (m >> 4).astype(jnp.int32)
    lo = (m & 0xF).astype(jnp.int32)
    return jnp.stack([hi, lo], axis=2).reshape(m.shape[0], -1).T


def _expand_limb_cols(m):
    """Device-side field expansion: (B, 32) uint8 big-endian values ->
    (20, B) uint32 13-bit limb columns (the jnp twin of the host-side
    _limb_rows — per-byte MSB-first bits, whole-value LSB reversal,
    13-bit regroup). Shared by the w4 and GLV byte programs."""
    B = m.shape[0]
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (m[:, :, None] >> shifts) & jnp.uint8(1)  # (B, 32, 8)
    bits = bits.reshape(B, 256)[:, ::-1]  # LSB-first over the value
    bits = jnp.concatenate(
        [bits, jnp.zeros((B, 13 * N_LIMBS - 256), m.dtype)], axis=1
    )
    w13 = (jnp.uint32(1) << jnp.arange(13, dtype=jnp.uint32))
    return (bits.reshape(B, N_LIMBS, 13).astype(jnp.uint32) * w13).sum(2).T


@partial(jax.jit, static_argnames=("interpret",))
def _w4_bytes_program(u1m, u2m, qxb, qyb, qinf8, r0b, rnb, wrap8,
                      interpret: bool = False):
    """The production w4 pipeline, ONE dispatch end-to-end: byte-matrix
    inputs ((B, 32) uint8 per 256-bit field — 1.7 MB per 10k sigs vs
    8.5 MB of pre-expanded u32 planes, which matters through a serving
    tunnel), device-side expansion to window planes / 13-bit limbs (plain
    XLA), then the 3D Pallas kernel over a (B/1024,)-step grid — the whole
    batch is one program, so a batch pays ONE dispatch round trip instead
    of B/1024 (measured 14.4k vs 6.8k sigs/s at B=10240 on the tunneled
    chip). Returns (2, 8, B/8): row 0 ok, row 1 degenerate."""
    from jax.experimental import pallas as pl

    B = qxb.shape[0]
    T = B // 8

    def windows(m):  # (B, 32) u8 -> (64, 8, T) i32, MSB-first nibbles
        return _expand_nibble_windows(m).reshape(64, 8, T)

    def limbs(m):  # (B, 32) u8 big-endian -> (20, 8, T) u32 13-bit limbs
        return _expand_limb_cols(m).reshape(N_LIMBS, 8, T)

    q2 = qinf8.astype(jnp.uint32).reshape(1, 8, T)
    w2 = wrap8.astype(jnp.uint32).reshape(1, 8, T)
    n_chunks = T // 128
    bs = lambda r: pl.BlockSpec((r, 8, 128), lambda i: (0, 0, i))  # noqa: E731
    call = pl.pallas_call(
        _verify_kernel_w4_3d,
        grid=(n_chunks,),
        in_specs=[bs(64), bs(64), bs(N_LIMBS), bs(N_LIMBS), bs(1),
                  bs(N_LIMBS), bs(N_LIMBS), bs(1)],
        out_specs=bs(2),
        out_shape=jax.ShapeDtypeStruct((2, 8, T), jnp.uint32),
        interpret=interpret,  # CPU meshes (sig_shard virtual-8) have no
        # Mosaic; interpret lowers the same kernel to plain XLA ops
    )
    return call(windows(u1m), windows(u2m), limbs(qxb), limbs(qyb), q2,
                limbs(r0b), limbs(rnb), w2)


def ecdsa_verify_batch_pallas_w4_bytes(u1m, u2m, qxb, qyb, q_inf8, r0b,
                                       rnb, wrap8, interpret: bool = False):
    """Byte-matrix w4 verify (see _w4_bytes_program). B must be a multiple
    of 1024; batches beyond 16384 are split into 16384-lane program calls
    so compiled shapes stay the bounded set {1024, 2048, 4096, then
    2048-granular to 16384} — at most 9 shapes, only those actually hit
    compile (the jit bakes B into shapes + grid; see _bucket_for). Returns
    (ok, degen) bool (B,) arrays — still device futures until
    materialized."""
    B = qxb.shape[0]
    assert B % 1024 == 0, B
    SPLIT = 16384
    if B <= SPLIT:
        out = _w4_bytes_program(u1m, u2m, qxb, qyb, q_inf8, r0b, rnb, wrap8,
                                interpret=interpret)
        return (out[0].reshape(B).astype(bool),
                out[1].reshape(B).astype(bool))
    oks, dgs = [], []
    for s in range(0, B, SPLIT):
        sl = slice(s, s + SPLIT)
        n = min(SPLIT, B - s)
        out = _w4_bytes_program(u1m[sl], u2m[sl], qxb[sl], qyb[sl],
                                q_inf8[sl], r0b[sl], rnb[sl], wrap8[sl],
                                interpret=interpret)
        oks.append(out[0].reshape(n))
        dgs.append(out[1].reshape(n))
    return (jnp.concatenate(oks).astype(bool),
            jnp.concatenate(dgs).astype(bool))


def bits_to_windows_np(scalar_bytes: np.ndarray, bucket: int) -> np.ndarray:
    """(n, 32) big-endian scalar bytes -> (64, bucket) uint32 4-bit window
    planes, MSB-first (window 0 = bits 255..252)."""
    n = scalar_bytes.shape[0]
    hi = (scalar_bytes >> 4).astype(np.uint32)
    lo = (scalar_bytes & 0xF).astype(np.uint32)
    inter = np.stack([hi, lo], axis=2).reshape(n, 64)
    out = np.zeros((64, bucket), np.uint32)
    out[:, :n] = inter.T
    return out


def ecdsa_verify_batch_pallas_w4(u1w, u2w, qx, qy, q_inf, r0, rn, wrap_ok):
    """Windowed Pallas verify. Returns (ok, degen) bool arrays of shape
    (B,); degen lanes MUST be re-verified on the CPU path (their ok value
    is garbage by design — see the module notes above)."""
    B = qx.shape[1]
    q2 = jnp.broadcast_to(
        jnp.asarray(q_inf).astype(jnp.uint32).reshape(1, B), (8, B)
    )
    w2 = jnp.broadcast_to(
        jnp.asarray(wrap_ok).astype(jnp.uint32).reshape(1, B), (8, B)
    )
    pieces = []
    for s in range(0, B, _PALLAS_SUPER):
        sl = slice(s, min(s + _PALLAS_SUPER, B))
        pieces.append(_pallas_verify_w4_program(
            u1w[:, sl], u2w[:, sl], qx[:, sl], qy[:, sl],
            q2[:, sl], r0[:, sl], rn[:, sl], w2[:, sl],
        )[0:2])
    out = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces, axis=1)
    return out[0].astype(bool), out[1].astype(bool)


# ---- GLV endomorphism verify kernel (round 6) ------------------------------
#
# secp256k1 admits the efficient endomorphism φ(x, y) = (βx, y) = λ·(x, y)
# (β³ = 1 mod p, λ³ = 1 mod n — the GLV construction, and the same split
# libsecp256k1 ships in secp256k1_scalar_split_lambda). Each verify scalar
# decomposes as k = k1 + λ·k2 (mod n) with |k1|, |k2| < 2^128 via lattice
# rounding against the basis (a1, b1), (a2, b2) — done on the HOST in the
# packer with exact Python ints (ops/ecdsa_batch.pack_records_glv), signs
# folded into table/comb selection. The joint ladder then runs 32 4-bit
# windows / 128 doublings over FOUR addition streams (Q, λQ, G, λG)
# instead of the w4 kernel's 64 windows / 256 doublings over two:
#
#   u1·G + u2·Q = s11·(±G) + s12·(±λG) + s21·(±Q) + s22·(±λQ)
#
# The λQ table is free given the Q table (X → βX per entry, Y negated when
# the two Q-stream signs differ), and the G streams leave the doubling
# chain entirely: they are settled by a FIXED-BASE COMB — a process-global
# table of d·256^i·G (and its φ/negation images) built once per process
# (see _glv_comb) — as 32 order-free mixed adds after the ladder, 8-bit
# digits, zero doublings. Verification-side GLV is safe: every scalar here
# is public (u1, u2 derive from the signature and message), so no
# constant-time discipline is required — lane-varying table gathers leak
# nothing an observer does not already have.
#
# This core is plain XLA (jnp + gather), not Pallas: the comb tables are
# captured numpy constants, which Mosaic forbids and in-kernel synthesis
# cannot afford at 16×512 entries (the w4 Pallas kernels remain the
# Mosaic-tuned path and the dispatch fallback; `-ecdsakernel=w4` forces
# them). Completeness contract is identical to w4: the cheap adds flag
# H == 0 collisions (degen plane) and the host re-verifies flagged lanes.

LAMBDA = 0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72
BETA = 0x7AE96A2B657C07106E64479EAC3434E99CF0497512F58995C1396C28719501EE
# lattice basis for the split (libsecp256k1 scalar_impl.h): a1 + b1·λ ==
# a2 + b2·λ == 0 (mod n); |k1|, |k2| stay below 2^128 for any k in [0, n)
# (proven bound ~2^127.7 — asserted by the unit suite over boundary cases)
_GLV_A1 = 0x3086D221A7D46BCDE86C90E49284EB15
_GLV_MINUS_B1 = 0xE4437ED6010E88286F547FA90ABFE4C3
_GLV_A2 = 0x114CA50F7A8E2F3F657C1108D9D44CFD8
_GLV_B2 = 0x3086D221A7D46BCDE86C90E49284EB15

GLV_WINDOWS = 32     # 4-bit windows over |k1|, |k2| < 2^128
GLV_COMB_TEETH = 16  # 8-bit fixed-base comb digits over |s| < 2^128

_BETA_CONST = _const(BETA)


def _round_div(a: int, b: int) -> int:
    """round(a / b) for b > 0, exact (ties round up, matching the
    reference's rounded-division split)."""
    q, r = divmod(a, b)
    return q + (1 if 2 * r >= b else 0)


def glv_split(k: int) -> tuple[int, int]:
    """k (mod n) -> signed (k1, k2) with k == k1 + λ·k2 (mod n) and
    |k1|, |k2| < 2^128. Exact lattice rounding — no precision games."""
    k %= N
    c1 = _round_div(_GLV_B2 * k, N)
    c2 = _round_div(_GLV_MINUS_B1 * k, N)
    k1 = k - c1 * _GLV_A1 - c2 * _GLV_A2
    k2 = c1 * _GLV_MINUS_B1 - c2 * _GLV_B2
    return k1, k2


def glv_decompose(k: int) -> tuple[int, int, int, int]:
    """glv_split with the signs folded out: (|k1|, neg1, |k2|, neg2),
    neg in {0, 1}. The packer ships magnitudes; signs select negated
    table/comb entries on device."""
    k1, k2 = glv_split(k)
    n1, n2 = int(k1 < 0), int(k2 < 0)
    s1, s2 = abs(k1), abs(k2)
    assert s1 < (1 << 128) and s2 < (1 << 128), (k, k1, k2)
    return s1, n1, s2, n2


# ---- process-global fixed-base comb for G / λG -----------------------------

_GLV_COMB = None
GLV_TABLE_BUILD_S = 0.0  # host build wall time, surfaced via gettpuinfo


def _limb_rows(vals: list[int]) -> np.ndarray:
    """ints -> (len, 20) uint32 13-bit limb rows (vectorized; the
    per-value to_limbs_np loop would cost seconds at comb scale)."""
    n = len(vals)
    blob = b"".join(v.to_bytes(32, "big") for v in vals)
    mat = np.frombuffer(blob, np.uint8).reshape(n, 32)
    bits = np.unpackbits(mat, axis=1)[:, ::-1]
    bits = np.concatenate(
        [bits, np.zeros((n, 13 * N_LIMBS - 256), np.uint8)], axis=1
    )
    return (
        bits.reshape(n, N_LIMBS, 13).astype(np.uint32) * _GLV_LIMB_W
    ).sum(axis=2)


_GLV_LIMB_W = (1 << np.arange(13, dtype=np.uint32))


def _glv_comb() -> tuple:
    """The fixed-base comb: numpy tables (GLV_COMB_TEETH, 512, 20) uint32

        gx[i, s·256 + d] = x(d · 256^i · G)
        gy[i, s·256 + d] = y(...) for s = 0, p − y(...) for s = 1
        lx[i, s·256 + d] = β · x(...)  (the λG stream; φ leaves y alone,
                                        so the λ stream reuses gy)

    d = 0 slots hold the d = 1 point (callers mask the add out). Built
    ONCE per process from Python-int affine arithmetic and cached — the
    u1·G streams stop paying any per-batch (or per-trace) table
    construction; the arrays are captured as XLA constants per compiled
    shape. ~4k point_adds, a few hundred ms, timed into
    GLV_TABLE_BUILD_S for gettpuinfo."""
    global _GLV_COMB, GLV_TABLE_BUILD_S
    if _GLV_COMB is not None:
        return _GLV_COMB
    from ..crypto.secp256k1 import G, point_add, point_double

    t0 = time.monotonic()
    base = G
    xs, ys = [], []
    for _i in range(GLV_COMB_TEETH):
        row_x, row_y = [], []
        cur = None
        for _d in range(1, 256):
            cur = point_add(cur, base)
            row_x.append(cur[0])
            row_y.append(cur[1])
        xs.append(row_x)
        ys.append(row_y)
        for _ in range(8):
            base = point_double(base)
    # flatten -> limb rows -> (teeth, 512, 20); entry 0/256 = d=1 dummy
    flat_x = [row[0] for row in xs] + [v for row in xs for v in row]
    flat_y = [row[0] for row in ys] + [v for row in ys for v in row]
    lim_x = _limb_rows(flat_x)
    lim_y = _limb_rows(flat_y)
    lim_lx = _limb_rows([v * BETA % P for v in flat_x])
    lim_ny = _limb_rows([P - v for v in flat_y])
    T = GLV_COMB_TEETH
    gx = np.zeros((T, 512, N_LIMBS), np.uint32)
    gy = np.zeros((T, 512, N_LIMBS), np.uint32)
    lx = np.zeros((T, 512, N_LIMBS), np.uint32)
    dummies_x, rows_x = lim_x[:T], lim_x[T:].reshape(T, 255, N_LIMBS)
    dummies_y, rows_y = lim_y[:T], lim_y[T:].reshape(T, 255, N_LIMBS)
    dummies_lx, rows_lx = lim_lx[:T], lim_lx[T:].reshape(T, 255, N_LIMBS)
    dummies_ny, rows_ny = lim_ny[:T], lim_ny[T:].reshape(T, 255, N_LIMBS)
    for i in range(T):
        gx[i, 0] = gx[i, 256] = dummies_x[i]
        gx[i, 1:256] = gx[i, 257:512] = rows_x[i]
        lx[i, 0] = lx[i, 256] = dummies_lx[i]
        lx[i, 1:256] = lx[i, 257:512] = rows_lx[i]
        gy[i, 0] = dummies_y[i]
        gy[i, 1:256] = rows_y[i]
        gy[i, 256] = dummies_ny[i]
        gy[i, 257:512] = rows_ny[i]
    GLV_TABLE_BUILD_S = time.monotonic() - t0
    _GLV_COMB = (gx, gy, lx)
    return _GLV_COMB


def _f_neg(y):
    """-y mod p for weak y: (0 + 2p − y) via the redistributed bias, then
    carry — weak output."""
    return f_carry(_BIAS_2P - y)


def _glv_q_tables(qx, qy, ydiff_u, q_inf_u, one):
    """Per-lane Q-stream tables, stacked for gather. Returns two
    (X, Y, Z) tuples of (16, 20, B) arrays: T1[j] = j·Q' (Q' is Q with
    the first Q-stream sign already folded into qy by the packer) and
    T2[j] = j·(±φ(Q')) — the λQ stream, derived from T1 by the
    endomorphism (X → βX; Y negated where ydiff_u says the two Q-stream
    signs differ). Entry 0 is a dummy (= entry 1, callers mask)."""
    shape = qx.shape
    q_jac = {
        "X": jnp.broadcast_to(qx, shape).astype(jnp.uint32),
        "Y": jnp.broadcast_to(qy, shape).astype(jnp.uint32),
        "Z": one,
        "inf": q_inf_u,
    }
    tab = [q_jac, pt_double(q_jac)]
    for _j in range(3, 16):
        added, _hz = _pt_add_mixed_cheap_u(tab[-1], qx, qy, q_inf_u, one)
        tab.append(added)
    entries = [tab[0]] + tab  # dummy 0 = 1·Q'
    t1 = tuple(
        jnp.stack([e[c] for e in entries], axis=0) for c in ("X", "Y", "Z")
    )
    beta = jnp.asarray(
        np.broadcast_to(_BETA_CONST, shape)
    ).astype(jnp.uint32)
    diff = ydiff_u != 0
    lam_entries = [
        (f_mul(beta, e["X"]), jnp.where(diff, _f_neg(e["Y"]), e["Y"]),
         e["Z"])
        for e in entries
    ]
    t2 = tuple(
        jnp.stack([e[c] for e in lam_entries], axis=0) for c in range(3)
    )
    return t1, t2


def _glv_tab_gather(t, w):
    """Gather one Jacobian entry per lane from a stacked (16, 20, B)
    table: w is the (1, B) int32 window value (0..15). One gather per
    coordinate — the XLA core's cheaper analogue of the w4 kernel's
    15-way select chain."""
    idx = jnp.broadcast_to(w[:, None, :], (1,) + t[0].shape[1:]).astype(
        jnp.int32
    )
    return tuple(jnp.take_along_axis(c, idx, axis=0)[0] for c in t)


def _glv_window_step(carry, w1, w2, t1, t2, q_inf_u):
    """One GLV ladder window: 4 doublings + full adds from the Q and λQ
    tables. w1/w2: (1, B) int32 values in 0..15."""
    acc, degen = carry
    acc = pt_double(pt_double(pt_double(pt_double(acc))))
    for t, w in ((t1, w1), (t2, w2)):
        x, y, z = _glv_tab_gather(t, w)
        q_sel = {"X": x, "Y": y, "Z": z, "inf": q_inf_u}
        act = jnp.where(w != 0, 1, 0) * (1 - q_inf_u)
        added, hz = _pt_add_full_cheap_u(acc, q_sel)
        acc = _pt_select_u(act, added, acc)
        degen = jnp.maximum(degen, hz * act)
    return acc, degen


def _glv_comb_step(carry, drow, sgrow, tab_x, tab_y, one, never_inf):
    """One fixed-base comb tooth for one G stream: a mixed add of the
    gathered affine constant. drow: (B,) int32 digit (0..255, 0 = skip);
    sgrow: (B,) int32 sign·256 offset; tab_x/tab_y: (512, 20) constant
    tables for this tooth position."""
    acc, degen = carry
    idx = sgrow + drow
    gx_sel = jnp.take(tab_x, idx, axis=0).T
    gy_sel = jnp.take(tab_y, idx, axis=0).T
    act = jnp.where(drow != 0, 1, 0)[None, :]
    added, hz = _pt_add_mixed_cheap_u(acc, gx_sel, gy_sel, never_inf, one)
    acc = _pt_select_u(act, added, acc)
    degen = jnp.maximum(degen, hz * act)
    return acc, degen


def _verify_core_glv(w1, w2, d1, sg1, d2, sg2, qx, qy, ydiff2, q_inf2,
                     r0, rn, wrap2):
    """GLV verify core (flat (B,) lanes, plain XLA).

    w1/w2: (32, B) int32 MSB-first 4-bit windows of |s21|, |s22| (the Q
    and λQ streams). d1/d2: (16, B) int32 8-bit comb digits of |s11|,
    |s12| (position i = weight 256^i). sg1/sg2: (B,) int32 G-stream sign
    flags (0/1). qx/qy: (20, B) weak limbs, qy with the first Q-stream
    sign folded. ydiff2/q_inf2/wrap2: (1, B) masks. Returns (ok, degen)
    (1, B) int32 planes; degen lanes MUST be re-verified by the caller."""
    B = qx.shape[1]
    one = jnp.broadcast_to(_ONE_CONST, (N_LIMBS, B)).astype(jnp.uint32)
    q_inf_u = q_inf2.astype(jnp.int32)
    ydiff_u = ydiff2.astype(jnp.int32)
    never_inf = jnp.zeros((1, B), jnp.int32)

    t1, t2 = _glv_q_tables(qx, qy, ydiff_u, q_inf_u, one)
    gx_tab, gy_tab, lx_tab = (jnp.asarray(c) for c in _glv_comb())

    # plain-XLA core: no Mosaic/shard_map varying-init gymnastics needed
    # (cf. the w4 core's derived-from-input accumulator init)
    zero_v = qx * U32_0
    acc0 = {
        "X": zero_v + one,
        "Y": zero_v + one,
        "Z": zero_v,
        "inf": jnp.ones((1, B), jnp.int32),
    }
    degen0 = jnp.zeros((1, B), jnp.int32)

    def wstep(i, carry):
        wr1 = jax.lax.dynamic_index_in_dim(w1, i, 0, keepdims=True)
        wr2 = jax.lax.dynamic_index_in_dim(w2, i, 0, keepdims=True)
        return _glv_window_step(carry, wr1.astype(jnp.int32),
                                wr2.astype(jnp.int32), t1, t2, q_inf_u)

    carry = jax.lax.fori_loop(0, GLV_WINDOWS, wstep, (acc0, degen0))

    sg1o = sg1.astype(jnp.int32) * 256
    sg2o = sg2.astype(jnp.int32) * 256

    def cstep(i, carry):
        # G stream from the G comb, λG stream from the β-mapped comb
        # (φ leaves y untouched, so both streams share gy)
        dr1 = jax.lax.dynamic_index_in_dim(d1, i, 0, keepdims=False)
        tx = jax.lax.dynamic_index_in_dim(gx_tab, i, 0, keepdims=False)
        ty = jax.lax.dynamic_index_in_dim(gy_tab, i, 0, keepdims=False)
        carry = _glv_comb_step(carry, dr1.astype(jnp.int32), sg1o, tx, ty,
                               one, never_inf)
        dr2 = jax.lax.dynamic_index_in_dim(d2, i, 0, keepdims=False)
        tlx = jax.lax.dynamic_index_in_dim(lx_tab, i, 0, keepdims=False)
        return _glv_comb_step(carry, dr2.astype(jnp.int32), sg2o, tlx, ty,
                              one, never_inf)

    acc, degen = jax.lax.fori_loop(0, GLV_COMB_TEETH, cstep, carry)
    return _verify_final(acc, degen, q_inf_u, r0, rn, wrap2)


@jax.jit
def _glv_program(d1m, d2m, sg1v, sg2v, s1m, s2m, ydiff8, qxb, qyb, qinf8,
                 r0b, rnb, wrap8):
    """The GLV pipeline, ONE dispatch end-to-end: byte-matrix inputs
    (16-byte scalar halves, 32-byte field elements), device-side
    expansion to window/digit planes and 13-bit limbs, then the GLV core.
    Returns (2, B) uint32: row 0 ok, row 1 degenerate."""
    B = qxb.shape[0]
    nib_windows = _expand_nibble_windows  # (B, 16) -> (32, B)
    limbs = _expand_limb_cols             # (B, 32) -> (20, B)

    ok, degen = _verify_core_glv(
        nib_windows(s1m), nib_windows(s2m),
        d1m.astype(jnp.int32).T, sg1v.astype(jnp.int32),
        d2m.astype(jnp.int32).T, sg2v.astype(jnp.int32),
        limbs(qxb), limbs(qyb),
        ydiff8.astype(jnp.uint32).reshape(1, B),
        qinf8.astype(jnp.uint32).reshape(1, B),
        limbs(r0b), limbs(rnb),
        wrap8.astype(jnp.uint32).reshape(1, B),
    )
    return jnp.concatenate(
        [ok.astype(jnp.uint32), degen.astype(jnp.uint32)], axis=0
    )


def ecdsa_verify_batch_glv(d1m, d2m, sg1v, sg2v, s1m, s2m, ydiff8, qxb,
                           qyb, qinf8, r0b, rnb, wrap8):
    """Byte-matrix GLV verify (see _glv_program). Batches beyond 16384
    lanes split into 16384-lane program calls so compiled shapes stay the
    same bounded bucket set as the w4 pipeline. Returns (ok, degen) bool
    (B,) arrays — device futures until materialized."""
    B = qxb.shape[0]
    SPLIT = 16384
    if B <= SPLIT:
        out = _glv_program(d1m, d2m, sg1v, sg2v, s1m, s2m, ydiff8, qxb,
                           qyb, qinf8, r0b, rnb, wrap8)
        return out[0].astype(bool), out[1].astype(bool)
    oks, dgs = [], []
    for s in range(0, B, SPLIT):
        sl = slice(s, s + SPLIT)
        out = _glv_program(d1m[sl], d2m[sl], sg1v[sl], sg2v[sl], s1m[sl],
                           s2m[sl], ydiff8[sl], qxb[sl], qyb[sl],
                           qinf8[sl], r0b[sl], rnb[sl], wrap8[sl])
        n = min(SPLIT, B - s)
        oks.append(out[0].reshape(n))
        dgs.append(out[1].reshape(n))
    return (jnp.concatenate(oks).astype(bool),
            jnp.concatenate(dgs).astype(bool))


# ---- device-side GLV decomposition (round 11) ------------------------------
#
# BENCH_r08's dispatch breakdown showed the GLV HOST pack dominating the
# verify path: 3.37 s of per-record Python-bigint lattice rounding +
# byte emit against 2.64 s of device execute (host_share 0.56). The split
# is exact integer arithmetic, so it moves on-device: the program below
# takes the SAME raw byte matrices as the w4 byte pipeline ((B, 32) uint8
# per 256-bit field — the host pack collapses to pack_records_w4_bytes'
# numpy byte emission) and computes the lattice rounding per lane with
# multi-limb integer arithmetic in the same 13-bit-limb discipline as the
# field core.
#
# Rounding is EXACT, not estimate-grade: c̃K = floor(k·gK / 2^384) (the
# libsecp g1/g2 Barrett constants, re-derived from the basis at import)
# lands in {cK − 1, cK} of the true cK = round(mK·k / n) for any k < n
# (|gK − 2^384·mK/n| <= 1/2 contributes < 2^-129 relative error, the
# floor at most 1), and one exact-residual correction step — compute
# ê = mK·k − c̃K·n in limbs, bump c̃K when 2ê >= n (n odd kills ties, so
# >= and > coincide on the even 2ê) — recovers cK precisely. The device
# decomposition is therefore BIT-IDENTICAL to glv_decompose's Python-int
# rounding, which stays in-tree as the KAT oracle and the differential
# reference, never the hot path.
#
# Integer-limb helpers are prefixed _z (no mod-p folding — these are
# plain multi-limb integers, widths chosen so every accumulation stays
# < 2^31 in uint32). All multiplications here are variable x CONSTANT
# (g1/g2/n/a1/a2/b1/b2 baked at trace time); the whole decomposition is
# ~10 small schoolbook muls + carries per lane — noise next to the
# verify ladder's 128 doublings. Like the field core, the helpers keep
# TWO forms behind field_parallel(): compact scan traces on CPU backends
# (an unrolled carry normalizer measured MINUTES of extra XLA compile on
# CPU — the same pathology the module header documents for f_mul) and
# fully parallel static forms on accelerators (where per-iteration
# buffer copies, not compile time, are the poison).

_GLV_G1_INT = _round_div(_GLV_B2 << 384, N)
_GLV_G2_INT = _round_div(_GLV_MINUS_B1 << 384, N)


def _zconst_limbs(value: int, width: int) -> np.ndarray:
    """int -> (width,) uint32 13-bit LE limb array (must fit)."""
    assert 0 <= value < (1 << (LIMB_BITS * width)), (value, width)
    return np.array(
        [(value >> (LIMB_BITS * i)) & int(MASK) for i in range(width)],
        np.uint32,
    )


def _zmul_const(a, c_limbs, width: int):
    """Exact-limb (La, B) x constant limb vector -> (width, B) raw
    columns, un-normalized. Accumulation bound: <= min(La, len(c)) <= 20
    terms of < 2^26 each, < 2^31 — u32-safe. Zero limbs of the constant
    cost nothing (skipped at trace time)."""
    La = a.shape[0]
    cols = jnp.zeros((width,) + a.shape[1:], jnp.uint32)
    for i, c in enumerate(c_limbs):
        if int(c):
            cols = cols.at[i:i + La].add(a * np.uint32(int(c)))
    return cols


def _znorm(cols):
    """Raw columns (< 2^31 each) -> exact 13-bit limbs, same width (the
    value must fit the width — top carry is structurally zero). CPU:
    one sequential carry scan settles exactly (carries ride the scan
    state). Parallel form: three rounds collapse any < 2^31 magnitudes
    to <= 2^13 + 1, then `width` single-carry ripple rounds settle
    exactly (cf. _exact_norm20)."""
    if not field_parallel():
        out, _carry = _sweep(cols)  # final carry structurally zero
        return out
    v = cols
    for _ in range(v.shape[0] + 3):
        c = v >> np.uint32(LIMB_BITS)
        v = (v & MASK) + jnp.concatenate(
            [jnp.zeros_like(c[:1]), c[:-1]], axis=0)
    return v


def _zge(a, b):
    """a >= b over equal-width EXACT limb planes; (B,) bool. CPU: the
    field core's MSB-first compare scan (width-generic). Parallel form:
    static unroll."""
    if not field_parallel():
        return _f_ge(a, b)
    gt = a[0] > a[0]   # varying-safe all-False / all-True inits
    eq = a[0] == a[0]
    for i in range(a.shape[0] - 1, -1, -1):
        gt = gt | (eq & (a[i] > b[i]))
        eq = eq & (a[i] == b[i])
    return gt | eq


def _zsub(a, b):
    """Exact a - b for equal-width exact limb planes with a >= b (borrow
    ripple). Garbage when a < b — callers select on _zge. CPU: the field
    core's borrow scan (width-generic); parallel form: static unroll."""
    if not field_parallel():
        return _f_sub_exact(a, b)
    outs = []
    borrow = a[0] * U32_0
    for i in range(a.shape[0]):
        v = a[i] - b[i] - borrow
        under = v >> np.uint32(31)
        outs.append(v + under * np.uint32(1 << LIMB_BITS))
        borrow = under
    return jnp.stack(outs, axis=0)


def _zdbl(v):
    """2*v for exact limbs -> (width + 1, B) exact limbs."""
    lo = (v << np.uint32(1)) & MASK
    hi = v >> np.uint32(LIMB_BITS - 1)
    carry = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
    return jnp.concatenate([lo + carry, hi[-1:]], axis=0)


def _zshr_384(v40):
    """floor(v / 2^384) for a (40, B) exact plane -> (11, B).
    384 = 29*13 + 7: output limb j = (v[29+j] >> 7) | (v[30+j] & 0x7F) << 6."""
    w = v40[29:]
    lo = w >> np.uint32(7)
    hi = (w & np.uint32(0x7F)) << np.uint32(LIMB_BITS - 7)
    return lo + jnp.concatenate([hi[1:], jnp.zeros_like(hi[:1])], axis=0)


def _glv_split_device(k20):
    """Device lattice decomposition: k20 is the (20, B) EXACT 13-bit limb
    plane of a scalar k < n. Returns (m1, n1, m2, n2): mK (10, B) exact
    limb planes of |kK| < 2^128 and nK (B,) bool sign flags with
    k == (-1)^n1·m1 + λ·(-1)^n2·m2 (mod n) — the same contract AND the
    same exact rounding as the host glv_decompose."""
    n_20 = _zconst_limbs(N, 20)

    def round_quot(g_int: int, m_int: int):
        # c̃ = floor(k·g / 2^384), then the exact-rounding correction:
        # ê = m·k − c̃·n; the true c has 2|ê| < n, so c̃ is exact unless
        # ê >= 0 and 2ê >= n, where c = c̃ + 1 (floor never overshoots).
        prod = _znorm(_zmul_const(k20, _zconst_limbs(g_int, 20), 40))
        c_est = _zshr_384(prod)                                   # (11, B)
        t = _znorm(_zmul_const(k20, _zconst_limbs(m_int, 10), 30))
        cn = _znorm(_zmul_const(c_est, n_20, 31))[:30]
        ge = _zge(t, cn)
        diff = _zsub(t, cn)              # = ê, valid only where ge
        n_31 = jnp.asarray(_zconst_limbs(N, 31)).reshape(
            (31,) + (1,) * (k20.ndim - 1)).astype(jnp.uint32)
        plus = ge & _zge(_zdbl(diff), jnp.broadcast_to(
            n_31, (31,) + diff.shape[1:]))
        bumped = jnp.concatenate(
            [c_est[0:1] + plus.astype(jnp.uint32), c_est[1:]], axis=0)
        return _znorm(bumped)

    c1 = round_quot(_GLV_G1_INT, _GLV_B2)
    c2 = round_quot(_GLV_G2_INT, _GLV_MINUS_B1)
    # k1 = k − c1·a1 − c2·a2 ; k2 = c1·(−b1) − c2·b2  (signed, |·| < 2^128)
    s = _znorm(_zmul_const(c1, _zconst_limbs(_GLV_A1, 10), 21)
               + _zmul_const(c2, _zconst_limbs(_GLV_A2, 10), 21))
    k_pad = jnp.concatenate([k20, jnp.zeros_like(k20[:1])], axis=0)
    n1 = ~_zge(k_pad, s)
    m1 = jnp.where(n1, _zsub(s, k_pad), _zsub(k_pad, s))[:10]
    p1 = _znorm(_zmul_const(c1, _zconst_limbs(_GLV_MINUS_B1, 10), 21))
    p2 = _znorm(_zmul_const(c2, _zconst_limbs(_GLV_B2, 10), 21))
    n2 = ~_zge(p1, p2)
    m2 = jnp.where(n2, _zsub(p2, p1), _zsub(p1, p2))[:10]
    return m1, n1, m2, n2


def _mag_bits128(m10):
    """(10, B) exact limb plane of a value < 2^128 -> (128, B) LSB-first
    bit planes (uint32 0/1)."""
    shifts = jnp.arange(13, dtype=jnp.uint32).reshape(1, 13, 1)
    bits = (m10[:, None, :] >> shifts) & jnp.uint32(1)
    return bits.reshape(130, m10.shape[1])[:128]


def _bits_to_comb_digits(bits):
    """(128, B) LSB-first bits -> (16, B) int32 8-bit comb digits (digit
    i = byte i little-endian = weight 256^i) — the device twin of the
    host packer's to_bytes(16, 'little') emission."""
    w = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32)).reshape(1, 8, 1)
    return (bits.reshape(16, 8, -1) * w).sum(1).astype(jnp.int32)


def _bits_to_nibble_windows(bits):
    """(128, B) LSB-first bits -> (32, B) int32 MSB-first 4-bit windows
    (window 0 = bits 127..124) — matches _expand_nibble_windows over the
    host packer's big-endian byte emission."""
    w = (jnp.uint32(1) << jnp.arange(4, dtype=jnp.uint32)).reshape(1, 4, 1)
    nib = (bits.reshape(32, 4, -1) * w).sum(1)
    return nib[::-1].astype(jnp.int32)


@jax.jit
def _glv_decompose_program(km):
    """Decompose-only jit surface: (B, 32) uint8 big-endian scalars
    (< n) -> (|k1| LE bytes (B, 16), n1 (B,), |k2| LE bytes (B, 16),
    n2 (B,)) — the differential-test window onto the in-kernel split
    (the fused _glv_dev_program below is the production consumer)."""
    m1, n1, m2, n2 = _glv_split_device(_expand_limb_cols(km))
    b1 = _bits_to_comb_digits(_mag_bits128(m1))
    b2 = _bits_to_comb_digits(_mag_bits128(m2))
    return (b1.T.astype(jnp.uint8), n1.astype(jnp.uint8),
            b2.T.astype(jnp.uint8), n2.astype(jnp.uint8))


def glv_decompose_device_batch(scalars) -> tuple:
    """Host-callable device split over (n, 32) big-endian scalar bytes;
    returns numpy (|k1| (n, 16) LE, n1, |k2| (n, 16) LE, n2)."""
    out = _glv_decompose_program(np.asarray(scalars, np.uint8))
    return tuple(np.asarray(o) for o in out)


@jax.jit
def _glv_dev_program(u1m, u2m, qxb, qyb, qinf8, r0b, rnb, wrap8):
    """The device-decompose GLV pipeline (round 11), ONE dispatch end to
    end: byte-matrix inputs IDENTICAL to the w4 byte pipeline (so the
    host pack is pack_records_w4_bytes' pure numpy byte emission),
    device-side exact lattice decomposition of u1/u2, window/digit/limb
    expansion, the sign-folded λQ y-select, then the GLV verify core.
    Returns (2, B) uint32: row 0 ok, row 1 degenerate."""
    B = qxb.shape[0]
    # ONE split over the stacked (2B,) lane axis — the decompose is
    # pure per-lane arithmetic, so stacking u1|u2 halves the traced
    # decompose graph (XLA CPU compile time scales with trace size)
    mm1, nn1, mm2, nn2 = _glv_split_device(
        _expand_limb_cols(jnp.concatenate([u1m, u2m], axis=0)))
    bb1 = _mag_bits128(mm1)
    bb2 = _mag_bits128(mm2)
    a1, na1, a2, na2 = bb1[:, :B], nn1[:B], bb2[:, :B], nn2[:B]
    b1, nb1, b2, nb2 = bb1[:, B:], nn1[B:], bb2[:, B:], nn2[B:]
    d1 = _bits_to_comb_digits(a1)      # G-stream digits
    d2 = _bits_to_comb_digits(a2)      # λG-stream digits
    w1 = _bits_to_nibble_windows(b1)   # Q-stream windows
    w2 = _bits_to_nibble_windows(b2)   # λQ-stream windows
    qy = _expand_limb_cols(qyb)
    nb1r = nb1.reshape(1, B)
    # the first Q-stream sign folds into qy (the host packer's P − qy
    # leg, done in the field here); the second folds into the λQ table's
    # y-select via ydiff — exactly pack_records_glv's emission contract
    qy = jnp.where(nb1r, _f_neg(qy), qy)
    ydiff = (nb1r ^ nb2.reshape(1, B)).astype(jnp.uint32)
    ok, degen = _verify_core_glv(
        w1, w2, d1, na1.astype(jnp.int32), d2, na2.astype(jnp.int32),
        _expand_limb_cols(qxb), qy, ydiff,
        qinf8.astype(jnp.uint32).reshape(1, B),
        _expand_limb_cols(r0b), _expand_limb_cols(rnb),
        wrap8.astype(jnp.uint32).reshape(1, B))
    return jnp.concatenate(
        [ok.astype(jnp.uint32), degen.astype(jnp.uint32)], axis=0)


def ecdsa_verify_batch_glv_dev(u1m, u2m, qxb, qyb, qinf8, r0b, rnb, wrap8):
    """Byte-matrix GLV verify with the decompose ON DEVICE (see
    _glv_dev_program). Input signature matches the w4 byte pipeline;
    batches beyond 16384 lanes split into 16384-lane program calls so
    compiled shapes stay the bounded bucket set. Returns (ok, degen)
    bool (B,) arrays — device futures until materialized."""
    B = qxb.shape[0]
    SPLIT = 16384
    if B <= SPLIT:
        out = _glv_dev_program(u1m, u2m, qxb, qyb, qinf8, r0b, rnb, wrap8)
        return out[0].astype(bool), out[1].astype(bool)
    oks, dgs = [], []
    for s in range(0, B, SPLIT):
        sl = slice(s, s + SPLIT)
        out = _glv_dev_program(u1m[sl], u2m[sl], qxb[sl], qyb[sl],
                               qinf8[sl], r0b[sl], rnb[sl], wrap8[sl])
        n = min(SPLIT, B - s)
        oks.append(out[0].reshape(n))
        dgs.append(out[1].reshape(n))
    return (jnp.concatenate(oks).astype(bool),
            jnp.concatenate(dgs).astype(bool))


# ---- numpy-vectorized host decomposition (fallback + reference) ------------
#
# The retained host-decompose path (device-decompose latched broken, or
# the explicit drill) must still beat the old per-record Python-bigint
# loop: the same estimate-plus-exact-correction algorithm as the device
# kernel, vectorized over records in 16-bit limbs on uint64 (products
# < 2^32, <= 16-term column sums < 2^37 — u64-safe). Also the
# differential reference the unit suite runs against glv_decompose.

_NP16_MASK = np.uint64(0xFFFF)


def _np_limbs16(mat: np.ndarray, width: int) -> np.ndarray:
    """(n, nb) uint8 big-endian -> (n, width) uint64 16-bit LE limbs."""
    rev = mat[:, ::-1].astype(np.uint64)
    out = np.zeros((mat.shape[0], width), np.uint64)
    half = mat.shape[1] // 2
    out[:, :half] = rev[:, 0::2] | (rev[:, 1::2] << np.uint64(8))
    return out


def _np_const16(value: int, width: int) -> np.ndarray:
    assert 0 <= value < (1 << (16 * width)), (value, width)
    return np.array([(value >> (16 * i)) & 0xFFFF for i in range(width)],
                    np.uint64)


def _np_mul(a: np.ndarray, c: np.ndarray) -> np.ndarray:
    """(n, La) exact 16-bit limbs x (Lc,) const -> (n, La + Lc) raw
    columns (u64-safe, un-normalized)."""
    n, La = a.shape
    cols = np.zeros((n, La + len(c)), np.uint64)
    for i, ci in enumerate(c):
        if int(ci):
            cols[:, i:i + La] += a * ci
    return cols


def _np_norm(cols: np.ndarray) -> np.ndarray:
    """Raw columns -> exact 16-bit limbs, same width (value must fit).
    Three rounds collapse any < 2^37 magnitudes to <= 2^16 + 1; the
    residual single-carry ripple is data-dependent on host, so loop
    until quiescent (typically 1-2 more passes) instead of the device
    kernel's fixed worst-case `width` rounds."""
    v = cols
    for _ in range(3):
        carry = v >> np.uint64(16)
        v = v & _NP16_MASK
        v[:, 1:] += carry[:, :-1]
    while True:
        carry = v >> np.uint64(16)
        if not carry.any():
            return v
        v = v & _NP16_MASK
        v[:, 1:] += carry[:, :-1]


def _np_sub(a: np.ndarray, b: np.ndarray) -> tuple:
    """Limbwise a - b with borrow ripple; returns (diff, underflow).
    underflow True where a < b (diff is then the wrapped complement)."""
    n, width = a.shape
    out = np.empty((n, width), np.uint64)
    borrow = np.zeros(n, np.uint64)
    for i in range(width):
        v = a[:, i] - b[:, i] - borrow
        borrow = v >> np.uint64(63)
        out[:, i] = v + (borrow << np.uint64(16))
    return out, borrow.astype(bool)


def _np_ge(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return ~_np_sub(a, b)[1]


def _np_dbl(v: np.ndarray) -> np.ndarray:
    out = np.zeros((v.shape[0], v.shape[1] + 1), np.uint64)
    out[:, :-1] = (v << np.uint64(1)) & _NP16_MASK
    out[:, 1:] += v >> np.uint64(15)
    return out


def _np_bytes_le(limbs: np.ndarray) -> np.ndarray:
    """(n, L) 16-bit limbs -> (n, 2L) uint8 little-endian bytes."""
    out = np.empty((limbs.shape[0], 2 * limbs.shape[1]), np.uint8)
    out[:, 0::2] = (limbs & np.uint64(0xFF)).astype(np.uint8)
    out[:, 1::2] = ((limbs >> np.uint64(8)) & np.uint64(0xFF)).astype(
        np.uint8)
    return out


def glv_split_batch_np(scalars: np.ndarray) -> tuple:
    """Numpy-vectorized exact lattice split: (n, 32) big-endian scalar
    bytes (each < n) -> (m1 (n, 8) u64 16-bit LE limbs, n1 (n,) bool,
    m2, n2), rounding identical to glv_split (asserted differentially
    by the unit suite)."""
    k = _np_limbs16(np.asarray(scalars, np.uint8), 16)
    n_16 = _np_const16(N, 16)

    def round_quot(g_int: int, m_int: int) -> np.ndarray:
        prod = _np_norm(_np_mul(k, _np_const16(g_int, 16)))    # (n, 32)
        c_est = prod[:, 24:].copy()     # floor(· / 2^384): 24 limbs off
        t = _np_norm(_np_mul(k, _np_const16(m_int, 8)))        # (n, 24)
        cn = _np_norm(_np_mul(c_est, n_16))                    # (n, 24)
        diff, under = _np_sub(t, cn)
        two = _np_dbl(diff)
        plus = (~under) & _np_ge(
            two, np.broadcast_to(_np_const16(N, two.shape[1]), two.shape))
        c_est[:, 0] += plus
        return _np_norm(c_est)

    c1 = round_quot(_GLV_G1_INT, _GLV_B2)
    c2 = round_quot(_GLV_G2_INT, _GLV_MINUS_B1)
    s_cols = _np_mul(c2, _np_const16(_GLV_A2, 9))              # (n, 17)
    s_cols[:, :16] += _np_mul(c1, _np_const16(_GLV_A1, 8))
    s = _np_norm(s_cols)
    k_pad = np.zeros_like(s)
    k_pad[:, :16] = k
    d_ks, n1 = _np_sub(k_pad, s)
    d_sk, _ = _np_sub(s, k_pad)
    m1 = np.where(n1[:, None], d_sk, d_ks)[:, :8]
    p1 = _np_norm(_np_mul(c1, _np_const16(_GLV_MINUS_B1, 8)))  # (n, 16)
    p2 = _np_norm(_np_mul(c2, _np_const16(_GLV_B2, 8)))
    d12, n2 = _np_sub(p1, p2)
    d21, _ = _np_sub(p2, p1)
    m2 = np.where(n2[:, None], d21, d12)[:, :8]
    return m1, n1, m2, n2


def glv_decompose_batch_np(scalars: np.ndarray) -> tuple:
    """glv_decompose, vectorized: (n, 32) big-endian scalar bytes ->
    (|k1| (n, 16) LE bytes, n1 (n,) uint8, |k2| (n, 16) LE bytes, n2)."""
    m1, n1, m2, n2 = glv_split_batch_np(scalars)
    return (_np_bytes_le(m1), n1.astype(np.uint8),
            _np_bytes_le(m2), n2.astype(np.uint8))


def field_neg_bytes_np(yb: np.ndarray) -> np.ndarray:
    """(n, 32) big-endian y (< p) -> (n, 32) big-endian p − y, vectorized
    (the host packer's Q-stream sign fold; y = 0 is never on the curve,
    so the p − 0 = p edge is unreachable from parsed pubkeys)."""
    yl = _np_limbs16(np.asarray(yb, np.uint8), 16)
    d, under = _np_sub(
        np.broadcast_to(_np_const16(P, 16), yl.shape).copy(), yl)
    return _np_bytes_le(d)[:, ::-1]

# ---- Pippenger/bucket MSM — Schnorr batch verification (round 19) ----------
#
# The GLV ladder above still pays a full group-law ladder PER SIGNATURE.
# BCH Schnorr signatures admit a true batch check: draw per-sig random
# 128-bit coefficients a_i and test
#
#     Σ a_i·R_i + Σ (a_i·e_i mod n)·P_i + ((n − Σ a_i·s_i) mod n)·G == O
#
# — one point-at-infinity check for the whole batch (soundness error
# 2^-128 per forged signature; the host layer in ops/ecdsa_batch.py owns
# coefficient drawing, the canary gate and the reject-side bisection).
# The kernel here is the generic engine: a multi-scalar multiplication
# Σ k_j·Q_j over M = 2N+1 (point, scalar) terms, Pippenger bucket
# accumulation with c = 4-bit windows.
#
# Compute shape (deliberately unlike the uniform-SIMD ladders): the batch
# is split into K independent STREAMS; each step gathers every stream's
# current bucket (take_along_axis over the 16-bucket axis), performs ONE
# complete mixed add at width K·64 (all 64 windows of all K streams in
# parallel), and scatters the results back through a 16-wide one-hot
# select — a gather/scatter bucket walk, not a ladder. Streams then merge
# pairwise (log2 K complete full adds at shrinking widths), buckets
# reduce to per-window sums via the suffix-running-sum identity
# Σ b·B_b = Σ_{j} Σ_{b>=j} B_b (15 iterations, 2 full adds at width 64),
# and a 64-window Horner ladder (4 doubles + 1 add per window at width 1)
# collapses to the final accumulator.
#
# COMPLETENESS IS LOAD-BEARING on the accept side: an adversary controls
# R_i and P_i, so bucket/merge/reduce additions CAN hit the same-point
# and opposite-point cases (identical R across two sigs landing in one
# bucket, crafted torsion-free collisions). Every addition in this
# pipeline is therefore the fully complete form (pt_add_mixed /
# pt_add_full) — unlike the w4/GLV ladders' cheap adds, there is no
# degenerate-lane escape hatch, because a single wrong add could turn a
# forged batch into an accepted infinity. The reject side never trusts
# the device at all (host bisects to the per-lane oracle).

# Stream-count cap: more streams = wider (better-utilized) adds but more
# merge work; M//32 keeps every stream >= 32 points deep so the merge
# tree stays a rounding error next to the bucket walk.
_MSM_STREAM_CAP = 128


def pt_add_full(pt: dict, q: dict) -> dict:
    """COMPLETE Jacobian + Jacobian add via branchless selects — the
    full-Jacobian analogue of pt_add_mixed's case analysis:
      P=inf -> Q;  Q=inf -> P;  P==Q -> double(P);  P==-Q -> infinity.
    add-2007-bl core, same field discipline as _pt_add_full_cheap_u, plus
    the two exact-norm zero tests and the internal double the cheap form
    omits. Masks are (B,)-shaped bools (plain-XLA path only)."""
    X1, Y1, Z1 = pt["X"], pt["Y"], pt["Z"]
    X2, Y2, Z2 = q["X"], q["Y"], q["Z"]
    Z1Z1 = f_sqr(Z1)
    Z2Z2 = f_sqr(Z2)
    U1 = f_mul(X1, Z2Z2)
    U2 = f_mul(X2, Z1Z1)
    S1 = f_mul(Y1, f_mul(Z2, Z2Z2))
    S2 = f_mul(Y2, f_mul(Z1, Z1Z1))
    H = f_carry_sub(U2, U1)
    R = f_carry_sub(S2, S1)
    h_zero = f_is_zero(H)
    r_zero = f_is_zero(R)
    finite_both = ~pt["inf"] & ~q["inf"]
    same = h_zero & r_zero & finite_both
    opposite = h_zero & ~r_zero & finite_both
    HH = f_sqr(H)
    HHH = f_mul(H, HH)
    V = f_mul(U1, HH)
    X3 = f_carry_sub(f_sqr(R), f_carry(f_add(HHH, f_carry(f_add(V, V)))))
    Y3 = f_carry_sub(f_mul(R, f_carry_sub(V, X3)), f_mul(S1, HHH))
    Z3 = f_mul(f_mul(Z1, Z2), H)
    out = {"X": X3, "Y": Y3, "Z": Z3, "inf": opposite}
    out = pt_select(same, pt_double(pt), out)
    out = pt_select(pt["inf"], q, out)
    out = pt_select(q["inf"] & ~pt["inf"], pt, out)
    return out


def _msm_accumulate(xm, ym, inf8, km) -> dict:
    """The MSM core: xm/ym (M, 32) uint8 big-endian affine coordinates,
    inf8 (M,) uint8 infinity/padding flags (flagged terms contribute
    nothing), km (M, 32) uint8 big-endian scalars (< n). M must be a
    multiple of the stream count (the host pads to the _MSM_BUCKETS
    ladder, all multiples of every admissible K). Returns the Jacobian
    accumulator point Σ k_j·Q_j at width 1."""
    M = xm.shape[0]
    K = max(1, min(_MSM_STREAM_CAP, M // 32))
    steps = M // K
    # stream-major point layout: stream k owns points k*steps .. k*steps+
    # steps-1, so a plain reshape splits the lane axis into (K, steps)
    xs = _expand_limb_cols(xm).reshape(N_LIMBS, K, steps)
    ys = _expand_limb_cols(ym).reshape(N_LIMBS, K, steps)
    p_inf = inf8.astype(bool).reshape(K, steps)
    # (64, M) MSB-first 4-bit windows -> (K*64, steps), lane = k*64 + w
    digits = _expand_nibble_windows(km).reshape(64, K, steps)
    digits = digits.transpose(1, 0, 2).reshape(K * 64, steps)
    lanes = K * 64

    # varying-safe infinity inits (shard_map carry-vma: see _sweep)
    v0 = xs[0, 0, 0] * U32_0
    t0 = v0 == v0

    def inf_pt(tail: tuple) -> dict:
        z = jnp.zeros((N_LIMBS,) + tail, jnp.uint32) + v0
        return {"X": z + np.uint32(1), "Y": z + np.uint32(1), "Z": z,
                "inf": jnp.zeros(tail, bool) | t0}

    bucket_ids = jnp.arange(16, dtype=jnp.int32)

    def step(t, bk):
        d = jax.lax.dynamic_index_in_dim(digits, t, 1, keepdims=False)
        qx = jax.lax.dynamic_index_in_dim(xs, t, 2, keepdims=False)
        qy = jax.lax.dynamic_index_in_dim(ys, t, 2, keepdims=False)
        qi = jax.lax.dynamic_index_in_dim(p_inf, t, 1, keepdims=False)
        # each stream's point fans out across its 64 window lanes
        qx = jnp.broadcast_to(
            qx[:, :, None], (N_LIMBS, K, 64)).reshape(N_LIMBS, lanes)
        qy = jnp.broadcast_to(
            qy[:, :, None], (N_LIMBS, K, 64)).reshape(N_LIMBS, lanes)
        qi = jnp.broadcast_to(qi[:, None], (K, 64)).reshape(lanes)
        cur = {
            "X": jnp.take_along_axis(bk["X"], d[None, :, None], axis=2)[
                ..., 0],
            "Y": jnp.take_along_axis(bk["Y"], d[None, :, None], axis=2)[
                ..., 0],
            "Z": jnp.take_along_axis(bk["Z"], d[None, :, None], axis=2)[
                ..., 0],
            "inf": jnp.take_along_axis(bk["inf"], d[:, None], axis=1)[:, 0],
        }
        new = pt_add_mixed(cur, qx, qy, qi)
        # one-hot write-back; digit-0 lanes and infinity points are
        # no-ops (bucket 0 is a sink the reduction never reads)
        hit = (bucket_ids[None, :] == d[:, None]) & (
            (d > 0) & ~qi)[:, None]
        return {
            "X": jnp.where(hit[None], new["X"][:, :, None], bk["X"]),
            "Y": jnp.where(hit[None], new["Y"][:, :, None], bk["Y"]),
            "Z": jnp.where(hit[None], new["Z"][:, :, None], bk["Z"]),
            "inf": jnp.where(hit, new["inf"][:, None], bk["inf"]),
        }

    bk = jax.lax.fori_loop(0, steps, step, inf_pt((lanes, 16)))

    # pairwise stream merge: log2(K) complete full adds at halving widths
    k = K
    cur = {"X": bk["X"].reshape(N_LIMBS, K, 1024),
           "Y": bk["Y"].reshape(N_LIMBS, K, 1024),
           "Z": bk["Z"].reshape(N_LIMBS, K, 1024),
           "inf": bk["inf"].reshape(K, 1024)}
    while k > 1:
        half = k // 2
        lo = {"X": cur["X"][:, :half].reshape(N_LIMBS, half * 1024),
              "Y": cur["Y"][:, :half].reshape(N_LIMBS, half * 1024),
              "Z": cur["Z"][:, :half].reshape(N_LIMBS, half * 1024),
              "inf": cur["inf"][:half].reshape(half * 1024)}
        hi = {"X": cur["X"][:, half:].reshape(N_LIMBS, half * 1024),
              "Y": cur["Y"][:, half:].reshape(N_LIMBS, half * 1024),
              "Z": cur["Z"][:, half:].reshape(N_LIMBS, half * 1024),
              "inf": cur["inf"][half:].reshape(half * 1024)}
        merged = pt_add_full(lo, hi)
        cur = {"X": merged["X"].reshape(N_LIMBS, half, 1024),
               "Y": merged["Y"].reshape(N_LIMBS, half, 1024),
               "Z": merged["Z"].reshape(N_LIMBS, half, 1024),
               "inf": merged["inf"].reshape(half, 1024)}
        k = half
    bX = cur["X"].reshape(N_LIMBS, 64, 16)
    bY = cur["Y"].reshape(N_LIMBS, 64, 16)
    bZ = cur["Z"].reshape(N_LIMBS, 64, 16)
    binf = cur["inf"].reshape(64, 16)

    # weighted bucket reduction via suffix running sums, b = 15 .. 1:
    # running += B_b; total += running  ==>  total = Σ b·B_b
    def red(i, carry):
        b = np.int32(15) - i
        running, total = carry
        e = {"X": jax.lax.dynamic_index_in_dim(bX, b, 2, keepdims=False),
             "Y": jax.lax.dynamic_index_in_dim(bY, b, 2, keepdims=False),
             "Z": jax.lax.dynamic_index_in_dim(bZ, b, 2, keepdims=False),
             "inf": jax.lax.dynamic_index_in_dim(binf, b, 1,
                                                 keepdims=False)}
        running = pt_add_full(running, e)
        total = pt_add_full(total, running)
        return (running, total)

    _, win = jax.lax.fori_loop(0, 15, red, (inf_pt((64,)), inf_pt((64,))))

    # MSB-first Horner over the 64 window sums: acc = 16*acc + W_w
    wX, wY, wZ, winf = win["X"], win["Y"], win["Z"], win["inf"]

    def horner(w, acc):
        for _ in range(4):
            acc = pt_double(acc)
        e = {"X": jax.lax.dynamic_index_in_dim(wX, w, 1, keepdims=True),
             "Y": jax.lax.dynamic_index_in_dim(wY, w, 1, keepdims=True),
             "Z": jax.lax.dynamic_index_in_dim(wZ, w, 1, keepdims=True),
             "inf": jax.lax.dynamic_slice_in_dim(winf, w, 1, 0)}
        return pt_add_full(acc, e)

    return jax.lax.fori_loop(0, 64, horner, inf_pt((1,)))


@jax.jit
def _msm_program(xm, ym, inf8, km):
    """The batch-verification jit surface: MSM over the packed terms,
    verdict = is the accumulator the point at infinity. Returns (1,)
    uint32 (1 = batch accepts). One compiled shape per _MSM_BUCKETS
    entry — the ecdsa_msm program watch budgets exactly that set."""
    acc = _msm_accumulate(xm, ym, inf8, km)
    return acc["inf"].astype(jnp.uint32)


@jax.jit
def _msm_partial_program(xm, ym, inf8, km):
    """Sharded-MSM building block (parallel/sig_shard): the accumulator
    POINT instead of the verdict, packed (61, 1) uint32 = X(20) || Y(20)
    || Z(20) || inf(1) weak limbs — per-chip partial sums fold on the
    host (MSM is a sum; it distributes over row shards)."""
    acc = _msm_accumulate(xm, ym, inf8, km)
    return jnp.concatenate(
        [acc["X"], acc["Y"], acc["Z"],
         acc["inf"].astype(jnp.uint32).reshape(1, 1)], axis=0)


def schnorr_msm_is_infinity(xm, ym, inf8, km) -> np.ndarray:
    """Host entry for the batch check: returns the (1,) uint32 verdict
    array (materialized — the MSM dispatch is eager by design; the
    bisection ladder above it is verdict-driven)."""
    out = _msm_program(np.asarray(xm, np.uint8), np.asarray(ym, np.uint8),
                       np.asarray(inf8, np.uint8), np.asarray(km, np.uint8))
    return np.asarray(out)


def msm_partial_point(xm, ym, inf8, km) -> tuple:
    """Host entry for one shard's partial MSM: returns ((X, Y, Z) Python
    ints, inf bool) — the Jacobian partial accumulator, host-foldable via
    the crypto oracle's point arithmetic."""
    out = np.asarray(_msm_partial_program(
        np.asarray(xm, np.uint8), np.asarray(ym, np.uint8),
        np.asarray(inf8, np.uint8), np.asarray(km, np.uint8)))
    x = from_limbs_np(out[0:N_LIMBS, 0]) % P
    y = from_limbs_np(out[N_LIMBS:2 * N_LIMBS, 0]) % P
    z = from_limbs_np(out[2 * N_LIMBS:3 * N_LIMBS, 0]) % P
    return (x, y, z), bool(out[3 * N_LIMBS, 0])
