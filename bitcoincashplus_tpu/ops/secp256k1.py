"""Vectorized secp256k1 batch ECDSA verification (jnp core).

Replaces the per-input secp256k1_ecdsa_verify calls fanned out by
CCheckQueue (src/checkqueue.h:~30 + src/secp256k1.c:~340) with one
lane-parallel dispatch: every VPU lane verifies one signature.

Design (SURVEY.md §8.4 "ECDSA batch"):
  - Field elements mod p live as (20, B) uint32 arrays: 20 limbs x 13 bits,
    limb-major so every op is elementwise over the lane (batch) axis.
    13-bit limbs make schoolbook products (< 2^26) directly accumulable in
    u32: a 20-term column sum stays under 2^31 with NO carry splitting —
    the reference's 5x52/10x26 limb choice (field_5x52_impl.h /
    field_10x26_impl.h) re-derived for a 32-bit-lane machine with no carry
    flag and no widening multiply.
  - Compact traces: carry sweeps are lax.scan over the limb axis and the
    schoolbook product is a lax.fori_loop of dynamic-slice adds, so the
    whole 256-step verify loop compiles in seconds (a fully unrolled SoA
    form measured 15s of XLA compile per single field-mul — unusable).
  - Magnitude discipline (stated per function):
      "weak"  = 13-bit limbs (top limb <= 0x1FF + eps), value < p + 2^33
      "loose" = limbs < 2^15 (add/sub outputs) — f_carry before multiplying
  - Jacobian points, branchless-complete add/double via jnp.where selects.
  - Verify needs NO field inversion: u1*G + u2*Q is compared via
    X_R == (r + k*n) * Z_R^2 for k in {0,1} (x-wraparound case included).
  - Scalar work mod n (w = s^-1, u1 = e*w, u2 = r*w) runs on the HOST with
    Python ints (ops/ecdsa_batch.py) — O(batch) microseconds.

Differentially tested against crypto/secp256k1.py (the Python-int oracle).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.secp256k1 import GX, GY, N, P

LIMB_BITS = 13
N_LIMBS = 20  # 20*13 = 260 bits
MASK = np.uint32((1 << LIMB_BITS) - 1)
U32_0 = np.uint32(0)

# p = 2^256 - C with C = 2^32 + 977:
#   2^256 == C                   (mod p)
#   2^260 == 16C = 2^36 + 15632  (mod p);  2^36 = 2^(13*2 + 10)
_FOLD_LO = np.uint32(15632)


def to_limbs_np(x: int) -> np.ndarray:
    return np.array(
        [(x >> (LIMB_BITS * i)) & int(MASK) for i in range(N_LIMBS)],
        dtype=np.uint32,
    )


def from_limbs_np(limbs) -> int:
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(np.asarray(limbs)))


def pack_batch_np(values: list[int]) -> np.ndarray:
    """list of ints -> (20, B) uint32."""
    return np.stack([to_limbs_np(v) for v in values], axis=-1)


def _const(value: int) -> np.ndarray:
    """(20, 1) constant, broadcastable against (20, B)."""
    return to_limbs_np(value).reshape(N_LIMBS, 1)


# Subtraction bias: 2p redistributed so every limb i<19 is >= 2^13 and limb
# 19 >= 0x1FF + 1 — (a + BIAS - b) is limbwise non-negative for weak a, b.
def _make_bias() -> np.ndarray:
    l = [int(v) for v in to_limbs_np(2 * P)]
    for i in range(N_LIMBS - 1):
        l[i] += 1 << LIMB_BITS
        l[i + 1] -= 1
    assert all(v >= (1 << LIMB_BITS) for v in l[:-1]) and l[-1] > 0x1FF
    assert sum(v << (LIMB_BITS * i) for i, v in enumerate(l)) == 2 * P
    return np.array(l, dtype=np.uint32).reshape(N_LIMBS, 1)


_BIAS_2P = _make_bias()


# ---- carry & reduction ----

def _sweep(limbs):
    """Carry-propagate along axis 0 (any u32 magnitudes < 2^31 + 2^19).
    Returns (13-bit limbs, carry) — carry < 2^19 at weight 2^(13*L)."""

    def body(carry, row):
        v = row + carry
        return v >> np.uint32(LIMB_BITS), v & MASK

    # init derived from the input so it stays chip-varying under shard_map
    # (an invariant jnp.zeros init trips the scan carry-vma check there)
    carry, out = jax.lax.scan(body, limbs[0] * U32_0, limbs)
    return out, carry


def _fold_260(lo, hi):
    """lo: (20, B) limbs (any magnitude < 2^30); hi: (H, B) 13-bit limbs at
    weights 2^(13*(20+j)). Folds hi in via 2^260 == 2^36 + 15632. Returns
    (max(20, H+2), B) with limbs < 2^31. Requires H + 2 <= 20 + H."""
    h_len = hi.shape[0]
    width = max(lo.shape[0], h_len + 2)
    zero = jnp.zeros((width - lo.shape[0],) + lo.shape[1:], dtype=lo.dtype)
    out = jnp.concatenate([lo, zero], axis=0)
    pr = hi * _FOLD_LO  # < 2^13 * 2^14 = 2^27
    out = out.at[0:h_len].add(pr & MASK)
    out = out.at[1 : h_len + 1].add(pr >> np.uint32(LIMB_BITS))
    out = out.at[2 : h_len + 2].add(hi << np.uint32(10))  # < 2^23
    return out


def _weaken(limbs20):
    """256-bit-boundary fold: bits >= 2^256 (top limb >> 9) fold down by
    C = 2^32 + 977 (977 at limb 0; 2^32 -> limb 2, factor 2^6). Input 13-bit
    normalized; output weak (top limb <= 0x1FF, early limbs may carry +1)."""
    h = limbs20[19] >> np.uint32(9)  # < 2^4
    out = limbs20.at[19].set(limbs20[19] & np.uint32(0x1FF))
    out = out.at[0].add(h * np.uint32(977))
    out = out.at[2].add(h << np.uint32(6))
    head, carry = _sweep(out[:5])
    out = jnp.concatenate([head, out[5:6] + carry, out[6:]], axis=0)
    return out


def f_carry(limbs) -> jnp.ndarray:
    """Normalize any accumulation ((L, B), limbs < 2^31, L in [20, 39]) to
    weak form. Each round: sweep to 13-bit (+carry), fold positions >= 20
    via 2^260 == 16C. Length trajectory 39 -> 23 -> 20 -> 20; the fixed
    round count always settles."""
    for _ in range(3):
        norm, carry = _sweep(limbs)
        hi = jnp.stack([carry & MASK, carry >> np.uint32(LIMB_BITS)], axis=0)
        if norm.shape[0] > N_LIMBS:
            hi = jnp.concatenate([norm[N_LIMBS:], hi], axis=0)
        limbs = _fold_260(norm[:N_LIMBS], hi)
    norm, carry = _sweep(limbs)
    # value < 2^260 by construction now; carry is structurally zero but is
    # folded anyway (no-op when zero) instead of asserting on a traced value
    hi = jnp.stack([carry & MASK, carry >> np.uint32(LIMB_BITS)], axis=0)
    limbs = _fold_260(norm[:N_LIMBS], hi)[:N_LIMBS]
    norm, _ = _sweep(limbs)
    return _weaken(norm)


def f_mul(a, b) -> jnp.ndarray:
    """(20,B) x (20,B) schoolbook; REQUIRES weak inputs. Products < 2^26+eps,
    20-term column sums < 2^31. Output weak."""
    width = 2 * N_LIMBS - 1
    shape = (width,) + tuple(np.broadcast_shapes(a.shape[1:], b.shape[1:]))
    # varying-safe zero init (see _sweep)
    cols0 = jnp.zeros(shape, dtype=jnp.uint32) + (a[0] * b[0] * U32_0)

    def body(i, cols):
        ai = jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=True)  # (1, B)
        return jax.lax.dynamic_update_slice_in_dim(
            cols,
            jax.lax.dynamic_slice_in_dim(cols, i, N_LIMBS, 0) + ai * b,
            i,
            0,
        )

    cols = jax.lax.fori_loop(0, N_LIMBS, body, cols0)
    return f_carry(cols)


def f_sqr(a) -> jnp.ndarray:
    return f_mul(a, a)


def f_add(a, b):
    """Limbwise add of weak values -> 'loose' (limbs < 2^14 + eps)."""
    return a + b


def f_sub(a, b):
    """(a - b) + 2p via the redistributed bias; weak inputs -> 'loose'."""
    return a + _BIAS_2P - b


def f_carry_sub(a, b):
    return f_carry(f_sub(a, b))


# ---- canonical form & comparisons ----

def _f_ge(a, b):
    """a >= b, MSB-first lexicographic over 13-bit-normalized (20,B) limbs."""

    def body(state, rows):
        gt, eq = state
        ai, bi = rows
        gt = gt | (eq & (ai > bi))
        eq = eq & (ai == bi)
        return (gt, eq), None

    init = (a[0] > a[0], a[0] == a[0])  # varying-safe (False…, True…)
    (gt, eq), _ = jax.lax.scan(body, init, (a[::-1], b[::-1]))
    return gt | eq


def _f_sub_exact(a, b):
    """a - b for normalized limbs with a >= b (borrow scan)."""

    def body(borrow, rows):
        ai, bi = rows
        v = ai - bi - borrow
        under = (v >> np.uint32(31)).astype(bool)
        out = jnp.where(under, v + np.uint32(1 << LIMB_BITS), v)
        return under.astype(jnp.uint32), out

    _, out = jax.lax.scan(body, a[0] * U32_0, (a, b))
    return out


_P_CONST = _const(P)


def f_canonical(a_weak):
    """Weak (< 2p) -> canonical [0, p): one conditional subtract of p."""
    p_limbs = jnp.broadcast_to(_P_CONST, a_weak.shape).astype(jnp.uint32)
    ge = _f_ge(a_weak, p_limbs)
    sub = _f_sub_exact(a_weak, p_limbs)
    return jnp.where(ge, sub, a_weak)


def f_is_zero(a_weak):
    return jnp.all(f_canonical(a_weak) == 0, axis=0)


def f_eq(a_weak, b_weak):
    return f_is_zero(f_carry_sub(a_weak, b_weak))


# ---- Jacobian point ops ----
# Point: dict {X, Y, Z: (20,B) weak, inf: (B,) bool}. Coordinate garbage
# under inf=True is never semantically read (selects gate it).

def pt_infinity(batch: int) -> dict:
    one = jnp.broadcast_to(_const(1), (N_LIMBS, batch)).astype(jnp.uint32)
    return {
        "X": one,
        "Y": one,
        "Z": jnp.zeros((N_LIMBS, batch), jnp.uint32),
        "inf": jnp.ones((batch,), bool),
    }


def pt_select(mask, t: dict, f: dict) -> dict:
    return {
        "X": jnp.where(mask, t["X"], f["X"]),
        "Y": jnp.where(mask, t["Y"], f["Y"]),
        "Z": jnp.where(mask, t["Z"], f["Z"]),
        "inf": jnp.where(mask, t["inf"], f["inf"]),
    }


def pt_double(pt: dict) -> dict:
    """Jacobian doubling on y² = x³ + 7 (a = 0) — dbl-2009-l:
    A=X², B=Y², C=B², D=2((X+B)²−A−C), E=3A, F=E²,
    X3=F−2D, Y3=E(D−X3)−8C, Z3=2YZ.
    secp256k1 has no 2-torsion (Y=0 unreachable on-curve), so doubling a
    finite point never lands at infinity — inf propagates unchanged (same
    argument as group_impl.h secp256k1_gej_double)."""
    X, Y, Z = pt["X"], pt["Y"], pt["Z"]
    A = f_sqr(X)
    Bb = f_sqr(Y)
    Cc = f_sqr(Bb)
    D = f_sqr(f_carry(f_add(X, Bb)))
    D = f_carry_sub(D, f_carry(f_add(A, Cc)))
    D = f_carry(f_add(D, D))
    E = f_carry(f_add(f_add(A, A), A))
    F = f_sqr(E)
    X3 = f_carry_sub(F, f_carry(f_add(D, D)))
    Y3 = f_mul(E, f_carry_sub(D, X3))
    C4 = f_carry(f_add(f_add(Cc, Cc), f_add(Cc, Cc)))
    C8 = f_carry(f_add(C4, C4))
    Y3 = f_carry_sub(Y3, C8)
    YZ = f_mul(Y, Z)
    Z3 = f_carry(f_add(YZ, YZ))
    return {"X": X3, "Y": Y3, "Z": Z3, "inf": pt["inf"]}


def pt_add_mixed(pt: dict, qx, qy, q_inf) -> dict:
    """P (Jacobian) + Q (affine), complete via selects — the branchless
    analogue of secp256k1_gej_add_ge_var's case analysis:
      P=inf -> Q;  Q=inf -> P;  P==Q -> double(P);  P==-Q -> infinity.
    madd: Z1Z1=Z², U2=qx·Z1Z1, S2=qy·Z·Z1Z1, H=U2−X, R=S2−Y,
    HH=H², HHH=H·HH, V=X·HH, X3=R²−HHH−2V, Y3=R(V−X3)−Y·HHH, Z3=Z·H."""
    X, Y, Z = pt["X"], pt["Y"], pt["Z"]
    Z1Z1 = f_sqr(Z)
    U2 = f_mul(qx, Z1Z1)
    S2 = f_mul(qy, f_mul(Z, Z1Z1))
    H = f_carry_sub(U2, X)
    R = f_carry_sub(S2, Y)
    h_zero = f_is_zero(H)
    r_zero = f_is_zero(R)
    finite_both = ~pt["inf"] & ~q_inf
    same = h_zero & r_zero & finite_both
    opposite = h_zero & ~r_zero & finite_both
    HH = f_sqr(H)
    HHH = f_mul(H, HH)
    V = f_mul(X, HH)
    X3 = f_carry_sub(f_sqr(R), f_carry(f_add(HHH, f_carry(f_add(V, V)))))
    Y3 = f_carry_sub(f_mul(R, f_carry_sub(V, X3)), f_mul(Y, HHH))
    Z3 = f_mul(Z, H)
    out = {"X": X3, "Y": Y3, "Z": Z3, "inf": opposite}

    out = pt_select(same, pt_double(pt), out)
    q_as_jac = {
        "X": jnp.broadcast_to(qx, X.shape).astype(jnp.uint32),
        "Y": jnp.broadcast_to(qy, X.shape).astype(jnp.uint32),
        "Z": jnp.broadcast_to(_const(1), X.shape).astype(jnp.uint32),
        "inf": q_inf,
    }
    out = pt_select(pt["inf"], q_as_jac, out)
    out = pt_select(q_inf & ~pt["inf"], pt, out)
    return out


# ---- batched u1*G + u2*Q and the verify equation ----

_GX_CONST = _const(GX)
_GY_CONST = _const(GY)


def ecdsa_verify_batch_device(u1_bits, u2_bits, qx, qy, q_inf, r0, rn,
                              wrap_ok):
    """u1_bits/u2_bits: (256, B) uint32 in {0,1}, MSB first. qx/qy/r0/rn:
    (20, B) weak limbs. q_inf: (B,) poison mask (malformed pubkey lanes).
    wrap_ok: (B,) bool — True iff r + n < p, i.e. the x-coordinate
    wraparound candidate rn = r + n is admissible. The reference
    (secp256k1_ecdsa_sig_verify, ecdsa_impl.h) only retries the +n
    candidate under that bound; accepting X == rn·Z² without the gate
    would falsely accept signatures with x_R = r + n - p. The gate is
    enforced HERE, in-kernel, so a host layer cannot mis-use rn.
    Returns (B,) bool validity.

    MSB-first joint double-and-add: 256 x (double + 2 select-merged mixed
    adds) — no data-dependent control flow; poisoned lanes compute garbage
    and report False."""
    batch = qx.shape[1]
    gx = jnp.broadcast_to(_GX_CONST, (N_LIMBS, batch)).astype(jnp.uint32)
    gy = jnp.broadcast_to(_GY_CONST, (N_LIMBS, batch)).astype(jnp.uint32)
    never_inf = jnp.zeros((batch,), bool)

    def step(i, acc):
        acc = pt_double(acc)
        with_g = pt_add_mixed(acc, gx, gy, never_inf)
        acc = pt_select(u1_bits[i].astype(bool), with_g, acc)
        with_q = pt_add_mixed(acc, qx, qy, q_inf)
        acc = pt_select(u2_bits[i].astype(bool) & ~q_inf, with_q, acc)
        return acc

    # infinity init derived from qx/q_inf so the fori_loop carry stays
    # chip-varying under shard_map (parallel/sig_shard)
    zero_v = qx * U32_0
    acc0 = {
        "X": zero_v + _const(1),
        "Y": zero_v + _const(1),
        "Z": zero_v,
        "inf": q_inf | (q_inf == q_inf),  # all True, varying
    }
    acc = jax.lax.fori_loop(0, 256, step, acc0)

    ZZ = f_sqr(acc["Z"])
    ok0 = f_eq(acc["X"], f_mul(r0, ZZ))
    ok1 = f_eq(acc["X"], f_mul(rn, ZZ)) & wrap_ok
    return ~acc["inf"] & ~q_inf & (ok0 | ok1)


@jax.jit
def ecdsa_verify_batch_jit(u1_bits, u2_bits, qx, qy, q_inf, r0, rn, wrap_ok):
    return ecdsa_verify_batch_device(
        u1_bits, u2_bits, qx, qy, q_inf, r0, rn, wrap_ok
    )
