"""TPU proof-of-work nonce sweep.

Replaces the scalar CPU mining loop in src/rpc/mining.cpp:~120
(generateBlocks):

    while (nMaxTries > 0 && nNonce < nInnerLoopCount &&
           !CheckProofOfWork(pblock->GetHash(), nBits, params)) ++nNonce;

with a data-parallel sweep: a `lax.while_loop` over nonce tiles, each tile
hashing TILE nonces at once from the header midstate (2 compressions per
nonce), comparing against the target as 8xu32 LE limbs on-device, and
early-exiting the loop on the first hit. One dispatch sweeps up to the whole
32-bit nonce space; the host polls a tiny (found, nonce, tiles) result.

Multi-chip sharding over ICI lives in parallel/nonce_shard.py (shard_map over
a ('chip',) mesh; each chip owns a contiguous nonce range).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.hashes import header_midstate
from .sha256 import (
    bytes_to_words_np,
    digest_to_limbs,
    le256,
    target_to_limbs_np,
)
from .sha256_sweep import hoist_template, sweep_digest_hoisted

# Default tile: 64Ki nonces per device loop iteration. Large enough to fill
# the 8x128 VPU lanes many times over (amortizing loop overhead), small
# enough to stay comfortably in VMEM (~16 live u32 vectors * 256KiB).
DEFAULT_TILE = 1 << 16


def _sweep_tile(pre, target_limbs, base_nonce, tile: int):
    """Hash one tile of `tile` consecutive nonces; return (hit, nonce).
    `nonce` is the lowest in-tile hit when hit is True (argmax finds the
    first True lane; nonces are base+iota so lane order == nonce order).
    ``pre`` is the per-template chunk-2 hoist (ops/sha256_sweep.
    hoist_template) — computed once per dispatch (or per template swap in
    the resident loop), never per nonce."""
    lanes = jax.lax.broadcasted_iota(jnp.uint32, (tile, 1), 0).squeeze(-1)
    nonces = base_nonce + lanes
    h8 = sweep_digest_hoisted(pre, nonces)
    ok = le256(digest_to_limbs(h8), target_limbs)
    hit = jnp.any(ok)
    idx = jnp.argmax(ok)
    return hit, nonces[idx]


def _boundary_tiles(start_nonce: int, max_nonces: int, tile: int) -> int:
    """Tile count for a sweep from ``start_nonce``, clamped against the
    2^32 nonce-space boundary: a sweep starting near the top must not
    wrap into (and re-hash / over-count) nonces below the start — the
    resident loop's rollover owns wrap policy, one full pass at a time."""
    space = (1 << 32) - (start_nonce & 0xFFFFFFFF)
    return min((max_nonces + tile - 1) // tile, (space + tile - 1) // tile)


@partial(jax.jit, static_argnames=("tile",))
def sweep_jit(midstate, tail, target_limbs, start_nonce, n_tiles, tile: int = DEFAULT_TILE):
    """Sweep [start_nonce, start_nonce + n_tiles*tile) for a PoW hit.

    midstate: (8,) uint32; tail: (3,) uint32 BE words of header bytes 64..75;
    target_limbs: (8,) uint32 LE limbs; start_nonce, n_tiles: uint32 scalars.
    Returns (found bool, nonce uint32, tiles_done uint32). Nonce arithmetic
    wraps mod 2^32 exactly like the reference's uint32 nNonce.
    """
    tgt = [target_limbs[j] for j in range(8)]
    # per-template hoist: traced scalars, computed once per dispatch and
    # lifted out of the while_loop by XLA (loop-invariant)
    pre = hoist_template([midstate[i] for i in range(8)],
                         [tail[i] for i in range(3)])

    def cond(carry):
        i, found, _ = carry
        return jnp.logical_and(i < n_tiles, jnp.logical_not(found))

    def body(carry):
        i, _, _ = carry
        base = start_nonce + i.astype(jnp.uint32) * np.uint32(tile)
        hit, nonce = _sweep_tile(pre, tgt, base, tile)
        return i + np.uint32(1), hit, nonce

    i0 = jnp.uint32(0)
    found0 = jnp.array(False)
    nonce0 = jnp.uint32(0)
    tiles, found, nonce = jax.lax.while_loop(cond, body, (i0, found0, nonce0))
    return found, nonce, tiles


def sweep_header_cpu(header80: bytes, target: int, start_nonce: int = 0,
                     max_nonces: int = 1 << 32):
    """Scalar host sweep — the reference generateBlocks inner loop
    (src/rpc/mining.cpp:~120) verbatim. This is the degraded-mode engine
    the miner circuit breaker falls back to when the device path is dead
    (ops/dispatch.supervised_sweep); same contract as sweep_header: first
    hit in nonce order wins, (nonce | None, hashes_attempted)."""
    from ..crypto.hashes import sha256d

    assert len(header80) == 80
    base = header80[:76]
    for i in range(max_nonces):
        nonce = (start_nonce + i) & 0xFFFFFFFF
        h = sha256d(base + nonce.to_bytes(4, "little"))
        if int.from_bytes(h, "little") <= target:
            return nonce, i + 1
    return None, max_nonces


def sweep_header(header80: bytes, target: int, start_nonce: int = 0,
                 max_nonces: int = 1 << 32, tile: int = DEFAULT_TILE):
    """Host API: search for a nonce making sha256d(header) <= target.

    Returns (nonce or None, hashes_attempted). The header's own nonce field is
    ignored; bytes 0..75 define the search. Mirrors generateBlocks' semantics
    (bounded attempts, first hit wins) at tile granularity. The search is
    clamped at the 2^32 nonce-space boundary (``_boundary_tiles``): a sweep
    starting near the top stops there instead of wrapping into — and
    over-counting / re-hashing — nonces below the start; rollover across
    the boundary is the resident loop's job (mining/resident.py).
    """
    from ..util import devicewatch as dw

    assert len(header80) == 80
    midstate = np.array(header_midstate(header80), dtype=np.uint32)
    tail = bytes_to_words_np(np.frombuffer(header80[64:76], dtype=np.uint8))
    tgt = target_to_limbs_np(target)
    n_tiles = _boundary_tiles(start_nonce, max_nonces, tile)
    # watched dispatch: the compiled shape is the (tile,) specialization —
    # a node mints at most a couple (DEFAULT_TILE + the regtest/CPU tile),
    # so a sweep that starts recompiling per call trips the sentinel
    dw.note_transfer("miner", "h2d",
                     int(midstate.nbytes + tail.nbytes + tgt.nbytes))
    t0 = time.perf_counter()
    with dw.program("miner_sweep", shape_budget=4).dispatch(
            tile, jitfn=sweep_jit,
            args=(midstate, tail, tgt, np.uint32(start_nonce),
                  np.uint32(n_tiles)),
            kwargs={"tile": tile}):
        found, nonce, tiles = sweep_jit(
            jnp.asarray(midstate), jnp.asarray(tail), jnp.asarray(tgt),
            jnp.uint32(start_nonce), jnp.uint32(n_tiles), tile=tile,
        )
        # the jit call above only ENQUEUES — settle inside the watch so
        # the sweep itself lands in the execute phase (the int() fetch
        # below would otherwise be billed the whole kernel as "transfer")
        jax.block_until_ready(tiles)
    dw.note_phase("miner", "execute", time.perf_counter() - t0)
    t0 = time.perf_counter()
    # attempted-hash accounting is also boundary-clamped: the final tile
    # may straddle 2^32, but nonces past the boundary were never part of
    # this sweep's contract
    hashes = min(int(tiles) * tile, (1 << 32) - (start_nonce & 0xFFFFFFFF))
    hit = bool(found)
    dw.note_transfer("miner", "d2h", 12,
                     seconds=time.perf_counter() - t0)
    if hit:
        return int(nonce), hashes
    return None, hashes
