"""Batched SHA-256 / SHA-256d on TPU (jnp core).

Replaces the reference's CPU SHA-256 paths for bulk work:
  - src/crypto/sha256.cpp:~40 (CSHA256::Transform) — 64-round compression,
    here fully unrolled over a uint32 batch so XLA maps it onto the 8x128
    VPU lanes (one message per lane).
  - src/primitives/block.cpp:~13 (CBlockHeader::GetHash) — 80-byte header
    double-SHA, both the full path and the midstate nonce-sweep path
    (SURVEY.md §4.5: header bytes 0..63 are constant across a sweep).
  - src/consensus/merkle.cpp:~45 (ComputeMerkleRoot) — one tree level =
    double-SHA of 64-byte concatenated digest pairs (see ops/merkle.py).

Conventions:
  - All hash state/words are big-endian 32-bit words (SHA-256's native view).
  - "limbs" arrays are the hash reinterpreted as a little-endian uint256 (the
    arith_uint256 view used by CheckProofOfWork): limb[j] = bits 32j..32j+31,
    i.e. limb[j] = bswap32(h[j]).
  - Everything is uint32; additions wrap mod 2^32 as SHA requires.

The scalar Python oracle lives in crypto/hashes.py (sha256_compress); tests
differential-check this module against it and hashlib.
"""

from __future__ import annotations

import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.hashes import SHA256_INIT, SHA256_K

U32 = jnp.uint32

_K = [np.uint32(k) for k in SHA256_K]
_INIT = np.array(SHA256_INIT, dtype=np.uint32)

# SHA-256 bit lengths for the message sizes we batch (in the padding word w15).
_LEN_80B = np.uint32(640)
_LEN_64B = np.uint32(512)
_LEN_32B = np.uint32(256)
_PAD_WORD = np.uint32(0x80000000)
_ZERO = np.uint32(0)


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def bswap32(x):
    """Byte-swap each uint32 lane (wire LE <-> SHA BE word views)."""
    return (
        ((x & np.uint32(0xFF)) << np.uint32(24))
        | ((x & np.uint32(0xFF00)) << np.uint32(8))
        | ((x >> np.uint32(8)) & np.uint32(0xFF00))
        | (x >> np.uint32(24))
    )


def backend_is_cpu() -> bool:
    """True when computation effectively runs on the XLA CPU backend.

    JAX_PLATFORMS=cpu (driver dryrun / CI) beats backend autodetection —
    the axon TPU plugin wins default-backend selection even then, but
    meshes built by parallel/mesh.local_devices honor the env var, so the
    computation really runs on CPU. Shared by every caller that picks a
    compile-friendly form per backend (here, node._select_sweep) so the
    detection logic has exactly one home.
    """
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() == "cpu":
        return True
    dd = jax.config.jax_default_device
    if dd is not None:
        return dd.platform == "cpu"
    return jax.default_backend() == "cpu"


def _use_unrolled() -> bool:
    """Unrolled rounds on TPU (best VPU schedule), lax.fori_loop on CPU.

    XLA's CPU backend (LLVM) compiles the fully-unrolled 64-round dataflow
    superlinearly slowly (minutes per variant — measured this session), while
    the TPU (Mosaic/XLA-TPU) handles it fine. The looped form compiles in ms
    everywhere and is the CI/test path; numerics are identical and both forms
    are differential-tested against hashlib.
    """
    override = os.environ.get("BCP_SHA_UNROLL")
    if override is not None:
        return override not in ("0", "false", "")
    return not backend_is_cpu()


def _compress_unrolled(state8: list, w16: list) -> list:
    ws = list(w16)
    a, b, c, d, e, f, g, h = state8
    for i in range(64):
        if i < 16:
            wi = ws[i]
        else:
            x15, x2 = ws[(i - 15) % 16], ws[(i - 2) % 16]
            s0 = _rotr(x15, 7) ^ _rotr(x15, 18) ^ (x15 >> np.uint32(3))
            s1 = _rotr(x2, 17) ^ _rotr(x2, 19) ^ (x2 >> np.uint32(10))
            ws[i % 16] = ws[i % 16] + s0 + ws[(i - 7) % 16] + s1
            wi = ws[i % 16]
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + _K[i] + wi
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
    out = (a, b, c, d, e, f, g, h)
    return [s + o for s, o in zip(state8, out)]


_K_ARR = np.array(SHA256_K, dtype=np.uint32)


def _compress_looped(state8: list, w16: list) -> list:
    """fori_loop form with a 16-word rolling schedule ring. The index
    identities (i-15)%16 == (i+1)%16 etc. keep all ring offsets positive."""
    zero = state8[0] * _ZERO
    for w in w16:
        zero = zero + w * _ZERO  # unify broadcast shape across state & words
    ws = jnp.stack([w + zero for w in w16])  # (16, ...)
    k = jnp.asarray(_K_ARR)

    def body(i, carry):
        a, b, c, d, e, f, g, h, ws = carry
        j = jax.lax.rem(i, 16)
        x16 = jax.lax.dynamic_index_in_dim(ws, j, 0, keepdims=False)
        x15 = jax.lax.dynamic_index_in_dim(ws, jax.lax.rem(i + 1, 16), 0, keepdims=False)
        x7 = jax.lax.dynamic_index_in_dim(ws, jax.lax.rem(i + 9, 16), 0, keepdims=False)
        x2 = jax.lax.dynamic_index_in_dim(ws, jax.lax.rem(i + 14, 16), 0, keepdims=False)
        s0w = _rotr(x15, 7) ^ _rotr(x15, 18) ^ (x15 >> np.uint32(3))
        s1w = _rotr(x2, 17) ^ _rotr(x2, 19) ^ (x2 >> np.uint32(10))
        wnew = x16 + s0w + x7 + s1w
        wi = jnp.where(i >= 16, wnew, x16)
        ws = jax.lax.dynamic_update_index_in_dim(ws, wi, j, 0)
        ki = jax.lax.dynamic_index_in_dim(k, i, 0, keepdims=False)
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + ki + wi
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        # rotation in (a..h) carry order: a'=t1+t2, b'=a, ..., e'=d+t1, ...
        return (t1 + t2, a, b, c, d + t1, e, f, g, ws)

    init = tuple(s + zero for s in state8) + (ws,)
    *out, _ = jax.lax.fori_loop(0, 64, body, init)
    return [s + o for s, o in zip(state8, out)]


def compress(state8: list, w16: list) -> list:
    """One SHA-256 compression over a batch — CSHA256::Transform
    (src/crypto/sha256.cpp:~40).

    state8: list of 8 uint32 arrays (broadcastable), w16: list of 16 uint32
    arrays (the message schedule seed). Returns the new state as a list of 8
    arrays. List-of-arrays (SoA) form keeps every round a pure elementwise
    VPU op with no gathers on the unrolled path.
    """
    if _use_unrolled():
        return _compress_unrolled(state8, w16)
    return _compress_looped(state8, w16)


def _init_state(like) -> list:
    """Fresh SHA-256 initial state broadcast against `like`'s shape."""
    zero = like * _ZERO
    return [zero + np.uint32(v) for v in _INIT]


def sha256_of_state(h8: list) -> list:
    """SHA-256 of a 32-byte digest held as 8 state words — the second hash of
    every double-SHA. Single padded block: msg || 0x80 || len=256."""
    zero = h8[0] * _ZERO
    w = list(h8) + [zero + _PAD_WORD] + [zero] * 6 + [zero + _LEN_32B]
    return compress(_init_state(h8[0]), w)


def sha256d_64(w16: list) -> list:
    """Double-SHA256 of a 64-byte message given as 16 BE words (batched).
    The Merkle inner-node hash (src/consensus/merkle.cpp:~45): 3 compressions
    (message block, padding block, second hash)."""
    zero = w16[0] * _ZERO
    h = compress(_init_state(w16[0]), w16)
    pad_block = [zero + _PAD_WORD] + [zero] * 14 + [zero + _LEN_64B]
    h = compress(h, pad_block)
    return sha256_of_state(h)


def sha256d_80(w20: list) -> list:
    """Double-SHA256 of an 80-byte message given as 20 BE words (batched) —
    CBlockHeader::GetHash without midstate reuse (full-header batch path,
    used for validating many headers at once)."""
    zero = w20[0] * _ZERO
    h = compress(_init_state(w20[0]), w20[:16])
    tail_block = (
        w20[16:20] + [zero + _PAD_WORD] + [zero] * 10 + [zero + _LEN_80B]
    )
    h = compress(h, tail_block)
    return sha256_of_state(h)


def header_sweep_digest(midstate8: list, tail3: list, nonces):
    """SHA-256d digests for a nonce sweep from a precomputed midstate.

    midstate8: 8 scalars/arrays — SHA-256 state after header bytes 0..63
    (crypto/hashes.header_midstate). tail3: BE words of header bytes 64..75
    (merkle tail, nTime, nBits). nonces: uint32 array of candidate nonces
    (host byte order; the header stores them LE so the BE message word is
    bswap32(nonce)).

    Returns 8 digest state words, each shaped like `nonces`. Cost: 2
    compressions per nonce (vs 3 without midstate) — the optimization the
    scalar reference loop (src/rpc/mining.cpp:~120) misses.

    This is the UNHOISTED reference form: the production sweep tile
    (ops/miner._sweep_tile) rides ops/sha256_sweep.sweep_digest_hoisted,
    which additionally hoists the chunk-2 sweep-constant rounds/schedule
    legs per template (ROOFLINE.md §8); tests differential the two.
    """
    zero = nonces * _ZERO
    w = (
        [zero + t for t in tail3]
        + [bswap32(nonces)]
        + [zero + _PAD_WORD]
        + [zero] * 10
        + [zero + _LEN_80B]
    )
    h = compress([zero + m for m in midstate8], w)
    return sha256_of_state(h)


def digest_to_limbs(h8: list) -> list:
    """Reinterpret digest state words as little-endian uint256 limbs
    (arith_uint256 view): limb[j] = bswap32(h[j]), limb 7 most significant."""
    return [bswap32(h) for h in h8]


def le256(limbs: list, target_limbs: list):
    """Branchless lexicographic hash <= target over LE limb arrays —
    CheckProofOfWork's arith_uint256 compare (src/pow.cpp:~74), evaluated
    per lane from the most significant limb down."""
    le = limbs[0] <= target_limbs[0]
    for j in range(1, 8):
        l, t = limbs[j], target_limbs[j]
        le = (l < t) | ((l == t) & le)
    return le


# ---- host-side packing helpers (numpy, not traced) ----

def target_to_limbs_np(target: int) -> np.ndarray:
    """256-bit target -> 8 LE uint32 limbs for the on-chip compare."""
    return np.array(
        [(target >> (32 * j)) & 0xFFFFFFFF for j in range(8)], dtype=np.uint32
    )


def digests_to_bytes(h8) -> np.ndarray:
    """Device digest state (8 arrays shaped (...,)) -> (..., 32) uint8 wire
    digests (BE bytes per word, as SHA outputs)."""
    stacked = np.stack([np.asarray(h) for h in h8], axis=-1)  # (..., 8)
    return stacked.astype(">u4").view(np.uint8).reshape(*stacked.shape[:-1], 32)


def bytes_to_words_np(data: np.ndarray) -> np.ndarray:
    """(..., 4k) uint8 byte array -> (..., k) uint32 BE words."""
    assert data.dtype == np.uint8 and data.shape[-1] % 4 == 0
    return (
        data.reshape(*data.shape[:-1], data.shape[-1] // 4, 4)
        .view(">u4")  # big-endian words, SHA's native view
        .squeeze(-1)
        .astype(np.uint32)
    )


def headers_to_words_np(headers: np.ndarray) -> np.ndarray:
    """(B, 80) uint8 serialized headers -> (B, 20) uint32 BE words."""
    assert headers.shape[-1] == 80
    return bytes_to_words_np(headers)


# ---- jitted batch entry points ----

@jax.jit
def sha256d_headers_jit(words20):
    """(B, 20) uint32 BE header words -> (B, 8) digest state words."""
    h8 = sha256d_80([words20[:, i] for i in range(20)])
    return jnp.stack(h8, axis=-1)


@jax.jit
def check_headers_pow_jit(words20, target_limbs):
    """(B, 20) header words + (8,) target limbs -> ((B,8) digests, (B,) ok).
    Batch header PoW validation for headers-first sync / reindex."""
    h8 = sha256d_80([words20[:, i] for i in range(20)])
    ok = le256(digest_to_limbs(h8), [target_limbs[j] for j in range(8)])
    return jnp.stack(h8, axis=-1), ok


def sha256d_headers_cpu(headers: np.ndarray) -> np.ndarray:
    """Reference CPU engine for the batch header hash — the sha256 circuit
    breaker's fallback target (ops/dispatch)."""
    from ..crypto.hashes import sha256d

    return np.frombuffer(
        b"".join(sha256d(headers[i].tobytes())
                 for i in range(headers.shape[0])),
        dtype=np.uint8,
    ).reshape(-1, 32)


def sha256d_headers(headers: np.ndarray) -> np.ndarray:
    """Convenience host API: (B, 80) uint8 headers -> (B, 32) uint8 digests.

    Supervised (ops/dispatch): the device batch is spot-checked against the
    host hash of lane 0 before it is trusted; failures/poison degrade to
    the per-header CPU loop without changing a single digest. The device
    leg is watched (util/devicewatch): header batches legitimately vary
    in size, so the program carries NO shape budget — compiles are
    counted and timed, never flagged."""
    from ..crypto.hashes import sha256d
    from ..util import devicewatch as dw
    from . import dispatch

    if headers.shape[0] == 0:
        return np.zeros((0, 32), dtype=np.uint8)

    def device() -> np.ndarray:
        words_np = headers_to_words_np(headers)
        dw.note_transfer("sha256", "h2d", int(words_np.nbytes))
        words = jnp.asarray(words_np)
        with dw.program("sha256_headers").dispatch(
                words_np.shape, jitfn=sha256d_headers_jit,
                args=(words_np,)):
            h = sha256d_headers_jit(words)
        t0 = time.perf_counter()
        out = digests_to_bytes([np.asarray(h[:, i]) for i in range(8)])
        dw.note_transfer("sha256", "d2h", int(out.nbytes),
                         seconds=time.perf_counter() - t0)
        return out

    def validate(digests: np.ndarray) -> bool:
        return digests[0].tobytes() == sha256d(headers[0].tobytes())

    out, _ = dispatch.supervised_call(
        "sha256", device, lambda: sha256d_headers_cpu(headers),
        validate=validate,
        poison=lambda d: np.bitwise_xor(d, np.uint8(0xFF)),
        items=int(headers.shape[0]),
    )
    return out
