"""Pallas/Mosaic TPU kernel for the SHA-256d nonce sweep.

Same search as ops/sha256_sweep.sweep_fast_jit (truncated-h7 candidate
sweep — see that module for the specialization math and the reference
citations), but hand-lowered through Pallas so the whole sweep runs as ONE
Mosaic kernel:

  - the nonce lattice is a VMEM-resident (sublanes, 128) u32 tile per grid
    step, generated in-register from a 2D iota (no HBM traffic at all:
    inputs are 8+3+2 scalars in SMEM, outputs are 3 scalars);
  - the grid dimension walks nonce tiles sequentially (TPU grid semantics),
    with an SMEM `found` flag checked via pl.when — tiles after the first
    hit are skipped, giving the same early-exit the lax.while_loop path has;
  - the first hit inside a tile is extracted with a min-reduction over
    linear lane indices (u32), avoiding 1D reshapes Mosaic dislikes.

The XLA and Pallas paths are differential-tested against each other and the
hashlib oracle; bench.py picks whichever is faster on the real chip.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..crypto.hashes import header_midstate, sha256d
from .sha256 import bswap32, bytes_to_words_np, target_to_limbs_np
from .sha256_sweep import sweep_h7

# Mosaic has no unsigned reductions, so the first-hit min runs on int32
# linear indices (always < 2^31 for any sane tile size).
_NOHIT = np.int32(0x7FFFFFFF)

# Tile geometry: (sublanes, 128) u32 lattice per grid step, swept on the
# real chip (tools/roofline.py): small tiles with very large grids win —
# the ~120-vector live set of the unrolled rounds must stay far below VMEM
# (64x128 u32 = 32KiB/vector ≈ 4MiB live), and the sequential grid is the
# cheap way to amortize per-dispatch overhead. Measured v5e-lite optimum:
# sublanes=64, grid 256Ki (0.95 GH/s vs 0.36-0.81 for 128-512 sublanes).
DEFAULT_SUBLANES = 64
LANES = 128


def _sweep_kernel(mid_ref, tail_ref, t7_ref, start_ref, ntiles_ref,
                  found_ref, nonce_ref, tiles_ref, *, sublanes: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        found_ref[0] = np.uint32(0)
        nonce_ref[0] = np.uint32(0)
        tiles_ref[0] = np.uint32(0)

    live = jnp.logical_and(found_ref[0] == 0,
                           i.astype(jnp.uint32) < ntiles_ref[0])

    @pl.when(live)
    def _work():
        tile = np.uint32(sublanes * LANES)
        base = start_ref[0] + i.astype(jnp.uint32) * tile
        rows = jax.lax.broadcasted_iota(jnp.uint32, (sublanes, LANES), 0)
        cols = jax.lax.broadcasted_iota(jnp.uint32, (sublanes, LANES), 1)
        lin = rows * np.uint32(LANES) + cols
        nonces = base + lin
        mid8 = [mid_ref[j] for j in range(8)]
        tail3 = [tail_ref[j] for j in range(3)]
        h7 = sweep_h7(mid8, tail3, nonces)
        ok = bswap32(h7) <= t7_ref[0]
        # first hit == smallest linear index among hits (lane order == nonce
        # order); _NOHIT if the tile has none.
        idx = jnp.min(jnp.where(ok, lin.astype(jnp.int32), _NOHIT))
        tiles_ref[0] = tiles_ref[0] + np.uint32(1)

        @pl.when(idx != _NOHIT)
        def _record():
            found_ref[0] = np.uint32(1)
            nonce_ref[0] = base + idx.astype(jnp.uint32)

    del _init, _work


@partial(jax.jit, static_argnames=("sublanes", "max_tiles", "interpret"))
def pallas_sweep_jit(midstate, tail, t7, start_nonce, n_tiles,
                     sublanes: int = DEFAULT_SUBLANES,
                     max_tiles: int = 4096, interpret: bool = False):
    """Candidate sweep of [start, start + n_tiles*tile) on the Pallas kernel.

    The grid is static (max_tiles); n_tiles (dynamic, <= max_tiles) gates the
    live programs so one compilation serves every sweep length. Returns
    (found bool, nonce u32, tiles_done u32) — the same contract as
    sha256_sweep.sweep_fast_jit; candidates need the host exact-check.
    """
    kernel = partial(_sweep_kernel, sublanes=sublanes)
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)  # noqa: E731
    found, nonce, tiles = pl.pallas_call(
        kernel,
        grid=(max_tiles,),
        in_specs=[smem(), smem(), smem(), smem(), smem()],
        out_specs=[smem(), smem(), smem()],
        out_shape=[
            jax.ShapeDtypeStruct((1,), jnp.uint32),
            jax.ShapeDtypeStruct((1,), jnp.uint32),
            jax.ShapeDtypeStruct((1,), jnp.uint32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(midstate, tail, jnp.reshape(t7, (1,)), jnp.reshape(start_nonce, (1,)),
      jnp.reshape(n_tiles, (1,)))
    return found[0] != 0, nonce[0], tiles[0]


def sweep_header_pallas(header80: bytes, target: int, start_nonce: int = 0,
                        max_nonces: int = 1 << 32,
                        sublanes: int = DEFAULT_SUBLANES,
                        max_tiles: int = 4096, interpret: bool = False):
    """Host API mirroring ops.sha256_sweep.sweep_header_fast on the Pallas
    kernel: exact (first-hit, bit-identical) results via host verification
    of device candidates."""
    assert len(header80) == 80
    midstate = jnp.asarray(np.array(header_midstate(header80), dtype=np.uint32))
    tail = jnp.asarray(bytes_to_words_np(np.frombuffer(header80[64:76], np.uint8)))
    t7 = jnp.uint32(target_to_limbs_np(target)[7])
    tile = sublanes * LANES

    hashes = 0
    nonce = start_nonce & 0xFFFFFFFF
    remaining = max_nonces
    while remaining > 0:
        want = min((remaining + tile - 1) // tile, (1 << 32) // tile)
        n_tiles = min(want, max_tiles)
        found, cand, tiles = pallas_sweep_jit(
            midstate, tail, t7, jnp.uint32(nonce), jnp.uint32(n_tiles),
            sublanes=sublanes, max_tiles=max_tiles, interpret=interpret,
        )
        hashes += int(tiles) * tile
        if bool(found):
            cand = int(cand)
            hdr = header80[:76] + cand.to_bytes(4, "little")
            if int.from_bytes(sha256d(hdr), "little") <= target:
                return cand, hashes
            consumed = (cand - nonce) & 0xFFFFFFFF
            remaining -= consumed + 1
            nonce = (cand + 1) & 0xFFFFFFFF
        else:
            remaining -= int(tiles) * tile
            nonce = (nonce + int(tiles) * tile) & 0xFFFFFFFF
            if int(tiles) == 0:
                break
    return None, hashes
