"""Supervised backend dispatch — circuit breakers over every TPU crossing.

Every consensus-relevant accelerator call (ops/sha256, ops/merkle,
ops/miner, ops/ecdsa_batch) funnels through ``supervised_call``: bounded
retries with jittered backoff absorb transient device errors; a per-
subsystem circuit breaker opens after N consecutive hard failures and
routes traffic to the reference CPU engine; probabilistic half-open probes
re-test the device and close the breaker on recovery. The ecdsa site
additionally carries a KERNEL chain inside the breaker boundary
(glv -> w4 -> XLA ladder, -ecdsakernel selects; ops/ecdsa_batch): the
known-answer probe lanes ride — and therefore validate — whichever
kernel actually served the batch, so a lying GLV mask is caught by the
same KAT gate as any other device fault. Validation probes
(known-answer lanes, witness pairs, hit re-verification) catch poisoned
device output before it is trusted, and every REJECT-side verdict is
additionally host-confirmed (ecdsa_batch False lanes, merkle_root
mismatches/mutations, pow batch failures) — a degraded backend costs
throughput, never correctness; the accept-side probes are defense-in-
depth against faulty hardware rather than a proof against an
adversarially crafted device.

State is surfaced via rpc/control.py's ``gettpuinfo`` (breaker state, trip
counts, fallback call/item tallies) and reset per test through
``reset()``/``configure()``. No jax import at module level: validation/
and the crash-test workers import this without touching the backend.

Env knobs (read at configure time):
    BCP_BREAKER_THRESHOLD  consecutive failures to open (default 3)
    BCP_BREAKER_COOLDOWN   seconds open before probes start (default 5)
    BCP_BREAKER_PROBE      half-open probe probability (default 0.25)
    BCP_BREAKER_RETRIES    in-call retries before a failure counts (def. 1)
    BCP_TPU_MERKLE_MIN     leaf count floor for the device Merkle path
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..util import devicewatch as dw
from ..util import telemetry as tm
from ..util.faults import INJECTOR, Backoff, PoisonedOutput, retry_call
from ..util.log import log_print, log_printf

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

# -- telemetry families (util/telemetry): per-subsystem dispatch latency
# split by the path that served the call, retry/fallback tallies, and a
# breaker-state collector projecting the live registry at scrape time.
_LAT = tm.histogram(
    "bcp_dispatch_latency_seconds",
    "Supervised backend-crossing latency per subsystem and serving path "
    "(device = the accelerator served it, cpu = breaker/failure fallback, "
    "settle = async handle materialization)",
    labels=("site", "path"))
_RETRIES = tm.counter(
    "bcp_dispatch_retries_total",
    "Same-call device retries absorbed by supervised dispatch",
    labels=("site",))
_FALLBACKS = tm.counter(
    "bcp_dispatch_fallback_total",
    "Calls served by the CPU engine because the device path was open or "
    "failed", labels=("site",))

_BREAKER_STATE_NUM = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


def _collect_breakers():
    """Registry collector: breaker state (0 closed / 1 half-open / 2 open)
    and the trip/probe/fallback tallies, one sample per subsystem."""
    snaps = snapshot()
    if not snaps:
        return []
    state = {"name": "bcp_breaker_state", "type": "gauge",
             "help": "Circuit-breaker state per subsystem "
                     "(0=closed 1=half-open 2=open)",
             "samples": []}
    out = [state]
    for field, help_ in (
        ("trips", "Times the breaker opened"),
        ("probes", "Half-open probes attempted"),
        ("recoveries", "Probes that closed the breaker"),
        ("fallback_calls", "Calls routed to the CPU engine"),
        ("fallback_items", "Items (sigs/hashes/leaves) served on CPU"),
    ):
        fam = {"name": f"bcp_breaker_{field}_total", "type": "counter",
               "help": help_, "samples": []}
        for name, snap in snaps.items():
            fam["samples"].append(({"subsystem": name}, snap[field]))
        out.append(fam)
    for name, snap in snaps.items():
        state["samples"].append(
            ({"subsystem": name}, _BREAKER_STATE_NUM[snap["state"]]))
    return out


tm.register_collector("dispatch_breakers", _collect_breakers)


@dataclass
class BreakerConfig:
    threshold: int = 3       # consecutive failures -> open
    cooldown: float = 5.0    # seconds open before probes may fire
    probe: float = 0.25      # half-open probe probability per allow()
    retries: int = 1         # same-call retries before a failure counts
    backoff_base: float = 0.02  # first retry delay (jittered, doubling)
    seed: Optional[int] = None  # probe rng seed (tests)

    @classmethod
    def from_env(cls) -> "BreakerConfig":
        g = os.environ.get
        return cls(
            threshold=int(g("BCP_BREAKER_THRESHOLD", "3")),
            cooldown=float(g("BCP_BREAKER_COOLDOWN", "5")),
            probe=float(g("BCP_BREAKER_PROBE", "0.25")),
            retries=int(g("BCP_BREAKER_RETRIES", "1")),
        )


class CircuitBreaker:
    """Per-subsystem failure gate (closed -> open -> half-open -> closed).

    ``allow()`` answers "may this call try the device?"; callers then report
    record_success()/record_failure(). While OPEN, allow() flips to a
    HALF_OPEN probe with probability cfg.probe once the cooldown elapsed —
    probabilistic probing keeps a recovering device from being stampeded by
    every pending caller at once. Thread-safe: RPC threads and the P2P loop
    read state while the validation thread dispatches."""

    def __init__(self, name: str, cfg: Optional[BreakerConfig] = None,
                 clock=time.monotonic):
        self.name = name
        self.cfg = cfg if cfg is not None else BreakerConfig.from_env()
        self._clock = clock
        self._rng = random.Random(self.cfg.seed)
        self._lock = threading.Lock()
        self.state = CLOSED
        self.consecutive_failures = 0
        self.trips = 0            # times the breaker opened
        self.opened_at = 0.0
        self.probes = 0           # half-open probes attempted
        self.recoveries = 0       # probes that closed the breaker
        self.fallback_calls = 0   # calls routed to the CPU engine
        self.fallback_items = 0   # items (sigs/hashes/leaves) in those calls
        self.last_error = ""

    def allow(self) -> bool:
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if (self._clock() - self.opened_at >= self.cfg.cooldown
                        and self._rng.random() < self.cfg.probe):
                    self.state = HALF_OPEN
                    self.probes += 1
                    return True
                return False
            # HALF_OPEN: one probe in flight; everyone else stays on CPU
            return False

    def record_success(self) -> None:
        with self._lock:
            if self.state == HALF_OPEN:
                self.recoveries += 1
                log_printf("breaker %s: half-open probe succeeded — closed",
                           self.name)
            self.state = CLOSED
            self.consecutive_failures = 0

    def record_failure(self, err: Optional[BaseException] = None) -> None:
        with self._lock:
            self.consecutive_failures += 1
            if err is not None:
                self.last_error = f"{type(err).__name__}: {err}"[:200]
            if self.state == HALF_OPEN or (
                self.state == CLOSED
                and self.consecutive_failures >= self.cfg.threshold
            ):
                reopened = self.state == HALF_OPEN
                self.state = OPEN
                self.opened_at = self._clock()
                self.trips += 1
                log_printf(
                    "breaker %s: %s after %d consecutive failure(s) (%s)",
                    self.name, "re-opened" if reopened else "OPEN",
                    self.consecutive_failures, self.last_error)

    def note_fallback(self, items: int = 1) -> None:
        with self._lock:
            self.fallback_calls += 1
            self.fallback_items += max(0, int(items))
        _FALLBACKS.labels(site=self.name).inc()

    def healthy(self) -> bool:
        """Read-only probe: is the device path currently trusted? Unlike
        allow() this never mutates state (no half-open transition), so
        planners — e.g. the ecdsa cross-block lane packer deciding whether
        aggregating for full device buckets is worth the latency — can
        consult it per item without stealing recovery probes."""
        with self._lock:
            return self.state == CLOSED

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "trips": self.trips,
                "probes": self.probes,
                "recoveries": self.recoveries,
                "fallback_calls": self.fallback_calls,
                "fallback_items": self.fallback_items,
                "last_error": self.last_error,
            }


_CONFIG = BreakerConfig.from_env()
_BREAKERS: dict[str, CircuitBreaker] = {}
_REG_LOCK = threading.Lock()


def breaker(name: str) -> CircuitBreaker:
    with _REG_LOCK:
        br = _BREAKERS.get(name)
        if br is None:
            br = _BREAKERS[name] = CircuitBreaker(name, cfg=_CONFIG)
        return br


def configure(**kwargs) -> BreakerConfig:
    """Replace the breaker config (tests: threshold/cooldown/probe/seed)
    and rebuild the registry so it applies to every subsystem."""
    global _CONFIG
    base = BreakerConfig.from_env()
    for k, v in kwargs.items():
        setattr(base, k, v)
    _CONFIG = base
    with _REG_LOCK:
        _BREAKERS.clear()
    return base


def reset() -> None:
    """Drop all breaker state and re-read env config (test isolation)."""
    global _CONFIG
    _CONFIG = BreakerConfig.from_env()
    with _REG_LOCK:
        _BREAKERS.clear()


def snapshot() -> dict:
    """gettpuinfo's ``breakers`` section: every subsystem that has been
    touched this process, keyed by name."""
    with _REG_LOCK:
        return {name: br.snapshot() for name, br in _BREAKERS.items()}


def supervised_call(site: str, device_fn: Callable, cpu_fn: Callable,
                    validate: Optional[Callable] = None,
                    poison: Optional[Callable] = None,
                    items: int = 1):
    """Run one backend-crossing call under supervision.

    device_fn() is attempted (with cfg.retries same-call retries and
    jittered backoff between them) unless the breaker is open; its output
    is passed through ``validate`` (a cheap host-side probe returning
    truthy on sane output) before it is trusted. Any exception or failed
    validation after the retries counts one breaker failure and the call
    is served by cpu_fn() instead. ``poison`` is the fault harness's
    output-corruption hook (applied when BCP_FAULT_MODE=poison-output is
    armed for this site) — it exists so tests can prove the validation
    probe actually gates the verdict path.

    Returns (result, used_device)."""
    br = breaker(site)
    if br.allow():
        calls = [0]

        def attempt():
            calls[0] += 1
            INJECTOR.on_call(site)
            out = device_fn()
            if poison is not None and INJECTOR.should_poison(site):
                out = poison(out)
            if validate is not None and not validate(out):
                raise PoisonedOutput(
                    f"{site}: device output failed validation probe")
            return out

        t0 = time.monotonic()
        try:
            with tm.span("dispatch.call", site=site, items=items):
                out = retry_call(
                    attempt, attempts=br.cfg.retries + 1,
                    backoff=Backoff(base=br.cfg.backoff_base, maximum=1.0),
                )
            br.record_success()
            if calls[0] > 1:
                _RETRIES.labels(site=site).inc(calls[0] - 1)
            dt = time.monotonic() - t0
            _LAT.labels(site=site, path="device").observe(dt)
            # synchronous crossing: the whole device leg (dispatch +
            # blocking materialization inside device_fn) is one
            # "execute" phase — async sites split execute/fetch
            # themselves (util/devicewatch phase vocabulary)
            dw.note_phase(site, "execute", dt)
            return out, True
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 — breaker boundary
            br.record_failure(e)
            if calls[0] > 1:
                _RETRIES.labels(site=site).inc(calls[0] - 1)
            log_print("tpu", "%s device call failed (%s) — CPU fallback",
                      site, e)
    br.note_fallback(items)
    t0 = time.monotonic()
    out = cpu_fn()
    _LAT.labels(site=site, path="cpu").observe(time.monotonic() - t0)
    return out, False


class SupervisedHandle:
    """An enqueued device computation under breaker supervision — the async
    counterpart of supervised_call, for any site that wants to overlap
    host work with device settle (SURVEY.md §3.2 P3). The ECDSA pipeline
    itself rides its specialized equivalent (ecdsa_batch.BatchHandle,
    which adds KAT lanes and reject-side host confirmation); this is the
    GENERIC form for the other subsystems' future async crossings.

    The enqueue runs eagerly (breaker-gated, fault-injected); validation
    probes, breaker accounting, and the CPU fallback all run at result()
    time, so an unresolved handle can ride in a pipeline for many host
    steps without losing supervision. result() is memoized and safe to
    call from multiple consumers (the first settle pays; the rest read)."""

    __slots__ = ("_site", "_pending", "_cpu_fn", "_validate", "_poison",
                 "_items", "_result", "_done", "used_device", "_ctx")

    def __init__(self, site, pending, cpu_fn, validate, poison, items,
                 used_device, ctx=None):
        self._site = site
        self._pending = pending      # zero-arg materializer, or None
        self._cpu_fn = cpu_fn
        self._validate = validate
        self._poison = poison
        self._items = items
        self._result = None
        self._done = pending is None
        self.used_device = used_device
        # trace-correlation handoff: the enqueue-side span context rides
        # the handle so the settle span — often on ANOTHER thread — links
        # back to the dispatching block's correlation chain
        self._ctx = ctx
        if self._done:
            self._result = cpu_fn()  # CPU path is synchronous anyway

    def result(self):
        if self._done:
            return self._result
        br = breaker(self._site)
        t0 = time.monotonic()
        try:
            with tm.span("dispatch.settle", parent=self._ctx,
                         site=self._site, items=self._items):
                out = self._pending()
            if self._poison is not None and INJECTOR.should_poison(self._site):
                out = self._poison(out)
            if self._validate is not None and not self._validate(out):
                raise PoisonedOutput(
                    f"{self._site}: device output failed validation probe")
            br.record_success()
            dt = time.monotonic() - t0
            _LAT.labels(site=self._site, path="settle").observe(dt)
            # async crossing: result() blocks on materialization — the
            # "fetch" phase of the dispatch decomposition
            dw.note_phase(self._site, "fetch", dt)
            self._result = out
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 — breaker boundary
            br.record_failure(e)
            br.note_fallback(self._items)
            log_print("tpu", "%s async settle failed (%s) — CPU fallback",
                      self._site, e)
            self._result = self._cpu_fn()
            self.used_device = False
        self._pending = None
        self._done = True
        return self._result


def supervised_enqueue(site: str, enqueue_fn: Callable, cpu_fn: Callable,
                       validate: Optional[Callable] = None,
                       poison: Optional[Callable] = None,
                       items: int = 1) -> SupervisedHandle:
    """Async supervised dispatch: enqueue_fn() must START the device work
    and return a zero-arg materializer that blocks until it settles (JAX
    async dispatch returns array futures, so `lambda: np.asarray(dev_out)`
    is the usual shape). A breaker-open site, or an enqueue_fn that raises,
    degrades to a handle whose result() is cpu_fn() — the caller's pipeline
    shape is preserved either way."""
    br = breaker(site)
    if br.allow():
        try:
            with tm.span("dispatch.enqueue", site=site, items=items):
                INJECTOR.on_call(site)
                pending = enqueue_fn()
                ctx = tm.trace_context()  # the enqueue span itself
            return SupervisedHandle(site, pending, cpu_fn, validate, poison,
                                    items, used_device=True, ctx=ctx)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 — breaker boundary
            br.record_failure(e)
            log_print("tpu", "%s async enqueue failed (%s) — CPU fallback",
                      site, e)
    br.note_fallback(items)
    return SupervisedHandle(site, None, cpu_fn, validate, poison, items,
                            used_device=False)


# ---------------------------------------------------------------------------
# Subsystem front doors used by validation/ and mining/ (lazy device import
# so CPU-only paths and crash-test workers never touch jax).
# ---------------------------------------------------------------------------

def _merkle_device_min() -> int:
    """Leaf-count floor for the device Merkle path: below it the dispatch
    round trip loses to the host loop (and ordinary regtest blocks stay on
    the byte-exact CPU reference)."""
    return int(os.environ.get("BCP_TPU_MERKLE_MIN", "512"))


def merkle_root(hashes: list, expected: Optional[bytes] = None) -> tuple:
    """Supervised Merkle root: device tree-reduction for large leaf sets,
    reference CPU loop otherwise (and whenever the merkle breaker is
    open).

    ``expected`` is the caller's claimed root (the block header's). A
    device result is never the sole basis for a VERDICT CHANGE in either
    direction:

    - reject side: a device root mismatch or mutated=True is confirmed by
      a full CPU recompute before it is returned — the witness probe
      catches gross corruption cheaply, but a single corrupted interior
      lane could otherwise pass it and make a lying device reject a valid
      block (forking the node off the honest chain);
    - accept side: a device mutated=False is only trusted when the leaf
      set has no duplicates. Equal interior nodes require equal leaf
      subsequences (absent SHA-256 collisions), so distinct leaves imply
      no CVE-2012-2459 mutation; any duplicate leaf forces the CPU
      reference to produce the flag.

    A bad device may cost one CPU recompute, never a verdict."""
    if len(hashes) >= _merkle_device_min():
        from ..consensus.merkle import compute_merkle_root
        from .merkle import compute_merkle_root_tpu_ex

        root, mutated, used_device = compute_merkle_root_tpu_ex(hashes)
        if used_device and (
            mutated
            or (expected is not None and root != expected)
            or len(set(hashes)) != len(hashes)
        ):
            return compute_merkle_root(hashes)
        return root, mutated
    from ..consensus.merkle import compute_merkle_root

    return compute_merkle_root(hashes)


def block_merkle_root(block) -> tuple:
    """BlockMerkleRoot through the supervised chooser (chainstate's
    check_block entry); the header's claimed root gates reject-path
    CPU confirmation."""
    return merkle_root([tx.txid for tx in block.vtx],
                       expected=block.header.hash_merkle_root)


def supervised_resident_sweep(resident):
    """Wrap a mining/resident.ResidentSweep's persistent loop in miner
    supervision: the resident segment pipeline (device-side buffer swaps,
    candidate FIFO, nonce rollover) runs as the device path, a claimed
    hit is host re-verified, and any device failure — including a dead
    backend mid-pipeline — degrades to the scalar host loop under the
    same miner circuit breaker as the per-dispatch path. The resident
    program rides the devicewatch compile sentinel as ``miner_resident``
    with its own shape budget (a template swap must never retrace)."""
    return supervised_sweep(inner=resident.sweep)


def supervised_sweep(inner=None):
    """Wrap a PoW sweep implementation (ops/miner.sweep_header,
    ops/sha256_sweep.sweep_header_fast, mining/resident.ResidentSweep.sweep,
    or the multi-chip shard) in miner
    supervision: a claimed hit is re-verified on host before it is trusted
    (2 hashes — free next to a sweep), and failures degrade to the scalar
    CPU loop, the reference generateBlocks inner loop. Returns a callable
    with the sweep_header signature."""
    def sweep(header80: bytes, target: int, start_nonce: int = 0,
              max_nonces: int = 1 << 32, tile: Optional[int] = None):
        from ..crypto.hashes import sha256d
        from .miner import DEFAULT_TILE, sweep_header_cpu

        dev = inner
        if dev is None:
            from .miner import sweep_header as dev  # noqa: PLC0415

        eff_tile = DEFAULT_TILE if tile is None else tile

        def device():
            return dev(header80, target, start_nonce=start_nonce,
                       max_nonces=max_nonces, tile=eff_tile)

        def cpu():
            return sweep_header_cpu(header80, target, start_nonce=start_nonce,
                                    max_nonces=max_nonces)

        def validate(res):
            nonce, _hashes = res
            if nonce is None:
                return True  # a missed hit costs work, never consensus
            hdr = header80[:76] + int(nonce).to_bytes(4, "little")
            return int.from_bytes(sha256d(hdr), "little") <= target

        def poison(res):
            nonce, hashes = res
            bad = (nonce ^ 1) if nonce is not None else start_nonce
            return (bad & 0xFFFFFFFF, hashes)

        out, _ = supervised_call("miner", device, cpu,
                                 validate=validate, poison=poison,
                                 items=1)
        return out

    return sweep
