// Native block-connect engine — the C++ hot path for -reindex / block import.
//
// The reference keeps its entire import pipeline in C++
// (src/validation.cpp:~4000 LoadExternalBlockFile, src/serialize.h codecs,
// src/coins.cpp UpdateCoins, src/consensus/tx_verify.cpp CheckTransaction);
// the round-4 profile showed the equivalent pure-Python path here sustains
// ~1.3 MB/s, projecting the mainnet byte leg alone to ~29 hours. This module
// is the TPU-framework answer: the HOST side of ConnectBlock (wire parse,
// sanity checks, merkle, UTXO apply, undo construction, and the P2PKH
// signature scan that feeds the TPU ECDSA batch) in native code, while the
// Python layer keeps orchestration (header context, block index, flush
// ordering) and the chip keeps the signature math.
//
// Semantics contract: behavior mirrors the Python reference implementation
// in this repo (validation/chainstate.py _connect_block_inner,
// consensus/tx_check.py, validation/scriptcheck.py) — differential-tested in
// tests/unit/test_native_connect.py. On ANY validation error the engine
// mutates nothing and the caller re-runs the block through the Python path
// for the authoritative verdict; the fast path is only ever taken to a
// successful, bit-identical conclusion (same undo blob, same chainstate
// rows) or abandoned wholesale.

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <unordered_map>
#include <vector>
#include <thread>
#include <atomic>
#include <chrono>

#include "common.h"

// from secp256k1.cpp (same .so)
extern "C" int bcp_pubkey_parse(const uint8_t* data, long len, uint8_t* out64);

namespace {

using bcpn::WireReader;
using bcpn::put_compact;

// ---------------------------------------------------------------------------
// constants (consensus/tx_check.py, crypto/secp256k1.py)
// ---------------------------------------------------------------------------

constexpr int64_t COIN = 100000000;
constexpr int64_t MAX_MONEY = 21000000 * COIN;
constexpr uint64_t MAX_TX_SIZE = 8000000;  // tx_check.MAX_BLOCK_SIZE
constexpr uint32_t LOCKTIME_THRESHOLD = 500000000;

// secp256k1 group order N, field prime P, N/2 (low-s bound) — big-endian
static const uint8_t SECP_N[32] = {
    0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFE,
    0xBA,0xAE,0xDC,0xE6,0xAF,0x48,0xA0,0x3B,0xBF,0xD2,0x5E,0x8C,0xD0,0x36,0x41,0x41};
static const uint8_t SECP_P[32] = {
    0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,
    0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFE,0xFF,0xFF,0xFC,0x2F};
static const uint8_t SECP_N_HALF[32] = {
    0x7F,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,0xFF,
    0x5D,0x57,0x6E,0x73,0x57,0xA4,0x50,0x1D,0xDF,0xE9,0x2F,0x46,0x68,0x1B,0x20,0xA0};

// script flag bits (script/interpreter.py)
constexpr uint32_t F_DERSIG = 1 << 2;
constexpr uint32_t F_LOW_S = 1 << 3;
constexpr uint32_t F_STRICTENC = 1 << 1;
constexpr uint32_t F_NULLFAIL = 1 << 14;
constexpr uint32_t F_FORKID = 1 << 16;
constexpr uint8_t SIGHASH_ANYONECANPAY = 0x80;
constexpr uint8_t SIGHASH_FORKID = 0x40;
constexpr uint8_t SIGHASH_NONE = 2;
constexpr uint8_t SIGHASH_SINGLE = 3;

// error codes (mapped to reject-reason strings in native.py)
enum {
    OK = 0,
    MISSING = 1,  // prevouts absent from the map: fetch-and-retry
    E_PARSE = -1,
    E_MERKLE = -2,
    E_MUTATED = -3,
    E_EMPTY = -4,
    E_OVERSIZE = -5,
    E_CB_MISSING = -6,
    E_CB_MULTIPLE = -7,
    E_VIN_EMPTY = -8,
    E_VOUT_EMPTY = -9,
    E_TX_OVERSIZE = -10,
    E_VOUT_NEG = -11,
    E_VOUT_TOOLARGE = -12,
    E_TXOUTTOTAL = -13,
    E_DUP_INPUTS = -14,
    E_CB_LENGTH = -15,
    E_PREVOUT_NULL = -16,
    E_NONFINAL = -17,
    E_BIP34 = -18,
    E_BIP30 = -19,
    E_MISSING_SPENT = -20,
    E_PREMATURE_CB = -21,
    E_INPUTVALUES = -22,
    E_IN_BELOWOUT = -23,
    E_FEE_RANGE = -24,
    E_CB_AMOUNT = -25,
    // script errors during the native P2PKH scan (block-fatal)
    E_S_EQUALVERIFY = -101,
    E_S_SIG_DER = -102,
    E_S_SIG_HIGH_S = -103,
    E_S_SIG_HASHTYPE = -104,
    E_S_ILLEGAL_FORKID = -105,
    E_S_MUST_USE_FORKID = -106,
    E_S_PUBKEYTYPE = -107,
    E_S_SIG_NULLFAIL = -108,
    E_S_EVAL_FALSE = -109,
};

// ---------------------------------------------------------------------------
// 256-bit big-endian helpers (for r/s range, low-s, r+N<P wraparound)
// ---------------------------------------------------------------------------

static int cmp256(const uint8_t a[32], const uint8_t b[32]) {
    return memcmp(a, b, 32);
}

static bool is_zero256(const uint8_t a[32]) {
    for (int i = 0; i < 32; i++) if (a[i]) return false;
    return true;
}

// out = a + N; returns carry (out is 32 bytes, big-endian)
static int add_n256(const uint8_t a[32], uint8_t out[32]) {
    unsigned carry = 0;
    for (int i = 31; i >= 0; i--) {
        unsigned s = unsigned(a[i]) + unsigned(SECP_N[i]) + carry;
        out[i] = uint8_t(s);
        carry = s >> 8;
    }
    return int(carry);
}

// ---------------------------------------------------------------------------
// parsed block (pointers into the caller's raw buffer: valid only during
// the connect call; export buffers copy whatever outlives it)
// ---------------------------------------------------------------------------

struct PIn {
    const uint8_t* prevout;  // 36 bytes
    const uint8_t* ss;
    uint32_t ss_len;
    uint32_t sequence;
};

struct POut {
    int64_t value;
    const uint8_t* spk;
    uint32_t spk_len;
};

struct PTx {
    const uint8_t* start;
    uint32_t size;
    int32_t version;
    uint32_t locktime;
    std::vector<PIn> vin;
    std::vector<POut> vout;
    uint8_t txid[32];
    uint32_t in_base;  // global input index of vin[0] (coinbase excluded)
};

struct Key36 {
    uint8_t b[36];
    bool operator==(const Key36& o) const { return memcmp(b, o.b, 36) == 0; }
};

struct KeyHash {
    size_t operator()(const Key36& k) const {
        uint64_t h;
        memcpy(&h, k.b, 8);  // txids are sha256d: uniformly distributed
        uint32_t n;
        memcpy(&n, k.b + 32, 4);
        return size_t(h ^ (uint64_t(n) * 0x9E3779B97F4A7C15ULL));
    }
};

// coin entry flags
constexpr uint8_t C_DIRTY = 1;   // differs from base since last flush
constexpr uint8_t C_FRESH = 2;   // base never saw it (spend = pure erase)
constexpr uint8_t C_SPENT = 4;   // tombstone: delete from base at flush

struct CoinEnt {
    int64_t value = 0;
    uint32_t height_code = 0;  // height*2 | coinbase (Coin.serialize code)
    uint8_t flags = 0;
    std::vector<uint8_t> spk;
};

struct Engine {
    std::unordered_map<Key36, CoinEnt, KeyHash> map;
    uint8_t best[32] = {0};
    uint64_t mem_bytes = 0;

    // per-connect outputs (valid until the next call on this engine)
    std::vector<PTx> txs;
    std::vector<uint8_t> undo;
    std::vector<uint8_t> txids;         // n_tx * 32
    std::vector<uint64_t> tx_offsets;   // n_tx * 2 (start, end)
    std::vector<uint32_t> tx_out_counts;
    std::vector<uint8_t> missing;       // n_missing * 36
    // spent-coin export, one slot per non-coinbase input (global order)
    std::vector<int64_t> spent_values;
    std::vector<uint32_t> spent_hc;
    std::vector<uint32_t> spent_spk_off;  // n_inputs + 1
    std::vector<uint8_t> spent_spk;
    // sig-scan export, one slot per non-coinbase input
    std::vector<uint8_t> sig_status;  // 0 = fast record, 1 = python fallback
    std::vector<uint8_t> sig_msg;     // n * 32
    std::vector<uint8_t> sig_rs;      // n * 64
    std::vector<uint8_t> sig_pub;     // n * 64
    std::vector<uint8_t> sig_rn;      // n * 32
    std::vector<uint8_t> sig_wrap;    // n
    std::vector<uint32_t> sig_txin;   // n * 2 (tx index, input index)

    long err_code = 0;
    long err_tx = -1;
    long err_in = -1;
    uint64_t sigscan_ns = 0;  // last connect's signature-scan wall time

    // deferred-commit overlay: connect(commit=0) validates and stages the
    // block's UTXO edits here; bcp_engine_commit applies them (or
    // bcp_engine_abort discards) — the Python-side fallback script checks
    // run between the two (see node.py _import_block_files_native)
    struct OvEnt {
        bool spent = false;
        bool created = false;
        int64_t value = 0;
        uint32_t height_code = 0;
        std::vector<uint8_t> spk;
    };
    std::unordered_map<Key36, OvEnt, KeyHash> ov;
    bool ov_valid = false;
    uint8_t pending_best[32] = {0};

    // flush export buffer
    std::vector<uint8_t> flush_buf;

    void note_err(long code, long t, long i) {
        err_code = code; err_tx = t; err_in = i;
    }

    uint64_t ent_mem(const CoinEnt& e) const {
        // rough accounting mirroring CoinsCache.estimated_bytes intent:
        // map node + key + entry + spk heap
        return 96 + e.spk.size();
    }
};

// ---------------------------------------------------------------------------
// block parse (wire layout identical to consensus/{tx,block}.py)
// ---------------------------------------------------------------------------

static bool parse_tx(WireReader& r, PTx& tx) {
    size_t start = r.pos;
    uint32_t version;
    if (!r.u32(&version)) return false;
    tx.version = int32_t(version);
    uint64_t nin;
    if (!r.compact(&nin)) return false;
    tx.vin.resize(0);
    tx.vin.reserve(size_t(nin) <= 4096 ? size_t(nin) : 4096);
    for (uint64_t i = 0; i < nin; i++) {
        PIn in;
        if (r.len - r.pos < 36) return false;
        in.prevout = r.p + r.pos;
        r.pos += 36;
        uint64_t sl;
        if (!r.compact(&sl)) return false;
        if (r.len - r.pos < sl) return false;
        in.ss = r.p + r.pos;
        in.ss_len = uint32_t(sl);
        r.pos += sl;
        if (!r.u32(&in.sequence)) return false;
        tx.vin.push_back(in);
    }
    uint64_t nout;
    if (!r.compact(&nout)) return false;
    tx.vout.resize(0);
    tx.vout.reserve(size_t(nout) <= 4096 ? size_t(nout) : 4096);
    for (uint64_t i = 0; i < nout; i++) {
        POut out;
        if (!r.i64(&out.value)) return false;
        uint64_t sl;
        if (!r.compact(&sl)) return false;
        if (r.len - r.pos < sl) return false;
        out.spk = r.p + r.pos;
        out.spk_len = uint32_t(sl);
        r.pos += sl;
        tx.vout.push_back(out);
    }
    if (!r.u32(&tx.locktime)) return false;
    tx.start = r.p + start;
    tx.size = uint32_t(r.pos - start);
    return true;
}

static bool parse_block(const uint8_t* raw, size_t len, std::vector<PTx>& txs) {
    WireReader r{raw, len};
    if (!r.skip(80)) return false;
    uint64_t n;
    if (!r.compact(&n)) return false;
    txs.resize(0);
    txs.reserve(size_t(n));
    uint32_t in_base = 0;
    for (uint64_t i = 0; i < n; i++) {
        txs.emplace_back();
        if (!parse_tx(r, txs.back())) return false;
        txs.back().in_base = in_base;
        if (i > 0)  // coinbase inputs don't occupy sig slots
            in_base += uint32_t(txs.back().vin.size());
    }
    return r.pos == len;  // CBlock.from_bytes rejects trailing bytes
}

// merkle root over txids with the CVE-2012-2459 mutation flag
// (consensus/merkle.py semantics)
static bool merkle_root(const std::vector<uint8_t>& txids, long n,
                        uint8_t root[32], bool* mutated) {
    if (n <= 0) return false;
    std::vector<uint8_t> level(txids.begin(), txids.begin() + n * 32);
    *mutated = false;
    long cnt = n;
    uint8_t pair[64];
    while (cnt > 1) {
        long next = 0;
        for (long i = 0; i < cnt; i += 2) {
            long j = (i + 1 < cnt) ? i + 1 : i;
            if (i + 1 < cnt &&
                memcmp(level.data() + 32 * i, level.data() + 32 * j, 32) == 0)
                *mutated = true;
            memcpy(pair, level.data() + 32 * i, 32);
            memcpy(pair + 32, level.data() + 32 * j, 32);
            bcpn::sha256d(pair, 64, level.data() + 32 * next);
            next++;
        }
        cnt = next;
    }
    memcpy(root, level.data(), 32);
    return true;
}

static bool is_coinbase(const PTx& tx) {
    if (tx.vin.size() != 1) return false;
    const uint8_t* p = tx.vin[0].prevout;
    for (int i = 0; i < 32; i++) if (p[i]) return false;
    uint32_t nidx;
    memcpy(&nidx, p + 32, 4);
    return nidx == 0xFFFFFFFF;
}

static bool prevout_is_null(const uint8_t* p) {
    for (int i = 0; i < 32; i++) if (p[i]) return false;
    uint32_t nidx;
    memcpy(&nidx, p + 32, 4);
    return nidx == 0xFFFFFFFF;
}

// CheckTransaction (consensus/tx_check.py) — returns 0 or error code
static long check_transaction(const PTx& tx) {
    if (tx.vin.empty()) return E_VIN_EMPTY;
    if (tx.vout.empty()) return E_VOUT_EMPTY;
    if (tx.size > MAX_TX_SIZE) return E_TX_OVERSIZE;
    int64_t total = 0;
    for (const POut& o : tx.vout) {
        if (o.value < 0) return E_VOUT_NEG;
        if (o.value > MAX_MONEY) return E_VOUT_TOOLARGE;
        total += o.value;
        if (total < 0 || total > MAX_MONEY) return E_TXOUTTOTAL;
    }
    if (tx.vin.size() > 1) {
        // duplicate-input check; small vins use O(n^2) (cache-friendly),
        // large vins a hash set
        if (tx.vin.size() <= 32) {
            for (size_t i = 0; i < tx.vin.size(); i++)
                for (size_t j = i + 1; j < tx.vin.size(); j++)
                    if (memcmp(tx.vin[i].prevout, tx.vin[j].prevout, 36) == 0)
                        return E_DUP_INPUTS;
        } else {
            std::unordered_map<Key36, char, KeyHash> seen;
            seen.reserve(tx.vin.size() * 2);
            for (const PIn& in : tx.vin) {
                Key36 k;
                memcpy(k.b, in.prevout, 36);
                if (!seen.emplace(k, 1).second) return E_DUP_INPUTS;
            }
        }
    }
    if (is_coinbase(tx)) {
        uint32_t l = tx.vin[0].ss_len;
        if (l < 2 || l > 100) return E_CB_LENGTH;
    } else {
        for (const PIn& in : tx.vin)
            if (prevout_is_null(in.prevout)) return E_PREVOUT_NULL;
    }
    return OK;
}

// IsFinalTx (consensus/tx_check.py) — block_time is the MTP (BIP113)
static bool is_final(const PTx& tx, uint32_t height, int64_t mtp) {
    if (tx.locktime == 0) return true;
    int64_t cutoff = tx.locktime < LOCKTIME_THRESHOLD ? int64_t(height) : mtp;
    if (int64_t(tx.locktime) < cutoff) return true;
    for (const PIn& in : tx.vin)
        if (in.sequence != 0xFFFFFFFF) return false;
    return true;
}

// ---------------------------------------------------------------------------
// P2PKH fast-path signature scan (validation/scriptcheck.py semantics)
// ---------------------------------------------------------------------------

// strict DER + hashtype tail (interpreter.py is_valid_signature_encoding)
static bool valid_sig_encoding(const uint8_t* sig, uint32_t len) {
    if (len < 9 || len > 73) return false;
    if (sig[0] != 0x30 || sig[1] != len - 3) return false;
    uint32_t len_r = sig[3];
    if (5 + len_r >= len) return false;
    uint32_t len_s = sig[5 + len_r];
    if (len_r + len_s + 7 != len) return false;
    if (sig[2] != 0x02 || len_r == 0 || (sig[4] & 0x80)) return false;
    if (len_r > 1 && sig[4] == 0x00 && !(sig[5] & 0x80)) return false;
    if (sig[len_r + 4] != 0x02 || len_s == 0 || (sig[len_r + 6] & 0x80)) return false;
    if (len_s > 1 && sig[len_r + 6] == 0x00 && !(sig[len_r + 7] & 0x80)) return false;
    return true;
}

// extract a DER integer into a 32-byte big-endian buffer; false if it does
// not fit in 256 bits (after the optional 0x00 sign byte)
static bool der_int_to_32(const uint8_t* p, uint32_t len, uint8_t out[32]) {
    while (len > 0 && p[0] == 0x00) { p++; len--; }
    if (len > 32) return false;
    memset(out, 0, 32);
    memcpy(out + 32 - len, p, len);
    return true;
}

// two direct pushes covering the whole scriptSig (scriptcheck._p2pkh_template)
static bool p2pkh_template(const uint8_t* ss, uint32_t ss_len,
                           const uint8_t* spk, uint32_t spk_len,
                           const uint8_t** sig, uint32_t* sig_len,
                           const uint8_t** pub, uint32_t* pub_len) {
    if (spk_len != 25 || spk[0] != 0x76 || spk[1] != 0xA9 || spk[2] != 20 ||
        spk[23] != 0x88 || spk[24] != 0xAC)
        return false;
    uint32_t pos = 0;
    const uint8_t* items[2];
    uint32_t lens[2];
    for (int k = 0; k < 2; k++) {
        if (pos >= ss_len) return false;
        uint8_t op = ss[pos];
        if (op == 0) {
            items[k] = ss + pos + 1;
            lens[k] = 0;
            pos += 1;
        } else if (op >= 1 && op <= 75) {
            if (pos + 1 + op > ss_len) return false;
            items[k] = ss + pos + 1;
            lens[k] = op;
            pos += 1 + op;
        } else {
            return false;
        }
    }
    if (pos != ss_len) return false;
    *sig = items[0]; *sig_len = lens[0];
    *pub = items[1]; *pub_len = lens[1];
    return true;
}

// forkid (BIP143-style) sighash midstates per tx (script/sighash.py
// SighashCache)
struct TxMidstates {
    uint8_t hash_prevouts[32];
    uint8_t hash_sequence[32];
    uint8_t hash_outputs[32];
};

static void compute_midstates(const PTx& tx, TxMidstates& m) {
    {
        bcpn::Sha256 a;
        for (const PIn& in : tx.vin) a.update(in.prevout, 36);
        uint8_t mid[32]; a.final(mid);
        bcpn::sha256(mid, 32, m.hash_prevouts);
    }
    {
        bcpn::Sha256 a;
        for (const PIn& in : tx.vin) {
            uint8_t seq[4];
            memcpy(seq, &in.sequence, 4);
            a.update(seq, 4);
        }
        uint8_t mid[32]; a.final(mid);
        bcpn::sha256(mid, 32, m.hash_sequence);
    }
    {
        bcpn::Sha256 a;
        for (const POut& o : tx.vout) {
            uint8_t v[8];
            memcpy(v, &o.value, 8);
            a.update(v, 8);
            std::vector<uint8_t> cs;
            put_compact(cs, o.spk_len);
            a.update(cs.data(), cs.size());
            a.update(o.spk, o.spk_len);
        }
        uint8_t mid[32]; a.final(mid);
        bcpn::sha256(mid, 32, m.hash_outputs);
    }
}

// signature_hash_forkid (script/sighash.py) for input in_idx with
// script_code = the 25-byte P2PKH spk and the spent amount
static void sighash_forkid(const PTx& tx, const TxMidstates& m,
                           uint32_t in_idx, uint8_t hashtype,
                           const uint8_t* script_code, uint32_t sc_len,
                           int64_t amount, uint8_t out[32]) {
    static const uint8_t zero[32] = {0};
    uint8_t base = hashtype & 0x1F;
    bool acp = (hashtype & SIGHASH_ANYONECANPAY) != 0;
    const uint8_t* hp = acp ? zero : m.hash_prevouts;
    const uint8_t* hs =
        (acp || base == SIGHASH_NONE || base == SIGHASH_SINGLE)
            ? zero : m.hash_sequence;
    uint8_t single_out[32];
    const uint8_t* ho;
    if (base != SIGHASH_NONE && base != SIGHASH_SINGLE) {
        ho = m.hash_outputs;
    } else if (base == SIGHASH_SINGLE && in_idx < tx.vout.size()) {
        const POut& o = tx.vout[in_idx];
        bcpn::Sha256 a;
        uint8_t v[8];
        memcpy(v, &o.value, 8);
        a.update(v, 8);
        std::vector<uint8_t> cs;
        put_compact(cs, o.spk_len);
        a.update(cs.data(), cs.size());
        a.update(o.spk, o.spk_len);
        uint8_t mid[32]; a.final(mid);
        bcpn::sha256(mid, 32, single_out);
        ho = single_out;
    } else {
        ho = zero;
    }
    bcpn::Sha256 a;
    uint8_t u32buf[4];
    uint32_t ver = uint32_t(tx.version);
    memcpy(u32buf, &ver, 4);
    a.update(u32buf, 4);
    a.update(hp, 32);
    a.update(hs, 32);
    a.update(tx.vin[in_idx].prevout, 36);
    std::vector<uint8_t> cs;
    put_compact(cs, sc_len);
    a.update(cs.data(), cs.size());
    a.update(script_code, sc_len);
    uint8_t amt[8];
    memcpy(amt, &amount, 8);
    a.update(amt, 8);
    memcpy(u32buf, &tx.vin[in_idx].sequence, 4);
    a.update(u32buf, 4);
    a.update(ho, 32);
    memcpy(u32buf, &tx.locktime, 4);
    a.update(u32buf, 4);
    uint32_t ht32 = hashtype;
    memcpy(u32buf, &ht32, 4);
    a.update(u32buf, 4);
    uint8_t mid[32];
    a.final(mid);
    bcpn::sha256(mid, 32, out);
}

// One input's fast-path scan. Returns OK and fills the record slot, a
// script error code (block-fatal), or sets *fallback for the Python
// interpreter. Mirrors scriptcheck._p2pkh_fast_verify +
// DeferringSignatureChecker.check_sig exactly.
static long scan_input(Engine& e, const PTx& tx, const TxMidstates& m,
                       uint32_t in_idx, uint32_t g, uint32_t flags) {
    const PIn& in = tx.vin[in_idx];
    const uint8_t* spk = e.spent_spk.data() + e.spent_spk_off[g];
    uint32_t spk_len = e.spent_spk_off[g + 1] - e.spent_spk_off[g];
    const uint8_t *sig, *pub;
    uint32_t sig_len, pub_len;
    if (!p2pkh_template(in.ss, in.ss_len, spk, spk_len,
                        &sig, &sig_len, &pub, &pub_len)) {
        e.sig_status[g] = 1;  // generic interpreter (Python) handles it
        return OK;
    }
    // DUP HASH160 <h20> EQUALVERIFY collapse
    uint8_t h20[20];
    bcpn::hash160(pub, pub_len, h20);
    if (memcmp(h20, spk + 3, 20) != 0) return E_S_EQUALVERIFY;
    // check_signature_encoding (empty sig passes encoding, fails later)
    if (sig_len != 0) {
        if ((flags & (F_DERSIG | F_LOW_S | F_STRICTENC)) &&
            !valid_sig_encoding(sig, sig_len))
            return E_S_SIG_DER;
        if (flags & F_LOW_S) {
            uint32_t len_r = sig[3];
            uint32_t len_s = sig[5 + len_r];
            uint8_t s32[32];
            if (!der_int_to_32(sig + 6 + len_r, len_s, s32) ||
                cmp256(s32, SECP_N_HALF) > 0)
                return E_S_SIG_HIGH_S;
        }
        if (flags & F_STRICTENC) {
            uint8_t ht = sig[sig_len - 1];
            uint8_t base = ht & uint8_t(~(SIGHASH_ANYONECANPAY | SIGHASH_FORKID));
            if (base < 1 || base > SIGHASH_SINGLE) return E_S_SIG_HASHTYPE;
            bool uses_forkid = (ht & SIGHASH_FORKID) != 0;
            bool forkid_on = (flags & F_FORKID) != 0;
            if (!forkid_on && uses_forkid) return E_S_ILLEGAL_FORKID;
            if (forkid_on && !uses_forkid) return E_S_MUST_USE_FORKID;
        }
    }
    // check_pubkey_encoding
    if (flags & F_STRICTENC) {
        bool ok = (pub_len == 33 && (pub[0] == 2 || pub[0] == 3)) ||
                  (pub_len == 65 && pub[0] == 4);
        if (!ok) return E_S_PUBKEYTYPE;
    }
    // check_sig: empty sig -> parse fails -> False -> eval-false (empty sig
    // is exempt from NULLFAIL's nullfail code, scriptcheck.py:110-113)
    if (sig_len == 0) return E_S_EVAL_FALSE;
    // non-forkid hashtype without STRICTENC would take the legacy sighash;
    // the fast scan only models the forkid digest — send it to Python
    uint8_t ht = sig[sig_len - 1];
    if (!(flags & F_FORKID) || !(ht & SIGHASH_FORKID)) {
        e.sig_status[g] = 1;
        return OK;
    }
    // pubkey parse (decompress): failure -> check_sig False -> NULLFAIL
    uint8_t pub64[64];
    if (!bcp_pubkey_parse(pub, long(pub_len), pub64))
        return E_S_SIG_NULLFAIL;
    // DER decode r, s (structure already validated if STRICTENC/DERSIG;
    // without those flags a malformed DER fails decode -> NULLFAIL)
    if (!valid_sig_encoding(sig, sig_len)) return E_S_SIG_NULLFAIL;
    uint32_t len_r = sig[3];
    uint32_t len_s = sig[5 + len_r];
    uint8_t r32[32], s32[32];
    if (!der_int_to_32(sig + 4, len_r, r32) ||
        !der_int_to_32(sig + 6 + len_r, len_s, s32))
        return E_S_SIG_NULLFAIL;
    // range: 1 <= r, s < N (DeferringSignatureChecker.check_sig)
    if (is_zero256(r32) || is_zero256(s32) ||
        cmp256(r32, SECP_N) >= 0 || cmp256(s32, SECP_N) >= 0)
        return E_S_SIG_NULLFAIL;
    // sighash + record emit
    uint8_t msg[32];
    sighash_forkid(tx, m, in_idx, ht, spk, spk_len,
                   e.spent_values[g], msg);
    memcpy(e.sig_msg.data() + 32 * g, msg, 32);
    memcpy(e.sig_rs.data() + 64 * g, r32, 32);
    memcpy(e.sig_rs.data() + 64 * g + 32, s32, 32);
    memcpy(e.sig_pub.data() + 64 * g, pub64, 64);
    // rn = r + N if r + N < P else r; wrap flag for the kernel's
    // x-wraparound candidate (ops/ecdsa_batch._pack_limbs semantics)
    uint8_t rn[32];
    int carry = add_n256(r32, rn);
    bool wrap = (carry == 0) && (cmp256(rn, SECP_P) < 0);
    memcpy(e.sig_rn.data() + 32 * g, wrap ? rn : r32, 32);
    e.sig_wrap[g] = wrap ? 1 : 0;
    e.sig_status[g] = 0;
    return OK;
}

static void commit_overlay(Engine& e) {
    if (!e.ov_valid) return;
    for (auto& kv : e.ov) {
        const Key36& k = kv.first;
        Engine::OvEnt& oe = kv.second;
        if (oe.created && !oe.spent) {
            CoinEnt ent;
            ent.value = oe.value;
            ent.height_code = oe.height_code;
            ent.flags = C_DIRTY | C_FRESH;
            ent.spk = std::move(oe.spk);
            auto it = e.map.find(k);
            if (it != e.map.end()) {
                // overwriting a SPENT tombstone of a base coin: the new
                // coin is NOT fresh (base still holds the stale row until
                // the flush's put replaces it)
                if (!(it->second.flags & C_FRESH)) ent.flags = C_DIRTY;
                e.mem_bytes -= e.ent_mem(it->second);
                e.mem_bytes += e.ent_mem(ent);
                it->second = std::move(ent);
            } else {
                e.mem_bytes += e.ent_mem(ent);
                e.map.emplace(k, std::move(ent));
            }
        } else if (oe.spent && !oe.created) {
            auto it = e.map.find(k);
            // (must exist: resolved during connect)
            if (it == e.map.end()) continue;
            if (it->second.flags & C_FRESH) {
                e.mem_bytes -= e.ent_mem(it->second);
                e.map.erase(it);
            } else {
                e.mem_bytes -= it->second.spk.size();
                it->second.flags = C_DIRTY | C_SPENT;
                it->second.spk.clear();
                it->second.spk.shrink_to_fit();
            }
        }
        // created && spent within the block: never touches the map
    }
    memcpy(e.best, e.pending_best, 32);
    e.ov.clear();
    e.ov_valid = false;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void* bcp_engine_new() { return new Engine(); }

void bcp_engine_free(void* e) { delete static_cast<Engine*>(e); }

void bcp_engine_set_best(void* ep, const uint8_t* h32) {
    memcpy(static_cast<Engine*>(ep)->best, h32, 32);
}

void bcp_engine_get_best(void* ep, uint8_t* out32) {
    memcpy(out32, static_cast<Engine*>(ep)->best, 32);
}

uint64_t bcp_engine_mem_bytes(void* ep) {
    return static_cast<Engine*>(ep)->mem_bytes;
}

long bcp_engine_entries(void* ep) {
    return long(static_cast<Engine*>(ep)->map.size());
}

// Insert a CLEAN coin read from the base store (miss servicing).
void bcp_engine_insert(void* ep, const uint8_t* key36, uint32_t height_code,
                       int64_t value, const uint8_t* spk, uint32_t spk_len) {
    Engine& e = *static_cast<Engine*>(ep);
    Key36 k;
    memcpy(k.b, key36, 36);
    CoinEnt ent;
    ent.value = value;
    ent.height_code = height_code;
    ent.flags = 0;
    ent.spk.assign(spk, spk + spk_len);
    auto it = e.map.find(k);
    if (it != e.map.end()) e.mem_bytes -= e.ent_mem(it->second);
    e.mem_bytes += e.ent_mem(ent);
    e.map[k] = std::move(ent);
}

// 1 = live coin (out params filled; spk pointer valid until next mutation),
// 0 = absent, -1 = spent tombstone
int bcp_engine_get(void* ep, const uint8_t* key36, uint32_t* height_code,
                   int64_t* value, const uint8_t** spk, uint32_t* spk_len) {
    Engine& e = *static_cast<Engine*>(ep);
    Key36 k;
    memcpy(k.b, key36, 36);
    auto it = e.map.find(k);
    if (it == e.map.end()) return 0;
    if (it->second.flags & C_SPENT) return -1;
    *height_code = it->second.height_code;
    *value = it->second.value;
    *spk = it->second.spk.data();
    *spk_len = uint32_t(it->second.spk.size());
    return 1;
}

long bcp_engine_error(void* ep, long* tx_idx, long* in_idx) {
    Engine& e = *static_cast<Engine*>(ep);
    *tx_idx = e.err_tx;
    *in_idx = e.err_in;
    return e.err_code;
}

const uint8_t* bcp_engine_missing(void* ep, long* count) {
    Engine& e = *static_cast<Engine*>(ep);
    *count = long(e.missing.size() / 36);
    return e.missing.data();
}

const uint8_t* bcp_engine_undo(void* ep, size_t* len) {
    Engine& e = *static_cast<Engine*>(ep);
    *len = e.undo.size();
    return e.undo.data();
}

long bcp_engine_n_tx(void* ep) {
    return long(static_cast<Engine*>(ep)->txs.size());
}

long bcp_engine_n_inputs(void* ep) {
    return long(static_cast<Engine*>(ep)->spent_values.size());
}

const uint8_t* bcp_engine_txids(void* ep) {
    return static_cast<Engine*>(ep)->txids.data();
}

const uint64_t* bcp_engine_tx_offsets(void* ep) {
    return static_cast<Engine*>(ep)->tx_offsets.data();
}

const uint32_t* bcp_engine_tx_out_counts(void* ep) {
    return static_cast<Engine*>(ep)->tx_out_counts.data();
}

const int64_t* bcp_engine_spent_values(void* ep) {
    return static_cast<Engine*>(ep)->spent_values.data();
}

const uint32_t* bcp_engine_spent_heightcodes(void* ep) {
    return static_cast<Engine*>(ep)->spent_hc.data();
}

const uint32_t* bcp_engine_spent_spk_offsets(void* ep) {
    return static_cast<Engine*>(ep)->spent_spk_off.data();
}

const uint8_t* bcp_engine_spent_spk_blob(void* ep, size_t* len) {
    Engine& e = *static_cast<Engine*>(ep);
    *len = e.spent_spk.size();
    return e.spent_spk.data();
}

const uint8_t* bcp_engine_sig_status(void* ep) {
    return static_cast<Engine*>(ep)->sig_status.data();
}
const uint8_t* bcp_engine_sig_msg(void* ep) {
    return static_cast<Engine*>(ep)->sig_msg.data();
}
const uint8_t* bcp_engine_sig_rs(void* ep) {
    return static_cast<Engine*>(ep)->sig_rs.data();
}
const uint8_t* bcp_engine_sig_pub(void* ep) {
    return static_cast<Engine*>(ep)->sig_pub.data();
}
const uint8_t* bcp_engine_sig_rn(void* ep) {
    return static_cast<Engine*>(ep)->sig_rn.data();
}
const uint8_t* bcp_engine_sig_wrap(void* ep) {
    return static_cast<Engine*>(ep)->sig_wrap.data();
}
const uint32_t* bcp_engine_sig_txin(void* ep) {
    return static_cast<Engine*>(ep)->sig_txin.data();
}

// The connect itself. See the ABI sketch in native.py for argument docs.
long bcp_engine_connect_block(
    void* ep, const uint8_t* raw, size_t raw_len,
    uint32_t height, int64_t subsidy,
    uint32_t max_block_size, uint32_t coinbase_maturity, int64_t mtp,
    const uint8_t* bip34_prefix, uint32_t bip34_len,
    uint32_t script_flags, int want_sigs, int check_merkle, int nthreads,
    int commit, uint8_t* block_hash_out32) {
    Engine& e = *static_cast<Engine*>(ep);
    e.err_code = 0; e.err_tx = -1; e.err_in = -1;
    e.missing.clear();
    e.ov.clear();
    e.ov_valid = false;

    if (!parse_block(raw, raw_len, e.txs)) {
        e.note_err(E_PARSE, -1, -1);
        return E_PARSE;
    }
    std::vector<PTx>& txs = e.txs;
    long n_tx = long(txs.size());
    bcpn::sha256d(raw, 80, block_hash_out32);

    // ---- CheckBlock (chainstate.check_block order) ----
    // txids (threaded: sha256d per tx dominates parse cost)
    e.txids.resize(size_t(n_tx) * 32);
    {
        unsigned hw = nthreads > 0 ? unsigned(nthreads)
                                   : std::thread::hardware_concurrency();
        if (hw == 0) hw = 1;
        unsigned nt = n_tx < 8 ? 1 : (hw > 8 ? 8 : hw);
        if (nt <= 1) {
            for (long i = 0; i < n_tx; i++)
                bcpn::sha256d(txs[i].start, txs[i].size,
                              e.txids.data() + 32 * i);
        } else {
            std::vector<std::thread> th;
            std::atomic<long> next{0};
            for (unsigned t = 0; t < nt; t++)
                th.emplace_back([&]() {
                    long i;
                    while ((i = next.fetch_add(1)) < n_tx)
                        bcpn::sha256d(txs[i].start, txs[i].size,
                                      e.txids.data() + 32 * i);
                });
            for (auto& t : th) t.join();
        }
        for (long i = 0; i < n_tx; i++)
            memcpy(txs[i].txid, e.txids.data() + 32 * i, 32);
    }
    if (check_merkle) {
        uint8_t root[32];
        bool mutated;
        if (!merkle_root(e.txids, n_tx, root, &mutated) ||
            memcmp(root, raw + 36, 32) != 0) {
            e.note_err(E_MERKLE, -1, -1);
            return E_MERKLE;
        }
        if (mutated) {
            e.note_err(E_MUTATED, -1, -1);
            return E_MUTATED;
        }
    }
    if (n_tx == 0) { e.note_err(E_EMPTY, -1, -1); return E_EMPTY; }
    if (raw_len > max_block_size) {
        e.note_err(E_OVERSIZE, -1, -1);
        return E_OVERSIZE;
    }
    if (!is_coinbase(txs[0])) {
        e.note_err(E_CB_MISSING, 0, -1);
        return E_CB_MISSING;
    }
    for (long i = 1; i < n_tx; i++)
        if (is_coinbase(txs[i])) {
            e.note_err(E_CB_MULTIPLE, i, -1);
            return E_CB_MULTIPLE;
        }
    for (long i = 0; i < n_tx; i++) {
        long rc = check_transaction(txs[i]);
        if (rc != OK) { e.note_err(rc, i, -1); return rc; }
    }

    // ---- ContextualCheckBlock: finality + BIP34 ----
    for (long i = 0; i < n_tx; i++)
        if (!is_final(txs[i], height, mtp)) {
            e.note_err(E_NONFINAL, i, -1);
            return E_NONFINAL;
        }
    if (bip34_prefix != nullptr && bip34_len > 0) {
        const PIn& cb = txs[0].vin[0];
        if (cb.ss_len < bip34_len ||
            memcmp(cb.ss, bip34_prefix, bip34_len) != 0) {
            e.note_err(E_BIP34, 0, -1);
            return E_BIP34;
        }
    }

    // ---- tx offsets / out counts export ----
    e.tx_offsets.resize(size_t(n_tx) * 2);
    e.tx_out_counts.resize(size_t(n_tx));
    for (long i = 0; i < n_tx; i++) {
        e.tx_offsets[2 * i] = uint64_t(txs[i].start - raw);
        e.tx_offsets[2 * i + 1] = uint64_t(txs[i].start - raw) + txs[i].size;
        e.tx_out_counts[i] = uint32_t(txs[i].vout.size());
    }

    // ---- BIP30 against the in-memory map (see native.py for the
    // base-store leg, which Python runs for pre-BIP34 heights only) ----
    for (long i = 0; i < n_tx; i++) {
        Key36 k;
        memcpy(k.b, txs[i].txid, 32);
        for (uint32_t o = 0; o < txs[i].vout.size(); o++) {
            memcpy(k.b + 32, &o, 4);
            auto it = e.map.find(k);
            if (it != e.map.end() && !(it->second.flags & C_SPENT)) {
                e.note_err(E_BIP30, i, long(o));
                return E_BIP30;
            }
        }
    }

    // ---- resolve inputs (overlay keeps the engine unmutated on failure)
    long n_inputs = 0;
    for (long i = 1; i < n_tx; i++) n_inputs += long(txs[i].vin.size());
    e.spent_values.assign(size_t(n_inputs), 0);
    e.spent_hc.assign(size_t(n_inputs), 0);
    e.spent_spk_off.assign(size_t(n_inputs) + 1, 0);
    e.spent_spk.clear();
    e.undo.clear();

    // overlay: outputs created by this block + spent marks for this block
    auto& ov = e.ov;
    ov.clear();
    e.ov_valid = false;
    ov.reserve(size_t(n_inputs) * 2 + 64);

    put_compact(e.undo, uint64_t(n_tx - 1));
    int64_t fees = 0;
    uint32_t g = 0;
    bool missing_any = false;

    for (long i = 0; i < n_tx; i++) {
        PTx& tx = txs[i];
        if (i > 0) {
            std::vector<uint8_t> txundo;
            put_compact(txundo, tx.vin.size());
            int64_t value_in = 0;
            for (uint32_t vi = 0; vi < tx.vin.size(); vi++, g++) {
                Key36 k;
                memcpy(k.b, tx.vin[vi].prevout, 36);
                int64_t value;
                uint32_t hc;
                const uint8_t* spk;
                uint32_t spk_len;
                auto oit = ov.find(k);
                if (oit != ov.end() && (oit->second.spent || oit->second.created)) {
                    if (oit->second.spent) {
                        e.note_err(E_MISSING_SPENT, i, vi);
                        return E_MISSING_SPENT;
                    }
                    value = oit->second.value;
                    hc = oit->second.height_code;
                    spk = oit->second.spk.data();
                    spk_len = uint32_t(oit->second.spk.size());
                    oit->second.spent = true;
                } else {
                    auto mit = e.map.find(k);
                    if (mit == e.map.end()) {
                        // not in the cache: the caller fetches from base
                        missing_any = true;
                        e.missing.insert(e.missing.end(), k.b, k.b + 36);
                        continue;
                    }
                    if (mit->second.flags & C_SPENT) {
                        e.note_err(E_MISSING_SPENT, i, vi);
                        return E_MISSING_SPENT;
                    }
                    value = mit->second.value;
                    hc = mit->second.height_code;
                    spk = mit->second.spk.data();
                    spk_len = uint32_t(mit->second.spk.size());
                    Engine::OvEnt& oe = ov[k];
                    oe.spent = true;
                }
                if (missing_any) continue;  // keep collecting misses only
                // coinbase maturity
                if ((hc & 1) &&
                    int64_t(height) - int64_t(hc >> 1) <
                        int64_t(coinbase_maturity)) {
                    e.note_err(E_PREMATURE_CB, i, vi);
                    return E_PREMATURE_CB;
                }
                value_in += value;
                // undo: Coin.serialize framed with its length
                std::vector<uint8_t> coin_ser;
                put_compact(coin_ser, hc);
                put_compact(coin_ser, uint64_t(value));
                put_compact(coin_ser, spk_len);
                coin_ser.insert(coin_ser.end(), spk, spk + spk_len);
                put_compact(txundo, coin_ser.size());
                txundo.insert(txundo.end(), coin_ser.begin(), coin_ser.end());
                // spent export
                e.spent_values[g] = value;
                e.spent_hc[g] = hc;
                e.spent_spk.insert(e.spent_spk.end(), spk, spk + spk_len);
                e.spent_spk_off[g + 1] = uint32_t(e.spent_spk.size());
            }
            if (!missing_any) {
                if (value_in < 0 || value_in > MAX_MONEY) {
                    e.note_err(E_INPUTVALUES, i, -1);
                    return E_INPUTVALUES;
                }
                int64_t value_out = 0;
                for (const POut& o : tx.vout) value_out += o.value;
                if (value_in < value_out) {
                    e.note_err(E_IN_BELOWOUT, i, -1);
                    return E_IN_BELOWOUT;
                }
                int64_t fee = value_in - value_out;
                if (fee < 0 || fee > MAX_MONEY) {
                    e.note_err(E_FEE_RANGE, i, -1);
                    return E_FEE_RANGE;
                }
                fees += fee;
                e.undo.insert(e.undo.end(), txundo.begin(), txundo.end());
            }
        }
        // add this tx's outputs to the overlay EVEN while collecting
        // misses: later intra-block spends must not read as base misses
        uint32_t hc = height * 2 + (i == 0 ? 1 : 0);
        Key36 k;
        memcpy(k.b, tx.txid, 32);
        for (uint32_t o = 0; o < tx.vout.size(); o++) {
            memcpy(k.b + 32, &o, 4);
            Engine::OvEnt& oe = ov[k];
            oe.created = true;
            oe.spent = false;
            oe.value = tx.vout[o].value;
            oe.height_code = hc;
            oe.spk.assign(tx.vout[o].spk, tx.vout[o].spk + tx.vout[o].spk_len);
        }
    }
    if (missing_any) return MISSING;

    // coinbase amount
    int64_t cb_out = 0;
    for (const POut& o : txs[0].vout) cb_out += o.value;
    if (cb_out > fees + subsidy) {
        e.note_err(E_CB_AMOUNT, 0, -1);
        return E_CB_AMOUNT;
    }

    // ---- signature scan (before commit: a script error must leave the
    // map untouched, exactly like the Python path's scratch view) ----
    e.sigscan_ns = 0;
    if (want_sigs && n_inputs > 0) {
        auto scan_t0 = std::chrono::steady_clock::now();
        e.sig_status.assign(size_t(n_inputs), 1);
        e.sig_msg.resize(size_t(n_inputs) * 32);
        e.sig_rs.resize(size_t(n_inputs) * 64);
        e.sig_pub.resize(size_t(n_inputs) * 64);
        e.sig_rn.resize(size_t(n_inputs) * 32);
        e.sig_wrap.assign(size_t(n_inputs), 0);
        e.sig_txin.resize(size_t(n_inputs) * 2);
        unsigned hw = nthreads > 0 ? unsigned(nthreads)
                                   : std::thread::hardware_concurrency();
        if (hw == 0) hw = 1;
        unsigned nt = (n_tx - 1) < 2 || n_inputs < 64 ? 1 : (hw > 16 ? 16 : hw);
        // first error by (tx, input) order wins, deterministically
        std::atomic<long> first_err_pos{-1};
        std::vector<long> err_codes(size_t(n_inputs), 0);
        auto work = [&](long t_begin, long t_end) {
            TxMidstates m;
            for (long i = t_begin; i < t_end; i++) {
                PTx& tx = txs[i];
                bool have_mid = false;
                for (uint32_t vi = 0; vi < tx.vin.size(); vi++) {
                    uint32_t gg = tx.in_base + vi;
                    e.sig_txin[2 * gg] = uint32_t(i);
                    e.sig_txin[2 * gg + 1] = vi;
                    if (!have_mid) {
                        compute_midstates(tx, m);
                        have_mid = true;
                    }
                    long rc = scan_input(e, tx, m, vi, gg, script_flags);
                    if (rc != OK) {
                        err_codes[gg] = rc;
                        long cur = first_err_pos.load();
                        while ((cur == -1 || long(gg) < cur) &&
                               !first_err_pos.compare_exchange_weak(cur, long(gg))) {}
                        return;  // this thread stops at its first error
                    }
                }
            }
        };
        if (nt <= 1) {
            work(1, n_tx);
        } else {
            // static partition by input count for balance
            std::vector<std::thread> th;
            std::vector<long> bounds;
            bounds.push_back(1);
            long per = (n_inputs + long(nt) - 1) / long(nt);
            long acc = 0;
            for (long i = 1; i < n_tx; i++) {
                acc += long(txs[i].vin.size());
                if (acc >= per && long(bounds.size()) < long(nt)) {
                    bounds.push_back(i + 1);
                    acc = 0;
                }
            }
            bounds.push_back(n_tx);
            for (size_t t = 0; t + 1 < bounds.size(); t++)
                th.emplace_back(work, bounds[t], bounds[t + 1]);
            for (auto& t : th) t.join();
        }
        e.sigscan_ns = uint64_t(
            std::chrono::duration_cast<std::chrono::nanoseconds>(
                std::chrono::steady_clock::now() - scan_t0).count());
        long fe = first_err_pos.load();
        if (fe >= 0) {
            long code = err_codes[size_t(fe)];
            e.note_err(code, e.sig_txin[2 * fe], e.sig_txin[2 * fe + 1]);
            return code;
        }
    } else {
        e.sig_status.assign(size_t(n_inputs), 1);
        e.sig_txin.resize(size_t(n_inputs) * 2);
        g = 0;
        for (long i = 1; i < n_tx; i++)
            for (uint32_t vi = 0; vi < txs[i].vin.size(); vi++, g++) {
                e.sig_txin[2 * g] = uint32_t(i);
                e.sig_txin[2 * g + 1] = vi;
            }
    }

    // ---- stage / commit the overlay ----
    memcpy(e.pending_best, block_hash_out32, 32);
    e.ov_valid = true;
    if (commit) commit_overlay(e);
    return OK;
}

// Wall nanoseconds the last successful connect spent in the signature
// scan (the per-sig host leg: sighash + encoding checks + pubkey parse) —
// the bench attributes this to the sig leg, not the byte leg.
uint64_t bcp_engine_sigscan_ns(void* ep) {
    return static_cast<Engine*>(ep)->sigscan_ns;
}

// Apply / discard a connect(commit=0)'s staged overlay.
void bcp_engine_commit(void* ep) { commit_overlay(*static_cast<Engine*>(ep)); }

void bcp_engine_abort(void* ep) {
    Engine& e = *static_cast<Engine*>(ep);
    e.ov.clear();
    e.ov_valid = false;
}

// Flush export. Entry format: key36 | tag u8 (0 = delete, 1 = put) |
// [u32 len | Coin.serialize bytes] — Python maps this 1:1 onto the
// CoinsDB batch (store/chainstatedb.py).
const uint8_t* bcp_engine_flush(void* ep, size_t* len, long* n_entries) {
    Engine& e = *static_cast<Engine*>(ep);
    e.flush_buf.clear();
    long n = 0;
    for (auto& kv : e.map) {
        const CoinEnt& c = kv.second;
        if (!(c.flags & C_DIRTY)) continue;
        e.flush_buf.insert(e.flush_buf.end(), kv.first.b, kv.first.b + 36);
        if (c.flags & C_SPENT) {
            e.flush_buf.push_back(0);
        } else {
            e.flush_buf.push_back(1);
            std::vector<uint8_t> ser;
            put_compact(ser, c.height_code);
            put_compact(ser, uint64_t(c.value));
            put_compact(ser, c.spk.size());
            ser.insert(ser.end(), c.spk.begin(), c.spk.end());
            uint32_t l = uint32_t(ser.size());
            const uint8_t* lp = reinterpret_cast<const uint8_t*>(&l);
            e.flush_buf.insert(e.flush_buf.end(), lp, lp + 4);
            e.flush_buf.insert(e.flush_buf.end(), ser.begin(), ser.end());
        }
        n++;
    }
    *len = e.flush_buf.size();
    *n_entries = n;
    return e.flush_buf.data();
}

// Drop everything (after a successful base batch-write), matching
// CoinsCache.flush()'s clear — memory stays bounded by -dbcache.
void bcp_engine_clear(void* ep) {
    Engine& e = *static_cast<Engine*>(ep);
    e.map.clear();
    e.mem_bytes = 0;
    e.flush_buf.clear();
    e.flush_buf.shrink_to_fit();
}

}  // extern "C"
