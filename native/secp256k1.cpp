// Native scalar secp256k1 ECDSA verification — the CPU-side verify path
// promised by SURVEY §3.1's binding plan ("Pallas batch-verify kernel +
// C++ scalar fallback module", ref src/secp256k1/src/secp256k1.c:~340).
//
// Role in the framework: the TPU Pallas kernel (ops/secp256k1.py) is the
// block-validation batch path; THIS module is what ATMP's standard-flags
// verify, inline legacy checks, and small batches below the dispatch floor
// run on. The Python-int oracle (crypto/secp256k1.py) stays the consensus
// reference; tests/unit/test_native.py differentially checks this module
// against it on valid/invalid/edge vectors.
//
// Design (own derivation for a generic 64-bit host, not a port):
//   - 256-bit values as 4 x uint64 little-endian limbs; products via
//     __uint128_t schoolbook with explicit spill tracking.
//   - One generic Solinas-style reduction for BOTH moduli: p and n are
//     each 2^256 - K with a small K (33 bits for p, 129 bits for n), so
//     an 8-word product folds by repeatedly rewriting high*2^256 as
//     high*K. Four folds + conditional subtracts fully reduce.
//   - Inversions are Fermat powers (s^-1 = s^(n-2)); verification is not
//     side-channel sensitive, so no constant-time machinery (same stance
//     as the reference's _var verify paths).
//   - u1*G + u2*Q via Straus/Shamir with wNAF digits: w=7 fixed affine
//     table for G (32 odd multiples, built once), w=5 Jacobian table for
//     Q (8 odd multiples per verify).
//   - The final x-coordinate check avoids any field inversion:
//     accept iff X == r*Z^2 or (r + n < p and X == (r+n)*Z^2), exactly
//     the oracle's (x_R - r) % n == 0 acceptance set.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

typedef uint64_t u64;
typedef unsigned __int128 u128;

struct N256 {
    u64 d[4];
};

// p = 2^256 - 0x1000003D1
static const N256 P_M = {{0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL,
                          0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL}};
static const u64 P_K[3] = {0x1000003D1ULL, 0, 0};
// n (group order) = 2^256 - 0x14551231950B75FC4402DA1732FC9BEBF
static const N256 N_M = {{0xBFD25E8CD0364141ULL, 0xBAAEDCE6AF48A03BULL,
                          0xFFFFFFFFFFFFFFFEULL, 0xFFFFFFFFFFFFFFFFULL}};
static const u64 N_K[3] = {0x402DA1732FC9BEBFULL, 0x4551231950B75FC4ULL, 1};

static const N256 GX_C = {{0x59F2815B16F81798ULL, 0x029BFCDB2DCE28D9ULL,
                           0x55A06295CE870B07ULL, 0x79BE667EF9DCBBACULL}};
static const N256 GY_C = {{0x9C47D08FFB10D4B8ULL, 0xFD17B448A6855419ULL,
                           0x5DA4FBFC0E1108A8ULL, 0x483ADA7726A3C465ULL}};
static const N256 ONE_C = {{1, 0, 0, 0}};

static inline int cmp_n(const N256& a, const N256& b) {
    for (int i = 3; i >= 0; i--) {
        if (a.d[i] < b.d[i]) return -1;
        if (a.d[i] > b.d[i]) return 1;
    }
    return 0;
}

static inline bool is_zero_n(const N256& a) {
    return (a.d[0] | a.d[1] | a.d[2] | a.d[3]) == 0;
}

static inline u64 add_n(N256& a, const N256& b) {
    u128 c = 0;
    for (int i = 0; i < 4; i++) {
        c += (u128)a.d[i] + b.d[i];
        a.d[i] = (u64)c;
        c >>= 64;
    }
    return (u64)c;
}

static inline u64 sub_n(N256& a, const N256& b) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 t = (u128)a.d[i] - b.d[i] - borrow;
        a.d[i] = (u64)t;
        borrow = (t >> 64) & 1;
    }
    return (u64)borrow;
}

// 4x4 schoolbook by diagonals. Column sums of four 128-bit products can
// exceed u128; `spill` counts wraparounds and re-enters at +2^64 of the
// shifted carry.
static void mul_wide(const N256& a, const N256& b, u64 out[8]) {
    u128 acc = 0;
    u64 spill = 0;
    for (int k = 0; k < 7; k++) {
        int lo = k >= 4 ? k - 3 : 0;
        int hi = k < 4 ? k : 3;
        for (int i = lo; i <= hi; i++) {
            u128 pr = (u128)a.d[i] * b.d[k - i];
            acc += pr;
            if (acc < pr) spill++;
        }
        out[k] = (u64)acc;
        acc = (acc >> 64) + ((u128)spill << 64);
        spill = 0;
    }
    out[7] = (u64)acc;
}

// Squaring: off-diagonal products doubled (10 muls instead of 16).
static void sqr_wide(const N256& a, u64 out[8]) {
    u128 acc = 0;
    u64 spill = 0;
    for (int k = 0; k < 7; k++) {
        int lo = k >= 4 ? k - 3 : 0;
        for (int i = lo; 2 * i < k; i++) {
            u128 pr = (u128)a.d[i] * a.d[k - i];
            acc += pr;
            if (acc < pr) spill++;
            acc += pr;
            if (acc < pr) spill++;
        }
        if ((k & 1) == 0) {
            u128 pr = (u128)a.d[k / 2] * a.d[k / 2];
            acc += pr;
            if (acc < pr) spill++;
        }
        out[k] = (u64)acc;
        acc = (acc >> 64) + ((u128)spill << 64);
        spill = 0;
    }
    out[7] = (u64)acc;
}

// Fold an 8-word product to a canonical 4-word residue mod m = 2^256 - K.
// Each round rewrites words 4..7 (value H) as H*K added to the low part;
// magnitudes shrink fast (K <= 2^129), four rounds always suffice, then at
// most two conditional subtracts.
static void reduce_wide(u64 l[8], const u64 K[3], const N256& m, N256& out) {
    for (int round = 0; round < 4; round++) {
        u64 hi[4] = {l[4], l[5], l[6], l[7]};
        if ((hi[0] | hi[1] | hi[2] | hi[3]) == 0) break;
        l[4] = l[5] = l[6] = l[7] = 0;
        u64 prod[8] = {0, 0, 0, 0, 0, 0, 0, 0};
        for (int i = 0; i < 4; i++) {
            u128 carry = 0;
            for (int j = 0; j < 3; j++) {
                u128 cur = (u128)prod[i + j] + (u128)hi[i] * K[j] + carry;
                prod[i + j] = (u64)cur;
                carry = cur >> 64;
            }
            for (int k = i + 3; carry; k++) {
                u128 cur = (u128)prod[k] + carry;
                prod[k] = (u64)cur;
                carry = cur >> 64;
            }
        }
        u128 c = 0;
        for (int i = 0; i < 8; i++) {
            c += (u128)l[i] + prod[i];
            l[i] = (u64)c;
            c >>= 64;
        }
    }
    memcpy(out.d, l, 32);
    while (cmp_n(out, m) >= 0) sub_n(out, m);
}

static void modmul(const N256& a, const N256& b, const u64 K[3],
                   const N256& m, N256& out) {
    u64 w[8];
    mul_wide(a, b, w);
    reduce_wide(w, K, m, out);
}

static void modpow(const N256& base, const N256& exp, const u64 K[3],
                   const N256& m, N256& out) {
    // 4-bit fixed-window square-and-multiply: 14 precompute muls + 252
    // squarings + <=63 window muls (~330 modmuls) vs the plain ladder's
    // ~480 for the high-hamming-weight exponents this module actually
    // raises to ((p+1)/4 sqrt, n-2 / p-2 inverses) — the per-signature
    // host cost of pubkey decompression and scalar inversion.
    N256 tbl[16];
    tbl[1] = base;
    for (int i = 2; i < 16; i++) modmul(tbl[i - 1], base, K, m, tbl[i]);
    N256 acc = ONE_C;
    bool started = false;
    for (int i = 63; i >= 0; i--) {
        int nib = int((exp.d[i >> 4] >> ((i & 15) * 4)) & 0xF);
        if (!started) {
            if (nib == 0) continue;
            acc = tbl[nib];
            started = true;
            continue;
        }
        for (int k = 0; k < 4; k++) modmul(acc, acc, K, m, acc);
        if (nib) modmul(acc, tbl[nib], K, m, acc);
    }
    out = acc;
}

// ---- field ops mod p (inputs/outputs always canonical, < p) ----

static inline void fmul(N256& r, const N256& a, const N256& b) {
    u64 w[8];
    mul_wide(a, b, w);
    reduce_wide(w, P_K, P_M, r);
}

static inline void fsqr(N256& r, const N256& a) {
    u64 w[8];
    sqr_wide(a, w);
    reduce_wide(w, P_K, P_M, r);
}

static inline void fadd(N256& r, const N256& a, const N256& b) {
    r = a;
    u64 c = add_n(r, b);
    if (c || cmp_n(r, P_M) >= 0) sub_n(r, P_M);
}

static inline void fsub(N256& r, const N256& a, const N256& b) {
    r = a;
    if (sub_n(r, b)) add_n(r, P_M);
}

static inline void fneg(N256& r, const N256& a) {
    N256 v = a;  // r may alias a
    if (is_zero_n(v)) {
        r = v;
    } else {
        r = P_M;
        sub_n(r, v);
    }
}

// ---- point arithmetic (Jacobian; y^2 = x^3 + 7) ----

struct Jac {
    N256 X, Y, Z;
    bool inf;
};

struct Aff {
    N256 x, y;
};

// dbl-2009-l (a = 0). secp256k1 has no 2-torsion, so Y = 0 never occurs
// for a finite on-curve point and doubling stays finite.
static void pt_double(Jac& r, const Jac& p) {
    if (p.inf) {
        r = p;
        return;
    }
    N256 A, B, C, D, E, F, t, X3, Y3, Z3;
    fsqr(A, p.X);
    fsqr(B, p.Y);
    fsqr(C, B);
    fadd(t, p.X, B);
    fsqr(t, t);
    fsub(t, t, A);
    fsub(t, t, C);
    fadd(D, t, t);
    fadd(E, A, A);
    fadd(E, E, A);
    fsqr(F, E);
    fadd(t, D, D);
    fsub(X3, F, t);
    fsub(t, D, X3);
    fmul(Y3, E, t);
    fadd(t, C, C);
    fadd(t, t, t);
    fadd(t, t, t);  // 8C
    fsub(Y3, Y3, t);
    fmul(Z3, p.Y, p.Z);
    fadd(Z3, Z3, Z3);
    r.X = X3;
    r.Y = Y3;
    r.Z = Z3;
    r.inf = false;
}

// madd-2007-bl: Jacobian P + affine Q, with the complete case analysis
// (P = inf -> Q, same -> double, opposite -> infinity) done by branch —
// the branchless select dance of the TPU kernel is unnecessary on a CPU.
static void pt_add_mixed(Jac& r, const Jac& p, const Aff& q) {
    if (p.inf) {
        r.X = q.x;
        r.Y = q.y;
        r.Z = ONE_C;
        r.inf = false;
        return;
    }
    N256 Z1Z1, U2, S2, H, R, HH, HHH, V, t, X3, Y3, Z3;
    fsqr(Z1Z1, p.Z);
    fmul(U2, q.x, Z1Z1);
    fmul(t, p.Z, Z1Z1);
    fmul(S2, q.y, t);
    fsub(H, U2, p.X);
    fsub(R, S2, p.Y);
    if (is_zero_n(H)) {
        if (is_zero_n(R)) {
            pt_double(r, p);
        } else {
            r.inf = true;
        }
        return;
    }
    fsqr(HH, H);
    fmul(HHH, H, HH);
    fmul(V, p.X, HH);
    fsqr(X3, R);
    fsub(X3, X3, HHH);
    fadd(t, V, V);
    fsub(X3, X3, t);
    fsub(t, V, X3);
    fmul(Y3, R, t);
    fmul(t, p.Y, HHH);
    fsub(Y3, Y3, t);
    fmul(Z3, p.Z, H);
    r.X = X3;
    r.Y = Y3;
    r.Z = Z3;
    r.inf = false;
}

// Full Jacobian + Jacobian add (add-2007-bl shape).
static void pt_add(Jac& r, const Jac& p, const Jac& q) {
    if (p.inf) {
        r = q;
        return;
    }
    if (q.inf) {
        r = p;
        return;
    }
    N256 Z1Z1, Z2Z2, U1, U2, S1, S2, H, R, HH, HHH, V, t, X3, Y3, Z3;
    fsqr(Z1Z1, p.Z);
    fsqr(Z2Z2, q.Z);
    fmul(U1, p.X, Z2Z2);
    fmul(U2, q.X, Z1Z1);
    fmul(t, q.Z, Z2Z2);
    fmul(S1, p.Y, t);
    fmul(t, p.Z, Z1Z1);
    fmul(S2, q.Y, t);
    fsub(H, U2, U1);
    fsub(R, S2, S1);
    if (is_zero_n(H)) {
        if (is_zero_n(R)) {
            pt_double(r, p);
        } else {
            r.inf = true;
        }
        return;
    }
    fsqr(HH, H);
    fmul(HHH, H, HH);
    fmul(V, U1, HH);
    fsqr(X3, R);
    fsub(X3, X3, HHH);
    fadd(t, V, V);
    fsub(X3, X3, t);
    fsub(t, V, X3);
    fmul(Y3, R, t);
    fmul(t, S1, HHH);
    fsub(Y3, Y3, t);
    fmul(t, p.Z, q.Z);
    fmul(Z3, t, H);
    r.X = X3;
    r.Y = Y3;
    r.Z = Z3;
    r.inf = false;
}

// ---- wNAF recoding ----
// Digits are 0 or odd in [-(2^(w-1)-1), 2^(w-1)-1]; at most 257 of them.

static int wnaf_recode(const N256& s, int w, int8_t out[260]) {
    u64 d[4] = {s.d[0], s.d[1], s.d[2], s.d[3]};
    int pos = 0;
    const u64 mask = (1u << w) - 1;
    while (d[0] | d[1] | d[2] | d[3]) {
        int8_t digit = 0;
        if (d[0] & 1) {
            int word = (int)(d[0] & mask);
            if (word >= (1 << (w - 1))) word -= (1 << w);
            digit = (int8_t)word;
            if (word > 0) {
                u128 borrow = (u128)(u64)word;
                for (int i = 0; i < 4 && borrow; i++) {
                    u128 t = (u128)d[i] - borrow;
                    d[i] = (u64)t;
                    borrow = (t >> 64) & 1;
                }
            } else {
                u128 carry = (u128)(u64)(-word);
                for (int i = 0; i < 4 && carry; i++) {
                    carry += d[i];
                    d[i] = (u64)carry;
                    carry >>= 64;
                }
            }
        }
        out[pos++] = digit;
        d[0] = (d[0] >> 1) | (d[1] << 63);
        d[1] = (d[1] >> 1) | (d[2] << 63);
        d[2] = (d[2] >> 1) | (d[3] << 63);
        d[3] >>= 1;
    }
    return pos;
}

// ---- fixed-base G table (w=7: odd multiples 1G..63G, affine) ----

static Aff g_tab[32];
static std::once_flag g_tab_once;

static void build_g_tab() {
    Jac j[32];
    j[0].X = GX_C;
    j[0].Y = GY_C;
    j[0].Z = ONE_C;
    j[0].inf = false;
    Jac g2;
    pt_double(g2, j[0]);
    for (int i = 1; i < 32; i++) pt_add(j[i], j[i - 1], g2);
    // one-time naive affine conversion (Fermat inverse per entry)
    N256 pm2 = P_M;
    pm2.d[0] -= 2;
    for (int i = 0; i < 32; i++) {
        N256 zi, zi2, zi3;
        modpow(j[i].Z, pm2, P_K, P_M, zi);
        fsqr(zi2, zi);
        fmul(zi3, zi2, zi);
        fmul(g_tab[i].x, j[i].X, zi2);
        fmul(g_tab[i].y, j[i].Y, zi3);
    }
}

// ---- u1*G + u2*Q with the r / r+n x-coordinate acceptance check ----

static bool ecmult_check(const N256& u1, const N256& u2, const Aff& Q,
                         const N256& r_sig) {
    std::call_once(g_tab_once, build_g_tab);

    // per-verify w=5 table of odd Q multiples (1Q, 3Q, ..., 15Q)
    Jac q_tab[8];
    q_tab[0].X = Q.x;
    q_tab[0].Y = Q.y;
    q_tab[0].Z = ONE_C;
    q_tab[0].inf = false;
    Jac q2;
    pt_double(q2, q_tab[0]);
    for (int i = 1; i < 8; i++) pt_add(q_tab[i], q_tab[i - 1], q2);

    int8_t w1[260], w2[260];
    int l1 = wnaf_recode(u1, 7, w1);
    int l2 = wnaf_recode(u2, 5, w2);
    int len = l1 > l2 ? l1 : l2;

    Jac acc;
    acc.inf = true;
    for (int i = len - 1; i >= 0; i--) {
        pt_double(acc, acc);
        if (i < l1 && w1[i]) {
            int dg = w1[i];
            if (dg > 0) {
                pt_add_mixed(acc, acc, g_tab[(dg - 1) >> 1]);
            } else {
                Aff neg = g_tab[(-dg - 1) >> 1];
                fneg(neg.y, neg.y);
                pt_add_mixed(acc, acc, neg);
            }
        }
        if (i < l2 && w2[i]) {
            int dg = w2[i];
            if (dg > 0) {
                pt_add(acc, acc, q_tab[(dg - 1) >> 1]);
            } else {
                Jac neg = q_tab[(-dg - 1) >> 1];
                fneg(neg.Y, neg.Y);
                pt_add(acc, acc, neg);
            }
        }
    }
    if (acc.inf || is_zero_n(acc.Z)) return false;

    // x_R == r (mod n) without inverting Z: X == r*Z^2, or the wraparound
    // candidate X == (r+n)*Z^2 admissible only when r + n < p.
    N256 zz, cand;
    fsqr(zz, acc.Z);
    fmul(cand, r_sig, zz);
    if (cmp_n(cand, acc.X) == 0) return true;
    N256 rn = r_sig;
    u64 carry = add_n(rn, N_M);
    if (!carry && cmp_n(rn, P_M) < 0) {
        fmul(cand, rn, zz);
        if (cmp_n(cand, acc.X) == 0) return true;
    }
    return false;
}

static inline N256 load_be(const uint8_t* p) {
    N256 out;
    for (int i = 0; i < 4; i++) {
        u64 v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | p[8 * (3 - i) + j];
        out.d[i] = v;
    }
    return out;
}

static inline void store_be(const N256& v, uint8_t* p) {
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            p[8 * (3 - i) + j] = (uint8_t)(v.d[i] >> (56 - 8 * j));
}

// Full single verify. Matches crypto/secp256k1.py ecdsa_verify on every
// reachable input (pubkeys arrive pre-validated from pubkey_parse; the
// on-curve check here is defense in depth, not a semantic difference).
static bool verify_one(const uint8_t pub[64], const uint8_t rs[64],
                       const uint8_t msg[32]) {
    N256 qx = load_be(pub), qy = load_be(pub + 32);
    if (cmp_n(qx, P_M) >= 0 || cmp_n(qy, P_M) >= 0) return false;
    N256 y2, x3, seven = {{7, 0, 0, 0}};
    fsqr(y2, qy);
    fsqr(x3, qx);
    fmul(x3, x3, qx);
    fadd(x3, x3, seven);
    if (cmp_n(y2, x3) != 0) return false;

    N256 r = load_be(rs), s = load_be(rs + 32), e = load_be(msg);
    if (is_zero_n(r) || cmp_n(r, N_M) >= 0) return false;
    if (is_zero_n(s) || cmp_n(s, N_M) >= 0) return false;
    if (cmp_n(e, N_M) >= 0) sub_n(e, N_M);  // e < 2^256 < 2n: one subtract

    N256 nm2 = N_M;
    nm2.d[0] -= 2;
    N256 w, u1, u2;
    modpow(s, nm2, N_K, N_M, w);  // w = s^-1 mod n
    modmul(e, w, N_K, N_M, u1);
    modmul(r, w, N_K, N_M, u2);
    Aff Q = {qx, qy};
    return ecmult_check(u1, u2, Q, r);
}

static void run_chunked(long n, int nthreads, void (*fn)(long, long, void*),
                        void* ctx) {
    if (nthreads <= 0) {
        unsigned hw = std::thread::hardware_concurrency();
        nthreads = hw ? (int)hw : 1;
    }
    if ((long)nthreads > n) nthreads = (int)(n > 0 ? n : 1);
    if (nthreads <= 1) {
        fn(0, n, ctx);
        return;
    }
    std::vector<std::thread> threads;
    long per = (n + nthreads - 1) / nthreads;
    for (int t = 0; t < nthreads; t++) {
        long lo = t * per;
        long hi = lo + per < n ? lo + per : n;
        if (lo >= hi) break;
        threads.emplace_back(fn, lo, hi, ctx);
    }
    for (auto& th : threads) th.join();
}

struct VerifyCtx {
    const uint8_t* pub;
    const uint8_t* rs;
    const uint8_t* msg;
    uint8_t* ok;
};

struct PrecompCtx {
    const uint8_t* rs;
    const uint8_t* msg;
    uint8_t* u1;
    uint8_t* u2;
    uint8_t* ok;
};

static void verify_range(long lo, long hi, void* p) {
    VerifyCtx* c = (VerifyCtx*)p;
    for (long i = lo; i < hi; i++)
        c->ok[i] = verify_one(c->pub + 64 * i, c->rs + 64 * i,
                              c->msg + 32 * i)
                       ? 1
                       : 0;
}

static void precompute_range(long lo, long hi, void* p) {
    // Montgomery batch inversion: ONE Fermat inversion for the whole
    // chunk plus 3 multiplications per element (prefix products, invert
    // the total, unwind) — vs a ~384-modmul modpow per signature. Range-
    // invalid s values are substituted with 1 to keep the running product
    // invertible; their lanes are flagged ok=0 and never trusted.
    PrecompCtx* c = (PrecompCtx*)p;
    long n = hi - lo;
    if (n <= 0) return;
    std::vector<N256> s_eff((size_t)n), prefix((size_t)n);
    N256 nm2 = N_M;
    nm2.d[0] -= 2;
    for (long i = 0; i < n; i++) {
        N256 r = load_be(c->rs + 64 * (lo + i));
        N256 s = load_be(c->rs + 64 * (lo + i) + 32);
        bool bad = is_zero_n(s) || cmp_n(s, N_M) >= 0 || is_zero_n(r) ||
                   cmp_n(r, N_M) >= 0;
        c->ok[lo + i] = bad ? 0 : 1;
        s_eff[(size_t)i] = bad ? ONE_C : s;
        if (i == 0) {
            prefix[0] = s_eff[0];
        } else {
            modmul(prefix[(size_t)i - 1], s_eff[(size_t)i], N_K, N_M,
                   prefix[(size_t)i]);
        }
    }
    N256 inv_run;
    modpow(prefix[(size_t)n - 1], nm2, N_K, N_M, inv_run);
    for (long i = n - 1; i >= 0; i--) {
        N256 w;
        if (i == 0) {
            w = inv_run;
        } else {
            modmul(inv_run, prefix[(size_t)i - 1], N_K, N_M, w);
            modmul(inv_run, s_eff[(size_t)i], N_K, N_M, inv_run);
        }
        long idx = lo + i;
        if (!c->ok[idx]) {
            memset(c->u1 + 32 * idx, 0, 32);
            memset(c->u2 + 32 * idx, 0, 32);
            continue;
        }
        N256 r = load_be(c->rs + 64 * idx);
        N256 e = load_be(c->msg + 32 * idx);
        if (cmp_n(e, N_M) >= 0) sub_n(e, N_M);
        N256 u1, u2;
        modmul(e, w, N_K, N_M, u1);
        modmul(r, w, N_K, N_M, u2);
        store_be(u1, c->u1 + 32 * idx);
        store_be(u2, c->u2 + 32 * idx);
    }
}

// k*G as affine x (mod p), via the fixed wNAF G table. Returns false for
// k = 0 / k >= n or if the ladder lands at infinity (unreachable for
// valid k, kept for safety).
static bool base_mult_affine_x(const N256& k, N256& x_out) {
    std::call_once(g_tab_once, build_g_tab);
    if (is_zero_n(k) || cmp_n(k, N_M) >= 0) return false;
    int8_t w1[260];
    int l1 = wnaf_recode(k, 7, w1);
    Jac acc;
    acc.inf = true;
    for (int i = l1 - 1; i >= 0; i--) {
        pt_double(acc, acc);
        if (w1[i]) {
            int dg = w1[i];
            if (dg > 0) {
                pt_add_mixed(acc, acc, g_tab[(dg - 1) >> 1]);
            } else {
                Aff neg = g_tab[(-dg - 1) >> 1];
                fneg(neg.y, neg.y);
                pt_add_mixed(acc, acc, neg);
            }
        }
    }
    if (acc.inf || is_zero_n(acc.Z)) return false;
    N256 pm2 = P_M, zi, zi2;
    pm2.d[0] -= 2;
    modpow(acc.Z, pm2, P_K, P_M, zi);
    fsqr(zi2, zi);
    fmul(x_out, acc.X, zi2);
    return true;
}

}  // namespace

extern "C" {

// ECDSA sign with a caller-supplied nonce (the RFC6979 derivation stays in
// Python so signatures are bit-identical to the oracle signer — HMAC cost
// is microseconds; the EC math here is what was slow). Writes r||s (32-byte
// big-endian each) with low-s normalization. Returns 1, or 0 when the
// caller must retry with the next nonce (r == 0 or s == 0) or inputs are
// out of range.
int bcp_ecdsa_sign(const uint8_t* sk32, const uint8_t* e32,
                   const uint8_t* k32, uint8_t* rs64_out) {
    N256 sk = load_be(sk32), e = load_be(e32), k = load_be(k32);
    if (is_zero_n(sk) || cmp_n(sk, N_M) >= 0) return 0;
    if (cmp_n(e, N_M) >= 0) sub_n(e, N_M);
    N256 x;
    if (!base_mult_affine_x(k, x)) return 0;
    N256 r = x;
    while (cmp_n(r, N_M) >= 0) sub_n(r, N_M);
    if (is_zero_n(r)) return 0;
    // s = k^-1 (e + r*sk) mod n
    N256 nm2 = N_M, kinv, rd, sum, s;
    nm2.d[0] -= 2;
    modpow(k, nm2, N_K, N_M, kinv);
    modmul(r, sk, N_K, N_M, rd);
    sum = e;
    if (add_n(sum, rd) || cmp_n(sum, N_M) >= 0) sub_n(sum, N_M);
    modmul(kinv, sum, N_K, N_M, s);
    if (is_zero_n(s)) return 0;
    // low-s: if s > n/2, s = n - s  (n odd: n/2 rounds down, so the
    // comparison s*2 > n is exact via add-with-carry)
    N256 s2 = s;
    u64 c = add_n(s2, s);
    if (c || cmp_n(s2, N_M) > 0) {
        N256 ns = N_M;
        sub_n(ns, s);
        s = ns;
    }
    store_be(r, rs64_out);
    store_be(s, rs64_out + 32);
    return 1;
}

// Single ECDSA verify: pub = 64-byte x||y (32-byte big-endian each),
// rs = 64-byte r||s, msg = 32-byte message hash. Returns 1 valid / 0 not.
int bcp_ecdsa_verify(const uint8_t* pub, const uint8_t* rs,
                     const uint8_t* msg) {
    return verify_one(pub, rs, msg) ? 1 : 0;
}

// Batch verify across nthreads host threads (nthreads <= 0: one per core).
void bcp_ecdsa_verify_batch(const uint8_t* pub, const uint8_t* rs,
                            const uint8_t* msg, long n, uint8_t* ok,
                            int nthreads) {
    VerifyCtx c = {pub, rs, msg, ok};
    run_chunked(n, nthreads, verify_range, &c);
}

// Scalar precomputation for the TPU batch packer: per signature computes
// u1 = e * s^-1 mod n and u2 = r * s^-1 mod n (32-byte big-endian out).
// ok[i] = 0 flags out-of-range r/s (caller must not trust u1/u2 there).
void bcp_ecdsa_precompute(const uint8_t* rs, const uint8_t* msg, long n,
                          uint8_t* u1, uint8_t* u2, uint8_t* ok,
                          int nthreads) {
    PrecompCtx c = {rs, msg, u1, u2, ok};
    run_chunked(n, nthreads, precompute_range, &c);
}

// Pubkey parse/decompress (CPubKey / secp256k1_ec_pubkey_parse semantics,
// matching crypto/secp256k1.pubkey_parse): 33-byte 02/03 compressed,
// 65-byte 04 uncompressed or 06/07 hybrid (hybrid requires matching y
// parity). Writes affine x||y (32-byte big-endian each); returns 1 ok,
// 0 malformed/off-curve.
int bcp_pubkey_parse(const uint8_t* data, long len, uint8_t* out64) {
    if (len == 33 && (data[0] == 2 || data[0] == 3)) {
        N256 x = load_be(data + 1);
        if (cmp_n(x, P_M) >= 0) return 0;
        // y^2 = x^3 + 7; sqrt via pow((p+1)/4) — p = 3 mod 4
        N256 y2, x3, seven = {{7, 0, 0, 0}};
        fsqr(x3, x);
        fmul(x3, x3, x);
        fadd(y2, x3, seven);
        // (p+1)/4
        static const N256 P14 = {{0xFFFFFFFFBFFFFF0CULL, 0xFFFFFFFFFFFFFFFFULL,
                                  0xFFFFFFFFFFFFFFFFULL, 0x3FFFFFFFFFFFFFFFULL}};
        N256 y;
        modpow(y2, P14, P_K, P_M, y);
        N256 chk;
        fsqr(chk, y);
        if (cmp_n(chk, y2) != 0) return 0;  // non-residue: off-curve x
        if ((y.d[0] & 1) != (data[0] & 1)) {
            N256 ny;
            fneg(ny, y);
            y = ny;
        }
        store_be(x, out64);
        store_be(y, out64 + 32);
        return 1;
    }
    if (len == 65 && (data[0] == 4 || data[0] == 6 || data[0] == 7)) {
        N256 x = load_be(data + 1), y = load_be(data + 33);
        if (cmp_n(x, P_M) >= 0 || cmp_n(y, P_M) >= 0) return 0;
        if ((data[0] == 6 || data[0] == 7) && (y.d[0] & 1) != (data[0] & 1))
            return 0;
        N256 y2, x3, seven = {{7, 0, 0, 0}};
        fsqr(y2, y);
        fsqr(x3, x);
        fmul(x3, x3, x);
        fadd(x3, x3, seven);
        if (cmp_n(y2, x3) != 0) return 0;
        store_be(x, out64);
        store_be(y, out64 + 32);
        return 1;
    }
    return 0;
}

}  // extern "C"
