// Native runtime components — the C++ layer the reference keeps for its
// IO/serialization hot paths (src/primitives/*.h serialization templates,
// src/crypto/sha256.cpp, src/consensus/merkle.cpp). The JAX/Pallas kernels
// are the TPU compute path; this library serves the HOST side of
// -reindex/block-store scans: wire-format parsing (tx boundaries + txids)
// and double-SHA256/merkle work, callable from Python via ctypes
// (bitcoincashplus_tpu/native.py). Python remains the consensus reference
// implementation; every native result is differential-tested against it.
//
// Build: make -C native   (produces libbcpnative.so)

#include <cstdint>
#include <cstring>
#include <cstddef>

#include "common.h"

namespace {

using bcpn::sha256d;

// ---------------------------------------------------------------------------
// Wire-format scanning (src/primitives/transaction.h serialization layout).
// Bounds-checked: every reader returns false on truncation, the parse entry
// points return negative error codes rather than reading past the buffer.
// ---------------------------------------------------------------------------

struct Reader {
    const uint8_t* p;
    size_t len, pos = 0;

    bool skip(size_t n) {
        if (len - pos < n) return false;
        pos += n;
        return true;
    }
    bool u32(uint32_t* out) {
        if (len - pos < 4) return false;
        memcpy(out, p + pos, 4);  // little-endian hosts only (x86/ARM LE)
        pos += 4;
        return true;
    }
    bool compact(uint64_t* out) {
        if (pos >= len) return false;
        uint8_t tag = p[pos++];
        if (tag < 253) { *out = tag; return true; }
        size_t n = tag == 253 ? 2 : tag == 254 ? 4 : 8;
        if (len - pos < n) return false;
        uint64_t v = 0;
        for (size_t i = 0; i < n; i++) v |= uint64_t(p[pos + i]) << (8 * i);
        pos += n;
        *out = v;
        return true;
    }
    bool var_bytes() {  // CompactSize length + payload
        uint64_t n;
        if (!compact(&n)) return false;
        if (n > len - pos) return false;  // never allocate on a lie
        pos += size_t(n);
        return true;
    }
};

// One transaction: advances r past it; writes [start, end) into *start/*end.
static bool scan_tx(Reader& r, size_t* start, size_t* end) {
    *start = r.pos;
    uint32_t version;
    if (!r.u32(&version)) return false;
    uint64_t nin;
    if (!r.compact(&nin)) return false;
    if (nin > 1000000) return false;  // absurd count = corrupt input
    for (uint64_t i = 0; i < nin; i++) {
        if (!r.skip(36)) return false;      // outpoint
        if (!r.var_bytes()) return false;   // scriptSig
        if (!r.skip(4)) return false;       // sequence
    }
    uint64_t nout;
    if (!r.compact(&nout)) return false;
    if (nout > 1000000) return false;
    for (uint64_t i = 0; i < nout; i++) {
        if (!r.skip(8)) return false;       // value
        if (!r.var_bytes()) return false;   // scriptPubKey
    }
    if (!r.skip(4)) return false;           // locktime
    *end = r.pos;
    return true;
}

}  // namespace

extern "C" {

// sha256d of a buffer.
void bcp_sha256d(const uint8_t* data, size_t len, uint8_t out32[32]) {
    sha256d(data, len, out32);
}

// Batch header hashing: n 80-byte headers -> n 32-byte digests.
void bcp_hash_headers(const uint8_t* headers, size_t n, uint8_t* out) {
    for (size_t i = 0; i < n; i++)
        sha256d(headers + 80 * i, 80, out + 32 * i);
}

// Scan a serialized block: writes tx count, per-tx txids (32 bytes each,
// wire order) and [start,end) byte offsets. Returns tx count, or
//   -1 truncated/corrupt header or tx
//   -2 more txs than max_tx (caller's buffers too small)
long bcp_scan_block(const uint8_t* data, size_t len,
                    uint8_t* txids, uint64_t* offsets, long max_tx) {
    Reader r{data, len};
    if (!r.skip(80)) return -1;  // header
    uint64_t n;
    if (!r.compact(&n)) return -1;
    if (max_tx < 0 || n > (uint64_t)max_tx) return -2;  // unsigned compare:
    // a 2^63+ CompactSize must hit the cap, not wrap negative past it
    for (uint64_t i = 0; i < n; i++) {
        size_t s, e;
        if (!scan_tx(r, &s, &e)) return -1;
        sha256d(data + s, e - s, txids + 32 * i);
        offsets[2 * i] = s;
        offsets[2 * i + 1] = e;
    }
    return (long)n;
}

// Merkle root with the CVE-2012-2459 duplicate-pair mutation flag
// (src/consensus/merkle.cpp ComputeMerkleRoot): txids = n*32 bytes in,
// root32 out; returns 1 if a mutation pattern was detected else 0,
// or -1 on n == 0.
long bcp_merkle_root(const uint8_t* txids, long n, uint8_t* root32) {
    if (n <= 0) return -1;
    // work buffer: level <= n hashes
    uint8_t* level = new uint8_t[size_t(n) * 32];
    memcpy(level, txids, size_t(n) * 32);
    long cnt = n;
    long mutated = 0;
    uint8_t pair[64];
    while (cnt > 1) {
        long next = 0;
        for (long i = 0; i < cnt; i += 2) {
            long j = (i + 1 < cnt) ? i + 1 : i;  // odd: pair with itself
            if (i + 1 < cnt && memcmp(level + 32*i, level + 32*j, 32) == 0)
                mutated = 1;  // identical consecutive pair
            memcpy(pair, level + 32*i, 32);
            memcpy(pair + 32, level + 32*j, 32);
            sha256d(pair, 64, level + 32*next);
            next++;
        }
        cnt = next;
    }
    memcpy(root32, level, 32);
    delete[] level;
    return mutated;
}

}  // extern "C"
