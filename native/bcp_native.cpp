// Native runtime components — the C++ layer the reference keeps for its
// IO/serialization hot paths (src/primitives/*.h serialization templates,
// src/crypto/sha256.cpp, src/consensus/merkle.cpp). The JAX/Pallas kernels
// are the TPU compute path; this library serves the HOST side of
// -reindex/block-store scans: wire-format parsing (tx boundaries + txids)
// and double-SHA256/merkle work, callable from Python via ctypes
// (bitcoincashplus_tpu/native.py). Python remains the consensus reference
// implementation; every native result is differential-tested against it.
//
// Build: make -C native   (produces libbcpnative.so)

#include <cstdint>
#include <cstring>
#include <cstddef>

// ---------------------------------------------------------------------------
// SHA-256 (FIPS-180-4), straightforward portable implementation.
// ---------------------------------------------------------------------------

namespace {

static const uint32_t K[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,
    0x923f82a4,0xab1c5ed5,0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,
    0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,0xe49b69c1,0xefbe4786,
    0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,
    0x06ca6351,0x14292967,0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,
    0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,0xa2bfe8a1,0xa81a664b,
    0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,
    0x5b9cca4f,0x682e6ff3,0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,
    0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2,
};

static inline uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

struct Sha256 {
    uint32_t h[8];
    uint8_t buf[64];
    uint64_t total = 0;
    size_t fill = 0;

    Sha256() {
        static const uint32_t init[8] = {
            0x6a09e667,0xbb67ae85,0x3c6ef372,0xa54ff53a,
            0x510e527f,0x9b05688c,0x1f83d9ab,0x5be0cd19,
        };
        memcpy(h, init, sizeof(h));
    }

    void transform(const uint8_t* p) {
        uint32_t w[64];
        for (int i = 0; i < 16; i++)
            w[i] = (uint32_t(p[4*i]) << 24) | (uint32_t(p[4*i+1]) << 16)
                 | (uint32_t(p[4*i+2]) << 8) | uint32_t(p[4*i+3]);
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = rotr(w[i-15],7) ^ rotr(w[i-15],18) ^ (w[i-15] >> 3);
            uint32_t s1 = rotr(w[i-2],17) ^ rotr(w[i-2],19) ^ (w[i-2] >> 10);
            w[i] = w[i-16] + s0 + w[i-7] + s1;
        }
        uint32_t a=h[0],b=h[1],c=h[2],d=h[3],e=h[4],f=h[5],g=h[6],hh=h[7];
        for (int i = 0; i < 64; i++) {
            uint32_t S1 = rotr(e,6) ^ rotr(e,11) ^ rotr(e,25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = hh + S1 + ch + K[i] + w[i];
            uint32_t S0 = rotr(a,2) ^ rotr(a,13) ^ rotr(a,22);
            uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t t2 = S0 + mj;
            hh=g; g=f; f=e; e=d+t1; d=c; c=b; b=a; a=t1+t2;
        }
        h[0]+=a; h[1]+=b; h[2]+=c; h[3]+=d; h[4]+=e; h[5]+=f; h[6]+=g; h[7]+=hh;
    }

    void update(const uint8_t* data, size_t len) {
        total += len;
        if (fill) {
            size_t take = 64 - fill;
            if (take > len) take = len;
            memcpy(buf + fill, data, take);
            fill += take; data += take; len -= take;
            if (fill == 64) { transform(buf); fill = 0; }
        }
        while (len >= 64) { transform(data); data += 64; len -= 64; }
        if (len) { memcpy(buf, data, len); fill = len; }
    }

    void final(uint8_t out[32]) {
        uint64_t bits = total * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t z = 0;
        while (fill != 56) update(&z, 1);
        uint8_t lenb[8];
        for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (56 - 8*i));
        update(lenb, 8);
        for (int i = 0; i < 8; i++) {
            out[4*i]   = uint8_t(h[i] >> 24);
            out[4*i+1] = uint8_t(h[i] >> 16);
            out[4*i+2] = uint8_t(h[i] >> 8);
            out[4*i+3] = uint8_t(h[i]);
        }
    }
};

static void sha256d(const uint8_t* data, size_t len, uint8_t out[32]) {
    uint8_t mid[32];
    Sha256 a; a.update(data, len); a.final(mid);
    Sha256 b; b.update(mid, 32); b.final(out);
}

// ---------------------------------------------------------------------------
// Wire-format scanning (src/primitives/transaction.h serialization layout).
// Bounds-checked: every reader returns false on truncation, the parse entry
// points return negative error codes rather than reading past the buffer.
// ---------------------------------------------------------------------------

struct Reader {
    const uint8_t* p;
    size_t len, pos = 0;

    bool skip(size_t n) {
        if (len - pos < n) return false;
        pos += n;
        return true;
    }
    bool u32(uint32_t* out) {
        if (len - pos < 4) return false;
        memcpy(out, p + pos, 4);  // little-endian hosts only (x86/ARM LE)
        pos += 4;
        return true;
    }
    bool compact(uint64_t* out) {
        if (pos >= len) return false;
        uint8_t tag = p[pos++];
        if (tag < 253) { *out = tag; return true; }
        size_t n = tag == 253 ? 2 : tag == 254 ? 4 : 8;
        if (len - pos < n) return false;
        uint64_t v = 0;
        for (size_t i = 0; i < n; i++) v |= uint64_t(p[pos + i]) << (8 * i);
        pos += n;
        *out = v;
        return true;
    }
    bool var_bytes() {  // CompactSize length + payload
        uint64_t n;
        if (!compact(&n)) return false;
        if (n > len - pos) return false;  // never allocate on a lie
        pos += size_t(n);
        return true;
    }
};

// One transaction: advances r past it; writes [start, end) into *start/*end.
static bool scan_tx(Reader& r, size_t* start, size_t* end) {
    *start = r.pos;
    uint32_t version;
    if (!r.u32(&version)) return false;
    uint64_t nin;
    if (!r.compact(&nin)) return false;
    if (nin > 1000000) return false;  // absurd count = corrupt input
    for (uint64_t i = 0; i < nin; i++) {
        if (!r.skip(36)) return false;      // outpoint
        if (!r.var_bytes()) return false;   // scriptSig
        if (!r.skip(4)) return false;       // sequence
    }
    uint64_t nout;
    if (!r.compact(&nout)) return false;
    if (nout > 1000000) return false;
    for (uint64_t i = 0; i < nout; i++) {
        if (!r.skip(8)) return false;       // value
        if (!r.var_bytes()) return false;   // scriptPubKey
    }
    if (!r.skip(4)) return false;           // locktime
    *end = r.pos;
    return true;
}

}  // namespace

extern "C" {

// sha256d of a buffer.
void bcp_sha256d(const uint8_t* data, size_t len, uint8_t out32[32]) {
    sha256d(data, len, out32);
}

// Batch header hashing: n 80-byte headers -> n 32-byte digests.
void bcp_hash_headers(const uint8_t* headers, size_t n, uint8_t* out) {
    for (size_t i = 0; i < n; i++)
        sha256d(headers + 80 * i, 80, out + 32 * i);
}

// Scan a serialized block: writes tx count, per-tx txids (32 bytes each,
// wire order) and [start,end) byte offsets. Returns tx count, or
//   -1 truncated/corrupt header or tx
//   -2 more txs than max_tx (caller's buffers too small)
long bcp_scan_block(const uint8_t* data, size_t len,
                    uint8_t* txids, uint64_t* offsets, long max_tx) {
    Reader r{data, len};
    if (!r.skip(80)) return -1;  // header
    uint64_t n;
    if (!r.compact(&n)) return -1;
    if (max_tx < 0 || n > (uint64_t)max_tx) return -2;  // unsigned compare:
    // a 2^63+ CompactSize must hit the cap, not wrap negative past it
    for (uint64_t i = 0; i < n; i++) {
        size_t s, e;
        if (!scan_tx(r, &s, &e)) return -1;
        sha256d(data + s, e - s, txids + 32 * i);
        offsets[2 * i] = s;
        offsets[2 * i + 1] = e;
    }
    return (long)n;
}

// Merkle root with the CVE-2012-2459 duplicate-pair mutation flag
// (src/consensus/merkle.cpp ComputeMerkleRoot): txids = n*32 bytes in,
// root32 out; returns 1 if a mutation pattern was detected else 0,
// or -1 on n == 0.
long bcp_merkle_root(const uint8_t* txids, long n, uint8_t* root32) {
    if (n <= 0) return -1;
    // work buffer: level <= n hashes
    uint8_t* level = new uint8_t[size_t(n) * 32];
    memcpy(level, txids, size_t(n) * 32);
    long cnt = n;
    long mutated = 0;
    uint8_t pair[64];
    while (cnt > 1) {
        long next = 0;
        for (long i = 0; i < cnt; i += 2) {
            long j = (i + 1 < cnt) ? i + 1 : i;  // odd: pair with itself
            if (i + 1 < cnt && memcmp(level + 32*i, level + 32*j, 32) == 0)
                mutated = 1;  // identical consecutive pair
            memcpy(pair, level + 32*i, 32);
            memcpy(pair + 32, level + 32*j, 32);
            sha256d(pair, 64, level + 32*next);
            next++;
        }
        cnt = next;
    }
    memcpy(root32, level, 32);
    delete[] level;
    return mutated;
}

}  // extern "C"
