// Shared native-runtime primitives: SHA-256 / SHA-256d, RIPEMD-160 and the
// bounds-checked wire reader. Header-only so each TU (bcp_native.cpp,
// connect.cpp) can use them without a separate link step — the Makefile
// compiles every .cpp straight into libbcpnative.so.
//
// Reference lineage: src/crypto/sha256.cpp, src/crypto/ripemd160.cpp,
// src/serialize.h (ReadCompactSize). Consensus behavior (canonical
// CompactSize, MAX_SIZE bound) mirrors consensus/serialize.py, the Python
// reference implementation in this repo.

#pragma once

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace bcpn {

// ---------------------------------------------------------------------------
// SHA-256 (FIPS-180-4)
// ---------------------------------------------------------------------------

static const uint32_t SHA256_K[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,
    0x923f82a4,0xab1c5ed5,0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,
    0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,0xe49b69c1,0xefbe4786,
    0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,
    0x06ca6351,0x14292967,0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,
    0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,0xa2bfe8a1,0xa81a664b,
    0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,
    0x5b9cca4f,0x682e6ff3,0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,
    0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2,
};

inline uint32_t rotr32(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

struct Sha256 {
    uint32_t h[8];
    uint8_t buf[64];
    uint64_t total = 0;
    size_t fill = 0;

    Sha256() {
        static const uint32_t init[8] = {
            0x6a09e667,0xbb67ae85,0x3c6ef372,0xa54ff53a,
            0x510e527f,0x9b05688c,0x1f83d9ab,0x5be0cd19,
        };
        memcpy(h, init, sizeof(h));
    }

    void transform(const uint8_t* p) {
        uint32_t w[64];
        for (int i = 0; i < 16; i++)
            w[i] = (uint32_t(p[4*i]) << 24) | (uint32_t(p[4*i+1]) << 16)
                 | (uint32_t(p[4*i+2]) << 8) | uint32_t(p[4*i+3]);
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = rotr32(w[i-15],7) ^ rotr32(w[i-15],18) ^ (w[i-15] >> 3);
            uint32_t s1 = rotr32(w[i-2],17) ^ rotr32(w[i-2],19) ^ (w[i-2] >> 10);
            w[i] = w[i-16] + s0 + w[i-7] + s1;
        }
        uint32_t a=h[0],b=h[1],c=h[2],d=h[3],e=h[4],f=h[5],g=h[6],hh=h[7];
        for (int i = 0; i < 64; i++) {
            uint32_t S1 = rotr32(e,6) ^ rotr32(e,11) ^ rotr32(e,25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = hh + S1 + ch + SHA256_K[i] + w[i];
            uint32_t S0 = rotr32(a,2) ^ rotr32(a,13) ^ rotr32(a,22);
            uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t t2 = S0 + mj;
            hh=g; g=f; f=e; e=d+t1; d=c; c=b; b=a; a=t1+t2;
        }
        h[0]+=a; h[1]+=b; h[2]+=c; h[3]+=d; h[4]+=e; h[5]+=f; h[6]+=g; h[7]+=hh;
    }

    void update(const uint8_t* data, size_t len) {
        total += len;
        if (fill) {
            size_t take = 64 - fill;
            if (take > len) take = len;
            memcpy(buf + fill, data, take);
            fill += take; data += take; len -= take;
            if (fill == 64) { transform(buf); fill = 0; }
        }
        while (len >= 64) { transform(data); data += 64; len -= 64; }
        if (len) { memcpy(buf, data, len); fill = len; }
    }

    void final(uint8_t out[32]) {
        uint64_t bits = total * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t z = 0;
        while (fill != 56) update(&z, 1);
        uint8_t lenb[8];
        for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (56 - 8*i));
        update(lenb, 8);
        for (int i = 0; i < 8; i++) {
            out[4*i]   = uint8_t(h[i] >> 24);
            out[4*i+1] = uint8_t(h[i] >> 16);
            out[4*i+2] = uint8_t(h[i] >> 8);
            out[4*i+3] = uint8_t(h[i]);
        }
    }
};

inline void sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
    Sha256 a; a.update(data, len); a.final(out);
}

inline void sha256d(const uint8_t* data, size_t len, uint8_t out[32]) {
    uint8_t mid[32];
    Sha256 a; a.update(data, len); a.final(mid);
    Sha256 b; b.update(mid, 32); b.final(out);
}

// ---------------------------------------------------------------------------
// RIPEMD-160 (for HASH160 = RIPEMD160(SHA256(x)) — script P2PKH matching)
// ---------------------------------------------------------------------------

struct Ripemd160 {
    uint32_t h[5] = {0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0};
    uint8_t buf[64];
    uint64_t total = 0;
    size_t fill = 0;

    static uint32_t rol(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }
    static uint32_t f1(uint32_t x, uint32_t y, uint32_t z) { return x ^ y ^ z; }
    static uint32_t f2(uint32_t x, uint32_t y, uint32_t z) { return (x & y) | (~x & z); }
    static uint32_t f3(uint32_t x, uint32_t y, uint32_t z) { return (x | ~y) ^ z; }
    static uint32_t f4(uint32_t x, uint32_t y, uint32_t z) { return (x & z) | (y & ~z); }
    static uint32_t f5(uint32_t x, uint32_t y, uint32_t z) { return x ^ (y | ~z); }

    void transform(const uint8_t* p) {
        static const int R1[80] = {
            0,1,2,3,4,5,6,7,8,9,10,11,12,13,14,15,
            7,4,13,1,10,6,15,3,12,0,9,5,2,14,11,8,
            3,10,14,4,9,15,8,1,2,7,0,6,13,11,5,12,
            1,9,11,10,0,8,12,4,13,3,7,15,14,5,6,2,
            4,0,5,9,7,12,2,10,14,1,3,8,11,6,15,13};
        static const int R2[80] = {
            5,14,7,0,9,2,11,4,13,6,15,8,1,10,3,12,
            6,11,3,7,0,13,5,10,14,15,8,12,4,9,1,2,
            15,5,1,3,7,14,6,9,11,8,12,2,10,0,4,13,
            8,6,4,1,3,11,15,0,5,12,2,13,9,7,10,14,
            12,15,10,4,1,5,8,7,6,2,13,14,0,3,9,11};
        static const int S1[80] = {
            11,14,15,12,5,8,7,9,11,13,14,15,6,7,9,8,
            7,6,8,13,11,9,7,15,7,12,15,9,11,7,13,12,
            11,13,6,7,14,9,13,15,14,8,13,6,5,12,7,5,
            11,12,14,15,14,15,9,8,9,14,5,6,8,6,5,12,
            9,15,5,11,6,8,13,12,5,12,13,14,11,8,5,6};
        static const int S2[80] = {
            8,9,9,11,13,15,15,5,7,7,8,11,14,14,12,6,
            9,13,15,7,12,8,9,11,7,7,12,7,6,15,13,11,
            9,7,15,11,8,6,6,14,12,13,5,14,13,13,7,5,
            15,5,8,11,14,14,6,14,6,9,12,9,12,5,15,8,
            8,5,12,9,12,5,14,6,8,13,6,5,15,13,11,11};
        static const uint32_t K1[5] = {0, 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xA953FD4E};
        static const uint32_t K2[5] = {0x50A28BE6, 0x5C4DD124, 0x6D703EF3, 0x7A6D76E9, 0};
        uint32_t x[16];
        for (int i = 0; i < 16; i++)
            x[i] = uint32_t(p[4*i]) | (uint32_t(p[4*i+1]) << 8)
                 | (uint32_t(p[4*i+2]) << 16) | (uint32_t(p[4*i+3]) << 24);
        uint32_t a1=h[0],b1=h[1],c1=h[2],d1=h[3],e1=h[4];
        uint32_t a2=h[0],b2=h[1],c2=h[2],d2=h[3],e2=h[4];
        for (int j = 0; j < 80; j++) {
            int rd = j / 16;
            uint32_t f, g;
            switch (rd) {
                case 0: f = f1(b1,c1,d1); g = f5(b2,c2,d2); break;
                case 1: f = f2(b1,c1,d1); g = f4(b2,c2,d2); break;
                case 2: f = f3(b1,c1,d1); g = f3(b2,c2,d2); break;
                case 3: f = f4(b1,c1,d1); g = f2(b2,c2,d2); break;
                default: f = f5(b1,c1,d1); g = f1(b2,c2,d2); break;
            }
            uint32_t t = rol(a1 + f + x[R1[j]] + K1[rd], S1[j]) + e1;
            a1 = e1; e1 = d1; d1 = rol(c1, 10); c1 = b1; b1 = t;
            t = rol(a2 + g + x[R2[j]] + K2[rd], S2[j]) + e2;
            a2 = e2; e2 = d2; d2 = rol(c2, 10); c2 = b2; b2 = t;
        }
        uint32_t t = h[1] + c1 + d2;
        h[1] = h[2] + d1 + e2;
        h[2] = h[3] + e1 + a2;
        h[3] = h[4] + a1 + b2;
        h[4] = h[0] + b1 + c2;
        h[0] = t;
    }

    void update(const uint8_t* data, size_t len) {
        total += len;
        if (fill) {
            size_t take = 64 - fill;
            if (take > len) take = len;
            memcpy(buf + fill, data, take);
            fill += take; data += take; len -= take;
            if (fill == 64) { transform(buf); fill = 0; }
        }
        while (len >= 64) { transform(data); data += 64; len -= 64; }
        if (len) { memcpy(buf, data, len); fill = len; }
    }

    void final(uint8_t out[20]) {
        uint64_t bits = total * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t z = 0;
        while (fill != 56) update(&z, 1);
        uint8_t lenb[8];
        for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (8 * i));
        update(lenb, 8);
        for (int i = 0; i < 5; i++) {
            out[4*i]   = uint8_t(h[i]);
            out[4*i+1] = uint8_t(h[i] >> 8);
            out[4*i+2] = uint8_t(h[i] >> 16);
            out[4*i+3] = uint8_t(h[i] >> 24);
        }
    }
};

inline void hash160(const uint8_t* data, size_t len, uint8_t out[20]) {
    uint8_t mid[32];
    sha256(data, len, mid);
    Ripemd160 r; r.update(mid, 32); r.final(out);
}

// ---------------------------------------------------------------------------
// Bounds-checked wire reader (CompactSize canonical per serialize.py)
// ---------------------------------------------------------------------------

constexpr uint64_t MAX_WIRE_SIZE = 0x02000000;  // serialize.py MAX_SIZE

struct WireReader {
    const uint8_t* p;
    size_t len, pos = 0;

    bool skip(size_t n) {
        if (len - pos < n) return false;
        pos += n;
        return true;
    }
    bool u8(uint8_t* out) {
        if (pos >= len) return false;
        *out = p[pos++];
        return true;
    }
    bool u32(uint32_t* out) {
        if (len - pos < 4) return false;
        memcpy(out, p + pos, 4);  // little-endian hosts only
        pos += 4;
        return true;
    }
    bool i64(int64_t* out) {
        if (len - pos < 8) return false;
        memcpy(out, p + pos, 8);
        pos += 8;
        return true;
    }
    // Canonical CompactSize with the MAX_SIZE range check, exactly as
    // deser_compact_size(range_check=True) enforces.
    bool compact(uint64_t* out) {
        uint8_t tag;
        if (!u8(&tag)) return false;
        uint64_t v;
        if (tag < 253) {
            v = tag;
        } else {
            size_t n = tag == 253 ? 2 : tag == 254 ? 4 : 8;
            if (len - pos < n) return false;
            v = 0;
            for (size_t i = 0; i < n; i++) v |= uint64_t(p[pos + i]) << (8 * i);
            pos += n;
            if (tag == 253 && v < 253) return false;          // non-canonical
            if (tag == 254 && v < 0x10000) return false;
            if (tag == 255 && v < 0x100000000ULL) return false;
        }
        if (v > MAX_WIRE_SIZE) return false;
        *out = v;
        return true;
    }
};

// ---------------------------------------------------------------------------
// CompactSize writer (for undo/coin serialization byte-identical to
// consensus/serialize.py ser_compact_size)
// ---------------------------------------------------------------------------

template <class Vec>
inline void put_compact(Vec& out, uint64_t n) {
    if (n < 253) {
        out.push_back(uint8_t(n));
    } else if (n <= 0xFFFF) {
        out.push_back(0xFD);
        out.push_back(uint8_t(n)); out.push_back(uint8_t(n >> 8));
    } else if (n <= 0xFFFFFFFFULL) {
        out.push_back(0xFE);
        for (int i = 0; i < 4; i++) out.push_back(uint8_t(n >> (8 * i)));
    } else {
        out.push_back(0xFF);
        for (int i = 0; i < 8; i++) out.push_back(uint8_t(n >> (8 * i)));
    }
}

}  // namespace bcpn
